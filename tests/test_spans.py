"""Request-lifecycle tracing tests: aux/spans.py (ring-buffer bounds,
nesting/ids, zero-overhead-off, Chrome export schema round-trip),
the serve lifecycle span chain (admit -> queued -> execute -> deliver),
the chaos-integrated retry/backoff span, trace.py unification, and the
SLO surface (oldest_queued_s gauge, slo_burn tiers, health latency)."""

import json
import threading

import numpy as np
import pytest

from slate_tpu.aux import faults, metrics, spans, trace


@pytest.fixture(autouse=True)
def _clean_registry():
    """Every test starts and ends with spans/metrics/trace/faults off
    and empty."""
    for mod in (metrics, spans, trace):
        mod.off()
    metrics.reset()
    spans.clear()
    trace.clear()
    faults.reset()
    yield
    for mod in (metrics, spans, trace):
        mod.off()
    metrics.reset()
    spans.clear()
    trace.clear()
    faults.reset()


def _service(**kw):
    from slate_tpu.serve.cache import ExecutableCache
    from slate_tpu.serve.service import SolverService

    cfg = dict(
        cache=ExecutableCache(manifest_path=None), batch_max=4,
        batch_window_s=0.002, dim_floor=16, nrhs_floor=4,
    )
    cfg.update(kw)
    return SolverService(**cfg)


def _prob(n, seed=0):
    r = np.random.default_rng(seed)
    return r.standard_normal((n, n)) + n * np.eye(n), r.standard_normal((n, 2))


# ---------------------------------------------------------------------------
# ring buffer: bounds, eviction, clear
# ---------------------------------------------------------------------------


def test_ring_buffer_bounded():
    spans.on(ring=8)
    for i in range(20):
        with spans.span(f"s{i}"):
            pass
    snap = spans.snapshot()
    assert len(snap) == 8  # flight recorder: last N only
    assert [s.name for s in snap] == [f"s{i}" for i in range(12, 20)]
    assert spans.evicted() == 12
    spans.clear()
    assert spans.snapshot() == [] and spans.evicted() == 0


def test_ring_resize_on_reenable():
    spans.on(ring=4)
    assert spans.capacity() == 4
    spans.on(ring=16)
    assert spans.capacity() == 16
    spans.on()  # bare re-enable keeps the configured capacity
    assert spans.capacity() == 16


# ---------------------------------------------------------------------------
# nesting, ids, annotation
# ---------------------------------------------------------------------------


def test_nesting_parent_child_ids():
    spans.on()
    tr = spans.new_trace()
    with spans.span("outer", trace=tr) as o:
        assert spans.current() is o
        with spans.span("inner") as i:
            assert spans.current() is i
            spans.annotate(depth=2)
    assert spans.current() is None
    inner = next(s for s in spans.snapshot() if s.name == "inner")
    outer = next(s for s in spans.snapshot() if s.name == "outer")
    assert inner.parent == outer.sid and inner.sid != outer.sid
    assert inner.trace == tr  # trace id inherited through nesting
    assert inner.attrs["depth"] == 2
    assert outer.t_start <= inner.t_start <= inner.t_end <= outer.t_end


def test_trace_ids_unique():
    spans.on()
    ids = {spans.new_trace() for _ in range(100)}
    assert len(ids) == 100


def test_manual_start_end_cross_thread():
    spans.on()
    sp = spans.start("lifecycle", trace=spans.new_trace(), lane="worker")
    done = threading.Event()

    def finisher():
        spans.end(sp, outcome="ok")
        done.set()

    threading.Thread(target=finisher).start()
    assert done.wait(5)
    rec = spans.snapshot()[-1]
    assert rec is sp and rec.attrs["outcome"] == "ok"
    # end() is idempotent: a second resolution must not double-record
    spans.end(sp, outcome="late")
    assert len(spans.snapshot()) == 1
    assert sp.attrs["outcome"] == "ok"


def test_exception_stamps_outcome():
    spans.on()
    with pytest.raises(ValueError):
        with spans.span("work"):
            raise ValueError("boom")
    assert spans.snapshot()[-1].attrs["outcome"] == "ValueError"


# ---------------------------------------------------------------------------
# zero overhead off
# ---------------------------------------------------------------------------


def test_off_records_nothing_and_returns_none():
    assert not spans.is_on()
    assert spans.start("x") is None
    spans.end(None)
    assert spans.record("x", 0.0, 1.0) is None
    assert spans.event("x") is None
    assert spans.current() is None
    spans.annotate(a=1)
    with spans.span("y") as sp:
        assert sp is None
    spans.on()
    assert spans.snapshot() == []  # the off-path calls left no trace


def test_serve_stream_zero_span_overhead_off(tmp_path):
    """With spans AND metrics off, a serve stream records nothing: the
    lifecycle call sites cost one bool each (the PR 2/PR 4
    zero-overhead criterion extended to the tracing layer)."""
    svc = _service()
    A, B = _prob(12)
    futs = [svc.submit("gesv", A, B) for _ in range(4)]
    for f in futs:
        assert np.all(np.isfinite(f.result(timeout=300)))
    svc.stop()
    spans.on()
    metrics.on()
    assert spans.snapshot() == []
    assert not metrics.histograms()


# ---------------------------------------------------------------------------
# Chrome export schema
# ---------------------------------------------------------------------------


def test_chrome_export_schema_round_trip(tmp_path):
    spans.on()
    tr = spans.new_trace()
    root = spans.start("request", trace=tr, lane="client", routine="gesv")
    with spans.span("child", trace=tr, lane="replica-0"):
        pass
    spans.event("breaker_open", trace=tr, lane="replica-0", bucket="b")
    spans.end(root, outcome="ok")
    path = str(tmp_path / "t.json")
    assert spans.export_chrome(path) == path
    data = json.load(open(path))
    evs = data["traceEvents"]
    assert isinstance(evs, list) and data["displayTimeUnit"] == "ms"
    metas = [e for e in evs if e["ph"] == "M"]
    lanes = {e["args"]["name"] for e in metas}
    assert {"client", "replica-0"} <= lanes
    complete = {e["name"]: e for e in evs if e["ph"] == "X"}
    assert {"request", "child"} <= set(complete)
    req = complete["request"]
    assert req["args"]["trace"] == tr
    assert req["args"]["outcome"] == "ok"
    assert req["dur"] >= 0 and req["ts"] >= 0  # microseconds, rebased
    inst = next(e for e in evs if e["ph"] == "i")
    assert inst["name"] == "breaker_open" and inst["args"]["bucket"] == "b"
    # tids are stable ints shared per lane
    assert complete["child"]["tid"] == inst["tid"]


def test_export_merges_legacy_trace_events(tmp_path):
    """trace.finish() default output is Chrome JSON over BOTH the
    legacy event list and the span ring (the unification satellite)."""
    trace.on()
    spans.on()
    with trace.Block("legacy_block"):
        pass
    with spans.span("ring_span"):
        pass
    path = str(tmp_path / "merged.json")
    assert trace.finish(path) == path
    evs = json.load(open(path))["traceEvents"]
    names = [e["name"] for e in evs if e.get("ph") == "X"]
    assert {"legacy_block", "ring_span"} <= set(names)
    # with both layers on, Block mirrors into BOTH recorders — the
    # export must dedup, not render every driver phase twice
    assert names.count("legacy_block") == 1
    # the .svg spelling keeps the legacy renderer
    svg = trace.finish(str(tmp_path / "t.svg"))
    assert open(svg).read().startswith("<svg")


def test_trace_block_feeds_span_ring_without_trace_on():
    """Block/traced emit into the ring even when the legacy trace layer
    is off — spans is the successor recorder."""

    @trace.traced("drv")
    def drv():
        return 1

    spans.on()
    assert drv() == 1
    with trace.Block("blk"):
        pass
    assert {s.name for s in spans.snapshot()} == {"drv", "blk"}
    assert trace._events == []  # legacy list untouched while trace off


def test_instrumented_driver_lands_on_ring():
    """@metrics.instrumented mirrors driver phases onto the span ring
    (one flight recorder), with metrics on or off."""

    @metrics.instrumented("probe_driver")
    def fn():
        return 7

    spans.on()
    assert fn() == 7
    assert "probe_driver" in {s.name for s in spans.snapshot()}


# ---------------------------------------------------------------------------
# serve lifecycle chain
# ---------------------------------------------------------------------------


def test_serve_request_span_chain_complete():
    spans.on(ring=4096)
    svc = _service()
    A, B = _prob(12)
    futs = [svc.submit("gesv", A, B) for _ in range(6)]
    for f in futs:
        assert np.all(np.isfinite(f.result(timeout=300)))
    svc.stop()
    bytr = spans.by_trace()
    roots = [
        sps for sps in bytr.values()
        if any(s.name == "request" for s in sps)
    ]
    assert len(roots) == 6  # one trace per request, no orphans
    for sps in roots:
        names = {s.name for s in sps}
        assert {"request", "admit", "queued"} <= names
        assert "execute" in names or "direct" in names
        root = next(s for s in sps if s.name == "request")
        assert root.attrs["outcome"] == "ok"
        assert root.attrs["bucket"] == "gesv.16x16x4.float64"
        # children nest inside the root interval
        for s in sps:
            if s.name in ("admit", "queued", "execute"):
                assert s.t_start >= root.t_start - 1e-6
                assert s.t_end <= root.t_end + 1e-6


def test_rejected_admission_closes_chain():
    spans.on()
    svc = _service(max_queue=1, start=False)  # paused: everything queues
    A, B = _prob(12)
    svc.submit("gesv", A, B)
    from slate_tpu.serve.service import Rejected

    with pytest.raises(Rejected):
        svc.submit("gesv", A, B)
    roots = [s for s in spans.snapshot() if s.name == "request"]
    assert roots and roots[-1].attrs["outcome"] == "Rejected"
    svc.stop()


def test_chaos_retry_span_shows_backoff_interval():
    """ISSUE satellite: a retried request's trace must carry a backoff
    span whose interval matches the recorded decorrelated-jitter delay
    — 'this request was slow because it sat out a retry backoff' is
    answerable from the flight recorder alone."""
    spans.on(ring=4096)
    metrics.on()
    svc = _service(retry_backoff_s=0.01, retry_seed=3)
    faults.arm("execute", once=True)  # exactly one batched failure
    faults.on()
    A, B = _prob(12)
    X = svc.submit("gesv", A, B, retries=2).result(timeout=300)
    assert np.all(np.isfinite(X))
    svc.stop()
    back = [s for s in spans.snapshot() if s.name == "backoff"]
    assert len(back) == 1
    sp = back[0]
    assert sp.trace is not None and sp.parent is not None
    assert sp.attrs["retries_left"] == 1
    # the span IS the planned backoff window, and it matches the
    # serve.retry_backoff_s timer the metrics layer recorded
    t = metrics.timers()["serve.retry_backoff_s"]
    assert sp.attrs["backoff_s"] == pytest.approx(t["total_s"], rel=1e-3)
    assert sp.dur_s == pytest.approx(sp.attrs["backoff_s"], rel=1e-3)
    # the retried request still delivered with a complete chain
    chain = {s.name for s in spans.by_trace()[sp.trace]}
    assert {"request", "admit", "queued", "execute", "backoff"} <= chain
    # the queued histogram saw the request ONCE (its second wait was
    # backoff, not queueing — re-observing would inflate queued p99
    # and break the queued-vs-execute subtraction)
    q = metrics.hist_summary("serve.latency.gesv.16x16x4.float64.queued")
    t = metrics.hist_summary("serve.latency.gesv.16x16x4.float64.total")
    assert q["count"] == t["count"] == 1


def test_refine_iterations_annotate_enclosing_span():
    """The mixed drivers stamp iteration counts onto the caller's span
    (spans.span parents explicitly too); with no enclosing span the
    count still lands on the ring as a `refine` instant."""
    import jax

    jax.config.update("jax_enable_x64", True)
    import slate_tpu as st
    from slate_tpu.matrix.matrix import Matrix

    spans.on()
    rng = np.random.default_rng(0)
    A = rng.standard_normal((16, 16)) + 16 * np.eye(16)
    B = rng.standard_normal((16, 2))
    with spans.span("solve") as sp:
        _X, info, iters = st.gesv_mixed(
            Matrix.from_global(A, 8), Matrix.from_global(B, 8)
        )
    assert int(info) == 0
    assert sp.attrs["refine_iters"] == iters
    assert sp.attrs["refine_converged"] is True
    spans.clear()
    st.gesv_mixed(Matrix.from_global(A, 8), Matrix.from_global(B, 8))
    inst = [s for s in spans.snapshot() if s.name == "refine"]
    assert inst and inst[0].attrs["refine_iters"] == iters


# ---------------------------------------------------------------------------
# SLO surface: oldest-queued gauge, burn tiers, health latency
# ---------------------------------------------------------------------------


def test_oldest_queued_gauge_exposes_stuck_head_of_line():
    metrics.on()
    svc = _service(start=False)  # no worker: requests sit queued
    A, B = _prob(12)
    import time as _t

    svc.submit("gesv", A, B)
    _t.sleep(0.05)
    svc.submit("gesv", A, B)  # admission re-gauges the queues
    g = metrics.gauges()["serve.replica.0.oldest_queued_s"]
    assert g >= 0.05  # the HEAD's age, not the newest request's
    h = svc.health()
    assert h["replicas"][0]["oldest_queued_s"] >= g
    svc.stop()
    assert metrics.gauges()["serve.replica.0.oldest_queued_s"] == 0.0


def test_health_latency_percentiles_and_slo_burn():
    metrics.on()
    svc = _service()
    A, B = _prob(12)
    futs = [svc.submit("gesv", A, B, deadline=300.0) for _ in range(5)]
    for f in futs:
        assert np.all(np.isfinite(f.result(timeout=300)))
    h = svc.health()
    lat = h["latency"]["gesv.16x16x4.float64"]
    assert lat["count"] == 5
    assert 0 < lat["p50"] <= lat["p95"] <= lat["p99"]
    # generous deadlines: every request lands in the healthy (<=50%)
    # tier — only the requests denominator ticks
    assert h["slo_burn"]["requests"] == 5
    assert "exhausted" not in h["slo_burn"]
    svc.stop()


def test_serve_latency_split_counts_align():
    metrics.on()
    svc = _service()
    A, B = _prob(12)
    futs = [svc.submit("gesv", A, B) for _ in range(7)]
    for f in futs:
        f.result(timeout=300)
    svc.stop()
    lbl = "gesv.16x16x4.float64"
    hh = metrics.histograms()
    q = hh[f"serve.latency.{lbl}.queued"]
    x = hh[f"serve.latency.{lbl}.execute"]
    t = hh[f"serve.latency.{lbl}.total"]
    rep = hh["serve.latency.replica.0.total"]
    assert q["count"] == x["count"] == t["count"] == rep["count"] == 7
    # queued + execute <= total on every percentile-free aggregate
    assert t["total_s"] >= x["total_s"]
