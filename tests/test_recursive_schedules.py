"""Recursive (divide & conquer) factorization schedules — parity against
the flat loops and scipy/LAPACK, compile-count guards, and the
FLOP-accounting acceptance bounds.

The recursive kernels (ops/chol_kernels.chol_recursive,
ops/lu_kernels.getrf_recursive, ops/qr_fast.geqrf_recursive) factor
exact halving-lattice shapes; tests use a small nb_switch so a few
hundred rows already exercise several recursion levels.  Heavy (n=2048)
end-to-end cases are marked slow (tier-1 budget)."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from slate_tpu.ops.chol_kernels import (
    blocked_potrf,
    chol_recursive,
    chol_schedule_flops,
    cholesky,
)
from slate_tpu.ops.lu_kernels import (
    blocked_getrf,
    getrf_recursive,
    getrf_schedule_flops,
)
from slate_tpu.ops.qr_fast import (
    geqrf_fast,
    geqrf_recursive,
    geqrf_schedule_flops,
)

# full dtype sweep; only f64 rides tier-1 (each parametrization costs a
# distinct XLA compile of the whole recursion graph, and the seed
# tier-1 gate has ~160 s of headroom on the 2-core box — ISSUE 3 asks
# for exactly this split: heavy cases go slow)
DTYPES = [
    pytest.param(jnp.float32, marks=pytest.mark.slow),
    jnp.float64,
    pytest.param(jnp.complex64, marks=pytest.mark.slow),
    pytest.param(jnp.complex128, marks=pytest.mark.slow),
]


def _tol(dtype, n):
    eps = float(jnp.finfo(jnp.zeros((), dtype).real.dtype).eps)
    return 50 * n * eps


def _rand(m, n, dtype, seed=0):
    key = jax.random.PRNGKey(seed)
    rt = jnp.zeros((), dtype).real.dtype
    a = jax.random.normal(key, (m, n), rt)
    if jnp.issubdtype(dtype, jnp.complexfloating):
        a = a + 1j * jax.random.normal(jax.random.PRNGKey(seed + 1), (m, n), rt)
    return a.astype(dtype)


def _spd(n, dtype, seed=0):
    a = _rand(n, n, dtype, seed)
    return a @ jnp.conj(a).T + n * jnp.eye(n, dtype=dtype)


# ---------------------------------------------------------------------------
# parity: recursive vs flat vs scipy
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", DTYPES)
def test_chol_recursive_parity(dtype):
    n = 192  # 192 -> split 128/64: two levels at nb_switch=64
    S = _spd(n, dtype)
    Lr = np.asarray(chol_recursive(S, nb_switch=64))
    Lf = np.asarray(blocked_potrf(S, 64))
    ref = np.linalg.cholesky(np.asarray(S))
    tol = _tol(dtype, n) * float(np.abs(ref).max())
    assert np.allclose(Lr, ref, atol=tol)
    assert np.allclose(np.tril(Lf), ref, atol=tol)


@pytest.mark.parametrize("dtype", DTYPES)
def test_getrf_recursive_parity(dtype):
    n = 192
    A = _rand(n, n, dtype, seed=2)
    LUr, pr = getrf_recursive(A, nb_switch=64)
    LUf, pf = blocked_getrf(A, 64)
    # same pivot sequence as the flat kernel on random (tie-free) input
    assert np.array_equal(np.asarray(pr), np.asarray(pf))
    assert np.allclose(
        np.asarray(LUr), np.asarray(LUf), atol=_tol(dtype, n)
    )
    # reconstruction against scipy: L U = A[perm]
    LU = np.asarray(LUr)
    perm = np.asarray(pr)
    L = np.tril(LU, -1) + np.eye(n)
    U = np.triu(LU)
    An = np.asarray(A)
    assert sorted(perm) == list(range(n))
    assert np.allclose(
        L @ U, An[perm], atol=_tol(dtype, n) * float(np.abs(An).max())
    )


@pytest.mark.slow
@pytest.mark.parametrize("dtype", [jnp.float64, jnp.complex64])
def test_getrf_recursive_tall(dtype):
    m, n = 320, 192  # tall + canonical-height padding inside (320->lat)
    A = _rand(m, n, dtype, seed=3)
    LU, perm = getrf_recursive(A, nb_switch=64)
    LU = np.asarray(LU)
    perm = np.asarray(perm)
    L = np.tril(LU[:, :n], -1) + np.eye(m, n)
    U = np.triu(LU[:n])
    An = np.asarray(A)
    assert sorted(perm) == list(range(m))
    assert np.allclose(
        L @ U, An[perm], atol=_tol(dtype, n) * float(np.abs(An).max())
    )


@pytest.mark.slow
@pytest.mark.parametrize(
    "dtype", [jnp.float32, jnp.float64, jnp.complex64, jnp.complex128]
)
def test_geqrf_recursive_parity(dtype):
    m = n = 192
    A = _rand(m, n, dtype, seed=4)
    Fr, taur = geqrf_recursive(A, nb_switch=64)
    Ff, tauf = geqrf_fast(A, nb=64, ib=16)
    # R matches the flat kernel up to column sign conventions — both use
    # the same larfg, so it matches exactly (tie-free random input)
    Rr = np.triu(np.asarray(Fr)[:n])
    Rf = np.triu(np.asarray(Ff)[:n])
    tol = _tol(dtype, n) * float(np.abs(Rr).max())
    assert np.allclose(Rr, Rf, atol=tol)
    # |R| parity vs scipy
    import scipy.linalg as sla

    Rs = sla.qr(np.asarray(A), mode="r")[0][:n]
    assert np.allclose(np.abs(Rr), np.abs(Rs), atol=tol)


@pytest.mark.slow
def test_geqrf_recursive_q_reconstruction():
    m, n = 320, 256
    A = _rand(m, n, jnp.float64, seed=5)
    F, taus = geqrf_recursive(A, nb_switch=64)
    F = np.asarray(F)
    R = np.triu(F[:n])
    # apply reflectors in reverse to [R; 0] to rebuild A
    C = np.vstack([R, np.zeros((m - n, n))])
    taus = np.asarray(taus)
    for j in range(n - 1, -1, -1):
        v = np.concatenate([np.zeros(j), [1.0], F[j + 1 :, j]])
        C = C - taus[j] * np.outer(v, v @ C)
    assert np.allclose(C, np.asarray(A), atol=1e-10 * n)


@pytest.mark.slow
def test_non_power_of_two_via_bucket_pad():
    # the cholesky dispatcher pads any n to the 128 lattice with a
    # unit-diagonal splice; 200 -> 256 exercises pad + crop around the
    # recursion
    n = 200
    S = _spd(n, jnp.float64, seed=6)
    L = cholesky(S, 64, schedule="recursive")
    ref = np.linalg.cholesky(np.asarray(S))
    assert np.allclose(np.asarray(L), ref, atol=1e-10 * n)


@pytest.mark.slow
def test_chol_recursive_lookahead_peel():
    # lookahead=3 peels two eager panels ahead of the halving split
    n = 512
    S = _spd(n, jnp.float64, seed=7)
    L = chol_recursive(S, nb_switch=64, lookahead=3)
    ref = np.linalg.cholesky(np.asarray(S))
    assert np.allclose(np.asarray(L), ref, atol=1e-10 * n)


@pytest.mark.slow
def test_getrf_recursive_lookahead_peel():
    n = 512
    A = _rand(n, n, jnp.float64, seed=8)
    LU, perm = getrf_recursive(A, nb_switch=64, lookahead=3)
    LU0, perm0 = getrf_recursive(A, nb_switch=64, lookahead=1)
    # peeling changes the schedule, not the factorization
    assert np.array_equal(np.asarray(perm), np.asarray(perm0))
    assert np.allclose(np.asarray(LU), np.asarray(LU0), atol=1e-10)


# ---------------------------------------------------------------------------
# FLOP accounting: the acceptance bounds at the flagship point
# ---------------------------------------------------------------------------


def test_flops_ratio_acceptance_n2048():
    """Recursive dpotrf/dgetrf at n=2048, nb=256 must execute <= 1.35x
    the model FLOP count (the flat loops run ~2-6x)."""
    ch = chol_schedule_flops(2048, 512, "recursive", nb_switch=256)
    assert ch["exec"] / ch["model"] <= 1.35, ch
    lu = getrf_schedule_flops(2048, 2048, 512, "recursive", nb_switch=256)
    assert lu["exec"] / lu["model"] <= 1.35, lu
    # and the flat loops really are the waste the recursion removes
    chf = chol_schedule_flops(2048, 512, "flat_fori")
    luf = getrf_schedule_flops(2048, 2048, 512, "flat")
    assert chf["exec"] / chf["model"] > 2.0
    assert luf["exec"] / luf["model"] > 2.0


def test_compile_units_bound_n2048():
    """Distinct compiled shapes for one recursive factor stay bounded:
    chol <= 2 log2(n/nb) + 5, lu/qr <= 2 log2(n/nb) + 14 (tall
    operand heights snap to the 2-leading-bits lattice, <= 2 per
    octave)."""
    L = 2 * math.log2(2048 / 256)
    ch = chol_schedule_flops(2048, 512, "recursive", nb_switch=256)
    assert len(ch["units"]) <= L + 5, sorted(ch["units"])
    lu = getrf_schedule_flops(2048, 2048, 512, "recursive", nb_switch=256)
    assert len(lu["units"]) <= L + 14, sorted(lu["units"])
    qr = geqrf_schedule_flops(2048, 2048, 512, "recursive", nb_switch=256)
    assert len(qr["units"]) <= L + 14, sorted(qr["units"])


def test_recursive_beats_flat_at_scale():
    for n in (2048, 4096, 8192):
        ch_r = chol_schedule_flops(n, 512, "recursive", nb_switch=256)
        ch_f = chol_schedule_flops(n, 512, "flat_fori")
        assert ch_r["exec"] < ch_f["exec"] / 2
        lu_r = getrf_schedule_flops(n, n, 512, "recursive", nb_switch=256)
        lu_f = getrf_schedule_flops(n, n, 512, "flat")
        assert lu_r["exec"] < lu_f["exec"] / 2
        qr_r = geqrf_schedule_flops(n, n, 512, "recursive", nb_switch=256)
        qr_f = geqrf_schedule_flops(n, n, 512, "flat")
        assert qr_r["exec"] < qr_f["exec"]


# ---------------------------------------------------------------------------
# compile-count guard + driver metrics integration
# ---------------------------------------------------------------------------


def test_compile_count_guard_recursive_driver():
    """One recursive factor = ONE top-level jit compilation per distinct
    driver shape (the recursion inlines into a single executable), and a
    repeat call at the same shape compiles nothing."""
    import slate_tpu as st
    from slate_tpu.aux import metrics
    from slate_tpu.enums import Option

    n = 256
    S = _spd(n, jnp.float64, seed=9)
    A = st.HermitianMatrix.from_global(S, 64, uplo=st.Uplo.Lower)
    opts = {Option.Schedule: "recursive", Option.BlockSize: 64}
    metrics.on()
    try:
        metrics.reset()
        L1, info1 = st.potrf(A, opts)
        first = metrics.counters().get("jit.compilations", 0)
        # the recursive path is one compile unit at the jit layer
        # (schedule shapes inline into one executable)
        assert first <= 2, metrics.counters()
        L2, info2 = st.potrf(A, opts)
        again = metrics.counters().get("jit.compilations", 0) - first
        assert again == 0, metrics.counters()
    finally:
        metrics.off()
    assert np.allclose(
        np.asarray(L1.to_global()), np.asarray(L2.to_global())
    )


def test_driver_flops_counters_match_accounting():
    """The factor.* counters recorded by the drivers equal the pure
    accounting functions for the traced shape."""
    import slate_tpu as st
    from slate_tpu.aux import metrics
    from slate_tpu.enums import Option
    from slate_tpu.ops.chol_kernels import resolve_schedule

    n = 256
    S = _spd(n, jnp.float64, seed=10)
    A = st.HermitianMatrix.from_global(S, 64, uplo=st.Uplo.Lower)
    opts = {Option.Schedule: "recursive", Option.BlockSize: 64}
    metrics.on()
    try:
        metrics.reset()
        st.potrf(A, opts)
        c = metrics.counters()
        fl = chol_schedule_flops(n, 256, "recursive", nb_switch=64)
        assert c["factor.potrf.flops_model"] == pytest.approx(fl["model"])
        assert c["factor.potrf.flops_exec"] == pytest.approx(fl["exec"])
        assert c["factor.flops_exec"] == pytest.approx(fl["exec"])
        units = metrics.gauges()["factor.potrf.compile_units"]
        assert units == len(fl["units"])
    finally:
        metrics.off()


def test_serve_bucket_key_schedule_roundtrip():
    """schedule is a first-class BucketKey component: distinct cache
    identity, manifest JSON round-trip, and back-compat default for old
    manifests."""
    from slate_tpu.serve import buckets as bk

    k_auto = bk.bucket_for("posv", 100, 100, 4, np.float64)
    k_rec = bk.bucket_for(
        "posv", 100, 100, 4, np.float64, schedule="recursive"
    )
    assert k_auto != k_rec and k_rec.schedule == "recursive"
    text = bk.manifest_dumps([(k_rec, 1), (k_auto, 8)])
    back = dict(bk.manifest_loads(text))
    assert back[k_rec] == 1 and back[k_auto] == 8
    # pre-schedule manifests parse with schedule="auto"
    legacy = {"routine": "posv", "m": 128, "n": 128, "nrhs": 8,
              "dtype": "float64", "nb": 64, "batch": 1}
    key = bk.BucketKey.from_json(legacy)
    assert key.schedule == "auto"


@pytest.mark.slow
def test_serve_recursive_schedule_end_to_end():
    """A recursive-schedule service serves correct solutions through
    the padded/batched path."""
    from slate_tpu.serve.cache import ExecutableCache
    from slate_tpu.serve.service import SolverService

    svc = SolverService(
        cache=ExecutableCache(manifest_path=None),
        batch_window_s=0.01,
        schedule="recursive",
        start=True,
    )
    try:
        rng = np.random.default_rng(11)
        a = rng.standard_normal((40, 40))
        S = a @ a.T + 40 * np.eye(40)
        B = rng.standard_normal((40, 3))
        X = svc.submit("posv", S, B).result(timeout=600)
        assert np.allclose(S @ X, B, atol=1e-8)
    finally:
        svc.stop()


# ---------------------------------------------------------------------------
# heavy end-to-end acceptance (slow): n=2048 through the real driver
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_recursive_driver_n2048_metrics_acceptance():
    import slate_tpu as st
    from slate_tpu.aux import metrics
    from slate_tpu.enums import Option

    n = 2048
    S = _spd(n, jnp.float64, seed=12)
    A = st.HermitianMatrix.from_global(S, 256, uplo=st.Uplo.Lower)
    opts = {Option.Schedule: "recursive", Option.BlockSize: 256}
    metrics.on()
    try:
        metrics.reset()
        L, info = st.potrf(A, opts)
        c = metrics.counters()
        assert int(info) == 0
        ratio = c["factor.potrf.flops_exec"] / c["factor.potrf.flops_model"]
        assert ratio <= 1.35, ratio
        ref = np.linalg.cholesky(np.asarray(S))
        assert np.allclose(
            np.asarray(L.to_global()), ref, atol=1e-8 * n
        )
    finally:
        metrics.off()
