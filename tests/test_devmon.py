"""Device telemetry plane (aux/devmon + the serve cost/memory
registry): build-time cost/memory capture, graceful degradation on
backends without the device APIs, manifest persistence, health()
surfacing, the roofline math, and the report/sentinel tools.

The zero-overhead-off criterion rides here too: with devmon off
(the default) the cache captures nothing, the manifest carries no
cost fields, and health() reports devices=None — the PR2 steady-state
compile-free contract is untouched (test_serve keeps asserting it).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from slate_tpu.aux import devmon, metrics
from slate_tpu.serve import buckets as bk
from slate_tpu.serve.cache import ExecutableCache
from slate_tpu.serve.service import SolverService

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _devmon_state():
    """devmon and metrics are process-global; every test starts and
    ends with both off and clean."""
    devmon.off()
    devmon.reset()
    metrics.off()
    metrics.reset()
    yield
    devmon.off()
    devmon.reset()
    metrics.off()
    metrics.reset()


def _key(n=12, nrhs=2, routine="gesv"):
    return bk.bucket_for(routine, n, n, nrhs, np.float64,
                         floor=16, nrhs_floor=4)


# ---------------------------------------------------------------------------
# analyze / capture primitives
# ---------------------------------------------------------------------------


def test_analyze_compiled_reads_cost_and_memory():
    import jax

    def f(a, b):
        return (a @ b).sum()

    c = jax.jit(f).lower(np.ones((32, 32)), np.ones((32, 32))).compile()
    rec = devmon.analyze_compiled(c)
    assert rec is not None
    assert rec["flops"] > 0
    assert rec["bytes_accessed"] > 0
    assert rec["argument_bytes"] > 0
    assert rec["output_bytes"] > 0
    # peak is the runtime's number when reported, else arg+out+temp
    assert rec["peak_bytes"] >= rec["argument_bytes"]


def test_analyze_compiled_output_only_backend_gets_peak():
    class OutputOnlyMem:
        output_size_in_bytes = 512

    class Fake:
        def cost_analysis(self):
            return {}

        def memory_analysis(self):
            return OutputOnlyMem()

    rec = devmon.analyze_compiled(Fake())
    # a backend exposing only output bytes still yields a computable
    # peak (the arg+out+temp fallback must not require arg/temp)
    assert rec["output_bytes"] == 512 and rec["peak_bytes"] == 512


def test_analyze_compiled_peak_fallback_discounts_aliasing():
    class DonatedMem:
        argument_size_in_bytes = 1000
        output_size_in_bytes = 1000
        temp_size_in_bytes = 100
        alias_size_in_bytes = 1000  # donated operands: in arg AND out

    class Fake:
        def cost_analysis(self):
            return {}

        def memory_analysis(self):
            return DonatedMem()

    rec = devmon.analyze_compiled(Fake())
    assert rec["peak_bytes"] == 1100  # not 2100: aliased counted once


def test_capture_jitted_records_into_metrics():
    import jax

    metrics.on()
    compiled, cost = devmon.capture_jitted(
        jax.jit(lambda a: (a * 2.0).sum()), (np.ones((8, 8)),),
        name="devmon.test.cap",
    )
    assert compiled is not None and cost is not None
    assert "device_kind" in cost
    assert metrics.costs()["devmon.test.cap"]["flops"] == cost["flops"]
    # the captured compile is reusable as the executable
    assert float(compiled(np.ones((8, 8)))) == 128.0


def test_capture_jitted_failure_degrades_to_none():
    class Broken:
        def lower(self, *a):
            raise RuntimeError("no lowering")

    compiled, cost = devmon.capture_jitted(Broken(), (np.ones(3),))
    assert compiled is None and cost is None


# ---------------------------------------------------------------------------
# device memory sampling: graceful on backends without the API
# ---------------------------------------------------------------------------


def test_sample_devices_graceful_none_on_cpu():
    rows = devmon.sample_devices()
    assert rows, "at least one device visible"
    for r in rows:
        assert set(r) >= {"id", "platform", "kind", "bytes_in_use",
                          "bytes_limit", "peak_bytes_in_use"}
        # XLA:CPU has no memory_stats: byte fields are None, not a crash
        assert r["bytes_in_use"] is None
        assert r["peak_bytes_in_use"] is None


def test_sample_devices_memory_stats_raising_never_crashes():
    class Weird:
        id = 99
        platform = "weird"
        device_kind = "weird9000"

        def memory_stats(self):
            raise RuntimeError("unsupported")

    [row] = devmon.sample_devices([Weird()])
    assert row["bytes_in_use"] is None


def test_sample_devices_gauges_and_high_water():
    class Fake:
        def __init__(self, use, peak=None):
            self.id = 7
            self.platform = "tpu"
            self.device_kind = "TPU v4"
            self._use, self._peak = use, peak

        def memory_stats(self):
            s = {"bytes_in_use": self._use, "bytes_limit": 1000}
            if self._peak is not None:
                s["peak_bytes_in_use"] = self._peak
            return s

    metrics.on()
    [r1] = devmon.sample_devices([Fake(100)])
    assert r1["bytes_in_use"] == 100 and r1["peak_bytes_in_use"] == 100
    [r2] = devmon.sample_devices([Fake(40)])
    # high-water mark is monotone even when the backend has no peak
    assert r2["peak_bytes_in_use"] == 100
    [r3] = devmon.sample_devices([Fake(40, peak=500)])
    assert r3["peak_bytes_in_use"] == 500
    g = metrics.gauges()
    assert g["serve.device.7.bytes_in_use"] == 40
    assert g["serve.device.7.bytes_in_use_peak"] == 500


# ---------------------------------------------------------------------------
# roofline peaks + attribution
# ---------------------------------------------------------------------------


def test_peaks_for_table_env_and_fallback(monkeypatch):
    # an ambient deployment override must not shift the default-table
    # assertions below
    monkeypatch.delenv(devmon.PEAKS_ENV, raising=False)
    p = devmon.peaks_for("cpu")
    assert p["source"] == "default" and p["ridge"] == pytest.approx(
        p["flops"] / p["bytes_per_s"])
    assert devmon.peaks_for("TPU v4 MegaCore")["flops"] == \
        devmon.DEFAULT_PEAKS["tpu v4"]["flops"]
    assert devmon.peaks_for("martian accelerator")["source"] == "fallback"
    monkeypatch.setenv(
        devmon.PEAKS_ENV,
        '{"cpu": {"flops": 1e9, "bytes_per_s": 1e8}}',
    )
    p = devmon.peaks_for("cpu")
    assert p["source"] == "env" and p["flops"] == 1e9 and p["ridge"] == 10.0
    # malformed override degrades to the built-in table, never crashes
    monkeypatch.setenv(devmon.PEAKS_ENV, "{broken")
    assert devmon.peaks_for("cpu")["source"] == "default"
    # zero/negative roofs are malformed too: the ridge and frac-of-
    # roof divisions must never see them
    monkeypatch.setenv(
        devmon.PEAKS_ENV, '{"cpu": {"flops": 0, "bytes_per_s": 1}}'
    )
    p = devmon.peaks_for("cpu")
    assert p["source"] == "default" and p["flops"] > 0
    assert devmon.roofline(
        1e9, 1e9, 0.1,
        {"flops": 0, "bytes_per_s": 0, "ridge": 0, "source": "x",
         "kind": "x"},
    ) is None
    # the fallback path honors an env override of the cpu row too
    monkeypatch.setenv(
        devmon.PEAKS_ENV, '{"cpu": {"flops": 2e11, "bytes_per_s": 8e10}}'
    )
    p = devmon.peaks_for("martian accelerator")
    assert p["source"] == "fallback" and p["flops"] == 2e11


def test_roofline_classification():
    pk = {"flops": 1e12, "bytes_per_s": 1e11, "ridge": 10.0,
          "source": "test", "kind": "t"}
    mem = devmon.roofline(1e9, 1e9, 0.01, pk)  # AI 1 < ridge 10
    assert mem["bound"] == "memory"
    assert mem["roof_flops"] == pytest.approx(1e11)  # AI * bw
    comp = devmon.roofline(1e12, 1e10, 0.5, pk)  # AI 100 >= ridge
    assert comp["bound"] == "compute"
    assert comp["roof_flops"] == pytest.approx(1e12)
    assert 0 < comp["frac_of_roof"] <= 1e3
    # unrateable inputs are None (the "unclassifiable" signal)
    assert devmon.roofline(0.0, 1e9, 0.01, pk) is None
    assert devmon.roofline(1e9, None, 0.01, pk) is None
    assert devmon.roofline(1e9, 1e9, 0.0, pk) is None
    # the bare SLATE_TPU_PEAKS row shape (no ridge/source) works too
    bare = devmon.roofline(1e9, 1e8, 0.1,
                           {"flops": 1e12, "bytes_per_s": 1e11})
    assert bare["ridge"] == 10.0 and bare["bound"] == "compute"


# ---------------------------------------------------------------------------
# serve cache registry: capture, persistence, restore, off-path
# ---------------------------------------------------------------------------


def test_cache_registry_capture_and_manifest_persist(tmp_path):
    devmon.on()
    man = str(tmp_path / "warmup.json")
    cache = ExecutableCache(manifest_path=man)
    key = _key()
    cache.ensure_manifest(key, (1,))
    cache.warmup(batch_max=1)
    rec = cache.cost(key, 1)
    assert rec is not None
    assert rec["flops"] > 0 and rec["bytes_accessed"] > 0
    assert rec["peak_bytes"] > 0 and rec["argument_bytes"] > 0
    doc = json.loads(open(man).read())
    [entry] = doc["entries"]
    assert entry["cost"]["flops"] == rec["flops"]
    # a fresh cache restores the registry from the manifest — no
    # recapture compile needed for the evidence to exist
    cache2 = ExecutableCache(manifest_path=man)
    assert cache2.cost(key, 1) == rec
    assert cache2.costs_by_label()[key.label][1]["flops"] == rec["flops"]


def test_registry_off_by_default_zero_touch(tmp_path):
    man = str(tmp_path / "warmup.json")
    cache = ExecutableCache(manifest_path=man)
    key = _key()
    cache.ensure_manifest(key, (1,))
    cache.warmup(batch_max=1)
    assert cache.cost(key, 1) is None
    assert cache.cost_registry() == {}
    doc = json.loads(open(man).read())
    assert all("cost" not in e for e in doc["entries"])


def test_registry_no_recapture_when_already_known(tmp_path, monkeypatch):
    devmon.on()
    man = str(tmp_path / "warmup.json")
    cache = ExecutableCache(manifest_path=man)
    key = _key()
    cache.ensure_manifest(key, (1,))
    cache.warmup(batch_max=1)
    # second cache on the same manifest: registry pre-loaded, so the
    # cold build must not call the capture path again
    cache2 = ExecutableCache(manifest_path=man)
    calls = []
    real = devmon.capture_jitted
    monkeypatch.setattr(
        devmon, "capture_jitted",
        lambda *a, **kw: calls.append(1) or real(*a, **kw),
    )
    cache2.warmup(batch_max=1)
    assert calls == []


def test_solve_phase_and_batched_entries_capture(tmp_path):
    devmon.on()
    metrics.on()
    cache = ExecutableCache(manifest_path=str(tmp_path / "m.json"))
    key = _key(routine="posv")
    skey = key.solve_sibling()
    cache.ensure_manifest(key, (1, 4))
    cache.ensure_manifest(skey, (1,))
    cache.warmup(batch_max=4)
    full1, full4 = cache.cost(key, 1), cache.cost(key, 4)
    solve1 = cache.cost(skey, 1)
    assert full1 and full4 and solve1
    # the batched executable does more work than the lone one, and the
    # trsm-only solve family costs an order less than its full sibling
    # (flops_model: the CPU vendor trsm reports no XLA flops — the
    # hand-model fallback is exactly what keeps it classifiable)
    assert full4["flops"] > full1["flops"]
    assert solve1["flops_model"] < full1["flops_model"]
    assert solve1["bytes_accessed"] > 0 and solve1["peak_bytes"] > 0
    # the metrics/JSONL record carries flops_model too — the roofline
    # report's model fallback reads it from there, not from the cache
    mrec = metrics.costs()[f"serve.{skey.label}.b1"]
    assert mrec["flops_model"] == solve1["flops_model"]


def test_registry_restore_mirrors_into_metrics(tmp_path):
    """A warm-restarted process skips the recapture compile but must
    still emit the restored records into ITS metrics registry — the
    JSONL cost rows roofline_report gates on."""
    devmon.on()
    man = str(tmp_path / "warmup.json")
    cache = ExecutableCache(manifest_path=man)
    key = _key()
    cache.ensure_manifest(key, (1,))
    cache.warmup(batch_max=1)
    # fresh-process analogue: clean metrics, registry preloaded from
    # the manifest, build skips capture but mirrors the known record
    metrics.reset()
    metrics.on()
    cache2 = ExecutableCache(manifest_path=man)
    cache2.warmup(batch_max=1)
    rec = metrics.costs().get(f"serve.{key.label}.b1")
    assert rec is not None and rec["flops"] > 0


def test_registry_foreign_device_kind_recaptured(tmp_path):
    """A manifest captured on another backend must not serve stale
    evidence here: a device_kind mismatch forces a recapture on THIS
    device kind (same-kind records are reused without a compile)."""
    devmon.on()
    metrics.on()
    man = str(tmp_path / "warmup.json")
    cache = ExecutableCache(manifest_path=man)
    key = _key()
    cache.ensure_manifest(key, (1,))
    cache.warmup(batch_max=1)
    # forge a foreign record in the manifest (CPU box -> TPU replica)
    doc = json.loads(open(man).read())
    doc["entries"][0]["cost"] = {"flops": 1.0, "bytes_accessed": 1.0,
                                 "peak_bytes": 1, "device_kind": "tpu v9"}
    open(man, "w").write(json.dumps(doc))
    cache2 = ExecutableCache(manifest_path=man)
    assert cache2.cost(key, 1)["device_kind"] == "tpu v9"
    cache2.warmup(batch_max=1)
    rec = cache2.cost(key, 1)
    assert rec["device_kind"] == devmon.default_device_kind()
    assert rec["flops"] > 1.0
    assert metrics.counters()["serve.cost_foreign_recaptured"] == 1


def test_registry_foreign_recapture_failure_drops_record(tmp_path,
                                                         monkeypatch):
    """When the recapture of foreign evidence FAILS, the foreign
    record must be dropped, not kept: no evidence beats wrong
    evidence (health/roofline would join another backend's bytes
    with this device's timers)."""
    devmon.on()
    man = str(tmp_path / "warmup.json")
    cache = ExecutableCache(manifest_path=man)
    key = _key()
    cache.ensure_manifest(key, (1,))
    cache.warmup(batch_max=1)
    doc = json.loads(open(man).read())
    doc["entries"][0]["cost"] = {"flops": 1.0, "device_kind": "tpu v9"}
    open(man, "w").write(json.dumps(doc))
    monkeypatch.setattr(devmon, "capture_jitted",
                        lambda *a, **kw: (None, None))
    cache2 = ExecutableCache(manifest_path=man)
    cache2.warmup(batch_max=1)
    assert cache2.cost(key, 1) is None
    doc = json.loads(open(man).read())
    assert all("cost" not in e for e in doc["entries"])


def test_manifest_cost_loads_ignores_legacy_entries():
    key = _key()
    text = bk.manifest_dumps([(key, 1)])
    assert bk.manifest_cost_loads(text) == {}
    text = bk.manifest_dumps([(key, 1)], {(key, 1): {"flops": 42.0}})
    assert bk.manifest_cost_loads(text) == {(key, 1): {"flops": 42.0}}
    # loads() round-trips regardless (old readers unaffected)
    assert bk.manifest_loads(text) == [(key, 1)]


# ---------------------------------------------------------------------------
# health() surfacing
# ---------------------------------------------------------------------------


def test_health_surfaces_cost_devices_and_peak_bytes():
    devmon.on()
    metrics.on()
    # factor_cache=False: these tests measure the registry surface,
    # not factor routing — an env-armed SLATE_TPU_FACTOR_CACHE would
    # detour the stream off the bucket-build path
    svc = SolverService(cache=ExecutableCache(manifest_path=None),
                        batch_max=4, batch_window_s=0.002,
                        dim_floor=16, nrhs_floor=4, factor_cache=False)
    try:
        rng = np.random.default_rng(0)
        A = rng.standard_normal((12, 12)) + 12 * np.eye(12)
        X = svc.submit("gesv", A, rng.standard_normal((12, 2))).result(
            timeout=300)
        assert np.all(np.isfinite(X))
        h = svc.health()
        key = _key()
        per = h["cost"][key.label]
        assert per[1]["flops"] > 0 and per[1]["peak_bytes"] > 0
        assert h["latency"][key.label]["peak_bytes"] >= per[1]["peak_bytes"]
        assert isinstance(h["devices"], list) and h["devices"]
        assert h["devices"][0]["bytes_in_use"] is None  # CPU: graceful
    finally:
        svc.stop()


def test_health_devmon_off_is_none_and_costless():
    metrics.on()
    # factor_cache=False: these tests measure the registry surface,
    # not factor routing — an env-armed SLATE_TPU_FACTOR_CACHE would
    # detour the stream off the bucket-build path
    svc = SolverService(cache=ExecutableCache(manifest_path=None),
                        batch_max=4, batch_window_s=0.002,
                        dim_floor=16, nrhs_floor=4, factor_cache=False)
    try:
        rng = np.random.default_rng(0)
        A = rng.standard_normal((12, 12)) + 12 * np.eye(12)
        svc.submit("gesv", A, rng.standard_normal((12, 2))).result(
            timeout=300)
        h = svc.health()
        assert h["devices"] is None
        assert h["cost"] is None
        assert "peak_bytes" not in h["latency"][_key().label]
    finally:
        svc.stop()


def test_health_cost_gated_on_devmon_despite_preloaded_registry(tmp_path):
    """A cost-bearing manifest preloads the cache registry regardless,
    but health() must not claim the telemetry plane is armed when it
    is not (and must not pay the registry copy per poll)."""
    devmon.on()
    man = str(tmp_path / "warmup.json")
    cache = ExecutableCache(manifest_path=man)
    key = _key()
    cache.ensure_manifest(key, (1,))
    cache.warmup(batch_max=1)
    devmon.off()
    svc = SolverService(cache=ExecutableCache(manifest_path=man),
                        start=False)
    h = svc.health()
    assert h["cost"] is None and h["devices"] is None
    devmon.on()
    h = svc.health()
    assert h["cost"][key.label][1]["flops"] > 0


# ---------------------------------------------------------------------------
# tools: roofline_report + bench_diff
# ---------------------------------------------------------------------------


def _run_tool(tool, *argv):
    return subprocess.run(
        [sys.executable, os.path.join("tools", tool), *argv],
        cwd=HERE, capture_output=True, text=True,
    )


def _write_jsonl(path, rows):
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


def test_roofline_report_classifies_and_gates(tmp_path):
    jsonl = tmp_path / "m.jsonl"
    exe = "gesv.16x16x4.float64.b1"
    _write_jsonl(jsonl, [
        {"type": "cost", "name": f"serve.{exe}", "flops": 2.0e7,
         "bytes_accessed": 1.0e5, "peak_bytes": 40000,
         "device_kind": "cpu"},
        {"type": "timer", "name": f"serve.{exe}.run", "count": 10,
         "total_s": 0.01},
    ])
    r = _run_tool("roofline_report.py", str(jsonl))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "compute" in r.stdout  # AI 200 >> cpu ridge 2.5
    # a warmed bucket with no cost record is unclassifiable -> nonzero
    _write_jsonl(jsonl, [
        {"type": "cost", "name": f"serve.{exe}", "flops": 2.0e7,
         "bytes_accessed": 1.0e5, "device_kind": "cpu"},
        {"type": "timer", "name": "serve.other.b1.run", "count": 3,
         "total_s": 0.01},
    ])
    r = _run_tool("roofline_report.py", str(jsonl))
    assert r.returncode == 1
    assert "unclassifiable" in r.stdout
    # no cost rows at all: nothing to verify -> nonzero
    _write_jsonl(jsonl, [
        {"type": "timer", "name": f"serve.{exe}.run", "count": 1,
         "total_s": 0.01},
    ])
    assert _run_tool("roofline_report.py", str(jsonl)).returncode == 1


def test_roofline_report_memory_bound_verdict(tmp_path):
    jsonl = tmp_path / "m.jsonl"
    exe = "gesv.16x16x4.float64.solve.b1"
    _write_jsonl(jsonl, [
        {"type": "cost", "name": f"serve.{exe}", "flops": 1.0e4,
         "bytes_accessed": 1.0e5, "device_kind": "cpu"},  # AI 0.1
        {"type": "timer", "name": f"serve.{exe}.run", "count": 5,
         "total_s": 0.005},
    ])
    r = _run_tool("roofline_report.py", str(jsonl))
    assert r.returncode == 0 and "memory" in r.stdout


def _bench_doc(scale=1.0, peak_scale=1.0):
    return {
        "metric": "sgemm", "value": 100.0 * scale, "unit": "GFLOP/s",
        "extra": {
            "dgemm": {"gflops": 50.0 * scale,
                      "peak_bytes": int(1e6 * peak_scale)},
            "skippy": {"skipped": "time budget"},
        },
    }


def test_bench_diff_passes_flat_and_fails_regression(tmp_path):
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(_bench_doc()))
    b.write_text(json.dumps(_bench_doc(scale=0.9)))
    assert _run_tool("bench_diff.py", str(a), str(b)).returncode == 0
    b.write_text(json.dumps(_bench_doc(scale=0.5)))
    r = _run_tool("bench_diff.py", str(a), str(b))
    assert r.returncode == 1 and "REGRESSION" in r.stdout


def test_bench_diff_flags_memory_growth(tmp_path):
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(_bench_doc()))
    b.write_text(json.dumps(_bench_doc(peak_scale=2.0)))
    r = _run_tool("bench_diff.py", str(a), str(b))
    assert r.returncode == 1 and "MEM GROWTH" in r.stdout


def test_bench_diff_floor_mode(tmp_path):
    floor, live = tmp_path / "floor.json", tmp_path / "live.json"
    live.write_text(json.dumps(_bench_doc()))
    # floor rates well below live, peak ceiling generously above it
    floor.write_text(json.dumps(_bench_doc(scale=0.1, peak_scale=4.0)))
    r = _run_tool("bench_diff.py", "--floor", str(floor), str(live))
    assert r.returncode == 0, r.stdout
    live.write_text(json.dumps(_bench_doc(scale=0.01)))
    assert _run_tool(
        "bench_diff.py", "--floor", str(floor), str(live)
    ).returncode == 1


def test_bench_diff_tolerates_malformed_entries(tmp_path):
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    base = _bench_doc()
    base["extra"]["weird"] = 5  # non-dict entry: noted, never a crash
    a.write_text(json.dumps(base))
    b.write_text(json.dumps(_bench_doc()))
    r = _run_tool("bench_diff.py", str(a), str(b))
    assert r.returncode == 0 and "baseline entry malformed" in r.stdout
    # candidate-side malformed entry (same label present on both sides)
    base = _bench_doc()
    base["extra"]["weird"] = {"gflops": 1.0}
    cand = _bench_doc()
    cand["extra"]["weird"] = 5
    a.write_text(json.dumps(base))
    b.write_text(json.dumps(cand))
    r = _run_tool("bench_diff.py", str(a), str(b))
    assert r.returncode == 0 and "candidate entry malformed" in r.stdout


def test_bench_diff_nothing_compared_is_unusable(tmp_path):
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    # an all-errored sweep still prints a JSON line; diffing it must
    # not report a clean bill of health
    doc = {"metric": "m", "value": None, "unit": "x",
           "extra": {"e1": {"error": "boom"}, "e2": {"skipped": "t"}}}
    a.write_text(json.dumps(_bench_doc()))
    b.write_text(json.dumps(doc))
    assert _run_tool("bench_diff.py", str(b), str(a)).returncode == 2
    assert _run_tool("bench_diff.py", str(a), str(b)).returncode == 2


def test_bench_diff_accepts_wrapped_trajectory_artifacts(tmp_path):
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps({"rc": 0, "parsed": _bench_doc()}))
    b.write_text(json.dumps({"rc": 0, "parsed": _bench_doc(scale=1.1)}))
    assert _run_tool("bench_diff.py", str(a), str(b)).returncode == 0
    # an artifact with no parsed payload (BENCH_r05) is unusable: rc 2
    b.write_text(json.dumps({"rc": 124, "tail": "died"}))
    assert _run_tool("bench_diff.py", str(a), str(b)).returncode == 2


def test_checked_in_trajectory_pair_and_floor_exist():
    # the --perf gate's inputs stay in the tree and stay parseable
    for name in ("BENCH_r03.json", "BENCH_r04.json",
                 "BENCH_FLOOR_CPU.json"):
        path = os.path.join(HERE, name)
        assert os.path.exists(path), name
    r = _run_tool("bench_diff.py", "BENCH_r03.json", "BENCH_r04.json")
    assert r.returncode == 0, r.stdout
