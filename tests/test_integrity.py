"""Integrity-plane suite (ISSUE 14): ABFT checksum encoding + traced
checks, the SLATE_TPU_INTEGRITY policy grammar, delivery
certification (a finite-but-wrong X never reaches the client),
per-replica quarantine with probe recovery, hedged re-execution
(first-correct-result-wins), the residual_ok edge cases certification
leans on, and the lifecycle satellites (stop(drain=True),
wait_ready timeout + restore_stuck_s).

A module-scoped ExecutableCache is shared so each (bucket, batch)
executable compiles once for the file.
"""

import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

from slate_tpu.aux import faults, metrics
from slate_tpu.exceptions import NumericalError, SlateError
from slate_tpu.integrity import (
    ABFT_BAD,
    ABFT_TAG,
    IntegrityPolicy,
    IntegrityScore,
    abft_flops,
    checksum_certificate,
    encode,
    encode_rhs,
    overhead_ratio,
)
from slate_tpu.integrity import abft as abft_mod
from slate_tpu.integrity import policy as pol_mod
from slate_tpu.serve import buckets as bk
from slate_tpu.serve.cache import ExecutableCache
from slate_tpu.serve.factor_cache import (
    FactorCache,
    factor_only,
    residual_ok,
)
from slate_tpu.serve.service import Rejected, SolverService, _HedgeGroup

FLOOR = 16
NRHS_FLOOR = 4


@pytest.fixture(autouse=True)
def integrity_env():
    """Metrics on (counters are part of the contract under test),
    faults disarmed before AND after every test."""
    metrics.off()
    metrics.reset()
    metrics.on()
    faults.reset()
    yield
    faults.reset()
    metrics.off()
    metrics.reset()


@pytest.fixture(scope="module")
def shared_cache():
    return ExecutableCache(manifest_path=None)


def _svc(cache, **kw):
    cfg = dict(
        cache=cache, batch_max=4, batch_window_s=0.002,
        dim_floor=FLOOR, nrhs_floor=NRHS_FLOOR, degrade_after=2,
        retry_backoff_s=0.002, retry_backoff_cap_s=0.05,
        breaker_cooldown_s=0.05,
    )
    cfg.update(kw)
    return SolverService(**cfg)


def _gesv_problem(n=12, nrhs=2, seed=0):
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((n, n)) + n * np.eye(n)
    B = rng.standard_normal((n, nrhs))
    return A, B


def _posv_problem(n=12, nrhs=2, seed=0):
    rng = np.random.default_rng(seed)
    G = rng.standard_normal((n, n))
    A = G @ G.T + n * np.eye(n)
    B = rng.standard_normal((n, nrhs))
    return A, B


# ---------------------------------------------------------------------------
# ABFT encoding + checks
# ---------------------------------------------------------------------------


def test_encode_checksum_identities():
    rng = np.random.default_rng(3)
    A = rng.standard_normal((7, 7))
    Ac = encode(A)
    assert Ac.shape == (8, 8)
    np.testing.assert_allclose(Ac[:7, 7], A.sum(axis=1))
    np.testing.assert_allclose(Ac[7, :7], A.sum(axis=0))
    assert np.isclose(Ac[7, 7], A.sum())
    # the bordered form of an invertible A is exactly singular — the
    # documented reason the cores verify relations instead of
    # factoring the encoding
    assert abs(np.linalg.det(Ac)) < 1e-8
    B = rng.standard_normal((7, 3))
    Bc = encode_rhs(B)
    np.testing.assert_allclose(Bc[7], B.sum(axis=0))


def test_checksum_certificate_pass_and_catch():
    A, B = _gesv_problem(seed=1)
    X = np.linalg.solve(A, B)
    assert checksum_certificate(A, B, X)
    Xw = X.copy()
    Xw[3, 1] = Xw[3, 1] * 2 + 1  # the faults.perturb shape
    assert not checksum_certificate(A, B, Xw)
    Xn = X.copy()
    Xn[0, 0] = np.nan
    assert not checksum_certificate(A, B, Xn)


def test_checksum_certificate_complex_and_vector():
    rng = np.random.default_rng(5)
    n = 10
    A = (rng.standard_normal((n, n)) + 1j * rng.standard_normal((n, n))
         + n * np.eye(n)).astype(np.complex128)
    b = rng.standard_normal(n) + 1j * rng.standard_normal(n)
    x = np.linalg.solve(A, b)
    assert checksum_certificate(A, b, x)
    xw = x.copy()
    xw[4] = xw[4] * 2 + 1
    assert not checksum_certificate(A, b, xw)


def test_traced_checks_catch_factor_and_solve_corruption():
    """gesv_check/posv_check (the in-trace relations) flag corruption
    in the factor and in the solution, and pass on clean pipelines."""
    A, B = _gesv_problem(n=10, seed=2)
    F, perm = factor_only("gesv", A)
    X = np.linalg.solve(A, B)
    assert not bool(abft_mod.gesv_check(A, B, F, perm, X))
    Fw = F.copy()
    Fw[4, 7] = Fw[4, 7] * 2 + 1  # corrupt U
    assert bool(abft_mod.gesv_check(A, B, Fw, perm, X))
    Fw2 = F.copy()
    Fw2[7, 2] = Fw2[7, 2] * 2 + 1  # corrupt L
    assert bool(abft_mod.gesv_check(A, B, Fw2, perm, X))
    Xw = X.copy()
    Xw[0, 0] += 1.0
    assert bool(abft_mod.gesv_check(A, B, F, perm, Xw))

    S, Bs = _posv_problem(n=10, seed=3)
    L, _ = factor_only("posv", S)
    Xs = np.linalg.solve(S, Bs)
    assert not bool(abft_mod.posv_check(S, Bs, L, Xs))
    Lw = L.copy()
    Lw[6, 3] = Lw[6, 3] * 2 + 1
    assert bool(abft_mod.posv_check(S, Bs, Lw, Xs))


def test_abft_core_clean_and_flags_via_info():
    """The checksummed core returns info==0 on a clean solve and the
    correct X; driver info (positive) wins over the flag."""
    core = abft_mod.build_core("gesv", 16, "auto")
    A, B = _gesv_problem(n=12, seed=4)
    Ap = bk.pad_square(A, 16)
    Bp = bk.pad_rhs(B, 16, 4)
    Xg, info = core(Ap, Bp)
    assert int(info) == 0
    assert np.abs(
        np.asarray(Xg)[:12, :2] - np.linalg.solve(A, B)
    ).max() < 1e-9
    # a singular input surfaces as POSITIVE driver info, not ABFT_BAD
    As = np.zeros((16, 16))
    Xg, info = core(As, Bp)
    assert int(info) > 0


def test_abft_overhead_ratio_at_2048():
    """The accounting-mirror acceptance bound: checksum overhead is
    <= 15% of model FLOPs at n=2048 for both routines (it is in fact
    under 1% — the O(n^2)-vs-O(n^3) point of ABFT)."""
    for routine in ("gesv", "posv"):
        key = bk.bucket_for(routine, 2048, 2048, 8, np.float64,
                            tag=ABFT_TAG)
        r = overhead_ratio(key)
        assert 0 < r <= 0.15, (routine, r)
    assert abft_flops(2048, 8) > 0


# ---------------------------------------------------------------------------
# policy grammar + score state machine
# ---------------------------------------------------------------------------


def test_policy_grammar():
    assert pol_mod.parse_spec("") is None
    assert pol_mod.parse_spec("off") is None
    assert pol_mod.parse_spec("0") is None
    p = pol_mod.parse_spec("full")
    assert p.mode == "full" and not p.abft and p.should_check()
    p = pol_mod.parse_spec("sample=0.5,abft,hedge=2.5,cooldown=1.5")
    assert p.mode == "sample" and p.sample_p == 0.5 and p.abft
    assert p.hedge_factor == 2.5 and p.quarantine_cooldown_s == 1.5
    assert p.describe() == "sample=0.5,abft"
    for bad in ("bogus", "sample", "sample=2.0", "full,nope=1",
                "full,threshold=0"):
        with pytest.raises(ValueError):
            pol_mod.parse_spec(bad)


def test_policy_env_and_explicit_off(monkeypatch):
    monkeypatch.setenv(pol_mod.INTEGRITY_ENV, "full,abft")
    p = pol_mod.from_options(None)
    assert p is not None and p.abft
    # explicit False is the off-switch even with the env armed
    assert pol_mod.from_options(False) is None
    # explicit policy object passes through
    mine = IntegrityPolicy(mode="full")
    assert pol_mod.from_options(mine) is mine
    monkeypatch.setenv(pol_mod.INTEGRITY_ENV, "off")
    assert pol_mod.from_options(None) is None


def test_policy_sample_mode_is_seeded():
    a = IntegrityPolicy(mode="sample", sample_p=0.5, seed=7)
    b = IntegrityPolicy(mode="sample", sample_p=0.5, seed=7)
    assert [a.should_check() for _ in range(32)] == [
        b.should_check() for _ in range(32)
    ]


def test_integrity_score_lifecycle():
    s = IntegrityScore(alpha=0.5, threshold=0.6, cooldown_s=10.0)
    t = 100.0
    assert s.observe(False, t) is None  # ewma 0.5: under threshold
    assert s.observe(False, t) == "quarantined"  # 0.75 > 0.6
    assert s.state == pol_mod.SCORE_QUARANTINED
    assert s.excluded(t + 1.0)
    # an OK during the cooldown is noted, not a probe
    assert s.observe(True, t + 1.0) is None
    assert s.state == pol_mod.SCORE_QUARANTINED
    # a FAILED verdict extends the quarantine window
    assert s.observe(False, t + 2.0) is None
    assert s.excluded(t + 11.0)  # cooldown restarted at t+2
    # past the cooldown the lane is selectable; the next pass recovers
    assert not s.excluded(t + 13.0)
    assert s.observe(True, t + 13.0) == "recovered"
    assert s.state == pol_mod.SCORE_OK and s.ewma == 0.0
    assert s.quarantines == 1
    snap = s.snapshot(t + 14.0)
    assert snap["state"] == "ok" and snap["quarantined_for_s"] is None


def test_score_interleaved_ok_decays():
    s = IntegrityScore(alpha=0.5, threshold=0.6, cooldown_s=1.0)
    t = 0.0
    for _ in range(8):  # isolated failures between passes never trip
        assert s.observe(False, t) is None
        assert s.observe(True, t) is None
        assert s.observe(True, t) is None
    assert s.state == pol_mod.SCORE_OK


# ---------------------------------------------------------------------------
# residual_ok edge cases (the fence certification leans on)
# ---------------------------------------------------------------------------


def test_residual_ok_complex():
    rng = np.random.default_rng(11)
    n = 10
    A = (rng.standard_normal((n, n)) + 1j * rng.standard_normal((n, n))
         + n * np.eye(n)).astype(np.complex128)
    B = (rng.standard_normal((n, 2))
         + 1j * rng.standard_normal((n, 2))).astype(np.complex128)
    X = np.linalg.solve(A, B)
    assert residual_ok(A, B, X)
    Xw = X.copy()
    Xw[2, 0] = Xw[2, 0] * 2 + 1
    assert not residual_ok(A, B, Xw)


def test_residual_ok_nrhs1_column_vector():
    A, B = _gesv_problem(nrhs=1, seed=12)
    X = np.linalg.solve(A, B)
    assert X.shape == (12, 1)
    assert residual_ok(A, B, X)
    Xw = X.copy()
    Xw[5, 0] += 1.0
    assert not residual_ok(A, B, Xw)


def test_residual_ok_zero_rhs():
    A, _ = _gesv_problem(seed=13)
    B = np.zeros((12, 2))
    assert residual_ok(A, B, np.zeros((12, 2)))  # exact solve
    Xw = np.zeros((12, 2))
    Xw[0, 0] = 1.0  # wrong against B=0 must still be caught
    assert not residual_ok(A, B, Xw)


def test_residual_ok_pad_identity_block_does_not_mask():
    """The bucket pad [[A,0],[0,I]] solves the pad rows EXACTLY — an
    exact pad block must not mask a corrupt top-left block."""
    A, B = _gesv_problem(n=12, seed=14)
    S = 16
    Ap = bk.pad_square(A, S)
    Bp = bk.pad_rhs(B, S, 2)
    Xp = np.linalg.solve(Ap, Bp)
    assert residual_ok(Ap, Bp, Xp)
    Xw = Xp.copy()
    Xw[3, 1] = Xw[3, 1] * 2 + 1  # corruption INSIDE the real block
    assert not residual_ok(Ap, Bp, Xw)
    assert not checksum_certificate(Ap, Bp, Xw)


# ---------------------------------------------------------------------------
# service integration: certification, hedged re-execution, quarantine
# ---------------------------------------------------------------------------


def test_abft_buckets_route_and_serve_correctly(shared_cache):
    pol = IntegrityPolicy(mode="full", abft=True, hedge_factor=0.0)
    svc = _svc(shared_cache, integrity=pol)
    try:
        A, B = _gesv_problem(seed=20)
        X = svc.submit("gesv", A, B).result(timeout=300)
        assert np.abs(X - np.linalg.solve(A, B)).max() < 1e-8
        S, Bs = _posv_problem(seed=21)
        Xs = svc.submit("posv", S, Bs).result(timeout=300)
        assert np.abs(Xs - np.linalg.solve(S, Bs)).max() < 1e-8
        c = metrics.counters()
        assert c.get("serve.integrity.checked", 0) >= 2
        assert c.get("serve.integrity.fail", 0) == 0
        # the checksummed executables live under the abft tag
        labels = [k.label for k, _b in svc.cache.entries()]
        assert any(ABFT_TAG in lbl for lbl in labels), labels
    finally:
        svc.stop()


def test_abft_excluded_when_factor_cache_on(shared_cache):
    """ABFT and the factor cache are mutually exclusive per service:
    factor-eligible traffic keeps its (already residual-fenced)
    machinery and the plain bucket key."""
    pol = IntegrityPolicy(mode="full", abft=True, hedge_factor=0.0)
    svc = _svc(shared_cache, integrity=pol, factor_cache=FactorCache())
    try:
        A, B = _gesv_problem(seed=22)
        X = svc.submit("gesv", A, B).result(timeout=300)
        assert np.abs(X - np.linalg.solve(A, B)).max() < 1e-8
        c = metrics.counters()
        assert c.get("serve.factor_cache.miss", 0) >= 1
    finally:
        svc.stop()


def test_certificate_failure_hedges_and_recovers(shared_cache):
    """sdc_solve corrupts a delivered X (finite): certification
    catches it, the request re-executes hedged to the other replica,
    and the client gets the CORRECT answer — plus the full counter
    chain (fail -> hedge.sent -> recovered + hedge.won)."""
    pol = IntegrityPolicy(mode="full", hedge_factor=0.0,
                          quarantine_cooldown_s=5.0)
    svc = _svc(shared_cache, integrity=pol, replicas=2)
    try:
        A, B = _gesv_problem(seed=23)
        svc.submit("gesv", A, B).result(timeout=300)  # warm path
        faults.arm("sdc_solve", once=True)
        faults.on()
        probs = [_gesv_problem(seed=30 + i) for i in range(6)]
        futs = [svc.submit("gesv", a, b) for a, b in probs]
        for (a, b), f in zip(probs, futs):
            X = f.result(timeout=300)
            assert np.abs(X - np.linalg.solve(a, b)).max() < 1e-8
        c = metrics.counters()
        assert c.get("faults.injected.sdc_solve", 0) == 1
        assert c.get("serve.integrity.fail", 0) >= 1
        assert c.get("serve.integrity.recovered", 0) >= 1
        assert c.get("serve.hedge.sent", 0) >= 1
        assert c.get("serve.hedge.won", 0) >= 1
    finally:
        svc.stop()


def test_sdc_factor_caught_on_factor_path(shared_cache):
    """sdc_factor poisons a fresh factor: this request's X is wrong
    (certification catches, re-executes) AND the poisoned cached
    factor's later hits fall to the residual fence (counted stale)."""
    pol = IntegrityPolicy(mode="full", hedge_factor=0.0)
    svc = _svc(shared_cache, integrity=pol, factor_cache=FactorCache())
    try:
        A, B = _gesv_problem(seed=40)
        faults.arm("sdc_factor", once=True)
        faults.on()
        X = svc.submit("gesv", A, B).result(timeout=300)
        assert np.abs(X - np.linalg.solve(A, B)).max() < 1e-8
        c = metrics.counters()
        assert c.get("faults.injected.sdc_factor", 0) == 1
        detected = (
            c.get("serve.integrity.fail", 0)
            + c.get("serve.factor_cache.stale", 0)
        )
        assert detected >= 1, c
    finally:
        svc.stop()


def test_quarantine_engages_and_probes_back(shared_cache):
    pol = IntegrityPolicy(mode="full", hedge_factor=0.0,
                          quarantine_cooldown_s=0.15, cert_retry_max=1)
    # batch_max=1: sdc_solve perturbs only element [0] of the solved
    # batch, so a coalesced batch delivers passing certificates for
    # items 1..k-1 and the pass/fail interleave holds the EWMA under
    # the quarantine threshold — singleton batches make every delivery
    # fail and the trip deterministic
    svc = _svc(shared_cache, integrity=pol, replicas=2, batch_max=1)
    try:
        A, B = _gesv_problem(seed=50)
        svc.submit("gesv", A, B).result(timeout=300)  # warm
        faults.arm("sdc_solve", every=1)
        faults.on()
        futs = [svc.submit("gesv", *_gesv_problem(seed=60 + i))
                for i in range(8)]
        for f in futs:
            try:
                f.result(timeout=300)  # typed errors allowed; hangs not
            except SlateError:
                pass
        c = metrics.counters()
        assert c.get("serve.integrity.quarantined", 0) >= 1
        assert svc.health()["integrity"]["quarantined"]
        faults.reset()
        time.sleep(0.2)  # cooldown elapses; next delivery is the probe
        for i in range(4):
            a, b = _gesv_problem(seed=80 + i)
            X = svc.submit("gesv", a, b).result(timeout=300)
            assert np.abs(X - np.linalg.solve(a, b)).max() < 1e-8
        h = svc.health()
        assert not h["integrity"]["quarantined"], h["integrity"]
        assert metrics.counters().get(
            "serve.integrity.unquarantined", 0
        ) >= 1
    finally:
        svc.stop()


def test_quarantined_lane_excluded_at_admission(shared_cache):
    """Admission steers around a quarantined lane while its cooldown
    runs, and selects it again once the cooldown elapses (the probe
    window) — the breaker-exclusion shape, fed by certificates."""
    pol = IntegrityPolicy(mode="full", quarantine_cooldown_s=10.0)
    svc = _svc(shared_cache, integrity=pol, replicas=2, start=False)
    try:
        r0 = svc._replicas[0]
        now = time.monotonic()
        r0.score.observe(False, now)
        assert r0.score.observe(False, now) == "quarantined"
        key = bk.bucket_for("gesv", 12, 12, 2, np.float64, floor=FLOOR,
                            nrhs_floor=NRHS_FLOOR)
        with svc._cond:
            for _ in range(6):
                assert svc._pick_replica_locked(key) is svc._replicas[1]
        # rewind the quarantine epoch past the cooldown: selectable again
        r0.score.quarantined_at = now - 11.0
        with svc._cond:
            picks = {svc._pick_replica_locked(key).name for _ in range(6)}
        assert "0" in picks
    finally:
        svc.stop()


def test_posv_certified_with_junk_upper_triangle(shared_cache):
    """posv reads only the LOWER triangle (the api contract) — junk
    above the diagonal must not fail certification on a numerically
    correct X (the certificate symmetrizes, like the traced check)."""
    pol = IntegrityPolicy(mode="full", hedge_factor=0.0)
    svc = _svc(shared_cache, integrity=pol)
    try:
        A, B = _posv_problem(seed=200)
        Aj = A.copy()
        Aj[np.triu_indices(12, 1)] = 1e3  # garbage upper triangle
        X = svc.submit("posv", Aj, B).result(timeout=300)
        assert np.abs(X - np.linalg.solve(A, B)).max() < 1e-8
        c = metrics.counters()
        assert c.get("serve.integrity.checked", 0) >= 1
        assert c.get("serve.integrity.fail", 0) == 0
        assert c.get("serve.integrity.abandoned", 0) == 0
    finally:
        svc.stop()


def test_integrity_off_zero_touch(shared_cache):
    """Unconfigured plane: _integrity is None, no integrity metrics,
    correct X — the zero-overhead contract."""
    svc = _svc(shared_cache)
    try:
        assert svc._integrity is None
        A, B = _gesv_problem(seed=90)
        X = svc.submit("gesv", A, B).result(timeout=300)
        assert np.abs(X - np.linalg.solve(A, B)).max() < 1e-9
        c = metrics.counters()
        assert c.get("serve.integrity.checked", 0) == 0
        assert svc.health()["integrity"] is None
    finally:
        svc.stop()


# ---------------------------------------------------------------------------
# hedging mechanics (deterministic, no worker races)
# ---------------------------------------------------------------------------


def test_hedge_group_first_result_wins():
    from slate_tpu.serve.service import _Request, _resolve, _resolve_exc

    A, B = _gesv_problem(seed=100)
    fut = Future()
    grp = _HedgeGroup()
    prim = _Request(routine="gesv", key=None, A=A, B=B, m=12, n=12,
                    nrhs=2, future=fut, hedge_group=grp)
    clone = _Request(routine="gesv", key=None, A=A, B=B, m=12, n=12,
                     nrhs=2, future=fut, is_hedge=True, hedge_group=grp)
    X = np.linalg.solve(A, B)
    _resolve(fut, X, clone)  # the hedge wins
    _resolve(fut, X + 1, prim)  # the primary arrives late: wasted
    assert np.abs(fut.result(timeout=1) - X).max() == 0
    c = metrics.counters()
    assert c.get("serve.hedge.won", 0) == 1
    assert c.get("serve.hedge.wasted", 0) == 1

    # exception suppression: one member's failure never fails the
    # future while its twin can still deliver; both failing does
    fut2 = Future()
    grp2 = _HedgeGroup()
    p2 = _Request(routine="gesv", key=None, A=A, B=B, m=12, n=12,
                  nrhs=2, future=fut2, hedge_group=grp2)
    c2 = _Request(routine="gesv", key=None, A=A, B=B, m=12, n=12,
                  nrhs=2, future=fut2, is_hedge=True, hedge_group=grp2)
    _resolve_exc(fut2, NumericalError("lane a died"), req=c2)
    assert not fut2.done()
    _resolve_exc(fut2, NumericalError("lane b died"), req=p2)
    with pytest.raises(NumericalError):
        fut2.result(timeout=1)


def test_straggler_sweep_clones_to_other_lane(shared_cache):
    """The straggler sweep hedges a queued request whose age passed
    the bucket p99 onto the other lane (semi-unit: paused service,
    hand-seeded histogram, no worker races)."""
    pol = IntegrityPolicy(mode="full", hedge_factor=1.0,
                          hedge_min_age_s=0.0)
    svc = _svc(shared_cache, integrity=pol, replicas=2, start=False)
    try:
        from slate_tpu.serve.service import _Request

        A, B = _gesv_problem(seed=110)
        key = bk.bucket_for("gesv", 12, 12, 2, np.float64, floor=FLOOR,
                            nrhs_floor=NRHS_FLOOR)
        for _ in range(4):  # the p99 history the trigger compares to
            metrics.observe_hist(f"serve.latency.{key.label}.total",
                                 0.001)
        req = _Request(routine="gesv", key=key, A=A, B=B, m=12, n=12,
                       nrhs=2)
        req.t_submit = time.monotonic() - 0.5  # well past p99
        svc._replicas[0].q.append(req)
        with svc._cond:
            svc._hedge_stragglers_locked(time.monotonic())
        assert len(svc._replicas[1].q) == 1
        clone = svc._replicas[1].q[0]
        assert clone.is_hedge and clone.hedge_group is req.hedge_group
        assert clone.future is req.future
        assert metrics.counters().get("serve.hedge.sent", 0) == 1
        # idempotent: a hedged request is never hedged twice
        with svc._cond:
            svc._hedge_stragglers_locked(time.monotonic())
        assert metrics.counters().get("serve.hedge.sent", 0) == 1
    finally:
        svc.stop()


# ---------------------------------------------------------------------------
# satellites: stop(drain=True), wait_ready timeout + restore_stuck_s
# ---------------------------------------------------------------------------


def test_stop_drain_completes_inflight(shared_cache):
    svc = _svc(shared_cache)
    A, B = _gesv_problem(seed=120)
    svc.submit("gesv", A, B).result(timeout=300)  # warm
    faults.arm("latency", every=1, ms=30)
    faults.on()
    probs = [_gesv_problem(seed=130 + i) for i in range(4)]
    futs = [svc.submit("gesv", a, b) for a, b in probs]
    svc.stop(drain=True, drain_timeout=30.0)
    faults.reset()
    for (a, b), f in zip(probs, futs):
        X = f.result(timeout=5)  # already resolved: drain completed it
        assert np.abs(X - np.linalg.solve(a, b)).max() < 1e-8
    c = metrics.counters()
    assert c.get("serve.drained", 0) >= 1
    assert c.get("serve.drain_abandoned", 0) == 0
    # admission is closed the moment the drain starts
    with pytest.raises(Rejected):
        svc.submit("gesv", A, B)


def test_stop_drain_bounded_abandons(shared_cache):
    svc = _svc(shared_cache)
    A, B = _gesv_problem(seed=140)
    svc.submit("gesv", A, B).result(timeout=300)  # warm
    faults.arm("latency", every=1, ms=300)
    faults.on()
    futs = [svc.submit("gesv", *_gesv_problem(seed=150 + i))
            for i in range(3)]
    svc.stop(drain=True, drain_timeout=0.05)
    faults.reset()
    resolved = 0
    for f in futs:
        try:
            f.result(timeout=10)
            resolved += 1
        except SlateError:
            resolved += 1  # Rejected leftovers: typed, never hung
    assert resolved == 3
    assert metrics.counters().get("serve.drain_abandoned", 0) >= 1


class _BlockingRestoreCache(ExecutableCache):
    """restore() parks on an event — the wedged-restore-thread
    scenario the wait_ready timeout + restore_stuck_s satellite is
    for."""

    def __init__(self):
        super().__init__(manifest_path=None)
        self.release = threading.Event()

    def restore(self, **kw):
        self.release.wait(timeout=30.0)
        return {"entries": 0, "restored": 0, "compiled": 0,
                "failed": 0, "skipped": 0}


def test_wait_ready_timeout_and_restore_stuck():
    cache = _BlockingRestoreCache()
    svc = SolverService(cache=cache, batch_max=2, dim_floor=FLOOR,
                        nrhs_floor=NRHS_FLOOR, restore_on_start=True,
                        restore_stuck_after_s=0.01)
    try:
        assert svc.wait_ready(0.15) is False  # bounded, returns False
        h = svc.health()
        assert h["phase"] == "restoring"
        assert h["restore_stuck_s"] is not None
        assert h["restore_stuck_s"] > 0.01
        cache.release.set()
        assert svc.wait_ready(10.0) is True
        h = svc.health()
        assert h["restore_stuck_s"] is None and h["phase"] == "ready"
    finally:
        cache.release.set()
        svc.stop()


def test_sdc_sites_registered():
    """The new sites are first-class in the faults registry (armable,
    SITE_SPECS-joined for chaos_report and the fault-site lint rule)."""
    from slate_tpu.aux.faults import SITE_REGISTRY

    for site in ("sdc_factor", "sdc_solve"):
        assert site in SITE_REGISTRY
        assert SITE_REGISTRY[site].recovery  # never a ghost site
        faults.arm(site, once=True)
    faults.reset()
