"""Philox matgen RNG tests (reference semantics: matgen/random.cc).

The key property under test: element (i, j) value depends only on
(seed, i, j) — never on tiling, sub-matrix offsets, or backend.
"""

import numpy as np
import pytest

from slate_tpu.matgen import philox


def _ij(m, n, ioff=0, joff=0):
    i = np.arange(ioff, ioff + m, dtype=np.uint64)[:, None]
    j = np.arange(joff, joff + n, dtype=np.uint64)[None, :]
    return np.broadcast_arrays(i + 0 * j, 0 * i + j)


class TestPhiloxCore:
    def test_reference_vector_identity(self):
        """philox_2x64({0,0}, 0) — pin the implementation with a self-vector
        and check basic statistical sanity of the stream."""
        L, R = philox.philox_2x64_np(np.uint64(0), np.uint64(0), 0)
        # must be deterministic and nonzero
        L2, R2 = philox.philox_2x64_np(np.uint64(0), np.uint64(0), 0)
        assert L == L2 and R == R2
        assert L != 0 and R != 0

    def test_distinct_counters_distinct_streams(self):
        i, j = _ij(64, 64)
        L, R = philox.philox_2x64_np(i, j, 1234)
        flat = np.stack([L.ravel(), R.ravel()], axis=1)
        assert len(np.unique(flat, axis=0)) == flat.shape[0]

    def test_seed_changes_stream(self):
        i, j = _ij(8, 8)
        L1, _ = philox.philox_2x64_np(i, j, 1)
        L2, _ = philox.philox_2x64_np(i, j, 2)
        assert not np.array_equal(L1, L2)

    def test_jnp_matches_np_bits(self):
        i, j = _ij(33, 17, ioff=5, joff=900)
        Ln, Rn = philox.philox_2x64_np(i, j, 42)
        (Lh, Ll), (Rh, Rl) = philox.philox_2x64_jnp(
            np.asarray(i, np.int64), np.asarray(j, np.int64), 42
        )
        L_j = (np.asarray(Lh, np.uint64) << np.uint64(32)) | np.asarray(Ll, np.uint64)
        R_j = (np.asarray(Rh, np.uint64) << np.uint64(32)) | np.asarray(Rl, np.uint64)
        np.testing.assert_array_equal(L_j, Ln)
        np.testing.assert_array_equal(R_j, Rn)


class TestDistributions:
    @pytest.mark.parametrize("dist", philox.DISTS)
    @pytest.mark.parametrize("dtype", [np.float32, np.float64])
    def test_offset_independence(self, dist, dtype):
        """Sub-matrix generation at offset equals slice of full generation
        (what makes generation tiling-independent; random.cc:163-175)."""
        i, j = _ij(16, 16)
        full = philox.random_np(dist, 7, i, j, dtype)
        i2, j2 = _ij(4, 4, ioff=8, joff=8)
        sub = philox.random_np(dist, 7, i2, j2, dtype)
        np.testing.assert_array_equal(full[8:12, 8:12], sub)

    @pytest.mark.parametrize("dtype", [np.float32, np.float64])
    def test_uniform_range(self, dtype):
        i, j = _ij(64, 64)
        x = philox.random_np("uniform", 3, i, j, dtype)
        assert x.min() >= 0.0 and x.max() < 1.0
        assert abs(x.mean() - 0.5) < 0.02

    def test_normal_moments(self):
        i, j = _ij(256, 256)
        x = philox.random_np("normal", 5, i, j, np.float64)
        assert abs(x.mean()) < 0.01
        assert abs(x.std() - 1.0) < 0.01

    def test_complex_parts(self):
        i, j = _ij(16, 16)
        z = philox.random_np("uniform", 11, i, j, np.complex128)
        re = philox.random_np("uniform", 11, i, j, np.float64)
        np.testing.assert_array_equal(z.real, re)
        assert np.all(z.imag >= 0) and np.all(z.imag < 1)

    @pytest.mark.parametrize("dist", ["uniform", "uniform_signed", "binary_signed"])
    def test_jnp_matches_np_values(self, dist):
        i, j = _ij(16, 16)
        xn = philox.random_np(dist, 9, i, j, np.float64)
        xj = philox.random_jnp(dist, 9, np.asarray(i, np.int64), np.asarray(j, np.int64), np.float64)
        np.testing.assert_array_equal(np.asarray(xj), xn)

    def test_jnp_matches_np_normal_close(self):
        i, j = _ij(16, 16)
        xn = philox.random_np("normal", 9, i, j, np.float64)
        xj = philox.random_jnp("normal", 9, np.asarray(i, np.int64), np.asarray(j, np.int64), np.float64)
        np.testing.assert_allclose(np.asarray(xj), xn, rtol=1e-12, atol=1e-12)
