"""Observability layer tests: aux/metrics.py (counters/gauges/timers,
compile-vs-run split, cost_analysis capture, JSONL round-trip,
zero-overhead-when-off, thread safety, the fallback/precision counters)
and aux/trace.py (Block nesting, traced, SVG output, shared timeline)."""

import json
import threading

import numpy as np
import pytest

from slate_tpu.aux import metrics, trace


@pytest.fixture(autouse=True)
def _clean_registry():
    """Every test starts and ends with metrics+trace off and empty."""
    metrics.off()
    metrics.reset()
    trace.off()
    trace.clear()
    yield
    metrics.off()
    metrics.reset()
    trace.off()
    trace.clear()


# ---------------------------------------------------------------------------
# counters / gauges / timers
# ---------------------------------------------------------------------------


def test_counters_and_gauges():
    metrics.on()
    metrics.inc("a")
    metrics.inc("a", 2)
    metrics.inc("b", 0.5)
    metrics.gauge("g", 3.25)
    assert metrics.counters() == {"a": 3, "b": 0.5}
    assert metrics.gauges() == {"g": 3.25}
    metrics.reset()
    assert metrics.counters() == {}
    assert metrics.gauges() == {}


def test_timer_stats():
    metrics.on()
    metrics.observe("t", 0.5)
    metrics.observe("t", 1.5)
    t = metrics.timers()["t"]
    assert t["count"] == 2
    assert t["total_s"] == pytest.approx(2.0)
    assert t["min_s"] == pytest.approx(0.5)
    assert t["max_s"] == pytest.approx(1.5)


def test_phase_records_timer_and_event():
    metrics.on()
    with metrics.phase("work") as ph:
        pass
    assert ph.seconds >= 0.0
    assert metrics.timers()["work"]["count"] == 1
    assert metrics.summary()["timers"]["work"]["count"] == 1


def test_phase_always_measures_without_recording():
    assert not metrics.is_on()
    with metrics.phase("hidden", always=True) as ph:
        x = sum(range(100))
    assert x == 4950
    assert ph.seconds > 0.0  # measured for the caller...
    metrics.on()
    assert metrics.timers() == {}  # ...but nothing was recorded


# ---------------------------------------------------------------------------
# histograms: log buckets, percentiles, deltas, JSONL
# ---------------------------------------------------------------------------


def test_histogram_percentiles_accurate_to_a_bucket():
    """p50/p95/p99 from the log-spaced buckets track the exact sample
    percentiles within one bucket ratio (10/decade => ~26% worst case;
    lognormal latencies land well inside that)."""
    import random

    random.seed(7)
    vals = sorted(random.lognormvariate(-5, 1) for _ in range(4000))
    metrics.on()
    for v in vals:
        metrics.observe_hist("lat", v)
    for p in (50, 95, 99):
        est = metrics.percentile("lat", p)
        exact = vals[int(p / 100 * len(vals)) - 1]
        assert est == pytest.approx(exact, rel=0.3), p
    s = metrics.hist_summary("lat")
    assert s["count"] == 4000
    assert s["min_s"] == pytest.approx(vals[0], abs=1e-6)  # 6-dp rounded
    assert s["max_s"] == pytest.approx(vals[-1], abs=1e-6)
    assert s["p50"] <= s["p95"] <= s["p99"] <= s["max_s"]


def test_histogram_extremes_clamped():
    metrics.on()
    metrics.observe_hist("h", 1e-9)   # underflow bucket
    metrics.observe_hist("h", 5e3)    # overflow bucket
    assert metrics.percentile("h", 1) == pytest.approx(1e-9)
    assert metrics.percentile("h", 99) == pytest.approx(5e3)


def test_histogram_single_observation_exact():
    metrics.on()
    metrics.observe_hist("one", 0.0123)
    # min/max clamping makes a single-sample histogram exact at every p
    assert metrics.percentile("one", 50) == pytest.approx(0.0123)
    assert metrics.percentile("one", 99) == pytest.approx(0.0123)


def test_observe_hist_off_is_noop():
    assert not metrics.is_on()
    metrics.observe_hist("h", 1.0)
    metrics.on()
    assert metrics.histograms() == {}
    assert metrics.percentile("h", 50) is None
    assert metrics.hist_summary("h") is None


def test_deltas_hist_windows_percentiles():
    metrics.on()
    metrics.observe_hist("d", 100.0)  # pre-window outlier
    with metrics.deltas() as d:
        for v in (0.001, 0.002, 0.004, 0.008):
            metrics.observe_hist("d", v)
        w = d.hist("d")
    assert w["count"] == 4
    assert w["total_s"] == pytest.approx(0.015)
    assert w["p99"] < 0.02  # the pre-window 100s sample is excluded
    assert d.hist("missing") is None


def test_hist_jsonl_round_trip_and_report(tmp_path):
    metrics.on()
    for v in (0.001, 0.01, 0.1):
        metrics.observe_hist("serve.latency.test.total", v)
    rep = metrics.report()
    assert "histogram" in rep and "serve.latency.test.total" in rep
    path = str(tmp_path / "h.jsonl")
    metrics.dump(path)
    rows = metrics.load_jsonl(path)
    h = [r for r in rows if r["type"] == "hist"]
    assert len(h) == 1 and h[0]["name"] == "serve.latency.test.total"
    assert h[0]["count"] == 3
    assert sum(c for _le, c in h[0]["buckets"]) == 3
    # bucket upper edges bracket the observations
    les = [le for le, _c in h[0]["buckets"]]
    assert all(isinstance(le, float) for le in les)
    # the percentile helper re-ranks from the wire form the same way
    counts = [0] * (len(metrics.HIST_EDGES) + 1)
    edge_index = {f"{e:.9g}": i for i, e in enumerate(metrics.HIST_EDGES)}
    for le, c in h[0]["buckets"]:
        counts[edge_index[f"{le:.9g}"]] = c
    est = metrics.Histogram.percentile_from(counts, 99)
    assert est == pytest.approx(h[0]["p99"], rel=0.35)
    assert metrics.summary()["histograms"]["serve.latency.test.total"][
        "count"] == 3


def test_driver_phase_feeds_histogram():
    """kind="driver" phases (the @instrumented decorator) land in a
    same-named histogram — factor/solve percentiles for free."""
    metrics.on()

    @metrics.instrumented("hist_drv")
    def drv():
        return 1

    for _ in range(3):
        drv()
    assert metrics.hist_summary("hist_drv")["count"] == 3
    # plain phases do NOT (timers already cover them)
    with metrics.phase("plain"):
        pass
    assert metrics.hist_summary("plain") is None


def test_hist_reset_clears():
    metrics.on()
    metrics.observe_hist("h", 1.0)
    metrics.reset()
    assert metrics.histograms() == {}


# ---------------------------------------------------------------------------
# zero overhead when off
# ---------------------------------------------------------------------------


def test_off_records_nothing():
    assert not metrics.is_on()
    metrics.inc("n")
    metrics.gauge("g", 1)
    metrics.observe("t", 1.0)
    with metrics.phase("p"):
        pass

    @metrics.instrumented("fn")
    def fn(x):
        return x + 1

    assert fn(1) == 2
    metrics.on()
    assert metrics.counters() == {}
    assert metrics.gauges() == {}
    assert metrics.timers() == {}


def test_instrumented_off_is_single_bool_check():
    """With metrics AND trace off the wrapper takes the early-return
    branch: no Timer object, no dict writes (the zero-overhead contract,
    like trace.on_ in the reference)."""
    calls = []

    @metrics.instrumented("probe")
    def fn():
        calls.append(metrics.is_on() or trace.is_on())

    fn()
    assert calls == [False]
    metrics.on()
    assert metrics.timers() == {}  # the off-path call left no trace


def test_instrument_jit_off_passthrough():
    import jax

    jitted = jax.jit(lambda x: x * 2)
    wrapped = metrics.instrument_jit(jitted, "double")
    out = wrapped(np.float64(3.0))
    assert float(out) == 6.0
    metrics.on()
    assert metrics.counters() == {}


# ---------------------------------------------------------------------------
# compile/run split + cost_analysis
# ---------------------------------------------------------------------------


def test_compile_run_split_tiny_jit():
    import jax.numpy as jnp

    metrics.on()
    f = metrics.jit(lambda a, b: a @ b, name="mm")
    x = jnp.ones((8, 8))
    f(x, x)  # first dispatch: compile
    f(x, x)  # cached: run
    f(x, x)
    c = metrics.counters()
    assert c["mm.compilations"] == 1
    assert c["jit.compilations"] == 1
    t = metrics.timers()
    assert t["mm.compile"]["count"] == 1
    assert t["mm.run"]["count"] == 2
    # a new shape signature recompiles — the recompile-storm signal
    y = jnp.ones((4, 4))
    f(y, y)
    assert metrics.counters()["mm.compilations"] == 2


def test_cost_analysis_flops_captured():
    import jax.numpy as jnp

    metrics.on()
    f = metrics.jit(lambda a, b: a @ b, name="mm8")
    x = jnp.ones((8, 8), jnp.float32)
    f(x, x)
    cost = metrics.costs().get("mm8")
    assert cost is not None
    assert cost["flops"] == pytest.approx(2 * 8**3 / 2, rel=1.0)  # 8^3..2*8^3
    assert cost["bytes_accessed"] > 0


def test_traced_calls_inside_outer_jit():
    """Calls inlined into an outer jit (tracer args) pass through with a
    counter instead of bogus trace-time timings."""
    import jax
    import jax.numpy as jnp

    metrics.on()
    inner = metrics.jit(lambda a: a + 1, name="inner")
    outer = jax.jit(lambda a: inner(a) * 2)
    out = outer(jnp.ones((4,)))
    np.testing.assert_array_equal(np.asarray(out), 4.0)
    c = metrics.counters()
    assert c.get("inner.traced_calls", 0) >= 1
    assert "inner.compilations" not in c


# ---------------------------------------------------------------------------
# JSONL round-trip + report
# ---------------------------------------------------------------------------


def test_jsonl_round_trip(tmp_path):
    import jax.numpy as jnp

    metrics.on()
    f = metrics.jit(lambda a: a * 2, name="x2")
    f(jnp.ones((4,)))
    metrics.inc("c", 7)
    with metrics.context("entry1"):
        with metrics.phase("ph"):
            pass
    path = str(tmp_path / "m.jsonl")
    assert metrics.dump(path) == path
    rows = metrics.load_jsonl(path)
    types = {r["type"] for r in rows}
    assert {"meta", "event", "counter", "timer"} <= types
    assert rows[0]["type"] == "meta"
    counters = {r["name"]: r["value"] for r in rows if r["type"] == "counter"}
    assert counters["c"] == 7
    events = [r for r in rows if r["type"] == "event"]
    kinds = {e["kind"] for e in events}
    assert "compile" in kinds and "phase" in kinds
    ph = [e for e in events if e["name"] == "ph"][0]
    assert ph["context"] == "entry1"
    # every line is valid standalone JSON (the exporter contract)
    with open(path) as fh:
        for line in fh:
            json.loads(line)


def test_report_table():
    metrics.on()
    metrics.observe("alpha", 0.25)
    metrics.inc("beta", 2)
    rep = metrics.report()
    assert "alpha" in rep and "beta" in rep and "timer" in rep


# ---------------------------------------------------------------------------
# thread safety
# ---------------------------------------------------------------------------


def test_thread_safety_counters_and_timers():
    metrics.on()
    N, M = 8, 200

    def work(i):
        for _ in range(M):
            metrics.inc("shared")
            metrics.observe(f"t{i % 2}", 0.001)
            with metrics.phase(f"p{i % 2}"):
                pass

    threads = [threading.Thread(target=work, args=(i,)) for i in range(N)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert metrics.counters()["shared"] == N * M
    t0 = metrics.timers()["t0"]
    t1 = metrics.timers()["t1"]
    assert t0["count"] + t1["count"] == N * M
    p0 = metrics.timers()["p0"]
    p1 = metrics.timers()["p1"]
    assert p0["count"] + p1["count"] == N * M


# ---------------------------------------------------------------------------
# wired counters: fallbacks, precision policy
# ---------------------------------------------------------------------------


def test_fallbacks_gathered_counter_increments(rng, grid22):
    """The gathered-fallback route must bump `fallbacks.gathered` (the
    aggregate MULTICHIP dryruns grep for) and the per-route counter."""
    from slate_tpu.drivers import blas3
    from slate_tpu.enums import Side, Uplo
    from slate_tpu.internal import fallbacks
    from slate_tpu.matrix.matrix import Matrix, TriangularMatrix

    metrics.on()
    fallbacks.reset()
    n, nb = 64, 16
    L0 = np.tril(rng.standard_normal((n, n))) + n * np.eye(n)
    L = TriangularMatrix.from_global(L0, nb, grid=grid22, uplo=Uplo.Lower)
    # non-conformable tiles (B mb != A nb): known gathered fallback
    B = Matrix.from_global(rng.standard_normal((n, 4)), 32, grid=grid22)
    blas3.trmm(Side.Left, 1.0, L, B)
    c = metrics.counters()
    assert c.get("fallbacks.gathered") == 1
    assert c.get("fallbacks.trmm") == 1
    # the legacy per-route Counter still ticks independently
    assert fallbacks.counters().get("trmm") == 1
    fallbacks.reset()


def test_precision_activation_counter(rng):
    import slate_tpu as st

    metrics.on()
    A = st.Matrix.from_global(
        rng.standard_normal((32, 32)).astype(np.float32), 16
    )
    B = st.Matrix.from_global(
        rng.standard_normal((32, 32)).astype(np.float32), 16
    )
    C = st.Matrix.from_global(np.zeros((32, 32), np.float32), 16)
    st.gemm(1.0, A, B, 0.0, C)
    assert metrics.counters().get(
        "precision.accurate_matmul_activations", 0) >= 1
    metrics.reset()
    # f64 inputs do not activate the policy
    A64 = st.Matrix.from_global(rng.standard_normal((32, 32)), 16)
    C64 = st.Matrix.from_global(np.zeros((32, 32)), 16)
    st.gemm(1.0, A64, A64, 0.0, C64)
    assert "precision.accurate_matmul_activations" not in metrics.counters()


def test_accurate_matmul_attached_to_eig_drivers():
    """Round-5 regression: @accurate_matmul must sit on he2hb itself (it
    had been displaced onto the _size_bucket_runs helper, silently
    running f32/c64 he2hb at bf16-pass precision)."""
    from slate_tpu.drivers import eig

    for fn in (eig.he2hb, eig.unmtr_he2hb, eig.heev, eig.hegst, eig.hegv):
        assert getattr(fn, "_accurate_matmul", False), fn.__name__
    # the helper is NOT a driver and must not carry the policy wrapper
    assert not hasattr(eig._size_bucket_runs, "_accurate_matmul")


def test_he2hb_f32_band_accuracy(rng):
    """f32 he2hb must preserve the spectrum to f32-parity bounds (guards
    the precision-policy placement end to end on CPU)."""
    import slate_tpu as st
    from slate_tpu.drivers.eig import he2hb

    n, nb = 48, 8
    G = rng.standard_normal((n, n)).astype(np.float32)
    S = ((G + G.T) / 2).astype(np.float32)
    A = st.HermitianMatrix.from_global(S, nb, uplo=st.Uplo.Lower)
    band, V, T = he2hb(A)
    wb = np.linalg.eigvalsh(np.asarray(band.full_global(), dtype=np.float64))
    wa = np.linalg.eigvalsh(S.astype(np.float64))
    scale = max(np.abs(wa).max(), 1.0)
    assert np.abs(wb - wa).max() / scale < 50 * n * np.finfo(np.float32).eps


# ---------------------------------------------------------------------------
# trace.py coverage: Block nesting, traced, SVG, shared timeline
# ---------------------------------------------------------------------------


def test_trace_block_nesting(tmp_path):
    trace.on()
    with trace.Block("outer"):
        with trace.Block("inner"):
            pass
    trace.off()
    names = {e.name for e in trace._events}
    assert names == {"outer", "inner"}
    inner = next(e for e in trace._events if e.name == "inner")
    outer = next(e for e in trace._events if e.name == "outer")
    # nested block is contained in the outer interval
    assert outer.start <= inner.start and inner.stop <= outer.stop


def test_traced_decorator_records_only_when_on():
    calls = []

    @trace.traced("fn")
    def fn():
        calls.append(1)

    fn()
    assert trace._events == [] and calls == [1]
    trace.on()
    fn()
    assert [e.name for e in trace._events] == ["fn"]


def test_trace_svg_output(tmp_path):
    trace.on()
    with trace.Block("phase_a"):
        pass
    with trace.Block("phase_b"):
        pass
    path = str(tmp_path / "trace.svg")
    out = trace.finish(path)
    assert out == path
    svg = open(path).read()
    assert svg.startswith("<svg")
    assert "phase_a" in svg and "phase_b" in svg


def test_metrics_phase_lands_on_trace_timeline(tmp_path):
    """Metrics phases and trace blocks share one timeline: finish() must
    render phases recorded through metrics while tracing is on."""
    trace.on()
    metrics.on()
    with metrics.phase("metric_phase"):
        pass
    with trace.Block("trace_block"):
        pass
    path = str(tmp_path / "t.svg")
    trace.finish(path)
    svg = open(path).read()
    assert "metric_phase" in svg and "trace_block" in svg


def test_instrumented_records_trace_when_metrics_off():
    """@instrumented subsumes trace.traced: tracing alone still gets the
    block even with the metrics registry off."""

    @metrics.instrumented("drv")
    def drv():
        return 7

    trace.on()
    assert drv() == 7
    assert [e.name for e in trace._events] == ["drv"]
    metrics.on()
    assert metrics.timers() == {}  # metrics stayed off during the call
