"""Native blocked Cholesky kernels (ops/chol_kernels.py) vs the vendor
factorization.  These run the accelerator path explicitly (the CPU
dispatcher would pick the vendor kernel), covering the block/panel
shapes the chip uses: unblocked ib strips, single-level panels, and the
two-level coarse recursion with the explicit panel inverse."""

import jax.numpy as jnp
import numpy as np
import pytest
import jax

from slate_tpu.ops.chol_kernels import (
    blocked_potrf,
    chol_fori,
    chol_unblocked,
    cholesky,
)


def _spd(n, dtype=jnp.float64, seed=0):
    key = jax.random.PRNGKey(seed)
    if jnp.issubdtype(dtype, jnp.complexfloating):
        rt = jnp.float64 if dtype == jnp.complex128 else jnp.float32
        a = jax.random.normal(key, (n, n), rt) + 1j * jax.random.normal(
            jax.random.PRNGKey(seed + 1), (n, n), rt
        )
        a = a.astype(dtype)
        return a @ jnp.conj(a).T + n * jnp.eye(n, dtype=dtype)
    a = jax.random.normal(key, (n, n), dtype)
    return a @ a.T + n * jnp.eye(n, dtype=dtype)


@pytest.mark.parametrize("n", [16, 64, 100, 256])
def test_chol_unblocked(n):
    S = _spd(n)
    L = chol_unblocked(S)
    ref = np.linalg.cholesky(np.asarray(S))
    assert np.allclose(np.asarray(L), ref, atol=1e-10 * n)


@pytest.mark.parametrize("n,nb", [(512, 128), (768, 256)])
def test_chol_fori(n, nb):
    S = _spd(n)
    L = chol_fori(S, nb)
    ref = np.linalg.cholesky(np.asarray(S))
    assert np.allclose(np.asarray(L), ref, atol=1e-10 * n)


@pytest.mark.parametrize(
    "n,nb",
    [
        (512, 128),     # single-level panels
        pytest.param(1280, 128, marks=pytest.mark.slow),    # coarse recursion, 2 levels
        pytest.param(1536, 256, marks=pytest.mark.slow),    # coarse, uneven last panel
    ],
)
def test_blocked_potrf(n, nb):
    S = _spd(n)
    L = blocked_potrf(S, nb)
    ref = np.linalg.cholesky(np.asarray(S))
    assert np.abs(np.asarray(L) - ref).max() / np.abs(ref).max() < 1e-12


def test_blocked_potrf_complex():
    S = _spd(256, jnp.complex128)
    L = blocked_potrf(S, 128)
    res = np.asarray(L @ jnp.conj(L).T - S)
    assert np.abs(res).max() / np.abs(np.asarray(S)).max() < 1e-12


def test_blocked_potrf_f32():
    S = _spd(384, jnp.float32)
    L = blocked_potrf(S, 128)
    res = np.asarray(L @ L.T - S)
    assert np.abs(res).max() / np.abs(np.asarray(S)).max() < 1e-4


def test_nonspd_yields_nan():
    S = _spd(128)
    S = S.at[60, 60].set(-1e6)
    L = blocked_potrf(S, 128)
    assert not bool(jnp.all(jnp.isfinite(L)))


def test_cholesky_dispatcher_cpu_matches():
    # on CPU the dispatcher uses the vendor kernel; just check contract
    S = _spd(200)
    L = cholesky(S)
    ref = np.linalg.cholesky(np.asarray(S))
    assert np.allclose(np.asarray(L), ref, atol=1e-8)
