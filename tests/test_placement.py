"""Placement tier tests: serve/placement policy, mesh-keyed buckets,
replica scale-out, spmd routing, and the mesh-aware warmup/restore.

Pure policy pieces (mesh grammar, thresholds, replica selection under
skewed load and breaker-open exclusion) are unit-tested; the
integration pieces run on the 8 fake CPU devices conftest forces
(xla_force_host_platform_device_count), including the ISSUE acceptance
stream: a warmed mixed small/large request mix dispatching across >= 2
replicas with zero steady-state compiles per replica, large-n requests
solved by the spmd drivers to single-device-driver parity, and
per-replica queue depth / breaker state in ``health()``.
"""

import json

import numpy as np
import pytest

from slate_tpu.aux import metrics
from slate_tpu.serve import buckets as bk
from slate_tpu.serve.cache import ExecutableCache
from slate_tpu.serve.placement import PlacementPolicy
from slate_tpu.serve.service import SolverService

FLOOR = 16
NRHS_FLOOR = 4


@pytest.fixture(autouse=True)
def metrics_on():
    """Placement metrics are part of the contract under test."""
    metrics.off()
    metrics.reset()
    metrics.on()
    yield
    metrics.off()
    metrics.reset()


@pytest.fixture(scope="module")
def shared_cache():
    return ExecutableCache(manifest_path=None)


def _gesv_problem(n, nrhs=2, seed=0):
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((n, n)) + n * np.eye(n)
    B = rng.standard_normal((n, nrhs))
    return A, B


# ---------------------------------------------------------------------------
# mesh grammar + mesh-keyed buckets
# ---------------------------------------------------------------------------


def test_parse_and_check_mesh():
    assert bk.parse_mesh("") == (0, 0)
    assert bk.parse_mesh("2x4") == (2, 4)
    assert bk.check_mesh("") == ""
    assert bk.check_mesh("2X4") == "2x4"
    for bad in ("x", "2x", "ax4", "0x4", "2x4x2", "-1x4"):
        with pytest.raises(ValueError):
            bk.parse_mesh(bad)


def test_mesh_fits():
    assert bk.mesh_fits("", 0) and bk.mesh_fits("", 1)
    assert bk.mesh_fits("2x4", 8)
    assert not bk.mesh_fits("2x4", 7)
    assert not bk.mesh_fits("4x4", 8)


def test_bucketkey_mesh_label_and_fingerprint():
    k0 = bk.bucket_for("gesv", 50, 50, 3, np.float64, floor=FLOOR)
    km = bk.bucket_for("gesv", 50, 50, 3, np.float64, floor=FLOOR,
                       mesh="2x4")
    assert km.mesh == "2x4" and k0.mesh == ""
    assert km.label.endswith(".mesh2x4")
    assert ".mesh" not in k0.label
    # the ROADMAP item 2 remnant: a sharded executable's artifact
    # identity must NOT collide with the single-device key's
    f0 = bk.fingerprint(bk.content_fields(k0, 1))
    fm = bk.fingerprint(bk.content_fields(km, 1))
    assert f0 != fm
    # JSON round trip preserves the mesh field
    assert bk.BucketKey.from_json(km.to_json()) == km


def test_bucket_for_mesh_validation():
    with pytest.raises(ValueError):  # sharded serving is full-precision
        bk.bucket_for("gesv", 32, 32, 2, np.float64, floor=FLOOR,
                      precision="mixed", mesh="2x2")
    with pytest.raises(ValueError):  # gels has no sharded path
        bk.bucket_for("gels", 64, 32, 2, np.float64, floor=FLOOR,
                      mesh="2x2")


def test_legacy_manifest_defaults_single_device():
    """Manifest entries written before the mesh field must load as
    single-device placements and re-serialize canonically (the PR 6
    schedule/precision legacy pattern)."""
    legacy = {
        "routine": "gesv", "m": 16, "n": 16, "nrhs": 4,
        "dtype": "float64", "nb": 16, "tag": "", "batch": 1,
        "schedule": "flat", "precision": "full",
    }  # no "mesh": a pre-placement writer
    text = json.dumps({"version": 1, "entries": [legacy]})
    [(key, batch)] = bk.manifest_loads(text)
    assert key.mesh == ""
    canon = json.loads(bk.manifest_dumps([(key, batch)]))
    [entry] = canon["entries"]
    assert entry["mesh"] == ""  # present + canonical on re-serialize
    assert bk.manifest_loads(json.dumps(canon)) == [(key, batch)]


# ---------------------------------------------------------------------------
# placement policy (pure decision logic)
# ---------------------------------------------------------------------------


def test_mesh_for_threshold_and_overrides():
    pol = PlacementPolicy(replicas=2, mesh="2x2", shard_threshold=100,
                          devices=[None] * 4)
    assert pol.mesh_for("gesv", 99) == ""  # below threshold: replicated
    assert pol.mesh_for("gesv", 100) == "2x2"  # at threshold: sharded
    assert pol.mesh_for("posv", 4096) == "2x2"
    assert pol.mesh_for("gels", 4096) == ""  # no sharded gels
    assert pol.mesh_for("gesv", 8, sharded=True) == "2x2"  # explicit
    assert pol.mesh_for("gesv", 4096, sharded=False) == ""  # forced off
    # no mesh configured: nothing routes sharded
    off = PlacementPolicy(replicas=2, shard_threshold=100)
    assert off.mesh_for("gesv", 4096) == ""
    assert off.mesh_for("gesv", 4096, sharded=True) == ""
    # threshold 0 disables size routing but keeps the explicit override
    explicit = PlacementPolicy(mesh="2x2", shard_threshold=0,
                               devices=[None] * 4)
    assert explicit.mesh_for("gesv", 1 << 20) == ""
    assert explicit.mesh_for("gesv", 8, sharded=True) == "2x2"


def test_select_replica_least_loaded_under_skew():
    pol = PlacementPolicy(replicas=4, devices=[None] * 4)
    # replica 2 is idle while the others are backed up
    assert pol.select_replica([5, 3, 0, 7]) == 2
    # repeated skewed selection keeps picking the least loaded
    assert pol.select_replica([5, 3, 1, 0]) == 3


def test_select_replica_breaker_exclusion():
    pol = PlacementPolicy(replicas=3, devices=[None] * 3)
    # the least-loaded replica's breaker is open: next healthy one wins
    assert pol.select_replica([0, 4, 2], [True, False, False]) == 2
    # ALL open: degrade to least-loaded overall (the per-replica
    # breaker still routes its requests direct downstream)
    assert pol.select_replica([3, 1, 2], [True, True, True]) == 1


def test_select_replica_round_robin_ties():
    pol = PlacementPolicy(replicas=3, devices=[None] * 3)
    picks = [pol.select_replica([0, 0, 0]) for _ in range(6)]
    # equal load: ties rotate instead of replica 0 absorbing everything
    assert sorted(set(picks)) == [0, 1, 2]
    rr = PlacementPolicy(replicas=3, strategy="round_robin",
                         devices=[None] * 3)
    assert [rr.select_replica([9, 0, 0]) for _ in range(3)] == [0, 1, 2]
    with pytest.raises(ValueError):
        PlacementPolicy(strategy="typo")


def test_replica_pinning_avoids_mesh_slice():
    """With enough devices, replica pinning starts past the first P*Q
    devices the spmd submesh binds — the two tiers stop contending
    while spare chips idle; a small pool overlaps instead of failing."""
    devs = list(range(8))  # device_for only indexes the pool
    pol = PlacementPolicy(replicas=3, mesh="2x2", devices=devs)
    assert [pol.device_for(i) for i in range(3)] == [4, 5, 6]
    small = PlacementPolicy(replicas=3, mesh="2x2", devices=devs[:4])
    assert [small.device_for(i) for i in range(3)] == [0, 1, 2]
    nomesh = PlacementPolicy(replicas=3, devices=devs)
    assert [nomesh.device_for(i) for i in range(3)] == [0, 1, 2]


def test_configure_replicas_shorthand():
    """serve.configure(replicas=N) must actually produce N replica
    lanes — the shorthand routes into the policy, not into a dead
    SolverService argument."""
    from slate_tpu.serve import api

    svc = api.configure(replicas=3, start=False)
    try:
        assert svc.placement.replicas == 3
        assert len(svc._replicas) == 3
    finally:
        api.shutdown()


def test_policy_from_options_and_devices():
    from slate_tpu.enums import Option

    pol = PlacementPolicy.from_options({
        Option.ServeReplicas: 3, Option.ServeMesh: "2x2",
        Option.ServeShardThreshold: 128,
    })
    assert (pol.replicas, pol.mesh, pol.shard_threshold) == (3, "2x2", 128)
    # default policy: single replica, no mesh, no device resolution
    dflt = PlacementPolicy.from_options(None)
    assert dflt.replicas == 1 and dflt.mesh == ""
    assert dflt.device_for(0) is None  # single replica never pins


# ---------------------------------------------------------------------------
# mesh-aware warmup / restore
# ---------------------------------------------------------------------------


def test_restore_skips_unfit_mesh(tmp_path):
    """A manifest entry whose mesh needs more devices than this process
    has is skipped (counted), never crashed on — a 1-device replica
    restoring a fleet manifest warms only what it can run."""
    key = bk.bucket_for("gesv", 32, 32, 2, np.float64, floor=FLOOR,
                        nrhs_floor=NRHS_FLOOR, mesh="4x4")  # needs 16 > 8
    man = tmp_path / "warmup.json"
    man.write_text(bk.manifest_dumps([(key, 1)]) + "\n")
    cache = ExecutableCache(manifest_path=str(man))
    with metrics.deltas() as d:
        out = cache.restore(batch_max=4)
    assert out["entries"] == 0 and out.get("mesh_unfit") == 1
    assert d.get("serve.mesh_unfit_skipped") == 1
    assert cache.warmup(batch_max=4) == 0  # warmup shares the filter


def test_warmup_primes_every_replica_device(devices):
    """After a device-aware warmup, dispatches on EVERY warmed device
    are compile-free — the multi-replica steady-state contract."""
    cache = ExecutableCache(manifest_path=None)
    key = bk.bucket_for("gesv", 12, 12, 2, np.float64, floor=FLOOR,
                        nrhs_floor=NRHS_FLOOR)
    cache.ensure_manifest(key, (1,))
    devs = [devices[0], devices[1]]
    cache.warmup(batch_max=1, devices=devs)
    A, B = _gesv_problem(12, seed=3)
    Ap, Bp = bk.pad_request(key, A, B)
    with metrics.deltas() as d:
        for dev in devs:
            X, info = cache.run(key, Ap[None], Bp[None], device=dev)
            assert int(info[0]) == 0
            assert np.abs(A @ X[0][:12, :2] - B).max() < 1e-9
        assert d.get("jit.compilations") == 0, (
            "warmed replica devices must not compile on dispatch"
        )
    # an UNwarmed device still pays its compile (the gauge of why
    # warmup takes the device list at all)
    with metrics.deltas() as d:
        cache.run(key, Ap[None], Bp[None], device=devices[2])
        assert d.get("jit.compilations") == 1


# ---------------------------------------------------------------------------
# service integration on the 8 fake devices
# ---------------------------------------------------------------------------


def _placement_service(shared_cache, **kw):
    cfg = dict(
        cache=shared_cache, batch_max=4, batch_window_s=0.002,
        dim_floor=FLOOR, nrhs_floor=NRHS_FLOOR,
        placement=PlacementPolicy(replicas=3, mesh="2x2",
                                  shard_threshold=40),
    )
    cfg.update(kw)
    return SolverService(**cfg)


def test_mixed_stream_dispatches_replicated_and_sharded(shared_cache):
    """The ISSUE acceptance stream: a warmed mixed small/large mix
    dispatches across >= 2 replicas (per-replica counters prove it),
    large-n requests route to the spmd drivers with single-device
    parity, steady state stays compile-free per replica, and health()
    exposes per-replica queue depth + breaker state."""
    svc = _placement_service(shared_cache)
    n_small, n_large = 12, 50  # 50 >= threshold 40 -> bucket 64, sharded
    key_s = bk.bucket_for("gesv", n_small, n_small, 2, np.float64,
                          floor=FLOOR, nrhs_floor=NRHS_FLOOR)
    key_l = bk.bucket_for("gesv", n_large, n_large, 2, np.float64,
                          floor=FLOOR, nrhs_floor=NRHS_FLOOR, mesh="2x2")
    shared_cache.ensure_manifest(key_s, (1, 4))
    shared_cache.ensure_manifest(key_l, (1,))
    svc.warmup()  # primes every replica device + the spmd executable
    problems = [
        _gesv_problem(n_small, seed=i) for i in range(18)
    ] + [_gesv_problem(n_large, seed=100 + i) for i in range(2)]
    with metrics.deltas() as d:
        futs = [svc.submit("gesv", A, B) for A, B in problems]
        for (A, B), f in zip(problems, futs):
            X = f.result(timeout=600)
            # parity with the single-device answer, replicated AND
            # sharded alike
            assert np.abs(X - np.linalg.solve(A, B)).max() < 1e-8
        assert d.get("jit.compilations") == 0, (
            "warmed mixed stream must be compile-free on every replica "
            f"(saw {d.get('jit.compilations')})"
        )
        assert d.get("serve.routed_sharded") == 2
        assert d.get("serve.replicated_dispatch") == 18
        busy = [
            i for i in range(3)
            if d.get(f"serve.replica.{i}.dispatched") > 0
        ]
        assert len(busy) >= 2, (
            "scale-out must spread the stream across replicas: "
            f"only replicas {busy} dispatched"
        )
        assert d.get("serve.replica.sharded.dispatched") == 2
    h = svc.health()
    assert [r["name"] for r in h["replicas"]] == ["0", "1", "2"]
    for r in h["replicas"]:
        assert r["queue_depth"] == 0 and isinstance(r["breakers"], dict)
        assert r["worker_alive"]
    assert h["sharded"]["mesh"] == "2x2"
    assert h["sharded"]["dispatched"] >= 2
    # per-replica queue-depth gauges exist (placement_report's rows)
    g = metrics.gauges()
    assert "serve.replica.0.queue_depth" in g
    assert "serve.replica.sharded.queue_depth" in g
    svc.stop()


def test_sharded_posv_parity(shared_cache):
    svc = _placement_service(shared_cache)
    rng = np.random.default_rng(5)
    n = 20
    G = rng.standard_normal((n, n))
    S = G @ G.T + n * np.eye(n)
    B = rng.standard_normal((n, 2))
    with metrics.deltas() as d:
        X = svc.submit("posv", S, B, sharded=True).result(timeout=600)
        assert d.get("serve.routed_sharded") == 1
    assert np.abs(X - np.linalg.solve(S, B)).max() < 1e-8
    svc.stop()


def test_sharded_override_validation(shared_cache):
    svc = _placement_service(shared_cache)
    A, B = _gesv_problem(12, seed=9)
    with pytest.raises(ValueError):  # explicitly sharded AND mixed
        svc.submit("gesv", A, B, sharded=True, precision="mixed")
    svc.stop()
    # a mixed SERVICE default must not break the sharded API: an
    # explicit sharded=True (no per-request precision) demotes the
    # inherited default and serves full-precision on the mesh
    svc_mixed = _placement_service(shared_cache, precision="mixed")
    with metrics.deltas() as d:
        X = svc_mixed.submit("gesv", A, B, sharded=True).result(timeout=600)
        assert d.get("serve.routed_sharded") == 1
    assert np.abs(X - np.linalg.solve(A, B)).max() < 1e-8
    svc_mixed.stop()
    # no mesh configured: explicit sharded must fail loudly
    svc2 = SolverService(cache=shared_cache, batch_max=4, dim_floor=FLOOR,
                         nrhs_floor=NRHS_FLOOR)
    with pytest.raises(ValueError):
        svc2.submit("gesv", A, B, sharded=True)
    svc2.stop()


def test_breaker_open_replica_excluded_at_admission(shared_cache):
    """Admission steers a bucket's traffic away from a replica whose
    breaker for that bucket is open — the sick lane sheds load to its
    peers instead of routing every request direct — but only while the
    cooldown runs: once it elapses the lane is selectable again, so
    the half-open probe (driven by traffic reaching the lane) can
    actually fire and heal it."""
    import time as _time

    svc = SolverService(
        cache=shared_cache, batch_max=4, dim_floor=FLOOR,
        nrhs_floor=NRHS_FLOOR, breaker_cooldown_s=60.0,
        placement=PlacementPolicy(replicas=2),
        start=False,  # paused: requests stay queued for inspection
    )
    A, B = _gesv_problem(12, seed=11)
    key = bk.bucket_for("gesv", 12, 12, 2, np.float64, floor=FLOOR,
                        nrhs_floor=NRHS_FLOOR)
    br = bk.Breaker()
    br.record_failure(_time.monotonic(), 1)  # open replica 0's breaker
    assert br.state == bk.BREAKER_OPEN
    svc._replicas[0].breakers[key] = br
    for _ in range(3):
        svc.submit("gesv", A, B)
    assert len(svc._replicas[0].q) == 0
    assert len(svc._replicas[1].q) == 3
    # health surfaces the per-replica breaker state
    h = svc.health()
    assert h["replicas"][0]["breakers"][key.label] == bk.BREAKER_OPEN
    assert h["breakers"][key.label] == bk.BREAKER_OPEN  # legacy merge
    # "elapse" the cooldown: the still-open lane must become selectable
    # again (it is now also the least loaded), or no probe could ever
    # reach it and the breaker would stay open forever
    br.opened_at -= 61.0
    svc.submit("gesv", A, B)
    assert len(svc._replicas[0].q) == 1
    svc.stop()


def test_sharded_artifact_roundtrip_mesh_keyed(tmp_path):
    """A mesh-sharded bucket executable round-trips through the
    artifact store under its mesh-shape-keyed fingerprint: the entry
    takes the counted cache_seed rung (serialized shard_map programs
    are not trusted across processes), its header carries the mesh
    field, and it shares nothing — path or fingerprint — with the
    single-device key (the ROADMAP item 2 remnant closed)."""
    from slate_tpu.serve.artifacts import ArtifactStore

    cache = ExecutableCache(manifest_path=None,
                            artifact_dir=str(tmp_path))
    key = bk.bucket_for("gesv", 20, 20, 2, np.float64, floor=FLOOR,
                        nrhs_floor=NRHS_FLOOR, mesh="2x2")
    k0 = bk.bucket_for("gesv", 20, 20, 2, np.float64, floor=FLOOR,
                       nrhs_floor=NRHS_FLOOR)
    store = cache.artifacts
    assert store.path_for(key, 1) != store.path_for(k0, 1)
    A, B = _gesv_problem(20, seed=21)
    Ap, Bp = bk.pad_request(key, A, B)
    with metrics.deltas() as d:
        X, info = cache.run(key, Ap[None], Bp[None])
        assert d.get("serve.artifact_saved_cache_seed") == 1
        assert d.get("serve.artifact_saved_export") == 0
    assert np.abs(A @ X[0][:20, :2] - B).max() < 1e-8
    [entry] = [e for e in store.entries() if "error" not in e]
    assert entry["mode"] == "cache_seed"
    assert entry["fields"]["mesh"] == "2x2"
    assert any("sharded-mesh" in t for t in entry.get("nonportable", ()))
    # a fresh store (new replica) finds + verifies the keyed entry:
    # counted cache_seed, never a silent miss or a single-device
    # collision
    fresh = ArtifactStore(str(tmp_path))
    with metrics.deltas() as d:
        assert fresh.load(key, 1) is None  # recompile rung, XLA-cached
        assert d.get(f"serve.artifact.{key.label}.b1.cache_seed") == 1
        assert fresh.load(k0, 1) is None
        assert d.get(f"serve.artifact.{k0.label}.b1.miss") == 1
    assert fresh.verified_cache_seed(key, 1)


def test_cold_build_single_flight(monkeypatch):
    """A same-bucket burst spread across replica workers must compile
    the executable ONCE per process — the lanes that lose the race
    wait for the winner's build instead of paying their own
    trace+compile (seconds to minutes per f64 shape)."""
    import threading
    import time as _time

    from slate_tpu.serve import cache as cache_mod

    builds = []
    orig = cache_mod._build_core

    def counting(key):
        builds.append(key)
        _time.sleep(0.05)  # widen the race window
        return orig(key)

    monkeypatch.setattr(cache_mod, "_build_core", counting)
    cache = ExecutableCache(manifest_path=None)
    key = bk.bucket_for("gesv", 12, 12, 2, np.float64, floor=FLOOR,
                        nrhs_floor=NRHS_FLOOR)
    A, B = _gesv_problem(12, seed=17)
    Ap, Bp = bk.pad_request(key, A, B)
    errs = []

    def hit():
        try:
            X, info = cache.run(key, Ap[None], Bp[None])
            assert int(info[0]) == 0
        except Exception as e:  # noqa: BLE001 — surfaced below
            errs.append(e)

    threads = [threading.Thread(target=hit) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    assert not errs, errs
    assert len(builds) == 1, f"expected one cold build, got {len(builds)}"


def test_unfit_mesh_fails_fast_at_construction(shared_cache):
    """A configured mesh the device pool cannot realize must fail at
    construction, not downgrade every sharded request to a
    breaker-tripping direct fallback."""
    from slate_tpu.exceptions import DistributedException

    with pytest.raises(DistributedException):
        SolverService(
            cache=shared_cache,
            placement=PlacementPolicy(mesh="4x4"),  # needs 16 > 8
            start=False,
        )


def test_sharded_lane_batches_after_deferred_warmup():
    """Same-bucket coalescing on the sharded lane activates only at
    batch points a warmup has realized.  A cold stream with queued
    company dispatches singly, RECORDS the batch point
    (``serve.mesh_batch_deferred`` + manifest), and after the next
    warmup the same traffic coalesces compile-free
    (``serve.batched`` + one sharded dispatch for two requests)."""
    cache = ExecutableCache(manifest_path=None)
    policy = PlacementPolicy(replicas=2, mesh="2x2", shard_threshold=40)
    n = 50
    key = bk.bucket_for("gesv", n, n, 2, np.float64, floor=FLOOR,
                        nrhs_floor=NRHS_FLOOR, mesh="2x2")
    problems = [_gesv_problem(n, seed=200 + i) for i in range(2)]

    # cold phase: two same-bucket sharded requests queued before start
    svc = SolverService(cache=cache, batch_max=4, batch_window_s=0.002,
                        dim_floor=FLOOR, nrhs_floor=NRHS_FLOOR,
                        placement=policy, start=False)
    assert not cache.is_live(key, 4)
    with metrics.deltas() as d:
        futs = [svc.submit("gesv", A, B) for A, B in problems]
        svc.start()
        for (A, B), f in zip(problems, futs):
            assert np.abs(f.result(timeout=600)
                          - np.linalg.solve(A, B)).max() < 1e-8
        assert d.get("serve.mesh_batch_deferred") == 1
        assert (d.get("serve.batched") or 0) == 0
        assert d.get("serve.replica.sharded.dispatched") == 2
    svc.warmup()  # realizes the recorded (1, batch_max) batch point
    assert cache.is_live(key, 4)
    svc.stop()

    # warmed phase: the identical stream now coalesces, compile-free
    svc = SolverService(cache=cache, batch_max=4, batch_window_s=0.002,
                        dim_floor=FLOOR, nrhs_floor=NRHS_FLOOR,
                        placement=policy, start=False)
    with metrics.deltas() as d:
        futs = [svc.submit("gesv", A, B) for A, B in problems]
        svc.start()
        for (A, B), f in zip(problems, futs):
            assert np.abs(f.result(timeout=600)
                          - np.linalg.solve(A, B)).max() < 1e-8
        assert d.get("serve.batched") == 1
        assert d.get("serve.batched_requests") == 2
        # per-request counter: 2 requests, but one coalesced execution
        assert d.get("serve.replica.sharded.dispatched") == 2
        assert d.get("jit.compilations") == 0
        assert (d.get("serve.mesh_batch_deferred") or 0) == 0
    svc.stop()


def test_single_replica_service_unchanged(shared_cache):
    """The default policy (1 replica, no mesh) is the pre-placement
    service: everything lands on replica 0, nothing routes sharded,
    and the legacy health keys keep their shapes."""
    svc = SolverService(cache=shared_cache, batch_max=4,
                        batch_window_s=0.002, dim_floor=FLOOR,
                        nrhs_floor=NRHS_FLOOR)
    A, B = _gesv_problem(12, seed=13)
    with metrics.deltas() as d:
        X = svc.submit("gesv", A, B).result(timeout=300)
        assert np.abs(A @ X - B).max() < 1e-9
        assert d.get("serve.routed_sharded") == 0
        assert d.get("serve.replica.0.dispatched") == 1
    h = svc.health()
    assert len(h["replicas"]) == 1 and h["sharded"] is None
    assert h["ok"] and h["queue_depth"] == 0
    svc.stop()
