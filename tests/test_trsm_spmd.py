"""SPMD triangular-solve pipeline tests (reference: test/test_trsm.cc;
the distributed solve stages of test_gesv.cc / test_posv.cc).

These exercise parallel/spmd_trsm.py — the shard_map row pipeline — both
directly and through the drivers, and assert the drivers do route
distributed solves through it (no global gather in the hot path).
"""

import numpy as np
import pytest

from slate_tpu.drivers import blas3, chol, lu
from slate_tpu.enums import Diag, Option, Side, Uplo
from slate_tpu.matrix.base import conj_transpose, transpose
from slate_tpu.matrix.matrix import HermitianMatrix, Matrix, TriangularMatrix
from slate_tpu.parallel import spmd_trsm
from slate_tpu.testing import checks


def _lower(rng, n, dtype=np.float64):
    L = np.tril(rng.standard_normal((n, n)))
    if np.dtype(dtype).kind == "c":
        L = L + 1j * np.tril(rng.standard_normal((n, n)))
    return (L + n * np.eye(n)).astype(dtype)


@pytest.mark.parametrize("n,nb", [(64, 16), (50, 16), (72, 8)])
def test_trsm_lower_distributed(rng, grid22, n, nb):
    L0 = _lower(rng, n)
    B0 = rng.standard_normal((n, 12))
    L = TriangularMatrix.from_global(L0, nb, grid=grid22, uplo=Uplo.Lower)
    B = Matrix.from_global(B0, nb, grid=grid22)
    X = blas3.trsm(Side.Left, 1.0, L, B)
    np.testing.assert_allclose(
        np.asarray(X.to_global()), np.linalg.solve(L0, B0), atol=1e-12
    )


@pytest.mark.parametrize("alpha", [1.0, -2.5])
def test_trsm_upper_distributed(rng, grid22, alpha):
    n, nb = 60, 16
    U0 = np.triu(rng.standard_normal((n, n))) + n * np.eye(n)
    B0 = rng.standard_normal((n, 8))
    U = TriangularMatrix.from_global(U0, nb, grid=grid22, uplo=Uplo.Upper)
    B = Matrix.from_global(B0, nb, grid=grid22)
    X = blas3.trsm(Side.Left, alpha, U, B)
    np.testing.assert_allclose(
        np.asarray(X.to_global()), np.linalg.solve(U0, alpha * B0), atol=1e-12
    )


def test_trsm_transposed_view_distributed(rng, grid22):
    """L^T X = B runs the backward (row-gather) pipeline."""
    n, nb = 50, 16
    L0 = _lower(rng, n)
    B0 = rng.standard_normal((n, 8))
    L = TriangularMatrix.from_global(L0, nb, grid=grid22, uplo=Uplo.Lower)
    B = Matrix.from_global(B0, nb, grid=grid22)
    X = blas3.trsm(Side.Left, 1.0, transpose(L), B)
    np.testing.assert_allclose(
        np.asarray(X.to_global()), np.linalg.solve(L0.T, B0), atol=1e-12
    )


def test_trsm_conj_transpose_complex_distributed(rng, grid42):
    n, nb = 64, 8
    L0 = _lower(rng, n, np.complex128)
    B0 = rng.standard_normal((n, 8)) + 1j * rng.standard_normal((n, 8))
    L = TriangularMatrix.from_global(L0, nb, grid=grid42, uplo=Uplo.Lower)
    B = Matrix.from_global(B0, nb, grid=grid42)
    X = blas3.trsm(Side.Left, 1.0, conj_transpose(L), B)
    np.testing.assert_allclose(
        np.asarray(X.to_global()), np.linalg.solve(L0.conj().T, B0), atol=1e-12
    )


def test_trsm_unit_diag_distributed(rng, grid22):
    n, nb = 48, 16
    L0 = np.tril(rng.standard_normal((n, n)), -1)
    B0 = rng.standard_normal((n, 4))
    L = TriangularMatrix.from_global(
        L0 + 7.0 * np.eye(n), nb, grid=grid22, uplo=Uplo.Lower, diag=Diag.Unit
    )
    B = Matrix.from_global(B0, nb, grid=grid22)
    X = blas3.trsm(Side.Left, 1.0, L, B)
    # unit diag: stored diagonal (7.0) must be ignored
    np.testing.assert_allclose(
        np.asarray(X.to_global()),
        np.linalg.solve(L0 + np.eye(n), B0),
        atol=1e-12,
    )


@pytest.mark.slow
def test_spmd_permute_rows(rng, grid22):
    n, nb = 50, 16
    B0 = rng.standard_normal((n, 8))
    B = Matrix.from_global(B0, nb, grid=grid22)
    m_pad = B.layout.P * B.layout.mb
    perm = np.arange(m_pad)
    rng.shuffle(perm[:n])  # padding rows stay in place
    out = spmd_trsm.spmd_permute_rows(
        grid22, B.data, B.layout, np.asarray(perm, np.int32)
    )
    got = np.asarray(Matrix(out, B.layout, grid=grid22).to_global())
    np.testing.assert_allclose(got, B0[perm[:n]], atol=0)


@pytest.mark.slow
def test_getrs_distributed_no_gather(rng, grid22, monkeypatch):
    """gesv distributed must not gather LU or B to global in the solve."""
    n, nb = 96, 16
    M0 = rng.standard_normal((n, n)) + n * np.eye(n)
    B0 = rng.standard_normal((n, 16))
    Am = Matrix.from_global(M0, nb, grid=grid22)
    Bm = Matrix.from_global(B0, nb, grid=grid22)
    LU, piv, info = lu.getrf(Am)
    assert int(info) == 0

    calls = {"n": 0}
    orig = spmd_trsm.spmd_trsm_left

    def counting(*a, **kw):
        calls["n"] += 1
        return orig(*a, **kw)

    monkeypatch.setattr(spmd_trsm, "spmd_trsm_left", counting)
    X = lu.getrs(LU, piv, Bm)
    assert calls["n"] == 2, "distributed getrs must use the SPMD trsm path"
    err = checks.solve_residual(M0, np.asarray(X.to_global()), B0)
    assert checks.passed(err, np.float64, factor=30), err


@pytest.mark.slow
def test_posv_distributed_spmd_solve(rng, grid22, monkeypatch):
    n, nb = 96, 16
    A0 = rng.standard_normal((n, n))
    A0 = A0 @ A0.T + n * np.eye(n)
    B0 = rng.standard_normal((n, 8))
    A = HermitianMatrix.from_global(A0, nb, grid=grid22, uplo=Uplo.Lower)
    B = Matrix.from_global(B0, nb, grid=grid22)

    calls = {"n": 0}
    orig = spmd_trsm.spmd_trsm_left

    def counting(*a, **kw):
        calls["n"] += 1
        return orig(*a, **kw)

    monkeypatch.setattr(spmd_trsm, "spmd_trsm_left", counting)
    X, L, info = chol.posv(A, B)
    assert int(info) == 0
    assert calls["n"] == 2, "distributed potrs must use the SPMD trsm path"
    err = checks.solve_residual(A0, np.asarray(X.to_global()), B0)
    assert checks.passed(err, np.float64, factor=30), err


@pytest.mark.slow
def test_gesv_distributed_ragged(rng, grid42):
    n, nb = 90, 16  # ragged last tile across a 4x2 grid
    M0 = rng.standard_normal((n, n)) + n * np.eye(n)
    B0 = rng.standard_normal((n, 4))
    X, LU, piv, info = lu.gesv(
        Matrix.from_global(M0, nb, grid=grid42),
        Matrix.from_global(B0, nb, grid=grid42),
    )
    assert int(info) == 0
    err = checks.solve_residual(M0, np.asarray(X.to_global()), B0)
    assert checks.passed(err, np.float64, factor=30), err


# ---------------------------------------------------------------------------
# right-side trsm (spmd_trsm_right) and distributed trmm (spmd_trmm)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("uplo", [Uplo.Lower, Uplo.Upper])
@pytest.mark.parametrize("opname", ["n", "t"])
def test_trsm_right_ops_distributed(rng, grid22, uplo, opname):
    n, nb = 50, 16
    T0 = rng.standard_normal((n, n))
    T0 = (np.tril(T0) if uplo == Uplo.Lower else np.triu(T0)) + n * np.eye(n)
    B0 = rng.standard_normal((8, n))
    T = TriangularMatrix.from_global(T0, nb, grid=grid22, uplo=uplo)
    B = Matrix.from_global(B0, nb, grid=grid22)
    A = T if opname == "n" else transpose(T)
    M = T0 if opname == "n" else T0.T
    X = blas3.trsm(Side.Right, 1.0, A, B)
    np.testing.assert_allclose(
        np.asarray(X.to_global()), np.linalg.solve(M.T, B0.T).T, atol=1e-11
    )


def test_trsm_right_complex_conj_distributed(rng, grid42):
    n, nb = 64, 8
    T0 = np.tril(
        rng.standard_normal((n, n)) + 1j * rng.standard_normal((n, n))
    ) + n * np.eye(n)
    B0 = rng.standard_normal((4, n)) + 1j * rng.standard_normal((4, n))
    T = TriangularMatrix.from_global(T0, nb, grid=grid42, uplo=Uplo.Lower)
    B = Matrix.from_global(B0, nb, grid=grid42)
    X = blas3.trsm(Side.Right, 1.0, conj_transpose(T), B)
    np.testing.assert_allclose(
        np.asarray(X.to_global()),
        np.linalg.solve(T0.conj(), B0.T).T,
        atol=1e-10,
    )


def test_trsm_right_unit_diag_distributed(rng, grid22):
    n, nb = 48, 16
    T0 = np.tril(rng.standard_normal((n, n)), -1)
    B0 = rng.standard_normal((6, n))
    T = TriangularMatrix.from_global(
        T0 + np.eye(n), nb, grid=grid22, uplo=Uplo.Lower, diag=Diag.Unit
    )
    B = Matrix.from_global(B0, nb, grid=grid22)
    X = blas3.trsm(Side.Right, 1.0, T, B)
    np.testing.assert_allclose(
        np.asarray(X.to_global()),
        np.linalg.solve((T0 + np.eye(n)).T, B0.T).T,
        atol=1e-11,
    )


@pytest.mark.parametrize("side", [Side.Left, Side.Right])
@pytest.mark.parametrize("uplo", [Uplo.Lower, Uplo.Upper])
@pytest.mark.parametrize("opname", ["n", "t"])
def test_trmm_distributed(rng, grid22, side, uplo, opname):
    n, nb = 50, 16
    T0 = rng.standard_normal((n, n))
    T0 = np.tril(T0) if uplo == Uplo.Lower else np.triu(T0)
    B0 = rng.standard_normal((n, 72) if side == Side.Left else (72, n))
    T = TriangularMatrix.from_global(T0, nb, grid=grid22, uplo=uplo)
    B = Matrix.from_global(B0, nb, grid=grid22)
    A = T if opname == "n" else transpose(T)
    M = T0 if opname == "n" else T0.T
    out = blas3.trmm(side, 1.5, A, B)
    want = 1.5 * (M @ B0 if side == Side.Left else B0 @ M)
    np.testing.assert_allclose(
        np.asarray(out.to_global()), want, atol=1e-11 * n
    )


def test_trmm_unit_diag_ragged_distributed(rng, grid42):
    n, nb = 58, 16  # ragged last tile
    T0 = np.tril(rng.standard_normal((n, n)), -1) + np.eye(n)
    B0 = rng.standard_normal((n, 10))
    T = TriangularMatrix.from_global(
        T0, nb, grid=grid42, uplo=Uplo.Lower, diag=Diag.Unit
    )
    B = Matrix.from_global(B0, nb, grid=grid42)
    out = blas3.trmm(Side.Left, 1.0, T, B)
    np.testing.assert_allclose(
        np.asarray(out.to_global()), T0 @ B0, atol=1e-11 * n
    )


def test_trmm_complex_conj_distributed(rng, grid22):
    n, nb = 48, 16
    T0 = np.triu(rng.standard_normal((n, n)) + 1j * rng.standard_normal((n, n)))
    B0 = rng.standard_normal((n, 6)) + 1j * rng.standard_normal((n, 6))
    T = TriangularMatrix.from_global(T0, nb, grid=grid22, uplo=Uplo.Upper)
    B = Matrix.from_global(B0, nb, grid=grid22)
    out = blas3.trmm(Side.Left, 1.0, conj_transpose(T), B)
    np.testing.assert_allclose(
        np.asarray(out.to_global()), T0.conj().T @ B0, atol=1e-10
    )
