"""Compat layer tests (reference: scalapack_api round-trips like
scalapack_gemm.cc, lapack_api/lapack_*.cc smoke tests).

The ScaLAPACK tests build real block-cyclic per-process buffers (numroc
layout), run the shims, and check against numpy — validating both the
descriptor index math and the driver routing.
"""

import numpy as np
import pytest

from slate_tpu.compat import lapack as lap
from slate_tpu.compat import scalapack as sca


def test_numroc_reference_values():
    # hand-checked ScaLAPACK TOOLS numroc cases
    assert sca.numroc(10, 3, 0, 0, 2) == 6  # blocks 0,2,3(partial)->rows 3+3... owner0: blk0(3)+blk2(3)... = 6
    assert sca.numroc(10, 3, 1, 0, 2) == 4
    assert sca.numroc(9, 3, 0, 0, 3) == 3
    assert sca.numroc(64, 16, 1, 0, 2) == 32


@pytest.mark.parametrize("m,n,mb,nb,p,q", [(50, 37, 8, 16, 2, 2), (64, 64, 16, 16, 2, 3)])
def test_scalapack_roundtrip(rng, m, n, mb, nb, p, q):
    grid = sca.BlacsGrid(p, q)
    desc = sca.descinit(m, n, mb, nb, grid)
    A = rng.standard_normal((m, n))
    locs = sca.to_scalapack(desc, A)
    # local shapes follow numroc
    for pr in range(p):
        for pc in range(q):
            assert locs[(pr, pc)].shape == (
                sca.numroc(m, mb, pr, 0, p),
                sca.numroc(n, nb, pc, 0, q),
            )
    back = sca.from_scalapack(desc, locs)
    np.testing.assert_array_equal(back, A)


def test_pdgemm(rng):
    m, n, k = 48, 40, 56
    grid = sca.BlacsGrid(2, 2)
    da = sca.descinit(m, k, 16, 16, grid)
    db = sca.descinit(k, n, 16, 16, grid)
    dc = sca.descinit(m, n, 16, 16, grid)
    A = rng.standard_normal((m, k))
    B = rng.standard_normal((k, n))
    C = rng.standard_normal((m, n))
    la, lb, lc = sca.to_scalapack(da, A), sca.to_scalapack(db, B), sca.to_scalapack(dc, C)
    sca.pdgemm("N", "N", m, n, k, 2.0, la, da, lb, db, -1.0, lc, dc)
    got = sca.from_scalapack(dc, lc)
    np.testing.assert_allclose(got, 2.0 * A @ B - C, atol=1e-10)


def test_pdgemm_trans(rng):
    m, n, k = 32, 24, 40
    grid = sca.BlacsGrid(2, 1)
    da = sca.descinit(k, m, 8, 8, grid)
    db = sca.descinit(n, k, 8, 8, grid)
    dc = sca.descinit(m, n, 8, 8, grid)
    A = rng.standard_normal((k, m))
    B = rng.standard_normal((n, k))
    C = np.zeros((m, n))
    la, lb, lc = sca.to_scalapack(da, A), sca.to_scalapack(db, B), sca.to_scalapack(dc, C)
    sca.pdgemm("T", "T", m, n, k, 1.0, la, da, lb, db, 0.0, lc, dc)
    np.testing.assert_allclose(sca.from_scalapack(dc, lc), A.T @ B.T, atol=1e-10)


def test_pdpotrf_pdgesv_roundtrip(rng):
    n = 48
    grid = sca.BlacsGrid(2, 2)
    desc = sca.descinit(n, n, 16, 16, grid)
    A0 = rng.standard_normal((n, n))
    A0 = A0 @ A0.T + n * np.eye(n)
    locs = sca.to_scalapack(desc, A0)
    info = sca.pdpotrf("L", n, locs, desc)
    assert info == 0
    L = np.tril(sca.from_scalapack(desc, locs))
    np.testing.assert_allclose(L @ L.T, A0, atol=1e-9 * n)

    # pdgesv on a general system
    M0 = rng.standard_normal((n, n)) + n * np.eye(n)
    B0 = rng.standard_normal((n, 8))
    db = sca.descinit(n, 8, 16, 16, grid)
    la, lb = sca.to_scalapack(desc, M0), sca.to_scalapack(db, B0)
    info = sca.pdgesv(n, 8, la, desc, lb, db)
    assert info == 0
    np.testing.assert_allclose(
        sca.from_scalapack(db, lb), np.linalg.solve(M0, B0), atol=1e-10
    )


def test_pdtrsm_and_plange(rng):
    n = 40
    grid = sca.BlacsGrid(2, 2)
    desc = sca.descinit(n, n, 8, 8, grid)
    L0 = np.tril(rng.standard_normal((n, n))) + n * np.eye(n)
    B0 = rng.standard_normal((n, 4))
    db = sca.descinit(n, 4, 8, 8, grid)
    la, lb = sca.to_scalapack(desc, L0), sca.to_scalapack(db, B0)
    sca.pdtrsm("L", "L", "N", "N", n, 4, 1.0, la, desc, lb, db)
    np.testing.assert_allclose(
        sca.from_scalapack(db, lb), np.linalg.solve(L0, B0), atol=1e-11
    )
    assert np.isclose(sca.pdlange("F", n, n, la, desc), np.linalg.norm(L0))


def test_lapack_shims(rng):
    n = 40
    A = rng.standard_normal((n, n)) + n * np.eye(n)
    B = rng.standard_normal((n, 4))
    X, info = lap.gesv(A, B)
    assert info == 0
    np.testing.assert_allclose(X, np.linalg.solve(A, B), atol=1e-10)

    LU, perm, info = lap.getrf(A)
    X2 = lap.getrs("N", LU, perm, B)
    np.testing.assert_allclose(X2, np.linalg.solve(A, B), atol=1e-10)

    S = A @ A.T + n * np.eye(n)
    L, info = lap.potrf("L", S)
    assert info == 0
    np.testing.assert_allclose(L @ L.T, S, atol=1e-8)

    C = lap.gemm("N", "T", 1.0, A, A, 0.0, np.zeros((n, n)))
    np.testing.assert_allclose(C, A @ A.T, atol=1e-10)

    w, Z, _ = lap.syev("V", "L", (A + A.T) / 2)
    np.testing.assert_allclose(w, np.linalg.eigvalsh((A + A.T) / 2), atol=1e-10)

    s, U, Vh = lap.gesvd("S", "S", A)
    np.testing.assert_allclose(s, np.linalg.svd(A, compute_uv=False), atol=1e-9)

    assert np.isclose(lap.lange("1", A), np.abs(A).sum(axis=0).max())


def test_typed_aliases_exist():
    for tc in "sdcz":
        assert hasattr(sca, f"p{tc}gemm")
        assert hasattr(sca, f"p{tc}gesv")
        assert hasattr(lap, f"slate_{tc}getrf")
        assert hasattr(lap, f"slate_{tc}heev")
