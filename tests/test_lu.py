"""LU family tests (reference: test/test_gesv.cc, test_getri.cc,
test_gesv_mixed; norm-scaled residual acceptance)."""

import numpy as np
import pytest

from slate_tpu.drivers import lu
from slate_tpu.enums import MethodLU, Norm, Option, Uplo
from slate_tpu.matrix.matrix import Matrix, TriangularMatrix
from slate_tpu.testing import checks


def _mk(rng, m, n, dtype=np.float64):
    A = rng.standard_normal((m, n))
    if np.dtype(dtype).kind == "c":
        A = A + 1j * rng.standard_normal((m, n))
    return A.astype(dtype)


def _lu_recompose(LUg, perm, m, n):
    L = np.tril(LUg, -1)[:, : min(m, n)] + np.eye(m, min(m, n))
    U = np.triu(LUg)[: min(m, n), :]
    return L @ U, perm


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("n,nb", [(64, 16), (50, 16), (33, 8)])
def test_getrf_single(rng, dtype, n, nb):
    A0 = _mk(rng, n, n, dtype)
    A = Matrix.from_global(A0, nb)
    LU, piv, info = lu.getrf(A)
    assert int(info) == 0
    G = np.asarray(LU.to_global())
    rec, _ = _lu_recompose(G, piv, n, n)
    # P A = L U  =>  A[perm] == rec
    perm = np.asarray(piv.perm)[:n]
    err = checks.factor_residual(A0[perm], rec, np.eye(n))
    assert checks.passed(err, dtype, factor=30), err


@pytest.mark.parametrize("n,nb", [(64, 16), (96, 16), (48, 8)])
def test_getrf_distributed(rng, grid22, n, nb):
    A0 = _mk(rng, n, n)
    A = Matrix.from_global(A0, nb, grid=grid22)
    LU, piv, info = lu.getrf(A)
    assert int(info) == 0
    G = np.asarray(LU.to_global())
    rec, _ = _lu_recompose(G, piv, n, n)
    perm = np.asarray(piv.perm)[:n]
    assert (perm < n).all(), "pivots must stay in the valid row range"
    err = checks.factor_residual(A0[perm], rec, np.eye(n))
    assert checks.passed(err, np.float64, factor=30), err


@pytest.mark.slow
def test_getrf_spmd_matches_lapack_pivoting(rng, grid22):
    """Distributed pivots must genuinely pivot: make the natural diagonal
    tiny so no-pivot LU would blow up."""
    n, nb = 32, 8
    A0 = _mk(rng, n, n)
    A0[np.arange(n), np.arange(n)] = 1e-14
    A = Matrix.from_global(A0, nb, grid=grid22)
    LU, piv, info = lu.getrf(A)
    X = lu.getrs(LU, piv, Matrix.from_global(np.eye(n), nb, grid=grid22))
    err = checks.solve_residual(A0, np.asarray(X.to_global()), np.eye(n))
    assert checks.passed(err, np.float64, factor=100), err


def test_getrf_distributed_4x2(rng, grid42):
    n, nb = 64, 8
    A0 = _mk(rng, n, n)
    A = Matrix.from_global(A0, nb, grid=grid42)
    LU, piv, info = lu.getrf(A)
    perm = np.asarray(piv.perm)[:n]
    G = np.asarray(LU.to_global())
    rec, _ = _lu_recompose(G, piv, n, n)
    err = checks.factor_residual(A0[perm], rec, np.eye(n))
    assert checks.passed(err, np.float64, factor=30), err


def test_gesv(rng):
    n, nrhs = 64, 8
    A0 = _mk(rng, n, n)
    B0 = _mk(rng, n, nrhs)
    X, LU, piv, info = lu.gesv(Matrix.from_global(A0, 16), Matrix.from_global(B0, 16))
    assert int(info) == 0
    err = checks.solve_residual(A0, np.asarray(X.to_global()), B0)
    assert checks.passed(err, np.float64, factor=30), err


@pytest.mark.slow
def test_gesv_distributed(rng, grid22):
    n, nrhs = 96, 16
    A0 = _mk(rng, n, n)
    B0 = _mk(rng, n, nrhs)
    X, LU, piv, info = lu.gesv(
        Matrix.from_global(A0, 16, grid=grid22),
        Matrix.from_global(B0, 16, grid=grid22),
    )
    assert int(info) == 0
    err = checks.solve_residual(A0, np.asarray(X.to_global()), B0)
    assert checks.passed(err, np.float64, factor=30), err


def test_getrf_nopiv(rng):
    n = 48
    A0 = _mk(rng, n, n) + n * np.eye(n)  # diagonally dominant: safe nopiv
    A = Matrix.from_global(A0, 16)
    LU, info = lu.getrf_nopiv(A)
    assert int(info) == 0
    G = np.asarray(LU.to_global())
    L = np.tril(G, -1) + np.eye(n)
    U = np.triu(G)
    err = checks.factor_residual(A0, L, U)
    assert checks.passed(err, np.float64, factor=30), err


def test_gesv_nopiv(rng):
    n, nrhs = 32, 4
    A0 = _mk(rng, n, n) + n * np.eye(n)
    B0 = _mk(rng, n, nrhs)
    X, LU, piv, info = lu.gesv_nopiv(
        Matrix.from_global(A0, 8), Matrix.from_global(B0, 8)
    )
    err = checks.solve_residual(A0, np.asarray(X.to_global()), B0)
    assert checks.passed(err, np.float64, factor=30), err


@pytest.mark.slow
def test_gesv_rbt(rng):
    n, nrhs = 40, 4
    A0 = _mk(rng, n, n)
    B0 = _mk(rng, n, nrhs)
    X, LU, piv, info = lu.gesv(
        Matrix.from_global(A0, 8),
        Matrix.from_global(B0, 8),
        opts={Option.MethodLU: MethodLU.RBT},
    )
    err = checks.solve_residual(A0, np.asarray(X.to_global()), B0)
    assert checks.passed(err, np.float64, factor=1000), err


def test_getri(rng):
    n = 40
    A0 = _mk(rng, n, n)
    LU, piv, info = lu.getrf(Matrix.from_global(A0, 8))
    Ainv = lu.getri(LU, piv)
    np.testing.assert_allclose(
        np.asarray(Ainv.to_global()) @ A0, np.eye(n), atol=1e-9
    )


def test_gesv_mixed(rng):
    n, nrhs = 64, 4
    A0 = _mk(rng, n, n) + n * np.eye(n)
    B0 = _mk(rng, n, nrhs)
    X, info, iters = lu.gesv_mixed(
        Matrix.from_global(A0, 16), Matrix.from_global(B0, 16)
    )
    assert int(info) == 0
    err = checks.solve_residual(A0, np.asarray(X.to_global()), B0)
    assert err < 1e-12, (err, iters)
    assert iters >= 0


def test_gesv_mixed_gmres(rng):
    n, nrhs = 48, 3
    A0 = _mk(rng, n, n) + n * np.eye(n)
    B0 = _mk(rng, n, nrhs)
    X, info, iters = lu.gesv_mixed_gmres(
        Matrix.from_global(A0, 16), Matrix.from_global(B0, 16)
    )
    assert int(info) == 0
    err = checks.solve_residual(A0, np.asarray(X.to_global()), B0)
    assert err < 1e-10, (err, iters)


def test_gecondest(rng):
    n = 32
    A0 = _mk(rng, n, n) + n * np.eye(n)
    A = Matrix.from_global(A0, 8)
    from slate_tpu.drivers.aux import norm as mat_norm

    anorm = mat_norm(Norm.One, A)
    LU, piv, _ = lu.getrf(A)
    rcond = float(lu.gecondest(LU, piv, anorm))
    ref = 1.0 / (np.linalg.norm(A0, 1) * np.linalg.norm(np.linalg.inv(A0), 1))
    np.testing.assert_allclose(rcond, ref, rtol=0.3)


def test_trcondest(rng):
    n = 32
    T0 = np.tril(_mk(rng, n, n)) + n * np.eye(n)
    T = TriangularMatrix.from_global(T0, 8, uplo=Uplo.Lower)
    rcond = float(lu.trcondest(T))
    ref = 1.0 / (np.linalg.norm(T0, 1) * np.linalg.norm(np.linalg.inv(T0), 1))
    np.testing.assert_allclose(rcond, ref, rtol=0.3)


def test_gecondest_norm1est(rng):
    """Hager/Higham estimate within the usual factor of the exact rcond."""
    from slate_tpu.drivers import lu as lu_mod

    n = 48
    M0 = rng.standard_normal((n, n)) + n * np.eye(n)
    LU, piv, _ = lu_mod.getrf(Matrix.from_global(M0, 16))
    anorm = np.linalg.norm(M0, 1)
    rcond = float(lu_mod.gecondest(LU, piv, anorm))
    ref = 1.0 / (anorm * np.linalg.norm(np.linalg.inv(M0), 1))
    assert ref <= rcond <= 3.0 * ref, (rcond, ref)


def test_trcondest_transposed_view(rng):
    from slate_tpu.drivers import lu as lu_mod
    from slate_tpu.matrix.base import conj_transpose
    from slate_tpu.matrix.matrix import TriangularMatrix
    from slate_tpu.enums import Uplo

    n = 40
    T0 = np.tril(rng.standard_normal((n, n))) + n * np.eye(n)
    T = TriangularMatrix.from_global(T0, 16, uplo=Uplo.Lower)
    r = float(lu_mod.trcondest(conj_transpose(T)))
    ref = 1.0 / (np.linalg.norm(T0.T, 1) * np.linalg.norm(np.linalg.inv(T0.T), 1))
    assert ref <= r * 1.001 and r <= 3.0 * ref, (r, ref)


def test_gesv_calu(rng):
    """Tournament-pivoting LU (reference: getrf_tntpiv.cc, MethodLU.CALU)."""
    from slate_tpu.enums import MethodLU, Option

    n, nb = 100, 16
    M0 = rng.standard_normal((n, n))
    B0 = rng.standard_normal((n, 4))
    X, LU, piv, info = lu.gesv(
        Matrix.from_global(M0, nb), Matrix.from_global(B0, nb),
        {Option.MethodLU: MethodLU.CALU},
    )
    assert int(info) == 0
    err = checks.solve_residual(M0, np.asarray(X.to_global()), B0)
    assert checks.passed(err, np.float64, factor=100), err
    # tournament pivoting keeps multipliers modest
    assert np.abs(np.tril(np.asarray(LU.to_global()), -1)).max() < 4.0


@pytest.mark.slow
def test_gesv_calu_distributed(rng, grid22):
    from slate_tpu.enums import MethodLU, Option

    n, nb = 96, 16
    M0 = rng.standard_normal((n, n)) + np.eye(n)
    B0 = rng.standard_normal((n, 4))
    X, LU, piv, info = lu.gesv(
        Matrix.from_global(M0, nb, grid=grid22),
        Matrix.from_global(B0, nb, grid=grid22),
        {Option.MethodLU: MethodLU.CALU},
    )
    assert int(info) == 0
    err = checks.solve_residual(M0, np.asarray(X.to_global()), B0)
    assert checks.passed(err, np.float64, factor=100), err


def test_tournament_pivots_selects_largest(rng):
    from slate_tpu.ops.lu_kernels import tournament_pivots

    M, nb = 128, 8
    panel = rng.standard_normal((M, nb)) * 0.1
    panel[77, 0] = 100.0  # dominant first-column entry must win slot 0
    win = np.asarray(tournament_pivots(panel, nb, 32))
    assert win[0] == 77
    assert len(set(win.tolist())) == nb  # distinct rows
