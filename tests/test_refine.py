"""refine/ — mixed-precision iterative refinement (ISSUE 5).

Coverage map (acceptance criteria in the ISSUE):

* policy: precision-pair selection per backend, Option routing
  (MaxIterations / Tolerance / UseFallbackSolver / RefineMethod).
* parity: gesv_mixed / posv_mixed match the direct f64 solve to the
  LAPACK-style residual bound on well-conditioned systems (f32 factor
  precision — the CPU tier-1 pair).
* convergence bounds on matgen.cond_matrix(cond=1e4) (deterministic
  spectra, not luck-of-the-draw) — <= 8 IR iterations.
* divergence at cond >> 1/eps_f32: fallback fires (refine.fallbacks
  bumped, iters < 0, accurate result) or, with the fallback disabled,
  a typed nonzero info — never a hang or silent garbage.
* GMRES-IR converges on a matrix where classical IR stalls
  (cond ~ 1/eps_f32; Carson & Higham SISC 2018 §4 separation).
* factor-step fault injection (info_nonzero / result_corrupt)
  exercises the fallback solver.
* serve: mixed-precision buckets (BucketKey.precision) stay
  compile-free in warmed steady state; persistent non-convergence
  demotes to the full-precision direct path through the breaker.
* the accurate_matmul sequence-scan fix (displaced-decorator counter).

Heavy parametrizations are marked ``slow`` (tier-1 budget).
"""

import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.aux import faults, metrics
from slate_tpu.enums import Option, RefineMethod
from slate_tpu.matgen import cond_matrix
from slate_tpu.matrix.matrix import HermitianMatrix, Matrix
from slate_tpu.refine import policy
from slate_tpu.refine.ir import backward_error, refine_while
from slate_tpu.testing import checks

EPS64 = float(np.finfo(np.float64).eps)


@pytest.fixture(autouse=True)
def _metrics_on():
    """refine.* counters are part of the subsystem contract; collect
    them for every test and restore the prior state after."""
    was_on = metrics.is_on()
    metrics.on()
    yield
    if not was_on:
        metrics.off()


@pytest.fixture(autouse=True)
def _faults_clean():
    yield
    faults.reset()


def _rhs(n, nrhs=2, seed=7):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((n, nrhs))


# ---------------------------------------------------------------------------
# policy
# ---------------------------------------------------------------------------


def test_policy_pairs_cpu():
    assert policy.factor_dtype(np.float64, "cpu") == np.dtype(np.float32)
    assert policy.factor_dtype(np.complex128, "cpu") == np.dtype(np.complex64)
    # CPU has no fast bf16 pipe: the f32 pair is degenerate
    assert policy.factor_dtype(np.float32, "cpu") == np.dtype(np.float32)
    pol = policy.select(np.float32, 64, backend="cpu")
    assert pol.degenerate


def test_policy_pairs_accelerator():
    # TPU/accelerator: f32 factors in bf16 (the MXU single-pass dtype)
    assert policy.factor_dtype(np.float32, "tpu") == "bfloat16"
    assert policy.factor_dtype(np.float64, "tpu") == np.dtype(np.float32)
    pol = policy.select(np.float32, 64, backend="tpu")
    assert pol.factor == "bfloat16" and not pol.degenerate


def test_policy_option_routing():
    pol = policy.select(np.float64, 64)
    assert pol.method == "ir" and pol.max_iterations == 30
    assert pol.use_fallback
    assert pol.tolerance == pytest.approx(8 * EPS64)
    pol = policy.select(
        np.float64, 64,
        {Option.RefineMethod: "gmres", Option.MaxIterations: 5,
         Option.Tolerance: 1e-10, Option.UseFallbackSolver: False},
    )
    assert pol.method == "gmres" and pol.max_iterations == 5
    assert pol.tolerance == 1e-10 and not pol.use_fallback
    # method_default only fills the Auto slot; explicit options win
    pol = policy.select(
        np.float64, 64, {Option.RefineMethod: RefineMethod.IR},
        method_default=RefineMethod.GMRES,
    )
    assert pol.method == "ir"
    pol = policy.select(np.float64, 64, method_default=RefineMethod.GMRES)
    assert pol.method == "gmres"


def test_policy_unknown_dtype_rejected():
    with pytest.raises(ValueError):
        policy.factor_dtype(np.int32)


# ---------------------------------------------------------------------------
# matgen cond= knob
# ---------------------------------------------------------------------------


def test_cond_matrix_specified_condition():
    A = cond_matrix(48, 1e4)
    assert np.linalg.cond(A) == pytest.approx(1e4, rel=1e-6)
    # bit-deterministic for a seed; different seed, different matrix
    assert np.array_equal(A, cond_matrix(48, 1e4))
    assert not np.array_equal(A, cond_matrix(48, 1e4, seed=1))


def test_cond_matrix_spd():
    S = cond_matrix(32, 1e6, spd=True)
    assert np.abs(S - S.T).max() < 1e-14
    w = np.linalg.eigvalsh(S)
    assert w.min() > 0
    assert w.max() / w.min() == pytest.approx(1e6, rel=1e-6)


def test_cond_matrix_rejects_bad_cond():
    from slate_tpu.exceptions import SlateError

    with pytest.raises(SlateError):
        cond_matrix(8, 0.5)


# ---------------------------------------------------------------------------
# IR core
# ---------------------------------------------------------------------------


def test_backward_error_of_exact_solution():
    import jax.numpy as jnp

    A = cond_matrix(32, 10.0)
    X = _rhs(32, 2, seed=1)
    B = A @ X
    berr = float(backward_error(jnp.asarray(A), jnp.asarray(X), jnp.asarray(B)))
    assert berr < 64 * EPS64


def test_refine_while_counts_steps():
    import jax.numpy as jnp

    A = jnp.asarray(cond_matrix(32, 10.0))
    B = jnp.asarray(_rhs(32))
    res = refine_while(A, B, lambda R: jnp.linalg.solve(A, R), 1e-14, 10)
    # an (essentially) exact inner solve converges on the first check
    assert bool(res.converged) and int(res.iters) <= 1


# ---------------------------------------------------------------------------
# drivers: parity, iteration bounds, fallback
# ---------------------------------------------------------------------------


def test_gesv_mixed_parity_direct_f64():
    n = 64
    A0 = cond_matrix(n, 1e3)
    B0 = _rhs(n, 3)
    X, info, iters = st.gesv_mixed(
        Matrix.from_global(A0, 16), Matrix.from_global(B0, 16)
    )
    assert int(info) == 0 and iters >= 0
    got = np.asarray(X.to_global())
    assert checks.solve_residual(A0, got, B0) < 50 * EPS64
    # matches the direct f64 solve to the residual bound
    ref = np.linalg.solve(A0, B0)
    assert np.abs(got - ref).max() / np.abs(ref).max() < 1e3 * n * EPS64


def test_posv_mixed_parity_direct_f64():
    n = 64
    A0 = cond_matrix(n, 1e3, spd=True)
    B0 = _rhs(n, 3)
    X, info, iters = st.posv_mixed(
        HermitianMatrix.from_global(A0, 16, uplo=st.Uplo.Lower),
        Matrix.from_global(B0, 16),
    )
    assert int(info) == 0 and iters >= 0
    assert checks.solve_residual(A0, np.asarray(X.to_global()), B0) < 50 * EPS64


@pytest.mark.parametrize("spd", [False, True], ids=["gesv", "posv"])
def test_mixed_converges_within_8_iters_at_cond_1e4(spd):
    n = 96
    A0 = cond_matrix(n, 1e4, spd=spd)
    B0 = _rhs(n, 2)
    if spd:
        X, info, iters = st.posv_mixed(
            HermitianMatrix.from_global(A0, 32, uplo=st.Uplo.Lower),
            Matrix.from_global(B0, 32),
        )
    else:
        X, info, iters = st.gesv_mixed(
            Matrix.from_global(A0, 32), Matrix.from_global(B0, 32)
        )
    assert int(info) == 0
    # ISSUE acceptance: converge in <= 8 IR iterations at cond=1e4
    assert 0 <= iters <= 8, iters
    assert checks.solve_residual(A0, np.asarray(X.to_global()), B0) < 50 * EPS64


def test_gesv_mixed_divergence_falls_back():
    n = 64
    A0 = cond_matrix(n, 1e9)  # cond * eps_f32 ~ 1e2: classical IR diverges
    B0 = _rhs(n)
    before = metrics.counters().get("refine.fallbacks", 0)
    X, info, iters = st.gesv_mixed(
        Matrix.from_global(A0, 16), Matrix.from_global(B0, 16)
    )
    assert iters < 0  # the fallback solver ran
    assert int(info) == 0  # ... and produced a usable full-precision solve
    assert metrics.counters().get("refine.fallbacks", 0) == before + 1
    got = np.asarray(X.to_global())
    assert np.all(np.isfinite(got))
    assert checks.solve_residual(A0, got, B0) < 100 * EPS64


def test_gesv_mixed_no_fallback_is_typed_not_garbage():
    n = 64
    A0 = cond_matrix(n, 1e9)
    B0 = _rhs(n)
    X, info, iters = st.gesv_mixed(
        Matrix.from_global(A0, 16), Matrix.from_global(B0, 16),
        {Option.UseFallbackSolver: False},
    )
    # no silent garbage: non-convergence surfaces as nonzero info
    assert int(info) != 0
    assert iters >= 0
    with pytest.raises(st.NumericalError):
        st.simplified.solve_mixed(
            Matrix.from_global(A0, 16), Matrix.from_global(B0, 16),
            {Option.UseFallbackSolver: False},
        )


def test_gmres_ir_converges_where_classical_ir_stalls():
    n = 64
    A0 = cond_matrix(n, 1e9)
    B0 = _rhs(n)
    opts = {Option.UseFallbackSolver: False}
    _X, info_ir, _ = st.gesv_mixed(
        Matrix.from_global(A0, 16), Matrix.from_global(B0, 16), opts
    )
    assert int(info_ir) != 0  # classical IR stalls at cond ~ 1/eps_f32...
    Xg, info_g, iters_g = st.gesv_mixed_gmres(
        Matrix.from_global(A0, 16), Matrix.from_global(B0, 16), opts
    )
    assert int(info_g) == 0 and iters_g > 0  # ...GMRES-IR converges
    got = np.asarray(Xg.to_global())
    ref = np.linalg.solve(A0, B0)
    assert np.abs(got - ref).max() / np.abs(ref).max() < 1e-6


def test_refine_metrics_recorded():
    n = 64
    A0 = cond_matrix(n, 1e3)
    B0 = _rhs(n)
    with metrics.deltas() as d:
        st.gesv_mixed(Matrix.from_global(A0, 16), Matrix.from_global(B0, 16))
    assert d.get("refine.calls") == 1
    assert d.get("refine.gesv_mixed.calls") == 1
    assert d.get("refine.converged") == 1
    assert d.get("refine.iterations") >= 1
    assert metrics.gauges().get("refine.residual") is not None


# ---------------------------------------------------------------------------
# factor-step fault injection -> fallback solver
# ---------------------------------------------------------------------------


def test_factor_fault_info_nonzero_exercises_fallback():
    n = 48
    A0 = cond_matrix(n, 10.0)
    B0 = _rhs(n)
    faults.arm("info_nonzero", once=True)
    faults.on()
    with metrics.deltas() as d:
        X, info, iters = st.gesv_mixed(
            Matrix.from_global(A0, 16), Matrix.from_global(B0, 16)
        )
    assert iters < 0 and int(info) == 0
    assert d.get("refine.fallbacks") == 1
    assert d.get("faults.injected.info_nonzero") == 1
    assert checks.solve_residual(A0, np.asarray(X.to_global()), B0) < 100 * EPS64


def test_factor_fault_result_corrupt_exercises_fallback():
    n = 48
    A0 = cond_matrix(n, 10.0, spd=True)
    B0 = _rhs(n)
    faults.arm("result_corrupt", once=True)
    faults.on()
    with metrics.deltas() as d:
        X, info, iters = st.posv_mixed(
            HermitianMatrix.from_global(A0, 16, uplo=st.Uplo.Lower),
            Matrix.from_global(B0, 16),
        )
    assert iters < 0 and int(info) == 0
    assert d.get("refine.fallbacks") == 1
    assert checks.solve_residual(A0, np.asarray(X.to_global()), B0) < 100 * EPS64


# ---------------------------------------------------------------------------
# serve integration: mixed-precision buckets
# ---------------------------------------------------------------------------

FLOOR, NRHS_FLOOR = 16, 4


def _mk_service(cache=None, **kw):
    from slate_tpu.serve.cache import ExecutableCache
    from slate_tpu.serve.service import SolverService

    return SolverService(
        cache=cache if cache is not None else ExecutableCache(manifest_path=None),
        batch_max=4, dim_floor=FLOOR, nrhs_floor=NRHS_FLOOR,
        precision="mixed", **kw,
    )


def test_bucketkey_precision_manifest_roundtrip():
    from slate_tpu.serve.buckets import (
        BucketKey, bucket_for, manifest_dumps, manifest_loads,
    )

    k = bucket_for("gesv", 12, 12, 2, np.float64, floor=FLOOR,
                   nrhs_floor=NRHS_FLOOR, precision="mixed")
    assert k.precision == "mixed" and k.label.endswith(".mixed")
    (k2, b2), = manifest_loads(manifest_dumps([(k, 4)]))
    assert k2 == k and b2 == 4
    # legacy manifests (no precision field) default to the full path
    legacy = BucketKey.from_json(
        {"routine": "gesv", "m": 16, "n": 16, "nrhs": 4,
         "dtype": "float64", "nb": 16}
    )
    assert legacy.precision == "full" and "mixed" not in legacy.label
    with pytest.raises(ValueError):
        bucket_for("gesv", 12, 12, 2, np.float64, precision="half")
    # gels has no mixed path: stays full regardless of the service-wide
    # setting instead of building an executable that cannot exist
    kg = bucket_for("gels", 24, 12, 2, np.float64, floor=FLOOR,
                    nrhs_floor=NRHS_FLOOR, precision="mixed")
    assert kg.precision == "full"


def test_serve_mixed_bucket_parity_and_steady_state(tmp_path):
    """ISSUE acceptance: serve mixed buckets stay compile-free in
    warmed steady state, and padded-and-cropped mixed results meet the
    direct drivers' bound."""
    from slate_tpu.serve.cache import ExecutableCache, direct_call

    rng = np.random.default_rng(0)
    n = 12
    A1 = rng.standard_normal((n, n)) + n * np.eye(n)
    B1 = rng.standard_normal((n, 2))
    G = rng.standard_normal((n, n))
    A2 = G @ G.T + n * np.eye(n)

    manifest = str(tmp_path / "warm_mixed.json")
    s1 = _mk_service(start=False)
    futs = [s1.submit("gesv", A1 + i * 0.01 * np.eye(n), B1) for i in range(4)]
    futs.append(s1.submit("posv", A2, B1))
    s1.start()
    for f in futs:
        f.result(timeout=300)
    s1.stop()
    s1.cache.save_manifest(manifest)

    # fresh cache: the manifest must round-trip the precision field and
    # warm the MIXED executables, after which a stream never compiles
    cache2 = ExecutableCache(manifest_path=None)
    s2 = _mk_service(cache=cache2, start=False)
    assert cache2.warmup(manifest, batch_max=4) >= 4
    with metrics.deltas() as d:
        futs = []
        for i in range(5):
            futs.append(s2.submit("gesv", A1 + i * 1e-3 * np.eye(n), B1))
            futs.append(s2.submit("posv", A2 + i * 1e-3 * np.eye(n), B1))
        s2.start()
        for f in futs:
            f.result(timeout=300)
        for _ in range(2):  # lone sequential requests hit the b1 point
            got = s2.submit("gesv", A1, B1).result(timeout=300)
        assert d.get("serve.requests") >= 12
        assert d.get("jit.compilations") == 0, "warmed mixed bucket compiled"
        assert d.get("serve.corrupt_result") == 0  # everything converged
    ref = direct_call("gesv", A1, B1)
    assert np.abs(got - ref).max() < 50 * EPS64 * max(np.abs(ref).max(), 1)
    s2.stop()


def test_serve_mixed_demotes_to_direct_on_persistent_stall():
    """A mixed bucket whose traffic defeats the refinement re-solves
    each item on the full-precision direct path (corrupt-result
    validation sees the NaN poison) and the breaker opens after
    degrade_after failures — the demotion the ISSUE asks for."""
    from slate_tpu.serve import buckets as bk

    n = 14
    A0 = cond_matrix(n, 1e9)  # stalls classical IR with an f32 factor
    B0 = _rhs(n)
    svc = _mk_service(degrade_after=2, breaker_cooldown_s=60.0, start=False)
    futs = [svc.submit("gesv", A0, B0) for _ in range(2)]
    with metrics.deltas() as d:
        svc.start()
        for f in futs:
            X = f.result(timeout=300)
            # delivered result is the full-precision direct re-solve
            assert np.all(np.isfinite(X))
            assert checks.solve_residual(A0, X, B0) < 200 * EPS64
        # third request: breaker is open, routes direct without even
        # touching the batched mixed path
        X = svc.submit("gesv", A0, B0).result(timeout=300)
        assert checks.solve_residual(A0, X, B0) < 200 * EPS64
        assert d.get("serve.corrupt_result") >= 1
        assert d.get("serve.refine_demoted") >= 1
        assert d.get("serve.fallbacks") >= 1
    health = svc.health()
    assert any(
        s == bk.BREAKER_OPEN and lbl.endswith(".mixed")
        for lbl, s in health["breakers"].items()
    ), health["breakers"]
    svc.stop()


def test_serve_per_request_precision_override():
    svc = _mk_service(start=False)
    try:
        rng = np.random.default_rng(3)
        n = 12
        A = rng.standard_normal((n, n)) + n * np.eye(n)
        B = rng.standard_normal((n, 1))
        f_full = svc.submit("gesv", A, B, precision="full")
        f_mixed = svc.submit("gesv", A, B)
        svc.start()
        Xf, Xm = f_full.result(timeout=300), f_mixed.result(timeout=300)
        assert checks.solve_residual(A, Xf, B) < 50 * EPS64
        assert checks.solve_residual(A, Xm, B) < 50 * EPS64
        labels = set(svc.health()["breakers"]) | {
            k.label for (k, _b) in svc.cache.entries()
        }
        assert any(l.endswith(".mixed") for l in labels)
        assert any(not l.endswith(".mixed") for l in labels)
    finally:
        svc.stop()


# ---------------------------------------------------------------------------
# accurate_matmul sequence-scan regression (small fix)
# ---------------------------------------------------------------------------


def test_accurate_matmul_scans_sequences_of_matrices():
    import jax.numpy as jnp

    from slate_tpu.internal.precision import accurate_matmul

    @accurate_matmul
    def apply_factors(factors):
        L, U = factors
        return L @ U

    assert apply_factors._accurate_matmul  # marker attr survives

    f32s = (jnp.eye(4, dtype=jnp.float32), jnp.eye(4, dtype=jnp.float32))
    f64s = [jnp.eye(4, dtype=jnp.float64), jnp.eye(4, dtype=jnp.float64)]
    with metrics.deltas() as d:
        apply_factors(f32s)  # 32-bit operands INSIDE a tuple must count
        assert d.get("precision.accurate_matmul_activations") == 1
        apply_factors(f64s)  # pure f64 must not
        assert d.get("precision.accurate_matmul_activations") == 1
        apply_factors(factors=f32s)  # and inside kwargs sequences
        assert d.get("precision.accurate_matmul_activations") == 2


# ---------------------------------------------------------------------------
# heavier parametrizations (slow: tier-1 budget)
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("dtype", [np.complex128], ids=["c128"])
def test_mixed_complex_parity_slow(dtype):
    rng = np.random.default_rng(5)
    n = 64
    A0 = (rng.standard_normal((n, n)) + 1j * rng.standard_normal((n, n))
          + n * np.eye(n)).astype(dtype)
    B0 = (rng.standard_normal((n, 2)) + 1j * rng.standard_normal((n, 2))
          ).astype(dtype)
    X, info, iters = st.gesv_mixed(
        Matrix.from_global(A0, 16), Matrix.from_global(B0, 16)
    )
    assert int(info) == 0 and iters >= 0
    assert checks.solve_residual(A0, np.asarray(X.to_global()), B0) < 50 * EPS64
    S0 = (A0 @ A0.conj().T + n * np.eye(n)).astype(dtype)
    X2, info2, it2 = st.posv_mixed_gmres(
        HermitianMatrix.from_global(S0, 16, uplo=st.Uplo.Lower),
        Matrix.from_global(B0, 16),
    )
    assert int(info2) == 0
    assert checks.solve_residual(S0, np.asarray(X2.to_global()), B0) < 50 * EPS64


@pytest.mark.slow
def test_gmres_restart_cycles_slow():
    """GMRES-IR pays extra cycles (not a fallback) as conditioning
    grows: iteration counts are monotone-ish in cond and stay positive
    until the Carson-Higham limit."""
    n = 64
    for cexp, max_iters in ((3, 90), (6, 240), (9, 900)):
        A0 = cond_matrix(n, 10.0 ** cexp)
        B0 = _rhs(n)
        _X, info, iters = st.gesv_mixed_gmres(
            Matrix.from_global(A0, 16), Matrix.from_global(B0, 16)
        )
        assert int(info) == 0 and 0 < iters <= max_iters, (cexp, iters)


@pytest.mark.slow
def test_serve_mixed_with_chaos_slow():
    """Mixed buckets + execute faults: every future resolves (result or
    typed error) and the stream recovers — the refine path composes
    with the PR4 containment layers."""
    from slate_tpu.exceptions import SlateError

    rng = np.random.default_rng(9)
    n = 12
    A = rng.standard_normal((n, n)) + n * np.eye(n)
    B = rng.standard_normal((n, 2))
    svc = _mk_service(
        start=False, retry_backoff_s=0.002, breaker_cooldown_s=0.02,
        faults_spec="execute:p=0.3,seed=5",
    )
    futs = [svc.submit("gesv", A + i * 1e-3 * np.eye(n), B, retries=2)
            for i in range(18)]
    svc.start()
    ok = typed = 0
    for f in futs:
        try:
            X = f.result(timeout=300)
            assert np.all(np.isfinite(X))
            ok += 1
        except SlateError:
            typed += 1
    assert ok + typed == len(futs)
    assert ok > 0
    svc.stop()
