"""Chaos suite: seeded fault injection (aux/faults) x the serve
hardening paths.

Matrix covered (site -> hardening that must absorb it):

    compile        -> direct-driver fallback (serve.fallbacks)
    execute        -> backoff retry, then fallback; breaker opens
    result_corrupt -> per-item direct re-solve (serve.corrupt_result)
    latency        -> late-miss accounting (serve.deadline_miss_late)
    worker_death   -> supervisor respawn + redelivery (worker_restarts)
    info_nonzero   -> typed NumericalError on exactly the poisoned item

plus the pure pieces: the SLATE_TPU_FAULTS grammar, trigger
determinism under seed, the decorrelated-backoff sequence, the Breaker
state machine, admission validation, structured error context, and the
ISSUE acceptance stream (worker_death + execute at p=0.2 over >= 50
mixed requests: every future resolves, restarts > 0, a degraded bucket
returns to the batched path via a half-open probe).

A module-scoped ExecutableCache is shared so each (bucket, batch)
executable compiles once for the file; heavy combinations live behind
the ``slow`` marker.
"""

import os
import random
import time

import numpy as np
import pytest

from slate_tpu.aux import faults, metrics
from slate_tpu.exceptions import InvalidInput, NumericalError, SlateError
from slate_tpu.serve import buckets as bk
from slate_tpu.serve.cache import ExecutableCache, direct_call
from slate_tpu.serve.service import (
    DeadlineExceeded,
    Rejected,
    SolverService,
    decorrelated_backoff,
)

FLOOR = 16
NRHS_FLOOR = 4


@pytest.fixture(autouse=True)
def chaos_env():
    """Metrics on (the counters are part of the contract under test),
    faults disarmed before AND after every test."""
    metrics.off()
    metrics.reset()
    metrics.on()
    faults.reset()
    yield
    faults.reset()
    metrics.off()
    metrics.reset()


@pytest.fixture(scope="module")
def shared_cache():
    return ExecutableCache(manifest_path=None)


def _svc(cache, **kw):
    cfg = dict(
        cache=cache, batch_max=4, batch_window_s=0.002,
        dim_floor=FLOOR, nrhs_floor=NRHS_FLOOR, degrade_after=2,
        retry_backoff_s=0.002, retry_backoff_cap_s=0.05,
        breaker_cooldown_s=0.05,
    )
    cfg.update(kw)
    return SolverService(**cfg)


def _gesv_problem(n=10, nrhs=1, seed=0):
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((n, n)) + n * np.eye(n)
    B = rng.standard_normal((n, nrhs))
    return A, B


# ---------------------------------------------------------------------------
# faults.py: grammar, triggers, determinism, zero side effects when off
# ---------------------------------------------------------------------------


def test_fault_spec_grammar():
    faults.configure(
        "execute:p=0.5,seed=3; latency:once,ms=2.5 ;worker_death:every=4"
    )
    st = faults.stats()
    assert set(st) == {"execute", "latency", "worker_death"}
    with pytest.raises(ValueError):
        faults.configure("nosite:p=0.1")
    with pytest.raises(ValueError):
        faults.configure("execute:bogus=1")
    with pytest.raises(ValueError):
        faults.configure("execute")  # missing ':trigger'
    with pytest.raises(ValueError):
        faults.arm("execute", p=0.5, every=2)  # two triggers
    with pytest.raises(ValueError):
        faults.arm("execute")  # no trigger


def test_trigger_patterns_deterministic():
    # p-mode: identical fire pattern under the same seed
    faults.reset()
    faults.arm("execute", p=0.3, seed=42)
    faults.on()
    pat1 = [faults.fire("execute") is not None for _ in range(50)]
    faults.reset()
    faults.arm("execute", p=0.3, seed=42)
    faults.on()
    pat2 = [faults.fire("execute") is not None for _ in range(50)]
    assert pat1 == pat2
    assert 0 < sum(pat1) < 50  # actually probabilistic, not all/none
    # every-Nth fires on exact multiples
    faults.reset()
    faults.arm("compile", every=3)
    faults.on()
    pat = [faults.fire("compile") is not None for _ in range(9)]
    assert pat == [False, False, True] * 3
    # once fires exactly once, on the after-th call
    faults.reset()
    faults.arm("latency", once=True, after=4)
    faults.on()
    pat = [faults.fire("latency") is not None for _ in range(8)]
    assert pat == [False, False, False, True, False, False, False, False]
    assert faults.stats()["latency"] == {"calls": 8, "fired": 1}


def test_faults_off_zero_side_effects():
    """Disabled faults are inert: no metric, no mutation, no sleep."""
    faults.arm("result_corrupt", once=True)  # armed but not on()
    x = np.ones((2, 2))
    with metrics.deltas() as d:
        assert faults.fire("result_corrupt") is None
        faults.check("execute")
        assert faults.sleep("latency") == 0.0
        assert faults.corrupt("result_corrupt", x) is x
        assert faults.poison_info("info_nonzero", x) is x
        assert not any(k.startswith("faults.") for k in d.all())
    assert faults.stats()["result_corrupt"]["calls"] == 0


def test_backoff_sequence_deterministic_and_bounded():
    base, cap = 0.01, 0.5

    def seq(seed):
        rng = random.Random(seed)
        out, prev = [], 0.0
        for _ in range(10):
            prev = decorrelated_backoff(rng, prev, base, cap)
            out.append(prev)
        return out

    s1, s2 = seq(7), seq(7)
    assert s1 == s2  # deterministic under seed
    assert seq(8) != s1  # actually seeded, not constant
    assert all(base <= d <= cap for d in s1)
    assert s1[0] == base  # sleep_0 = base (prev=0 collapses the range)
    assert max(s1) > base  # jitter grows the window


def test_breaker_state_machine_unit():
    br = bk.Breaker()
    assert br.state == bk.BREAKER_CLOSED
    assert not br.record_failure(now=100.0, degrade_after=2)  # streak 1
    assert br.record_failure(now=101.0, degrade_after=2)  # opens
    assert br.state == bk.BREAKER_OPEN and br.opens == 1
    assert not br.try_half_open(now=101.5, cooldown_s=1.0)  # too soon
    assert br.try_half_open(now=102.5, cooldown_s=1.0)
    assert br.state == bk.BREAKER_HALF_OPEN
    assert br.record_failure(now=103.0, degrade_after=2)  # probe fails
    assert br.state == bk.BREAKER_OPEN and br.opened_at == 103.0
    assert br.try_half_open(now=105.0, cooldown_s=1.0)
    assert br.record_success()  # probe heals -> the recovery transition
    assert br.state == bk.BREAKER_CLOSED and br.streak == 0
    assert not br.record_success()  # closed success is not a recovery


# ---------------------------------------------------------------------------
# site x hardening: each injected site is absorbed by its recovery path
# ---------------------------------------------------------------------------


def test_execute_fault_retries_with_backoff(shared_cache):
    A, B = _gesv_problem()
    faults.arm("execute", once=True)
    faults.on()
    s = _svc(shared_cache)
    with metrics.deltas() as d:
        X = s.submit("gesv", A, B, retries=1).result(timeout=120)
        assert np.all(np.isfinite(X))
        assert d.get("serve.retries") == 1
        assert d.get("faults.injected.execute") == 1
        assert d.get("serve.fallbacks") == 0  # retry absorbed it
    t = metrics.timers().get("serve.retry_backoff_s")
    assert t is not None and t["count"] >= 1 and t["min_s"] >= s.retry_backoff_s
    s.stop()


def test_compile_fault_falls_back_direct():
    A, B = _gesv_problem()
    faults.arm("compile", once=True)
    faults.on()
    # fresh cache: the compile site only fires on cold builds
    s = _svc(ExecutableCache(manifest_path=None))
    with metrics.deltas() as d:
        X = s.submit("gesv", A, B).result(timeout=120)  # no retry budget
        assert np.abs(A @ X - B).max() < 1e-8
        assert d.get("faults.injected.compile") == 1
        assert d.get("serve.fallbacks") == 1
    s.stop()


def test_worker_death_respawns_and_redelivers(shared_cache):
    rng = np.random.default_rng(1)
    n = 10
    B = rng.standard_normal((n, 2))
    mats = [rng.standard_normal((n, n)) + n * np.eye(n) for _ in range(3)]
    faults.arm("worker_death", once=True)
    faults.on()
    s = _svc(shared_cache, start=False)
    with metrics.deltas() as d:
        futs = [s.submit("gesv", A, B, retries=1) for A in mats]
        s.start()
        out = [f.result(timeout=120) for f in futs]
        assert d.get("serve.worker_restarts") == 1
        assert d.get("faults.injected.worker_death") == 1
    for A, X in zip(mats, out):
        assert np.abs(A @ X - B).max() < 1e-8  # redelivered, correct
    h = s.health()
    assert h["worker_restarts"] == 1 and h["worker_alive"] and h["ok"]
    s.stop()


def test_worker_death_fails_fast_without_budget(shared_cache):
    A, B = _gesv_problem()
    faults.arm("worker_death", once=True)
    faults.on()
    s = _svc(shared_cache, start=False)
    fut = s.submit("gesv", A, B)  # retries=0: no budget to redeliver
    s.start()
    with pytest.raises(SlateError, match="worker died"):
        fut.result(timeout=120)
    # the respawned worker keeps serving
    X = s.submit("gesv", A, B).result(timeout=120)
    assert np.all(np.isfinite(X))
    assert s.health()["worker_alive"]
    s.stop()


def test_info_nonzero_poisons_exactly_one_item(shared_cache):
    rng = np.random.default_rng(2)
    n = 10
    B = rng.standard_normal((n, 1))
    mats = [rng.standard_normal((n, n)) + n * np.eye(n) for _ in range(3)]
    faults.arm("info_nonzero", once=True, info=3)
    faults.on()
    s = _svc(shared_cache, start=False)
    with metrics.deltas() as d:
        futs = [s.submit("gesv", A, B) for A in mats]
        s.start()
        # poison lands on batch item 0 == the oldest request
        with pytest.raises(NumericalError) as ei:
            futs[0].result(timeout=120)
        assert ei.value.info == 3
        assert ei.value.routine == "gesv"  # structured context attached
        assert ei.value.bucket == "gesv.16x16x4.float64"
        for A, f in zip(mats[1:], futs[1:]):
            X = f.result(timeout=120)
            assert np.abs(A @ X - B).max() < 1e-8  # others unharmed
        assert d.get("serve.numerical_errors") == 1
    s.stop()


def test_result_corrupt_recovers_via_direct(shared_cache):
    A, B = _gesv_problem(seed=3)
    faults.arm("result_corrupt", once=True)
    faults.on()
    s = _svc(shared_cache)
    with metrics.deltas() as d:
        X = s.submit("gesv", A, B).result(timeout=120)
        assert np.all(np.isfinite(X))  # never delivers the NaN
        assert np.abs(A @ X - B).max() < 1e-8
        assert d.get("serve.corrupt_result") == 1
        assert d.get("faults.injected.result_corrupt") == 1
    s.stop()


def test_latency_fault_counts_late_miss(shared_cache):
    A, B = _gesv_problem(seed=4)
    faults.arm("latency", once=True, ms=400)
    faults.on()
    s = _svc(shared_cache)  # idle: pops well before the 0.15 s deadline
    with metrics.deltas() as d:
        X = s.submit("gesv", A, B, deadline=0.15).result(timeout=120)
        assert np.all(np.isfinite(X))  # late, but still delivered
        assert d.get("serve.deadline_miss_late") == 1
        assert d.get("serve.deadline_miss_queued") == 0
        assert d.get("serve.deadline_miss") == 1  # total stays the sum
    s.stop()


def test_deadline_queued_cancel_counter(shared_cache):
    """The other half of the deadline_miss split: a queued cancel."""
    A, B = _gesv_problem(seed=5)
    s = _svc(shared_cache, start=False)
    with metrics.deltas() as d:
        fut = s.submit("gesv", A, B, deadline=0.01)
        time.sleep(0.05)  # expires while the worker is paused
        s.start()
        with pytest.raises(DeadlineExceeded) as ei:
            fut.result(timeout=120)
        assert d.get("serve.deadline_miss_queued") == 1
        assert d.get("serve.deadline_miss_late") == 0
        assert d.get("serve.deadline_miss") == 1
    assert ei.value.routine == "gesv" and ei.value.bucket
    s.stop()


def test_deadline_cancels_during_backoff(shared_cache):
    """A request whose deadline passes while it is backing off is
    queued-cancelled promptly by the worker's sweep — the retry backoff
    must not extend the deadline by up to the backoff cap."""
    A, B = _gesv_problem(seed=6)
    s = _svc(shared_cache, retry_backoff_s=0.8, retry_backoff_cap_s=1.5)
    s.submit("gesv", A, B).result(timeout=120)  # warm: dispatch is fast
    faults.arm("execute", every=1)  # every batched dispatch fails
    faults.on()
    t0 = time.monotonic()
    with metrics.deltas() as d:
        fut = s.submit("gesv", A, B, retries=3, deadline=0.15)
        with pytest.raises(DeadlineExceeded):
            fut.result(timeout=120)
        elapsed = time.monotonic() - t0
        assert d.get("serve.deadline_miss_queued") == 1
    # without the sweep the cancel waits out the 0.8 s backoff floor
    assert elapsed < 0.6, f"deadline cancel delayed by backoff: {elapsed:.3f}s"
    s.stop()


def test_corrupt_results_open_breaker(shared_cache):
    """Delivered garbage is a batched-path failure even though nothing
    raised: a bucket whose executable deterministically corrupts every
    result must open its breaker (it would otherwise pay batched
    dispatch + per-item direct re-solve forever and report healthy)."""
    A, B = _gesv_problem(seed=7)
    faults.arm("result_corrupt", every=1)
    faults.on()
    s = _svc(shared_cache)  # degrade_after=2
    with metrics.deltas() as d:
        for _ in range(2):
            X = s.submit("gesv", A, B).result(timeout=120)
            assert np.all(np.isfinite(X))  # re-solved direct, not garbage
        assert d.get("serve.corrupt_result") == 2
        assert d.get("serve.breaker_open") == 1
        assert d.get("serve.breaker_closed") == 0
    assert s.health()["open_buckets"]
    s.stop()


# ---------------------------------------------------------------------------
# circuit breaker: open -> half-open -> closed recovery
# ---------------------------------------------------------------------------


class HealingCache(ExecutableCache):
    """Fails the batched path a fixed number of times, then heals."""

    def __init__(self, fail_times):
        super().__init__(manifest_path=None)
        self.fail_times = fail_times
        self.calls = 0

    def run(self, key, A_batch, B_batch):
        self.calls += 1
        if self.calls <= self.fail_times:
            raise RuntimeError("injected batched failure")
        return super().run(key, A_batch, B_batch)


def test_breaker_opens_half_opens_closes():
    A, B = _gesv_problem(seed=6)
    hc = HealingCache(fail_times=2)
    # cooldown far beyond test timing: transitions happen only when the
    # test rewinds opened_at (deterministic on a loaded box)
    s = _svc(hc, breaker_cooldown_s=60.0)
    key = bk.bucket_for(
        "gesv", 10, 10, 1, A.dtype, floor=FLOOR, nrhs_floor=NRHS_FLOOR
    )
    label = key.label
    with metrics.deltas() as d:
        # two consecutive failures (retry included) open the breaker
        X = s.submit("gesv", A, B, retries=1).result(timeout=120)
        assert np.abs(A @ X - B).max() < 1e-8  # direct fallback result
        assert d.get("serve.breaker_open") == 1
        assert d.get("serve.degraded") == 1  # legacy alias still counts
        assert s.health()["breakers"][label] == bk.BREAKER_OPEN
        assert s.health()["open_buckets"] == [label]
        # while open: routed direct, the batched path is NOT touched
        calls_before = hc.calls
        s.submit("gesv", A, B).result(timeout=120)
        assert hc.calls == calls_before
        # "elapse" the cooldown: half-open probe heals and closes
        s._breakers[key].opened_at -= 61.0
        X3 = s.submit("gesv", A, B).result(timeout=120)
        assert np.abs(A @ X3 - B).max() < 1e-8
        assert hc.calls == calls_before + 1  # the probe went batched
        assert d.get("serve.breaker_half_open") == 1
        assert d.get("serve.breaker_closed") == 1
        assert s.health()["breakers"][label] == bk.BREAKER_CLOSED
        # and the bucket stays on the batched path afterwards
        s.submit("gesv", A, B).result(timeout=120)
        assert hc.calls == calls_before + 2
    s.stop()


def test_breaker_failed_probe_reopens():
    A, B = _gesv_problem(seed=7)
    hc = HealingCache(fail_times=3)  # 2 to open + 1 failed probe
    s = _svc(hc, breaker_cooldown_s=60.0)
    key = bk.bucket_for(
        "gesv", 10, 10, 1, A.dtype, floor=FLOOR, nrhs_floor=NRHS_FLOOR
    )
    with metrics.deltas() as d:
        s.submit("gesv", A, B, retries=1).result(timeout=120)
        assert d.get("serve.breaker_open") == 1
        s._breakers[key].opened_at -= 61.0
        s.submit("gesv", A, B).result(timeout=120)  # probe fails -> reopen
        assert d.get("serve.breaker_half_open") == 1
        assert d.get("serve.breaker_open") == 2
        assert d.get("serve.breaker_closed") == 0
        s._breakers[key].opened_at -= 61.0
        s.submit("gesv", A, B).result(timeout=120)  # healed probe closes
        assert d.get("serve.breaker_closed") == 1
    s.stop()


# ---------------------------------------------------------------------------
# admission checks
# ---------------------------------------------------------------------------


def test_invalid_input_rejected_before_queue(shared_cache):
    A, B = _gesv_problem(seed=8)
    Abad = A.copy()
    Abad[3, 3] = np.nan
    Bbad = B.copy()
    Bbad[0, 0] = np.inf
    s = _svc(shared_cache)
    with metrics.deltas() as d:
        with pytest.raises(InvalidInput) as ei:
            s.submit("gesv", Abad, B)
        with pytest.raises(InvalidInput):
            s.submit("gesv", A, Bbad)
        assert d.get("serve.invalid_input") == 2
        assert d.get("serve.requests") == 0  # never admitted
    assert s.queue_depth() == 0
    assert ei.value.routine == "gesv"
    assert "non-finite" in str(ei.value)
    s.stop()
    # toggleable: validate=False admits the same operands
    s2 = _svc(shared_cache, validate=False, start=False)
    fut = s2.submit("gesv", Abad, B)
    assert s2.queue_depth() == 1
    s2.stop()  # resolves the future with Rejected; nothing hangs
    with pytest.raises(Rejected):
        fut.result(timeout=10)


def test_structured_context_on_every_error_path(shared_cache):
    A, B = _gesv_problem(seed=9)
    s = _svc(shared_cache, max_queue=1, start=False)
    f1 = s.submit("gesv", A, B)
    with pytest.raises(Rejected) as ei:
        s.submit("gesv", A, B)  # queue full
    assert ei.value.routine == "gesv"
    s.stop()
    with pytest.raises(Rejected) as ei2:
        f1.result(timeout=10)  # drained on stop
    assert ei2.value.routine == "gesv"
    assert ei2.value.bucket == "gesv.16x16x4.float64"
    assert "[routine=gesv" in str(ei2.value)


def test_health_snapshot_shape(shared_cache):
    s = _svc(shared_cache)
    h = s.health()
    for field in (
        "ok", "running", "worker_alive", "worker_restarts", "queue_depth",
        "queue_limit", "inflight", "breakers", "open_buckets",
        "failures_60s", "failure_rate_60s", "uptime_s",
    ):
        assert field in h, field
    assert h["ok"] and h["running"] and h["worker_alive"]
    assert h["queue_limit"] == s.max_queue
    s.stop()
    h2 = s.health()
    assert not h2["ok"] and not h2["running"]


# ---------------------------------------------------------------------------
# ISSUE acceptance: faulty mixed stream to steady recovery
# ---------------------------------------------------------------------------


def test_acceptance_faulty_stream_all_futures_resolve(shared_cache):
    """worker_death + execute injected at p=0.2 over a >= 50-request
    mixed stream (seeded): every future resolves (result or typed
    exception, none hang), the worker restart counter is > 0, and at
    least one degraded bucket returns to the batched path via a
    half-open probe."""
    rng = np.random.default_rng(0)
    n1, n2 = 10, 20
    B1 = rng.standard_normal((n1, 2))
    G = rng.standard_normal((n2, n2))
    A2 = G @ G.T + n2 * np.eye(n2)
    B2 = rng.standard_normal((n2, 3))

    faults.arm("execute", p=0.2, seed=11)
    faults.arm("worker_death", p=0.2, seed=13)
    faults.on()
    s = _svc(shared_cache, breaker_cooldown_s=0.02, retry_backoff_s=0.001,
             start=False)
    futs = []
    for i in range(54):
        if i % 3 == 2:
            futs.append(s.submit("posv", A2 + i * 1e-3 * np.eye(n2), B2,
                                 retries=2))
        else:
            A = rng.standard_normal((n1, n1)) + n1 * np.eye(n1)
            futs.append(s.submit("gesv", A, B1, retries=2))
    s.start()
    resolved = typed = 0
    for f in futs:
        try:
            X = f.result(timeout=300)  # a hung future fails the test here
            assert np.all(np.isfinite(X))
            resolved += 1
        except SlateError:
            typed += 1
    assert resolved + typed == len(futs)  # every future resolved
    assert resolved > 0
    c = metrics.counters()
    assert c.get("serve.worker_restarts", 0) > 0
    assert c.get("faults.injected.execute", 0) > 0
    assert c.get("faults.injected.worker_death", 0) > 0

    # recovery leg: stop injecting; any open breaker must return to the
    # batched path through a half-open probe
    faults.reset()
    if not s.health()["open_buckets"]:
        # the seeded stream didn't open a breaker (possible under
        # thread-timing variance): force one open deterministically
        faults.arm("execute", every=1)
        faults.on()
        A, B = _gesv_problem(seed=21)
        for _ in range(2):
            try:
                s.submit("gesv", A, B).result(timeout=120)
            except SlateError:
                pass  # every=1 faults the direct fallback too — typed
        faults.reset()
    assert s.health()["open_buckets"]
    time.sleep(0.05)  # past the cooldown
    with metrics.deltas() as d:
        # one request per previously-open bucket probes and heals it
        A, B = _gesv_problem(seed=22)
        s.submit("gesv", A, B).result(timeout=120)
        Xp = s.submit("posv", A2, B2).result(timeout=120)
        assert np.all(np.isfinite(Xp))
        assert d.get("serve.breaker_closed") >= 1
    assert s.health()["open_buckets"] == []  # batched path restored
    s.stop()


# ---------------------------------------------------------------------------
# tools/chaos_report.py: injected-vs-recovered join over a metrics JSONL
# ---------------------------------------------------------------------------


def _load_chaos_report():
    import importlib.util

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools", "chaos_report.py",
    )
    spec = importlib.util.spec_from_file_location("chaos_report", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_chaos_report_flags_unrecovered_sites(tmp_path):
    import json

    cr = _load_chaos_report()
    path = tmp_path / "m.jsonl"
    rows = [
        {"type": "meta", "schema": 1},
        {"type": "counter", "name": "faults.injected.execute", "value": 5},
        {"type": "counter", "name": "serve.retries", "value": 4},
        {"type": "counter", "name": "serve.fallbacks", "value": 1},
        {"type": "counter", "name": "faults.injected.worker_death", "value": 2},
        # no serve.worker_restarts -> worker_death must be flagged
        {"type": "counter", "name": "faults.injected.latency", "value": 3},
        # latency with no deadline traffic is informational, NOT flagged
    ]
    path.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    rep = cr.analyze(str(path))
    by_site = {r["site"]: r for r in rep}
    assert by_site["execute"]["injected"] == 5
    assert by_site["execute"]["recovered"] == 5  # retries + fallbacks
    assert not by_site["execute"]["flagged"]
    assert by_site["worker_death"]["injected"] == 2
    assert by_site["worker_death"]["flagged"]
    assert not by_site["latency"]["flagged"]  # informational site
    assert cr.main([str(path)]) == 1  # flagged site -> nonzero exit


def test_chaos_report_end_to_end(shared_cache, tmp_path):
    """A real faulty run's JSONL round-trips through the report with
    every injected site showing a recovery signal."""
    cr = _load_chaos_report()
    A, B = _gesv_problem(seed=23)
    faults.arm("execute", once=True)
    faults.on()
    s = _svc(shared_cache)
    s.submit("gesv", A, B, retries=1).result(timeout=120)
    s.stop()
    faults.reset()
    path = str(tmp_path / "run.jsonl")
    metrics.dump(path)
    rep = cr.analyze(path)
    by_site = {r["site"]: r for r in rep}
    assert "execute" in by_site
    assert not by_site["execute"]["flagged"]
    assert cr.main([path]) == 0


# ---------------------------------------------------------------------------
# heavy combinations (slow marker: excluded from tier-1)
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize(
    "site,kw,recovery",
    [
        ("execute", dict(p=0.5, seed=5), "serve.retries"),
        ("worker_death", dict(every=3), "serve.worker_restarts"),
        ("result_corrupt", dict(every=2), "serve.corrupt_result"),
        ("info_nonzero", dict(every=5), "serve.numerical_errors"),
        ("latency", dict(p=0.5, seed=9, ms=5), None),
    ],
)
def test_site_matrix_stream(shared_cache, site, kw, recovery):
    """Sustained injection per site over a 20-request stream: every
    future resolves and the site's recovery metric fires."""
    rng = np.random.default_rng(31)
    n = 10
    B = rng.standard_normal((n, 1))
    faults.arm(site, **kw)
    faults.on()
    s = _svc(shared_cache, retry_backoff_s=0.001, breaker_cooldown_s=0.01,
             start=False)
    futs = []
    for _ in range(20):
        A = rng.standard_normal((n, n)) + n * np.eye(n)
        futs.append(s.submit("gesv", A, B, retries=2))
    s.start()
    outcomes = []
    for f in futs:
        try:
            X = f.result(timeout=300)
            assert np.all(np.isfinite(X))
            outcomes.append("ok")
        except SlateError:
            outcomes.append("typed")
    assert len(outcomes) == 20  # nothing hung
    st = faults.stats()[site]
    assert st["fired"] > 0
    if recovery is not None:
        assert metrics.counters().get(recovery, 0) > 0, recovery
    s.stop()


@pytest.mark.slow
def test_env_spec_drives_service(shared_cache, monkeypatch):
    """The Option.Faults spec string arms + enables injection through
    the service constructor (the SLATE_TPU_FAULTS production path)."""
    A, B = _gesv_problem(seed=41)
    s = _svc(shared_cache, faults_spec="execute:once", start=False)
    assert faults.is_on() and "execute" in faults.stats()
    fut = s.submit("gesv", A, B, retries=1)
    s.start()
    X = fut.result(timeout=120)
    assert np.all(np.isfinite(X))
    assert faults.stats()["execute"]["fired"] == 1
    s.stop()
    # the arming service owns the global injection state: stop() disarms,
    # so a discarded chaos service cannot poison later services
    assert not faults.is_on() and faults.stats() == {}
