"""Aux driver tests: add/copy/scale/set/norms/redistribute
(reference: unit-test analogues test_geadd/gescale/geset + test/test_*norm*)."""

import numpy as np
import pytest

from slate_tpu.drivers import aux
from slate_tpu.enums import Diag, Norm, NormScope, Side, Uplo
from slate_tpu.matrix.matrix import (
    HermitianMatrix,
    Matrix,
    SymmetricMatrix,
    TrapezoidMatrix,
    TriangularMatrix,
)


def _mk(rng, m, n, dtype=np.float64):
    A = rng.standard_normal((m, n))
    if np.dtype(dtype).kind == "c":
        A = A + 1j * rng.standard_normal((m, n))
    return A.astype(dtype)


def test_add(rng):
    A0, B0 = _mk(rng, 50, 30), _mk(rng, 50, 30)
    A, B = Matrix.from_global(A0, 16), Matrix.from_global(B0, 16)
    B2 = aux.add(2.0, A, -1.0, B)
    np.testing.assert_allclose(np.asarray(B2.to_global()), 2 * A0 - B0, atol=1e-14)


def test_add_triangular_masked(rng):
    A0, B0 = _mk(rng, 32, 32), _mk(rng, 32, 32)
    A = TriangularMatrix.from_global(A0, 8, uplo=Uplo.Lower)
    B = TriangularMatrix.from_global(B0, 8, uplo=Uplo.Lower)
    B2 = aux.add(1.0, A, 1.0, B)
    G = np.asarray(B2.to_global())
    np.testing.assert_allclose(np.tril(G), np.tril(A0 + B0), atol=1e-14)
    # upper (unreferenced) triangle untouched
    np.testing.assert_allclose(np.triu(G, 1), np.triu(B0, 1), atol=1e-14)


def test_copy_precision(rng):
    A0 = _mk(rng, 20, 20)
    A = Matrix.from_global(A0, 8)
    B = Matrix.zeros(20, 20, 8, dtype=np.float32)
    B2 = aux.copy(A, B)
    assert B2.dtype == np.float32
    np.testing.assert_allclose(np.asarray(B2.to_global()), A0.astype(np.float32))


def test_scale_set(rng):
    A0 = _mk(rng, 24, 24)
    A = Matrix.from_global(A0, 8)
    A2 = aux.scale(3.0, 2.0, A)
    np.testing.assert_allclose(np.asarray(A2.to_global()), A0 * 1.5, atol=1e-14)
    A3 = aux.set(0.0, 1.0, A)
    np.testing.assert_array_equal(np.asarray(A3.to_global()), np.eye(24))


def test_scale_row_col(rng):
    A0 = _mk(rng, 12, 10)
    R = np.arange(1.0, 13.0)
    C = np.arange(1.0, 11.0)
    A = Matrix.from_global(A0, 4)
    A2 = aux.scale_row_col(R, C, A)
    np.testing.assert_allclose(
        np.asarray(A2.to_global()), np.diag(R) @ A0 @ np.diag(C), atol=1e-12
    )


def test_set_lambdas():
    import jax.numpy as jnp

    A = Matrix.zeros(10, 10, 4, dtype=np.float64)
    A2 = aux.set_lambdas(lambda i, j: (i + 10 * j).astype(jnp.float64), A)
    i, j = np.meshgrid(np.arange(10), np.arange(10), indexing="ij")
    np.testing.assert_array_equal(np.asarray(A2.to_global()), i + 10 * j)


@pytest.mark.parametrize("norm_t", [Norm.Max, Norm.One, Norm.Inf, Norm.Fro])
@pytest.mark.parametrize("shape", [(40, 30), (13, 57)])
def test_genorm(rng, norm_t, shape):
    A0 = _mk(rng, *shape)
    A = Matrix.from_global(A0, 16)
    got = float(aux.norm(norm_t, A))
    ref = {
        Norm.Max: np.abs(A0).max(),
        Norm.One: np.abs(A0).sum(axis=0).max(),
        Norm.Inf: np.abs(A0).sum(axis=1).max(),
        Norm.Fro: np.linalg.norm(A0, "fro"),
    }[norm_t]
    np.testing.assert_allclose(got, ref, rtol=1e-13)


def test_genorm_scopes(rng):
    A0 = _mk(rng, 20, 12)
    A = Matrix.from_global(A0, 8)
    cols = np.asarray(aux.norm(Norm.One, A, scope=NormScope.Columns))
    np.testing.assert_allclose(cols, np.abs(A0).sum(axis=0), rtol=1e-14)
    rows = np.asarray(aux.norm(Norm.Inf, A, scope=NormScope.Rows))
    np.testing.assert_allclose(rows, np.abs(A0).sum(axis=1), rtol=1e-14)


@pytest.mark.parametrize("norm_t", [Norm.Max, Norm.One, Norm.Inf, Norm.Fro])
@pytest.mark.parametrize("uplo", [Uplo.Lower, Uplo.Upper])
def test_synorm(rng, norm_t, uplo):
    S0 = _mk(rng, 30, 30)
    S0 = S0 + S0.T
    S = SymmetricMatrix.from_global(S0, 8, uplo=uplo)
    got = float(aux.norm(norm_t, S))
    ref = {
        Norm.Max: np.abs(S0).max(),
        Norm.One: np.abs(S0).sum(axis=0).max(),
        Norm.Inf: np.abs(S0).sum(axis=1).max(),
        Norm.Fro: np.linalg.norm(S0, "fro"),
    }[norm_t]
    np.testing.assert_allclose(got, ref, rtol=1e-13)


@pytest.mark.parametrize("norm_t", [Norm.Max, Norm.One, Norm.Fro])
def test_henorm_complex(rng, norm_t):
    H0 = _mk(rng, 24, 24, np.complex128)
    H0 = H0 + H0.conj().T
    H = HermitianMatrix.from_global(H0, 8, uplo=Uplo.Lower)
    got = float(aux.norm(norm_t, H))
    ref = {
        Norm.Max: np.abs(H0).max(),
        Norm.One: np.abs(H0).sum(axis=0).max(),
        Norm.Fro: np.linalg.norm(H0, "fro"),
    }[norm_t]
    np.testing.assert_allclose(got, ref, rtol=1e-13)


@pytest.mark.parametrize("diag", [Diag.NonUnit, Diag.Unit])
def test_trnorm(rng, diag):
    T0 = np.tril(_mk(rng, 20, 20))
    T = TriangularMatrix.from_global(T0, 8, uplo=Uplo.Lower, diag=diag)
    ref_mat = T0.copy()
    if diag == Diag.Unit:
        np.fill_diagonal(ref_mat, 1.0)
    got = float(aux.norm(Norm.One, T))
    np.testing.assert_allclose(got, np.abs(ref_mat).sum(axis=0).max(), rtol=1e-13)


def test_norm_distributed_matches(rng, grid22):
    A0 = _mk(rng, 64, 64)
    A_s = Matrix.from_global(A0, 16)
    A_d = Matrix.from_global(A0, 16, grid=grid22)
    for nt in (Norm.Max, Norm.One, Norm.Inf, Norm.Fro):
        np.testing.assert_allclose(
            float(aux.norm(nt, A_d)), float(aux.norm(nt, A_s)), rtol=1e-14
        )


def test_redistribute(rng, grid22):
    A0 = _mk(rng, 48, 48)
    A = Matrix.from_global(A0, 16)  # single
    B = Matrix.zeros(48, 48, 8, grid=grid22, dtype=np.float64)
    B2 = aux.redistribute(A, B)
    np.testing.assert_array_equal(np.asarray(B2.to_global()), A0)
    assert B2.layout.p == 2


def test_print_matrix(rng):
    A0 = _mk(rng, 8, 8)
    A = Matrix.from_global(A0, 4)
    text = aux.print_matrix("A", A, verbose=4)
    assert "A = [" in text and "8x8" in text
    assert aux.print_matrix("A", A, verbose=1).startswith("% A")
    assert aux.print_matrix("A", A, verbose=0) == ""


def test_transpose_views(rng):
    from slate_tpu.matrix.base import conj_transpose, transpose

    A0 = _mk(rng, 30, 20, np.complex128)
    A = Matrix.from_global(A0, 8)
    At = transpose(A)
    assert (At.m, At.n) == (20, 30)
    np.testing.assert_array_equal(np.asarray(At.to_global()), A0.T)
    Ah = conj_transpose(A)
    np.testing.assert_array_equal(np.asarray(Ah.to_global()), A0.conj().T)
    # resolved() materializes
    Ar = At.resolved()
    np.testing.assert_allclose(np.asarray(Ar.to_global()), A0.T)


def test_sub(rng):
    A0 = _mk(rng, 64, 64)
    A = Matrix.from_global(A0, 8)
    S = A.sub(2, 4, 1, 3)  # tile rows 2-4, cols 1-3
    np.testing.assert_array_equal(
        np.asarray(S.to_global()), A0[16:40, 8:32]
    )


@pytest.mark.parametrize("shape,src,dst", [
    ((50, 37), (16, 16), (8, 8)),     # ragged last tiles both sides
    ((40, 30), (16, 9), (8, 16)),     # rectangular, different aspect
])
def test_redistribute_edge_tilings(rng, grid22, shape, src, dst):
    m, n = shape
    A0 = rng.standard_normal((m, n))
    A = Matrix.from_global(A0, src[0], src[1], grid=grid22)
    B = Matrix.from_global(np.zeros((m, n)), dst[0], dst[1])
    out = aux.redistribute(A, B)
    np.testing.assert_array_equal(np.asarray(out.to_global()), A0)


def test_redistribute_transposed_source(rng, grid22):
    from slate_tpu.matrix.base import transpose

    m, n = 37, 50
    M0 = rng.standard_normal((n, m))
    At = transpose(Matrix.from_global(M0, 16, grid=grid22))  # m x n view
    B = Matrix.from_global(np.zeros((m, n)), 8, grid=grid22)
    out = aux.redistribute(At, B)
    np.testing.assert_array_equal(np.asarray(out.to_global()), M0.T)


def test_hemm_dimension_mismatch_raises(rng, grid22):
    from slate_tpu.drivers import blas3
    from slate_tpu.exceptions import DimensionError
    from slate_tpu.matrix.matrix import HermitianMatrix

    A0 = rng.standard_normal((33, 33)); A0 = (A0 + A0.T) / 2
    A = HermitianMatrix.from_global(A0, 16, grid=grid22, uplo=Uplo.Lower)
    B = Matrix.from_global(rng.standard_normal((40, 4)), 16, grid=grid22)
    C = Matrix.from_global(np.zeros((33, 4)), 16, grid=grid22)
    with pytest.raises(DimensionError):
        blas3.hemm(Side.Left, 1.0, A, B, 0.0, C)


class TestDebugDumps:
    """aux/debug.py (reference: Debug.cc:66-340 tile maps + lives)."""

    def test_dump_single(self, rng):
        from slate_tpu.aux import debug
        from slate_tpu.matrix.matrix import Matrix

        A = Matrix.from_global(rng.standard_normal((50, 34)), 16)
        s = debug.dump(A, "t")
        assert "tiles_map" in s and "storage_map" in s
        assert "all live tiles finite" in s

    def test_dump_distributed_and_nan(self, rng, grid22):
        import numpy as np

        from slate_tpu.aux import debug
        from slate_tpu.matrix.matrix import Matrix

        A0 = rng.standard_normal((64, 64))
        A0[3, 3] = np.nan
        A = Matrix.from_global(A0, 16, grid=grid22)
        s = debug.dump(A, "d")
        assert "NON-FINITE tiles" in s
        assert "PartitionSpec" in s or "sharding:" in s
        # ownership map shows the 2x2 cyclic pattern
        assert "0,0" in s and "1,1" in s


@pytest.mark.slow
def test_redistribute_spmd_no_fallback(rng, grid22):
    """Same-grid distributed redistribute takes the SPMD two-phase
    re-send (parallel/spmd_redistribute.py) — no recorded gather."""
    from slate_tpu.enums import Option
    from slate_tpu.internal import fallbacks

    A0 = rng.standard_normal((70, 52))
    A = Matrix.from_global(A0, 16, grid=grid22)
    B = Matrix.from_global(np.zeros((70, 52)), 8, grid=grid22)
    fallbacks.reset()
    out = aux.redistribute(A, B, opts={Option.RequireSpmd: True})
    assert fallbacks.counters() == {}
    np.testing.assert_allclose(np.asarray(out.to_global()), A0, atol=0)
