"""Eigen/SVD family tests (reference: test/test_heev.cc, test_svd.cc,
test_hegv.cc: eigenvalue accuracy + back-transformed vector residuals)."""

import numpy as np
import pytest

from slate_tpu.drivers import eig, svd as svd_mod
from slate_tpu.enums import Uplo
from slate_tpu.matgen.generate import generate_2d
from slate_tpu.matrix.matrix import HermitianMatrix, Matrix
from slate_tpu.testing import checks


def _herm(rng, n, dtype=np.float64):
    A = rng.standard_normal((n, n))
    if np.dtype(dtype).kind == "c":
        A = A + 1j * rng.standard_normal((n, n))
    return ((A + A.conj().T) / 2).astype(dtype)


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("n,nb", [(48, 16), (33, 8)])
def test_he2hb_band_similarity(rng, dtype, n, nb):
    A0 = _herm(rng, n, dtype)
    A = HermitianMatrix.from_global(A0, nb, uplo=Uplo.Lower)
    band, V, T = eig.he2hb(A)
    B = np.asarray(band.to_global())
    # band structure: zero outside bandwidth nb
    i, j = np.meshgrid(range(n), range(n), indexing="ij")
    assert np.abs(B[np.abs(i - j) > nb]).max() < 1e-10
    # similarity: same eigenvalues
    np.testing.assert_allclose(
        np.linalg.eigvalsh(B), np.linalg.eigvalsh(A0), atol=1e-9
    )


def test_he2hb_back_transform(rng):
    n, nb = 32, 8
    A0 = _herm(rng, n)
    A = HermitianMatrix.from_global(A0, nb, uplo=Uplo.Lower)
    band, V, T = eig.he2hb(A)
    B = np.asarray(band.to_global())
    # Q B Q^H == A  with Q from unmtr_he2hb
    from slate_tpu.enums import Op, Side

    eye = Matrix.from_global(np.eye(n), nb)
    Q = np.asarray(eig.unmtr_he2hb(Side.Left, Op.NoTrans, V, T, eye).to_global())
    np.testing.assert_allclose(Q @ Q.conj().T, np.eye(n), atol=1e-10)
    np.testing.assert_allclose(Q @ B @ Q.conj().T, A0, atol=1e-9)


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_heev(rng, dtype):
    n, nb = 48, 16
    A0 = _herm(rng, n, dtype)
    A = HermitianMatrix.from_global(A0, nb, uplo=Uplo.Lower)
    w, Z = eig.heev(A)
    np.testing.assert_allclose(np.asarray(w), np.linalg.eigvalsh(A0), atol=1e-9)
    Zg = np.asarray(Z.to_global())
    # residual ||A Z - Z diag(w)||
    R = A0 @ Zg - Zg * np.asarray(w)[None, :]
    assert np.abs(R).max() < 1e-8
    assert checks.passed(checks.ortho_residual(Zg), dtype, factor=100)


def test_heev_novec(rng):
    A0 = _herm(rng, 24)
    A = HermitianMatrix.from_global(A0, 8, uplo=Uplo.Lower)
    w, Z = eig.heev(A, vectors=False)
    assert Z is None
    np.testing.assert_allclose(np.asarray(w), np.linalg.eigvalsh(A0), atol=1e-10)


def test_heev_matgen_spectrum(rng):
    """heev on a matgen matrix with known spectrum."""
    A2d, S = generate_2d("heev_geo", 32, 32, cond=100.0, seed=5)
    A = HermitianMatrix.from_global(np.asarray(A2d), 8, uplo=Uplo.Lower)
    w, _ = eig.heev(A, vectors=False)
    np.testing.assert_allclose(
        sorted(np.asarray(w)), sorted(np.asarray(S)), atol=1e-10
    )


@pytest.mark.slow
def test_sterf_steqr_stedc(rng):
    n = 32
    d = rng.standard_normal(n)
    e = rng.standard_normal(n - 1)
    T = np.diag(d) + np.diag(e, 1) + np.diag(e, -1)
    ref = np.linalg.eigvalsh(T)
    np.testing.assert_allclose(np.asarray(eig.sterf(d, e)), ref, atol=1e-12)
    w, Z = eig.steqr(d, e)
    np.testing.assert_allclose(np.asarray(w), ref, atol=1e-12)
    R = T @ np.asarray(Z) - np.asarray(Z) * np.asarray(w)[None, :]
    assert np.abs(R).max() < 1e-10
    w2, _ = eig.stedc(d, e, vectors=False)
    np.testing.assert_allclose(np.asarray(w2), ref, atol=1e-12)


def test_hegv(rng):
    n, nb = 32, 8
    A0 = _herm(rng, n)
    B0 = rng.standard_normal((n, n))
    B0 = B0 @ B0.T + n * np.eye(n)
    A = HermitianMatrix.from_global(A0, nb, uplo=Uplo.Lower)
    B = HermitianMatrix.from_global(B0, nb, uplo=Uplo.Lower)
    w, X, info = eig.hegv(1, A, B)
    assert int(info) == 0
    # residual: A x = w B x
    Xg = np.asarray(X.to_global())
    R = A0 @ Xg - (B0 @ Xg) * np.asarray(w)[None, :]
    assert np.abs(R).max() < 1e-7, np.abs(R).max()


@pytest.mark.parametrize("m,n", [(48, 48), (64, 32), (32, 64), (40, 24)])
def test_svd_values(rng, m, n):
    A0 = rng.standard_normal((m, n))
    A = Matrix.from_global(A0, 8)
    s, _, _ = svd_mod.svd(A)
    np.testing.assert_allclose(
        np.asarray(s), np.linalg.svd(A0, compute_uv=False), atol=1e-10
    )


@pytest.mark.parametrize("m,n", [(48, 48), (64, 32), (32, 64)])
def test_svd_vectors(rng, m, n):
    A0 = rng.standard_normal((m, n))
    A = Matrix.from_global(A0, 8)
    s, U, Vh = svd_mod.svd(A, vectors=True)
    k = min(m, n)
    Ug = np.asarray(U.to_global())[:, :k]
    Vhg = np.asarray(Vh.to_global())[:k]
    rec = (Ug * np.asarray(s)[None, :k]) @ Vhg
    assert np.abs(rec - A0).max() < 1e-8, np.abs(rec - A0).max()
    assert checks.passed(checks.ortho_residual(Ug), np.float64, factor=100)


def test_ge2tb_band_structure(rng):
    m = n = 40
    nb = 8
    A0 = rng.standard_normal((m, n))
    A = Matrix.from_global(A0, nb)
    band, UV, UT, VV, VT = svd_mod.ge2tb(A)
    B = np.asarray(band.to_global())
    i, j = np.meshgrid(range(m), range(n), indexing="ij")
    # upper triangular band: zeros below diag and beyond superdiag band
    assert np.abs(B[(i > j)]).max() < 1e-10
    assert np.abs(B[(j - i) > 2 * nb]).max() < 1e-10
    # same singular values
    np.testing.assert_allclose(
        np.linalg.svd(B, compute_uv=False),
        np.linalg.svd(A0, compute_uv=False),
        atol=1e-9,
    )


@pytest.mark.slow
def test_bdsqr(rng):
    n = 16
    d = rng.standard_normal(n)
    e = rng.standard_normal(n - 1)
    B = np.diag(d) + np.diag(e, 1)
    s, U, Vh = svd_mod.bdsqr(d, e, vectors=True)
    np.testing.assert_allclose(
        np.asarray(s), np.linalg.svd(B, compute_uv=False), atol=1e-12
    )


@pytest.mark.parametrize("n,nb", [(50, 16), (23, 8)])
def test_heev_ragged(rng, n, nb):
    """Ragged last panel: rows < taus columns in larft (regression for the
    short-panel crash at n % nb != 0; reference he2hb.cc:174-185 handles
    short panels via per-group batching)."""
    A0 = _herm(rng, n)
    A = HermitianMatrix.from_global(A0, nb, uplo=Uplo.Lower)
    w, Z = eig.heev(A)
    np.testing.assert_allclose(np.asarray(w), np.linalg.eigvalsh(A0), atol=1e-9)
    Zg = np.asarray(Z.to_global())
    R = A0 @ Zg - Zg * np.asarray(w)[None, :]
    assert np.abs(R).max() < 1e-8


@pytest.mark.parametrize("m,n,nb", [(50, 50, 16), (50, 34, 16), (34, 50, 16)])
def test_svd_ragged(rng, m, n, nb):
    A0 = rng.standard_normal((m, n))
    A = Matrix.from_global(A0, nb)
    s, U, Vh = svd_mod.svd(A, vectors=True)
    np.testing.assert_allclose(
        np.asarray(s), np.linalg.svd(A0, compute_uv=False), atol=1e-10
    )
    k = min(m, n)
    Ug = np.asarray(U.to_global())[:, :k]
    Vhg = np.asarray(Vh.to_global())[:k]
    rec = (Ug * np.asarray(s)[None, :k]) @ Vhg
    assert np.abs(rec - A0).max() < 1e-8, np.abs(rec - A0).max()


@pytest.mark.slow
def test_heev_distributed_inputs(rng, grid22):
    """heev executes with mesh-sharded inputs (two-stage path under
    GSPMD; the back-transforms repack onto the grid)."""
    n, nb = 80, 8
    A0 = rng.standard_normal((n, n))
    A0 = (A0 + A0.T) / 2
    A = HermitianMatrix.from_global(A0, nb, grid=grid22, uplo=Uplo.Lower)
    w, Z = eig.heev(A)
    w, Zg = np.asarray(w), np.asarray(Z.to_global())
    np.testing.assert_allclose(w, np.linalg.eigvalsh(A0), atol=1e-11 * n)
    res = np.abs(A0 @ Zg - Zg * w[None, :]).max()
    assert res < 1e-11 * np.abs(A0).max() * n, res


@pytest.mark.slow
def test_svd_distributed_inputs(rng, grid22):
    m, n, nb = 100, 60, 4
    A0 = rng.standard_normal((m, n))
    A = Matrix.from_global(A0, nb, grid=grid22)
    s, U, Vh = svd_mod.svd(A, vectors=True)
    s = np.asarray(s)
    np.testing.assert_allclose(
        s, np.linalg.svd(A0, compute_uv=False), atol=1e-10 * s.max()
    )
