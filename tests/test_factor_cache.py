"""Factor-cache tests: slate_tpu/serve/factor_cache + the solve-phase
(trsm-only) bucket family + the chol up/downdate kernel.

A module-scoped ExecutableCache is shared across service tests so each
(bucket, batch) executable compiles once for the whole file (the
test_serve pattern); services are built per test against small bucket
floors.  The ISSUE acceptance stream (1 factorization + >= 20 warmed
same-A solves, hit >= 19, 0 compiles, parity, eviction + invalidation
fallbacks) lives here; the <= 10% solve-vs-full executable-cost
criterion is asserted through the schedule-accounting mirror
(``buckets.phase_flops``) at the production bucket shapes.
"""

import time

import numpy as np
import pytest

from slate_tpu.aux import faults, metrics
from slate_tpu.serve import buckets as bk
from slate_tpu.serve.cache import ExecutableCache
from slate_tpu.serve.factor_cache import (
    FactorCache,
    FactorEntry,
    factor_only,
    matrix_fingerprint,
    parse_env_spec,
    residual_ok,
    solve_from_factor,
)
from slate_tpu.serve.service import SolverService

FLOOR = 16
NRHS_FLOOR = 4


@pytest.fixture(autouse=True)
def metrics_on():
    metrics.off()
    metrics.reset()
    metrics.on()
    yield
    metrics.off()
    metrics.reset()
    faults.reset()


@pytest.fixture(scope="module")
def shared_cache():
    return ExecutableCache(manifest_path=None)


def _svc(shared_cache, **kw):
    kw.setdefault("batch_max", 4)
    kw.setdefault("batch_window_s", 0.002)
    kw.setdefault("dim_floor", FLOOR)
    kw.setdefault("nrhs_floor", NRHS_FLOOR)
    return SolverService(cache=shared_cache, **kw)


def _gesv_prob(n, seed=0, nrhs=2):
    r = np.random.default_rng(seed)
    return (r.standard_normal((n, n)) + n * np.eye(n),
            r.standard_normal((n, nrhs)))


def _posv_prob(n, seed=0, nrhs=2):
    r = np.random.default_rng(seed)
    G = r.standard_normal((n, n))
    return G @ G.T + n * np.eye(n), r.standard_normal((n, nrhs))


# ---------------------------------------------------------------------------
# BucketKey.phase
# ---------------------------------------------------------------------------


def test_phase_label_and_roundtrip():
    k = bk.bucket_for("gesv", 12, 12, 2, np.float64, floor=FLOOR,
                      nrhs_floor=NRHS_FLOOR)
    assert k.phase == "full" and not k.label.endswith(".solve")
    s = k.solve_sibling()
    assert s.phase == "solve" and s.label == k.label + ".solve"
    assert s != k
    assert bk.BucketKey.from_json(s.to_json()) == s
    # manifest round-trip keeps both phases distinct
    text = bk.manifest_dumps([(k, 1), (s, 1), (s, 4)])
    back = bk.manifest_loads(text)
    assert (k, 1) in back and (s, 1) in back and (s, 4) in back


def test_legacy_manifest_defaults_phase_full():
    e = {"routine": "gesv", "m": 16, "n": 16, "nrhs": 4,
         "dtype": "float64", "nb": 16, "tag": "", "batch": 1}
    k = bk.BucketKey.from_json(e)
    assert k.phase == "full"
    assert "phase" in k.to_json()  # re-serializes canonically


def test_bucket_for_phase_validation():
    kw = dict(floor=FLOOR, nrhs_floor=NRHS_FLOOR)
    # gels gained a solve phase (fabric tier): Q^H b + trsm against the
    # cached (V/R + T-stack) pack, whose operand is taller than A
    kg = bk.bucket_for("gels", 32, 16, 2, np.float64, phase="solve", **kw)
    assert kg.phase == "solve" and kg.label.endswith(".solve")
    assert bk.solve_factor_shape(kg) == (
        kg.m + bk.gels_pack_kt(kg) * kg.nb, kg.n)
    with pytest.raises(ValueError):
        bk.bucket_for("gesv", 16, 16, 2, np.float64, phase="solve",
                      precision="mixed", **kw)
    with pytest.raises(ValueError):
        bk.bucket_for("gesv", 16, 16, 2, np.float64, phase="solve",
                      mesh="2x2", **kw)
    with pytest.raises(ValueError):
        bk.bucket_for("gesv", 16, 16, 2, np.float64, phase="nope", **kw)


def test_phase_flops_solve_under_10pct():
    """The ISSUE acceptance cost criterion via the accounting mirror:
    at the production bucket shapes the trsm-only executable models
    <= 10% of its full-phase sibling's FLOPs."""
    for routine, n, nrhs in (("gesv", 256, 8), ("gesv", 512, 8),
                             ("posv", 512, 8), ("gesv", 2048, 8)):
        k = bk.bucket_for(routine, n, n, nrhs, np.float64)
        full = bk.phase_flops(k)
        solve = bk.phase_flops(k.solve_sibling())
        assert solve <= 0.10 * full, (routine, n, solve / full)
        # batch scaling is linear on both
        assert bk.phase_flops(k, 4) == pytest.approx(4 * full)


# ---------------------------------------------------------------------------
# fingerprinting
# ---------------------------------------------------------------------------


def test_matrix_fingerprint_sensitivity():
    A = np.arange(16.0).reshape(4, 4)
    fp = matrix_fingerprint(A, "gesv")
    assert fp == matrix_fingerprint(A.copy(), "gesv")  # bytes, not id
    A2 = A.copy()
    A2[0, 0] = 1.0
    assert matrix_fingerprint(A2, "gesv") != fp  # any byte drift rekeys
    assert matrix_fingerprint(A, "posv") != fp
    assert matrix_fingerprint(A.astype(np.float32), "gesv") != fp
    assert matrix_fingerprint(A, "gesv", schedule="recursive") != fp
    # non-contiguous views hash their logical bytes
    F = np.asfortranarray(A)
    assert matrix_fingerprint(F, "gesv") == fp


def test_parse_env_spec():
    assert parse_env_spec("") is None
    assert parse_env_spec("0") is None
    assert parse_env_spec("off") is None
    assert parse_env_spec("1") == {}
    assert parse_env_spec("entries=8,bytes=2e6") == {
        "max_entries": 8, "max_bytes": 2_000_000
    }
    with pytest.raises(ValueError):
        parse_env_spec("entries")
    with pytest.raises(ValueError):
        parse_env_spec("nope=3")


# ---------------------------------------------------------------------------
# FactorCache unit (no service, no jax dispatch)
# ---------------------------------------------------------------------------


def _entry(fp, n=4, routine="gesv", S=16):
    key = bk.bucket_for(routine, n, n, 2, np.float64, floor=S,
                        nrhs_floor=NRHS_FLOOR)
    F = np.eye(S)
    perm = np.arange(n, dtype=np.int64) if routine == "gesv" else None
    return FactorEntry(fp=fp, routine=routine, key=key, factor=F,
                       perm=perm, n=n)


def test_lru_entry_budget_eviction():
    fc = FactorCache(max_entries=2, max_bytes=1 << 30)
    assert fc.put(_entry("a" * 64)) and fc.put(_entry("b" * 64))
    assert fc.get("a" * 64) is not None  # refresh: "b" becomes LRU
    fc.put(_entry("c" * 64))
    assert fc.get("b" * 64) is None and fc.get("a" * 64) is not None
    assert metrics.counters().get("serve.factor_cache.evict") == 1
    assert len(fc) == 2


def test_byte_budget_eviction_and_uncacheable():
    one = _entry("a" * 64).nbytes
    fc = FactorCache(max_entries=100, max_bytes=int(one * 2.5))
    for c in "abc":
        fc.put(_entry(c * 64))
    assert len(fc) == 2 and fc.bytes <= fc.max_bytes
    assert fc.get("a" * 64) is None  # LRU paid the byte budget
    # an entry that alone exceeds the budget is never stored
    big = FactorCache(max_entries=4, max_bytes=one - 1)
    assert big.put(_entry("d" * 64)) is False
    assert len(big) == 0
    assert metrics.counters().get("serve.factor_cache.uncacheable") == 1


def test_invalidate_and_invalidate_all():
    fc = FactorCache(max_entries=8)
    fc.put(_entry("a" * 64))
    fc.put(_entry("b" * 64))
    assert fc.invalidate("a" * 64) is True
    assert fc.invalidate("a" * 64) is False  # already gone
    assert fc.invalidate_all() == 1
    assert len(fc) == 0 and fc.bytes == 0
    c = metrics.counters()
    assert c.get("serve.factor_cache.invalidate") == 2


# ---------------------------------------------------------------------------
# chol up/downdate kernel
# ---------------------------------------------------------------------------


def _chol(A):
    return np.linalg.cholesky(A)


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_chol_rank1_update_matches_refactor(dtype, rng):
    from slate_tpu.ops.chol_kernels import chol_rank1_update

    n = 24
    G = rng.standard_normal((n, n)).astype(dtype)
    if np.issubdtype(dtype, np.complexfloating):
        G = G + 1j * rng.standard_normal((n, n))
    A = G @ np.conj(G).T + n * np.eye(n, dtype=dtype)
    u = rng.standard_normal(n).astype(dtype)
    if np.issubdtype(dtype, np.complexfloating):
        u = u + 1j * rng.standard_normal(n)
    L = _chol(A)
    L1 = np.asarray(chol_rank1_update(L, u))
    ref = _chol(A + np.outer(u, np.conj(u)))
    assert np.abs(L1 - ref).max() < 1e-10


def test_chol_update_rank2_and_downdate(rng):
    from slate_tpu.ops.chol_kernels import chol_update

    n = 20
    G = rng.standard_normal((n, n))
    A = G @ G.T + n * np.eye(n)
    U = rng.standard_normal((n, 2))
    L = _chol(A)
    up = np.asarray(chol_update(L, U))
    assert np.abs(up - _chol(A + U @ U.T)).max() < 1e-10
    # downdate back: recover the original factor
    down = np.asarray(chol_update(up, U, downdate=True))
    assert np.abs(down - L).max() < 1e-8


def test_chol_downdate_breakdown_yields_nan(rng):
    from slate_tpu.ops.chol_kernels import chol_rank1_update

    n = 8
    A = np.eye(n)
    u = np.zeros(n)
    u[0] = 2.0  # A - u u^T is indefinite
    L = _chol(A)
    out = np.asarray(chol_rank1_update(L, u, downdate=True))
    assert not np.all(np.isfinite(out))


# ---------------------------------------------------------------------------
# factor production + residual fence
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("routine", ["gesv", "posv"])
def test_factor_only_and_solve_from_factor(routine, rng):
    n = 12
    A, B = (_gesv_prob if routine == "gesv" else _posv_prob)(n, seed=3)
    F, perm = factor_only(routine, A)
    assert (perm is None) == (routine == "posv")
    key = bk.bucket_for(routine, n, n, 2, np.float64, floor=FLOOR,
                        nrhs_floor=NRHS_FLOOR)
    entry = FactorEntry(fp="x" * 64, routine=routine, key=key,
                        factor=bk.pad_square(F, key.n), perm=perm, n=n)
    X = solve_from_factor(entry, B)
    ref = np.linalg.solve(A, B)
    assert np.abs(X - ref).max() < 1e-9
    assert residual_ok(A, B, X)
    assert not residual_ok(A, B, X + 0.1)  # wrong X trips the fence
    assert not residual_ok(A, B, X * np.nan)


def test_update_posv_rekeys_and_matches(rng):
    n = 12
    A, B = _posv_prob(n, seed=4)
    fc = FactorCache(max_entries=4)
    F, _ = factor_only("posv", A)
    key = bk.bucket_for("posv", n, n, 2, np.float64, floor=FLOOR,
                        nrhs_floor=NRHS_FLOOR)
    fp = matrix_fingerprint(A, "posv", schedule=key.schedule)
    fc.put(FactorEntry(fp=fp, routine="posv", key=key,
                       factor=bk.pad_square(F, key.n), perm=None, n=n))
    u = rng.standard_normal(n)
    A2 = A + np.outer(u, u)
    fp2 = fc.update(fp, A2, u)
    assert fp2 == matrix_fingerprint(A2, "posv", schedule=key.schedule)
    assert fc.get(fp) is None and fc.get(fp2) is not None
    X = solve_from_factor(fc.get(fp2), B)
    assert np.abs(X - np.linalg.solve(A2, B)).max() < 1e-8
    c = metrics.counters()
    assert c.get("serve.factor_cache.update") == 1
    assert not c.get("serve.factor_cache.update_refactor")
    # unknown fp -> None (caller just submits A2)
    assert fc.update("z" * 64, A2, u) is None


def test_update_gesv_falls_back_to_refactor(rng):
    n = 12
    A, B = _gesv_prob(n, seed=5)
    fc = FactorCache(max_entries=4)
    F, perm = factor_only("gesv", A)
    key = bk.bucket_for("gesv", n, n, 2, np.float64, floor=FLOOR,
                        nrhs_floor=NRHS_FLOOR)
    fp = matrix_fingerprint(A, "gesv", schedule=key.schedule)
    fc.put(FactorEntry(fp=fp, routine="gesv", key=key,
                       factor=bk.pad_square(F, key.n), perm=perm, n=n))
    u = rng.standard_normal(n)
    A2 = A + np.outer(u, u)
    fp2 = fc.update(fp, A2, u)
    X = solve_from_factor(fc.get(fp2), B)
    assert np.abs(X - np.linalg.solve(A2, B)).max() < 1e-9
    assert metrics.counters().get(
        "serve.factor_cache.update_refactor") == 1


# ---------------------------------------------------------------------------
# solve-phase executables: manifest + artifact identity
# ---------------------------------------------------------------------------


def test_solve_artifact_never_collides_with_full(tmp_path):
    """ISSUE satellite: a solve-phase artifact has its own path AND its
    own fingerprint; a fresh-store restore brings both phases live from
    distinct files."""
    from slate_tpu.serve.artifacts import ArtifactStore

    full = bk.bucket_for("gesv", 12, 12, 2, np.float64, floor=FLOOR,
                         nrhs_floor=NRHS_FLOOR, schedule="recursive")
    solve = full.solve_sibling()
    assert bk.fingerprint(bk.content_fields(full, 1)) != bk.fingerprint(
        bk.content_fields(solve, 1)
    )
    store = ArtifactStore(str(tmp_path / "a"))
    assert store.path_for(full, 1) != store.path_for(solve, 1)

    man = str(tmp_path / "m.json")
    cache = ExecutableCache(manifest_path=man,
                            artifact_dir=str(tmp_path / "a"))
    cache.ensure_manifest(full, (1,))
    cache.ensure_manifest(solve, (1,))
    cache.warmup(batch_max=1)
    headers = [h for h in cache.artifacts.entries() if "fields" in h]
    phases = {h["fields"]["phase"] for h in headers}
    assert phases == {"full", "solve"}
    fps = {h["fingerprint"] for h in headers}
    assert len(fps) == len(headers)  # no collisions
    # fresh store, fresh cache: restore proves two distinct paths load
    cache2 = ExecutableCache(manifest_path=man,
                             artifact_dir=str(tmp_path / "a"))
    res = cache2.restore(batch_max=1)
    assert res["entries"] == 2 and res["failed"] == 0
    assert res["restored"] + res["compiled"] == 2


# ---------------------------------------------------------------------------
# service end-to-end
# ---------------------------------------------------------------------------


def test_disabled_by_default(shared_cache):
    svc = _svc(shared_cache)
    try:
        assert svc.factor_cache is None
        A, B = _gesv_prob(12, seed=6)
        with metrics.deltas() as d:
            X = svc.submit("gesv", A, B).result(timeout=300)
            assert not d.get("serve.factor_cache.miss")
            assert not d.get("serve.factor_cache.hit")
        assert np.abs(X - np.linalg.solve(A, B)).max() < 1e-9
        assert svc.health()["factor_cache"] is None
    finally:
        svc.stop()


def test_acceptance_repeated_A_stream(shared_cache):
    """ISSUE acceptance: after one submit(A, B0) factorization, a
    >= 20-request warmed same-A stream is trsm-only (hit >= 19), pays
    ZERO compiles, and matches the direct driver."""
    fc = FactorCache(max_entries=8)
    svc = _svc(shared_cache, factor_cache=fc)
    try:
        A, B0 = _gesv_prob(12, seed=7)
        with metrics.deltas() as d:
            X0 = svc.submit("gesv", A, B0).result(timeout=300)
            assert d.get("serve.factor_cache.miss") == 1
        assert np.abs(X0 - np.linalg.solve(A, B0)).max() < 1e-9
        svc.warmup()  # the miss registered the solve bucket
        rng = np.random.default_rng(8)
        Bs = [rng.standard_normal((12, 2)) for _ in range(20)]
        with metrics.deltas() as d:
            futs = [svc.submit("gesv", A, B) for B in Bs]
            Xs = [f.result(timeout=300) for f in futs]
            assert d.get("serve.factor_cache.hit") >= 19
            assert d.get("jit.compilations") == 0, (
                "warmed repeated-A stream must not compile")
        for B, X in zip(Bs, Xs):
            assert np.abs(X - np.linalg.solve(A, B)).max() < 1e-9
        assert svc.health()["factor_cache"]["entries"] == 1
    finally:
        svc.stop()


def test_posv_hit_parity(shared_cache):
    fc = FactorCache(max_entries=8)
    svc = _svc(shared_cache, factor_cache=fc)
    try:
        A, B = _posv_prob(12, seed=9)
        svc.submit("posv", A, B).result(timeout=300)
        svc.warmup()
        with metrics.deltas() as d:
            X = svc.submit("posv", A, B).result(timeout=300)
            assert d.get("serve.factor_cache.hit") == 1
        assert np.abs(X - np.linalg.solve(A, B)).max() < 1e-9
    finally:
        svc.stop()


def test_eviction_tight_byte_budget_counted_refactor(shared_cache):
    """A budget too small to hold any factor degrades every request to
    a counted refactor — correct X, zero hits, never an error."""
    fc = FactorCache(max_entries=8, max_bytes=64)  # no factor fits
    svc = _svc(shared_cache, factor_cache=fc)
    try:
        A, _ = _gesv_prob(12, seed=10)
        rng = np.random.default_rng(11)
        with metrics.deltas() as d:
            for _ in range(3):
                B = rng.standard_normal((12, 2))
                X = svc.submit("gesv", A, B).result(timeout=300)
                assert np.abs(X - np.linalg.solve(A, B)).max() < 1e-9
            assert d.get("serve.factor_cache.hit") == 0
            assert d.get("serve.factor_cache.miss") == 3
            assert d.get("serve.factor_cache.uncacheable") == 3
        assert len(fc) == 0
    finally:
        svc.stop()


def test_invalidation_falls_back_counted(shared_cache):
    fc = FactorCache(max_entries=8)
    svc = _svc(shared_cache, factor_cache=fc)
    try:
        A, B = _gesv_prob(12, seed=12)
        svc.submit("gesv", A, B).result(timeout=300)
        svc.warmup()
        fp = matrix_fingerprint(A, "gesv", schedule=svc.schedule)
        assert fc.invalidate(fp)
        with metrics.deltas() as d:
            X = svc.submit("gesv", A, B).result(timeout=300)
            assert d.get("serve.factor_cache.miss") == 1
            assert d.get("serve.factor_cache.hit") == 0
        assert np.abs(X - np.linalg.solve(A, B)).max() < 1e-9
        # and the refactor re-cached: the next request hits again
        with metrics.deltas() as d:
            svc.submit("gesv", A, B).result(timeout=300)
            assert d.get("serve.factor_cache.hit") == 1
    finally:
        svc.stop()


def test_factor_stale_chaos_revalidates(shared_cache):
    """The factor_stale site serves a silently-wrong factor on a hit:
    the residual fence must catch it, count it, and re-solve — the
    delivered X is still correct."""
    fc = FactorCache(max_entries=8)
    svc = _svc(shared_cache, factor_cache=fc)
    try:
        A, B = _gesv_prob(12, seed=13)
        svc.submit("gesv", A, B).result(timeout=300)
        svc.warmup()
        faults.arm("factor_stale", once=True)
        faults.on()
        with metrics.deltas() as d:
            X = svc.submit("gesv", A, B).result(timeout=300)
            assert d.get("serve.factor_cache.stale") == 1
        faults.reset()
        assert np.abs(X - np.linalg.solve(A, B)).max() < 1e-9
    finally:
        faults.reset()
        svc.stop()


def test_spill_on_open_breaker(shared_cache):
    """A hit whose owning lane's solve-bucket breaker is cooling down
    spills off the batched solve executable (counted) — the direct
    path may still reuse the healthy cached factor, but it never
    dispatches into the sick executable, and X stays right."""
    fc = FactorCache(max_entries=8)
    svc = _svc(shared_cache, factor_cache=fc)
    try:
        A, B = _gesv_prob(12, seed=14)
        svc.submit("gesv", A, B).result(timeout=300)
        svc.warmup()
        fp = matrix_fingerprint(A, "gesv", schedule=svc.schedule)
        skey = fc.get(fp).solve_key
        rep = svc._replicas[0]
        br = svc._breaker(rep, skey)
        br.state = bk.BREAKER_OPEN
        br.opened_at = time.monotonic()

        def _runs():
            return sum(
                v["count"] for k, v in metrics.timers().items()
                if k.startswith(f"serve.{skey.label}.b")
                and k.endswith(".run")
            )

        runs0 = _runs()
        with metrics.deltas() as d:
            X = svc.submit("gesv", A, B).result(timeout=300)
            assert d.get("serve.factor_cache.spill") == 1
        # the solve EXECUTABLE never dispatched into the sick lane
        assert _runs() == runs0
        assert np.abs(X - np.linalg.solve(A, B)).max() < 1e-9
        br.state = bk.BREAKER_CLOSED  # leave the shared lane healthy
    finally:
        svc.stop()


def test_hit_with_different_nrhs_bucket(shared_cache):
    """A same-A request whose B is wider than the factoring request's
    dispatches at ITS OWN solve bucket (the cached factor depends only
    on n) — not the entry's, which would crash the pad."""
    fc = FactorCache(max_entries=8)
    svc = _svc(shared_cache, factor_cache=fc)
    try:
        A, B2 = _gesv_prob(12, seed=18, nrhs=2)   # nrhs bucket 4
        svc.submit("gesv", A, B2).result(timeout=300)
        svc.warmup()
        rng = np.random.default_rng(19)
        B8 = rng.standard_normal((12, 7))          # nrhs bucket 8
        with metrics.deltas() as d:
            X = svc.submit("gesv", A, B8).result(timeout=300)
            assert d.get("serve.factor_cache.hit") == 1
            assert d.get("serve.breaker_open") == 0
        assert np.abs(X - np.linalg.solve(A, B8)).max() < 1e-9
    finally:
        svc.stop()


def test_gels_factors_once_then_hits(shared_cache):
    """Gels joined the factor-cache family (fabric tier): repeated-A
    least squares factors once (QR pack) and every later same-A request
    is a counted hit served from the pack — with X matching lstsq."""
    fc = FactorCache(max_entries=8)
    svc = _svc(shared_cache, factor_cache=fc)
    try:
        rng = np.random.default_rng(15)
        A = rng.standard_normal((20, 12))
        B = rng.standard_normal((20, 2))
        with metrics.deltas() as d:
            X0 = svc.submit("gels", A, B).result(timeout=300)
            assert d.get("serve.factor_cache.miss") == 1
        assert len(fc) == 1
        B2 = rng.standard_normal((20, 2))
        with metrics.deltas() as d:
            X1 = svc.submit("gels", A, B2).result(timeout=300)
            assert d.get("serve.factor_cache.hit") == 1
        ref0, ref1 = (np.linalg.lstsq(A, b, rcond=None)[0]
                      for b in (B, B2))
        assert np.abs(X0 - ref0).max() < 1e-9
        assert np.abs(X1 - ref1).max() < 1e-9
    finally:
        svc.stop()


def test_same_A_burst_factors_once(shared_cache):
    """A burst of same-A requests admitted before the factor lands
    must not factor N times: the first member factors, the rest find
    the entry mid-flight (counted hits)."""
    fc = FactorCache(max_entries=8)
    svc = _svc(shared_cache, factor_cache=fc, start=False)
    try:
        A, _ = _gesv_prob(12, seed=16)
        rng = np.random.default_rng(17)
        futs = [svc.submit("gesv", A, rng.standard_normal((12, 2)))
                for _ in range(4)]
        with metrics.deltas() as d:
            svc.start()
            Xs = [f.result(timeout=300) for f in futs]
        for X in Xs:
            assert np.all(np.isfinite(X))
        c = metrics.counters()
        assert c.get("serve.factor_cache.hit", 0) >= 1
        assert c.get("serve.factor_cache.miss") == 4  # admission misses
        assert len(fc) == 1  # one factor serves the whole burst
    finally:
        svc.stop()
