"""Test configuration: 8 virtual CPU devices + x64.

Multi-chip shardings are validated on a simulated mesh
(xla_force_host_platform_device_count), mirroring how the driver's
dryrun_multichip validates the real multi-chip path.  f64 is enabled for
ScaLAPACK-parity residual checks (SURVEY §7 hard-part (5)).
"""

import os

# Force CPU: the harness presets JAX_PLATFORMS=axon (one real TPU chip) and
# the plugin overrides the env var, so jax.config is the reliable switch.
# Unit tests need the 8-device virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="session")
def grid22(devices):
    from slate_tpu.parallel.grid import ProcessGrid

    return ProcessGrid.from_devices(devices[:4], p=2, q=2)


@pytest.fixture(scope="session")
def grid42(devices):
    from slate_tpu.parallel.grid import ProcessGrid

    return ProcessGrid.from_devices(devices, p=4, q=2)


@pytest.fixture(scope="session")
def grid11(devices):
    from slate_tpu.parallel.grid import ProcessGrid

    return ProcessGrid.single(devices[0])


@pytest.fixture
def rng():
    return np.random.default_rng(42)
