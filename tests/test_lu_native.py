"""Native blocked LU kernels (ops/lu_kernels.py) — the f64-on-TPU path
(reference: src/getrf.cc:85-214 blocked right-looking factorization).

On CPU the vendor path is taken by default, so these tests call the
native kernels directly to validate them against numpy on every platform.
"""

import numpy as np
import pytest

from slate_tpu.ops import lu_kernels


@pytest.mark.parametrize("M,nb", [(64, 16), (64, 8), (48, 16), (16, 16)])
def test_panel_lu(rng, M, nb):
    panel = rng.standard_normal((M, nb))
    lu, perm = lu_kernels.panel_lu(np.asarray(panel))
    lu = np.asarray(lu)
    perm = np.asarray(perm)
    L = np.tril(lu, -1)[:, :nb] + np.eye(M, nb)
    U = np.triu(lu[:nb])
    np.testing.assert_allclose(panel[perm], L @ U, atol=1e-12)
    # partial pivoting: multipliers bounded by 1
    assert np.abs(np.tril(lu, -1)).max() <= 1.0 + 1e-12


def test_panel_lu_complex(rng):
    M, nb = 40, 8
    panel = rng.standard_normal((M, nb)) + 1j * rng.standard_normal((M, nb))
    lu, perm = lu_kernels.panel_lu(panel.astype(np.complex128))
    lu = np.asarray(lu)
    L = np.tril(lu, -1)[:, :nb] + np.eye(M, nb)
    U = np.triu(lu[:nb])
    np.testing.assert_allclose(panel[np.asarray(perm)], L @ U, atol=1e-12)


@pytest.mark.parametrize("n,nb", [(64, 16), (96, 32), (32, 32)])
def test_blocked_getrf(rng, n, nb):
    A = rng.standard_normal((n, n))
    LU, perm = lu_kernels.blocked_getrf(np.asarray(A), nb)
    LU = np.asarray(LU)
    perm = np.asarray(perm)
    L = np.tril(LU, -1) + np.eye(n)
    U = np.triu(LU)
    err = np.abs(A[perm] - L @ U).max() / np.abs(A).max()
    assert err < 1e-13, err
    assert np.abs(np.tril(LU, -1)).max() <= 1.0 + 1e-12


def test_blocked_getrf_matches_vendor(rng):
    """Same pivot choices as LAPACK on a generic matrix."""
    from jax import lax

    n, nb = 64, 16
    A = rng.standard_normal((n, n))
    LU, perm = lu_kernels.blocked_getrf(np.asarray(A), nb)
    lu_ref, _, perm_ref = lax.linalg.lu(np.asarray(A))
    np.testing.assert_allclose(np.asarray(LU), np.asarray(lu_ref), atol=1e-10)
    np.testing.assert_array_equal(np.asarray(perm), np.asarray(perm_ref))


def test_blocked_getrf_singular(rng):
    """Zero column: no NaNs, zero U diagonal for the info check."""
    n, nb = 32, 16
    A = rng.standard_normal((n, n))
    A[:, 5] = 0.0
    LU, perm = lu_kernels.blocked_getrf(np.asarray(A), nb)
    LU = np.asarray(LU)
    assert np.isfinite(LU).all()


def test_getrf_forced_native(rng, monkeypatch):
    """Drive the full getrf driver through the native path."""
    from slate_tpu.drivers import lu as lu_driver
    from slate_tpu.matrix.matrix import Matrix
    from slate_tpu.testing import checks

    monkeypatch.setattr(lu_kernels, "lu_supported", lambda dt: False)
    n, nb = 50, 16
    A0 = rng.standard_normal((n, n))
    B0 = rng.standard_normal((n, 4))
    X, LU, piv, info = lu_driver.gesv(
        Matrix.from_global(A0, nb), Matrix.from_global(B0, nb)
    )
    assert int(info) == 0
    err = checks.solve_residual(A0, np.asarray(X.to_global()), B0)
    assert checks.passed(err, np.float64, factor=30), err
