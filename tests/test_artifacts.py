"""Durable executable artifact tests: slate_tpu/serve/artifacts.

Covers the fingerprint (content + runtime halves, sensitivity to every
field), the integrity-verification ladder (miss / corrupt / stale /
load_fail / cache_seed — each counted, none fatal), the cross-process
write lock with stale-break, the cache integration (restore before
compile, save after build, self-heal after corruption), the three new
chaos sites, and the service readiness phases (cold -> restoring ->
ready) with the in-process restart drill: a fresh cache on a warmed
artifact dir serves a steady-state stream with ZERO compiles.

A module-scoped warmed store is shared so the expensive builds happen
once; corruption tests copy artifacts into per-test dirs rather than
poisoning the shared store.  The true cross-process drill (new
interpreter, same artifact dir) lives in ``run_tests.py --coldstart``.
"""

import json
import os
import shutil
import time

import numpy as np
import pytest

from slate_tpu.aux import faults, metrics
from slate_tpu.serve import buckets as bk
from slate_tpu.serve.artifacts import (
    ARTIFACTS_ENV,
    ArtifactStore,
    _FileLock,
    runtime_fields,
    store_from_env,
)
from slate_tpu.serve.cache import ExecutableCache, _warm_inputs, direct_call
from slate_tpu.serve.service import (
    PHASE_COLD,
    PHASE_READY,
    SolverService,
)

FLOOR = 16
NRHS_FLOOR = 4


@pytest.fixture(autouse=True)
def clean_env():
    """Metrics on (the artifact counters are the contract under test),
    faults disarmed before AND after."""
    metrics.off()
    metrics.reset()
    metrics.on()
    faults.reset()
    yield
    faults.reset()
    metrics.off()
    metrics.reset()


def _key(nrhs=2):
    # schedule="recursive": the PR3 pure-JAX kernels trace custom-call
    # free, so jax.export persists a module a FRESH process can run
    # (schedule="auto" routes to vendor LAPACK on CPU, whose custom
    # calls the portability guard sends to the cache_seed rung)
    return bk.bucket_for(
        "gesv", 10, 10, nrhs, np.float64, floor=FLOOR,
        nrhs_floor=NRHS_FLOOR, schedule="recursive",
    )


@pytest.fixture(scope="module")
def warmed(tmp_path_factory):
    """One warmed (manifest + artifact dir) pair for the module: the
    gesv 16x16x4 f64 bucket at both batch points, built once."""
    root = tmp_path_factory.mktemp("artifacts")
    man = str(root / "warmup.json")
    art = str(root / "store")
    metrics.on()  # records the builds; per-test fixture resets after
    cache = ExecutableCache(manifest_path=man, artifact_dir=art)
    cache.ensure_manifest(_key(), (1, 4))
    cache.warmup(batch_max=4)
    assert sorted(
        n for n in os.listdir(art) if n.endswith(".slate_exe")
    ), "warmup must have persisted artifacts"
    return {"man": man, "art": art, "key": _key()}


def _problem(n=10, nrhs=2, seed=0):
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((n, n)) + n * np.eye(n)
    B = rng.standard_normal((n, nrhs))
    return A, B


def _copy_store(warmed, tmp_path):
    dst = str(tmp_path / "store")
    shutil.copytree(warmed["art"], dst)
    # the copied lock/xla-cache dirs are fine; only .slate_exe matters
    return dst


def _artifact_path(store_dir, key, batch):
    return ArtifactStore(store_dir).path_for(key, batch)


# ---------------------------------------------------------------------------
# fingerprint
# ---------------------------------------------------------------------------


def test_content_fields_cover_schedule_precision_batch():
    k = bk.BucketKey(
        "gesv", 16, 16, 4, "float64", 16,
        schedule="recursive", precision="mixed",
    )
    f = bk.content_fields(k, 4)
    assert f["schedule"] == "recursive"
    assert f["precision"] == "mixed"
    assert f["batch"] == 4
    base = bk.fingerprint(f)
    for field, other in (
        ("schedule", "flat"), ("precision", "full"), ("batch", 1),
        ("dtype", "float32"), ("m", 32), ("nb", 8),
    ):
        assert bk.fingerprint({**f, field: other}) != base, field


def test_runtime_fields_shape():
    f = runtime_fields()
    assert set(f) == {"jax", "jaxlib", "backend", "device_kind", "x64"}
    assert f["backend"] == "cpu"
    assert f["x64"] is True  # conftest enables x64


def test_store_fingerprint_includes_runtime(tmp_path):
    st = ArtifactStore(str(tmp_path / "s"))
    fp, fields = st.fingerprint(_key(), 1)
    assert fields["jaxlib"] and "batch" in fields and "x64" in fields
    assert fp == bk.fingerprint(fields)


# ---------------------------------------------------------------------------
# store: save/load ladder
# ---------------------------------------------------------------------------


def test_load_miss_on_empty_store(tmp_path):
    st = ArtifactStore(str(tmp_path / "s"))
    with metrics.deltas() as d:
        assert st.load(_key(), 1) is None
    assert d.get("serve.artifact_miss") == 1
    assert d.get(f"serve.artifact.{_key().label}.b1.miss") == 1


def test_save_load_roundtrip_executes(warmed):
    import jax

    st = ArtifactStore(warmed["art"])
    with metrics.deltas() as d:
        call = st.load(warmed["key"], 1)
    assert call is not None
    assert d.get("serve.artifact_hit") == 1
    A, B = _warm_inputs(warmed["key"], 1)
    X, info = jax.jit(call)(A, B)
    assert np.all(np.isfinite(np.asarray(X)))


def test_corrupt_byte_flip_detected(warmed, tmp_path):
    dst = _copy_store(warmed, tmp_path)
    path = _artifact_path(dst, warmed["key"], 1)
    blob = bytearray(open(path, "rb").read())
    blob[-3] ^= 0xFF  # payload byte, past the header line
    open(path, "wb").write(bytes(blob))
    st = ArtifactStore(dst)
    with metrics.deltas() as d:
        assert st.load(warmed["key"], 1) is None
    assert d.get("serve.artifact_corrupt") == 1
    assert d.get("serve.artifact_hit") == 0


def test_truncated_and_garbage_artifacts_are_corrupt(warmed, tmp_path):
    dst = _copy_store(warmed, tmp_path)
    path = _artifact_path(dst, warmed["key"], 1)
    blob = open(path, "rb").read()
    st = ArtifactStore(dst)
    with metrics.deltas() as d:
        open(path, "wb").write(blob[: len(blob) // 2])  # truncated payload
        assert st.load(warmed["key"], 1) is None
        open(path, "wb").write(b"not an artifact at all")  # garbage header
        assert st.load(warmed["key"], 1) is None
        open(path, "wb").write(b"")  # zero-length file
        assert st.load(warmed["key"], 1) is None
    assert d.get("serve.artifact_corrupt") == 3


def test_stale_fingerprint_detected(warmed, tmp_path):
    """A header written by a 'different' environment (here: its
    fingerprint rewritten) must read as stale — checksum alone passing
    is not enough to load."""
    dst = _copy_store(warmed, tmp_path)
    path = _artifact_path(dst, warmed["key"], 1)
    blob = open(path, "rb").read()
    nl = blob.index(b"\n")
    header = json.loads(blob[:nl].decode())
    header["fingerprint"] = "0" * 64  # stale: some other jaxlib/device
    open(path, "wb").write(
        (json.dumps(header, sort_keys=True) + "\n").encode() + blob[nl + 1:]
    )
    st = ArtifactStore(dst)
    with metrics.deltas() as d:
        assert st.load(warmed["key"], 1) is None
    assert d.get("serve.artifact_stale") == 1
    assert d.get("serve.artifact_corrupt") == 0


def test_cache_seed_fallback_when_export_refuses(tmp_path, monkeypatch):
    """Computations jax.export cannot serialize (donated/sharded) must
    still produce a durable entry — mode cache_seed — and load as a
    counted recompile, never an error."""
    import jax

    def boom(*a, **kw):
        raise NotImplementedError("export unsupported for this computation")

    monkeypatch.setattr(jax.export, "export", boom)
    st = ArtifactStore(str(tmp_path / "s"))
    key = _key()
    jitted = jax.jit(lambda a, b: (a, np.int32(0)))
    mode = st.save(key, 1, jitted, ())
    assert mode == "cache_seed"
    entry = [e for e in st.entries() if "error" not in e][0]
    assert entry["mode"] == "cache_seed" and entry["payload_bytes"] == 0
    monkeypatch.undo()
    with metrics.deltas() as d:
        assert st.load(key, 1) is None  # recompile rung, XLA-cache warmed
    assert d.get("serve.artifact_cache_seed") == 1
    assert d.get("serve.artifact_corrupt") == 0


def test_nonportable_custom_calls_take_cache_seed_rung(tmp_path):
    """An executable whose exported module embeds vendor custom calls
    (jnp.linalg.solve lowers to LAPACK ffi calls on CPU) must NOT be
    persisted as an export blob — a deserialized vendor call can
    segfault in a fresh process, which no checksum catches.  The guard
    routes it to cache_seed and records why."""
    import jax
    import jax.numpy as jnp

    st = ArtifactStore(str(tmp_path / "s"))
    key = bk.bucket_for(
        "gesv", 10, 10, 2, np.float64, floor=FLOOR, nrhs_floor=NRHS_FLOOR
    )  # schedule="auto" -> vendor LAPACK on CPU
    jitted = jax.jit(
        lambda a, b: (jnp.linalg.solve(a, b), jnp.zeros((1,), jnp.int32))
    )
    specs = (
        jax.ShapeDtypeStruct((1, 16, 16), np.float64),
        jax.ShapeDtypeStruct((1, 16, NRHS_FLOOR), np.float64),
    )
    with metrics.deltas() as d:
        assert st.save(key, 1, jitted, specs) == "cache_seed"
    assert d.get("serve.artifact_saved_cache_seed") == 1
    [entry] = [e for e in st.entries() if "error" not in e]
    assert entry["mode"] == "cache_seed" and entry["payload_bytes"] == 0
    assert any("lapack" in t for t in entry["nonportable"]), entry


def test_save_never_raises_on_unwritable_root(tmp_path):
    st = ArtifactStore(str(tmp_path / "s"))
    st.root = str(tmp_path / "s" / "gone" / "deeper")  # invalid mid-flight
    with metrics.deltas() as d:
        st.save(_key(), 1, None, ())  # jitted=None would also explode
    assert d.get("serve.artifact_save_error") == 1


# ---------------------------------------------------------------------------
# chaos: the three new fault sites
# ---------------------------------------------------------------------------


def test_fault_site_artifact_corrupt(warmed):
    st = ArtifactStore(warmed["art"])
    faults.arm("artifact_corrupt", once=True)
    faults.on()
    with metrics.deltas() as d:
        assert st.load(warmed["key"], 1) is None  # injected flip caught
        assert st.load(warmed["key"], 1) is not None  # once => healthy after
    assert d.get("serve.artifact_corrupt") == 1
    assert d.get("faults.injected.artifact_corrupt") == 1
    assert d.get("serve.artifact_hit") == 1


def test_fault_site_artifact_stale(warmed):
    st = ArtifactStore(warmed["art"])
    faults.arm("artifact_stale", once=True)
    faults.on()
    with metrics.deltas() as d:
        assert st.load(warmed["key"], 1) is None
        assert st.load(warmed["key"], 1) is not None
    assert d.get("serve.artifact_stale") == 1
    assert d.get("faults.injected.artifact_stale") == 1


def test_fault_site_artifact_load_fail(warmed):
    st = ArtifactStore(warmed["art"])
    faults.arm("artifact_load_fail", once=True)
    faults.on()
    with metrics.deltas() as d:
        assert st.load(warmed["key"], 1) is None  # deserialize raised
        assert st.load(warmed["key"], 1) is not None
    assert d.get("serve.artifact_load_fail") == 1
    assert d.get("faults.injected.artifact_load_fail") == 1


# ---------------------------------------------------------------------------
# cross-process lock
# ---------------------------------------------------------------------------


def test_filelock_acquire_release(tmp_path):
    p = str(tmp_path / ".lock")
    with _FileLock(p):
        assert os.path.exists(p)
    assert not os.path.exists(p)


def test_filelock_breaks_stale_lock(tmp_path):
    p = str(tmp_path / ".lock")
    open(p, "w").write("12345\n")
    old = time.time() - 3600
    os.utime(p, (old, old))  # a crashed writer's leftover
    t0 = time.monotonic()
    with _FileLock(p, timeout_s=5.0):
        assert time.monotonic() - t0 < 1.0  # broke it, didn't wait out
        assert os.path.exists(p)
    assert not os.path.exists(p)


def test_filelock_times_out_without_wedging(tmp_path):
    p = str(tmp_path / ".lock")
    open(p, "w").write("12345\n")  # fresh lock, never released
    with metrics.deltas() as d:
        t0 = time.monotonic()
        with _FileLock(p, timeout_s=0.1, stale_s=3600):
            pass  # proceeds unlocked: rename keeps writes atomic anyway
        assert 0.1 <= time.monotonic() - t0 < 2.0
    assert d.get("serve.artifact_lock_timeout") == 1
    os.unlink(p)


# ---------------------------------------------------------------------------
# cache integration + readiness (the in-process restart drill)
# ---------------------------------------------------------------------------


def test_env_activation(tmp_path, monkeypatch):
    monkeypatch.delenv(ARTIFACTS_ENV, raising=False)
    assert store_from_env() is None
    assert ExecutableCache(manifest_path=None).artifacts is None
    monkeypatch.setenv(ARTIFACTS_ENV, str(tmp_path / "a"))
    c = ExecutableCache(manifest_path=None)
    assert c.artifacts is not None
    assert c.artifacts.root == str(tmp_path / "a")


def test_restart_drill_restore_then_zero_compiles(warmed):
    """The acceptance drill, in-process: a FRESH cache pointed at the
    warmed artifact dir restores (not recompiles), reaches ready, and
    a >= 20-request steady-state stream pays zero jit compiles."""
    cache = ExecutableCache(
        manifest_path=warmed["man"], artifact_dir=warmed["art"]
    )
    svc = SolverService(
        cache=cache, batch_max=4, batch_window_s=0.005,
        dim_floor=FLOOR, nrhs_floor=NRHS_FLOOR, schedule="recursive",
        start=False,
    )
    assert svc.health()["phase"] == PHASE_COLD
    with metrics.deltas() as d:
        svc.start()
        assert svc.wait_ready(timeout=120)
    h = svc.health()
    assert h["phase"] == PHASE_READY and h["ready"]
    assert h["restore"]["entries"] == 2
    assert h["restore"]["restored"] == 2, h["restore"]
    assert h["restore"]["compiled"] == 0 and h["restore"]["failed"] == 0
    assert d.get("serve.artifact_hit") == 2
    A, B = _problem()
    with metrics.deltas() as d:
        futs = []
        for i in range(4):  # coalesced: the b4 batch point
            futs.append(svc.submit("gesv", A + i * 1e-3 * np.eye(10), B))
        for f in futs:
            assert np.all(np.isfinite(f.result(timeout=120)))
        for i in range(16):  # lone sequential: the b1 batch point
            X = svc.submit("gesv", A, B).result(timeout=120)
        assert d.get("serve.requests") == 20
        assert d.get("jit.compilations") == 0, "restored steady state compiled"
    ref = direct_call("gesv", A, B)
    assert np.abs(X - ref).max() < 1e-9 * max(np.abs(ref).max(), 1.0)
    svc.stop()


def test_corrupt_artifact_recompiles_and_self_heals(warmed, tmp_path):
    """Byte-flip drill: the corrupted entry falls back to a counted
    recompile (results stay correct), the rebuild overwrites the bad
    file, and the NEXT restore loads everything again."""
    dst = _copy_store(warmed, tmp_path)
    path = _artifact_path(dst, warmed["key"], 1)
    blob = bytearray(open(path, "rb").read())
    blob[-1] ^= 0x10
    open(path, "wb").write(bytes(blob))

    cache = ExecutableCache(manifest_path=warmed["man"], artifact_dir=dst)
    with metrics.deltas() as d:
        out = cache.restore(batch_max=4)
    assert out == {"entries": 2, "restored": 1, "compiled": 1,
                   "failed": 0, "skipped": 0}
    assert d.get("serve.artifact_corrupt") == 1
    assert d.get("serve.artifact_saved") == 1  # the self-heal rewrite
    A, B = _problem()
    svc = SolverService(
        cache=cache, batch_max=4, dim_floor=FLOOR, nrhs_floor=NRHS_FLOOR,
        schedule="recursive",
    )
    X = svc.submit("gesv", A, B).result(timeout=120)
    ref = direct_call("gesv", A, B)
    assert np.abs(X - ref).max() < 1e-9 * max(np.abs(ref).max(), 1.0)
    svc.stop()

    cache2 = ExecutableCache(manifest_path=warmed["man"], artifact_dir=dst)
    with metrics.deltas() as d:
        out2 = cache2.restore(batch_max=4)
    assert out2["restored"] == 2 and out2["compiled"] == 0  # healed
    assert d.get("serve.artifact_corrupt") == 0


def test_warmup_from_artifacts_counts_zero_compiles(warmed):
    """warmup() on a fully-persisted store restores every entry, so it
    must report 0 compiles (the compile accounting feeds alerting)."""
    cache = ExecutableCache(
        manifest_path=warmed["man"], artifact_dir=warmed["art"]
    )
    with metrics.deltas() as d:
        assert cache.warmup(batch_max=4) == 0
    assert d.get("serve.warmup_compiles") == 0
    assert d.get("serve.artifact_hit") == 2


def test_cache_seed_verdict_skips_redundant_resave(tmp_path, monkeypatch):
    """A bucket whose artifact is (and stays) cache_seed must not pay
    a jax.export retrace + byte-identical rewrite on every replica's
    cold build — load() verified the entry; executable() trusts it."""
    import jax

    man = str(tmp_path / "m.json")
    art = str(tmp_path / "a")
    key = _key()
    with monkeypatch.context() as m:
        def boom(*a, **kw):
            raise NotImplementedError("export unsupported")

        m.setattr(jax.export, "export", boom)
        c1 = ExecutableCache(manifest_path=man, artifact_dir=art)
        c1.ensure_manifest(key, (1,))
        c1.warmup(batch_max=1)  # persists a cache_seed entry
    c2 = ExecutableCache(manifest_path=man, artifact_dir=art)
    with metrics.deltas() as d:
        c2.restore(batch_max=1)  # load -> cache_seed -> recompile
    assert d.get("serve.artifact_cache_seed") == 1
    assert d.get("serve.artifact_saved") == 0  # no byte-identical rewrite


def test_wait_ready_false_on_never_started_service(warmed):
    cache = ExecutableCache(
        manifest_path=warmed["man"], artifact_dir=warmed["art"]
    )
    svc = SolverService(
        cache=cache, dim_floor=FLOOR, nrhs_floor=NRHS_FLOOR, start=False,
    )
    t0 = time.time()
    assert svc.wait_ready(timeout=30) is False  # immediate, not a hang
    assert time.time() - t0 < 5.0
    assert svc.health()["phase"] == PHASE_COLD


def test_ready_immediately_without_artifact_store():
    svc = SolverService(
        cache=ExecutableCache(manifest_path=None),
        dim_floor=FLOOR, nrhs_floor=NRHS_FLOOR, start=False,
    )
    assert svc.health()["phase"] == PHASE_COLD
    assert not svc.health()["ready"]
    svc.start()
    assert svc.wait_ready(timeout=10)
    h = svc.health()
    assert h["phase"] == PHASE_READY and h["ready"] and h["restore"] is None
    svc.stop()


def test_restore_on_start_false_skips_restore(warmed):
    cache = ExecutableCache(
        manifest_path=warmed["man"], artifact_dir=warmed["art"]
    )
    with metrics.deltas() as d:
        svc = SolverService(
            cache=cache, dim_floor=FLOOR, nrhs_floor=NRHS_FLOOR,
            restore_on_start=False,
        )
        assert svc.wait_ready(timeout=10)
        assert svc.health()["restore"] is None
        assert d.get("serve.artifact_hit") == 0
    svc.stop()


def test_restore_chaos_degrades_but_reaches_ready(warmed, tmp_path):
    """All three artifact sites armed during a restore: every rung
    degrades to a recompile, the service still reaches ready, and the
    stream serves correct results."""
    dst = _copy_store(warmed, tmp_path)
    # first load: corrupt fires (and returns before the stale rung
    # evaluates); second load: corrupt is spent, stale fires on its
    # own first evaluation
    faults.configure("artifact_corrupt:once;artifact_stale:once")
    faults.on()
    cache = ExecutableCache(manifest_path=warmed["man"], artifact_dir=dst)
    svc = SolverService(
        cache=cache, batch_max=4, dim_floor=FLOOR, nrhs_floor=NRHS_FLOOR,
        schedule="recursive", start=False,
    )
    with metrics.deltas() as d:
        svc.start()
        assert svc.wait_ready(timeout=240)
        h = svc.health()
        assert h["restore"]["failed"] == 0
        assert h["restore"]["compiled"] == 2  # both loads were injected
        A, B = _problem()
        X = svc.submit("gesv", A, B).result(timeout=120)
    assert d.get("serve.artifact_corrupt") == 1
    assert d.get("serve.artifact_stale") == 1
    ref = direct_call("gesv", A, B)
    assert np.abs(X - ref).max() < 1e-9 * max(np.abs(ref).max(), 1.0)
    svc.stop()
