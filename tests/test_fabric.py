"""Factor-fabric tests: slate_tpu/fabric (device arena + streaming
gels sessions) and their serving-tier integration.

Covers the ISSUE acceptance set: arena budget/LRU/cross-replica/spill
semantics, streamed update-vs-refactor parity (f64/c128, rank 1 and
rank k), breakdown -> counted refactor with a correct X, session
serving under arena eviction pressure, the warmed gels-solve-bucket
steady state (compile-free, hits-only, upload-free), and the residual
fence on every streamed solve.  A module-scoped ExecutableCache is
shared so each gels bucket compiles once for the whole file (the
test_factor_cache pattern).
"""

import numpy as np
import pytest

from slate_tpu.aux import faults, metrics
from slate_tpu.exceptions import DimensionError, InvalidInput
from slate_tpu.fabric.arena import (
    ARENA_ENV,
    FactorArena,
    arena_from_options,
    parse_arena_spec,
)
from slate_tpu.fabric.session import FactorSession, _update_r
from slate_tpu.serve import buckets as bk
from slate_tpu.serve.cache import ExecutableCache
from slate_tpu.serve.factor_cache import (
    FactorCache,
    FactorEntry,
    gels_factor_pack,
    matrix_fingerprint,
    residual_ok,
    solve_from_factor,
)
from slate_tpu.serve.placement import PlacementPolicy
from slate_tpu.serve.service import SolverService

FLOOR = 16
NRHS_FLOOR = 4


@pytest.fixture(autouse=True)
def metrics_on():
    metrics.off()
    metrics.reset()
    metrics.on()
    yield
    metrics.off()
    metrics.reset()
    faults.reset()


@pytest.fixture(scope="module")
def shared_cache():
    return ExecutableCache(manifest_path=None)


def _svc(shared_cache, **kw):
    kw.setdefault("batch_max", 4)
    kw.setdefault("batch_window_s", 0.002)
    kw.setdefault("dim_floor", FLOOR)
    kw.setdefault("nrhs_floor", NRHS_FLOOR)
    return SolverService(cache=shared_cache, **kw)


def _tall(m, n, seed=0, dtype=np.float64):
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((m, n))
    if np.issubdtype(np.dtype(dtype), np.complexfloating):
        A = A + 1j * rng.standard_normal((m, n))
    return A.astype(dtype)


def _lstsq(A, B):
    return np.linalg.lstsq(A, B, rcond=None)[0]


# ---------------------------------------------------------------------------
# arena: activation grammar
# ---------------------------------------------------------------------------


def test_parse_arena_spec():
    for off in ("", "0", "off", "false", "no", "OFF"):
        assert parse_arena_spec(off) is None
    for on in ("1", "on", "true", "yes", "ON"):
        assert parse_arena_spec(on) == {}
    assert parse_arena_spec("bytes=4096") == {"max_bytes": 4096}
    assert parse_arena_spec("bytes=1e6") == {"max_bytes": 1000000}
    with pytest.raises(ValueError):
        parse_arena_spec("entries=4")
    with pytest.raises(ValueError):
        parse_arena_spec("bytes")


def test_arena_from_env_and_options(monkeypatch):
    from slate_tpu.enums import Option

    monkeypatch.setenv(ARENA_ENV, "bytes=2048")
    ar = arena_from_options()
    assert ar is not None and ar.max_bytes == 2048
    # an explicitly-off env wins over an armed option spec
    monkeypatch.setenv(ARENA_ENV, "off")
    assert arena_from_options({Option.ServeFactorArena: "1"}) is None
    # env unset: the option spec decides
    monkeypatch.delenv(ARENA_ENV)
    assert arena_from_options() is None  # default spec "" = off
    ar = arena_from_options({Option.ServeFactorArena: "bytes=512"})
    assert ar is not None and ar.max_bytes == 512


def test_service_default_has_no_arena(shared_cache):
    """OFF by default: a factor-cache service without the env/option
    carries arena=None (the one-branch hot path), and an arena is
    never constructed without a factor cache to feed it."""
    svc = _svc(shared_cache, factor_cache=FactorCache(max_entries=4),
               start=False)
    assert svc.arena is None
    svc.stop()
    svc = _svc(shared_cache, factor_cache=False,
               factor_arena=FactorArena(), start=False)
    assert svc.arena is None  # no cache -> nothing to make resident
    svc.stop()


# ---------------------------------------------------------------------------
# arena: residency semantics
# ---------------------------------------------------------------------------


def test_arena_hit_counts_upload_avoided():
    ar = FactorArena(max_bytes=1 << 20)
    F = np.ones((8, 8))
    buf = ar.put("fp-a", "lane0", F)
    assert buf is not None and len(ar) == 1
    with metrics.deltas() as d:
        got = ar.get("fp-a", "lane0")
        assert got is buf
        assert d.get("serve.arena.hit") == 1
        assert d.get("serve.arena.upload_avoided_bytes") == F.nbytes
        assert d.get("serve.arena.lane.lane0.hit") == 1
    with metrics.deltas() as d:
        assert ar.get("fp-b", "lane0", any_lane=False) is None
        assert d.get("serve.arena.miss") == 1


def test_arena_lru_budget_eviction():
    F = np.ones((8, 8))  # 512 B each
    ar = FactorArena(max_bytes=2 * F.nbytes)
    ar.put("a", "l", F)
    ar.put("b", "l", F)
    ar.get("a", "l")  # refresh a: b becomes LRU
    with metrics.deltas() as d:
        ar.put("c", "l", F)
        assert d.get("serve.arena.evict") == 1
    assert ar.get("b", "l", any_lane=False) is None  # evicted
    assert ar.get("a", "l") is not None
    assert ar.get("c", "l") is not None
    assert ar.stats()["bytes"] <= ar.max_bytes


def test_arena_oversize_uncacheable():
    F = np.ones((16, 16))
    ar = FactorArena(max_bytes=F.nbytes - 1)
    buf = ar.put("big", "l", F)
    assert buf is not None  # caller still dispatches this upload
    assert len(ar) == 0  # but it never became resident
    assert ar.get("big", "l", any_lane=False) is None


def test_arena_cross_replica_share():
    import jax

    ar = FactorArena(max_bytes=1 << 20)
    F = np.arange(16.0).reshape(4, 4)
    ar.put("fp", "lane0", F)
    dev = jax.devices()[0]
    with metrics.deltas() as d:
        buf = ar.get("fp", "lane1", device=dev)
        assert buf is not None
        assert d.get("serve.arena.cross_replica") == 1
    assert np.asarray(buf).tolist() == F.tolist()
    # the copy installed on the requesting lane: next get is a hit
    with metrics.deltas() as d:
        assert ar.get("fp", "lane1") is not None
        assert d.get("serve.arena.hit") == 1


def test_arena_drop_spill_drop_lane():
    F = np.ones((4, 4))
    ar = FactorArena(max_bytes=1 << 20)
    for i in range(4):
        ar.put(f"fp{i}", "l0", F)
    ar.put("fp0", "l1", F)
    assert ar.drop("fp0") == 2  # both lanes
    assert ar.get("fp0", "l0", any_lane=False) is None
    with metrics.deltas() as d:
        # 3 resident: keep floor(3 * 0.5) = 1, spill the 2 LRU
        n = ar.spill("l0", keep_frac=0.5)
        assert n == 2 and d.get("serve.arena.spill") == 2
    assert ar.drop_lane("l0") == 1  # the MRU survivor
    assert ar.stats()["lanes"].get("l0", {}).get("entries", 0) == 0


# ---------------------------------------------------------------------------
# gels factor pack (factor-cache layer)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_gels_pack_solve_parity(dtype):
    m, n, nrhs = 20, 12, 2
    key = bk.bucket_for("gels", m, n, nrhs, dtype, floor=FLOOR,
                        nrhs_floor=NRHS_FLOOR)
    A = _tall(m, n, seed=1, dtype=dtype)
    pack = gels_factor_pack(A, key)
    assert pack.shape == bk.solve_factor_shape(key)
    entry = FactorEntry(fp="x", routine="gels", key=key, factor=pack,
                        perm=None, n=n)
    B = _tall(m, nrhs, seed=2, dtype=dtype)
    X = solve_from_factor(entry, B)
    assert X.shape == (n, nrhs)
    assert np.abs(X - _lstsq(A, B)).max() < 1e-9
    assert residual_ok(A, B, X, routine="gels")
    # a finite-but-wrong X fails the gels (normal-equations) fence
    bad = np.array(X)
    bad[0, 0] = bad[0, 0] * 2 + 1
    assert not residual_ok(A, B, bad, routine="gels")


def test_factor_cache_update_rejects_gels():
    key = bk.bucket_for("gels", 20, 12, 2, np.float64, floor=FLOOR,
                        nrhs_floor=NRHS_FLOOR)
    A = _tall(20, 12, seed=3)
    fc = FactorCache(max_entries=4)
    entry = FactorEntry(fp="g1", routine="gels", key=key,
                        factor=gels_factor_pack(A, key), perm=None, n=12)
    assert fc.put(entry)
    with pytest.raises(ValueError, match="session"):
        fc.update("g1", A, np.ones(12))


# ---------------------------------------------------------------------------
# session: streamed update vs refactor parity
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("k", [1, 5])
def test_update_r_matches_refactor(dtype, k):
    """The O(k n^2) Householder fold keeps R^H R = A^H A to sqrt(eps)
    — rank-1 and rank-k appends, real and complex."""
    m, n = 40, 13
    A = _tall(m, n, seed=4, dtype=dtype)
    R = np.array(np.linalg.qr(A, mode="r")[:n])
    C = _tall(k, n, seed=5, dtype=dtype)
    _update_r(R, np.array(C))
    A2 = np.vstack([A, C])
    G, G2 = R.conj().T @ R, A2.conj().T @ A2
    tol = np.sqrt(np.finfo(np.dtype(dtype)).eps)
    assert np.abs(G - G2).max() <= tol * np.abs(G2).max()
    # and the factor stayed upper triangular
    assert np.abs(np.tril(R, -1)).max() == 0.0


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("k", [1, 4])
def test_session_update_vs_refactor_parity(dtype, k):
    m, n = 30, 10
    A = _tall(m, n, seed=6, dtype=dtype)
    s = FactorSession(None, A)
    C = _tall(k, n, seed=7, dtype=dtype)
    with metrics.deltas() as d:
        s.append(C)
        assert d.get("fabric.session.factor") == 1
        assert d.get("fabric.session.update") == 1
        assert d.get("fabric.session.update_rows") == k
    A2 = np.vstack([A, C])
    B = _tall(m + k, 3, seed=8, dtype=dtype)
    with metrics.deltas() as d:
        X = s.solve(B)
        assert d.get("fabric.session.solve") == 1
        assert d.get("fabric.session.fence_fail") == 0
    ref = _lstsq(A2, B)
    tol = np.sqrt(np.finfo(np.dtype(dtype)).eps)
    assert np.abs(X - ref).max() <= tol * max(np.abs(ref).max(), 1.0)
    assert not s.pristine and s.shape == (m + k, n)


def test_session_many_appends_stay_fenced():
    """Every streamed solve is fenced (fabric.session.solve counts
    them all; zero fence failures on a well-conditioned stream)."""
    rng = np.random.default_rng(9)
    A = _tall(25, 8, seed=9)
    s = FactorSession(None, A)
    A_cur = A
    with metrics.deltas() as d:
        for i in range(6):
            C = rng.standard_normal((2, 8))
            s.append(C)
            A_cur = np.vstack([A_cur, C])
            B = rng.standard_normal((A_cur.shape[0], 2))
            assert np.abs(s.solve(B) - _lstsq(A_cur, B)).max() < 1e-9
        assert d.get("fabric.session.solve") == 6
        assert d.get("fabric.session.fence_fail") == 0
        assert d.get("fabric.session.refactor") == 0
        assert d.get("fabric.session.update_rows") == 12


def test_session_fence_failure_pays_counted_refactor():
    """A corrupted maintained factor must never surface as a wrong X:
    the fence trips, a counted refactor repairs R, and the delivered
    X is correct."""
    A = _tall(30, 10, seed=10)
    s = FactorSession(None, A)
    s.append(_tall(3, 10, seed=11))
    # bit-rot the maintained triangle behind the session's back
    with s._lock:
        s._R = np.array(s._R)
        s._R[0, 0] = s._R[0, 0] * 2 + 1
    B = _tall(33, 2, seed=12)
    with metrics.deltas() as d:
        X = s.solve(B)
        assert d.get("fabric.session.fence_fail") == 1
        assert d.get("fabric.session.refactor") == 1
    assert np.abs(X - _lstsq(np.asarray(s._A), B)).max() < 1e-9


def test_session_update_fault_site_recovers():
    """The session_update chaos site perturbs R after a fold; the next
    solve's fence catches it and the refactor path delivers a correct
    X — never a silent wrong answer."""
    A = _tall(30, 10, seed=13)
    s = FactorSession(None, A)
    s.append(_tall(2, 10, seed=14))  # builds R (un-faulted)
    faults.arm("session_update", once=True)
    faults.on()
    try:
        s.append(_tall(2, 10, seed=15))  # the fold this site poisons
        B = _tall(34, 2, seed=16)
        with metrics.deltas() as d:
            X = s.solve(B)
            assert d.get("fabric.session.refactor") == 1
        assert np.abs(X - _lstsq(np.asarray(s._A), B)).max() < 1e-9
    finally:
        faults.reset()


def test_session_breakdown_on_rank_collapse_refactors():
    """An update that collapses a diagonal (rank-deficient fold) is a
    breakdown: append itself repairs via a counted refactor."""
    A = np.eye(12, 8) + 0.01 * _tall(12, 8, seed=17)
    s = FactorSession(None, A)
    s.append(_tall(1, 8, seed=18))
    with s._lock:  # simulate a collapsed pivot from a degenerate fold
        s._R = np.array(s._R)
        s._R[3, 3] = 0.0
    with metrics.deltas() as d:
        # an all-zero row leaves every column untouched, so the
        # collapsed pivot survives the fold and trips the breakdown
        # check inside append itself
        s.append(np.zeros((1, 8)))
        assert d.get("fabric.session.refactor") == 1
    B = _tall(14, 2, seed=20)
    assert np.abs(s.solve(B) - _lstsq(np.asarray(s._A), B)).max() < 1e-9


def test_session_validation():
    with pytest.raises(InvalidInput):
        FactorSession(None, _tall(20, 10), routine="gesv")
    with pytest.raises(DimensionError):
        FactorSession(None, _tall(8, 10))  # wide
    with pytest.raises(InvalidInput):
        FactorSession(None, np.full((10, 4), np.nan))
    s = FactorSession(None, _tall(20, 10, seed=21))
    with pytest.raises(DimensionError):
        s.append(np.ones((2, 7)))  # wrong column count
    with pytest.raises(InvalidInput):
        s.append(np.full((1, 10), np.inf))
    s.append(np.ones((1, 10)))
    with pytest.raises(DimensionError):
        s.solve(np.ones((20, 2)))  # stale m after append


# ---------------------------------------------------------------------------
# serving-tier integration
# ---------------------------------------------------------------------------


def test_warmed_session_stream_compile_free(shared_cache):
    """The acceptance steady state: pristine session solves ride the
    warmed gels solve bucket — hits only, zero compiles, zero factor
    re-uploads (the arena holds the pack device-resident)."""
    fc = FactorCache(max_entries=8)
    svc = _svc(shared_cache, factor_cache=fc, factor_arena=FactorArena())
    try:
        rng = np.random.default_rng(22)
        A = _tall(20, 12, seed=22)
        svc.submit("gels", A, rng.standard_normal((20, 2))).result(
            timeout=300
        )
        svc.warmup()  # the miss registered the solve bucket
        s = FactorSession(svc, A)
        with metrics.deltas() as d:
            for _ in range(5):
                B = rng.standard_normal((20, 2))
                X = s.solve(B)
                assert np.abs(X - _lstsq(A, B)).max() < 1e-9
            assert d.get("serve.factor_cache.hit") == 5
            assert d.get("jit.compilations") == 0
            assert d.get("serve.arena.upload_avoided_bytes") > 0
            # zero per-hit re-upload once resident: exactly one upload
            assert d.get("serve.arena.upload_bytes") == 0 or (
                d.get("serve.arena.hit") >= 4
            )
        assert s.pristine
    finally:
        svc.stop()


def test_arena_upload_avoided_accounting(shared_cache):
    """upload_avoided_bytes = factor pack bytes x device hits — the
    zero-per-hit-transfer acceptance, by arithmetic."""
    fc = FactorCache(max_entries=8)
    svc = _svc(shared_cache, factor_cache=fc, factor_arena=FactorArena())
    try:
        rng = np.random.default_rng(23)
        A = _tall(20, 12, seed=23)
        svc.submit("gels", A, rng.standard_normal((20, 2))).result(
            timeout=300
        )
        svc.warmup()
        fp = matrix_fingerprint(A, "gels", schedule=svc.schedule)
        nbytes = fc.get(fp).factor.nbytes
        with metrics.deltas() as d:
            for _ in range(4):
                svc.submit(
                    "gels", A, rng.standard_normal((20, 2))
                ).result(timeout=300)
            hits = int(d.get("serve.arena.hit"))
            assert hits >= 3
            assert d.get("serve.arena.upload_avoided_bytes") == \
                hits * nbytes
    finally:
        svc.stop()


def test_session_survives_arena_eviction_pressure(shared_cache):
    """Arena eviction under byte pressure only costs a re-upload:
    alternating same-bucket sessions whose packs cannot co-reside keep
    solving correctly while serve.arena.evict counts the churn."""
    fc = FactorCache(max_entries=8)
    key = bk.bucket_for("gels", 20, 12, 2, np.float64, floor=FLOOR,
                        nrhs_floor=NRHS_FLOOR)
    pack_bytes = int(np.prod(bk.solve_factor_shape(key))) * 8
    svc = _svc(shared_cache, factor_cache=fc,
               factor_arena=FactorArena(max_bytes=pack_bytes))
    try:
        rng = np.random.default_rng(24)
        As = [_tall(20, 12, seed=30 + i) for i in range(2)]
        sessions = [FactorSession(svc, A) for A in As]
        with metrics.deltas() as d:
            for _ in range(3):
                for A, s in zip(As, sessions):
                    B = rng.standard_normal((20, 2))
                    assert np.abs(s.solve(B) - _lstsq(A, B)).max() < 1e-9
            assert d.get("serve.arena.evict") >= 1
    finally:
        svc.stop()


def test_cross_lane_hit_on_cooling_breaker(shared_cache):
    """Satellite: a hit whose owning lane's solve-bucket breaker is
    cooling re-routes to the least-loaded healthy lane and STILL
    reuses the cached factor through that lane's solve bucket —
    counted cross_lane_hit, not a direct-path spill."""
    import time as _time

    fc = FactorCache(max_entries=8)
    svc = _svc(shared_cache, factor_cache=fc,
               placement=PlacementPolicy(replicas=2))
    try:
        rng = np.random.default_rng(25)
        n = 12
        A = rng.standard_normal((n, n)) + n * np.eye(n)
        B = rng.standard_normal((n, 2))
        svc.submit("gesv", A, B).result(timeout=300)
        svc.warmup()
        fp = matrix_fingerprint(A, "gesv", schedule=svc.schedule)
        entry = fc.get(fp)
        own = next(r for r in svc._replicas if r.name == entry.replica)
        br = svc._breaker(own, entry.solve_key)
        br.state = bk.BREAKER_OPEN
        br.opened_at = _time.monotonic()
        with metrics.deltas() as d:
            X = svc.submit("gesv", A, B).result(timeout=300)
            assert d.get("serve.factor_cache.cross_lane_hit") == 1
            assert d.get("serve.factor_cache.spill") == 0
            assert d.get("serve.factor_cache.hit") == 1
        assert np.abs(X - np.linalg.solve(A, B)).max() < 1e-9
        br.state = bk.BREAKER_CLOSED
    finally:
        svc.stop()


def test_invalidation_drops_arena_residency(shared_cache):
    """fc invalidation and arena residency stay coherent: the service
    drops the fingerprint's device buffers with the host entry."""
    fc = FactorCache(max_entries=8)
    ar = FactorArena()
    svc = _svc(shared_cache, factor_cache=fc, factor_arena=ar)
    try:
        rng = np.random.default_rng(26)
        A = _tall(20, 12, seed=26)
        svc.submit("gels", A, rng.standard_normal((20, 2))).result(
            timeout=300
        )
        svc.warmup()
        svc.submit("gels", A, rng.standard_normal((20, 2))).result(
            timeout=300
        )
        assert len(ar) == 1
        fp = matrix_fingerprint(A, "gels", schedule=svc.schedule)
        fc.invalidate(fp)
        ar.drop(fp)  # what serve.api.invalidate() does
        assert len(ar) == 0
        h = svc.health()
        assert h["arena"]["entries"] == 0
    finally:
        svc.stop()
