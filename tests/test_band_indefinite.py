"""Band + indefinite + simplified API tests (reference: test_gbsv.cc,
test_pbsv.cc, test_hesv.cc, test_tbsm.cc)."""

import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.drivers import band as band_mod
from slate_tpu.drivers import indefinite as indef
from slate_tpu.enums import Diag, Side, Uplo
from slate_tpu.matrix.matrix import (
    BandMatrix,
    HermitianBandMatrix,
    HermitianMatrix,
    Matrix,
    TriangularBandMatrix,
)
from slate_tpu.testing import checks


def _band_np(rng, n, kl, ku):
    A = rng.standard_normal((n, n))
    i, j = np.meshgrid(range(n), range(n), indexing="ij")
    A[(j - i > ku) | (i - j > kl)] = 0
    return A


def test_gbmm(rng):
    n, kl, ku = 32, 3, 2
    A0 = _band_np(rng, n, kl, ku)
    B0 = rng.standard_normal((n, 8))
    A = BandMatrix.from_global(A0, kl, ku, 8)
    B = Matrix.from_global(B0, 8)
    C = Matrix.zeros(n, 8, 8, dtype=np.float64)
    C2 = band_mod.gbmm(1.0, A, B, 0.0, C)
    np.testing.assert_allclose(np.asarray(C2.to_global()), A0 @ B0, atol=1e-12)


def test_gbsv(rng):
    n, kl, ku = 48, 4, 3
    A0 = _band_np(rng, n, kl, ku) + 10 * np.eye(n)
    B0 = rng.standard_normal((n, 4))
    A = BandMatrix.from_global(A0, kl, ku, 8)
    B = Matrix.from_global(B0, 8)
    X, LU, piv, info = band_mod.gbsv(A, B)
    assert int(info) == 0
    err = checks.solve_residual(A0, np.asarray(X.to_global()), B0)
    assert checks.passed(err, np.float64, factor=30), err


def test_pbsv(rng):
    n, kd = 40, 4
    A0 = _band_np(rng, n, kd, kd)
    A0 = (A0 + A0.T) / 2 + n * np.eye(n)
    B0 = rng.standard_normal((n, 4))
    base = Matrix.from_global(np.tril(A0), 8)
    Ah = HermitianBandMatrix(base.data, base.layout, kd=kd, uplo=Uplo.Lower)
    X, L, info = band_mod.pbsv(Ah, Matrix.from_global(B0, 8))
    assert int(info) == 0
    err = checks.solve_residual(A0, np.asarray(X.to_global()), B0)
    assert checks.passed(err, np.float64, factor=30), err


def test_tbsm(rng):
    n, kd = 32, 3
    T0 = np.tril(_band_np(rng, n, kd, 0)) + n * np.eye(n)
    B0 = rng.standard_normal((n, 4))
    T = TriangularBandMatrix(
        Matrix.from_global(T0, 8).data,
        Matrix.from_global(T0, 8).layout,
        kd=kd,
        uplo=Uplo.Lower,
    )
    X = band_mod.tbsm(Side.Left, 1.0, T, Matrix.from_global(B0, 8))
    err = checks.solve_residual(T0, np.asarray(X.to_global()), B0)
    assert checks.passed(err, np.float64, factor=30), err


def test_hesv(rng):
    n = 40
    A0 = rng.standard_normal((n, n))
    A0 = (A0 + A0.T) / 2  # indefinite
    B0 = rng.standard_normal((n, 4))
    A = HermitianMatrix.from_global(A0, 8, uplo=Uplo.Lower)
    X, L, d, info = indef.hesv(A, Matrix.from_global(B0, 8))
    err = checks.solve_residual(A0, np.asarray(X.to_global()), B0)
    assert checks.passed(err, np.float64, factor=1000), err


def test_hetrf_factorization(rng):
    n = 24
    A0 = rng.standard_normal((n, n))
    A0 = (A0 + A0.T) / 2 + n * np.eye(n)  # definite => nopiv safe
    A = HermitianMatrix.from_global(A0, 8, uplo=Uplo.Lower)
    L, d, info = indef.hetrf(A)
    assert int(info) == 0
    Lg = np.tril(np.asarray(L.to_global()), -1) + np.eye(n)
    rec = Lg @ np.diag(np.asarray(d)) @ Lg.T
    np.testing.assert_allclose(rec, A0, atol=1e-9)


def test_hesv_complex(rng):
    n = 24
    A0 = rng.standard_normal((n, n)) + 1j * rng.standard_normal((n, n))
    A0 = (A0 + A0.conj().T) / 2
    B0 = rng.standard_normal((n, 2)).astype(np.complex128)
    A = HermitianMatrix.from_global(A0, 8, uplo=Uplo.Lower)
    X, L, d, info = indef.hesv(A, Matrix.from_global(B0, 8))
    err = checks.solve_residual(A0, np.asarray(X.to_global()), B0)
    assert checks.passed(err, np.complex128, factor=1000), err


class TestSimplifiedAPI:
    def test_multiply_dispatch(self, rng):
        n = 24
        A0 = rng.standard_normal((n, n))
        B0 = rng.standard_normal((n, n))
        C = Matrix.zeros(n, n, 8, dtype=np.float64)
        C2 = st.simplified.multiply(
            1.0, Matrix.from_global(A0, 8), Matrix.from_global(B0, 8), 0.0, C
        )
        np.testing.assert_allclose(np.asarray(C2.to_global()), A0 @ B0, atol=1e-12)
        # hermitian dispatch
        H0 = (A0 + A0.T) / 2
        H = HermitianMatrix.from_global(H0, 8, uplo=Uplo.Lower)
        C3 = st.simplified.multiply(1.0, H, Matrix.from_global(B0, 8), 0.0, C)
        np.testing.assert_allclose(np.asarray(C3.to_global()), H0 @ B0, atol=1e-12)

    def test_solver_verbs(self, rng):
        n = 32
        A0 = rng.standard_normal((n, n)) + n * np.eye(n)
        B0 = rng.standard_normal((n, 4))
        X = st.simplified.lu_solve(Matrix.from_global(A0, 8), Matrix.from_global(B0, 8))
        np.testing.assert_allclose(
            np.asarray(X.to_global()), np.linalg.solve(A0, B0), atol=1e-9
        )
        S0 = A0 @ A0.T + n * np.eye(n)
        Xc = st.simplified.chol_solve(
            HermitianMatrix.from_global(S0, 8, uplo=Uplo.Lower),
            Matrix.from_global(B0, 8),
        )
        np.testing.assert_allclose(
            np.asarray(Xc.to_global()), np.linalg.solve(S0, B0), atol=1e-8
        )

    def test_eig_svd_verbs(self, rng):
        n = 24
        A0 = rng.standard_normal((n, n))
        H0 = (A0 + A0.T) / 2
        w = st.simplified.eig_vals(HermitianMatrix.from_global(H0, 8, uplo=Uplo.Lower))
        np.testing.assert_allclose(np.asarray(w), np.linalg.eigvalsh(H0), atol=1e-10)
        s = st.simplified.svd_vals(Matrix.from_global(A0, 8))
        np.testing.assert_allclose(
            np.asarray(s), np.linalg.svd(A0, compute_uv=False), atol=1e-10
        )

    def test_least_squares_verb(self, rng):
        m, n = 40, 24
        A0 = rng.standard_normal((m, n))
        B0 = rng.standard_normal((m, 2))
        X = st.simplified.least_squares_solve(
            Matrix.from_global(A0, 8), Matrix.from_global(B0, 8)
        )
        ref, *_ = np.linalg.lstsq(A0, B0, rcond=None)
        np.testing.assert_allclose(np.asarray(X.to_global())[:n], ref, atol=1e-8)


def test_public_api_surface():
    """The slate.hh-equivalent surface must be importable from the root."""
    for name in (
        "gemm", "hemm", "symm", "herk", "her2k", "syrk", "syr2k", "trmm",
        "trsm", "add", "copy", "scale", "set", "norm", "colNorms",
        "potrf", "potrs", "posv", "potri", "trtri", "posv_mixed",
        "getrf", "getrs", "gesv", "getri", "gesv_mixed", "gesv_rbt",
        "geqrf", "unmqr", "gelqf", "unmlq", "cholqr", "gels",
        "heev", "hegv", "he2hb", "sterf", "steqr", "stedc",
        "svd", "ge2tb", "bdsqr", "gbmm", "gbsv", "pbsv", "tbsm",
        "hesv", "hetrf", "hetrs", "generate_matrix", "Matrix",
        "HermitianMatrix", "TriangularMatrix", "BandMatrix",
        "ProcessGrid", "TileLayout", "Pivots", "TriangularFactors",
    ):
        assert hasattr(st, name), name


def test_hesv_zero_leading_minors(rng):
    """hetrf must survive exactly-singular leading minors via the
    pivoted Aasen refactor (the reference hetrf's algorithm)."""
    import jax.numpy as jnp

    n = 32
    A0 = np.kron(np.eye(n // 2), np.array([[0.0, 1.0], [1.0, 0.0]]))
    B0 = rng.standard_normal((n, 3))
    A = HermitianMatrix.from_global(jnp.asarray(A0), 8, uplo=Uplo.Lower)
    X, L, d, info = indef.hesv(A, Matrix.from_global(jnp.asarray(B0), 8))
    assert hasattr(L, "_aasen"), "breakdown must refactor with Aasen"
    assert np.abs(A0 @ np.asarray(X.to_global()) - B0).max() < 1e-8


def test_hesv_zero_minors_complex(rng):
    import jax.numpy as jnp

    n = 16
    A0 = np.kron(np.eye(n // 2), np.array([[0, 1j], [-1j, 0]])).astype(complex)
    B0 = (rng.standard_normal((n, 2)) + 1j * rng.standard_normal((n, 2)))
    A = HermitianMatrix.from_global(jnp.asarray(A0), 8, uplo=Uplo.Lower)
    X, L, d, info = indef.hesv(A, Matrix.from_global(jnp.asarray(B0), 8))
    assert np.abs(A0 @ np.asarray(X.to_global()) - B0).max() < 1e-8


def test_hesv_near_singular_leading_minor(rng):
    """A 1e-13-pivot leading minor (not an exact zero) must trip the
    growth/d-ratio breakdown detection and refactor with Aasen —
    exact-zero-only detection would hand the catastrophic growth to IR
    (VERDICT r2 weak point #30)."""
    import jax.numpy as jnp

    n = 32
    A0 = rng.standard_normal((n, n))
    A0 = (A0 + A0.T) / 2 + np.diag(np.abs(rng.standard_normal(n)) + 1)
    A0[0, 0] = 1e-13  # near-singular 1x1 leading minor
    B0 = rng.standard_normal((n, 3))
    A = HermitianMatrix.from_global(jnp.asarray(A0), 8, uplo=Uplo.Lower)
    X, L, d, info = indef.hesv(A, Matrix.from_global(jnp.asarray(B0), 8))
    assert hasattr(L, "_aasen"), "near-singular minor must trip the refactor"
    res = np.abs(A0 @ np.asarray(X.to_global()) - B0).max()
    assert res < 1e-9 * max(np.abs(A0).max(), 1.0)


def test_hetrf_traced_lazy_info(rng):
    """Inside jit there is no host info value to branch on, so hetrf
    follows the other drivers' lazy-info contract: it returns the
    no-pivot factor and the info ARRAY (nonzero = breakdown) instead of
    raising — the old concrete-info TypeError path is gone.  The
    singular-minor matrix that trips the eager Aasen refactor must flag
    info != 0 through the trace; a healthy matrix must flag 0."""
    import jax
    import jax.numpy as jnp

    n, nb = 16, 8

    @jax.jit
    def traced_info(Ag):
        A = HermitianMatrix.from_global(Ag, nb, uplo=Uplo.Lower)
        _L, _d, info = indef.hetrf(A)
        return info

    # singular leading minors (every odd leading minor is singular)
    A0 = np.kron(np.eye(n // 2), np.array([[0.0, 1.0], [1.0, 0.0]]))
    assert int(traced_info(jnp.asarray(A0))) != 0
    # well-conditioned SPD: same trace, clean info
    S0 = 3.0 * np.eye(n)
    assert int(traced_info(jnp.asarray(S0))) == 0
    # eager calls on the same singular-minor matrix still take the
    # host-driven Aasen refactor (the breakdown path is not lost)
    L, d, info = indef.hetrf(HermitianMatrix.from_global(A0, nb, uplo=Uplo.Lower))
    assert getattr(L, "_aasen", None) is not None


def test_simplified_indefinite_solve_surfaces_breakdown(rng):
    """simplified.indefinite_solve returns only X, so it must demand
    the info flag itself: a traced breakdown NaN-poisons X (never a
    silently-wrong finite solution), an eager breakdown recovers via
    Aasen, and eager hetrs-with-zero-d stays guarded."""
    import jax
    import jax.numpy as jnp

    import slate_tpu as st

    n, nb = 16, 8
    A0 = np.kron(np.eye(n // 2), np.array([[0.0, 1.0], [1.0, 0.0]]))
    B0 = rng.standard_normal((n, 2))

    @jax.jit
    def traced(Ag, Bg):
        A = HermitianMatrix.from_global(Ag, nb, uplo=Uplo.Lower)
        return st.simplified.indefinite_solve(A, Matrix.from_global(Bg, nb)).to_global()

    Xt = np.asarray(traced(jnp.asarray(A0), jnp.asarray(B0)))
    assert not np.any(np.isfinite(Xt)), "traced breakdown must poison X"
    # the same trace on a healthy matrix returns the clean solution
    S0 = np.diag(np.arange(1.0, n + 1))
    Xs = np.asarray(traced(jnp.asarray(S0), jnp.asarray(B0)))
    assert np.abs(S0 @ Xs - B0).max() < 1e-8
    # eager: breakdown refactors via Aasen and solves exactly
    Xe = st.simplified.indefinite_solve(
        HermitianMatrix.from_global(A0, nb, uplo=Uplo.Lower),
        Matrix.from_global(B0, nb),
    )
    assert np.abs(A0 @ np.asarray(Xe.to_global()) - B0).max() < 1e-8


def test_hetrf_aasen_direct(rng):
    """Aasen's pivoted LTL^H (reference: src/hetrf.cc's algorithm) as an
    explicit method: factor + solve residuals at LAPACK grade."""
    from slate_tpu.drivers.indefinite import hetrf, hetrs

    n, nb = 64, 16
    A0 = rng.standard_normal((n, n))
    A0 = (A0 + A0.T) / 2
    A = HermitianMatrix.from_global(A0, nb, uplo=Uplo.Lower)
    L, d, info = hetrf(A, method="aasen")
    assert int(info) == 0
    assert getattr(L, "_aasen", None) is not None
    B0 = rng.standard_normal((n, 3))
    B = Matrix.from_global(B0, nb)
    X = hetrs(L, d, B)
    err = np.abs(A0 @ np.asarray(X.to_global()) - B0).max()
    assert err < 1e-11 * n, err


def test_hetrf_aasen_complex(rng):
    from slate_tpu.drivers.indefinite import hetrf, hetrs

    n, nb = 48, 16
    A0 = rng.standard_normal((n, n)) + 1j * rng.standard_normal((n, n))
    A0 = (A0 + A0.conj().T) / 2
    A = HermitianMatrix.from_global(A0, nb, uplo=Uplo.Lower)
    L, d, info = hetrf(A, method="aasen")
    B0 = (rng.standard_normal((n, 2))
          + 1j * rng.standard_normal((n, 2)))
    B = Matrix.from_global(B0, nb)
    X = hetrs(L, d, B)
    err = np.abs(A0 @ np.asarray(X.to_global()) - B0).max()
    assert err < 1e-11 * n, err


def test_hetrf_auto_breakdown_routes_to_aasen(rng):
    """The zero-diagonal chain breaks the pivot-free pass; 'auto' must
    recover through the pivoted Aasen factorization."""
    from slate_tpu.drivers.indefinite import hesv, hetrf

    n, nb = 32, 8
    A0 = np.diag(np.ones(n - 1), 1) + np.diag(np.ones(n - 1), -1)
    A = HermitianMatrix.from_global(A0, nb, uplo=Uplo.Lower)
    L, d, info = hetrf(A)
    assert getattr(L, "_aasen", None) is not None, (
        "breakdown must refactor with Aasen"
    )
    B0 = rng.standard_normal((n, 2))
    B = Matrix.from_global(B0, nb)
    X, L2, d2, info2 = hesv(A, B)
    err = np.abs(A0 @ np.asarray(X.to_global()) - B0).max()
    assert err < 1e-10 * n, err
