"""Fast blocked LU/QR kernels (ops/lu_fast.py, ops/qr_fast.py) — the
default large-n accelerator paths.  The backend gate in
lu_kernels.lu_global / householder.geqrf means CPU runs would never
reach them indirectly, so these tests call the kernels directly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.linalg as sla

from slate_tpu.ops.lu_fast import blocked_getrf_fast
from slate_tpu.ops.qr_fast import geqrf_fast
from slate_tpu.ops.householder import (
    apply_block_reflector,
    larft,
    materialize_v,
)


@pytest.mark.parametrize(
    "n,nb,ib",
    [
        (256, 128, 16),
        pytest.param(384, 128, 32, marks=pytest.mark.slow),
        # n > coarse_panels*nb exercises the multi-panel fori_loop path
        # (W > nb) that the bench sizes hit (ADVICE r3)
        pytest.param(1280, 128, 32, marks=pytest.mark.slow),
    ],
)
def test_lu_fast_vs_scipy(n, nb, ib):
    key = jax.random.PRNGKey(n)
    G = jax.random.normal(key, (n, n), jnp.float64)
    LU, perm = jax.jit(lambda g: blocked_getrf_fast(g, nb, ib=ib))(G)
    LU = np.asarray(LU)
    perm = np.asarray(perm)
    L = np.tril(LU, -1) + np.eye(n)
    U = np.triu(LU)
    Gn = np.asarray(G)
    res = np.abs(L @ U - Gn[perm]).max() / np.abs(Gn).max()
    assert res < 1e-12
    # pivot parity with LAPACK (random input: no magnitude ties)
    lu_ref, piv_ref = sla.lu_factor(Gn)
    pref = np.arange(n)
    for i, p in enumerate(piv_ref):
        pref[[i, p]] = pref[[p, i]]
    assert (perm == pref).all()
    assert np.abs(LU - lu_ref).max() < 1e-9 * np.abs(lu_ref).max()


def test_lu_fast_singularish():
    # an exactly-singular column must produce a zero L column, not NaN
    n = 256
    key = jax.random.PRNGKey(0)
    G = jax.random.normal(key, (n, n), jnp.float64)
    G = G.at[:, 10].set(0.0)
    LU, perm = jax.jit(lambda g: blocked_getrf_fast(g, 128, ib=16))(G)
    assert bool(jnp.all(jnp.isfinite(LU)))


@pytest.mark.parametrize(
    "m,n,nb,ib",
    [
        (256, 256, 128, 16),
        pytest.param(384, 256, 128, 32, marks=pytest.mark.slow),
        # multi-panel W > nb path (see test_lu_fast_vs_scipy)
        pytest.param(1280, 1280, 128, 32, marks=pytest.mark.slow),
    ],
)
def test_qr_fast(m, n, nb, ib):
    key = jax.random.PRNGKey(m + n)
    G = jax.random.normal(key, (m, n), jnp.float64)
    fac, taus = jax.jit(lambda g: geqrf_fast(g, nb, ib=ib))(G)
    # reconstruct Q^H G via block reflectors and compare to R
    C = jnp.eye(m, dtype=jnp.float64)
    for k in range(0, n, nb):
        V = materialize_v(fac[:, k : k + nb], offset=k)
        T = larft(V, taus[k : k + nb])
        C = apply_block_reflector(V, T, C, trans=True)
    QhG = np.asarray(C) @ np.asarray(G)
    R = np.triu(np.asarray(fac))
    assert np.abs(QhG - R[:m]).max() / np.abs(np.asarray(G)).max() < 1e-12
    # R diag matches the vendor QR's |diag|
    rref = np.linalg.qr(np.asarray(G), mode="r")
    assert np.allclose(np.abs(np.diagonal(R)[:n]), np.abs(np.diagonal(rref)), atol=1e-9)
