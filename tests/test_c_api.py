"""C ABI tests (reference: the lapack_api/c_api test coverage): compile
c_api/slate_tpu_c.c at test time, load it into this process (the
embedded-interpreter path detects the live interpreter), and drive the
LAPACK-style entry points through ctypes with residual checks."""

import ctypes
import os
import shutil
import subprocess
import sys
import sysconfig

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def clib(tmp_path_factory):
    cc = shutil.which("cc") or shutil.which("gcc")
    if cc is None:
        pytest.skip("no C compiler")
    build = tmp_path_factory.mktemp("c_api")
    so = build / "libslate_tpu.so"
    inc = sysconfig.get_paths()["include"]
    cmd = [
        cc, "-O1", "-fPIC", "-shared", f"-I{inc}",
        os.path.join(ROOT, "c_api", "slate_tpu_c.c"), "-o", str(so),
    ]
    r = subprocess.run(cmd, capture_output=True, text=True)
    if r.returncode != 0:
        pytest.skip(f"c_api compile failed: {r.stderr[-800:]}")
    lib = ctypes.CDLL(str(so), mode=ctypes.RTLD_GLOBAL)
    lib.slate_tpu_init.restype = ctypes.c_int
    assert lib.slate_tpu_init() == 0
    return lib


def _dp(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


def _ip(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


I64 = ctypes.c_int64


def test_c_dgesv(clib, rng):
    n, nrhs = 48, 3
    A0 = rng.standard_normal((n, n)) + n * np.eye(n)
    B0 = rng.standard_normal((n, nrhs))
    a = np.asfortranarray(A0)
    b = np.asfortranarray(B0)
    ipiv = np.zeros(n, np.int64)
    info = clib.slate_tpu_dgesv(
        I64(n), I64(nrhs), _dp(a), I64(n), _ip(ipiv), _dp(b), I64(n)
    )
    assert info == 0
    assert np.abs(A0 @ b - B0).max() < 1e-10
    # ipiv is a valid 1-based swap list reproducing the permutation
    assert ipiv.min() >= 1 and ipiv.max() <= n
    # a holds L\U consistent with the swaps
    rows = list(range(n))
    for i, j1 in enumerate(ipiv):
        j = int(j1) - 1
        rows[i], rows[j] = rows[j], rows[i]
    L = np.tril(a, -1) + np.eye(n)
    U = np.triu(a)
    assert np.abs(L @ U - A0[rows]).max() < 1e-10 * np.abs(A0).max() * n


def test_c_dposv(clib, rng):
    n, nrhs = 40, 2
    A0 = rng.standard_normal((n, n))
    A0 = A0 @ A0.T + n * np.eye(n)
    B0 = rng.standard_normal((n, nrhs))
    a = np.asfortranarray(A0)
    b = np.asfortranarray(B0)
    info = clib.slate_tpu_dposv(
        ctypes.c_char(b"l"), I64(n), I64(nrhs), _dp(a), I64(n), _dp(b), I64(n)
    )
    assert info == 0
    assert np.abs(A0 @ b - B0).max() < 1e-10


def test_c_dpotrf_info(clib, rng):
    n = 24
    A0 = -np.eye(n)  # not SPD
    a = np.asfortranarray(A0)
    info = clib.slate_tpu_dpotrf(ctypes.c_char(b"l"), I64(n), _dp(a), I64(n))
    assert info != 0


def test_c_dsyev(clib, rng):
    n = 32
    A0 = rng.standard_normal((n, n))
    A0 = (A0 + A0.T) / 2
    a = np.asfortranarray(A0)
    w = np.zeros(n)
    info = clib.slate_tpu_dsyev(
        ctypes.c_char(b"v"), ctypes.c_char(b"l"), I64(n), _dp(a), I64(n), _dp(w)
    )
    assert info == 0
    np.testing.assert_allclose(w, np.linalg.eigvalsh(A0), atol=1e-9)
    assert np.abs(A0 @ a - a * w[None, :]).max() < 1e-9 * n


def test_c_dgemm(clib, rng):
    m, n, k = 24, 20, 28
    A0 = rng.standard_normal((m, k))
    B0 = rng.standard_normal((k, n))
    C0 = rng.standard_normal((m, n))
    a, b, c = map(np.asfortranarray, (A0, B0, C0))
    info = clib.slate_tpu_dgemm(
        ctypes.c_char(b"n"), ctypes.c_char(b"n"),
        I64(m), I64(n), I64(k), ctypes.c_double(2.0),
        _dp(a), I64(m), _dp(b), I64(k), ctypes.c_double(0.5), _dp(c), I64(m),
    )
    assert info == 0
    np.testing.assert_allclose(c, 2.0 * A0 @ B0 + 0.5 * C0, atol=1e-11)


def test_fortran_module_compiles(tmp_path):
    """Compile-check the ISO_C_BINDING Fortran module (c_api/slate_tpu.f90)
    when a Fortran compiler is present (reference: the generated
    slate.f90 module, tools/fortran/).  Verifies every interface block
    parses and binds; linking/running is covered by the C-ABI tests
    over the same symbols."""
    fc = shutil.which("gfortran") or shutil.which("flang") or shutil.which(
        "f95"
    )
    if fc is None:
        pytest.skip("no Fortran compiler")
    src = os.path.join(ROOT, "c_api", "slate_tpu.f90")
    r = subprocess.run(
        [fc, "-c", "-fsyntax-only" if "gfortran" in fc else "-c", src],
        capture_output=True, text=True, cwd=tmp_path,
    )
    assert r.returncode == 0, r.stderr[-800:]
