"""Distributed hegst (parallel/spmd_hegst.py) — reference:
src/hegst.cc + internal_hegst.cc distribute the two-sided reduction;
these tests assert the SPMD composition matches the gathered route and
that hegv runs gather-free end-to-end under Option.RequireSpmd."""

import numpy as np
import pytest

import jax.numpy as jnp

from slate_tpu.drivers import chol, eig
from slate_tpu.enums import Option, Uplo
from slate_tpu.internal import fallbacks
from slate_tpu.matrix.base import BaseMatrix
from slate_tpu.matrix.matrix import HermitianMatrix
from slate_tpu.parallel.layout import TileLayout, tiles_from_global
from slate_tpu.parallel.spmd_hegst import spmd_hermitian_full


def _herm(rng, n, dtype=np.float64):
    A = rng.standard_normal((n, n)).astype(dtype)
    if np.issubdtype(dtype, np.complexfloating):
        A = A + 1j * rng.standard_normal((n, n))
    return (A + A.conj().T) / 2


def _spd(rng, n, dtype=np.float64):
    B = rng.standard_normal((n, n)).astype(dtype)
    if np.issubdtype(dtype, np.complexfloating):
        B = B + 1j * rng.standard_normal((n, n))
    return B @ B.conj().T + n * np.eye(n)


@pytest.mark.parametrize("n,nb", [(64, 16), (50, 16)])
@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_spmd_hermitian_full(rng, grid22, n, nb, dtype):
    A0 = _herm(rng, n, dtype)
    lay = TileLayout(n, n, nb, nb, grid22.p, grid22.q)
    stored = np.tril(A0)  # lower storage; upper junk must be ignored
    T = tiles_from_global(jnp.asarray(stored), lay)
    full = spmd_hermitian_full(grid22, T, lay, lower=True)
    from slate_tpu.parallel.layout import tiles_to_global

    G = np.asarray(tiles_to_global(full, lay))
    np.testing.assert_allclose(G, A0, atol=1e-13)


@pytest.mark.parametrize("n,nb", [(64, 16), (50, 16)])
@pytest.mark.parametrize(
    "dtype",
    [np.float64, pytest.param(np.complex128, marks=pytest.mark.slow)],
)
def test_hegst_spmd_matches_gathered(rng, grid22, n, nb, dtype):
    A0 = _herm(rng, n, dtype)
    B0 = _spd(rng, n, dtype)
    Ad = HermitianMatrix.from_global(A0, nb, grid=grid22, uplo=Uplo.Lower)
    Bd = HermitianMatrix.from_global(B0, nb, grid=grid22, uplo=Uplo.Lower)
    L, info = chol.potrf(Bd)
    C_d = eig.hegst(1, Ad, L)
    # reference: gathered evaluation with numpy
    Lg = np.asarray(L.to_global())
    Lg = np.tril(Lg)
    C_ref = np.linalg.solve(Lg, A0) @ np.linalg.inv(Lg.conj().T)
    Cg = np.asarray(C_d.full_global())
    err = np.abs(Cg - C_ref).max() / (np.abs(C_ref).max() * n)
    assert err < 1e-13, err


@pytest.mark.slow
def test_hegv_spmd_gather_free(rng, grid22, monkeypatch):
    """hegv end-to-end on the mesh under RequireSpmd: no gathered
    fallback records, no global materialization."""
    n, nb = 80, 16  # n > 4 nb so heev takes the two-stage path
    A0 = _herm(rng, n)
    B0 = _spd(rng, n)
    Ad = HermitianMatrix.from_global(A0, nb, grid=grid22, uplo=Uplo.Lower)
    Bd = HermitianMatrix.from_global(B0, nb, grid=grid22, uplo=Uplo.Lower)

    def boom(self, *a, **kw):  # pragma: no cover
        raise AssertionError("full-matrix gather in hegv spmd path")

    fallbacks.reset()
    monkeypatch.setattr(BaseMatrix, "to_global", boom)
    monkeypatch.setattr(HermitianMatrix, "full_global", boom, raising=True)
    opts = {Option.RequireSpmd: True}
    w, X, info = eig.hegv(1, Ad, Bd, opts=opts, vectors=True)
    monkeypatch.undo()
    assert fallbacks.counters() == {}
    w = np.asarray(w)
    Xg = np.asarray(X.to_global())
    # residual of the generalized problem: A x = lambda B x
    R = A0 @ Xg - B0 @ Xg * w[None, :]
    err = np.abs(R).max() / (np.abs(A0).max() * n)
    assert err < 1e-11, err
    wref = np.linalg.eigvalsh(np.linalg.solve(
        np.linalg.cholesky(B0), A0 @ np.linalg.inv(
            np.linalg.cholesky(B0).conj().T)
    ))
    np.testing.assert_allclose(np.sort(w), wref, atol=1e-10 * n)
