"""Fleet-tier tests: wire framing, spec parsing, router edge cases
(exactly-once under host death + hedging, drain racing re-dispatch,
no-resurrection reports, forced rejoin probes), the worker front-end
over a fake service, the stitch/merge/report tools, and a real
spawned-subprocess end-to-end.

Router tests run against a fake ``_rpc`` (no sockets, no processes):
the edge cases under test are lock-ordering and exactly-once
bookkeeping in the ROUTER, which the fake makes deterministic.  The
tools are exercised as subprocesses on hand-built files — they are
stdlib-only by contract.
"""

import json
import os
import socket
import subprocess
import sys
import threading
import time
import types
from concurrent.futures import Future

import numpy as np
import pytest

from slate_tpu.aux import metrics
from slate_tpu.exceptions import NumericalError
from slate_tpu.fleet import (
    FleetError,
    FleetRouter,
    FleetWorker,
    HostDead,
    parse_fleet,
    wire,
)
from slate_tpu.fleet.router import (
    HOST_DEAD,
    HOST_LIVE,
    HOST_REJOINED,
    _rebuild_exc,
)
from slate_tpu.integrity.policy import residual_certificate
from slate_tpu.serve.service import Rejected

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TOOLS = os.path.join(_REPO, "tools")


@pytest.fixture(autouse=True)
def metrics_on():
    metrics.off()
    metrics.reset()
    metrics.on()
    yield
    metrics.off()
    metrics.reset()


def _counter(name: str) -> float:
    return float(metrics.counters().get(name, 0.0))


# ---------------------------------------------------------------------------
# wire framing
# ---------------------------------------------------------------------------


class TestWire:
    def test_roundtrip_header_and_arrays(self):
        a, b = socket.socketpair()
        try:
            A = np.arange(12, dtype=np.float32).reshape(3, 4)
            B = np.ones((3, 1), dtype=np.float64)
            wire.send_msg(a, {"op": "solve", "n": 3}, {"A": A, "B": B})
            header, arrays = wire.recv_msg(b)
            assert header == {"op": "solve", "n": 3}
            np.testing.assert_array_equal(arrays["A"], A)
            np.testing.assert_array_equal(arrays["B"], B)
            assert arrays["A"].dtype == np.float32
        finally:
            a.close()
            b.close()

    def test_noncontiguous_array_roundtrips(self):
        a, b = socket.socketpair()
        try:
            A = np.arange(16, dtype=np.float32).reshape(4, 4).T
            wire.send_msg(a, {}, {"A": A})
            _, arrays = wire.recv_msg(b)
            np.testing.assert_array_equal(arrays["A"], A)
        finally:
            a.close()
            b.close()

    def test_eof_mid_frame_raises_connection_error(self):
        a, b = socket.socketpair()
        a.sendall(b"\x00\x00\x10\x00partial")
        a.close()
        try:
            with pytest.raises(ConnectionError):
                wire.recv_msg(b)
        finally:
            b.close()

    def test_oversized_header_refused(self):
        a, b = socket.socketpair()
        import struct

        a.sendall(struct.pack(">I", wire.MAX_HEADER_BYTES + 1))
        try:
            with pytest.raises(wire.ProtocolError):
                wire.recv_msg(b)
        finally:
            a.close()
            b.close()


# ---------------------------------------------------------------------------
# spec parsing
# ---------------------------------------------------------------------------


class TestParseFleet:
    def test_spawn_and_knobs(self):
        kw = parse_fleet("spawn=3,cert=0.5,hedge=1.5,retries=4,"
                         "redispatch=1,dead_after=2,respawn")
        assert kw == {
            "spawn": 3, "cert": "sample=0.5", "hedge_s": 1.5,
            "rpc_retries": 4, "redispatch_max": 1, "dead_after": 2,
            "respawn": True,
        }

    def test_connect_addrs(self):
        kw = parse_fleet("connect=10.0.0.1:9001+:9002")
        assert kw["connect"] == (("10.0.0.1", 9001), ("127.0.0.1", 9002))

    def test_cert_spellings(self):
        assert parse_fleet("spawn=1,cert=full")["cert"] == "full"
        assert parse_fleet("spawn=1,cert=off")["cert"] == "off"
        assert parse_fleet("spawn=1,cert=sample=0.3")["cert"] == "sample=0.3"

    def test_needs_hosts(self):
        with pytest.raises(ValueError, match="spawn=<n> or connect"):
            parse_fleet("cert=full")

    def test_unknown_key_names_itself(self):
        with pytest.raises(ValueError, match="bogus"):
            parse_fleet("spawn=1,bogus=3")


# ---------------------------------------------------------------------------
# residual certificate
# ---------------------------------------------------------------------------


class TestResidualCertificate:
    def _spd(self, n=8, dtype=np.float32):
        rng = np.random.default_rng(5)
        A = rng.standard_normal((n, n))
        A = (A @ A.T + n * np.eye(n)).astype(dtype)
        return A

    def test_correct_solve_passes(self):
        A = self._spd()
        X = np.linalg.solve(A, np.ones((8, 2), dtype=np.float32))
        assert residual_certificate("gesv", A, X, np.ones((8, 2)))

    def test_corrupted_solve_fails(self):
        A = self._spd()
        B = np.ones((8, 2), dtype=np.float32)
        X = np.linalg.solve(A, B)
        X[0, 0] += 1.0
        assert not residual_certificate("gesv", A, X, B)

    def test_dtype_rebases_to_delivered_precision(self):
        # float64 operands, float32 solve: the fence must use float32's
        # eps or every correct mixed-precision delivery fails
        A = self._spd(dtype=np.float64)
        B = np.ones((8, 2), dtype=np.float64)
        X = np.linalg.solve(
            A.astype(np.float32), B.astype(np.float32)
        )
        assert residual_certificate("gesv", A, X, B)

    def test_posv_ignores_upper_junk(self):
        A = self._spd()
        B = np.ones((8, 1), dtype=np.float32)
        X = np.linalg.solve(A, B)
        junk = np.array(A)
        junk[np.triu_indices(8, 1)] = 777.0  # posv contract: lower only
        assert residual_certificate("posv", junk, X, B)
        assert not residual_certificate("gesv", junk, X, B)

    def test_gels_vacuous(self):
        assert residual_certificate("gels", np.eye(3), np.zeros(3),
                                    np.ones(3))


# ---------------------------------------------------------------------------
# router edge cases (fake RPC)
# ---------------------------------------------------------------------------


def _fake_router(n=2, **kw):
    """Connect-mode router that never opens a socket: tests install a
    fake ``_rpc`` before any dispatch."""
    kw.setdefault("heartbeat_s", 60.0)  # quiet during the test
    kw.setdefault("cert", "off")
    kw.setdefault("rpc_retries", 0)
    addrs = tuple(("127.0.0.1", 59000 + i) for i in range(n))
    return FleetRouter(connect=addrs, **kw)


def _install_rpc(r, fn):
    r._rpc = types.MethodType(fn, r)


def _ok_reply(X):
    return {"ok": True, "op": "solve"}, {"X": X}


class TestRouterEdgeCases:
    A = np.eye(4, dtype=np.float32)
    B = np.ones((4, 1), dtype=np.float32)
    X = np.ones((4, 1), dtype=np.float32)

    def test_least_loaded_pick_and_exclusion(self):
        r = _fake_router(n=3)
        _install_rpc(r, lambda self, *a, **k: _ok_reply(None))
        r.start()
        try:
            with r._lock:
                r._hosts["0"].inflight = 5
                r._hosts["1"].queue_depth = 1
                r._hosts["2"].queue_depth = 3
                assert r._pick_host_locked().name == "1"
                assert r._pick_host_locked(exclude={"1"}).name == "2"
                r._hosts["2"].state = HOST_DEAD
                assert r._pick_host_locked(exclude={"1"}).name == "0"
                assert r._pick_host_locked(exclude={"0", "1"}) is None
        finally:
            r.stop(drain=False)

    def test_host_death_with_hedge_twin_resolves_exactly_once(self):
        r = _fake_router(n=2, redispatch_max=2)
        gate = threading.Event()
        results = []

        def rpc(self, host, header, arrays=None, **kw):
            if header.get("op") != "solve":
                return {"ok": True}, {}
            if host.name == "0":
                gate.wait(timeout=30)
                raise ConnectionError("host 0 died mid-RPC")
            return _ok_reply(TestRouterEdgeCases.X)

        _install_rpc(r, rpc)
        r.start()
        try:
            fut = r.submit("gesv", self.A, self.B, deadline=30.0)
            with r._lock:
                assert len(r._pending) == 1
                p = next(iter(r._pending.values()))
            # hedge twin onto host 1 while the primary hangs on host 0
            with r._lock:
                p.hedged = True
            r._spawn_run(p, r._hosts["1"], hedge=True)
            results.append(fut.result(timeout=30))
            # the fleet declares host 0 dead while the twin already won
            r._note_host_failure(r._hosts["0"], hard=True)
            gate.set()  # the stuck RPC now fails too — must be a no-op
            time.sleep(0.2)
            assert fut.done() and fut.result() is not None
            np.testing.assert_array_equal(results[0], self.X)
            assert _counter("fleet.delivered") == 1
            assert _counter("fleet.typed_errors") == 0
            assert _counter("fleet.hedge.won") == 1
        finally:
            gate.set()
            r.stop(drain=False)

    def test_host_death_before_hedge_resolution_survivor_delivers(self):
        r = _fake_router(n=2, redispatch_max=2)
        gate0, gate1 = threading.Event(), threading.Event()

        def rpc(self, host, header, arrays=None, **kw):
            if header.get("op") != "solve":
                return {"ok": True}, {}
            if host.name == "0":
                gate0.wait(timeout=30)
                raise ConnectionError("host 0 died")
            gate1.wait(timeout=30)
            return _ok_reply(TestRouterEdgeCases.X)

        _install_rpc(r, rpc)
        r.start()
        try:
            fut = r.submit("gesv", self.A, self.B, deadline=30.0)
            with r._lock:
                p = next(iter(r._pending.values()))
                p.hedged = True
            r._spawn_run(p, r._hosts["1"], hedge=True)
            # both inflight; host 0 dies hard -> fail-fast dooms its
            # member, but the hedge twin is alive: no typed error, the
            # request waits for the survivor
            r._note_host_failure(r._hosts["0"], hard=True)
            gate0.set()
            assert not fut.done()
            gate1.set()
            np.testing.assert_array_equal(fut.result(timeout=30), self.X)
            assert _counter("fleet.delivered") == 1
            assert _counter("fleet.typed_errors") == 0
        finally:
            gate0.set()
            gate1.set()
            r.stop(drain=False)

    def test_redispatch_after_host_death(self):
        r = _fake_router(n=2, redispatch_max=2)
        gate = threading.Event()

        def rpc(self, host, header, arrays=None, **kw):
            if header.get("op") != "solve":
                return {"ok": True}, {}
            if host.name == "0":
                gate.wait(timeout=30)
                raise ConnectionError("host 0 died")
            return _ok_reply(TestRouterEdgeCases.X)

        _install_rpc(r, rpc)
        r.start()
        try:
            fut = r.submit("gesv", self.A, self.B, deadline=30.0)
            time.sleep(0.1)
            # death fail-fast re-dispatches the inflight member to the
            # surviving host WITHOUT waiting for the stuck RPC
            r._note_host_failure(r._hosts["0"], hard=True)
            np.testing.assert_array_equal(fut.result(timeout=30), self.X)
            gate.set()
            assert _counter("fleet.redispatched") == 1
            assert _counter("fleet.host_dead") == 1
        finally:
            gate.set()
            r.stop(drain=False)

    def test_report_after_death_does_not_resurrect(self):
        r = _fake_router(n=2)
        _install_rpc(r, lambda self, *a, **k: ({"ok": True}, {}))
        r.start()
        try:
            h = r._hosts["0"]
            r._note_host_failure(h, hard=True)
            with r._lock:
                assert h.state == HOST_DEAD
            r._note_report(h, {"queue_depth": 0, "burn": 0.1})
            with r._lock:
                assert h.state == HOST_DEAD  # stats only, never state
            # an ANSWERED rpc is the only way back, and it rejoins with
            # a pending certification probe rather than plain live
            r._note_host_ok(h)
            with r._lock:
                assert h.state == HOST_REJOINED
                assert h.probe_pending
        finally:
            r.stop(drain=False)

    def test_drain_racing_redispatch_resolves_typed(self):
        r = _fake_router(n=2, redispatch_max=2)
        entered = threading.Event()
        gate = threading.Event()

        def rpc(self, host, header, arrays=None, **kw):
            if header.get("op") != "solve":
                return {"ok": True}, {}
            entered.set()
            gate.wait(timeout=30)
            raise ConnectionError("failed during drain")

        _install_rpc(r, rpc)
        r.start()
        fut = r.submit("gesv", self.A, self.B, deadline=30.0)
        assert entered.wait(timeout=10)
        stopper = threading.Thread(
            target=r.stop, kwargs={"drain": True, "timeout": 20.0}
        )
        stopper.start()
        time.sleep(0.1)  # stop() is draining; now the member fails
        gate.set()
        with pytest.raises(FleetError):
            fut.result(timeout=30)
        stopper.join(timeout=30)
        assert not stopper.is_alive()
        assert _counter("fleet.redispatched") == 0
        assert _counter("fleet.typed_errors") == 1

    def test_submit_with_no_live_host_fails_typed(self):
        r = _fake_router(n=1)
        _install_rpc(r, lambda self, *a, **k: ({"ok": True}, {}))
        r.start()
        try:
            r._note_host_failure(r._hosts["0"], hard=True)
            fut = r.submit("gesv", self.A, self.B)
            with pytest.raises(HostDead, match="no live fleet host"):
                fut.result(timeout=10)
        finally:
            r.stop(drain=False)

    def test_submit_while_draining_refused(self):
        r = _fake_router(n=1)
        _install_rpc(r, lambda self, *a, **k: ({"ok": True}, {}))
        r.start()
        with r._lock:
            r._draining = True
        with pytest.raises(Rejected, match="draining"):
            r.submit("gesv", self.A, self.B)
        assert _counter("fleet.refused") == 1
        with r._lock:
            r._draining = False
        r.stop(drain=False)

    def test_rejoined_probe_certified_despite_sampling(self):
        # sample=1e-9 would certify ~never; a rejoined host's delivery
        # must be checked anyway, and a wrong probe must not deliver
        r = _fake_router(n=2, cert="sample=0.000000001")
        bad = np.full((4, 1), 7.0, dtype=np.float32)

        def rpc(self, host, header, arrays=None, **kw):
            if header.get("op") != "solve":
                return {"ok": True}, {}
            if host.name == "0":
                return _ok_reply(bad)  # finite but wrong
            return _ok_reply(
                np.linalg.solve(arrays["A"], arrays["B"]).astype(
                    np.float32
                )
            )

        _install_rpc(r, rpc)
        r.start()
        try:
            with r._lock:
                r._hosts["0"].probe_pending = True
                r._hosts["0"].state = HOST_REJOINED
                r._hosts["1"].inflight = 10  # steer the pick to host 0
            fut = r.submit("gesv", self.A, self.B, deadline=30.0)
            X = fut.result(timeout=30)
            np.testing.assert_allclose(X, self.B, atol=1e-5)
            assert _counter("fleet.cert.checked") >= 1
            assert _counter("fleet.cert.fail") >= 1
            assert _counter("fleet.redispatched") == 1
            with r._lock:
                # failed probe: still not recovered
                assert r._hosts["0"].probe_pending
        finally:
            r.stop(drain=False)

    def test_unsampled_delivery_skips_certificate(self):
        r = _fake_router(n=1, cert="sample=0.000000001")
        _install_rpc(
            r,
            lambda self, host, header, arrays=None, **kw:
            _ok_reply(TestRouterEdgeCases.X)
            if header.get("op") == "solve" else ({"ok": True}, {}),
        )
        r.start()
        try:
            r.submit("gesv", self.A, self.B).result(timeout=30)
            assert _counter("fleet.cert.checked") == 0
        finally:
            r.stop(drain=False)

    def test_typed_worker_error_resolves_without_retry(self):
        r = _fake_router(n=2)

        def rpc(self, host, header, arrays=None, **kw):
            if header.get("op") != "solve":
                return {"ok": True}, {}
            return {
                "ok": False, "error": "NumericalError",
                "message": "singular", "context": {"routine": "gesv"},
            }, {}

        _install_rpc(r, rpc)
        r.start()
        try:
            with pytest.raises(NumericalError, match="singular"):
                r.submit("gesv", self.A, self.B).result(timeout=30)
            # deterministic failure: the second host was never tried
            assert _counter("fleet.redispatched") == 0
        finally:
            r.stop(drain=False)

    def test_host_local_rejected_redispatches(self):
        r = _fake_router(n=2)

        def rpc(self, host, header, arrays=None, **kw):
            if header.get("op") != "solve":
                return {"ok": True}, {}
            if host.name == "0":
                return {"ok": False, "error": "Rejected",
                        "message": "queue full", "context": {}}, {}
            return _ok_reply(TestRouterEdgeCases.X)

        _install_rpc(r, rpc)
        r.start()
        try:
            with r._lock:
                r._hosts["1"].inflight = 10
            X = r.submit("gesv", self.A, self.B).result(timeout=30)
            np.testing.assert_array_equal(X, self.X)
            assert _counter("fleet.redispatched") == 1
        finally:
            r.stop(drain=False)

    def test_global_quota_refuses_fleet_wide(self):
        r = _fake_router(
            n=2, tenants="abuser:rate=1,burst=2;victim:rate=50,burst=20",
        )
        _install_rpc(
            r,
            lambda self, host, header, arrays=None, **kw:
            _ok_reply(TestRouterEdgeCases.X)
            if header.get("op") == "solve" else ({"ok": True}, {}),
        )
        r.start()
        try:
            rejected = 0
            for _ in range(10):
                try:
                    r.submit("gesv", self.A, self.B,
                             tenant="abuser").result(timeout=30)
                except Rejected:
                    rejected += 1
            assert rejected > 0
            assert _counter("fleet.rejected_quota") == rejected
            # the victim is untouched by the abuser's quota
            r.submit("gesv", self.A, self.B,
                     tenant="victim").result(timeout=30)
        finally:
            r.stop(drain=False)

    def test_rebuild_exc_maps_taxonomy(self):
        e = _rebuild_exc({
            "error": "Rejected", "message": "queue full",
            "context": {"routine": "gesv", "tenant": "a"},
        })
        assert isinstance(e, Rejected)
        assert e.context()["routine"] == "gesv"
        assert e.context()["tenant"] == "a"
        e = _rebuild_exc({"error": "NoSuchClass", "message": "x"})
        assert isinstance(e, FleetError)

    def test_health_shape(self):
        r = _fake_router(n=2, tenants="a:rate=10,burst=5")
        _install_rpc(r, lambda self, *a, **k: ({"ok": True}, {}))
        r.start()
        try:
            h = r.health()
            assert set(h) == {
                "hosts", "pending", "draining", "admission", "tenants",
            }
            assert h["hosts"]["0"]["state"] == HOST_LIVE
            assert "score" in h["hosts"]["0"]
            assert h["admission"] is not None
        finally:
            r.stop(drain=False)


# ---------------------------------------------------------------------------
# worker front-end (fake service, real sockets)
# ---------------------------------------------------------------------------


class _FakeService:
    def __init__(self, fail=None):
        self.fail = fail
        self.seen = []

    def submit(self, routine, A, B, **kw):
        self.seen.append((routine, dict(kw)))
        fut = Future()
        if self.fail is not None:
            fut.set_exception(self.fail)
        else:
            fut.set_result(np.linalg.solve(A, B))
        return fut

    def health(self):
        return {"phase": "ready", "queue_depth": 2, "inflight": 1,
                "admission": {"burn_ewma": 0.25}}

    def stop(self, **kw):
        self.stopped = True


@pytest.fixture()
def live_worker():
    svc = _FakeService()
    w = FleetWorker(host="127.0.0.1", service=svc)
    w.bind()
    t = threading.Thread(target=w.serve_forever,
                         kwargs={"announce": False}, daemon=True)
    t.start()
    yield w, svc
    w.shutdown()
    t.join(timeout=5)


def _call(w, header, arrays=None):
    with socket.create_connection(("127.0.0.1", w.port), timeout=10) as s:
        wire.send_msg(s, header, arrays)
        return wire.recv_msg(s)


class TestWorker:
    def test_solve_roundtrip_adopts_trace(self, live_worker):
        w, svc = live_worker
        A = np.eye(3, dtype=np.float64)
        B = np.full((3, 1), 2.0)
        reply, arrays = _call(
            w,
            {"op": "solve", "routine": "gesv", "deadline": 5.0,
             "tenant": "a", "trace": "t1-2"},
            {"A": A, "B": B},
        )
        assert reply["ok"]
        np.testing.assert_array_equal(arrays["X"], B)
        routine, kw = svc.seen[0]
        assert routine == "gesv"
        assert kw["trace_id"] == "t1-2"
        assert kw["tenant"] == "a"
        assert kw["deadline"] == 5.0

    def test_typed_error_crosses_by_name(self):
        svc = _FakeService(
            fail=Rejected("full").with_context(routine="gesv")
        )
        w = FleetWorker(host="127.0.0.1", service=svc)
        w.bind()
        t = threading.Thread(target=w.serve_forever,
                             kwargs={"announce": False}, daemon=True)
        t.start()
        try:
            reply, _ = _call(
                w, {"op": "solve", "routine": "gesv"},
                {"A": np.eye(2), "B": np.ones((2, 1))},
            )
            assert reply == {
                "ok": False, "error": "Rejected", "message": "full",
                "context": {"routine": "gesv"},
            }
        finally:
            w.shutdown()
            t.join(timeout=5)

    def test_report_op(self, live_worker):
        w, _ = live_worker
        reply, _ = _call(w, {"op": "report"})
        assert reply["ok"] and reply["phase"] == "ready"
        assert reply["queue_depth"] == 2 and reply["burn"] == 0.25
        assert reply["pid"] == os.getpid()

    def test_unknown_op_is_typed(self, live_worker):
        w, _ = live_worker
        reply, _ = _call(w, {"op": "frobnicate"})
        assert not reply["ok"] and reply["error"] == "ProtocolError"


# ---------------------------------------------------------------------------
# tools: trace_stitch / metrics_merge --tag / fleet_report
# ---------------------------------------------------------------------------


def _run_tool(name, *args):
    return subprocess.run(
        [sys.executable, os.path.join(_TOOLS, name), *args],
        capture_output=True, text=True,
    )


def _chrome(pid, events, pname=None):
    rows = []
    if pname:
        rows.append({"name": "process_name", "ph": "M", "pid": pid,
                     "args": {"name": pname}})
    for name, tid, ts, args in events:
        rows.append({"name": name, "ph": "X", "pid": pid, "tid": tid,
                     "ts": ts, "dur": 10.0, "cat": "span", "args": args})
    return {"traceEvents": rows}


class TestTraceStitch:
    def test_joined_chain_no_orphans(self, tmp_path):
        router = _chrome(100, [
            ("request", 0, 0.0, {"span": 1, "trace": "t64-1"}),
            ("dispatch", 1, 2.0,
             {"span": 2, "parent": 1, "trace": "t64-1"}),
        ], pname="router")
        host = _chrome(200, [
            ("request", 0, 0.0, {"span": 1, "trace": "t64-1"}),
            ("execute", 1, 1.0,
             {"span": 2, "parent": 1, "trace": "t64-1"}),
        ], pname="host0")
        rp, hp = tmp_path / "r.json", tmp_path / "h.json"
        rp.write_text(json.dumps(router))
        hp.write_text(json.dumps(host))
        out = tmp_path / "stitched.json"
        res = _run_tool("trace_stitch.py", str(rp), str(hp),
                        "-o", str(out))
        assert res.returncode == 0, res.stdout + res.stderr
        assert "cross=1 orphans=0" in res.stdout
        doc = json.loads(out.read_text())
        spans_args = [
            e["args"] for e in doc["traceEvents"]
            if e.get("ph") == "X"
        ]
        # per-process span namespacing: two hosts' sid 1 never alias
        sids = {a["span"] for a in spans_args}
        assert sids == {"100:1", "100:2", "200:1", "200:2"}
        # process_name metadata preserved
        names = {
            e["args"]["name"] for e in doc["traceEvents"]
            if e.get("name") == "process_name"
        }
        assert names == {"router", "host0"}

    def test_orphan_chain_flags_nonzero(self, tmp_path):
        # trace minted by pid 0x3e7, but no file from that process
        host = _chrome(200, [
            ("request", 0, 0.0, {"span": 1, "trace": "t3e7-9"}),
        ])
        hp = tmp_path / "h.json"
        hp.write_text(json.dumps(host))
        res = _run_tool("trace_stitch.py", str(hp))
        assert res.returncode == 2
        assert "orphans=1" in res.stdout
        res = _run_tool("trace_stitch.py", str(hp), "--allow-orphans")
        assert res.returncode == 0

    def test_pid_collision_rekeyed(self, tmp_path):
        a = _chrome(100, [("x", 0, 0.0, {"span": 1, "trace": "t64-1"})])
        b = _chrome(100, [("y", 0, 0.0, {"span": 1, "trace": "t64-2"})])
        ap, bp = tmp_path / "a.json", tmp_path / "b.json"
        ap.write_text(json.dumps(a))
        bp.write_text(json.dumps(b))
        out = tmp_path / "s.json"
        res = _run_tool("trace_stitch.py", str(ap), str(bp), "-o",
                        str(out), "--allow-orphans")
        assert res.returncode == 0
        doc = json.loads(out.read_text())
        pids = {e["pid"] for e in doc["traceEvents"]}
        assert len(pids) == 2


class TestMetricsMergeTag:
    def test_tagged_rows_precede_preserved_globals(self, tmp_path):
        a = [{"type": "counter", "name": "fleet.delivered", "value": 5},
             {"type": "gauge", "name": "g", "value": 1}]
        b = [{"type": "counter", "name": "fleet.delivered", "value": 3},
             {"type": "gauge", "name": "g", "value": 9}]
        ap, bp = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
        ap.write_text("\n".join(json.dumps(r) for r in a))
        bp.write_text("\n".join(json.dumps(r) for r in b))
        res = _run_tool("metrics_merge.py", "--tag", "host0", "--tag",
                        "host1", str(ap), str(bp))
        assert res.returncode == 0
        rows = [json.loads(x) for x in res.stdout.splitlines()]
        tagged = [r for r in rows if "src" in r]
        plain = [r for r in rows if "src" not in r and
                 r["type"] == "counter"]
        assert {(r["name"], r["src"], r["value"]) for r in tagged
                if r["type"] == "counter"} == {
            ("fleet.delivered", "host0", 5),
            ("fleet.delivered", "host1", 3),
        }
        assert plain == [
            {"type": "counter", "name": "fleet.delivered", "value": 8.0}
        ]
        # tagged rows come FIRST so last-wins loaders land on globals
        assert rows.index(tagged[0]) < rows.index(plain[0])

    def test_tag_count_mismatch_fails(self, tmp_path):
        ap = tmp_path / "a.jsonl"
        ap.write_text("")
        res = _run_tool("metrics_merge.py", "--tag", "x", "--tag", "y",
                        str(ap))
        assert res.returncode != 0
        assert "pair positionally" in res.stderr

    def test_untagged_output_unchanged(self, tmp_path):
        ap = tmp_path / "a.jsonl"
        ap.write_text(json.dumps(
            {"type": "counter", "name": "c", "value": 1}
        ))
        res = _run_tool("metrics_merge.py", str(ap))
        rows = [json.loads(x) for x in res.stdout.splitlines()]
        assert all("src" not in r for r in rows if r["type"] != "timeline")


class TestFleetReport:
    def _write(self, tmp_path, rows):
        p = tmp_path / "m.jsonl"
        p.write_text("\n".join(json.dumps(r) for r in rows))
        return str(p)

    def _base(self, **over):
        rows = {
            "fleet.submitted": 10, "fleet.delivered": 8,
            "fleet.typed_errors": 2, "fleet.bad_results": 0,
        }
        rows.update(over)
        return [{"type": "counter", "name": k, "value": v}
                for k, v in rows.items()]

    def test_reconciled_run_passes(self, tmp_path):
        rows = self._base() + [
            {"type": "gauge", "name": "fleet.trace_orphans", "value": 0},
        ]
        res = _run_tool("fleet_report.py",
                        self._write(tmp_path, rows), "--require-stitch")
        assert res.returncode == 0, res.stdout

    def test_hung_future_fails(self, tmp_path):
        rows = self._base(**{"fleet.delivered": 7})
        res = _run_tool("fleet_report.py", self._write(tmp_path, rows))
        assert res.returncode == 1
        assert "FAIL  no hung futures" in res.stdout

    def test_bad_result_fails(self, tmp_path):
        rows = self._base(**{"fleet.bad_results": 1})
        res = _run_tool("fleet_report.py", self._write(tmp_path, rows))
        assert res.returncode == 1
        assert "FAIL  no silent wrong answers" in res.stdout

    def test_sdc_without_recovery_fails(self, tmp_path):
        rows = self._base(**{
            "faults.injected.sdc_solve": 3, "fleet.cert.fail": 2,
            "fleet.quarantined": 1, "fleet.unquarantined": 0,
        })
        res = _run_tool("fleet_report.py", self._write(tmp_path, rows))
        assert res.returncode == 1
        assert "FAIL  sdc quarantined + probe-recovered" in res.stdout

    def test_victim_p99_judged_from_tenant_hist(self, tmp_path):
        rows = self._base(**{"fleet.rejected_quota": 4}) + [
            {"type": "hist", "name": "fleet.latency.tenant.v.total",
             "count": 5, "p99": 0.4},
        ]
        res = _run_tool("fleet_report.py", self._write(tmp_path, rows),
                        "--victim", "v", "--p99-budget", "1.0")
        assert res.returncode == 0, res.stdout
        res = _run_tool("fleet_report.py", self._write(tmp_path, rows),
                        "--victim", "v", "--p99-budget", "0.1")
        assert res.returncode == 1

    def test_missing_stitch_gauge_fails_when_required(self, tmp_path):
        res = _run_tool("fleet_report.py",
                        self._write(tmp_path, self._base()),
                        "--require-stitch")
        assert res.returncode == 1
        assert "gauge missing" in res.stdout

    def test_non_fleet_jsonl_refused(self, tmp_path):
        rows = [{"type": "counter", "name": "serve.dispatches",
                 "value": 1}]
        res = _run_tool("fleet_report.py", self._write(tmp_path, rows))
        assert res.returncode == 2


# ---------------------------------------------------------------------------
# serve.api zero-overhead-off wiring
# ---------------------------------------------------------------------------


class TestApiWiring:
    def test_fleet_off_is_none_branch(self):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("SLATE_TPU_FLEET", None)
        out = subprocess.run(
            [sys.executable, "-c",
             "from slate_tpu.serve import api; "
             "print(api._fleet, api.get_fleet())"],
            capture_output=True, text=True, env=env, cwd=_REPO,
        )
        assert out.returncode == 0, out.stderr
        assert out.stdout.split() == ["None", "None"]

    def test_fleet_env_builds_router_at_import(self):
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   SLATE_TPU_FLEET="spawn=2,cert=full")
        out = subprocess.run(
            [sys.executable, "-c",
             "from slate_tpu.serve import api; "
             "print(type(api._fleet).__name__, api._fleet.spawn, "
             "api._fleet.policy.describe())"],
            capture_output=True, text=True, env=env, cwd=_REPO,
        )
        assert out.returncode == 0, out.stderr
        assert out.stdout.split() == ["FleetRouter", "2", "full"]


# ---------------------------------------------------------------------------
# spawned-subprocess end-to-end
# ---------------------------------------------------------------------------


class TestEndToEnd:
    def test_spawned_worker_solves_and_drains(self, tmp_path):
        r = FleetRouter(
            spawn=1, cert="full", heartbeat_s=0.3, rpc_timeout_s=60,
            spawn_env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": _REPO},
        )
        r.start()
        try:
            rng = np.random.default_rng(0)
            A = (rng.standard_normal((8, 8))
                 + 8 * np.eye(8)).astype(np.float32)
            B = rng.standard_normal((8, 2)).astype(np.float32)
            futs = [r.submit("gesv", A, B, deadline=90.0)
                    for _ in range(3)]
            for f in futs:
                X = f.result(timeout=120)
                assert np.max(np.abs(A @ X - B)) < 1e-3
            assert _counter("fleet.delivered") == 3
            assert _counter("fleet.cert.checked") == 3
        finally:
            r.stop(drain=True)
        # drained, reaped: the worker process is gone
        with r._lock:
            procs = [h.proc for h in r._hosts.values()]
        assert all(p.poll() is not None for p in procs)

    @pytest.mark.slow
    def test_sigkill_mid_stream_every_future_resolves(self):
        r = FleetRouter(
            spawn=2, cert="sample=0.25", heartbeat_s=0.2,
            rpc_timeout_s=60, dead_after=2, respawn=True,
            spawn_env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": _REPO},
        )
        r.start()
        try:
            rng = np.random.default_rng(0)
            A = (rng.standard_normal((16, 16))
                 + 16 * np.eye(16)).astype(np.float32)
            B = rng.standard_normal((16, 2)).astype(np.float32)
            for f in [r.submit("gesv", A, B, deadline=90.0)
                      for _ in range(4)]:
                f.result(timeout=120)
            futs = [r.submit("gesv", A, B, deadline=90.0)
                    for _ in range(8)]
            with r._lock:
                proc = r._hosts["0"].proc
            proc.kill()
            for f in futs:
                X = f.result(timeout=120)  # value or typed, never hung
                assert np.max(np.abs(A @ X - B)) < 1e-3
            # the killed host came back: respawn -> rejoin -> probe
            deadline = time.time() + 60
            state = None
            while time.time() < deadline:
                state = r.health()["hosts"]["0"]["state"]
                if state in ("live", "rejoined"):
                    break
                time.sleep(0.3)
            assert state in ("live", "rejoined")
            assert _counter("fleet.host_dead") >= 1
            assert _counter("fleet.redispatched") >= 1
            assert _counter("fleet.host_respawned") >= 1
        finally:
            r.stop(drain=True)
