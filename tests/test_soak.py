"""Soak-fabric tests: workload recorder, deterministic replay,
health timeline, span-ring pressure, metrics merge, and the unified
soak verdict (``tools/soak_report.py``).

The service-backed tests share one module-scoped ExecutableCache (the
test_serve pattern) so each (bucket, batch) executable compiles once
for the file; the report/merge tools are exercised on hand-built
JSONLs (they are stdlib-only by contract and must work without the
library).
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from slate_tpu.aux import metrics, spans
from slate_tpu.integrity import policy as ipol
from slate_tpu.serve import buckets as bk
from slate_tpu.serve import service as serve_service
from slate_tpu.serve.cache import ExecutableCache
from slate_tpu.serve.factor_cache import FactorCache
from slate_tpu.serve.service import SolverService
from slate_tpu.soak import record, replay
from slate_tpu.soak.timeline import TimelineSampler, sample_row

FLOOR = 16
NRHS_FLOOR = 4
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TOOLS = os.path.join(_REPO, "tools")


@pytest.fixture(autouse=True)
def metrics_on():
    metrics.off()
    metrics.reset()
    metrics.on()
    yield
    metrics.off()
    metrics.reset()


@pytest.fixture(scope="module")
def shared_cache():
    return ExecutableCache(manifest_path=None)


def _ensure(cache, routine, n, batches=(1, 4)):
    k = bk.bucket_for(routine, n, n, 2, np.float64,
                      floor=FLOOR, nrhs_floor=NRHS_FLOOR)
    cache.ensure_manifest(k, batches)
    cache.ensure_manifest(k.solve_sibling(), batches)
    return k


# ---------------------------------------------------------------------------
# generators + materialize (pure, no service)
# ---------------------------------------------------------------------------


def test_generators_deterministic():
    for name, gen in replay.GENERATORS.items():
        a = gen(60, seed=3)
        b = gen(60, seed=3)
        assert a == b, name
        c = gen(60, seed=4)
        assert a != c, name
        assert len(a) == 60 or name == "adversarial_flood", name
        for row in a:
            for f in record.SPEC_FIELDS:
                assert f in row, (name, f)


def test_materialize_repeat_structure():
    rows = replay.gen_repeated_a(12, seed=1, distinct=3)
    cache = {}
    groups = {}
    for r in rows:
        A, B = replay.materialize(r, seed=0, cache=cache)
        groups.setdefault(r["repeat_fp"], []).append((A, B))
    assert len(groups) == 3
    for fp, items in groups.items():
        a0 = items[0][0]
        for A, B in items[1:]:
            # same repeat_fp -> byte-identical matrix, fresh rhs
            assert A.tobytes() == a0.tobytes(), fp
            assert B.tobytes() != items[0][1].tobytes(), fp
    mats = {items[0][0].tobytes() for items in groups.values()}
    assert len(mats) == 3  # distinct groups get distinct matrices
    # the cache memoizes A per group
    assert len(cache) == 3


def test_materialize_solvable_and_seed_sensitivity():
    row = replay.gen_multitenant(1, seed=0)[0]
    A0, B0 = replay.materialize(row, seed=0)
    A1, _ = replay.materialize(row, seed=1)
    assert A0.tobytes() != A1.tobytes()  # replay seed perturbs operands
    X = np.linalg.solve(A0, B0)
    assert np.all(np.isfinite(X))
    assert replay._residual_ok(row["routine"], A0, B0, X)
    assert not replay._residual_ok(row["routine"], A0, B0, X * 2 + 1)


def test_warm_spec_one_row_per_pool():
    spec = replay.merge_specs(
        replay.gen_repeated_a(40, seed=2, distinct=4),
        replay.gen_multitenant(40, seed=1, distinct=4),
    )
    warm = replay.warm_spec(spec, gap_s=0.01)
    fps = [w["repeat_fp"] for w in warm]
    assert len(fps) == len(set(fps))  # one row per pool
    assert set(fps) == {r["repeat_fp"] for r in spec if r["repeat_fp"]}
    assert all(w["deadline_s"] is None for w in warm)
    offs = [w["t_offset"] for w in warm]
    assert offs == sorted(offs)
    assert offs[-1] == pytest.approx(0.01 * (len(warm) - 1))


def test_spec_save_load_roundtrip(tmp_path):
    rows = replay.gen_deadline_storm(25, seed=9)
    path = str(tmp_path / "spec.jsonl")
    record.save(rows, path, source="synth")
    back = record.load(path)
    stripped = [{k: v for k, v in r.items() if k != "type"} for r in back]
    assert stripped == sorted(rows, key=lambda r: r["t_offset"])
    head = json.loads(open(path).read().splitlines()[0])
    assert head["type"] == "spec_meta"
    assert head["count"] == 25
    assert head["source"] == "synth"
    # a newer spec version must refuse loudly, not misparse silently
    with open(path, "w") as f:
        f.write(json.dumps({
            "type": "spec_meta", "version": record.SPEC_VERSION + 1,
            "count": 0,
        }) + "\n")
    with pytest.raises(ValueError, match="newer"):
        record.load(path)


def test_mix_histogram():
    rows = replay.gen_multitenant(40, seed=1, distinct=4)
    mix = record.mix_histogram(rows)
    assert sum(mix["tenants"].values()) == 40
    assert set(mix["tenants"]) == {"gold", "free"}
    assert mix["tenants"]["free"] == 10  # every 4th row
    assert sum(mix["priorities"].values()) == 40
    assert sum(mix["repeat_groups"].values()) == 40
    assert all(":" in s for s in mix["shapes"])


# ---------------------------------------------------------------------------
# span-ring pressure + metrics timeline primitives
# ---------------------------------------------------------------------------


def test_spans_pressure():
    spans.on(ring=8)
    try:
        spans.clear()
        p = spans.pressure()
        assert p["capacity"] == 8
        assert p["size"] == 0
        assert p["evicted"] == 0
        assert p["window_s"] == 0.0
        for i in range(12):
            spans.end(spans.start("request"))
        p = spans.pressure()
        assert p["size"] == 8
        assert p["evicted"] == 4
        assert p["window_s"] >= 0.0
    finally:
        spans.off()
        spans.clear()


def test_metrics_timeline_rows(tmp_path):
    metrics.record_timeline({"queue_depth": 3, "ready": True})
    metrics.record_timeline({"queue_depth": 5, "t": 1.25})
    rows = metrics.timeline()
    assert len(rows) == 2
    assert rows[0]["queue_depth"] == 3
    assert "t" in rows[0]  # stamped at record time when absent
    assert rows[1]["t"] == 1.25
    path = str(tmp_path / "m.jsonl")
    metrics.dump(path)
    dumped = [
        json.loads(line) for line in open(path)
        if json.loads(line).get("type") == "timeline"
    ]
    assert len(dumped) == 2
    assert dumped[1]["queue_depth"] == 5
    metrics.reset()
    assert metrics.timeline() == []


def test_metrics_timeline_off_is_free():
    metrics.off()
    metrics.record_timeline({"queue_depth": 1})
    metrics.on()
    assert metrics.timeline() == []


# ---------------------------------------------------------------------------
# recorder + replay + timeline against a live service
# ---------------------------------------------------------------------------


def _service(shared_cache, **kw):
    defaults = dict(
        cache=shared_cache, batch_max=4, batch_window_s=0.001,
        dim_floor=FLOOR, nrhs_floor=NRHS_FLOOR,
    )
    defaults.update(kw)
    return SolverService(**defaults)


def test_recorder_tap_and_zero_overhead_off(shared_cache):
    _ensure(shared_cache, "gesv", 12)
    assert serve_service._delivery_taps == []  # off by default
    svc = _service(shared_cache, factor_cache=FactorCache(max_entries=8))
    try:
        rng = np.random.default_rng(0)
        A = rng.standard_normal((12, 12)) + 12 * np.eye(12)
        rec = record.Recorder()
        with rec:
            assert len(serve_service._delivery_taps) == 1
            futs = [
                svc.submit("gesv", A, rng.standard_normal((12, 2)),
                           deadline=30.0)
                for _ in range(3)
            ]
            for f in futs:
                f.result(timeout=300)
        assert serve_service._delivery_taps == []  # detached
        rows = rec.rows()
        assert len(rows) == 3
        for r in rows:
            assert r["routine"] == "gesv"
            assert r["bucket_shape"] == [12, 12, 2]
            assert r["dtype"] == "float64"
            assert r["deadline_s"] == pytest.approx(30.0, abs=0.5)
            assert r["repeat_fp"]  # factor cache armed -> fingerprinted
        # same A -> same fingerprint -> same matrix_seed (the recorded
        # spec preserves the same-A burst for the factor cache)
        assert len({r["repeat_fp"] for r in rows}) == 1
        assert len({r["matrix_seed"] for r in rows}) == 1
        assert len({r["rhs_seed"] for r in rows}) == 3
        # resolutions after detach are not recorded
        svc.submit("gesv", A, rng.standard_normal((12, 2))).result(
            timeout=300)
        assert len(rec.rows()) == 3
    finally:
        svc.stop()


def test_replay_reconciles_and_records_round_trip(shared_cache):
    _ensure(shared_cache, "gesv", 12)
    svc = _service(shared_cache, factor_cache=FactorCache(max_entries=8))
    spans.on(ring=4096)
    try:
        spans.clear()
        spec = replay.gen_repeated_a(30, seed=5, rate_rps=500, distinct=2)
        rec = record.Recorder()
        with rec:
            res = replay.replay(svc, spec, speed=2.0, seed=0)
        assert res["submitted"] == 30
        assert res["submitted"] == (
            res["delivered"] + res["typed_errors"] + res["refused"]
        )
        assert res["bad_results"] == 0
        assert res["p50_s"] is not None
        c = metrics.counters()
        assert c["soak.submitted"] == 30
        assert c["soak.delivered"] == res["delivered"]
        assert len(rec.rows()) == res["delivered"] + res["typed_errors"]
        assert replay.orphan_spans() == 0
        # ring -> spec reconstruction sees the same request stream
        ring_rows = record.from_ring()
        assert len(ring_rows) >= res["delivered"]
        assert all(r["routine"] == "gesv" for r in ring_rows)
    finally:
        svc.stop()
        spans.off()
        spans.clear()


def test_timeline_sampler(shared_cache):
    _ensure(shared_cache, "gesv", 12)
    svc = _service(shared_cache)
    try:
        with TimelineSampler(svc, period_s=0.02):
            time.sleep(0.15)
        rows = metrics.timeline()
        assert len(rows) >= 4  # baseline + cadence + terminal
        for r in rows:
            assert isinstance(r["ready"], bool)
            assert isinstance(r["queue_depth"], int)
            assert isinstance(r["breakers_open"], int)
            assert "t" in r
        ts = [r["t"] for r in rows]
        assert ts == sorted(ts)
    finally:
        svc.stop()


def test_sample_row_with_planes_armed(shared_cache):
    _ensure(shared_cache, "gesv", 12)
    svc = _service(
        shared_cache,
        factor_cache=FactorCache(max_entries=8),
        tenants="gold:weight=4;free:rate=100,share=0.5",
        adaptive=True, latency_budget_s=0.5,
        integrity=ipol.parse_spec("full"),
    )
    spans.on(ring=1024)
    try:
        row = sample_row(svc)
        assert isinstance(row["quarantined"], int)
        assert isinstance(row["ring_evicted"], int)
        assert isinstance(row["factor_cache_bytes"], int)
        assert "overload_level" in row
    finally:
        svc.stop()
        spans.off()
        spans.clear()


def test_health_all_planes_armed_sections_and_latency(shared_cache):
    """Satellite: health() with EVERY plane armed at once — all
    documented sections present with stable types, and the probe
    stays cheap enough to poll."""
    _ensure(shared_cache, "gesv", 12)
    svc = _service(
        shared_cache,
        factor_cache=FactorCache(max_entries=8),
        tenants="gold:weight=4;free:rate=100,share=0.5",
        adaptive=True, latency_budget_s=0.5,
        integrity=ipol.parse_spec("full,hedge=1.5,cooldown=0.5"),
    )
    spans.on(ring=1024)
    try:
        rng = np.random.default_rng(1)
        A = rng.standard_normal((12, 12)) + 12 * np.eye(12)
        for tenant in ("gold", "free"):
            svc.submit("gesv", A, rng.standard_normal((12, 2)),
                       tenant=tenant).result(timeout=300)
        t0 = time.monotonic()
        h = svc.health()
        probe_s = time.monotonic() - t0
        assert probe_s < 0.25, f"health() took {probe_s:.3f}s"
        for key in ("ok", "phase", "ready", "restore", "integrity",
                    "running", "worker_alive", "worker_restarts",
                    "queue_depth", "queue_limit", "inflight", "breakers",
                    "open_buckets", "replicas", "sharded", "latency",
                    "slo_burn", "trace_ring", "cost", "devices",
                    "factor_cache", "tenants", "admission",
                    "failures_60s", "failure_rate_60s", "uptime_s"):
            assert key in h, key
        assert isinstance(h["ready"], bool)
        assert isinstance(h["queue_depth"], int)
        assert isinstance(h["replicas"], list)
        # every armed plane populates its section (None = plane off)
        assert h["integrity"] is not None
        assert h["integrity"]["policy"].startswith("full")
        assert h["factor_cache"] is not None
        assert isinstance(h["factor_cache"]["entries"], int)
        assert h["tenants"] is not None
        assert h["admission"] is not None
        assert h["trace_ring"] is not None
        assert h["trace_ring"] == spans.pressure()
        assert isinstance(h["latency"], dict) and h["latency"]
        for row in h["latency"].values():
            assert set(row) >= {"count", "p50", "p95", "p99"}
    finally:
        svc.stop()
        spans.off()
        spans.clear()


# ---------------------------------------------------------------------------
# tools: metrics_merge + soak_report (subprocess, stdlib-only contract)
# ---------------------------------------------------------------------------


def _hist_row(name, values):
    sys.path.insert(0, _TOOLS)
    try:
        import metrics_merge as mm
    finally:
        sys.path.pop(0)
    counts = [0] * (len(mm.HIST_EDGES) + 1)
    for v in values:
        i = 0
        while i < len(mm.HIST_EDGES) and v > mm.HIST_EDGES[i]:
            i += 1
        counts[i] += 1
    ordered = sorted(values)
    return {
        "type": "hist", "name": name, "count": len(values),
        "total_s": round(sum(values), 6), "min_s": min(values),
        "max_s": max(values),
        "p50": ordered[len(ordered) // 2], "p95": ordered[-1],
        "p99": ordered[-1],
        "buckets": [
            ["inf" if i >= len(mm.HIST_EDGES)
             else float(f"{mm.HIST_EDGES[i]:.9g}"), k]
            for i, k in enumerate(counts) if k
        ],
    }


def _write_jsonl(path, rows):
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


def test_metrics_merge(tmp_path):
    a = str(tmp_path / "a.jsonl")
    b = str(tmp_path / "b.jsonl")
    merged = str(tmp_path / "m.jsonl")
    _write_jsonl(a, [
        {"type": "meta", "schema": 1},
        {"type": "counter", "name": "soak.submitted", "value": 10},
        {"type": "gauge", "name": "g", "value": 1},
        {"type": "timer", "name": "t", "count": 2, "total_s": 1.0,
         "min_s": 0.4, "max_s": 0.6},
        _hist_row("serve.latency.x.total", [0.01, 0.02, 0.04]),
        {"type": "timeline", "t": 0.5, "queue_depth": 1},
        {"type": "event", "name": "debug", "t": 0.1},
    ])
    _write_jsonl(b, [
        {"type": "counter", "name": "soak.submitted", "value": 5},
        {"type": "gauge", "name": "g", "value": 7},
        {"type": "timer", "name": "t", "count": 1, "total_s": 0.2,
         "min_s": 0.2, "max_s": 0.2},
        _hist_row("serve.latency.x.total", [0.08]),
        {"type": "timeline", "t": 0.25, "queue_depth": 9},
    ])
    rc = subprocess.call(
        [sys.executable, os.path.join(_TOOLS, "metrics_merge.py"),
         a, b, "-o", merged],
    )
    assert rc == 0
    rows = [json.loads(line) for line in open(merged)]
    by = {}
    for r in rows:
        by.setdefault(r["type"], []).append(r)
    assert "event" not in by  # dropped
    [meta] = by["meta"]
    assert meta["merged_from"] == ["a.jsonl", "b.jsonl"]
    [ctr] = by["counter"]
    assert ctr["value"] == 15  # counters sum
    [g] = by["gauge"]
    assert g["value"] == 7  # last wins
    [t] = by["timer"]
    assert (t["count"], t["total_s"], t["min_s"], t["max_s"]) == (
        3, 1.2, 0.2, 0.6)
    [h] = by["hist"]
    assert h["count"] == 4
    assert sum(k for _le, k in h["buckets"]) == 4
    assert 0.01 <= h["p50"] <= 0.04  # re-ranked from merged buckets
    assert 0.04 < h["p99"] <= 0.08
    tl = by["timeline"]
    assert [r["t"] for r in tl] == [0.25, 0.5]  # re-sorted
    assert tl[0]["src"] == "b.jsonl"
    # an off-lattice edge is a schema violation, not a silent misfile
    bad = str(tmp_path / "bad.jsonl")
    _write_jsonl(bad, [
        {"type": "hist", "name": "h", "count": 1, "total_s": 1.0,
         "min_s": 1.0, "max_s": 1.0, "buckets": [[0.007, 1]]},
    ])
    rc = subprocess.call(
        [sys.executable, os.path.join(_TOOLS, "metrics_merge.py"),
         bad, "-o", str(tmp_path / "out.jsonl")],
        stderr=subprocess.DEVNULL,
    )
    assert rc != 0


def _verdict_rows(submitted=100, delivered=90, typed=4, refused=6,
                  bad=0, orphans=0, compiles=0, serve_requests=None,
                  timeline_n=5, p99=0.05):
    if serve_requests is None:
        serve_requests = submitted - refused
    rows = [
        {"type": "meta", "schema": 1},
        {"type": "counter", "name": "soak.submitted", "value": submitted},
        {"type": "counter", "name": "soak.delivered", "value": delivered},
        {"type": "counter", "name": "soak.typed_errors", "value": typed},
        {"type": "counter", "name": "soak.refused", "value": refused},
        {"type": "counter", "name": "soak.bad_results", "value": bad},
        {"type": "counter", "name": "serve.requests",
         "value": serve_requests},
        {"type": "counter", "name": "jit.compilations", "value": compiles},
        {"type": "gauge", "name": "soak.orphan_spans", "value": orphans},
        _hist_row("serve.latency.gesv.16x16x4.float64.total",
                  [p99 / 2, p99 / 2, p99]),
    ]
    rows += [
        {"type": "timeline", "t": 0.1 * i, "ready": True,
         "breakers_open": 0}
        for i in range(timeline_n)
    ]
    return rows


def _report(path, *extra):
    return subprocess.call(
        [sys.executable, os.path.join(_TOOLS, "soak_report.py"),
         path, *extra],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


def test_soak_report_verdicts(tmp_path):
    ok = str(tmp_path / "ok.jsonl")
    _write_jsonl(ok, _verdict_rows())
    assert _report(ok, "--p99-budget-ms", "200") == 0

    # each violation flips the verdict on its own
    cases = {
        "unaccounted.jsonl": _verdict_rows(delivered=89),
        "escape.jsonl": _verdict_rows(bad=3),
        "orphan.jsonl": _verdict_rows(orphans=2),
        "compile.jsonl": _verdict_rows(compiles=1),
        "admission.jsonl": _verdict_rows(serve_requests=80),
        "tail.jsonl": _verdict_rows(p99=5.0),
    }
    for name, rows in cases.items():
        path = str(tmp_path / name)
        _write_jsonl(path, rows)
        assert _report(path, "--p99-budget-ms", "200") == 1, name

    # a run that never recovered from a disruption is flagged
    stuck = _verdict_rows()
    stuck += [{"type": "timeline", "t": 9.0, "ready": True,
               "breakers_open": 2}]
    path = str(tmp_path / "stuck.jsonl")
    _write_jsonl(path, stuck)
    assert _report(path, "--p99-budget-ms", "200") == 1

    # a disruption that CLOSED passes (and obeys --max-recovery-s)
    healed = _verdict_rows()
    healed += [
        {"type": "timeline", "t": 9.0, "ready": True, "breakers_open": 2},
        {"type": "timeline", "t": 9.2, "ready": True, "breakers_open": 0},
    ]
    path = str(tmp_path / "healed.jsonl")
    _write_jsonl(path, healed)
    assert _report(path, "--p99-budget-ms", "200") == 0
    assert _report(path, "--p99-budget-ms", "200",
                   "--max-recovery-s", "0.1") == 1

    # not a soak JSONL -> unusable input, exit 2
    empty = str(tmp_path / "empty.jsonl")
    _write_jsonl(empty, [{"type": "meta", "schema": 1}])
    assert _report(empty) == 2


def test_soak_report_timeline_floor(tmp_path):
    path = str(tmp_path / "thin.jsonl")
    _write_jsonl(path, _verdict_rows(timeline_n=1))
    assert _report(path, "--min-timeline-rows", "5") == 1
    assert _report(path, "--min-timeline-rows", "1") == 0
