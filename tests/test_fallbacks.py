"""Fallback accounting tests: every driver route that abandons the SPMD
path for a gathered-global evaluation must be recorded, and must raise
under Option.RequireSpmd (reference behavior: SLATE never silently
gathers a distributed matrix — internal/fallbacks.py)."""

import numpy as np
import pytest

from slate_tpu.drivers import blas3, chol, lu
from slate_tpu.enums import Diag, MethodLU, Op, Option, Side, Uplo
from slate_tpu.exceptions import DistributedException
from slate_tpu.internal import fallbacks
from slate_tpu.matrix.base import BaseMatrix, conj_transpose, transpose
from slate_tpu.matrix.matrix import HermitianMatrix, Matrix, TriangularMatrix

REQ = {Option.RequireSpmd: True}


@pytest.fixture(autouse=True)
def _reset_counters():
    fallbacks.reset()
    yield
    fallbacks.reset()


def _tri(rng, n, nb, grid):
    L0 = np.tril(rng.standard_normal((n, n))) + n * np.eye(n)
    return L0, TriangularMatrix.from_global(L0, nb, grid=grid, uplo=Uplo.Lower)


def test_trmm_distributed_records_and_raises(rng, grid22):
    """Non-conformable tiles (B mb != A nb) fall back and record."""
    n, nb = 64, 16
    L0, L = _tri(rng, n, nb, grid22)
    B = Matrix.from_global(rng.standard_normal((n, 4)), 32, grid=grid22)
    blas3.trmm(Side.Left, 1.0, L, B)
    assert fallbacks.counters().get("trmm") == 1
    with pytest.raises(DistributedException):
        blas3.trmm(Side.Left, 1.0, L, B, opts=REQ)


def test_trsm_viewed_b_records_and_raises(rng, grid22):
    """A transposed B view is not spmd-conformable: falls back, records."""
    n, nb = 32, 16
    L0, L = _tri(rng, n, nb, grid22)
    B = Matrix.from_global(rng.standard_normal((n, 4)), nb, grid=grid22)
    Bt = transpose(Matrix.from_global(rng.standard_normal((4, n)), nb, grid=grid22))
    blas3.trsm(Side.Left, 1.0, L, Bt)
    assert fallbacks.counters().get("trsm") == 1
    with pytest.raises(DistributedException):
        blas3.trsm(Side.Left, 1.0, L, Bt, opts=REQ)


def test_trsm_right_side_spmd(rng, grid22):
    """Right-side solves ride the SPMD column pipeline now: no fallback."""
    n, nb = 50, 16
    L0, L = _tri(rng, n, nb, grid22)
    B0 = rng.standard_normal((8, n))
    B = Matrix.from_global(B0, nb, grid=grid22)
    X = blas3.trsm(Side.Right, 1.0, L, B, opts=REQ)
    assert fallbacks.counters() == {}
    np.testing.assert_allclose(
        np.asarray(X.to_global()),
        np.linalg.solve(L0.T, B0.T).T,
        atol=1e-11,
    )


def test_trmm_spmd(rng, grid22):
    """Distributed trmm rides the triangular SUMMA: no fallback."""
    n, nb = 50, 16
    L0, L = _tri(rng, n, nb, grid22)
    B0 = rng.standard_normal((n, 8))
    B = Matrix.from_global(B0, nb, grid=grid22)
    out = blas3.trmm(Side.Left, 2.0, L, B, opts=REQ)
    assert fallbacks.counters() == {}
    np.testing.assert_allclose(
        np.asarray(out.to_global()), 2.0 * (L0 @ B0), atol=1e-11 * n
    )


def test_calu_distributed_spmd_no_warning(rng, grid22):
    """Distributed CALU rides the mesh tournament: no warning, no
    fallback, LAPACK-grade solve residual."""
    import warnings as _w

    n, nb = 64, 16
    A0 = rng.standard_normal((n, n)) + n * np.eye(n)
    A = Matrix.from_global(A0, nb, grid=grid22)
    with _w.catch_warnings():
        _w.simplefilter("error")
        LU, piv, info = lu.getrf(
            A, {Option.MethodLU: MethodLU.CALU, Option.RequireSpmd: True}
        )
    assert fallbacks.counters() == {}
    assert int(info) == 0
    lu2d = np.asarray(LU.to_global())
    L = np.tril(lu2d, -1) + np.eye(n)
    U = np.triu(lu2d)
    perm = np.asarray(piv.perm)[:n]
    res = np.abs(L @ U - A0[perm]).max() / np.abs(A0).max()
    assert res < 1e-12, res


def test_calu_distributed_warns_on_fallback(rng, grid22):
    """UseShardMap=False distributed CALU still gathers: warn + record;
    string option keys canonicalize in the gate."""
    n, nb = 64, 16
    A0 = rng.standard_normal((n, n)) + n * np.eye(n)
    A = Matrix.from_global(A0, nb, grid=grid22)
    with pytest.warns(UserWarning, match="gathers"):
        lu.getrf(A, {"method_lu": "calu", "useshardmap": False})
    assert fallbacks.counters().get("getrf_tntpiv") == 1
    with pytest.warns(UserWarning, match="gathers"):
        with pytest.raises(DistributedException):
            lu.getrf(
                A,
                {
                    Option.MethodLU: MethodLU.CALU,
                    Option.UseShardMap: False,
                    Option.RequireSpmd: True,
                },
            )


def test_herk_mixed_op_records(rng, grid22):
    n, nb = 32, 16
    A = Matrix.from_global(rng.standard_normal((n, n)), nb, grid=grid22)
    C0 = rng.standard_normal((n, n))
    C = HermitianMatrix.from_global(
        C0 + C0.T, nb, grid=grid22, uplo=Uplo.Lower
    )
    # syrk of a conj-transposed view is a mixed op/conj combo: falls back
    blas3.syrk(1.0, conj_transpose(A), 0.0, C)
    assert fallbacks.counters().get("herk") == 1
    with pytest.raises(DistributedException):
        blas3.syrk(1.0, conj_transpose(A), 0.0, C, opts=REQ)


@pytest.mark.slow
def test_herk_transposed_grid_spmd(rng, grid42):
    """herk/syrk on a non-square mesh must NOT fall back (the old SUMMA
    route resolved A^H onto the transposed grid and gathered)."""
    n, nb = 64, 16
    A0 = rng.standard_normal((n, n))
    C0 = rng.standard_normal((n, n))
    C0 = C0 + C0.T
    A = Matrix.from_global(A0, nb, grid=grid42)
    C = HermitianMatrix.from_global(C0, nb, grid=grid42, uplo=Uplo.Lower)
    out = blas3.herk(1.0, A, 0.5, C, opts=REQ)
    assert fallbacks.counters() == {}
    got = np.tril(np.asarray(out.to_global()))
    want = np.tril(A0 @ A0.T + 0.5 * C0)
    np.testing.assert_allclose(got, want, atol=1e-11 * n)


def test_herk_trans_view_spmd(rng, grid22):
    """herk of A^H (ConjTrans view) rides the row-gather kernel."""
    n, k, nb = 48, 32, 16
    A0 = rng.standard_normal((k, n))
    C0 = rng.standard_normal((n, n))
    C0 = C0 + C0.T
    A = Matrix.from_global(A0, nb, grid=grid22)
    C = HermitianMatrix.from_global(C0, nb, grid=grid22, uplo=Uplo.Lower)
    out = blas3.herk(1.0, conj_transpose(A), 0.5, C, opts=REQ)
    assert fallbacks.counters() == {}
    got = np.tril(np.asarray(out.to_global()))
    want = np.tril(A0.T @ A0 + 0.5 * C0)
    np.testing.assert_allclose(got, want, atol=1e-11 * n)


@pytest.mark.slow
def test_her2k_spmd_no_fallback(rng, grid22):
    n, k, nb = 48, 32, 16
    A0 = rng.standard_normal((n, k))
    B0 = rng.standard_normal((n, k))
    C0 = rng.standard_normal((n, n))
    C0 = C0 + C0.T
    A = Matrix.from_global(A0, nb, grid=grid22)
    B = Matrix.from_global(B0, nb, grid=grid22)
    C = HermitianMatrix.from_global(C0, nb, grid=grid22, uplo=Uplo.Lower)
    out = blas3.syr2k(1.0, A, B, 0.5, C, opts=REQ)
    assert fallbacks.counters() == {}
    got = np.tril(np.asarray(out.to_global()))
    want = np.tril(A0 @ B0.T + B0 @ A0.T + 0.5 * C0)
    np.testing.assert_allclose(got, want, atol=1e-11 * n)


def test_potrf_lower_no_gather(rng, grid22, monkeypatch):
    """Distributed lower potrf reads only stored tiles — no mirror."""
    n, nb = 64, 16
    A0 = rng.standard_normal((n, n))
    A0 = A0 @ A0.T + n * np.eye(n)
    A = HermitianMatrix.from_global(A0, nb, grid=grid22, uplo=Uplo.Lower)

    def boom(self, *a, **kw):  # pragma: no cover - failure path
        raise AssertionError("gather in distributed lower potrf")

    monkeypatch.setattr(BaseMatrix, "to_global", boom)
    monkeypatch.setattr(HermitianMatrix, "full_global", boom)
    L, info = chol.potrf(A, REQ)
    assert fallbacks.counters() == {}


def test_getrs_fallback_records(rng, grid22):
    """A non-conformable B layout falls back and is recorded."""
    n, nb = 64, 16
    A0 = rng.standard_normal((n, n)) + n * np.eye(n)
    A = Matrix.from_global(A0, nb, grid=grid22)
    LU, piv, info = lu.getrf(A)
    B = Matrix.from_global(rng.standard_normal((n, 4)), 32, grid=grid22)
    lu.getrs(LU, piv, B)
    assert fallbacks.counters().get("getrs") == 1
    with pytest.raises(DistributedException):
        lu.getrs(LU, piv, B, opts=REQ)


def test_counters_reset():
    fallbacks.record("x")
    assert fallbacks.counters() == {"x": 1}
    fallbacks.reset()
    assert fallbacks.counters() == {}


@pytest.mark.slow
@pytest.mark.parametrize("kind", ["svd_geo", "svd_arith"])
def test_calu_distributed_illconditioned_parity(rng, grid22, kind):
    """Mesh-tournament CALU matches partial pivoting's solve quality on
    ill-conditioned matgen kinds (reference: test_gesv.cc tntpiv runs)."""
    from slate_tpu.matgen.generate import generate_2d

    n, nb = 96, 16
    A0 = np.asarray(generate_2d(kind, n, n, cond=1e8, seed=11)[0])
    B0 = rng.standard_normal((n, 3))
    A = Matrix.from_global(A0, nb, grid=grid22)
    B = Matrix.from_global(B0, nb, grid=grid22)

    LUc, pivc, infoc = lu.getrf(A, {Option.MethodLU: MethodLU.CALU})
    Xc = lu.getrs(LUc, pivc, B)
    LUp, pivp, infop = lu.getrf(A)
    Xp = lu.getrs(LUp, pivp, B)
    from slate_tpu.testing import checks

    ec = checks.solve_residual(A0, np.asarray(Xc.to_global()), B0)
    ep = checks.solve_residual(A0, np.asarray(Xp.to_global()), B0)
    assert checks.passed(ec, np.float64, factor=60), (ec, ep)
    # parity: tournament within ~30x of partial pivoting's backward error
    assert ec <= 30 * max(ep, np.finfo(np.float64).eps), (ec, ep)
