"""slate-lint (slate_tpu/analysis): per-rule fixture positives and
clean negatives, suppression + baseline semantics, JSON schema, and
the self-run asserting the shipped tree is clean.

Fixture snippets are written into a throwaway repo skeleton (the
engine's path scoping — serve/ for the gating and exception rules,
tools/*_report.py for the consumer side of metric drift — is part of
what is under test).  The linter is stdlib-only, so these tests never
touch jax.
"""

import json
import os
import sys
import textwrap

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from slate_tpu import analysis
from slate_tpu.analysis import core

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mini_repo(tmp_path, files, readme=None):
    """Lay out {relpath: source} under tmp_path and return its root."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    if readme is not None:
        (tmp_path / "README.md").write_text(textwrap.dedent(readme))
    return str(tmp_path)


def _lint(root, rule):
    return analysis.run(root, rules=[rule])


def _rules_of(result):
    return [f.rule for f in result.findings]


# ---------------------------------------------------------------------------
# rule registry
# ---------------------------------------------------------------------------


def test_registry_ships_the_eight_rules():
    expected = {
        "metric-drift", "fault-site", "hot-path-gating", "trace-safety",
        "pytree-safety", "lock-discipline", "env-drift",
        "exception-context",
    }
    assert expected <= set(analysis.RULES)
    for name in expected:
        r = analysis.RULES[name]
        assert r.summary and r.bug  # documented, not just registered


# ---------------------------------------------------------------------------
# rule 1: metric-drift
# ---------------------------------------------------------------------------


def test_metric_drift_positive(tmp_path):
    root = _mini_repo(tmp_path, {
        "slate_tpu/serve/svc.py": """
            from ..aux import metrics
            metrics.inc("serve.requests")
        """,
        "tools/foo_report.py": """
            def load(counters):
                return counters.get("serve.requets_typo", 0)
        """,
    })
    res = _lint(root, "metric-drift")
    assert _rules_of(res) == ["metric-drift"]
    assert "serve.requets_typo" in res.findings[0].message
    assert res.findings[0].path == "tools/foo_report.py"


def test_metric_drift_negative_exact_prefix_and_readme(tmp_path):
    root = _mini_repo(tmp_path, {
        "slate_tpu/serve/svc.py": """
            from ..aux import metrics
            metrics.inc("serve.requests")
            def g(label):
                metrics.observe_hist(f"serve.latency.{label}.total", 0.1)
        """,
        "tools/foo_report.py": """
            def load(counters):
                a = counters.get("serve.requests", 0)
                b = [k for k in counters if k.startswith("serve.latency.")]
                return a, b
        """,
    }, readme="""
        Metrics: `serve.requests` and per bucket
        `serve.latency.<bucket>.total`.
    """)
    assert _lint(root, "metric-drift").ok


def test_metric_drift_not_vacuous_under_bare_root_fstring(tmp_path):
    # an emitter like f"serve.{label}.b{batch}" must NOT whitelist the
    # whole serve.* namespace (the bare-root prefix is discarded)
    root = _mini_repo(tmp_path, {
        "slate_tpu/serve/svc.py": """
            from ..aux import metrics
            metrics.inc("serve.requests")
            def g(label, batch):
                metrics.observe(f"serve.{label}.b{batch}", 0.1)
        """,
        "tools/foo_report.py": """
            def load(counters):
                return counters.get("serve.totally_bogus_counter", 0)
        """,
    })
    res = _lint(root, "metric-drift")
    assert len(res.findings) == 1
    assert "serve.totally_bogus_counter" in res.findings[0].message


def test_metric_drift_suffix_matches_computed_base(tmp_path):
    # the {base}.leaf idiom: name = f"refine.{r}" then f"{name}.calls"
    # — consumed "refine.calls" matches via the constant suffix
    root = _mini_repo(tmp_path, {
        "slate_tpu/refine/ir.py": """
            from ..aux import metrics
            def f(routine):
                name = f"refine.{routine}"
                metrics.inc(f"{name}.calls")
        """,
        "tools/foo_report.py": """
            def load(counters):
                good = counters.get("refine.calls", 0)
                return good
        """,
    })
    assert _lint(root, "metric-drift").ok


def test_metric_drift_readme_positive(tmp_path):
    root = _mini_repo(tmp_path, {
        "slate_tpu/serve/svc.py": """
            from ..aux import metrics
            metrics.inc("serve.requests")
        """,
    }, readme="Docs mention `serve.ghost_counter` here.\n")
    res = _lint(root, "metric-drift")
    assert [f.path for f in res.findings] == ["README.md"]
    assert "serve.ghost_counter" in res.findings[0].message


# ---------------------------------------------------------------------------
# rule 2: fault-site
# ---------------------------------------------------------------------------

_FAULTS_FIXTURE = """
    class SiteSpec:
        def __init__(self, name, recovery=(), informational=False):
            pass

    SITE_SPECS = (
        SiteSpec("execute", recovery=("serve.retries",)),
        SiteSpec("latency", recovery=(), informational=True),
    )
"""


def test_fault_site_positive_undeclared_site(tmp_path):
    root = _mini_repo(tmp_path, {
        "slate_tpu/aux/faults.py": _FAULTS_FIXTURE,
        "slate_tpu/serve/svc.py": """
            from ..aux import faults, metrics
            metrics.inc("serve.retries")
            def f():
                faults.check("execute")
                faults.check("exceute_typo")
        """,
    })
    res = _lint(root, "fault-site")
    assert len(res.findings) == 1
    assert "exceute_typo" in res.findings[0].message


def test_fault_site_positive_unrecoverable_and_ghost_counter(tmp_path):
    root = _mini_repo(tmp_path, {
        "slate_tpu/aux/faults.py": """
            class SiteSpec:
                def __init__(self, name, recovery=(), informational=False):
                    pass

            SITE_SPECS = (
                SiteSpec("orphan"),
                SiteSpec("ghost", recovery=("serve.not_emitted",)),
            )
        """,
        "slate_tpu/serve/svc.py": """
            from ..aux import metrics
            metrics.inc("serve.retries")
        """,
    })
    msgs = " | ".join(f.message for f in _lint(root, "fault-site").findings)
    assert "orphan" in msgs and "no recovery" in msgs
    assert "serve.not_emitted" in msgs


def test_fault_site_sdc_site_without_recovery_fails(tmp_path):
    """ISSUE 14 satellite: an SDC-style site declared with no recovery
    counters (and not informational) must fail the fault-site rule —
    chaos_report could otherwise never show its containment, and an
    injection there would flag CI forever."""
    root = _mini_repo(tmp_path, {
        "slate_tpu/aux/faults.py": """
            class SiteSpec:
                def __init__(self, name, recovery=(), informational=False):
                    pass

            SITE_SPECS = (
                SiteSpec("sdc_solve"),
                SiteSpec("sdc_factor", recovery=("serve.integrity.fail",)),
            )
        """,
        "slate_tpu/serve/svc.py": """
            from ..aux import faults, metrics
            metrics.inc("serve.integrity.fail")
            def f(x):
                return faults.perturb("sdc_solve", x)
        """,
    })
    res = _lint(root, "fault-site")
    msgs = " | ".join(f.message for f in res.findings)
    assert "sdc_solve" in msgs and "no recovery" in msgs
    # the sibling WITH an emitted recovery family is clean
    assert "sdc_factor" not in msgs


def test_fault_site_negative(tmp_path):
    root = _mini_repo(tmp_path, {
        "slate_tpu/aux/faults.py": _FAULTS_FIXTURE,
        "slate_tpu/serve/svc.py": """
            from ..aux import faults, metrics
            metrics.inc("serve.retries")
            def f():
                faults.check("execute")
                faults.sleep("latency")
        """,
    })
    assert _lint(root, "fault-site").ok


def test_fault_site_registry_matches_chaos_report():
    """The shipped chaos_report derives RECOVERY/INFORMATIONAL from the
    shipped registry (single source of truth, satellite refactor)."""
    import importlib.util

    from slate_tpu.aux.faults import SITE_REGISTRY, SITES

    spec = importlib.util.spec_from_file_location(
        "chaos_report_lintcheck",
        os.path.join(REPO_ROOT, "tools", "chaos_report.py"),
    )
    cr = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cr)
    assert set(cr.RECOVERY) == set(SITES)
    for site, spec_ in SITE_REGISTRY.items():
        assert cr.RECOVERY[site] == spec_.recovery
    assert cr.INFORMATIONAL == {
        s for s, sp in SITE_REGISTRY.items() if sp.informational
    }


# ---------------------------------------------------------------------------
# rule 3: hot-path-gating
# ---------------------------------------------------------------------------


def test_hot_path_gating_positive(tmp_path):
    root = _mini_repo(tmp_path, {
        "slate_tpu/serve/svc.py": """
            from ..aux import metrics

            def deliver(label, waste):
                metrics.inc(f"serve.latency.{label}.total")
                metrics.inc("serve.pad", compute_waste(waste))
        """,
    })
    res = _lint(root, "hot-path-gating")
    assert len(res.findings) == 2


def test_hot_path_gating_negative_gates(tmp_path):
    root = _mini_repo(tmp_path, {
        "slate_tpu/serve/svc.py": """
            from ..aux import metrics, spans

            def deliver(label, req, waste):
                metrics.inc("serve.requests")         # literal: free
                if metrics.is_on():
                    metrics.inc(f"serve.latency.{label}.total")
                mon = metrics.is_on()
                if mon:
                    metrics.inc("serve.pad", compute_waste(waste))
                if req.span is not None:
                    spans.annotate(req.span, outcome=classify(req))
                try:
                    pass
                except Exception:
                    metrics.inc(f"serve.fail.{label}")  # cold: exempt

            def capture(name):
                if not metrics.is_on():
                    return
                metrics.observe(f"{name}.cost", measure(name))
        """,
    })
    assert _lint(root, "hot-path-gating").ok


def test_hot_path_gating_polarity_and_branch(tmp_path):
    # the OFF branch of a gate is NOT gated: else of is_on(), the body
    # of `if not mon:`, and the body of an early-return guard all run
    # exactly when the subsystem is disarmed
    root = _mini_repo(tmp_path, {
        "slate_tpu/serve/svc.py": """
            from ..aux import metrics

            def a(label):
                if metrics.is_on():
                    pass
                else:
                    metrics.inc(f"serve.{label}.off_branch")

            def b(label):
                mon = metrics.is_on()
                if not mon:
                    metrics.inc(f"serve.{label}.off_body")

            def c(label):
                if not metrics.is_on():
                    metrics.inc(f"serve.{label}.guard_body")
                    return
                metrics.inc(f"serve.{label}.covered_after_guard")  # gated
        """,
    })
    res = _lint(root, "hot-path-gating")
    msgs = " | ".join(f.message for f in res.findings)
    assert len(res.findings) == 3, msgs
    # the call AFTER the early-return guard stays covered
    lines = {f.line for f in res.findings}
    src = (tmp_path / "slate_tpu/serve/svc.py").read_text()
    covered_line = next(
        i for i, ln in enumerate(src.splitlines(), 1)
        if "covered_after_guard" in ln
    )
    assert covered_line not in lines


def test_hot_path_gating_out_of_scope_negative(tmp_path):
    # the rule polices serve hot paths; drivers/ own instrumentation
    # conventions are out of scope
    root = _mini_repo(tmp_path, {
        "slate_tpu/drivers/x.py": """
            from ..aux import metrics
            def f(label):
                metrics.inc(f"refine.{label}.calls")
        """,
    })
    assert _lint(root, "hot-path-gating").ok


# ---------------------------------------------------------------------------
# rule 4: trace-safety
# ---------------------------------------------------------------------------


def test_trace_safety_positive(tmp_path):
    root = _mini_repo(tmp_path, {
        "slate_tpu/ops/k.py": """
            import numpy as np
            import jax
            from jax import lax

            def body(carry, x):
                if x > 0:
                    carry = carry + x
                s = float(x)
                np.linalg.norm(x)
                return carry, s

            def run(xs):
                return lax.scan(body, 0.0, xs)
        """,
    })
    res = _lint(root, "trace-safety")
    msgs = " | ".join(f.message for f in res.findings)
    assert len(res.findings) == 3
    assert "`if`" in msgs and "float()" in msgs and "numpy" in msgs


def test_trace_safety_negative(tmp_path):
    root = _mini_repo(tmp_path, {
        "slate_tpu/ops/k.py": """
            import numpy as np
            import jax
            from functools import partial
            from jax import lax

            @partial(jax.jit, static_argnames=("n",))
            def core(A, n):
                if n > 8:                # static_argnames: python value
                    A = A + 1
                if A.shape[0] > 4:       # shapes are static under trace
                    A = A * 2
                if A is None:            # identity check never traces
                    return A
                pad = np.zeros(A.shape)  # np over static shape: host-side
                return lax.cond(A.sum() > 0, lambda a: a, lambda a: -a, A)

            def host(A):
                if A.any():              # not a traced context at all
                    return float(A[0])
                return 0.0
        """,
    })
    assert _lint(root, "trace-safety").ok


# ---------------------------------------------------------------------------
# rule 5: pytree-safety
# ---------------------------------------------------------------------------


def test_pytree_safety_positive(tmp_path):
    root = _mini_repo(tmp_path, {
        "slate_tpu/x.py": """
            import enum
            import numpy as np
            import jax
            from dataclasses import dataclass

            class Option(enum.Enum):
                Schedule = 1

            def run(v):
                return jax.jit(lambda t: t)({Option.Schedule: v})

            @dataclass
            class Entry:
                factor: np.ndarray
        """,
    })
    res = _lint(root, "pytree-safety")
    msgs = " | ".join(f.message for f in res.findings)
    assert len(res.findings) == 2
    assert "Option.Schedule" in msgs
    assert "eq=False" in msgs


def test_pytree_safety_negative(tmp_path):
    root = _mini_repo(tmp_path, {
        "slate_tpu/x.py": """
            import enum
            import numpy as np
            import jax
            from dataclasses import dataclass

            class Option(enum.Enum):
                Schedule = 1

            def configure(opts):
                # enum-keyed dicts OUTSIDE jax are the options idiom
                return {Option.Schedule: "auto", **(opts or {})}

            @dataclass(eq=False)
            class Entry:
                factor: np.ndarray

            @jax.tree_util.register_pytree_node_class
            @dataclass
            class Pivots:
                perm: np.ndarray

                def tree_flatten(self):
                    return (self.perm,), None
        """,
    })
    assert _lint(root, "pytree-safety").ok


# ---------------------------------------------------------------------------
# rule 6: lock-discipline
# ---------------------------------------------------------------------------

_LOCKED_SRC = """
    import threading

    class Pool:
        def __init__(self):
            self._cond = threading.Condition()
            self.q = []  # guarded by: _cond

        def good(self):
            with self._cond:
                return len(self.q)

        def _drain_locked(self):
            return list(self.q)   # caller holds the lock (convention)

        def bad(self):
            return len(self.q)
"""


def test_lock_discipline_positive_and_exemptions(tmp_path):
    root = _mini_repo(tmp_path, {"slate_tpu/serve/svc.py": _LOCKED_SRC})
    res = _lint(root, "lock-discipline")
    assert len(res.findings) == 1
    # only the unlocked access in bad() fires — with-block, __init__,
    # and the _locked suffix are all exempt
    assert res.findings[0].line == textwrap.dedent(
        _LOCKED_SRC
    ).splitlines().index("        return len(self.q)") + 1


def test_lock_discipline_external_variant(tmp_path):
    root = _mini_repo(tmp_path, {
        "slate_tpu/serve/svc.py": """
            import threading

            class Queue:
                def __init__(self):
                    self._items = []  # guarded by: _lock (external)

                def pop(self):
                    return self._items.pop()  # internal: documented API

            class Owner:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.q = Queue()

                def ok(self):
                    with self._lock:
                        return self.q._items

                def racy(self):
                    return self.q._items
        """,
    })
    res = _lint(root, "lock-discipline")
    assert len(res.findings) == 1
    assert "racy" not in res.findings[0].message  # finding names the attr
    assert "_items" in res.findings[0].message


def test_lock_discipline_same_attr_under_two_guards(tmp_path):
    # one attribute NAME annotated in two classes with different locks:
    # holding either lock is clean, holding neither is one finding
    root = _mini_repo(tmp_path, {
        "slate_tpu/serve/svc.py": """
            import threading

            class A:
                def __init__(self):
                    self._la = threading.Lock()
                    self.state = 0  # guarded by: _la

            class B:
                def __init__(self):
                    self._lb = threading.Lock()
                    self.state = 0  # guarded by: _lb

            def ok_a(a):
                with a._la:
                    return a.state

            def ok_b(b):
                with b._lb:
                    return b.state

            def racy(x):
                return x.state
        """,
    })
    res = _lint(root, "lock-discipline")
    assert len(res.findings) == 1
    assert "_la/_lb" in res.findings[0].message


def test_lock_discipline_local_variable_comment_registers_nothing(tmp_path):
    # a "guarded by" comment on a method-LOCAL variable is not an
    # attribute annotation — it must not police same-named attributes
    root = _mini_repo(tmp_path, {
        "slate_tpu/serve/svc.py": """
            class C:
                def m(self):
                    level = 0  # guarded by: _lock
                    return level

            def reader(x):
                return x.level
        """,
    })
    assert _lint(root, "lock-discipline").ok


def test_lock_discipline_negative_unannotated(tmp_path):
    root = _mini_repo(tmp_path, {
        "slate_tpu/serve/svc.py": """
            class Pool:
                def __init__(self):
                    self.q = []

                def free(self):
                    return len(self.q)   # nothing declared: no findings
        """,
    })
    assert _lint(root, "lock-discipline").ok


# ---------------------------------------------------------------------------
# rule 7: env-drift
# ---------------------------------------------------------------------------


def test_env_drift_both_directions(tmp_path):
    root = _mini_repo(tmp_path, {
        "slate_tpu/x.py": """
            import os
            a = os.environ.get("SLATE_TPU_DOCUMENTED")
            b = os.environ.get("SLATE_TPU_SECRET_KNOB")
        """,
    }, readme="""
        | `SLATE_TPU_DOCUMENTED=1` | does things |
        | `SLATE_TPU_ZOMBIE=1` | no longer exists |
    """)
    res = _lint(root, "env-drift")
    msgs = {f.message.split(" ")[0] for f in res.findings}
    assert msgs == {"SLATE_TPU_SECRET_KNOB", "README"} or len(res.findings) == 2
    texts = " | ".join(f.message for f in res.findings)
    assert "SLATE_TPU_SECRET_KNOB" in texts
    assert "SLATE_TPU_ZOMBIE" in texts


def test_env_drift_negative(tmp_path):
    root = _mini_repo(tmp_path, {
        "slate_tpu/x.py": """
            import os
            a = os.environ.get("SLATE_TPU_KNOB")
        """,
    }, readme="`SLATE_TPU_KNOB=1` documented here.\n")
    assert _lint(root, "env-drift").ok


# ---------------------------------------------------------------------------
# rule 8: exception-context
# ---------------------------------------------------------------------------

_EXC_COMMON = """
            class SlateError(Exception):
                def with_context(self, **kw):
                    return self

            class Rejected(SlateError):
                pass
"""


def test_exception_context_positive(tmp_path):
    root = _mini_repo(tmp_path, {
        "slate_tpu/serve/svc.py": _EXC_COMMON + """
            def submit(routine):
                raise Rejected("queue full")
        """,
    })
    res = _lint(root, "exception-context")
    assert len(res.findings) == 1
    assert "Rejected" in res.findings[0].message


def test_exception_context_negative(tmp_path):
    root = _mini_repo(tmp_path, {
        "slate_tpu/serve/svc.py": _EXC_COMMON + """
            def submit(routine):
                raise Rejected("queue full").with_context(routine=routine)

            def passthrough(e):
                raise e                      # re-raise keeps its context

            def config_error():
                raise ValueError("not a SlateError: out of scope")

            class Svc:
                def __init__(self, mesh):
                    # construction-time config errors carry no request
                    raise Rejected(f"bad mesh {mesh}")
        """,
    })
    assert _lint(root, "exception-context").ok


# ---------------------------------------------------------------------------
# suppression + baseline semantics
# ---------------------------------------------------------------------------


def test_inline_suppression(tmp_path):
    root = _mini_repo(tmp_path, {
        "slate_tpu/serve/svc.py": """
            import threading

            class Pool:
                def __init__(self):
                    self._cond = threading.Condition()
                    self.q = []  # guarded by: _cond

                def racy(self):
                    # deliberate: depth probe tolerates a torn read
                    return len(self.q)  # slate-lint: disable=lock-discipline
        """,
    })
    res = _lint(root, "lock-discipline")
    assert res.ok
    assert res.suppressed == 1


def test_suppression_is_per_rule(tmp_path):
    root = _mini_repo(tmp_path, {
        "slate_tpu/serve/svc.py": """
            import threading

            class Pool:
                def __init__(self):
                    self._cond = threading.Condition()
                    self.q = []  # guarded by: _cond

                def racy(self):
                    return len(self.q)  # slate-lint: disable=env-drift
        """,
    })
    res = _lint(root, "lock-discipline")
    assert len(res.findings) == 1  # wrong rule name: not silenced


def test_baseline_accepts_legacy_and_catches_new(tmp_path):
    files = {
        "slate_tpu/serve/svc.py": _EXC_COMMON + """
            def submit(routine):
                raise Rejected("legacy")
        """,
    }
    root = _mini_repo(tmp_path, files)
    first = _lint(root, "exception-context")
    assert len(first.findings) == 1

    bl_path = os.path.join(root, analysis.BASELINE_NAME)
    analysis.write_baseline(bl_path, first)
    baseline = analysis.load_baseline(bl_path)
    again = analysis.run(root, rules=["exception-context"],
                         baseline=baseline)
    assert again.ok and again.baselined == 1

    # a NEW violation still fails even with the old baseline loaded
    with open(os.path.join(root, "slate_tpu/serve/svc.py"), "a") as f:
        f.write("\n\ndef submit2(routine):\n"
                "    raise Rejected('new one')\n")
    newrun = analysis.run(root, rules=["exception-context"],
                          baseline=baseline)
    assert len(newrun.findings) == 1 and newrun.baselined == 1
    assert "new one" in open(
        os.path.join(root, "slate_tpu/serve/svc.py")).read()


def test_baseline_does_not_grandfather_identical_duplicates(tmp_path):
    # fingerprints carry an occurrence ordinal: baselining one
    # copy-paste instance must not silently accept a second identical
    # line added later in the same file
    root = _mini_repo(tmp_path, {
        "slate_tpu/serve/svc.py": _EXC_COMMON + """
            def submit(routine):
                raise Rejected("dup")
        """,
    })
    first = _lint(root, "exception-context")
    bl_path = os.path.join(root, analysis.BASELINE_NAME)
    analysis.write_baseline(bl_path, first)
    with open(os.path.join(root, "slate_tpu/serve/svc.py"), "a") as f:
        f.write("\n\ndef submit2(routine):\n"
                "    raise Rejected(\"dup\")\n")  # byte-identical line
    again = analysis.run(root, rules=["exception-context"],
                         baseline=analysis.load_baseline(bl_path))
    assert again.baselined == 1
    assert len(again.findings) == 1  # the clone is NEW, not baselined


def test_baseline_fingerprint_survives_line_shift(tmp_path):
    root = _mini_repo(tmp_path, {
        "slate_tpu/serve/svc.py": _EXC_COMMON + """
            def submit(routine):
                raise Rejected("legacy")
        """,
    })
    first = _lint(root, "exception-context")
    bl_path = os.path.join(root, analysis.BASELINE_NAME)
    analysis.write_baseline(bl_path, first)
    # shift the file down: the fingerprint is line-number free
    p = os.path.join(root, "slate_tpu/serve/svc.py")
    src = open(p).read()
    with open(p, "w") as f:
        f.write("# a new comment line\n# another\n" + src)
    again = analysis.run(root, rules=["exception-context"],
                         baseline=analysis.load_baseline(bl_path))
    assert again.ok and again.baselined == 1


# ---------------------------------------------------------------------------
# output formats + engine behavior
# ---------------------------------------------------------------------------


def test_json_schema(tmp_path):
    root = _mini_repo(tmp_path, {
        "slate_tpu/serve/svc.py": _EXC_COMMON + """
            def submit(routine):
                raise Rejected("oops")
        """,
    })
    res = _lint(root, "exception-context")
    doc = res.to_json()
    assert doc["version"] == 1 and doc["ok"] is False
    assert doc["counts"] == {"new": 1, "baselined": 0, "suppressed": 0}
    (f,) = doc["findings"]
    assert set(f) == {"rule", "path", "line", "col", "message",
                      "fingerprint"}
    assert f["rule"] == "exception-context"
    assert f["path"] == "slate_tpu/serve/svc.py"
    assert isinstance(f["line"], int) and f["line"] > 0
    assert len(f["fingerprint"]) == 16
    json.dumps(doc)  # round-trippable


def test_syntax_error_is_a_finding_not_a_crash(tmp_path):
    root = _mini_repo(tmp_path, {
        "slate_tpu/broken.py": "def oops(:\n",
        "slate_tpu/fine.py": "x = 1\n",
    })
    res = analysis.run(root)
    assert any(f.rule == "parse-error" for f in res.findings)


def test_cli_list_and_clean_exit():
    import subprocess

    out = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools", "slate_lint.py"),
         "--list"],
        capture_output=True, text=True,
    )
    assert out.returncode == 0
    for name in ("metric-drift", "lock-discipline", "env-drift"):
        assert name in out.stdout


# ---------------------------------------------------------------------------
# the self-run: the shipped tree is clean, fast, with an empty baseline
# ---------------------------------------------------------------------------


def test_shipped_tree_is_clean():
    baseline = analysis.load_baseline(
        os.path.join(REPO_ROOT, analysis.BASELINE_NAME)
    )
    assert baseline == set(), (
        "the shipped baseline must stay empty: fix or suppress new "
        "findings instead of grandfathering them"
    )
    res = analysis.run(REPO_ROOT, baseline=baseline)
    assert res.files > 100  # the full tree was actually discovered
    assert res.ok, "\n" + res.render()


def test_shipped_tree_lint_runtime_budget():
    res = analysis.run(REPO_ROOT)
    # the run_tests.py --lint budget is 15 s on the 2-core CI box; the
    # suite asserts a looser bound so a slow box doesn't flake tier-1
    assert res.duration_s < 30.0, f"lint took {res.duration_s:.1f}s"
