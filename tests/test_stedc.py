"""Native tridiagonal divide & conquer (ops/stedc.py) — the stedc
redesign (reference: src/stedc*.cc).  Checks eigenvalues against the
vendor eigensolver and verifies residual + orthogonality on adversarial
spectra (clusters, degenerate matrices, scaled problems)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from slate_tpu.ops.stedc import stedc


def _check(d, e, wtol=5e-13, vtol=5e-12):
    d = jnp.asarray(d, jnp.float64)
    e = jnp.asarray(e, jnp.float64)
    n = d.shape[0]
    w, Q = jax.jit(stedc)(d, e)
    T = (
        np.diag(np.asarray(d))
        + np.diag(np.asarray(e), 1)
        + np.diag(np.asarray(e), -1)
    )
    wref = np.linalg.eigvalsh(T)
    scale = max(np.abs(wref).max(), 1e-30)
    assert np.abs(np.asarray(w) - wref).max() / scale < wtol
    Qn = np.asarray(Q)
    res = np.abs(T @ Qn - Qn * np.asarray(w)[None, :]).max() / scale
    assert res < vtol
    orth = np.abs(Qn.T @ Qn - np.eye(n)).max()
    assert orth < vtol


@pytest.mark.parametrize(
    "n",
    [1, 2, 3, 5, 16,
     # big merge trees: each n pays its own stedc jit compile
     # (minutes-scale dominance on the 2-core tier-1 box; n=64 was
     # 12.6 s of tier-1 wall — the small sizes keep the routing and
     # merge coverage)
     pytest.param(64, marks=pytest.mark.slow),
     pytest.param(100, marks=pytest.mark.slow),
     pytest.param(257, marks=pytest.mark.slow)],
)
def test_random(n):
    rng = np.random.default_rng(n)
    _check(rng.standard_normal(n), rng.standard_normal(max(n - 1, 0)))


@pytest.mark.slow
def test_toeplitz():
    _check(np.zeros(96), np.ones(95))


def test_identity():
    _check(np.ones(64), np.zeros(63))


def test_near_identity():
    _check(np.ones(64), 1e-14 * np.ones(63))


def test_wilkinson():
    m = 10
    _check(np.abs(np.arange(-m, m + 1)).astype(float), np.ones(2 * m))


@pytest.mark.slow
def test_glued_wilkinson():
    m = 10
    dw = np.abs(np.arange(-m, m + 1)).astype(float)
    dg = np.concatenate([dw] * 4)
    eg = np.ones(len(dg) - 1)
    eg[len(dw) - 1 :: len(dw)] = 1e-8
    _check(dg, eg[: len(dg) - 1])


def test_clustered():
    rng = np.random.default_rng(7)
    _check(np.repeat(rng.standard_normal(8), 8), 1e-13 * rng.standard_normal(63))


@pytest.mark.slow
def test_scaled_tiny():
    rng = np.random.default_rng(3)
    _check(1e-20 * rng.standard_normal(48), 1e-20 * rng.standard_normal(47))


@pytest.mark.slow
def test_mixed_scale():
    rng = np.random.default_rng(5)
    d = np.concatenate([1e8 * np.ones(24), 1e-8 * np.ones(24)])
    _check(d * rng.standard_normal(48), rng.standard_normal(47))


@pytest.mark.slow
def test_driver_steqr_routes_to_dc():
    # slow: 22.5 s of tier-1 wall on the 2-core box (driver-level
    # steqr compile); stedc routing itself stays covered by the
    # tier-1 test_random sizes above
    from slate_tpu.drivers.eig import steqr

    rng = np.random.default_rng(11)
    d = jnp.asarray(rng.standard_normal(24))
    e = jnp.asarray(rng.standard_normal(23))
    w, Z = steqr(d, e, vectors=True)
    T = np.diag(np.asarray(d)) + np.diag(np.asarray(e), 1) + np.diag(np.asarray(e), -1)
    assert np.allclose(np.asarray(T @ Z), np.asarray(Z * w[None, :]), atol=1e-11)
