"""Race & deadlock detection plane: static rule fixtures
(race-guarded-by, race-lock-order), the lock-order graph artifact, and
the dynamic sync runtime (lockset checker, inversion detection with
both stacks, Condition hand-off regression, zero-overhead off).

The static halves are stdlib-only (no jax); the dynamic halves use
plain threads against ``aux/sync`` directly, so the whole file runs in
milliseconds inside tier-1.
"""

import json
import os
import sys
import textwrap
import threading

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from slate_tpu import analysis
from slate_tpu.analysis import core, races
from slate_tpu.aux import sync

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mini_repo(tmp_path, files, readme=None):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    if readme is not None:
        (tmp_path / "README.md").write_text(textwrap.dedent(readme))
    return str(tmp_path)


def _lint(root, rule):
    return analysis.run(root, rules=[rule])


@pytest.fixture(autouse=True)
def _sync_teardown():
    yield
    sync.reset()


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


def test_registry_ships_the_race_rules():
    for name in ("race-guarded-by", "race-lock-order"):
        assert name in analysis.RULES
        r = analysis.RULES[name]
        assert r.summary and r.bug


def test_json_report_carries_schema_version(tmp_path):
    root = _mini_repo(tmp_path, {"slate_tpu/mod.py": "x = 1\n"})
    doc = analysis.run(root).to_json()
    assert doc["schema_version"] == 2
    assert doc["version"] == 1  # legacy field stays


# ---------------------------------------------------------------------------
# race-guarded-by: _locked call discipline
# ---------------------------------------------------------------------------

_SVC_FIXTURE = """
    class Service:
        def __init__(self):
            self._cond = threading.Condition()
            self.q = []  # guarded by: _cond

        def _pop_locked(self):
            return self.q.pop()

        def good(self):
            with self._cond:
                return self._pop_locked()

        def bad(self):
            return self._pop_locked()
"""


def test_locked_call_without_lock_flagged(tmp_path):
    root = _mini_repo(tmp_path, {"slate_tpu/serve/svc.py": _SVC_FIXTURE})
    res = _lint(root, "race-guarded-by")
    assert len(res.findings) == 1
    f = res.findings[0]
    assert "_pop_locked" in f.message and "_cond" in f.message
    # the flagged line is the UNLOCKED call, not the locked one
    assert "return self._pop_locked()" in open(
        os.path.join(root, f.path)
    ).read().splitlines()[f.line - 1]


def test_locked_call_chain_and_init_exempt(tmp_path):
    root = _mini_repo(tmp_path, {
        "slate_tpu/serve/svc.py": """
            class Service:
                def __init__(self):
                    self._cond = threading.Condition()
                    self.q = []  # guarded by: _cond
                    self._pop_locked()  # construction precedes sharing

                def _pop_locked(self):
                    return self.q.pop()

                def _sweep_locked(self):
                    return self._pop_locked()  # caller-holds propagates

                def run(self):
                    with self._cond:
                        return self._sweep_locked()
        """,
    })
    assert _lint(root, "race-guarded-by").ok


def test_locked_transitive_requirement(tmp_path):
    # _outer_locked touches nothing itself but calls _pop_locked —
    # the requirement propagates, so the unlocked caller is flagged
    root = _mini_repo(tmp_path, {
        "slate_tpu/serve/svc.py": """
            class Service:
                def __init__(self):
                    self._cond = threading.Condition()
                    self.q = []  # guarded by: _cond

                def _pop_locked(self):
                    return self.q.pop()

                def _outer_locked(self):
                    return self._pop_locked()

                def bad(self):
                    return self._outer_locked()
        """,
    })
    res = _lint(root, "race-guarded-by")
    assert len(res.findings) == 1
    assert "_outer_locked" in res.findings[0].message


def test_locked_mutual_recursion_order_independent(tmp_path):
    # _a_locked (touches a guarded field) and _b_locked call each
    # other.  The good caller of _a_locked is checked FIRST, so
    # _b_locked's requirements are first computed inside _a_locked's
    # traversal under the recursion cut — memoizing that truncated
    # result would let the later unlocked _b_locked call slip through
    root = _mini_repo(tmp_path, {
        "slate_tpu/serve/svc.py": """
            class Service:
                def __init__(self):
                    self._cond = threading.Condition()
                    self.q = []  # guarded by: _cond

                def _a_locked(self, again=False):
                    self.q.pop()
                    if again:
                        return self._b_locked()

                def _b_locked(self):
                    return self._a_locked()

                def good(self):
                    with self._cond:
                        return self._a_locked()

                def bad(self):
                    return self._b_locked()
        """,
    })
    res = _lint(root, "race-guarded-by")
    assert len(res.findings) == 1
    f = res.findings[0]
    assert "_b_locked" in f.message and "_cond" in f.message


# ---------------------------------------------------------------------------
# race-guarded-by: cross-module fields
# ---------------------------------------------------------------------------

_CTRL = """
    class Controller:
        def __init__(self):
            self._lock = threading.Lock()
            self.level = 0  # guarded by: _lock
"""


def test_cross_module_access_flagged(tmp_path):
    root = _mini_repo(tmp_path, {
        "slate_tpu/serve/ctrl.py": _CTRL,
        "slate_tpu/serve/svc.py": """
            def peek(ctrl):
                return ctrl.level
        """,
    })
    res = _lint(root, "race-guarded-by")
    assert len(res.findings) == 1
    f = res.findings[0]
    assert f.path == "slate_tpu/serve/svc.py"
    assert "'level'" in f.message and "ctrl.py" in f.message


def test_cross_module_access_under_lock_ok(tmp_path):
    root = _mini_repo(tmp_path, {
        "slate_tpu/serve/ctrl.py": _CTRL,
        "slate_tpu/serve/svc.py": """
            def peek(ctrl):
                with ctrl._lock:
                    return ctrl.level
        """,
    })
    assert _lint(root, "race-guarded-by").ok


def test_ambiguous_attr_not_resolved_cross_module(tmp_path):
    # a second class defines `level` WITHOUT a guard: the name is
    # unresolvable project-wide, so cross-module checking stands down
    # (the intraprocedural rule stays the fallback)
    root = _mini_repo(tmp_path, {
        "slate_tpu/serve/ctrl.py": _CTRL,
        "slate_tpu/serve/other.py": """
            class Gauge:
                def __init__(self):
                    self.level = 0.0  # plain, unguarded
        """,
        "slate_tpu/serve/svc.py": """
            def peek(ctrl):
                return ctrl.level
        """,
    })
    assert _lint(root, "race-guarded-by").ok


def test_suppression_applies_to_race_rule(tmp_path):
    root = _mini_repo(tmp_path, {
        "slate_tpu/serve/ctrl.py": _CTRL,
        "slate_tpu/serve/svc.py": """
            def peek(ctrl):
                # deliberately racy fast-path read
                return ctrl.level  # slate-lint: disable=race-guarded-by
        """,
    })
    res = _lint(root, "race-guarded-by")
    assert res.ok and res.suppressed == 1


# ---------------------------------------------------------------------------
# race-lock-order: the static graph
# ---------------------------------------------------------------------------

_INVERTED = """
    import threading

    a = threading.Lock()
    b = threading.Lock()

    def one():
        with a:
            with b:
                pass

    def two():
        with b:
            with a:
                pass
"""


def test_lock_order_cycle_flagged(tmp_path):
    root = _mini_repo(tmp_path, {"slate_tpu/serve/locks.py": _INVERTED})
    res = _lint(root, "race-lock-order")
    assert len(res.findings) == 1
    assert "cycle" in res.findings[0].message


def test_lock_order_nested_without_cycle_ok(tmp_path):
    root = _mini_repo(tmp_path, {
        "slate_tpu/serve/locks.py": """
            import threading

            a = threading.Lock()
            b = threading.Lock()

            def one():
                with a:
                    with b:
                        pass
        """,
    })
    assert _lint(root, "race-lock-order").ok


def test_lock_order_call_through_edge(tmp_path):
    # the edge exists even though no `with` nests lexically: the call
    # made under `a` acquires `b` inside the callee
    root = _mini_repo(tmp_path, {
        "slate_tpu/serve/locks.py": """
            import threading

            a = threading.Lock()
            b = threading.Lock()

            def inner():
                with b:
                    pass

            def outer():
                with a:
                    inner()

            def inverted():
                with b:
                    with a:
                        pass
        """,
    })
    res = _lint(root, "race-lock-order")
    assert len(res.findings) == 1
    assert "cycle" in res.findings[0].message


def test_lock_order_new_edge_vs_artifact(tmp_path):
    root = _mini_repo(tmp_path, {
        "slate_tpu/serve/locks.py": """
            import threading

            a = threading.Lock()
            b = threading.Lock()

            def one():
                with a:
                    with b:
                        pass
        """,
    })
    # artifact with no edges: the tree's edge is NEW -> finding
    with open(os.path.join(root, races.LOCK_GRAPH_NAME), "w") as fh:
        json.dump({"version": 1, "edges": []}, fh)
    res = _lint(root, "race-lock-order")
    assert len(res.findings) == 1
    assert "new lock-order edge" in res.findings[0].message
    # regenerating the artifact clears it
    loaded = core.load_project(root)
    races.write_graph_artifact(root, loaded.project)
    assert _lint(root, "race-lock-order").ok


def test_lock_order_stale_artifact_edge(tmp_path):
    root = _mini_repo(tmp_path, {
        "slate_tpu/serve/locks.py": "import threading\n",
    })
    with open(os.path.join(root, races.LOCK_GRAPH_NAME), "w") as fh:
        json.dump({"version": 1, "edges": [
            {"from": "ghost.a", "to": "ghost.b", "via": "gone.py:1"},
        ]}, fh)
    res = _lint(root, "race-lock-order")
    assert len(res.findings) == 1
    assert "no longer" in res.findings[0].message


# ---------------------------------------------------------------------------
# the shipped tree
# ---------------------------------------------------------------------------


def test_shipped_tree_graph_acyclic_and_artifact_fresh():
    loaded = core.load_project(REPO_ROOT)
    edges = races.lock_graph(loaded.project)
    assert edges, "the serve tier has nested lock regions; none found"
    assert races.graph_cycles(edges) == []
    known = races.load_graph_artifact(REPO_ROOT)
    assert known is not None, "LOCK_ORDER.json missing at the repo root"
    assert known == set(edges), (
        "LOCK_ORDER.json out of sync with the tree — regenerate with "
        "tools/slate_lint.py --write-lock-graph after reviewing the "
        f"diff: new={sorted(set(edges) - known)} "
        f"stale={sorted(known - set(edges))}"
    )


def test_shipped_tree_clean_under_race_rules():
    res = analysis.run(
        REPO_ROOT, rules=["race-guarded-by", "race-lock-order"]
    )
    assert res.ok, res.render()


def test_shipped_graph_carries_the_call_through_edges():
    # the edges that motivated the whole-program pass: no `with` nests
    # these lexically — they exist only through calls made under _cond
    loaded = core.load_project(REPO_ROOT)
    edges = set(races.lock_graph(loaded.project))
    assert (
        "serve/service.SolverService._cond",
        "serve/admission.AdmissionControl._lock",
    ) in edges
    assert (
        "serve/service.SolverService._cond",
        "integrity/policy.IntegrityScore._lock",
    ) in edges


# ---------------------------------------------------------------------------
# dynamic: the sync runtime
# ---------------------------------------------------------------------------


def test_sync_off_returns_plain_primitives():
    assert not sync.is_on()
    assert type(sync.Lock()) is type(threading.Lock())
    assert type(sync.RLock()) is type(threading.RLock())
    assert isinstance(sync.Condition(), threading.Condition)
    sync.guarded(object(), "x")  # no-op
    sync.hb_publish(object())  # no-op
    assert sync.violations() == []


def test_sync_configure_grammar():
    assert sync.configure("1,seed=3,yield=0.5,yield_us=10") is True
    assert sync.is_on()
    sync.reset()
    assert not sync.is_on()
    assert sync.configure("0") is False
    with pytest.raises(ValueError):
        sync.configure("banana")
    with pytest.raises(ValueError):
        sync.configure("1,yield=2.0")
    with pytest.raises(ValueError):
        sync.configure("1,bogus=1")


def test_deadlock_inversion_reported_with_both_stacks():
    """The deterministic deadlock-reproduction fixture: two locks,
    inverted order, sequenced threads (records both orders without
    actually deadlocking) — the detector must report the inversion
    with BOTH stacks well before any watchdog would fire."""
    sync.configure("1,seed=0")
    A = sync.Lock(name="fix.A")
    B = sync.Lock(name="fix.B")

    def t1():
        with A:
            with B:
                pass

    def t2():
        with B:
            with A:
                pass

    th = threading.Thread(target=t1)
    th.start()
    th.join(5.0)
    th = threading.Thread(target=t2)
    th.start()
    th.join(5.0)
    v = [x for x in sync.violations() if x["kind"] == "lock_order"]
    assert len(v) == 1
    assert sorted(v[0]["locks"]) == ["fix.A", "fix.B"]
    s1, s2 = v[0]["stacks"]
    assert s1 and s2 and s1 != s2  # both halves of the inversion
    assert "t1" in s1 and "t2" in s2  # ...and they name the two sites
    # the runtime graph carries both orders
    pairs = {(e["from"], e["to"]) for e in sync.order_edges()}
    assert ("fix.A", "fix.B") in pairs and ("fix.B", "fix.A") in pairs


def test_unguarded_write_caught():
    sync.configure("1")

    class Shared:
        pass

    s = Shared()
    s.hits = 0

    def writer():
        sync.guarded(s, "hits")
        s.hits += 1

    th = threading.Thread(target=writer)
    th.start()
    th.join(5.0)
    sync.guarded(s, "hits")
    s.hits += 1
    v = [x for x in sync.violations() if x["kind"] == "lockset"]
    assert len(v) == 1
    assert "Shared.hits" in v[0]["field"]
    assert len(v[0]["stacks"]) == 2


def test_consistent_lock_keeps_lockset_clean():
    sync.configure("1")
    L = sync.Lock(name="fix.L")

    class Shared:
        pass

    s = Shared()

    def toucher():
        with L:
            sync.guarded(s, "n")
            s.n = 1

    for _ in range(3):
        th = threading.Thread(target=toucher)
        th.start()
        th.join(5.0)
    assert sync.violations() == []


def test_condition_handoff_does_not_false_positive():
    """Regression: the publish-under-notify / read-after-wait hand-off
    (the service's enqueue -> worker pattern, and the chaos tests'
    future plumbing) must NOT trip the lockset checker — the
    happens-before edge through Condition wait/notify orders the two
    lock-free accesses."""
    sync.configure("1")
    cond = sync.Condition(name="fix.cond")

    class Box:
        pass

    box = Box()
    ready = []

    def producer():
        sync.guarded(box, "payload")
        box.payload = 42  # lock-free publish...
        with cond:
            ready.append(1)
            cond.notify_all()  # ...sequenced before the notify

    def consumer():
        with cond:
            while not ready:
                cond.wait(5.0)
        sync.guarded(box, "payload")  # lock-free read after wait
        assert box.payload == 42

    tc = threading.Thread(target=consumer)
    tc.start()
    tp = threading.Thread(target=producer)
    tp.start()
    tp.join(5.0)
    tc.join(5.0)
    assert sync.violations() == [], sync.violations()


def test_condition_handoff_predicate_already_true_no_false_positive():
    """Regression: a consumer that finds its predicate ALREADY true
    never calls wait(), so the hand-off must also be received at
    Condition acquire — notify runs under the lock, so any publish
    visible there is lock-ordered before the consumer."""
    sync.configure("1")
    cond = sync.Condition(name="fix.cond2")

    class Box:
        pass

    box = Box()
    ready = []

    def producer():
        sync.guarded(box, "payload")
        box.payload = 7
        with cond:
            ready.append(1)
            cond.notify_all()

    tp = threading.Thread(target=producer)
    tp.start()
    tp.join(5.0)
    # the producer fully finished: the consumer's predicate is true on
    # entry and wait() never runs
    with cond:
        while not ready:  # pragma: no cover - predicate already true
            cond.wait(5.0)
    sync.guarded(box, "payload")
    assert box.payload == 7
    assert sync.violations() == [], sync.violations()


def test_configure_plain_resets_stale_tuning():
    # "1" means DEFAULTS: a previous configure's perturbation tuning
    # must not leak into a later plain arming in the same process
    sync.configure("1,seed=7,yield=0.2,yield_us=50")
    assert sync.report()["seed"] == 7 and sync.report()["yield_p"] == 0.2
    sync.reset()
    sync.configure("1")
    rep = sync.report()
    assert rep["seed"] == 0 and rep["yield_p"] == 0.0


def test_dead_object_field_state_invalidated_for_id_reuse():
    """Regression: a short-lived probed object (a hedge group per
    straggler clone) dies and CPython reuses its address — the stale
    field state, lockset refined to the DEAD object's lock, would
    empty-intersect the new object's lock and report a false
    positive.  The weakref death callback queues the key and the next
    probe drains it."""
    sync.configure("1")
    L = sync.Lock(name="fix.L2")

    class Shared:
        pass

    s = Shared()
    with L:
        sync.guarded(s, "n")
    key = (id(s), "n")
    assert key in sync._fields
    del s  # CPython: refcount zero fires the weakref callback now
    assert key in sync._dead
    other = Shared()  # frequently lands on the reused address
    with L:
        sync.guarded(other, "n")  # the probe drains the queue first
    assert not sync._dead
    assert sync.violations() == []


def test_report_lists_probed_field_names():
    sync.configure("1")

    class Box:
        pass

    b = Box()
    sync.guarded(b, "n")
    assert "Box.n" in sync.report()["field_names"]


def test_future_style_handoff_via_hb_publish_receive():
    sync.configure("1")

    class Box:
        pass

    box = Box()
    token = object()

    def worker():
        sync.guarded(box, "result")
        box.result = "X"
        sync.hb_publish(token)

    th = threading.Thread(target=worker)
    th.start()
    th.join(5.0)
    sync.hb_receive(token)
    sync.guarded(box, "result")  # ordered: no violation
    assert box.result == "X"
    assert sync.violations() == []


def test_rlock_reentrancy_no_self_edge():
    sync.configure("1")
    R = sync.RLock(name="fix.R")
    with R:
        with R:
            pass
    assert sync.order_edges() == []
    assert sync.violations() == []


def test_dump_roundtrip(tmp_path):
    sync.configure("1,seed=9")
    A = sync.Lock(name="fix.A2")
    B = sync.Lock(name="fix.B2")
    with A:
        with B:
            pass
    path = sync.dump(str(tmp_path / "sync.json"))
    doc = json.load(open(path))
    assert doc["seed"] == 9
    assert {(e["from"], e["to"]) for e in doc["edges"]} == {
        ("fix.A2", "fix.B2")
    }
    assert doc["violations"] == []
