"""Matrix generator tests (reference: matgen/ kinds + kind grammar)."""

import numpy as np
import pytest

from slate_tpu.exceptions import SlateError
from slate_tpu.matgen.generate import generate_2d, generate_matrix, parse_kind
from slate_tpu.matrix.matrix import Matrix


def G(kind, m=16, n=16, **kw):
    A, S = generate_2d(kind, m, n, **kw)
    return np.asarray(A), (None if S is None else np.asarray(S))


class TestSpecialKinds:
    def test_identity_zeros_ones(self):
        assert np.array_equal(G("identity")[0], np.eye(16))
        assert np.array_equal(G("zeros")[0], np.zeros((16, 16)))
        assert np.array_equal(G("ones")[0], np.ones((16, 16)))

    def test_jordan(self):
        A, _ = G("jordan", 4, 4)
        assert np.array_equal(
            A, np.eye(4) + np.diag(np.ones(3), 1)
        )
        At, _ = G("jordanT", 4, 4)
        assert np.array_equal(At, A.T)

    def test_minij_hilb_lehmer(self):
        A, _ = G("minij", 5, 5)
        i, j = np.meshgrid(range(5), range(5), indexing="ij")
        assert np.array_equal(A, np.minimum(i, j) + 1)
        H, _ = G("hilb", 5, 5)
        np.testing.assert_allclose(H, 1.0 / (i + j + 1))
        L, _ = G("lehmer", 5, 5)
        np.testing.assert_allclose(L, (np.minimum(i, j) + 1) / (np.maximum(i, j) + 1))

    def test_tridiag_clement_toeppen(self):
        T, _ = G("tridiag", 6, 6)
        assert np.array_equal(T, 2 * np.eye(6) - np.eye(6, k=1) - np.eye(6, k=-1))
        C, _ = G("clement", 4, 4)
        # i-j==1 -> mx-j-1; i-j==-1 -> j
        assert C[1, 0] == 3 and C[0, 1] == 1 and C[2, 2] == 0

    def test_gcdmat_riemann_redheff(self):
        A, _ = G("gcdmat", 6, 6)
        assert A[3, 5] == np.gcd(4, 6)
        R, _ = G("redheff", 6, 6)
        assert R[0, 0] == 1 and R[2, 5] == 1 and R[2, 4] == 0

    def test_orthog_is_orthogonal(self):
        Q, _ = G("orthog", 12, 12)
        np.testing.assert_allclose(Q @ Q.T, np.eye(12), atol=1e-12)

    def test_kms_pei_fiedler_circul(self):
        K, _ = G("kms", 5, 5)
        np.testing.assert_allclose(K[0, 3], 0.5**3)
        P, _ = G("pei", 3, 3)
        assert np.array_equal(P, np.ones((3, 3)) + np.eye(3))
        F, _ = G("fiedler", 4, 4)
        assert F[0, 3] == 3
        Ci, _ = G("circul", 4, 4)
        assert Ci[0, 0] == 1 and Ci[3, 0] == 2  # wraps


class TestRandomKinds:
    def test_rand_reproducible(self):
        A1, _ = G("rand", seed=7)
        A2, _ = G("rand", seed=7)
        assert np.array_equal(A1, A2)
        A3, _ = G("rand", seed=8)
        assert not np.array_equal(A1, A3)

    def test_rands_range(self):
        A, _ = G("rands", 64, 64)
        assert A.min() < 0 < A.max() and np.abs(A).max() <= 1

    def test_randb_binary(self):
        A, _ = G("randb", 32, 32)
        assert set(np.unique(A)) <= {0.0, 1.0}

    def test_rand_dominant(self):
        A, _ = G("rand_dominant", 16, 16)
        for i in range(16):
            off = np.abs(A[i]).sum() - np.abs(A[i, i])
            assert np.abs(A[i, i]) >= off - 1e-10

    def test_zerocol(self):
        A, _ = G("rand_zerocol3", 8, 8)
        assert np.all(A[:, 3] == 0)
        A, _ = G("rand_zerocol0.5", 8, 8)
        assert np.all(A[:, 3] == 0)  # 0.5 * (8-1) = 3


class TestSpectrumKinds:
    def test_svd_singular_values(self):
        A, S = G("svd_geo", 24, 24, cond=100.0)
        sv = np.linalg.svd(A, compute_uv=False)
        np.testing.assert_allclose(sorted(sv), sorted(np.abs(S)), rtol=1e-10)
        np.testing.assert_allclose(sv.max() / sv.min(), 100.0, rtol=1e-8)

    def test_heev_eigenvalues(self):
        A, S = G("heev_arith", 20, 20, cond=50.0)
        np.testing.assert_allclose(A, A.T.conj(), atol=1e-12)
        ev = np.linalg.eigvalsh(A)
        np.testing.assert_allclose(sorted(ev), sorted(np.asarray(S)), atol=1e-10)

    def test_poev_positive(self):
        A, S = G("poev_logrand", 20, 20, cond=10.0)
        ev = np.linalg.eigvalsh(A)
        assert ev.min() > 0
        assert (np.asarray(S) > 0).all()

    def test_geev_spectrum(self):
        A, S = G("geev_arith", 16, 16, cond=10.0)
        ev = np.linalg.eigvals(A)
        np.testing.assert_allclose(sorted(ev.real), sorted(np.asarray(S)), atol=1e-8)

    def test_diag(self):
        A, S = G("diag_arith", 10, 10, cond=4.0)
        np.testing.assert_allclose(np.diag(A), np.asarray(S))
        assert np.abs(A - np.diag(np.diag(A))).max() == 0

    def test_rectangular_svd(self):
        A, S = G("svd_geo", 30, 18, cond=10.0)
        assert A.shape == (30, 18) and S.shape == (18,)
        sv = np.linalg.svd(A, compute_uv=False)
        np.testing.assert_allclose(sorted(sv), sorted(np.abs(S)), rtol=1e-9)

    def test_complex_heev(self):
        A, S = G("heev_geo", 16, 16, dtype=np.complex128, cond=10.0)
        np.testing.assert_allclose(A, A.conj().T, atol=1e-12)
        ev = np.linalg.eigvalsh(A)
        np.testing.assert_allclose(sorted(ev), sorted(np.asarray(S)), atol=1e-10)


class TestGrammar:
    def test_parse(self):
        assert parse_kind("rand")[0] == "rand"
        base, dist, smax, dom, zc = parse_kind("svd_geo_dominant")
        assert base == "svd" and dist == "geo" and dom

    def test_bad_kind(self):
        with pytest.raises(SlateError):
            parse_kind("noSuchKind_x")
        with pytest.raises(SlateError):
            parse_kind("rand_geo")  # dist on non-spectrum kind
        with pytest.raises(SlateError):
            generate_2d("hilb_bogus", 4, 4)

    def test_generate_matrix_api(self, grid22):
        A = Matrix.zeros(32, 32, 8, dtype=np.float64, grid=grid22)
        A2, S = generate_matrix("rand", A, seed=3)
        full, _ = generate_2d("rand", 32, 32, seed=3)
        np.testing.assert_array_equal(np.asarray(A2.to_global()), np.asarray(full))


def test_generate_tiles_device_path_bit_identical(rng, grid22):
    """Device-side per-tile generation matches the host path bit-for-bit
    and is invariant to tiling (the Philox counter-RNG contract)."""
    from slate_tpu.matgen.generate import generate_2d, generate_matrix
    from slate_tpu.matrix.matrix import Matrix

    m, n = 50, 37
    ref = np.asarray(generate_2d("rand", m, n, np.float64, seed=7)[0])
    A = Matrix.from_global(np.zeros((m, n)), 16, grid=grid22)
    out, _ = generate_matrix("rand", A, seed=7)
    np.testing.assert_array_equal(np.asarray(out.to_global()), ref)
    B = Matrix.from_global(np.zeros((m, n)), 8)
    out2, _ = generate_matrix("rand", B, seed=7)
    np.testing.assert_array_equal(np.asarray(out2.to_global()), ref)
