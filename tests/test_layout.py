"""TileLayout round-trip and index-map tests (reference semantics:
BaseMatrix.hh tileRank/tileMb/tileNb, func.hh grids)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from slate_tpu.parallel.layout import (
    TileLayout,
    eye_splice,
    tiles_from_global,
    tiles_to_global,
)


@pytest.mark.parametrize(
    "m,n,mb,nb,p,q",
    [
        (8, 8, 4, 4, 1, 1),
        (100, 80, 16, 16, 2, 2),
        (33, 65, 8, 16, 4, 2),
        (7, 7, 8, 8, 2, 2),  # single partial tile
        (64, 64, 16, 16, 3, 2),  # p doesn't divide mt
    ],
)
def test_roundtrip(m, n, mb, nb, p, q):
    layout = TileLayout(m, n, mb, nb, p, q)
    A = np.random.default_rng(0).standard_normal((m, n))
    T = tiles_from_global(jnp.asarray(A), layout)
    assert T.shape == layout.storage_shape
    back = tiles_to_global(T, layout)
    np.testing.assert_array_equal(np.asarray(back), A)


def test_storage_permutation_is_cyclic():
    layout = TileLayout(64, 64, 8, 8, 2, 2)  # mt = nt = 8
    # storage rows [0..3] hold tiles i % 2 == 0 (process row 0), [4..7] i%2==1
    for s in range(layout.P):
        i = layout.lrow(s)
        assert layout.srow(i) == s
        r = s // layout.mtl
        assert i % layout.p == r, "slot block r must hold process-row r tiles"


def test_tile_sizes_ragged():
    layout = TileLayout(100, 70, 16, 32, 2, 2)
    assert layout.mt == 7 and layout.nt == 3
    assert layout.tileMb(6) == 100 - 6 * 16
    assert layout.tileMb(0) == 16
    assert layout.tileNb(2) == 70 - 2 * 32
    # masks agree with tile sizes
    mask = np.asarray(layout.element_mask())
    assert mask.sum() == 100 * 70


def test_tile_rank_cyclic():
    layout = TileLayout(64, 64, 8, 8, 2, 3)
    for i in range(layout.mt):
        for j in range(layout.nt):
            assert layout.tileRank(i, j) == (i % 2, j % 3)


def test_sharded_placement(grid22):
    """Each process's shard must hold exactly its block-cyclic tiles."""
    layout = TileLayout(64, 64, 8, 8, grid22.p, grid22.q)
    A = np.arange(64 * 64, dtype=np.float32).reshape(64, 64)
    T = tiles_from_global(jnp.asarray(A), layout)
    T = jax.device_put(T, grid22.tile_sharding())
    # shard for mesh position (0, 0) holds tiles (i%2==0, j%2==0)
    shards = {s.device: s for s in T.addressable_shards}
    mesh_devs = np.asarray(grid22.mesh.devices)
    shard00 = np.asarray(shards[mesh_devs[0, 0]].data)
    assert shard00.shape == (layout.mtl, layout.ntl, 8, 8)
    # tile (0,0) of the shard is global tile (0,0): elements A[0:8, 0:8]
    np.testing.assert_array_equal(shard00[0, 0], A[0:8, 0:8])
    # tile (1,1) of the shard is global tile (2,2): elements A[16:24, 16:24]
    np.testing.assert_array_equal(shard00[1, 1], A[16:24, 16:24])


def test_eye_splice_pads_diagonal():
    layout = TileLayout(10, 10, 4, 4, 1, 1)  # padded to 12x12
    T = tiles_from_global(jnp.zeros((10, 10)), layout)
    T = eye_splice(layout, T)
    A = np.asarray(
        tiles_to_global(T, TileLayout(12, 12, 4, 4, 1, 1))
    )
    # in-range part untouched (zero), padding diagonal = 1
    assert A[:10, :10].sum() == 0
    np.testing.assert_array_equal(np.diag(A)[10:], [1.0, 1.0])
