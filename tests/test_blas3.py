"""BLAS3 driver tests vs numpy references with the reference tester's
norm-based acceptance (residual <= 3 eps; test_gemm.cc:192-207)."""

import jax.numpy as jnp
import numpy as np
import pytest

from slate_tpu.drivers import blas3
from slate_tpu.enums import Diag, MethodGemm, Op, Option, Side, Uplo
from slate_tpu.matrix.matrix import (
    HermitianMatrix,
    Matrix,
    SymmetricMatrix,
    TriangularMatrix,
)
from slate_tpu.matrix.base import conj_transpose, transpose
from slate_tpu.testing import checks


def _mk(rng, m, n, dtype=np.float64):
    A = rng.standard_normal((m, n))
    if np.dtype(dtype).kind == "c":
        A = A + 1j * rng.standard_normal((m, n))
    return A.astype(dtype)


@pytest.mark.parametrize("dtype", [np.float32, np.float64, np.complex128])
@pytest.mark.parametrize("mnk", [(64, 48, 32), (100, 70, 50), (17, 19, 23)])
def test_gemm_single(rng, dtype, mnk):
    m, n, k = mnk
    A0, B0, C0 = _mk(rng, m, k, dtype), _mk(rng, k, n, dtype), _mk(rng, m, n, dtype)
    alpha, beta = 2.5, -0.5
    A = Matrix.from_global(A0, 16)
    B = Matrix.from_global(B0, 16)
    C = Matrix.from_global(C0, 16)
    C2 = blas3.gemm(alpha, A, B, beta, C)
    ref = alpha * A0 @ B0 + beta * C0
    err = checks.gemm_residual(np.asarray(C2.to_global()), ref, alpha, A0, B0, beta, C0)
    assert checks.passed(err, dtype), err


@pytest.mark.parametrize("opA", [Op.NoTrans, Op.Trans, Op.ConjTrans])
@pytest.mark.parametrize("opB", [Op.NoTrans, Op.Trans])
def test_gemm_ops(rng, opA, opB):
    m, n, k = 40, 30, 20
    dtype = np.complex128
    A0 = _mk(rng, m, k, dtype)
    B0 = _mk(rng, k, n, dtype)
    C0 = _mk(rng, m, n, dtype)
    Aop = {Op.NoTrans: lambda x: x, Op.Trans: lambda x: x.T, Op.ConjTrans: lambda x: x.conj().T}
    A = Matrix.from_global(Aop[opA](A0), 8)
    B = Matrix.from_global(Bop := Aop[opB](B0), 8)
    if opA == Op.Trans:
        A = transpose(A)
    elif opA == Op.ConjTrans:
        A = conj_transpose(A)
    if opB == Op.Trans:
        B = transpose(B)
    C = Matrix.from_global(C0, 8)
    C2 = blas3.gemm(1.0, A, B, 0.0, C)
    ref = A0 @ B0
    err = checks.gemm_residual(np.asarray(C2.to_global()), ref, 1.0, A0, B0, 0.0, C0)
    assert checks.passed(err, dtype), (opA, opB, err)


@pytest.mark.parametrize("method", [MethodGemm.C, MethodGemm.A])
@pytest.mark.parametrize("mnk", [(96, 96, 96), (80, 48, 64), (90, 54, 70)])
def test_gemm_distributed(rng, grid22, method, mnk):
    m, n, k = mnk
    dtype = np.float64
    A0, B0, C0 = _mk(rng, m, k, dtype), _mk(rng, k, n, dtype), _mk(rng, m, n, dtype)
    A = Matrix.from_global(A0, 16, grid=grid22)
    B = Matrix.from_global(B0, 16, grid=grid22)
    C = Matrix.from_global(C0, 16, grid=grid22)
    C2 = blas3.gemm(1.5, A, B, 0.5, C, opts={Option.MethodGemm: method})
    ref = 1.5 * A0 @ B0 + 0.5 * C0
    err = checks.gemm_residual(np.asarray(C2.to_global()), ref, 1.5, A0, B0, 0.5, C0)
    assert checks.passed(err, dtype), (method, err)
    # distribution must be preserved
    assert C2.layout == C.layout


def test_gemm_distributed_4x2(rng, grid42):
    m, n, k = 64, 64, 96
    A0, B0, C0 = _mk(rng, m, k), _mk(rng, k, n), _mk(rng, m, n)
    A = Matrix.from_global(A0, 8, grid=grid42)
    B = Matrix.from_global(B0, 8, grid=grid42)
    C = Matrix.from_global(C0, 8, grid=grid42)
    C2 = blas3.gemm(1.0, A, B, 0.0, C)
    err = checks.gemm_residual(np.asarray(C2.to_global()), A0 @ B0, 1.0, A0, B0, 0.0, C0)
    assert checks.passed(err, np.float64), err


def test_symm_hemm(rng):
    n, m = 48, 48
    S0 = _mk(rng, n, n)
    S0 = (S0 + S0.T) / 2
    B0, C0 = _mk(rng, n, m), _mk(rng, n, m)
    S = SymmetricMatrix.from_global(S0, 16, uplo=Uplo.Lower)
    B = Matrix.from_global(B0, 16)
    C = Matrix.from_global(C0, 16)
    C2 = blas3.symm(Side.Left, 2.0, S, B, 1.0, C)
    ref = 2.0 * S0 @ B0 + C0
    assert checks.passed(
        checks.gemm_residual(np.asarray(C2.to_global()), ref, 2.0, S0, B0, 1.0, C0),
        np.float64,
    )
    # hemm with complex Hermitian
    H0 = _mk(rng, n, n, np.complex128)
    H0 = (H0 + H0.conj().T) / 2
    H = HermitianMatrix.from_global(H0, 16, uplo=Uplo.Upper)
    Bc = Matrix.from_global(B0.astype(np.complex128), 16)
    Cc = Matrix.from_global(C0.astype(np.complex128), 16)
    C3 = blas3.hemm(Side.Right, 1.0, H, Bc, 0.0, Cc)
    # note: Side.Right: C = B H
    refh = B0.astype(np.complex128) @ H0
    assert checks.passed(
        checks.gemm_residual(np.asarray(C3.to_global()), refh, 1.0, B0, H0, 0.0, C0),
        np.complex128,
    )


def test_syrk_herk(rng):
    n, k = 40, 24
    A0 = _mk(rng, n, k)
    C0 = _mk(rng, n, n)
    C0 = (C0 + C0.T) / 2
    A = Matrix.from_global(A0, 8)
    C = SymmetricMatrix.from_global(C0, 8, uplo=Uplo.Lower)
    C2 = blas3.syrk(1.0, A, 0.5, C)
    ref = A0 @ A0.T + 0.5 * C0
    err = checks.gemm_residual(np.asarray(C2.to_global()), ref, 1.0, A0, A0.T, 0.5, C0)
    assert checks.passed(err, np.float64)

    Az = _mk(rng, n, k, np.complex128)
    Cz = _mk(rng, n, n, np.complex128)
    Cz = (Cz + Cz.conj().T) / 2
    Ch = HermitianMatrix.from_global(Cz, 8, uplo=Uplo.Lower)
    C3 = blas3.herk(1.0, Matrix.from_global(Az, 8), 1.0, Ch)
    refh = Az @ Az.conj().T + Cz
    err = checks.gemm_residual(np.asarray(C3.to_global()), refh, 1.0, Az, Az.conj().T, 1.0, Cz)
    assert checks.passed(err, np.complex128)
    # result must be Hermitian
    G = np.asarray(C3.to_global())
    np.testing.assert_allclose(G, G.conj().T, atol=1e-12)


def test_syr2k_her2k(rng):
    n, k = 32, 16
    A0, B0 = _mk(rng, n, k), _mk(rng, n, k)
    C0 = _mk(rng, n, n)
    C0 = (C0 + C0.T) / 2
    C = SymmetricMatrix.from_global(C0, 8, uplo=Uplo.Upper)
    C2 = blas3.syr2k(1.0, Matrix.from_global(A0, 8), Matrix.from_global(B0, 8), 1.0, C)
    ref = A0 @ B0.T + B0 @ A0.T + C0
    assert np.allclose(np.asarray(C2.to_global()), ref, atol=1e-10)


@pytest.mark.parametrize("side", [Side.Left, Side.Right])
@pytest.mark.parametrize("uplo", [Uplo.Lower, Uplo.Upper])
@pytest.mark.parametrize("op", [Op.NoTrans, Op.Trans])
def test_trsm_trmm(rng, side, uplo, op):
    n, m = 48, 32
    dim = n if side == Side.Left else m
    T0 = _mk(rng, dim, dim)
    T0 = np.tril(T0) if uplo == Uplo.Lower else np.triu(T0)
    T0 += np.eye(dim) * dim  # well-conditioned
    B0 = _mk(rng, n, m)
    T = TriangularMatrix.from_global(T0, 16, uplo=uplo)
    if op == Op.Trans:
        T = transpose(T)
    B = Matrix.from_global(B0, 16)
    X = blas3.trsm(side, 1.0, T, B)
    Topd = T0.T if op == Op.Trans else T0
    Xg = np.asarray(X.to_global())
    if side == Side.Left:
        resid = checks.solve_residual(Topd, Xg, B0)
    else:
        resid = checks.solve_residual(Topd.T, Xg.T, B0.T)
    assert checks.passed(resid, np.float64, factor=30), resid
    # trmm inverts trsm
    B2 = blas3.trmm(side, 1.0, T, X)
    np.testing.assert_allclose(np.asarray(B2.to_global()), B0, rtol=1e-9, atol=1e-9)


def test_trsm_unit_diag(rng):
    n = 32
    T0 = np.tril(_mk(rng, n, n), -1) + np.eye(n)
    B0 = _mk(rng, n, 8)
    # store garbage on the diagonal: Diag.Unit must ignore it
    Tg = T0 + np.diag(rng.standard_normal(n))
    T = TriangularMatrix.from_global(Tg, 8, uplo=Uplo.Lower, diag=Diag.Unit)
    X = blas3.trsm(Side.Left, 1.0, T, Matrix.from_global(B0, 8))
    ref = np.linalg.solve(T0, B0)
    np.testing.assert_allclose(np.asarray(X.to_global()), ref, rtol=1e-9, atol=1e-9)


@pytest.mark.slow
def test_herk_distributed_spmd(rng, grid22):
    n, k, nb = 64, 48, 16
    A0 = rng.standard_normal((n, k))
    C0 = rng.standard_normal((n, n)); C0 = (C0 + C0.T) / 2
    A = Matrix.from_global(A0, nb, grid=grid22)
    C = HermitianMatrix.from_global(C0, nb, grid=grid22, uplo=Uplo.Lower)
    out = blas3.herk(1.0, A, 0.5, C)
    np.testing.assert_allclose(
        np.asarray(out.full_global()), A0 @ A0.T + 0.5 * C0, atol=1e-11
    )


@pytest.mark.slow
def test_her2k_distributed_complex(rng, grid22):
    n, k, nb = 48, 32, 16
    A0 = rng.standard_normal((n, k)) + 1j * rng.standard_normal((n, k))
    B0 = rng.standard_normal((n, k)) + 1j * rng.standard_normal((n, k))
    C0 = rng.standard_normal((n, n)) + 1j * rng.standard_normal((n, n))
    C0 = (C0 + C0.conj().T) / 2
    alpha = 1.3 - 0.4j
    out = blas3.her2k(
        alpha,
        Matrix.from_global(A0.astype(np.complex128), nb, grid=grid22),
        Matrix.from_global(B0.astype(np.complex128), nb, grid=grid22),
        0.5,
        HermitianMatrix.from_global(
            C0.astype(np.complex128), nb, grid=grid22, uplo=Uplo.Lower
        ),
    )
    ref = alpha * A0 @ B0.conj().T + np.conj(alpha) * B0 @ A0.conj().T + 0.5 * C0
    np.testing.assert_allclose(np.asarray(out.full_global()), ref, atol=1e-11)


def test_hemm_distributed_spmd(rng, grid22):
    n, w, nb = 64, 32, 16
    C0 = rng.standard_normal((n, n)); C0 = (C0 + C0.T) / 2
    B0 = rng.standard_normal((n, w))
    out = blas3.hemm(
        Side.Left, 2.0,
        HermitianMatrix.from_global(C0, nb, grid=grid22, uplo=Uplo.Lower),
        Matrix.from_global(B0, nb, grid=grid22),
        0.0,
        Matrix.from_global(np.zeros((n, w)), nb, grid=grid22),
    )
    np.testing.assert_allclose(np.asarray(out.to_global()), 2.0 * C0 @ B0, atol=1e-11)


def test_hemm_distributed_no_mirror(rng, grid22, monkeypatch):
    """The distributed hemm assembles A's panels from the stored
    triangle — full_global must never be called."""
    from slate_tpu.matrix.base import BaseMatrix

    n, w, nb = 64, 32, 16
    C0 = rng.standard_normal((n, n)); C0 = (C0 + C0.T) / 2
    B0 = rng.standard_normal((n, w))
    A = HermitianMatrix.from_global(C0, nb, grid=grid22, uplo=Uplo.Lower)
    B = Matrix.from_global(B0, nb, grid=grid22)
    C = Matrix.from_global(np.zeros((n, w)), nb, grid=grid22)

    def boom(self, *a, **kw):  # pragma: no cover
        raise AssertionError("gather in distributed hemm")

    monkeypatch.setattr(HermitianMatrix, "full_global", boom)
    monkeypatch.setattr(BaseMatrix, "to_global", boom)
    out = blas3.hemm(Side.Left, 1.0, A, B, 0.0, C)
    assert out.data.shape == C.data.shape


@pytest.mark.parametrize("uplo", [Uplo.Lower, Uplo.Upper])
def test_hemm_right_distributed(rng, grid42, uplo):
    n, w, nb = 64, 48, 8
    A0 = rng.standard_normal((n, n)); A0 = (A0 + A0.T) / 2
    B0 = rng.standard_normal((w, n))
    A = HermitianMatrix.from_global(A0, nb, grid=grid42, uplo=uplo)
    B = Matrix.from_global(B0, nb, grid=grid42)
    C = Matrix.from_global(rng.standard_normal((w, n)), nb, grid=grid42)
    C0 = np.asarray(C.to_global())
    out = blas3.hemm(Side.Right, 1.5, A, B, 0.5, C)
    np.testing.assert_allclose(
        np.asarray(out.to_global()), 1.5 * B0 @ A0 + 0.5 * C0, atol=1e-11 * n
    )


def test_hemm_complex_distributed(rng, grid22):
    n, w, nb = 48, 32, 16
    A0 = rng.standard_normal((n, n)) + 1j * rng.standard_normal((n, n))
    A0 = (A0 + A0.conj().T) / 2
    B0 = rng.standard_normal((n, w)) + 1j * rng.standard_normal((n, w))
    A = HermitianMatrix.from_global(A0, nb, grid=grid22, uplo=Uplo.Lower)
    B = Matrix.from_global(B0, nb, grid=grid22)
    C = Matrix.from_global(np.zeros((n, w), complex), nb, grid=grid22)
    out = blas3.hemm(Side.Left, 1.0, A, B, 0.0, C)
    np.testing.assert_allclose(
        np.asarray(out.to_global()), A0 @ B0, atol=1e-11 * n
    )


def test_symm_complex_distributed_no_conj(rng, grid22):
    """Complex SYMMETRIC (not Hermitian) symm must mirror WITHOUT
    conjugation on the spmd path."""
    n, w, nb = 48, 32, 16
    A0 = rng.standard_normal((n, n)) + 1j * rng.standard_normal((n, n))
    A0 = (A0 + A0.T) / 2  # complex symmetric: A == A^T
    B0 = rng.standard_normal((n, w)) + 1j * rng.standard_normal((n, w))
    A = SymmetricMatrix.from_global(A0, nb, grid=grid22, uplo=Uplo.Lower)
    B = Matrix.from_global(B0, nb, grid=grid22)
    C = Matrix.from_global(np.zeros((n, w), complex), nb, grid=grid22)
    out = blas3.symm(Side.Left, 1.0, A, B, 0.0, C)
    np.testing.assert_allclose(
        np.asarray(out.to_global()), A0 @ B0, atol=1e-11 * n
    )
