"""Batched inverse-iteration tridiagonal eigenvectors (ops/stein.py) —
the independent fallback for stedc (reference role: steqr_impl.cc;
algorithmically dstebz+dstein)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from slate_tpu.drivers.eig import steqr
from slate_tpu.ops.bulge import tridiag_eigvals_bisect
from slate_tpu.ops.stein import stein


def _check(d, e, rtol=5e-11):
    d = jnp.asarray(d, jnp.float64)
    e = jnp.asarray(e, jnp.float64)
    n = d.shape[0]
    w = tridiag_eigvals_bisect(d, e)
    Z = stein(d, e, w)
    T = (
        np.diag(np.asarray(d))
        + np.diag(np.asarray(e), 1)
        + np.diag(np.asarray(e), -1)
    )
    wn = np.asarray(w)
    Zn = np.asarray(Z)
    scale = max(np.abs(wn).max(), 1e-30)
    res = np.abs(T @ Zn - Zn * wn[None, :]).max() / scale
    assert res < rtol * n, res
    orth = np.abs(Zn.T @ Zn - np.eye(n)).max()
    assert orth < rtol * n, orth


@pytest.mark.parametrize("n", [2, 3, 16, 64, 157])
def test_random(n):
    rng = np.random.default_rng(n)
    _check(rng.standard_normal(n), rng.standard_normal(max(n - 1, 0)))


def test_toeplitz():
    _check(np.zeros(96), np.ones(95))


def test_identity_cluster():
    # fully degenerate spectrum: any orthonormal basis is an eigenbasis
    _check(np.ones(32), np.zeros(31))


def test_wilkinson():
    m = 10
    _check(np.abs(np.arange(-m, m + 1)).astype(float), np.ones(2 * m))


def test_scaled():
    rng = np.random.default_rng(5)
    _check(1e8 * rng.standard_normal(48), 1e8 * rng.standard_normal(47))


def test_steqr_method_stein():
    rng = np.random.default_rng(11)
    d = jnp.asarray(rng.standard_normal(40))
    e = jnp.asarray(rng.standard_normal(39))
    w, Z = steqr(d, e, vectors=True, method="stein")
    T = (
        np.diag(np.asarray(d))
        + np.diag(np.asarray(e), 1)
        + np.diag(np.asarray(e), -1)
    )
    assert np.abs(
        np.asarray(T @ Z) - np.asarray(Z * w[None, :])
    ).max() < 1e-10
