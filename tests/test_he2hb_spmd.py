"""Distributed two-stage reduction tests (reference: test/test_heev.cc,
test_svd.cc distributed runs).

These exercise parallel/spmd_he2hb.py and parallel/spmd_ge2tb.py — the
shard_map stage-1 panel pipelines — directly and through the drivers,
and assert the drivers route distributed inputs through them with NO
full-matrix gather anywhere in stage 1 (the reference distributes
he2hb/ge2tb the same way: src/he2hb.cc:98-185, src/ge2tb.cc).
"""

import numpy as np
import pytest

from slate_tpu.drivers import eig, svd as svd_mod
from slate_tpu.enums import Op, Side, Uplo
from slate_tpu.matrix.base import BaseMatrix
from slate_tpu.matrix.matrix import HermitianMatrix, Matrix
from slate_tpu.parallel import spmd_ge2tb, spmd_he2hb
from slate_tpu.testing import checks


def _herm(rng, n, dtype=np.float64):
    A = rng.standard_normal((n, n)).astype(dtype)
    if np.issubdtype(dtype, np.complexfloating):
        A = A + 1j * rng.standard_normal((n, n))
    return (A + A.conj().T) / 2


def _no_gather(monkeypatch):
    """Patch every gather route to raise; returns a restore-free context
    (monkeypatch undoes it)."""

    def boom(self, *a, **kw):  # pragma: no cover - failure path
        raise AssertionError("full-matrix gather in a gather-free path")

    monkeypatch.setattr(BaseMatrix, "to_global", boom)
    monkeypatch.setattr(HermitianMatrix, "full_global", boom, raising=True)


@pytest.mark.parametrize("n,nb", [(64, 16), (50, 16)])
def test_he2hb_spmd_band_spectrum(rng, grid22, n, nb):
    """The distributed band is banded and orthogonally similar to A.

    (Elementwise band parity with the gathered path does not hold: the
    two paths use different — equally valid — reflector sign
    conventions, so the bands differ by a signed diagonal similarity.)"""
    A0 = _herm(rng, n)
    Ad = HermitianMatrix.from_global(A0, nb, grid=grid22, uplo=Uplo.Lower)
    band_d, Vd, Td = eig.he2hb(Ad)
    Gd = np.asarray(band_d.to_global())
    low_d = np.tril(Gd)
    # band-ness of the stored triangle
    out_of_band = np.tri(n, n, -nb - 1) > 0
    assert np.abs(low_d[out_of_band]).max() < 1e-12
    B = low_d + np.tril(low_d, -1).T
    np.testing.assert_allclose(
        np.linalg.eigvalsh(B), np.linalg.eigvalsh(A0), atol=1e-12 * n
    )


def test_he2hb_spmd_gather_free(rng, grid22, monkeypatch):
    n, nb = 64, 16
    A0 = _herm(rng, n)
    Ad = HermitianMatrix.from_global(A0, nb, grid=grid22, uplo=Uplo.Lower)

    calls = {"n": 0}
    orig = spmd_he2hb.spmd_he2hb

    def counting(*a, **kw):
        calls["n"] += 1
        return orig(*a, **kw)

    monkeypatch.setattr(spmd_he2hb, "spmd_he2hb", counting)
    _no_gather(monkeypatch)
    band, V, T = eig.he2hb(Ad)
    assert calls["n"] == 1, "distributed he2hb must run the shard_map pipeline"
    assert band.data.shape == Ad.data.shape


def test_he2hb_spmd_reconstructs(rng, grid22):
    """Q B Q^H == A: apply the distributed back-transform to the band."""
    n, nb = 64, 16
    A0 = _herm(rng, n)
    Ad = HermitianMatrix.from_global(A0, nb, grid=grid22, uplo=Uplo.Lower)
    band, V, T = eig.he2hb(Ad)
    G = np.asarray(band.to_global())
    B = np.tril(G) * (np.tri(n, n, 0) - np.tri(n, n, -nb - 1) > 0)
    B = B + np.tril(B, -1).T
    Bm = Matrix.from_global(B, nb, grid=grid22)
    QB = eig.unmtr_he2hb(Side.Left, Op.NoTrans, V, T, Bm)
    QBm = Matrix.from_global(np.asarray(QB.to_global()).T, nb, grid=grid22)
    QBQ = eig.unmtr_he2hb(Side.Left, Op.NoTrans, V, T, QBm)
    rec = np.asarray(QBQ.to_global()).T
    err = np.abs(rec - A0).max() / (np.abs(A0).max() * n)
    assert err < 1e-13, err


def test_unmtr_he2hb_spmd_matches_gathered(rng, grid22):
    """The distributed apply matches the gathered apply of the SAME V/T."""
    n, nb = 64, 16
    A0 = _herm(rng, n)
    C0 = rng.standard_normal((n, n))
    Ad = HermitianMatrix.from_global(A0, nb, grid=grid22, uplo=Uplo.Lower)
    band_d, Vd, Td = eig.he2hb(Ad)
    V1 = Matrix.from_global(np.asarray(Vd.to_global()), nb)
    Cd = Matrix.from_global(C0, nb, grid=grid22)
    C1 = Matrix.from_global(C0, nb)
    for op in (Op.NoTrans, Op.ConjTrans):
        out_d = eig.unmtr_he2hb(Side.Left, op, Vd, Td, Cd)
        out_1 = eig.unmtr_he2hb(Side.Left, op, V1, Td, C1)
        np.testing.assert_allclose(
            np.asarray(out_d.to_global()),
            np.asarray(out_1.to_global()),
            atol=1e-10,
        )


@pytest.mark.parametrize("gridname", ["grid22", "grid42"])
def test_heev_spmd_vectors_residual(rng, gridname, request):
    grid = request.getfixturevalue(gridname)
    n, nb = 64, 16
    A0 = _herm(rng, n)
    Ad = HermitianMatrix.from_global(A0, nb, grid=grid, uplo=Uplo.Lower)
    w, Z = eig.heev(Ad)
    Zg = np.asarray(Z.to_global())
    err = np.abs(A0 @ Zg - Zg * np.asarray(w)[None, :]).max() / (
        np.abs(A0).max() * n
    )
    assert err < 1e-12, err
    orth = np.abs(Zg.T @ Zg - np.eye(n)).max()
    assert orth < 1e-12 * n, orth


def test_heev_spmd_complex(rng, grid22):
    n, nb = 48, 16
    A0 = _herm(rng, n, np.complex128)
    Ad = HermitianMatrix.from_global(A0, nb, grid=grid22, uplo=Uplo.Lower)
    w, Z = eig.heev(Ad)
    np.testing.assert_allclose(
        np.asarray(w), np.linalg.eigvalsh(A0), atol=1e-11 * n
    )
    Zg = np.asarray(Z.to_global())
    err = np.abs(A0 @ Zg - Zg * np.asarray(w)[None, :]).max()
    assert err < 1e-10 * n, err


# ---------------------------------------------------------------------------
# ge2tb
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("m,n,nb", [(64, 64, 16), (80, 64, 16), (70, 50, 16)])
def test_ge2tb_spmd_band_values(rng, grid22, m, n, nb):
    """The distributed band is orthogonally equivalent to A: its singular
    values match."""
    A0 = rng.standard_normal((m, n))
    Ad = Matrix.from_global(A0, nb, grid=grid22)
    band, UV, UT, VV, VT = svd_mod.ge2tb(Ad)
    G = np.asarray(band.to_global())
    # band-ness: only the diagonal + nb superdiagonals are populated
    i, j = np.meshgrid(range(m), range(n), indexing="ij")
    out_of_band = (j < i) | (j > i + nb)
    assert np.abs(G[out_of_band]).max() < 1e-12
    np.testing.assert_allclose(
        np.linalg.svd(G, compute_uv=False),
        np.linalg.svd(A0, compute_uv=False),
        atol=1e-10 * max(m, n),
    )


def test_ge2tb_spmd_gather_free(rng, grid22, monkeypatch):
    m, n, nb = 64, 64, 16
    A0 = rng.standard_normal((m, n))
    Ad = Matrix.from_global(A0, nb, grid=grid22)

    calls = {"n": 0}
    orig = spmd_ge2tb.spmd_ge2tb

    def counting(*a, **kw):
        calls["n"] += 1
        return orig(*a, **kw)

    monkeypatch.setattr(spmd_ge2tb, "spmd_ge2tb", counting)
    _no_gather(monkeypatch)
    band, UV, UT, VV, VT = svd_mod.ge2tb(Ad)
    assert calls["n"] == 1, "distributed ge2tb must run the shard_map pipeline"


@pytest.mark.parametrize(
    "gridname",
    ["grid22", pytest.param("grid42", marks=pytest.mark.slow)],
)
def test_svd_spmd_vectors_residual(rng, gridname, request):
    grid = request.getfixturevalue(gridname)
    m, n, nb = 80, 64, 16
    A0 = rng.standard_normal((m, n))
    Ad = Matrix.from_global(A0, nb, grid=grid)
    s, U, Vh = svd_mod.svd(Ad, vectors=True)
    s = np.asarray(s)
    np.testing.assert_allclose(
        s, np.linalg.svd(A0, compute_uv=False), atol=1e-10 * m
    )
    Ug = np.asarray(U.to_global())[:, :n]
    Vhg = np.asarray(Vh.to_global())
    rec = Ug * s[None, :] @ Vhg
    err = np.abs(rec - A0).max() / (np.abs(A0).max() * max(m, n))
    assert err < 1e-12, err
