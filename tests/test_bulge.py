"""Stage-2 bulge chasing tests (reference: src/hb2st.cc wavefront,
src/unmtr_hb2st.cc, src/sterf.cc, src/tb2bd.cc + bdsqr.cc).

Checks the superstep wavefront kernel against dense references: the
tridiagonal must be orthogonally similar to the band matrix, the chase
reflectors must reproduce band eigenvectors, and bisection must match
eigvalsh.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from slate_tpu.ops import bulge


def _band(rng, n, b, dtype=np.float64):
    A = rng.standard_normal((n, n))
    if np.dtype(dtype).kind == "c":
        A = A + 1j * rng.standard_normal((n, n))
    A = (A + A.conj().T) / 2
    mask = np.abs(np.subtract.outer(np.arange(n), np.arange(n))) <= b
    return (A * mask).astype(dtype)


@pytest.mark.parametrize("n,b", [(24, 4), (50, 8), (64, 16), (37, 5), (30, 2)])
def test_hb2st_eigenvalues(rng, n, b):
    Ab = _band(rng, n, b)
    W = bulge.band_to_storage(jnp.asarray(Ab), b, n + 4 * b + 8)
    d, e, u, VS, TAUS = bulge.hb2st(W, n, b)
    T = np.diag(np.asarray(d)) + np.diag(np.asarray(e), 1) + np.diag(np.asarray(e), -1)
    err = np.abs(np.linalg.eigvalsh(Ab) - np.linalg.eigvalsh(T)).max()
    assert err < 1e-12 * max(np.abs(Ab).max(), 1), err


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_hb2st_back_transform(rng, dtype):
    n, b = 45, 6
    Ab = _band(rng, n, b, dtype)
    W = bulge.band_to_storage(jnp.asarray(Ab), b, n + 4 * b + 8)
    d, e, u, VS, TAUS = bulge.hb2st(W, n, b)
    T = np.diag(np.asarray(d)) + np.diag(np.asarray(e), 1) + np.diag(np.asarray(e), -1)
    wT, ZT = np.linalg.eigh(T)
    Zin = (np.asarray(u)[:, None] * ZT).astype(dtype)
    Z = np.asarray(bulge.unmtr_hb2st(VS, TAUS, jnp.asarray(Zin), n, b))
    res = np.abs(Ab @ Z - Z * wT[None, :]).max()
    assert res < 1e-12 * np.abs(Ab).max(), res
    assert np.abs(Z.conj().T @ Z - np.eye(n)).max() < 1e-12


@pytest.mark.parametrize(
    "n,b,dtype,trans",
    [
        (45, 6, np.float64, False),
        (45, 6, np.complex128, False),
        (45, 6, np.float64, True),
        (64, 16, np.float64, False),  # n_sweeps not divisible by b
        (37, 5, np.complex128, True),
        (30, 2, np.float64, False),   # minimal bandwidth
        (24, 4, np.float64, False),
    ],
)
def test_unmtr_hb2st_diamond_matches_sweep(rng, n, b, dtype, trans):
    """The diamond-blocked compact-WY apply must agree with the rank-1
    per-sweep reference kernel on real chase reflectors."""
    Ab = _band(rng, n, b, dtype)
    W = bulge.band_to_storage(jnp.asarray(Ab), b, n + 4 * b + 8)
    _, _, _, VS, TAUS = bulge.hb2st(W, n, b)
    Z0 = rng.standard_normal((n, 13))
    if np.dtype(dtype).kind == "c":
        Z0 = Z0 + 1j * rng.standard_normal((n, 13))
    Z0 = jnp.asarray(Z0.astype(dtype))
    ref = np.asarray(bulge._unmtr_hb2st_sweep(VS, TAUS, Z0, n, b, trans=trans))
    got = np.asarray(bulge.unmtr_hb2st(VS, TAUS, Z0, n, b, trans=trans))
    np.testing.assert_allclose(got, ref, atol=1e-12)


def test_unmtr_hb2st_placeholder_identity(rng):
    """b<=1 bands skip the chase; the placeholder VS must back-transform
    as the identity (regression: negative-pad crash in the diamond path)."""
    n, b = 10, 1
    Ab = _band(rng, n, b)
    W = bulge.band_to_storage(jnp.asarray(Ab), b, n + 4 * b + 8)
    _, _, _, VS, TAUS = bulge.hb2st(W, n, b)
    Z0 = jnp.asarray(rng.standard_normal((n, 3)))
    np.testing.assert_array_equal(
        np.asarray(bulge.unmtr_hb2st(VS, TAUS, Z0, n, b)), np.asarray(Z0)
    )


def test_unmtr_hb2st_trans_inverts(rng):
    n, b = 32, 4
    Ab = _band(rng, n, b)
    W = bulge.band_to_storage(jnp.asarray(Ab), b, n + 4 * b + 8)
    _, _, _, VS, TAUS = bulge.hb2st(W, n, b)
    Z0 = rng.standard_normal((n, 5))
    Z1 = bulge.unmtr_hb2st(VS, TAUS, jnp.asarray(Z0), n, b)
    Z2 = np.asarray(bulge.unmtr_hb2st(VS, TAUS, Z1, n, b, trans=True))
    np.testing.assert_allclose(Z2, Z0, atol=1e-12)


@pytest.mark.parametrize("n", [8, 33, 100])
def test_bisection_matches_eigvalsh(rng, n):
    d = rng.standard_normal(n)
    e = rng.standard_normal(n - 1)
    w = np.asarray(bulge.tridiag_eigvals_bisect(jnp.asarray(d), jnp.asarray(e)))
    T = np.diag(d) + np.diag(e, 1) + np.diag(e, -1)
    ref = np.linalg.eigvalsh(T)
    np.testing.assert_allclose(w, ref, atol=1e-12 * max(1, np.abs(ref).max()))
    # ascending order guaranteed
    assert (np.diff(w) >= -1e-14).all()


def test_bisection_clustered(rng):
    # repeated eigenvalues: glued Wilkinson-style matrix
    d = np.concatenate([np.zeros(5), np.ones(5), np.ones(5) + 1e-9])
    e = np.full(14, 1e-12)
    w = np.asarray(bulge.tridiag_eigvals_bisect(jnp.asarray(d), jnp.asarray(e)))
    ref = np.linalg.eigvalsh(np.diag(d) + np.diag(e, 1) + np.diag(e, -1))
    np.testing.assert_allclose(w, ref, atol=1e-10)


@pytest.mark.slow
def test_bdsqr_values_and_vectors(rng):
    from slate_tpu.drivers.svd import bdsqr

    n = 24
    d = rng.standard_normal(n) + 2
    e = rng.standard_normal(n - 1)
    B = np.diag(d) + np.diag(e, 1)
    ref = np.linalg.svd(B, compute_uv=False)
    s, _, _ = bdsqr(jnp.asarray(d), jnp.asarray(e), vectors=False)
    np.testing.assert_allclose(np.asarray(s), ref, atol=1e-11)
    s2, U, Vt = bdsqr(jnp.asarray(d), jnp.asarray(e), vectors=True)
    s2, U = np.asarray(s2), np.asarray(U)
    rec = (U * s2[None, :]) @ np.asarray(Vt)  # Vt rows are right vectors
    np.testing.assert_allclose(rec, B, atol=1e-10)


@pytest.mark.slow
def test_heev_two_stage_vs_dense_agreement(rng):
    """Driver-level: the two-stage path (n > 4 nb) matches eigvalsh.

    slow: 17.8 s of tier-1 wall on the 2-core box (n=80 two-stage
    compile); the staged-path coverage stays tier-1 via the smaller
    hb2st/unmtr cases above."""
    import slate_tpu as st

    n, nb = 80, 8
    A0 = rng.standard_normal((n, n))
    A0 = (A0 + A0.T) / 2
    A = st.HermitianMatrix.from_global(A0, nb, uplo=st.Uplo.Lower)
    w, Z = st.heev(A)
    w, Zg = np.asarray(w), np.asarray(Z.to_global())
    np.testing.assert_allclose(w, np.linalg.eigvalsh(A0), atol=1e-12 * n)
    res = np.abs(A0 @ Zg - Zg * w[None, :]).max()
    assert res < 1e-12 * np.abs(A0).max() * n, res


@pytest.mark.slow
def test_svd_jw_band_path(rng):
    import slate_tpu as st

    m, n, nb = 100, 60, 4  # n > 4*(2 nb + 1) -> JW stage
    A0 = rng.standard_normal((m, n))
    A = st.Matrix.from_global(A0, nb)
    s, U, Vh = st.svd(A, vectors=True)
    s = np.asarray(s)
    sref = np.linalg.svd(A0, compute_uv=False)
    np.testing.assert_allclose(s, sref, atol=1e-11 * sref.max())
    rec = (np.asarray(U.to_global()) * s[None, :]) @ np.asarray(Vh.to_global())
    np.testing.assert_allclose(rec, A0, atol=1e-10)
