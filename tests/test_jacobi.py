"""Parallel-order Jacobi polish kernels (ops/jacobi.py) — the TPU-f64
accuracy layer for spectral routines (SURVEY §7 hard-part (5)).

On CPU eigh/svd are already exact, so these tests feed the polishers a
*perturbed* starting basis and check they recover working precision.
"""

import numpy as np
import pytest

from slate_tpu.ops import jacobi


def _perturbed_basis(rng, V, scale=1e-7):
    """Orthonormal basis a small rotation away from V (mimics the TPU
    vendor eigh's ~1e-7 residual)."""
    n = V.shape[0]
    E = rng.standard_normal((n, n)) * scale
    if np.iscomplexobj(V):
        E = E + 1j * rng.standard_normal((n, n)) * scale
    Q, _ = np.linalg.qr(V + V @ E)
    return Q


@pytest.mark.parametrize("n", [16, 50, 65])
def test_eigh_polish_real(rng, n):
    A = rng.standard_normal((n, n))
    S = (A + A.T) / 2
    w_ref, V_ref = np.linalg.eigh(S)
    V0 = _perturbed_basis(rng, V_ref)
    w, V = jacobi.jacobi_eigh_polish(S, V0)
    w, V = np.asarray(w), np.asarray(V)
    res = np.abs(S @ V - V * w[None, :]).max() / max(np.abs(S).max(), 1)
    assert res < 1e-13, res
    assert np.abs(V.T @ V - np.eye(n)).max() < 1e-13
    np.testing.assert_allclose(w, w_ref, atol=1e-12 * np.abs(w_ref).max())


def test_eigh_polish_complex(rng):
    n = 40
    A = rng.standard_normal((n, n)) + 1j * rng.standard_normal((n, n))
    H = (A + A.conj().T) / 2
    w_ref, V_ref = np.linalg.eigh(H)
    V0 = _perturbed_basis(rng, V_ref)
    w, V = jacobi.jacobi_eigh_polish(H.astype(np.complex128), V0)
    w, V = np.asarray(w), np.asarray(V)
    res = np.abs(H @ V - V * w[None, :]).max() / np.abs(H).max()
    assert res < 1e-13, res
    assert np.abs(V.conj().T @ V - np.eye(n)).max() < 1e-13


def test_eigh_polish_clustered(rng):
    """Tight eigenvalue clusters: the invariant-subspace residual must
    still reach working precision (Jacobi handles clusters natively)."""
    n = 32
    w_true = np.sort(np.concatenate([np.ones(8), np.ones(8) + 1e-12,
                                     rng.standard_normal(16) * 10]))
    Q, _ = np.linalg.qr(rng.standard_normal((n, n)))
    S = (Q * w_true[None, :]) @ Q.T
    S = (S + S.T) / 2
    V0 = _perturbed_basis(rng, Q)
    w, V = jacobi.jacobi_eigh_polish(S, V0)
    w, V = np.asarray(w), np.asarray(V)
    res = np.abs(S @ V - V * w[None, :]).max() / np.abs(S).max()
    assert res < 1e-12, res


@pytest.mark.parametrize("n", [16, 50])
def test_svd_polish(rng, n):
    A = rng.standard_normal((n, n))
    U_ref, s_ref, Vh_ref = np.linalg.svd(A)
    V0 = _perturbed_basis(rng, Vh_ref.T)
    U, s, V = jacobi.jacobi_svd_polish(A, V0)
    U, s, V = np.asarray(U), np.asarray(s), np.asarray(V)
    res = np.abs((U * s[None, :]) @ V.T - A).max() / np.abs(A).max()
    assert res < 1e-13, res
    assert np.abs(U.T @ U - np.eye(n)).max() < 1e-12
    np.testing.assert_allclose(s, s_ref, atol=1e-12 * s_ref.max())


def test_svd_polish_complex(rng):
    n = 24
    A = rng.standard_normal((n, n)) + 1j * rng.standard_normal((n, n))
    U_ref, s_ref, Vh_ref = np.linalg.svd(A)
    V0 = _perturbed_basis(rng, Vh_ref.conj().T)
    U, s, V = jacobi.jacobi_svd_polish(A.astype(np.complex128), V0)
    U, s, V = np.asarray(U), np.asarray(s), np.asarray(V)
    res = np.abs((U * s[None, :]) @ V.conj().T - A).max() / np.abs(A).max()
    assert res < 1e-13, res
    np.testing.assert_allclose(s, s_ref, atol=1e-12 * s_ref.max())


def test_accurate_wrappers_cpu_passthrough(rng):
    """On CPU the wrappers are the vendor kernels (no polish cost)."""
    n = 20
    A = rng.standard_normal((n, n))
    S = (A + A.T) / 2
    w, V = jacobi.eigh_accurate(S)
    np.testing.assert_allclose(np.asarray(w), np.linalg.eigvalsh(S), atol=1e-12)
    U, s, Vh = jacobi.svd_accurate(A)
    np.testing.assert_allclose(np.asarray(s), np.linalg.svd(A, compute_uv=False), atol=1e-12)
