"""Band-limited stage-2 gather (parallel/band_gather.py) and the native
host bulge chaser (slate_tpu/native) — reference semantics:
he2hbGather/ge2tbGather move O(n kd) between the eigensolver stages
(HermitianBandMatrix.hh:310, TriangularBandMatrix.hh:327,
src/heev.cc:133-151), and hb2st runs as native CPU code over the
gathered band (src/hb2st.cc:44-187)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from slate_tpu import native
from slate_tpu.drivers import eig
from slate_tpu.enums import Uplo
from slate_tpu.matrix.base import BaseMatrix
from slate_tpu.matrix.matrix import HermitianMatrix
from slate_tpu.ops import bulge
from slate_tpu.parallel.band_gather import (
    band_storage_tiles,
    spmd_band_storage,
    spmd_upper_band_diagonals,
    upper_band_diagonals_tiles,
)
from slate_tpu.parallel.layout import TileLayout, tiles_from_global


def _lower_band(rng, n, nb):
    A = rng.standard_normal((n, n))
    A = (A + A.T) / 2
    return np.tril(np.triu(np.tril(A), -nb))


@pytest.mark.parametrize(
    "n,nb",
    [(96, 16), (100, 16), pytest.param(64, 32, marks=pytest.mark.slow)],
)
def test_band_storage_tiles_matches_dense(rng, n, nb):
    lay = TileLayout(n, n, nb, nb, 1, 1)
    G = _lower_band(rng, n, nb)
    T = tiles_from_global(jnp.asarray(G), lay)
    npad = n + 4 * nb + 8
    W_ref = np.asarray(
        bulge.band_to_storage(jnp.asarray(G + np.tril(G, -1).T), nb, npad)
    )
    W = np.asarray(band_storage_tiles(T, lay, npad))
    np.testing.assert_allclose(W, W_ref, atol=0)


@pytest.mark.parametrize("n,nb", [(96, 16), (100, 16)])
def test_upper_band_diagonals_matches_dense(rng, n, nb):
    lay = TileLayout(n, n, nb, nb, 1, 1)
    B = np.triu(np.tril(rng.standard_normal((n, n)), nb))
    T = tiles_from_global(jnp.asarray(B), lay)
    Dg = np.asarray(upper_band_diagonals_tiles(T, lay, n))
    ref = np.stack(
        [np.concatenate([np.diagonal(B, t), np.zeros(t)])
         for t in range(nb + 1)]
    )
    np.testing.assert_allclose(Dg, ref, atol=0)


@pytest.mark.parametrize("n,nb", [(96, 16), (100, 16)])
def test_spmd_band_storage_matches(rng, grid22, n, nb):
    lay = TileLayout(n, n, nb, nb, grid22.p, grid22.q)
    G = _lower_band(rng, n, nb)
    T = tiles_from_global(jnp.asarray(G), lay)
    npad = n + 4 * nb + 8
    W_ref = np.asarray(
        bulge.band_to_storage(jnp.asarray(G + np.tril(G, -1).T), nb, npad)
    )
    W = np.asarray(spmd_band_storage(grid22, T, lay, npad))
    np.testing.assert_allclose(W, W_ref, atol=0)


def test_spmd_upper_band_diagonals_matches(rng, grid22):
    n, nb = 96, 16
    lay = TileLayout(n, n, nb, nb, grid22.p, grid22.q)
    B = np.triu(np.tril(rng.standard_normal((n, n)), nb))
    T = tiles_from_global(jnp.asarray(B), lay)
    Dg = np.asarray(spmd_upper_band_diagonals(grid22, T, lay, n))
    ref = np.stack(
        [np.concatenate([np.diagonal(B, t), np.zeros(t)])
         for t in range(nb + 1)]
    )
    np.testing.assert_allclose(Dg, ref, atol=0)


# ---------------------------------------------------------------------------
# native host chaser
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n,b", [(24, 4), (65, 8), (129, 16)])
def test_native_hb2st_matches_wavefront(rng, n, b):
    if not native.hb2st_available():
        pytest.skip("no C compiler for the native chaser")
    G = _lower_band(rng, n, b)
    Gfull = G + np.tril(G, -1).T
    n_pad = n + 4 * b + 8
    W = np.asarray(bulge.band_to_storage(jnp.asarray(Gfull), b, n_pad))
    d1, e1, u1, VS1, TAUS1 = map(
        np.asarray, bulge.hb2st(jnp.asarray(W), n, b)
    )
    d2, e2, VS2, TAUS2 = native.hb2st_host(W, n, b)
    assert np.abs(d1 - d2).max() < 1e-10
    assert np.abs(e1 - e2).max() < 1e-10
    assert VS1.shape == VS2.shape and TAUS1.shape == TAUS2.shape
    assert np.abs(VS1 - VS2).max() < 1e-9
    # the tridiagonal is orthogonally similar to the band
    T1 = np.diag(d2) + np.diag(e2, 1) + np.diag(e2, -1)
    np.testing.assert_allclose(
        np.linalg.eigvalsh(T1), np.linalg.eigvalsh(Gfull), atol=1e-11 * n
    )


@pytest.mark.slow
def test_heev_native_path_residual(rng):
    """heev eagerly routes stage 2 through the native chaser (real f64);
    the full driver keeps LAPACK-grade residuals."""
    if not native.hb2st_available():
        pytest.skip("no C compiler for the native chaser")
    n, nb = 80, 16  # n > 4 nb: the two-stage path
    A0 = rng.standard_normal((n, n))
    A0 = (A0 + A0.T) / 2
    A = HermitianMatrix.from_global(A0, nb, uplo=Uplo.Lower)
    w, Z = eig.heev(A)
    Zg = np.asarray(Z.to_global())
    w = np.asarray(w)
    err = np.abs(A0 @ Zg - Zg * w[None, :]).max() / (np.abs(A0).max() * n)
    assert err < 1e-12, err
    orth = np.abs(Zg.T @ Zg - np.eye(n)).max()
    assert orth < 1e-12 * n, orth
    np.testing.assert_allclose(w, np.linalg.eigvalsh(A0), atol=1e-11 * n)


@pytest.mark.slow
def test_heev_spmd_two_stage_gather_free(rng, grid22, monkeypatch):
    """Distributed heev through the two-stage path never materializes a
    dense global array: stage 1 is the spmd pipeline, the stage gather
    is band-limited (spmd_band_storage), and the back-transforms are
    distributed."""
    n, nb = 80, 16  # n > 4 nb

    def boom(self, *a, **kw):  # pragma: no cover
        raise AssertionError("full-matrix gather in the two-stage path")

    A0 = rng.standard_normal((n, n))
    A0 = (A0 + A0.T) / 2
    Ad = HermitianMatrix.from_global(A0, nb, grid=grid22, uplo=Uplo.Lower)
    monkeypatch.setattr(BaseMatrix, "to_global", boom)
    monkeypatch.setattr(HermitianMatrix, "full_global", boom, raising=True)
    w, Z = eig.heev(Ad)
    monkeypatch.undo()
    Zg = np.asarray(Z.to_global())
    w = np.asarray(w)
    err = np.abs(A0 @ Zg - Zg * w[None, :]).max() / (np.abs(A0).max() * n)
    assert err < 1e-12, err


def test_native_hb2st_ranged_chunks_match_whole(rng):
    """Chunked ranged chase + overlapped upload (hb2st_host_device) must
    be bit-identical to the whole-chase path (the band IS the state)."""
    if not native.hb2st_available():
        pytest.skip("no C compiler for the native chaser")
    n, b = 129, 16
    G = _lower_band(rng, n, b)
    Gfull = G + np.tril(G, -1).T
    n_pad = n + 4 * b + 8
    W = np.asarray(bulge.band_to_storage(jnp.asarray(Gfull), b, n_pad))
    d1, e1, VS1, TAUS1 = native.hb2st_host(W, n, b)
    d2, e2, VS2, TAUS2 = native.hb2st_host_device(W, n, b, chunk_sweeps=17)
    np.testing.assert_array_equal(d1, np.asarray(d2))
    np.testing.assert_array_equal(e1, np.asarray(e2))
    np.testing.assert_array_equal(VS1, np.asarray(VS2))
    np.testing.assert_array_equal(TAUS1, np.asarray(TAUS2))
