"""Serving layer tests: slate_tpu/serve (buckets, cache, service, api).

A module-scoped ExecutableCache is shared across tests so each
(bucket, batch) executable compiles once for the whole file; services
are built per test (cheap — one thread) against small bucket floors.
"""

import json
import os
import time

import numpy as np
import pytest

from slate_tpu.aux import metrics
from slate_tpu.exceptions import NumericalError
from slate_tpu.serve import buckets as bk
from slate_tpu.serve.cache import ExecutableCache, direct_call
from slate_tpu.serve.service import DeadlineExceeded, Rejected, SolverService

FLOOR = 16
NRHS_FLOOR = 4


@pytest.fixture(autouse=True)
def metrics_on():
    """Serving metrics are part of the contract under test."""
    metrics.off()
    metrics.reset()
    metrics.on()
    yield
    metrics.off()
    metrics.reset()


@pytest.fixture(scope="module")
def shared_cache():
    return ExecutableCache(manifest_path=None)


@pytest.fixture
def svc(shared_cache):
    s = SolverService(
        cache=shared_cache, batch_max=4, batch_window_s=0.005,
        dim_floor=FLOOR, nrhs_floor=NRHS_FLOOR,
    )
    yield s
    s.stop()


def _tol(dtype):
    # padded-then-cropped must match the direct driver within a few
    # driver-tolerance units; the ops themselves are identical modulo
    # the identity pad block
    return 200 * np.finfo(np.dtype(dtype)).eps


# ---------------------------------------------------------------------------
# buckets
# ---------------------------------------------------------------------------


def test_halving_bucket_matches_doubling_lattice():
    for n in (1, 3, 16, 17, 64, 65, 100, 1000):
        got = bk.bucket_dim(n, floor=16)
        # same lattice as the drivers' halving rule under a pow2 cap
        assert got == bk.halving_bucket(n, total=4096, floor=16)
        assert got >= n and (got == 16 or got // 2 < n)
    with pytest.raises(ValueError):
        bk.bucket_dim(0)


def test_size_bucket_runs_matches_eig():
    from slate_tpu.drivers.eig import _size_bucket_runs

    heights = [100, 90, 60, 40, 10, 5]
    assert list(_size_bucket_runs(heights, 128, floor=16)) == list(
        bk.size_bucket_runs(heights, 128, floor=16)
    )
    # the documented non-pow2 case: halvings of total, not pow2ceil
    assert bk.halving_bucket(2500, 6144, floor=1024) == 3072


def test_bucket_mn_keeps_room_for_unit_pad_columns():
    Mb, Nb = bk.bucket_mn(16, 13, floor=16)
    # pad columns (3) would not fit below m=16 rows at Mb=16
    assert Mb - 16 >= Nb - 13


def test_bucketkey_manifest_roundtrip(tmp_path):
    k1 = bk.bucket_for("gesv", 50, 50, 3, np.float64, floor=FLOOR)
    k2 = bk.bucket_for("gels", 70, 30, 2, np.float32, floor=FLOOR)
    text = bk.manifest_dumps([(k1, 4), (k2, 1)])
    back = bk.manifest_loads(text)
    assert (k1, 4) in back and (k2, 1) in back
    assert k1 == bk.BucketKey.from_json(k1.to_json())


def _legacy_entry(**drop):
    """One manifest entry as a pre-PR3/PR5 writer would have produced
    it: no schedule and/or no precision key."""
    e = {
        "routine": "gesv", "m": 16, "n": 16, "nrhs": 4,
        "dtype": "float64", "nb": 16, "tag": "", "batch": 1,
        "schedule": "flat", "precision": "mixed",
    }
    for k in drop:
        del e[k]
    return e


@pytest.mark.parametrize(
    "drop", [("schedule",), ("precision",), ("schedule", "precision")],
    ids=["no-schedule", "no-precision", "neither"],
)
def test_legacy_manifest_roundtrip_defaults(drop):
    """Entries from manifests that predate the PR3 ``schedule`` and
    PR5 ``precision`` BucketKey fields must load with the documented
    defaults ("auto"/"full") and re-serialize canonically (both keys
    present, so the manifest upgrades in place on the next flush)."""
    legacy = _legacy_entry(**{k: 1 for k in drop})
    text = json.dumps({"version": 1, "entries": [legacy]})
    [(key, batch)] = bk.manifest_loads(text)
    assert key.schedule == ("auto" if "schedule" in drop else "flat")
    assert key.precision == ("full" if "precision" in drop else "mixed")
    assert batch == 1
    canon = json.loads(bk.manifest_dumps([(key, batch)]))
    [entry] = canon["entries"]
    assert entry["schedule"] == key.schedule  # re-serialized explicitly
    assert entry["precision"] == key.precision
    # and the canonical form round-trips to the identical key
    assert bk.manifest_loads(json.dumps(canon)) == [(key, batch)]


def test_corrupt_manifest_counts_and_warns_once(tmp_path):
    """A corrupt warmup manifest must never block serving — but it is
    counted (serve.manifest_corrupt) and warned about once per path,
    not silently swallowed."""
    path = str(tmp_path / "broken.json")
    with open(path, "w") as f:
        f.write('{"version": 1, "entries": [{"routine": "gesv"')  # torn
    with metrics.deltas() as d:
        with pytest.warns(RuntimeWarning, match="broken.json"):
            c = ExecutableCache(manifest_path=path)
        assert c.entries() == []  # serving continues, recipe empty
        import warnings as _warnings

        with _warnings.catch_warnings():  # second open: counted, no spam
            _warnings.simplefilter("error")
            c2 = ExecutableCache(manifest_path=path)
        assert c2.entries() == []
    assert d.get("serve.manifest_corrupt") == 2
    # entries missing required keys are also a corrupt manifest, not a
    # crash (KeyError path)
    with open(path, "w") as f:
        f.write('{"version": 1, "entries": [{"routine": "gesv"}]}')
    with metrics.deltas() as d:
        c3 = ExecutableCache(manifest_path=path)
        assert c3.entries() == []
    assert d.get("serve.manifest_corrupt") == 1


# ---------------------------------------------------------------------------
# pad correctness: padded-then-cropped == direct driver (ISSUE satellite)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
@pytest.mark.parametrize("n,nrhs", [(10, 2), (20, 3)])
def test_pad_correctness_gesv(svc, dtype, n, nrhs):
    rng = np.random.default_rng(n)
    A = rng.standard_normal((n, n)).astype(dtype) + n * np.eye(n, dtype=dtype)
    B = rng.standard_normal((n, nrhs)).astype(dtype)
    got = svc.submit("gesv", A, B).result(timeout=120)
    ref = direct_call("gesv", A, B)
    assert got.shape == (n, nrhs) and got.dtype == A.dtype
    denom = max(np.abs(ref).max(), 1.0)
    assert np.abs(got - ref).max() / denom < _tol(dtype)


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_pad_correctness_posv(svc, dtype):
    n, nrhs = 20, 3
    rng = np.random.default_rng(7)
    G = rng.standard_normal((n, n))
    A = (G @ G.T + n * np.eye(n)).astype(dtype)
    B = rng.standard_normal((n, nrhs)).astype(dtype)
    got = svc.submit("posv", A, B).result(timeout=120)
    ref = direct_call("posv", A, B)
    denom = max(np.abs(ref).max(), 1.0)
    assert np.abs(got - ref).max() / denom < _tol(dtype)


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
@pytest.mark.parametrize("m,n", [(24, 24), (40, 12)])
def test_pad_correctness_gels(svc, dtype, m, n):
    """Square and tall least squares across f32/f64."""
    rng = np.random.default_rng(m + n)
    A = rng.standard_normal((m, n)).astype(dtype)
    B = rng.standard_normal((m, 2)).astype(dtype)
    got = svc.submit("gels", A, B).result(timeout=120)
    ref = np.linalg.lstsq(
        A.astype(np.float64), B.astype(np.float64), rcond=None
    )[0]
    assert got.shape == (n, 2)
    assert np.abs(got - ref).max() < 1e4 * np.finfo(np.dtype(dtype)).eps


def test_gels_underdetermined_direct(svc):
    """m < n takes the direct driver (minimum-norm), counted as
    direct-only routing, not as a fallback."""
    rng = np.random.default_rng(3)
    A = rng.standard_normal((10, 30))
    B = rng.standard_normal((10, 2))
    with metrics.deltas() as d:
        got = svc.submit("gels", A, B).result(timeout=120)
    ref = np.linalg.lstsq(A, B, rcond=None)[0]
    assert np.abs(got - ref).max() < 1e-8
    assert d.get("serve.direct_only") == 1
    assert d.get("serve.fallbacks") == 0


# ---------------------------------------------------------------------------
# coalescing + steady-state compile-free serving (acceptance criteria)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_steady_state_compile_free_after_warmup(shared_cache, tmp_path):
    # slow: 18.5 s of tier-1 wall (full warmup of both buckets' batch
    # points); the tier-1 zero-compile acceptance now rides on
    # test_artifacts.test_restart_drill_restore_then_zero_compiles,
    # and run_tests.py --coldstart drills the cross-process version
    rng = np.random.default_rng(0)
    n1, n2 = 10, 20
    A1 = rng.standard_normal((n1, n1)) + n1 * np.eye(n1)
    B1 = rng.standard_normal((n1, 2))
    G = rng.standard_normal((n2, n2))
    A2 = G @ G.T + n2 * np.eye(n2)
    B2 = rng.standard_normal((n2, 3))

    # phase 1: drive traffic through a paused-then-started service so
    # batches coalesce; capture the manifest it grew
    manifest = str(tmp_path / "warmup.json")
    s1 = SolverService(
        cache=shared_cache, batch_max=4, dim_floor=FLOOR,
        nrhs_floor=NRHS_FLOOR, start=False,
    )
    futs = [s1.submit("gesv", A1 + i * 0.01 * np.eye(n1), B1) for i in range(4)]
    futs += [s1.submit("posv", A2, B2)]
    s1.start()
    for f in futs:
        f.result(timeout=120)
    s1.stop()
    shared_cache.save_manifest(manifest)

    # phase 2: fresh cache + service; warmup the manifest, then a mixed
    # stream of >= 20 requests must not compile anything new
    cache2 = ExecutableCache(manifest_path=None)
    s2 = SolverService(
        cache=cache2, batch_max=4, dim_floor=FLOOR,
        nrhs_floor=NRHS_FLOOR, start=False,
    )
    compiled = cache2.warmup(manifest, batch_max=4)
    assert compiled >= 4  # both batch points of both buckets
    with metrics.deltas() as d:
        futs = []
        for i in range(8):
            futs.append(s2.submit("gesv", A1 + i * 1e-3 * np.eye(n1), B1))
            futs.append(s2.submit("posv", A2 + i * 1e-3 * np.eye(n2), B2))
        s2.start()
        for f in futs:
            f.result(timeout=120)
        for i in range(6):  # lone sequential requests hit the b1 point
            got = s2.submit("gesv", A1, B1).result(timeout=120)
        assert d.get("serve.requests") >= 20
        assert d.get("jit.compilations") == 0, "steady state must not compile"
        assert d.get("serve.batched") >= 1
        assert d.get("serve.bucket_pad_waste") > 0
    ref = direct_call("gesv", A1, B1)
    assert np.abs(got - ref).max() < _tol(np.float64) * np.abs(ref).max()
    s2.stop()


def test_coalescing_batches_same_bucket(svc, shared_cache):
    rng = np.random.default_rng(1)
    n = 10
    B = rng.standard_normal((n, 2))
    mats = [rng.standard_normal((n, n)) + n * np.eye(n) for _ in range(6)]
    svc.stop()
    s = SolverService(
        cache=shared_cache, batch_max=4, dim_floor=FLOOR,
        nrhs_floor=NRHS_FLOOR, start=False,
    )
    with metrics.deltas() as d:
        futs = [s.submit("gesv", A, B) for A in mats]
        s.start()
        out = [f.result(timeout=120) for f in futs]
    assert d.get("serve.batched") >= 1
    assert d.get("serve.batched_requests") >= 4
    for A, X in zip(mats, out):
        assert np.abs(A @ X - B).max() < 1e-9
    s.stop()


# ---------------------------------------------------------------------------
# deadlines, backpressure, failures
# ---------------------------------------------------------------------------


def test_deadline_miss_cancels_queued_request(shared_cache):
    rng = np.random.default_rng(2)
    n = 10
    A = rng.standard_normal((n, n)) + n * np.eye(n)
    B = rng.standard_normal((n, 1))
    s = SolverService(
        cache=shared_cache, batch_max=2, dim_floor=FLOOR,
        nrhs_floor=NRHS_FLOOR, start=False,
    )
    with metrics.deltas() as d:
        fut = s.submit("gesv", A, B, deadline=0.01)
        time.sleep(0.05)  # expires while the worker is paused
        s.start()
        with pytest.raises(DeadlineExceeded):
            fut.result(timeout=120)
        assert d.get("serve.deadline_miss") == 1
        # the split: a queued cancel, NOT a late finish
        assert d.get("serve.deadline_miss_queued") == 1
        assert d.get("serve.deadline_miss_late") == 0
    s.stop()


def test_queue_full_rejected(shared_cache):
    rng = np.random.default_rng(4)
    n = 10
    A = rng.standard_normal((n, n)) + n * np.eye(n)
    B = rng.standard_normal((n, 1))
    s = SolverService(
        cache=shared_cache, max_queue=2, dim_floor=FLOOR,
        nrhs_floor=NRHS_FLOOR, start=False,
    )
    f1 = s.submit("gesv", A, B)
    f2 = s.submit("gesv", A, B)
    with metrics.deltas() as d:
        with pytest.raises(Rejected):
            s.submit("gesv", A, B)
        assert d.get("serve.rejected") == 1
    s.start()
    assert f1.result(timeout=120).shape == (n, 1)
    assert f2.result(timeout=120).shape == (n, 1)
    s.stop()


def test_stop_resolves_pending_futures(shared_cache):
    rng = np.random.default_rng(5)
    n = 10
    A = rng.standard_normal((n, n)) + n * np.eye(n)
    B = rng.standard_normal((n, 1))
    s = SolverService(cache=shared_cache, dim_floor=FLOOR,
                      nrhs_floor=NRHS_FLOOR, start=False)
    fut = s.submit("gesv", A, B)
    s.stop()
    with pytest.raises(Rejected):
        fut.result(timeout=10)


def test_retry_then_fallback_and_degrade(shared_cache):
    """A failing batched path retries per policy, falls back to the
    direct driver, and degrades the bucket after repeated failures."""

    class FlakyCache(ExecutableCache):
        def __init__(self):
            super().__init__(manifest_path=None)
            self.fails = 0

        def run(self, key, A_batch, B_batch):
            self.fails += 1
            raise RuntimeError("injected executable failure")

    rng = np.random.default_rng(6)
    n = 10
    A = rng.standard_normal((n, n)) + n * np.eye(n)
    B = rng.standard_normal((n, 1))
    fc = FlakyCache()
    s = SolverService(
        cache=fc, batch_max=2, dim_floor=FLOOR, nrhs_floor=NRHS_FLOOR,
        degrade_after=2,
    )
    with metrics.deltas() as d:
        X = s.submit("gesv", A, B, retries=1).result(timeout=120)
        assert np.abs(A @ X - B).max() < 1e-9  # fallback result is real
        assert fc.fails == 2  # first try + one retry
        assert d.get("serve.fallbacks") == 1
        assert d.get("serve.degraded") == 1  # streak hit degrade_after
        # degraded bucket goes straight to the direct driver now
        X2 = s.submit("gesv", A, B).result(timeout=120)
        assert fc.fails == 2
        assert d.get("serve.fallbacks") == 2
        assert np.abs(A @ X2 - B).max() < 1e-9
    s.stop()


def test_posv_not_spd_raises_numerical(svc):
    n = 10
    A = -np.eye(n)
    B = np.ones((n, 1))
    with pytest.raises(NumericalError):
        svc.submit("posv", A, B).result(timeout=120)


def test_bad_shapes_rejected_at_submit(svc):
    with pytest.raises(ValueError):
        svc.submit("gesv", np.ones((4, 5)), np.ones((4, 1)))
    with pytest.raises(ValueError):
        svc.submit("gesv", np.ones((4, 4)), np.ones((3, 1)))


# ---------------------------------------------------------------------------
# warmup manifest env + api surface
# ---------------------------------------------------------------------------


def test_warmup_env_manifest_records(tmp_path, monkeypatch):
    path = str(tmp_path / "m.json")
    monkeypatch.setenv("SLATE_TPU_WARMUP", path)
    c = ExecutableCache()  # picks the env path up
    assert c.manifest_path == path
    key = bk.bucket_for("gesv", 10, 10, 1, np.float64, floor=FLOOR)
    c.ensure_manifest(key, (1,))
    assert os.path.exists(path)
    c2 = ExecutableCache(manifest_path=path)
    assert (key, 1) in c2.entries()


def test_api_singleton_and_options(monkeypatch):
    from slate_tpu import serve
    from slate_tpu.enums import Option

    svc = serve.configure(
        {Option.ServeQueueLimit: 7}, dim_floor=FLOOR, nrhs_floor=NRHS_FLOOR
    )
    try:
        assert svc.max_queue == 7
        assert serve.get_service() is svc
        assert serve.get_cache() is svc.cache
    finally:
        serve.shutdown()
