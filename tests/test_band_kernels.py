"""Windowed band kernel tests (ops/band_kernels.py) and the band-aware
driver routes (reference: test/test_pbsv.cc, test_gbsv.cc, test_tbsm.cc
acceptance: norm-based residuals at LAPACK tolerance)."""

import numpy as np
import pytest

from slate_tpu.drivers import band
from slate_tpu.enums import Diag, Op, Side, Uplo
from slate_tpu.matrix.base import conj_transpose, transpose
from slate_tpu.matrix.matrix import (
    BandMatrix,
    HermitianBandMatrix,
    Matrix,
    TriangularBandMatrix,
)
from slate_tpu.ops import band_kernels


def _spd_band(rng, n, kd, dtype=np.float64):
    i = np.arange(n)
    mask = np.abs(i[:, None] - i[None, :]) <= kd
    A = rng.standard_normal((n, n)).astype(dtype)
    if np.issubdtype(dtype, np.complexfloating):
        A = A + 1j * rng.standard_normal((n, n))
    A = (A + A.conj().T) / 2 * mask
    A = A + (2 * kd + 2) * np.eye(n)
    return A


def _gen_band(rng, n, kl, ku):
    i = np.arange(n)
    mask = ((i[None, :] - i[:, None]) <= ku) & ((i[:, None] - i[None, :]) <= kl)
    return (rng.standard_normal((n, n)) + 2 * np.eye(n)) * mask


@pytest.mark.parametrize("n,kd", [(200, 8), (333, 17), (128, 1)])
def test_band_potrf_lower_kernel(rng, n, kd):
    A = _spd_band(rng, n, kd)
    L = np.asarray(band_kernels.band_potrf_lower(A, kd))
    assert np.abs(np.triu(L, 1)).max() == 0
    i = np.arange(n)
    assert np.abs(L[(i[:, None] - i[None, :]) > kd]).max() == 0
    res = np.abs(L @ L.T - A).max() / np.abs(A).max()
    assert res < 1e-13 * n, res


def test_band_potrf_complex(rng):
    n, kd = 150, 6
    A = _spd_band(rng, n, kd, np.complex128)
    L = np.asarray(band_kernels.band_potrf_lower(A, kd))
    res = np.abs(L @ L.conj().T - A).max() / np.abs(A).max()
    assert res < 1e-13 * n, res


@pytest.mark.parametrize("n,kd,unit", [(180, 7, False), (255, 16, True)])
def test_band_trsm_lower_kernel(rng, n, kd, unit):
    i = np.arange(n)
    mask = (i[:, None] - i[None, :] <= kd) & (i[:, None] >= i[None, :])
    # keep the substitution well-conditioned: unit-lower with O(1)
    # multipliers has exp(n) solution growth, which no solver survives
    L = rng.standard_normal((n, n)) * mask * (0.1 / np.sqrt(kd))
    np.fill_diagonal(L, 1.0 if unit else np.abs(L.diagonal()) + n)
    B = rng.standard_normal((n, 5))
    X = np.asarray(band_kernels.band_trsm_lower(L, B, kd, unit_diag=unit))
    res = np.abs(L @ X - B).max() / np.abs(B).max()
    assert res < 1e-10, res


@pytest.mark.parametrize("n,kl,ku", [(200, 5, 3), (257, 12, 9), (150, 1, 1)])
def test_band_getrf_getrs_kernel(rng, n, kl, ku):
    A = _gen_band(rng, n, kl, ku)
    lu2d, lperms, perm, w = band_kernels.band_getrf(A, kl, ku)
    lu2d_np, perm_np = np.asarray(lu2d), np.asarray(perm)
    U = np.triu(lu2d_np)
    L = np.tril(lu2d_np, -1)
    # U fill-in bounded by kl + ku; L multipliers within the window span
    i = np.arange(n)
    assert np.abs(U[(i[None, :] - i[:, None]) > kl + ku]).max() == 0
    assert np.abs(L[(i[:, None] - i[None, :]) >= w + kl]).max() == 0
    assert sorted(perm_np.tolist()) == list(range(n))
    # the factorization is validated through its interleaved solve
    B = rng.standard_normal((n, 4))
    X = np.asarray(band_kernels.band_getrs(lu2d, lperms, w, kl, ku, B))
    res = np.abs(A @ X - B).max() / np.abs(B).max()
    assert res < 1e-10 * n, res


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("uplo", [Uplo.Lower, Uplo.Upper])
def test_pbsv_band_aware(rng, uplo):
    n, kd, nb = 192, 9, 32
    A0 = _spd_band(rng, n, kd)
    B0 = rng.standard_normal((n, 4))
    A = HermitianBandMatrix(
        Matrix.from_global(A0, nb).data,
        Matrix.from_global(A0, nb).layout,
        kd=kd,
        uplo=uplo,
    )
    B = Matrix.from_global(B0, nb)
    X, L, info = band.pbsv(A, B)
    assert int(info) == 0
    res = np.abs(A0 @ np.asarray(X.to_global()) - B0).max() / np.abs(B0).max()
    assert res < 1e-11, res


def test_gbsv_band_aware(rng):
    n, kl, ku, nb = 200, 6, 4, 32
    A0 = _gen_band(rng, n, kl, ku)
    B0 = rng.standard_normal((n, 3))
    A = BandMatrix.from_global(A0, kl, ku, nb)
    B = Matrix.from_global(B0, nb)
    X, LU, piv, info = band.gbsv(A, B)
    assert int(info) == 0
    res = np.abs(A0 @ np.asarray(X.to_global()) - B0).max() / np.abs(B0).max()
    assert res < 1e-10, res


@pytest.mark.parametrize("uplo", [Uplo.Lower, Uplo.Upper])
@pytest.mark.parametrize("opname", ["n", "t"])
@pytest.mark.parametrize("side", [Side.Left, Side.Right])
def test_tbsm_band_aware(rng, uplo, opname, side):
    n, kd, nb = 160, 8, 32
    i = np.arange(n)
    if uplo == Uplo.Lower:
        mask = (i[:, None] - i[None, :] <= kd) & (i[:, None] >= i[None, :])
    else:
        mask = (i[None, :] - i[:, None] <= kd) & (i[:, None] <= i[None, :])
    T0 = rng.standard_normal((n, n)) * mask + (n + 2) * np.eye(n)
    B0 = rng.standard_normal((n, 6) if side == Side.Left else (6, n))
    T = TriangularBandMatrix(
        Matrix.from_global(T0, nb).data,
        Matrix.from_global(T0, nb).layout,
        kd=kd,
        uplo=uplo,
    )
    A = T if opname == "n" else transpose(T)
    M = T0 if opname == "n" else T0.T
    B = Matrix.from_global(B0, nb)
    X = band.tbsm(side, 1.0, A, B)
    Xg = np.asarray(X.to_global())
    want = (
        np.linalg.solve(M, B0)
        if side == Side.Left
        else np.linalg.solve(M.T, B0.T).T
    )
    np.testing.assert_allclose(Xg, want, atol=1e-10)
