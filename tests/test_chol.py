"""Cholesky family tests (reference: test/test_posv.cc, test_potri.cc,
test_trtri.cc; acceptance = norm-scaled residual <= tol)."""

import numpy as np
import pytest

from slate_tpu.drivers import chol
from slate_tpu.enums import Option, Uplo
from slate_tpu.matrix.matrix import HermitianMatrix, Matrix, TriangularMatrix
from slate_tpu.testing import checks


def _spd(rng, n, dtype=np.float64):
    A = rng.standard_normal((n, n))
    if np.dtype(dtype).kind == "c":
        A = A + 1j * rng.standard_normal((n, n))
    A = A @ A.conj().T + n * np.eye(n)
    return A.astype(dtype)


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("n,nb", [(64, 16), (50, 16), (33, 8)])
def test_potrf_single(rng, dtype, n, nb):
    A0 = _spd(rng, n, dtype)
    A = HermitianMatrix.from_global(A0, nb, uplo=Uplo.Lower)
    L, info = chol.potrf(A)
    assert int(info) == 0
    Lg = np.tril(np.asarray(L.to_global()))
    err = checks.factor_residual(A0, Lg)
    assert checks.passed(err, dtype, factor=30), err


def test_potrf_upper(rng):
    A0 = _spd(rng, 48)
    A = HermitianMatrix.from_global(A0, 16, uplo=Uplo.Upper)
    U, info = chol.potrf(A)
    assert int(info) == 0 and U.uplo == Uplo.Upper
    Ug = np.triu(np.asarray(U.to_global()))
    err = checks.factor_residual(A0, Ug.conj().T)
    assert checks.passed(err, np.float64, factor=30), err


@pytest.mark.parametrize("n,nb", [(64, 16), (96, 16), (72, 8), (90, 16), (53, 8)])
def test_potrf_distributed(rng, grid22, n, nb):
    A0 = _spd(rng, n)
    A = HermitianMatrix.from_global(A0, nb, grid=grid22, uplo=Uplo.Lower)
    L, info = chol.potrf(A)
    assert int(info) == 0
    Lg = np.tril(np.asarray(L.to_global()))
    err = checks.factor_residual(A0, Lg)
    assert checks.passed(err, np.float64, factor=30), err


def test_potrf_distributed_complex_4x2(rng, grid42):
    n, nb = 64, 8
    A0 = _spd(rng, n, np.complex128)
    A = HermitianMatrix.from_global(A0, nb, grid=grid42, uplo=Uplo.Lower)
    L, info = chol.potrf(A)
    assert int(info) == 0
    Lg = np.tril(np.asarray(L.to_global()))
    err = checks.factor_residual(A0, Lg)
    assert checks.passed(err, np.complex128, factor=30), err


def test_potrf_spmd_matches_global(rng, grid22):
    """The explicit mesh algorithm must agree with XLA's cholesky."""
    n, nb = 80, 16
    A0 = _spd(rng, n)
    L_ref = np.linalg.cholesky(A0)
    A = HermitianMatrix.from_global(A0, nb, grid=grid22, uplo=Uplo.Lower)
    L, _ = chol.potrf(A)
    np.testing.assert_allclose(np.tril(np.asarray(L.to_global())), L_ref, atol=1e-9)


def test_potrf_not_spd(rng):
    A0 = -np.eye(16)
    A = HermitianMatrix.from_global(A0, 8, uplo=Uplo.Lower)
    _, info = chol.potrf(A)
    assert int(info) > 0


def test_posv(rng):
    n, nrhs = 64, 8
    A0 = _spd(rng, n)
    B0 = rng.standard_normal((n, nrhs))
    A = HermitianMatrix.from_global(A0, 16, uplo=Uplo.Lower)
    B = Matrix.from_global(B0, 16)
    X, L, info = chol.posv(A, B)
    assert int(info) == 0
    err = checks.solve_residual(A0, np.asarray(X.to_global()), B0)
    assert checks.passed(err, np.float64, factor=30), err


def test_posv_distributed(rng, grid22):
    n, nrhs = 96, 16
    A0 = _spd(rng, n)
    B0 = rng.standard_normal((n, nrhs))
    A = HermitianMatrix.from_global(A0, 16, grid=grid22, uplo=Uplo.Lower)
    B = Matrix.from_global(B0, 16, grid=grid22)
    X, L, info = chol.posv(A, B)
    assert int(info) == 0
    err = checks.solve_residual(A0, np.asarray(X.to_global()), B0)
    assert checks.passed(err, np.float64, factor=30), err


def test_trtri(rng):
    n = 40
    T0 = np.tril(rng.standard_normal((n, n))) + n * np.eye(n)
    T = TriangularMatrix.from_global(T0, 16, uplo=Uplo.Lower)
    Tinv = chol.trtri(T)
    got = np.tril(np.asarray(Tinv.to_global()))
    np.testing.assert_allclose(got @ T0, np.eye(n), atol=1e-10)


def test_potri(rng):
    n = 32
    A0 = _spd(rng, n)
    A = HermitianMatrix.from_global(A0, 8, uplo=Uplo.Lower)
    L, _ = chol.potrf(A)
    Ainv = chol.potri(L)
    got = np.asarray(Ainv.full_global())
    np.testing.assert_allclose(got @ A0, np.eye(n), atol=1e-8)


def test_posv_mixed(rng):
    n, nrhs = 64, 4
    A0 = _spd(rng, n)
    B0 = rng.standard_normal((n, nrhs))
    A = HermitianMatrix.from_global(A0, 16, uplo=Uplo.Lower)
    B = Matrix.from_global(B0, 16)
    X, info, iters = chol.posv_mixed(A, B)
    assert int(info) == 0
    err = checks.solve_residual(A0, np.asarray(X.to_global()), B0)
    # refinement should reach near working precision
    assert err < 1e-12, (err, iters)
    assert iters >= 0  # no fallback needed for well-conditioned A


def test_pocondest(rng):
    n = 32
    A0 = _spd(rng, n)
    from slate_tpu.drivers.aux import norm as mat_norm
    from slate_tpu.enums import Norm

    A = HermitianMatrix.from_global(A0, 8, uplo=Uplo.Lower)
    anorm = mat_norm(Norm.One, A)
    L, _ = chol.potrf(A)
    rcond = float(chol.pocondest(L, anorm))
    ref = 1.0 / (np.linalg.norm(A0, 1) * np.linalg.norm(np.linalg.inv(A0), 1))
    # Hager/Higham estimates a lower bound on ||A^-1||_1, so rcond is an
    # upper bound on the true rcond, reliably within a small factor
    assert ref * 0.999 <= rcond <= 3.0 * ref, (rcond, ref)


def test_posv_mixed_gmres(rng):
    n, nrhs = 48, 4
    A0 = _spd(rng, n)
    B0 = rng.standard_normal((n, nrhs))
    A = HermitianMatrix.from_global(A0, 16, uplo=Uplo.Lower)
    B = Matrix.from_global(B0, 16)
    X, info, iters = chol.posv_mixed_gmres(A, B)
    assert int(info) == 0
    err = np.abs(np.asarray(X.to_global()) - np.linalg.solve(A0, B0)).max()
    assert err < 1e-12, (err, iters)
