"""Grid/blocksize lambda tests (reference: unit_test/test_func.cc)."""

from slate_tpu.enums import GridOrder
from slate_tpu import func


def test_uniform_blocksize():
    size = func.uniform_blocksize(100, 16)
    assert [size(i) for i in range(7)] == [16] * 6 + [4]
    size = func.uniform_blocksize(64, 16)
    assert [size(i) for i in range(4)] == [16] * 4


def test_max_blocksize():
    assert func.max_blocksize(7, func.uniform_blocksize(100, 16)) == 16
    assert func.max_blocksize(0, func.uniform_blocksize(100, 16)) == 0


def test_process_2d_grid_col():
    f = func.process_2d_grid(GridOrder.Col, 2, 3)
    assert f((0, 0)) == 0
    assert f((1, 0)) == 1
    assert f((0, 1)) == 2
    assert f((2, 3)) == 0  # wraps


def test_process_2d_grid_row():
    f = func.process_2d_grid(GridOrder.Row, 2, 3)
    assert f((0, 0)) == 0
    assert f((0, 1)) == 1
    assert f((1, 0)) == 3


def test_device_2d_grid_blocks():
    f = func.device_2d_grid(GridOrder.Col, 2, 2, 2, 2)
    # tiles (0..1, 0..1) all map to device 0
    assert {f((i, j)) for i in range(2) for j in range(2)} == {0}
    assert f((2, 0)) == 1


def test_transpose_grid():
    f = func.process_2d_grid(GridOrder.Col, 2, 3)
    ft = func.transpose_grid(f)
    assert ft((1, 2)) == f((2, 1))


def test_is_2d_cyclic_grid_detects():
    for order in (GridOrder.Col, GridOrder.Row):
        f = func.process_2d_grid(order, 2, 3)
        ok, detected, p, q = func.is_2d_cyclic_grid(8, 9, f)
        assert ok and p == 2 and q == 3 and detected == order


def test_is_2d_cyclic_grid_rejects():
    f = func.round_robin(4)
    ok, order, p, q = func.is_2d_cyclic_grid(8, 8, f)
    assert not ok and order == GridOrder.Unknown
