"""QR/LQ/gels tests (reference: test/test_geqrf.cc, test_gels.cc;
orthogonality + factorization residual acceptance)."""

import numpy as np
import pytest

from slate_tpu.drivers import qr
from slate_tpu.enums import MethodGels, Op, Option, Side
from slate_tpu.matrix.matrix import Matrix
from slate_tpu.testing import checks


def _mk(rng, m, n, dtype=np.float64):
    A = rng.standard_normal((m, n))
    if np.dtype(dtype).kind == "c":
        A = A + 1j * rng.standard_normal((m, n))
    return A.astype(dtype)


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("m,n,nb", [(64, 64, 16), (80, 48, 16), (50, 30, 8), (33, 33, 8)])
def test_geqrf_single(rng, dtype, m, n, nb):
    A0 = _mk(rng, m, n, dtype)
    A = Matrix.from_global(A0, nb)
    fac, T = qr.geqrf(A)
    Q = np.asarray(qr.ungqr(fac, T).to_global())
    R = np.triu(np.asarray(fac.to_global()))[: min(m, n), :]
    # orthogonality
    orth = checks.ortho_residual(Q)
    assert checks.passed(orth, dtype, factor=30), orth
    # reconstruction
    err = checks.factor_residual(A0, Q, R)
    assert checks.passed(err, dtype, factor=30), err


@pytest.mark.parametrize(
    "m,n,nb",
    [(96, 96, 16), (96, 64, 16), (64, 64, 8), (90, 70, 16),
     pytest.param(75, 75, 8, marks=pytest.mark.slow)],
)
def test_geqrf_distributed(rng, grid22, m, n, nb):
    A0 = _mk(rng, m, n)
    A = Matrix.from_global(A0, nb, grid=grid22)
    fac, T = qr.geqrf(A)
    Q = np.asarray(qr.ungqr(fac, T).to_global())
    R = np.triu(np.asarray(fac.to_global()))[: min(m, n), :]
    orth = checks.ortho_residual(Q)
    assert checks.passed(orth, np.float64, factor=30), orth
    err = checks.factor_residual(A0, Q, R)
    assert checks.passed(err, np.float64, factor=30), err


def test_geqrf_distributed_complex_4x2(rng, grid42):
    m, n, nb = 64, 48, 8
    A0 = _mk(rng, m, n, np.complex128)
    A = Matrix.from_global(A0, nb, grid=grid42)
    fac, T = qr.geqrf(A)
    Q = np.asarray(qr.ungqr(fac, T).to_global())
    R = np.triu(np.asarray(fac.to_global()))[:n, :]
    assert checks.passed(checks.ortho_residual(Q), np.complex128, factor=30)
    assert checks.passed(checks.factor_residual(A0, Q, R), np.complex128, factor=30)


@pytest.mark.parametrize("side", [Side.Left, Side.Right])
@pytest.mark.parametrize("op", [Op.NoTrans, Op.ConjTrans])
def test_unmqr_ops(rng, side, op):
    m, n = 48, 48
    A0 = _mk(rng, m, n)
    C0 = _mk(rng, m, n)
    fac, T = qr.geqrf(Matrix.from_global(A0, 16))
    Qm = np.asarray(qr.ungqr(fac, T).to_global())
    C2 = qr.unmqr(side, op, fac, T, Matrix.from_global(C0, 16))
    Qop = Qm.conj().T if op == Op.ConjTrans else Qm
    ref = Qop @ C0 if side == Side.Left else C0 @ Qop
    np.testing.assert_allclose(np.asarray(C2.to_global()), ref, atol=1e-10)


def test_gelqf_unmlq(rng):
    m, n = 32, 56
    A0 = _mk(rng, m, n)
    A = Matrix.from_global(A0, 8)
    fac, T = qr.gelqf(A)
    L = np.tril(np.asarray(fac.to_global())[:, :m])
    # Q via unmlq on identity rows: Q = unmlq(Left, NoTrans, I_n)
    eyeN = Matrix.from_global(np.eye(n), 8)
    Qfull = np.asarray(qr.unmlq(Side.Left, Op.NoTrans, fac, T, eyeN).to_global())
    Q = Qfull[:m]  # first m rows span the row space... use reconstruction:
    # A = L @ Q with Q the first m rows of the orthogonal factor
    err = checks.factor_residual(A0, L, Q)
    assert checks.passed(err, np.float64, factor=100), err
    orth = checks.ortho_residual(Qfull.T)
    assert checks.passed(orth, np.float64, factor=100), orth


def test_cholqr(rng):
    m, n = 80, 24
    A0 = _mk(rng, m, n)
    Q, R, info = qr.cholqr(Matrix.from_global(A0, 8))
    assert int(info) == 0
    Qg = np.asarray(Q.to_global())
    Rg = np.triu(np.asarray(R.to_global()))
    assert checks.passed(checks.ortho_residual(Qg), np.float64, factor=1000)
    err = checks.factor_residual(A0, Qg, Rg)
    assert checks.passed(err, np.float64, factor=1000), err


@pytest.mark.parametrize("method", [MethodGels.QR, MethodGels.CholQR])
def test_gels_overdetermined(rng, method):
    m, n, nrhs = 64, 32, 4
    A0 = _mk(rng, m, n)
    B0 = _mk(rng, m, nrhs)
    X = qr.gels(
        Matrix.from_global(A0, 16),
        Matrix.from_global(B0, 16),
        opts={Option.MethodGels: method},
    )
    Xg = np.asarray(X.to_global())[:n]
    ref, *_ = np.linalg.lstsq(A0, B0, rcond=None)
    np.testing.assert_allclose(Xg, ref, atol=1e-8)


def test_gels_underdetermined(rng):
    m, n, nrhs = 24, 48, 3
    A0 = _mk(rng, m, n)
    B0 = _mk(rng, m, nrhs)
    X = qr.gels(Matrix.from_global(A0, 8), Matrix.from_global(B0, 8))
    Xg = np.asarray(X.to_global())
    ref, *_ = np.linalg.lstsq(A0, B0, rcond=None)  # min-norm solution
    np.testing.assert_allclose(Xg, ref, atol=1e-8)


def test_gels_distributed(rng, grid22):
    m, n, nrhs = 96, 48, 8
    A0 = _mk(rng, m, n)
    B0 = _mk(rng, m, nrhs)
    X = qr.gels(
        Matrix.from_global(A0, 16, grid=grid22),
        Matrix.from_global(B0, 16, grid=grid22),
    )
    Xg = np.asarray(X.to_global())[:n]
    ref, *_ = np.linalg.lstsq(A0, B0, rcond=None)
    np.testing.assert_allclose(Xg, ref, atol=1e-8)


def test_larft_matches_recurrence(rng):
    """T = inv(D^-1 + strictu(V^H V)) must equal the LAPACK column
    recurrence."""
    from slate_tpu.ops.householder import larft

    m, nb = 20, 6
    V = np.tril(rng.standard_normal((m, nb)), -1)
    V[np.arange(nb), np.arange(nb)] = 1.0
    taus = rng.uniform(0.5, 1.5, nb)
    T = np.asarray(larft(V, taus))
    # column recurrence
    Tr = np.zeros((nb, nb))
    for j in range(nb):
        Tr[j, j] = taus[j]
        if j:
            Tr[:j, j] = -taus[j] * Tr[:j, :j] @ (V[:, :j].T @ V[:, j])
    np.testing.assert_allclose(T, Tr, atol=1e-12)
    # with a dead reflector
    taus[2] = 0.0
    T = np.asarray(larft(V, taus))
    assert np.allclose(T[2, :], 0) and np.allclose(T[:, 2], 0)


@pytest.mark.slow
def test_geqrf_blocked_own_implementation(rng):
    """Our blocked Householder geqrf (used when XLA's primitive is
    unavailable) must match LAPACK semantics."""
    import jax.numpy as jnp

    from slate_tpu.ops.householder import geqrf_blocked, larft, materialize_v

    for dtype in (np.float64, np.complex128):
        m, n = 40, 24
        A0 = _mk(rng, m, n, dtype)
        fac, taus = geqrf_blocked(jnp.asarray(A0), nb=8)
        fac, taus = np.asarray(fac), np.asarray(taus)
        R = np.triu(fac)[:n]
        # rebuild Q from reflectors
        Q = np.eye(m, dtype=dtype)
        for j in range(n):
            v = np.zeros(m, dtype=dtype)
            v[j] = 1.0
            v[j + 1 :] = fac[j + 1 :, j]
            H = np.eye(m, dtype=dtype) - taus[j] * np.outer(v, v.conj())
            Q = Q @ H
        err = checks.factor_residual(A0, Q[:, :n], R)
        assert checks.passed(err, dtype, factor=50), (dtype, err)
