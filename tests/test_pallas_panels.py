"""Pallas panel-kernel schedule family — per-kernel parity against the
jnp reference twins (interpret mode on CPU), exact pivot-order equality
with ``lax.linalg.lu``, driver parity of ``schedule="pallas"`` with the
recursive family, the FLOP-accounting acceptance (pallas exec <=
recursive exec at the flagship point), and the serve round-trip: a
``schedule="pallas"`` bucket warms, persists to artifacts, and restores
compile-free.

All kernels run with ``interpret=True`` here: that lowers the fused
bodies to plain XLA ops, which is exactly how the pallas family reaches
CPU parity and how its serve executables export custom-call-free.
Only f64 rides tier-1 (each dtype costs a distinct compile of the whole
graph on the 2-core box); f32/c64/c128 are marked slow."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

from slate_tpu.ops.chol_kernels import (
    chol_recursive,
    chol_schedule_flops,
    cholesky,
)
from slate_tpu.ops.lu_kernels import getrf_recursive, getrf_schedule_flops
from slate_tpu.ops.pallas import panel_kernels as pk
from slate_tpu.ops.qr_fast import (
    geqrf_pallas,
    geqrf_recursive,
    geqrf_schedule_flops,
)

DTYPES = [
    pytest.param(jnp.float32, marks=pytest.mark.slow),
    jnp.float64,
    pytest.param(jnp.complex64, marks=pytest.mark.slow),
    pytest.param(jnp.complex128, marks=pytest.mark.slow),
]


def _tol(dtype, n):
    eps = float(jnp.finfo(jnp.zeros((), dtype).real.dtype).eps)
    return 50 * n * eps


def _rand(m, n, dtype, seed=0):
    key = jax.random.PRNGKey(seed)
    rt = jnp.zeros((), dtype).real.dtype
    a = jax.random.normal(key, (m, n), rt)
    if jnp.issubdtype(dtype, jnp.complexfloating):
        a = a + 1j * jax.random.normal(jax.random.PRNGKey(seed + 1), (m, n), rt)
    return a.astype(dtype)


def _spd(n, dtype, seed=0):
    a = _rand(n, n, dtype, seed)
    return a @ jnp.conj(a).T + n * jnp.eye(n, dtype=dtype)


def _tri(n, dtype, lower, unit, seed=0):
    # scale the strict triangle so the substitution stays conditioned
    # (a N(0,1) strict triangle amplifies error exponentially in n)
    a = _rand(n, n, dtype, seed) * 0.3
    d = 2.0 + jnp.abs(_rand(n, 1, dtype, seed + 7).real).astype(dtype)
    t = jnp.tril(a, -1) if lower else jnp.triu(a, 1)
    diag = jnp.ones((n,), dtype) if unit else d[:, 0]
    return t + jnp.diag(diag)


# ---------------------------------------------------------------------------
# kernel parity: pallas (interpret) vs the jnp reference twins
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", DTYPES)
def test_chol_base_kernel_parity(dtype):
    n = 64
    G = _spd(n, dtype)
    got = np.tril(np.asarray(pk.chol_base_pallas(G, interpret=True)))
    ref = np.tril(np.asarray(pk.chol_base_reference(G)))
    tol = _tol(dtype, n) * float(np.abs(ref).max())
    assert np.allclose(got, ref, atol=tol)


@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("shape", [(96, 32, None), (96, 32, 80), (160, 24, None)])
def test_panel_lu_kernel_parity(dtype, shape):
    # tall, act-masked, and non-power-of-two panel widths; the fused
    # kernel replicates panel_lu's arithmetic verbatim, so floats and
    # pivots are EXACTLY equal, not merely close
    m, nb, act = shape
    P = _rand(m, nb, dtype, seed=2)
    lu_p, perm_p = pk.panel_lu_pallas(P, act=act, interpret=True)
    lu_r, perm_r = pk.panel_lu_reference(P, act=act)
    assert np.array_equal(np.asarray(perm_p), np.asarray(perm_r))
    assert np.array_equal(np.asarray(lu_p), np.asarray(lu_r))


@pytest.mark.parametrize("dtype", DTYPES)
def test_larft_kernel_parity(dtype):
    # consistent compact-WY data: unit-diagonal V with a small strict
    # lower part and tau = 2/||v||^2 (an exactly unitary reflector), so
    # T^-1 stays well-conditioned — arbitrary (V, tau) pairs make the
    # triangular solve blow up and compare garbage against garbage
    m, nb = 96, 32
    V = jnp.tril(_rand(m, nb, dtype, seed=3), -1) * 0.1 + jnp.eye(
        m, nb, dtype=dtype
    )
    taus = (2.0 / jnp.sum(jnp.abs(V) ** 2, axis=0)).astype(dtype)
    T_p = np.asarray(pk.larft_pallas(V, taus, interpret=True))
    T_r = np.asarray(pk.larft_reference(V, taus))
    tol = _tol(dtype, m) * max(float(np.abs(T_r).max()), 1.0)
    assert np.allclose(T_p, T_r, atol=tol)


@pytest.mark.parametrize("dtype", DTYPES)
def test_syrk_and_gemm_kernel_parity(dtype):
    nb, k = 64, 32
    C = _spd(nb, dtype, seed=5)
    A = _rand(nb, k, dtype, seed=6)
    got = np.asarray(pk.syrk_diag_pallas(C, A, interpret=True))
    ref = np.asarray(pk.syrk_diag_reference(C, A))
    tol = _tol(dtype, nb) * float(np.abs(ref).max())
    assert np.allclose(got, ref, atol=tol)

    B = _rand(nb, k, dtype, seed=7)
    got = np.asarray(pk.gemm_sub_pallas(C, A, B, interpret=True))
    ref = np.asarray(pk.gemm_sub_reference(C, A, B))
    assert np.allclose(got, ref, atol=tol)


@pytest.mark.parametrize("dtype", DTYPES)
def test_trsm_kernel_parity(dtype):
    n, nrhs = 96, 8
    B = _rand(n, nrhs, dtype, seed=8)
    for lower, unit in ((True, False), (True, True), (False, False)):
        T = _tri(n, dtype, lower=lower, unit=unit, seed=9)
        if lower:
            X = pk.trsm_lower_pallas(T, B, unit=unit, interpret=True)
            ref = pk.trsm_lower_reference(T, B, unit=unit)
        else:
            X = pk.trsm_upper_pallas(T, B, interpret=True)
            ref = pk.trsm_upper_reference(T, B)
        ref = np.asarray(ref)
        err = np.abs(np.asarray(X) - ref).max()
        assert err <= _tol(dtype, n) * max(float(np.abs(ref).max()), 1.0)


def test_trsm_reads_only_its_triangle():
    # packed-LU storage: the other triangle holds factor data, and the
    # substitution must never touch it
    n, nrhs = 64, 4
    L = _tri(n, jnp.float64, lower=True, unit=True, seed=10)
    U = jnp.triu(_rand(n, n, jnp.float64, seed=11))  # garbage upper
    packed = jnp.tril(L, -1) + U
    B = _rand(n, nrhs, jnp.float64, seed=12)
    X = pk.trsm_lower_pallas(packed, B, unit=True, interpret=True)
    ref = pk.trsm_lower_reference(L, B, unit=True)
    assert np.allclose(np.asarray(X), np.asarray(ref), atol=1e-12 * n)


# ---------------------------------------------------------------------------
# schedule-family parity: family="pallas" vs family="recursive"
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", DTYPES)
def test_chol_family_parity(dtype):
    n = 192
    S = _spd(n, dtype, seed=13)
    Lp = np.asarray(chol_recursive(S, nb_switch=64, family="pallas"))
    ref = np.linalg.cholesky(np.asarray(S))
    tol = _tol(dtype, n) * float(np.abs(ref).max())
    assert np.allclose(Lp, ref, atol=tol)


@pytest.mark.parametrize("dtype", DTYPES)
def test_getrf_family_parity_exact(dtype):
    # the pallas panel replicates panel_lu's arithmetic, so the whole
    # recursion is bitwise-equal to the recursive family
    n = 192
    A = _rand(n, n, dtype, seed=14)
    LUp, pp = getrf_recursive(A, nb_switch=64, family="pallas")
    LUr, pr = getrf_recursive(A, nb_switch=64, family="recursive")
    assert np.array_equal(np.asarray(pp), np.asarray(pr))
    assert np.array_equal(np.asarray(LUp), np.asarray(LUr))


def test_getrf_pallas_pivot_order_matches_vendor():
    """EXACT pivot-order equality with lax.linalg.lu on tie-free random
    input — the fused in-register pivot search picks the same rows as
    the vendor partial-pivot sweep."""
    n = 192
    A = _rand(n, n, jnp.float64, seed=15)
    _, perm = getrf_recursive(A, nb_switch=64, family="pallas")
    _, _, vendor_perm = lax.linalg.lu(A)
    assert np.array_equal(np.asarray(perm), np.asarray(vendor_perm))


@pytest.mark.parametrize("dtype", DTYPES)
def test_geqrf_family_parity_exact(dtype):
    n = 192
    A = _rand(n, n, dtype, seed=16)
    Fp, taup = geqrf_pallas(A, 64)
    Fr, taur = geqrf_recursive(A, nb_switch=64)
    assert np.array_equal(np.asarray(Fp), np.asarray(Fr))
    assert np.array_equal(np.asarray(taup), np.asarray(taur))


@pytest.mark.slow
def test_getrf_family_parity_tall():
    m, n = 320, 192
    A = _rand(m, n, jnp.float64, seed=17)
    LUp, pp = getrf_recursive(A, nb_switch=64, family="pallas")
    LUr, pr = getrf_recursive(A, nb_switch=64, family="recursive")
    assert np.array_equal(np.asarray(pp), np.asarray(pr))
    assert np.array_equal(np.asarray(LUp), np.asarray(LUr))


@pytest.mark.slow
def test_non_power_of_two_via_bucket_pad():
    # the cholesky dispatcher pads any n to the 128 lattice; 200 -> 256
    # exercises pad + crop around the pallas recursion
    n = 200
    S = _spd(n, jnp.float64, seed=18)
    L = cholesky(S, 64, schedule="pallas")
    ref = np.linalg.cholesky(np.asarray(S))
    assert np.allclose(np.asarray(L), ref, atol=1e-10 * n)


# ---------------------------------------------------------------------------
# solve-phase trsm routing through the drivers
# ---------------------------------------------------------------------------


def test_potrs_pallas_route_matches_vendor():
    from slate_tpu.drivers.chol import potrs_from_global

    n, nrhs = 64, 4
    S = _spd(n, jnp.float64, seed=19)
    L = jnp.linalg.cholesky(S)
    B = _rand(n, nrhs, jnp.float64, seed=20)
    Xp = np.asarray(potrs_from_global(L, B, schedule="pallas"))
    Xv = np.asarray(potrs_from_global(L, B, schedule="auto"))
    assert np.allclose(Xp, Xv, atol=1e-10 * n)


def test_getrs_pallas_route_matches_vendor():
    from slate_tpu.drivers.lu import getrs_from_global

    n, nrhs = 64, 4
    A = _rand(n, n, jnp.float64, seed=21) + n * jnp.eye(n)
    LU, _piv, perm = lax.linalg.lu(A)
    B = _rand(n, nrhs, jnp.float64, seed=22)
    Bp = B[perm]
    Xp = np.asarray(getrs_from_global(LU, Bp, schedule="pallas"))
    Xv = np.asarray(getrs_from_global(LU, Bp, schedule="auto"))
    assert np.allclose(Xp, Xv, atol=1e-10 * n)
    # and the route actually solves: A X = B
    assert np.allclose(
        np.asarray(A) @ Xp, np.asarray(B), atol=1e-9 * n
    )


# ---------------------------------------------------------------------------
# FLOP accounting: pallas exec <= recursive exec at the flagship point
# ---------------------------------------------------------------------------


def test_pallas_flops_ratio_not_worse_than_recursive():
    """Acceptance: flops_exec/flops_model for the pallas family <= the
    recursive family at n=2048, nb=256 for all three routines (the
    fused base cases remove the strip-mined panel overhead, they never
    add work)."""
    for fn, shape in (
        (chol_schedule_flops, (2048, 512)),
        (getrf_schedule_flops, (2048, 2048, 512)),
        (geqrf_schedule_flops, (2048, 2048, 512)),
    ):
        fp = fn(*shape, "pallas", nb_switch=256)
        fr = fn(*shape, "recursive", nb_switch=256)
        assert fp["model"] == fr["model"]
        assert fp["exec"] / fp["model"] <= fr["exec"] / fr["model"], (
            fn.__name__, fp, fr,
        )


def test_pallas_compile_units_bound_n2048():
    """Per-octave compile-unit bounds for the pallas family.  chol gets
    +3 over the recursive bound: the triangle-aware syrk splits each
    trailing update into a diagonal unit (pallas_syrk) plus an
    off-diagonal gemm unit, one extra distinct shape per octave."""
    L = 2 * math.log2(2048 / 256)
    ch = chol_schedule_flops(2048, 512, "pallas", nb_switch=256)
    assert len(ch["units"]) <= L + 8, sorted(ch["units"])
    assert any(str(u[0]).startswith("pallas_") for u in ch["units"])
    lu = getrf_schedule_flops(2048, 2048, 512, "pallas", nb_switch=256)
    assert len(lu["units"]) <= L + 14, sorted(lu["units"])
    assert any(str(u[0]).startswith("pallas_") for u in lu["units"])
    qr = geqrf_schedule_flops(2048, 2048, 512, "pallas", nb_switch=256)
    assert len(qr["units"]) <= L + 14, sorted(qr["units"])
    assert any(str(u[0]).startswith("pallas_") for u in qr["units"])


# ---------------------------------------------------------------------------
# driver integration: Option.Schedule "pallas" + metrics mirrors
# ---------------------------------------------------------------------------


def test_driver_pallas_compile_guard_and_flops_counters():
    import slate_tpu as st
    from slate_tpu.aux import metrics
    from slate_tpu.enums import Option

    n = 256
    S = _spd(n, jnp.float64, seed=23)
    A = st.HermitianMatrix.from_global(S, 64, uplo=st.Uplo.Lower)
    opts = {Option.Schedule: "pallas", Option.BlockSize: 64}
    metrics.on()
    try:
        metrics.reset()
        L1, info1 = st.potrf(A, opts)
        c = metrics.counters()
        first = c.get("jit.compilations", 0)
        assert first <= 2, c
        fl = chol_schedule_flops(n, 256, "pallas", nb_switch=64)
        assert c["factor.potrf.flops_model"] == pytest.approx(fl["model"])
        assert c["factor.potrf.flops_exec"] == pytest.approx(fl["exec"])
        units = metrics.gauges()["factor.potrf.compile_units"]
        assert units == len(fl["units"])
        L2, info2 = st.potrf(A, opts)
        again = metrics.counters().get("jit.compilations", 0) - first
        assert again == 0, metrics.counters()
    finally:
        metrics.off()
    assert int(info1) == 0
    ref = np.linalg.cholesky(np.asarray(S))
    assert np.allclose(np.asarray(L1.to_global()), ref, atol=1e-9 * n)
    assert np.allclose(
        np.asarray(L1.to_global()), np.asarray(L2.to_global())
    )


def test_schedule_enum_and_bucket_roundtrip():
    from slate_tpu.enums import Schedule
    from slate_tpu.serve import buckets as bk

    assert Schedule.from_string("pallas") is Schedule.Pallas
    assert Schedule.from_string("panel") is Schedule.Pallas  # alias
    k_auto = bk.bucket_for("posv", 100, 100, 4, np.float64)
    k_pal = bk.bucket_for(
        "posv", 100, 100, 4, np.float64, schedule="pallas"
    )
    assert k_auto != k_pal and k_pal.schedule == "pallas"
    text = bk.manifest_dumps([(k_pal, 2)])
    back = dict(bk.manifest_loads(text))
    assert back[k_pal] == 2


# ---------------------------------------------------------------------------
# serve round-trip: a pallas bucket warms, persists, restores compile-free
# ---------------------------------------------------------------------------


def test_serve_pallas_bucket_warm_persist_restore(tmp_path):
    """A schedule="pallas" bucket traces custom-call-free (interpret
    mode lowers to plain XLA ops), so jax.export persists it and a
    FRESH cache restores the executable without compiling."""
    import os

    from slate_tpu.aux import metrics
    from slate_tpu.serve import buckets as bk
    from slate_tpu.serve.cache import ExecutableCache, direct_call

    key = bk.bucket_for(
        "gesv", 10, 10, 2, np.float64, floor=16, nrhs_floor=4,
        schedule="pallas",
    )
    man = str(tmp_path / "warmup.json")
    art = str(tmp_path / "store")
    metrics.off()
    metrics.reset()
    metrics.on()
    try:
        cache = ExecutableCache(manifest_path=man, artifact_dir=art)
        cache.ensure_manifest(key, (1,))
        assert cache.warmup(batch_max=1) >= 1
        assert [
            f for f in os.listdir(art) if f.endswith(".slate_exe")
        ], "pallas warmup must persist artifacts"

        # a fresh cache restores from the export artifact (the ladder
        # counts it restored, not compiled — the re-jit of the
        # deserialized module is served by the store-seeded XLA cache)
        fresh = ExecutableCache(manifest_path=man, artifact_dir=art)
        with metrics.deltas() as d:
            out = fresh.restore(batch_max=1)
        assert out["restored"] >= 1 and out["compiled"] == 0, out
        assert d.get("serve.artifact_hit") >= 1

        # steady state on the restored executable: real data through
        # the padded bucket, zero further compiles
        rng = np.random.default_rng(24)
        A = rng.standard_normal((10, 10)) + 10 * np.eye(10)
        B = rng.standard_normal((10, 2))
        Ap = np.eye(16)
        Ap[:10, :10] = A
        Bp = np.zeros((16, 4))
        Bp[:10, :2] = B
        with metrics.deltas() as d:
            X, info = fresh.run(key, Ap[None], Bp[None])
        assert d.get("jit.compilations") == 0
        assert int(info[0]) == 0
        ref = direct_call("gesv", A, B)
        err = np.abs(X[0][:10, :2] - ref).max()
        assert err < 1e-9 * max(np.abs(ref).max(), 1.0)
    finally:
        metrics.off()
        metrics.reset()
