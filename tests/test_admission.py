"""Admission-plane tests: tenant grammar, token-bucket quotas,
weighted-fair queues, the AIMD batch-window controller, the overload
shed controller, and the service integration.

Controller units run on fake clocks / replayed latency sequences
(deterministic: AIMD convergence, hysteresis no-flap, quota refill).
Integration covers the ISSUE acceptance pieces: the fairness invariant
(an abusive tenant's shed count > 0 while the well-behaved tenant's
p99 holds, under deterministic injected latency), per-tenant typed
``Rejected``/``Shed`` with tenant/priority context, the ``tenants``
health section, the ``tenant_flood`` fault site, the capped
``serve.tenant.*`` metric family, and the zero-overhead contract: a
default service has NO admission plane — plain deque lanes, no new
metrics, byte-identical results (PR2's steady-state compile-free test
rides on this unchanged).
"""

import time
from collections import deque

import numpy as np
import pytest

from slate_tpu.aux import faults, metrics
from slate_tpu.exceptions import SlateError
from slate_tpu.serve import admission as adm
from slate_tpu.serve import buckets as bk
from slate_tpu.serve.admission import (
    AdaptiveWindow,
    AdmissionControl,
    FairQueue,
    OverloadController,
    TenantConfig,
    TokenBucket,
    parse_tenants,
)
from slate_tpu.serve.cache import ExecutableCache
from slate_tpu.serve.service import Rejected, Shed, SolverService

FLOOR = 16
NRHS_FLOOR = 4


@pytest.fixture(autouse=True)
def clean_state():
    """Metrics are part of the contract under test; faults must never
    leak across tests."""
    metrics.off()
    metrics.reset()
    metrics.on()
    faults.reset()
    yield
    faults.reset()
    metrics.off()
    metrics.reset()


def _gesv_problem(n, nrhs=2, seed=0):
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((n, n)) + n * np.eye(n)
    B = rng.standard_normal((n, nrhs))
    return A, B


def _service(**kw):
    kw.setdefault("cache", ExecutableCache(manifest_path=None))
    kw.setdefault("batch_max", 4)
    kw.setdefault("dim_floor", FLOOR)
    kw.setdefault("nrhs_floor", NRHS_FLOOR)
    return SolverService(**kw)


# ---------------------------------------------------------------------------
# grammar + config
# ---------------------------------------------------------------------------


def test_parse_tenants_grammar():
    cfgs = parse_tenants(
        "gold:weight=4;free:weight=1,rate=20,burst=4,share=0.25;bare"
    )
    assert cfgs["gold"].weight == 4.0
    assert cfgs["gold"].rate == 0.0  # unlimited
    assert cfgs["free"].rate == 20.0
    assert cfgs["free"].burst == 4
    assert cfgs["free"].share == 0.25
    assert cfgs["bare"] == TenantConfig(name="bare")


def test_parse_tenants_default_is_template():
    a = AdmissionControl(
        tenants=parse_tenants("default:weight=2,rate=5;gold:weight=8")
    )
    assert a.config_for("gold").weight == 8.0
    # unnamed tenants inherit the default entry's knobs
    anon = a.config_for("someone-new")
    assert anon.weight == 2.0 and anon.rate == 5.0


def test_parse_tenants_errors():
    with pytest.raises(ValueError, match="unknown tenant spec key"):
        parse_tenants("t:wieght=2")
    with pytest.raises(ValueError, match="empty tenant name"):
        parse_tenants(":weight=2")
    with pytest.raises(ValueError, match="tenant spec item"):
        parse_tenants("t:weight")
    with pytest.raises(ValueError, match="weight must be > 0"):
        parse_tenants("t:weight=0")
    with pytest.raises(ValueError, match="share must be in"):
        parse_tenants("t:share=1.5")
    # a burst with no refill would be silently inert — refuse to start
    # rather than ignore a quota the operator believes is active
    with pytest.raises(ValueError, match="burst= requires rate="):
        parse_tenants("t:burst=10")


def test_check_priority():
    assert bk.check_priority("high") == bk.PRIO_HIGH
    assert bk.check_priority("normal") == bk.PRIO_NORMAL
    assert bk.check_priority("low") == bk.PRIO_LOW
    assert bk.check_priority(2) == 2
    assert bk.priority_name(0) == "high"
    with pytest.raises(ValueError):
        bk.check_priority("urgent")
    with pytest.raises(ValueError):
        bk.check_priority(3)


# ---------------------------------------------------------------------------
# token bucket (fake clock)
# ---------------------------------------------------------------------------


def test_token_bucket_refill_deterministic():
    tb = TokenBucket(rate=10.0, capacity=2, now=0.0)
    assert tb.take(0.0) and tb.take(0.0)  # burst of 2
    assert not tb.take(0.0)  # dry
    assert not tb.take(0.05)  # 0.5 tokens refilled: still < 1
    assert tb.take(0.1)  # 1 token at t=0.1 (0.5 + 0.5)
    assert not tb.take(0.1)


def test_token_bucket_caps_at_capacity():
    tb = TokenBucket(rate=100.0, capacity=3, now=0.0)
    for _ in range(3):
        assert tb.take(10.0)  # long idle refills to capacity, not 1000
    assert not tb.take(10.0)
    assert tb.remaining(10.0) < 1.0


# ---------------------------------------------------------------------------
# weighted-fair queue
# ---------------------------------------------------------------------------


class _R:
    """Request stub: the fields FairQueue schedules on."""

    def __init__(self, tenant, t_submit, not_before=0.0):
        self.tenant = tenant
        self.t_submit = t_submit
        self.not_before = not_before

    def __repr__(self):
        return f"R({self.tenant}@{self.t_submit})"


def _fq(spec="") -> FairQueue:
    return AdmissionControl(
        tenants=parse_tenants(spec) if spec else {"default": TenantConfig("default")}
    ).new_queue()


def test_fairqueue_single_tenant_is_fifo():
    q = _fq()
    reqs = [_R("default", i) for i in range(6)]
    for r in reqs:
        q.append(r)
    popped = [q.pop_eligible(100.0) for _ in range(6)]
    assert popped == reqs  # exactly the old FIFO
    assert q.pop_eligible(100.0) is None


def test_fairqueue_weighted_interleave():
    q = _fq("a:weight=3;b:weight=1")
    for i in range(8):
        q.append(_R("a", i))
    for i in range(8):
        q.append(_R("b", 10 + i))
    first8 = [q.pop_eligible(100.0).tenant for _ in range(8)]
    # 3:1 drain over any window (±1 for the tie at v=0)
    assert first8.count("a") == 6 and first8.count("b") == 2


def test_fairqueue_no_head_of_line_blocking():
    q = _fq("a:weight=1;b:weight=1")
    for i in range(6):
        q.append(_R("a", i))  # the flood
    q.append(_R("b", 6))  # the victim, last in arrival order
    pops = [q.pop_eligible(100.0).tenant for _ in range(3)]
    # b is served 2nd, not 7th: equal weights alternate
    assert pops[1] == "b"


def test_fairqueue_idle_tenant_no_catchup_monopoly():
    q = _fq("a:weight=1;b:weight=1")
    for i in range(5):
        q.append(_R("a", i))
    for _ in range(5):
        q.pop_eligible(100.0)  # a's vtime runs ahead alone
    # b arrives late: clamped to virtual now, it alternates instead of
    # monopolizing the lane to catch up
    for i in range(3):
        q.append(_R("a", 10 + i))
    for i in range(3):
        q.append(_R("b", 20 + i))
    first4 = [q.pop_eligible(100.0).tenant for _ in range(4)]
    assert first4.count("b") == 2, first4


def test_fairqueue_closed_loop_tenant_cannot_starve_backlog():
    """A 1-deep closed-loop tenant (resubmits after every pop, so its
    queue empties each time) must still be charged virtual time: it
    drains in weight proportion against a backlogged heavy tenant
    instead of re-entering in the past and head-of-line-blocking it."""
    q = _fq("gold:weight=4;free:weight=1")
    for i in range(40):
        q.append(_R("gold", i))
    q.append(_R("free", 100))
    pops = []
    t = 200
    for _ in range(30):
        r = q.pop_eligible(1000.0)
        pops.append(r.tenant)
        if r.tenant == "free":
            q.append(_R("free", t))  # closed loop: one in flight
            t += 1
    # ~4:1 by weight; the uncharged-finish bug gave free 29 of 30
    assert pops.count("gold") >= 20, pops


def test_fairqueue_vnow_monotone_after_stale_backoff_pop():
    """A request popped late off a stale small vtime (it sat in retry
    backoff while the lane advanced) must not drag the virtual now
    backwards — a regressed vnow would hand the next arriving tenant a
    catch-up monopoly."""
    q = _fq()
    slow = _R("c", 0, not_before=50.0)  # backs off while a is served
    q.append(slow)
    for i in range(6):
        q.append(_R("a", 1 + i))
    for _ in range(4):
        assert q.pop_eligible(10.0).tenant == "a"  # vnow advances to 3
    assert q.pop_eligible(60.0) is slow  # stale vtime 0, popped late
    for i in range(3):
        q.append(_R("d", 20 + i))  # new tenant: clamps to vnow
    pops = [q.pop_eligible(100.0).tenant for _ in range(4)]
    # with a regressed vnow, d would win 3 straight catch-up pops
    assert pops.count("d") == 2, pops


def test_fairqueue_backoff_eligibility():
    q = _fq()
    a = _R("default", 0, not_before=50.0)  # backing off
    b = _R("default", 1)
    q.append(a)
    q.append(b)
    assert q.pop_eligible(10.0) is b  # a ineligible at t=10
    assert q.pop_eligible(10.0) is None
    assert q.pop_eligible(60.0) is a


def test_fairqueue_deque_surface_and_depth():
    q = _fq("a:weight=1;b:weight=1")
    r1, r2, r3 = _R("a", 0), _R("b", 1), _R("a", 2)
    q.append(r1)
    q.append(r2)
    q.append(r3)
    assert len(q) == 3
    assert list(q) == [r1, r2, r3]  # arrival order
    assert q.depth("a") == 2 and q.depth("b") == 1
    q.remove(r3)
    assert q.depth("a") == 1
    retry = _R("a", 3)
    q.appendleft(retry)
    assert list(q)[0] is retry  # retry goes to the head
    q.clear()
    assert len(q) == 0 and q.depth("a") == 0


# ---------------------------------------------------------------------------
# AIMD adaptive window
# ---------------------------------------------------------------------------


def test_aimd_shrinks_under_pressure():
    w = AdaptiveWindow(ceiling_s=0.01, decide_every=4)
    assert w.window_s == 0.01  # starts static
    for _ in range(16):
        w.observe(0.5, budget_s=0.25)  # 2x over budget
    assert w.window_s < 0.01 / 4  # multiplicative decrease converges
    assert w.shrinks == 4 and w.widens == 0


def test_aimd_widens_on_recovery_bounded_by_ceiling():
    w = AdaptiveWindow(ceiling_s=0.01, decide_every=4)
    for _ in range(16):
        w.observe(0.5, budget_s=0.25)
    low = w.window_s
    for _ in range(200):
        w.observe(0.01, budget_s=0.25)  # way under budget
    assert w.window_s == pytest.approx(0.01)  # additive climb, capped
    assert w.window_s > low and w.widens >= 1


def test_aimd_hysteresis_band_holds():
    w = AdaptiveWindow(ceiling_s=0.01, decide_every=4)
    for _ in range(40):
        # between 0.5x and 1.0x budget: the hold band — no flapping
        assert w.observe(0.2, budget_s=0.25) is None
    assert w.window_s == 0.01 and w.shrinks == 0 and w.widens == 0


def test_aimd_judges_each_request_against_its_own_budget():
    """Mixed deadlines in one bucket: the decision is the worst burn
    RATIO, so a healthy 2 s solve inside a 5 s budget never shrinks
    the window just because a 50 ms-budget request completed it."""
    w = AdaptiveWindow(ceiling_s=0.01, decide_every=4)
    for _ in range(16):
        w.observe(2.0, budget_s=5.0)  # ratio 0.4: healthy
        w.observe(0.04, budget_s=0.05)  # ratio 0.8: hold band
    assert w.window_s == 0.01 and w.shrinks == 0 and w.widens == 0
    for _ in range(4):
        w.observe(0.2, budget_s=0.05)  # ratio 4: a real melt
    assert w.shrinks >= 1


def test_aimd_no_budget_no_decisions():
    w = AdaptiveWindow(ceiling_s=0.01, decide_every=2)
    for _ in range(10):
        assert w.observe(99.0, budget_s=0.0) is None
    assert w.window_s == 0.01


# ---------------------------------------------------------------------------
# overload controller (fake clock)
# ---------------------------------------------------------------------------


def test_overload_escalates_immediately_and_sheds_low_first():
    oc = OverloadController(alpha=0.5, dwell_s=1.0)
    assert not oc.sheds(bk.PRIO_LOW)
    tr = []
    for i in range(6):
        t = oc.observe(2.0, now=0.01 * i)  # sustained heavy burn
        if t:
            tr.append(t)
    assert (0, 1) in tr and (1, 2) in tr  # escalation needs no dwell
    assert oc.level == 2
    assert oc.sheds(bk.PRIO_LOW) and oc.sheds(bk.PRIO_NORMAL)
    assert not oc.sheds(bk.PRIO_HIGH)  # high is never shed


def test_overload_deescalation_requires_dwell():
    oc = OverloadController(alpha=1.0, dwell_s=10.0)
    assert oc.observe(2.0, now=0.0) == (0, 2)
    # burn collapses, but the dwell has not elapsed: hold the level
    assert oc.observe(0.0, now=1.0) is None
    assert oc.level == 2
    # past the dwell: recover one decision at a time
    assert oc.observe(0.0, now=11.0) == (2, 0)
    assert oc.level == 0


def test_overload_no_flap_near_threshold():
    oc = OverloadController(alpha=0.3, dwell_s=0.5)
    transitions = 0
    t = 0.0
    for i in range(200):
        t += 0.001  # all 200 observations inside one dwell window
        burn = 1.1 if i % 2 else 0.7  # oscillating around enter_low
        if oc.observe(burn, now=t):
            transitions += 1
    # the EWMA smooths the oscillation and the dwell blocks rapid
    # de-escalation: one level change at most, never a flap storm
    assert transitions <= 1


def test_overload_tick_recovers_a_latched_level():
    """Anti-latch: at shed level, refused requests never execute, so
    no burn sample would ever arrive — tick() must decay the idle EWMA
    and de-escalate on its own once the flood stops."""
    oc = OverloadController(alpha=1.0, dwell_s=0.5)
    assert oc.observe(3.0, now=0.0) == (0, 2)
    # silence shorter than a dwell: nothing decays, level holds
    assert oc.tick(0.4) is None and oc.level == 2
    # a few idle dwell windows halve the EWMA down through both exit
    # thresholds; tick alone (no traffic at all) recovers the service
    moved = [oc.tick(0.5 * k) for k in range(1, 12)]
    assert oc.level == 0, (oc.level, oc.ewma)
    assert any(m is not None for m in moved)
    # and tick can never escalate (the EWMA only shrinks)
    assert all(m is None or m[1] < m[0] for m in moved)


def test_overload_window_factor():
    oc = OverloadController(shrink=0.25)
    assert oc.window_factor() == 1.0
    oc.level = 1
    assert oc.window_factor() == 0.25
    oc.level = 2
    assert oc.window_factor() == 0.0625


def test_overload_hysteresis_validation():
    with pytest.raises(ValueError, match="hysteresis"):
        OverloadController(enter=(0.5, 1.0), exit=(0.6, 1.1))


# ---------------------------------------------------------------------------
# AdmissionControl resolution + metrics cap
# ---------------------------------------------------------------------------


def test_from_options_default_is_none(monkeypatch):
    monkeypatch.delenv(adm.TENANTS_ENV, raising=False)
    monkeypatch.delenv(adm.ADAPTIVE_ENV, raising=False)
    assert AdmissionControl.from_options() is None


def test_from_options_env_activation(monkeypatch):
    monkeypatch.setenv(adm.TENANTS_ENV, "gold:weight=2")
    monkeypatch.setenv(adm.ADAPTIVE_ENV, "0.25")
    a = AdmissionControl.from_options(ceiling_s=0.005)
    assert a is not None and a.tenancy and a.adaptive
    assert a.budget_s == 0.25
    assert a.config_for("gold").weight == 2.0


def test_from_options_env_malformed_raises(monkeypatch):
    monkeypatch.delenv(adm.TENANTS_ENV, raising=False)
    monkeypatch.setenv(adm.ADAPTIVE_ENV, "fast")
    with pytest.raises(ValueError, match=adm.ADAPTIVE_ENV):
        AdmissionControl.from_options()


def test_from_options_env_zero_budget_is_off(monkeypatch):
    """"0.0" means off like "0" — a plane armed with a budget no
    controller can use would be pure overhead."""
    monkeypatch.delenv(adm.TENANTS_ENV, raising=False)
    for off in ("0", "0.0", "0.00", "false", "off", ""):
        monkeypatch.setenv(adm.ADAPTIVE_ENV, off)
        assert AdmissionControl.from_options() is None, off


def test_api_explicit_off_overrides_env(monkeypatch):
    """A baseline/AB service built through the api layer with an
    EXPLICIT off value must win over an env-armed plane (the
    env-override trap factor_cache=False exists for)."""
    from slate_tpu.enums import Option
    from slate_tpu.serve import api as serve_api

    monkeypatch.setenv(adm.TENANTS_ENV, "gold:weight=2")
    svc = serve_api._make_service(
        {Option.ServeTenantQuota: ""}, start=False
    )
    try:
        assert svc._admission is None
    finally:
        svc.stop()
    # and with the option unset, the env still arms the plane
    svc2 = serve_api._make_service(None, start=False)
    try:
        assert svc2._admission is not None and svc2._admission.tenancy
    finally:
        svc2.stop()


def test_tenant_flood_requires_tenancy():
    """The flood site is tenancy-gated: on an adaptive-only plane the
    synthetic burst would inherit an unlimited default quota and admit
    wholesale — so it must not fire there at all."""
    A, B = _gesv_problem(12)
    svc = _service(adaptive=True, latency_budget_s=1.0)
    try:
        assert svc._admission is not None and not svc._admission.tenancy
        faults.arm("tenant_flood", once=True, burst=10)
        faults.on()
        assert np.all(np.isfinite(
            svc.submit("gesv", A, B).result(timeout=120)
        ))
        c = metrics.counters()
        assert c.get("faults.injected.tenant_flood", 0) == 0
        assert c.get("serve.tenant.flood.admitted", 0) == 0
    finally:
        faults.reset()
        svc.stop()


def test_quota_take_with_fake_clock():
    clock = [0.0]
    a = AdmissionControl(
        tenants=parse_tenants("t:rate=2,burst=2"),
        clock=lambda: clock[0],
    )
    assert a.quota_take("t", 0.0) and a.quota_take("t", 0.0)
    assert not a.quota_take("t", 0.0)
    assert a.quota_take("t", 0.5)  # one token back after 0.5 s at 2/s
    assert a.quota_remaining("t", 0.5) < 1.0
    # unlimited tenants never block and report no quota
    assert a.quota_take("other", 0.0)
    assert a.quota_remaining("other", 0.0) is None


def test_tenant_metric_family_is_capped():
    a = AdmissionControl(tenants=parse_tenants("default:weight=1"))
    for i in range(adm.TENANT_METRIC_CAP + 20):
        a.tenant_event(f"tenant-{i}", "admitted")
    c = metrics.counters()
    per_tenant = [
        k for k in c if k.startswith("serve.tenant.")
        and k.endswith(".admitted")
    ]
    assert len(per_tenant) == adm.TENANT_METRIC_CAP
    assert c.get("serve.tenant_overflow", 0) == 20
    # the health ints are NOT capped at the metric cap: recent tenants
    # stay accounted (the state cap, far larger, bounds them)
    h = a.tenants_health({})
    assert h[f"tenant-{adm.TENANT_METRIC_CAP + 10}"]["admitted"] == 1


def test_tenant_state_is_capped_configured_tenants_survive():
    """The control plane's own memory is bounded like its metrics: a
    churning id stream evicts the oldest UNCONFIGURED state while
    spec-named tenants keep theirs (bucket state included)."""
    a = AdmissionControl(tenants=parse_tenants("vip:rate=5,burst=2"))
    a.quota_take("vip", 0.0)  # vip's bucket: 1 of 2 tokens left
    for i in range(adm.TENANT_STATE_CAP + 50):
        a.tenant_event(f"churn-{i}", "admitted")
    assert len(a._states) <= adm.TENANT_STATE_CAP + 1
    assert "churn-0" not in a._states  # oldest churner evicted
    st = a._states["vip"]  # the configured tenant survived the churn
    assert st.bucket is not None and st.bucket.tokens == 1.0


# ---------------------------------------------------------------------------
# service integration
# ---------------------------------------------------------------------------


def test_default_service_plane_off_byte_identical():
    """Zero-overhead contract: an unconfigured service has NO admission
    plane (plain deque lanes, no tenant/adaptive/shed metrics), and
    tagging requests on it changes nothing — byte-identical X."""
    A, B = _gesv_problem(12)
    svc = _service()
    try:
        assert svc._admission is None
        assert all(isinstance(rep.q, deque) for rep in svc._lanes)
        with metrics.deltas():
            X1 = svc.submit("gesv", A, B).result(timeout=120)
            X2 = svc.submit(
                "gesv", A, B, tenant="anyone", priority="low"
            ).result(timeout=120)
        assert X1.tobytes() == X2.tobytes()
        h = svc.health()
        assert h["tenants"] is None and h["admission"] is None
        leaked = [
            k for k in metrics.counters()
            if k.startswith(("serve.tenant", "serve.adaptive",
                             "serve.shed", "serve.overload",
                             "serve.rejected_quota",
                             "serve.rejected_share"))
        ]
        assert not leaked, leaked
        # a typo'd priority still fails loudly, plane or no plane
        with pytest.raises(ValueError):
            svc.submit("gesv", A, B, priority="urgent")
    finally:
        svc.stop()


def test_quota_rejects_hot_tenant_only():
    A, B = _gesv_problem(12)
    svc = _service(tenants="free:rate=1,burst=2")
    try:
        ok = 0
        rejected = []
        for _ in range(5):
            try:
                svc.submit("gesv", A, B, tenant="free").result(timeout=120)
                ok += 1
            except Rejected as e:
                rejected.append(e)
        assert ok == 2 and len(rejected) == 3
        e = rejected[0]
        assert e.tenant == "free" and e.priority == "normal"
        assert "tenant" in str(e)
        # the neighbor (unlimited) is untouched by free's dry bucket
        X = svc.submit("gesv", A, B, tenant="gold").result(timeout=120)
        assert np.all(np.isfinite(X))
        c = metrics.counters()
        assert c.get("serve.rejected_quota") == 3
        assert c.get("serve.tenant.free.rejected") == 3
        assert c.get("serve.tenant.gold.admitted") == 1
    finally:
        svc.stop()


def test_queue_full_rejection_does_not_drain_quota():
    """Fairness of the quota itself: a rejection caused by OTHERS (the
    shared queue is full) must not consume the victim's token — the
    bucket is charged only for requests actually admitted."""
    A, B = _gesv_problem(12)
    svc = _service(
        tenants="scarce:rate=0.1,burst=1", max_queue=2, start=False
    )
    try:
        f1 = svc.submit("gesv", A, B, tenant="big")
        f2 = svc.submit("gesv", A, B, tenant="big")
        with pytest.raises(Rejected, match="queue full"):
            svc.submit("gesv", A, B, tenant="scarce")
        svc.start()
        for f in (f1, f2):
            assert np.all(np.isfinite(f.result(timeout=120)))
        # the queue-full rejection above did NOT charge scarce's only
        # token: this admission succeeds...
        assert np.all(np.isfinite(
            svc.submit("gesv", A, B, tenant="scarce").result(timeout=120)
        ))
        # ...and only now is the bucket dry
        with pytest.raises(Rejected, match="quota"):
            svc.submit("gesv", A, B, tenant="scarce")
    finally:
        svc.stop()


def test_share_cap_rejects_per_tenant():
    A, B = _gesv_problem(12)
    svc = _service(
        tenants="hog:share=0.1", max_queue=20, start=False
    )  # paused: the queue holds, share cap = 2 of 20
    try:
        svc.submit("gesv", A, B, tenant="hog")
        svc.submit("gesv", A, B, tenant="hog")
        with pytest.raises(Rejected, match="queue share"):
            svc.submit("gesv", A, B, tenant="hog")
        # a neighbor still gets in behind the hog's cap
        f = svc.submit("gesv", A, B, tenant="polite")
        assert metrics.counters().get("serve.rejected_share") == 1
        svc.start()
        assert np.all(np.isfinite(f.result(timeout=120)))
    finally:
        svc.stop()


def test_shed_typed_with_context_and_priority_order():
    A, B = _gesv_problem(12)
    svc = _service(tenants="default:weight=1", latency_budget_s=0.1)
    try:
        # force sustained overload through the public observe path
        for i in range(10):
            svc._admission.overload.observe(3.0, now=time.monotonic())
        assert svc._admission.overload.level == 2
        with pytest.raises(Shed) as ei:
            svc.submit("gesv", A, B, tenant="t", priority="low")
        e = ei.value
        assert e.tenant == "t" and e.priority == "low"
        assert "overload" in str(e)
        with pytest.raises(Shed):
            svc.submit("gesv", A, B, priority="normal")
        # high priority is never shed
        X = svc.submit("gesv", A, B, priority="high").result(timeout=120)
        assert np.all(np.isfinite(X))
        c = metrics.counters()
        assert c.get("serve.shed") == 2
        h = svc.health()
        assert h["admission"]["overload_level"] == 2
        assert h["admission"]["shedding"] == ["normal", "low"]
    finally:
        svc.stop()


def test_health_tenants_section():
    A, B = _gesv_problem(12)
    svc = _service(tenants="gold:weight=4;free:rate=5,burst=1")
    try:
        svc.submit("gesv", A, B, tenant="gold").result(timeout=120)
        with pytest.raises(Rejected):
            for _ in range(3):
                svc.submit("gesv", A, B, tenant="free")
        h = svc.health()["tenants"]
        assert h["gold"]["admitted"] == 1 and h["gold"]["weight"] == 4.0
        assert h["gold"]["quota_remaining"] is None  # unlimited
        assert h["free"]["rejected"] >= 1
        assert h["free"]["quota_remaining"] is not None
        assert set(h["gold"]["burn"]) == {
            "requests", "over_50", "over_80", "exhausted"
        }
    finally:
        svc.stop()


def test_tenant_flood_fault_site():
    """The chaos satellite: one armed tenant_flood injection bursts 10
    synthetic low-priority requests from tenant "flood"; the tight
    quota refuses most, every future still resolves."""
    A, B = _gesv_problem(12)
    svc = _service(tenants="flood:rate=1,burst=2,share=0.2")
    try:
        faults.arm("tenant_flood", once=True, burst=10)
        faults.on()
        X = svc.submit("gesv", A, B, tenant="real").result(timeout=120)
        assert np.all(np.isfinite(X))
        c = metrics.counters()
        assert c.get("faults.injected.tenant_flood") == 1
        assert c.get("serve.tenant.flood.rejected", 0) >= 8
        assert c.get("serve.tenant.flood.admitted", 0) <= 2
        assert c.get("serve.tenant.real.admitted") == 1
    finally:
        faults.reset()
        svc.stop()


def test_adaptive_window_shrinks_and_records():
    """Over-budget deliveries move the bucket's AIMD window down from
    the static ceiling, with the trajectory in metrics."""
    A, B = _gesv_problem(12)
    svc = _service(
        tenants="default:weight=1", adaptive=True,
        latency_budget_s=1e-4,  # everything is over budget on purpose
        batch_window_s=0.005,
    )
    try:
        label = bk.bucket_for(
            "gesv", 12, 12, 2, np.float64, floor=FLOOR,
            nrhs_floor=NRHS_FLOOR,
        ).label
        futs = [
            svc.submit("gesv", A, B, priority="high")  # high: never shed
            for _ in range(20)
        ]
        for f in futs:
            assert np.all(np.isfinite(f.result(timeout=120)))
        win = svc._admission.window_for(label)
        assert win < 0.005  # shrunk below the ceiling
        c = metrics.counters()
        assert c.get(f"serve.adaptive.{label}.shrink", 0) >= 1
        assert c.get("serve.adaptive.changes", 0) >= 1
        g = metrics.gauges()
        assert g.get(f"serve.adaptive.{label}.window_s") < 0.005
        assert label in svc.health()["admission"]["windows"]
    finally:
        svc.stop()


def test_fairness_invariant_abuser_shed_victim_p99_holds():
    """The ISSUE acceptance, scaled down: under deterministic injected
    latency an abusive flood is quota-capped and eventually SHED
    (typed, counted) while the well-behaved tenant's p99 stays within
    budget."""
    BUDGET = 0.25
    A_a, B_a = _gesv_problem(12, seed=1)
    good_probs = [_gesv_problem(24, seed=100 + i) for i in range(6)]
    svc = _service(
        tenants="good:weight=4;abuser:rate=10,burst=4,share=0.25",
        adaptive=True, latency_budget_s=BUDGET,
        batch_window_s=0.005,
    )
    try:
        # warm both buckets so the stream measures queueing
        for k in (
            bk.bucket_for("gesv", 12, 12, 2, np.float64, floor=FLOOR,
                          nrhs_floor=NRHS_FLOOR),
            bk.bucket_for("gesv", 24, 24, 2, np.float64, floor=FLOOR,
                          nrhs_floor=NRHS_FLOOR),
        ):
            svc.cache.ensure_manifest(k, (1, 4))
        svc.warmup()
        faults.arm("latency", every=1, ms=20.0)  # 20 ms per dispatch
        faults.on()
        futs = []
        shed = rejected = 0

        def abuse(**kw):
            nonlocal shed, rejected
            try:
                futs.append(svc.submit(
                    "gesv", A_a, B_a, tenant="abuser", priority="low",
                    **kw,
                ))
            except Shed:
                shed += 1
            except Rejected:
                rejected += 1

        for _ in range(24):  # the flood
            abuse()
        for A, B in good_probs:  # the victim
            futs.append(svc.submit(
                "gesv", A, B, tenant="good", priority="high",
                deadline=10.0,
            ))
        time.sleep(0.4)  # phase-1 drains, abuser tokens refill
        for _ in range(8):  # tight deadlines melt the abuser's SLO
            abuse(deadline=0.015)
        deadline = time.monotonic() + 10.0
        while shed == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
            abuse(deadline=0.015)
        for f in futs:
            try:
                assert np.all(np.isfinite(f.result(timeout=120)))
            except SlateError:
                pass  # typed (DeadlineExceeded): resolved, not hung
        assert shed > 0, "the abuser was never shed"
        assert rejected > 0, "the abuser quota never engaged"
        p99_good = metrics.percentile(
            "serve.latency.tenant.good.total", 99
        )
        assert p99_good is not None and p99_good <= BUDGET, p99_good
        h = svc.health()
        assert h["tenants"]["abuser"]["shed"] == shed
        assert h["admission"]["overload_level"] >= 1
    finally:
        faults.reset()
        svc.stop()


def test_serve_exports_shed_and_admission():
    import slate_tpu.serve as serve

    assert serve.Shed is Shed
    assert serve.TenantConfig is TenantConfig
    assert serve.admission is adm
