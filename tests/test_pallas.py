"""Pallas kernel tests in interpreter mode (the kernels compile natively
on TPU; interpret=True checks the same lowering logic on CPU)."""

import numpy as np
import pytest

from slate_tpu.ops.pallas import kernels as pk


@pytest.fixture
def tiles(rng):
    return np.asarray(rng.standard_normal((6, 16, 8)), np.float32)


@pytest.mark.parametrize("kind", ["max", "fro_sumsq", "one", "inf"])
def test_tile_norms_interpret(tiles, kind):
    got = np.asarray(pk.tile_norms_pallas(tiles, kind, interpret=True))
    ref = np.asarray(pk.tile_norms_reference(tiles, kind))
    np.testing.assert_allclose(got, ref, rtol=1e-6)


def test_tile_transpose_interpret(tiles):
    got = np.asarray(pk.tile_transpose_pallas(tiles, interpret=True))
    np.testing.assert_array_equal(got, tiles.transpose(0, 2, 1))


def test_butterfly_level_interpret(rng):
    X = np.asarray(rng.standard_normal((32, 8)), np.float32)
    D1 = np.asarray(rng.uniform(0.9, 1.1, 16), np.float32)
    D2 = np.asarray(rng.uniform(0.9, 1.1, 16), np.float32)
    for tr in (True, False):
        got = np.asarray(pk.butterfly_level_pallas(X, D1, D2, tr, interpret=True))
        ref = np.asarray(pk.butterfly_level_reference(X, D1, D2, tr))
        # sqrt(0.5) is weak-typed f32 in the kernel vs f64 in the reference
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_tile_geadd_interpret(tiles, rng):
    B = np.asarray(rng.standard_normal(tiles.shape), np.float32)
    got = np.asarray(pk.tile_geadd_pallas(2.0, tiles, -0.5, B, interpret=True))
    np.testing.assert_allclose(got, 2.0 * tiles - 0.5 * B, rtol=1e-6)


def test_dispatch_uses_reference_on_cpu(tiles):
    # on the CPU test platform the dispatcher must take the jnp path
    out = pk.tile_norms(tiles, "max")
    np.testing.assert_allclose(
        np.asarray(out), np.abs(tiles).max(axis=(1, 2)), rtol=1e-6
    )
