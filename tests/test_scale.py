"""Elastic capacity plane tests: the SLATE_TPU_SCALE grammar, the
pure hysteresis controller (seeded determinism, no-flap), the signal
aggregator's pure fold, predictive warmup planning from recorded
traces, and the live add/remove replica lifecycle (drain with
inflight work, factor re-homing, terminal health rows).

The service-backed tests share one module-scoped ExecutableCache (the
test_serve pattern) so each (bucket, batch) executable compiles once
for the file; controller/aggregator/plan tests are pure and never
touch jax.
"""

import numpy as np
import pytest

from slate_tpu.aux import metrics
from slate_tpu.scale import controller as ctl
from slate_tpu.scale import signals as sig
from slate_tpu.scale import warmup_plan as wp
from slate_tpu.serve import buckets as bk
from slate_tpu.serve.cache import ExecutableCache
from slate_tpu.serve.factor_cache import FactorCache
from slate_tpu.serve.service import SolverService
from slate_tpu.soak import replay

FLOOR = 16
NRHS_FLOOR = 4


@pytest.fixture(autouse=True)
def metrics_on():
    metrics.off()
    metrics.reset()
    metrics.on()
    yield
    metrics.off()
    metrics.reset()


@pytest.fixture(scope="module")
def shared_cache():
    return ExecutableCache(manifest_path=None)


def _service(shared_cache, **kw):
    cfg = dict(
        cache=shared_cache, batch_max=1, batch_window_s=0.0005,
        dim_floor=FLOOR, nrhs_floor=NRHS_FLOOR, replicas=1,
        factor_cache=FactorCache(max_entries=64),
    )
    cfg.update(kw)
    svc = SolverService(**cfg)
    k = bk.bucket_for("gesv", 12, 12, 2, np.float64, floor=FLOOR,
                      nrhs_floor=NRHS_FLOOR)
    svc.cache.ensure_manifest(k, (1,))
    svc.cache.ensure_manifest(k.solve_sibling(), (1,))
    svc.warmup()
    return svc


def _ops(rng, n=12, nrhs=2):
    A = rng.standard_normal((n, n)) + n * np.eye(n)
    B = rng.standard_normal((n, nrhs))
    return A, B


# ---------------------------------------------------------------------------
# SLATE_TPU_SCALE grammar + policy validation
# ---------------------------------------------------------------------------


def test_parse_spec_off_tokens():
    for spec in ("", "0", "off", "OFF", "false", "no"):
        assert ctl.parse_spec(spec) is None


def test_parse_spec_defaults_and_kv():
    assert ctl.parse_spec("on") == ctl.ScalePolicy()
    assert ctl.parse_spec("1") == ctl.ScalePolicy()
    p = ctl.parse_spec("min=2,max=6,up=1.5,down=0.1,step=3,period=0.5")
    assert (p.min_replicas, p.max_replicas) == (2, 6)
    assert (p.up_threshold, p.down_threshold) == (1.5, 0.1)
    assert (p.step_max, p.period_s) == (3, 0.5)


def test_parse_spec_rejects_unknown_keys():
    with pytest.raises(ValueError):
        ctl.parse_spec("replicas=3")
    with pytest.raises(ValueError):
        ctl.parse_spec("min")  # bare token, not k=v


def test_policy_validation():
    with pytest.raises(ValueError):
        ctl.ScalePolicy(min_replicas=0)
    with pytest.raises(ValueError):
        ctl.ScalePolicy(min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError):
        ctl.ScalePolicy(up_threshold=0.5, down_threshold=0.5)


# ---------------------------------------------------------------------------
# controller: hysteresis, cooldowns, AIMD, determinism
# ---------------------------------------------------------------------------


def _snap(t, pressure, replicas):
    return sig.PressureSnapshot(
        t=t, replicas=replicas, queue_depth=0, inflight=0,
        queue_per_replica=0.0, oldest_queued_s=0.0, burn_ewma=0.0,
        overload_level=0, request_rate=0.0, hedge_rate=0.0,
        pad_waste_rate=0.0, hbm_headroom_frac=None, pressure=pressure,
    )


def test_controller_aimd_up_and_single_step_down():
    pol = ctl.ScalePolicy(min_replicas=1, max_replicas=8,
                          up_cooldown_s=1.0, down_cooldown_s=2.0,
                          step_max=4)
    c = ctl.ScaleController(pol)
    d1 = c.decide(_snap(0.0, 2.0, 1))
    assert (d1.action, d1.delta) == (ctl.UP, 1)
    # inside the up cooldown: hold, whatever the pressure says
    assert c.decide(_snap(0.5, 3.0, 2)).action == ctl.HOLD
    # sustained saturation: the step doubles (1 -> 2 -> 4, capped)
    d2 = c.decide(_snap(1.1, 2.0, 2))
    assert (d2.action, d2.delta) == (ctl.UP, 2)
    d3 = c.decide(_snap(2.2, 2.0, 4))
    assert (d3.action, d3.delta) == (ctl.UP, 4)
    # scale-down is additive: one lane, after the longer cooldown
    assert c.decide(_snap(3.0, 0.0, 8)).action == ctl.HOLD
    d4 = c.decide(_snap(4.3, 0.0, 8))
    assert (d4.action, d4.delta) == (ctl.DOWN, 1)


def test_controller_bound_holds():
    c = ctl.ScaleController(ctl.ScalePolicy(min_replicas=1,
                                            max_replicas=2))
    assert c.decide(_snap(0.0, 5.0, 2)).reason == "at max_replicas"
    assert c.decide(_snap(1.0, 0.0, 1)).reason == "at min_replicas"
    assert c.decide(_snap(2.0, 0.5, 1)).reason == "in hysteresis band"


def _raw_stream():
    """A deterministic synthetic observation stream: quiet, a queue
    burst, quiet again.  Plain dicts — exactly what read_raw returns —
    so the fold is exercised end to end without a service."""
    rows = []
    reqs = 0.0
    for i in range(60):
        burst = 10 <= i < 30
        reqs += 4.0 if burst else 1.0
        rows.append({
            # the fleet grows mid-stream (as the actuator would have
            # made it): the quiet tail must produce scale-DOWNs
            "t": i * 0.05, "replicas": 2.0 if i >= 30 else 1.0,
            "queue_depth": 9.0 if burst else 0.0,
            "inflight": 1.0,
            "oldest_queued_s": 0.8 if burst else 0.0,
            "burn_ewma": 0.3 if burst else 0.0,
            "overload_level": 0.0, "requests": reqs,
            "hedges": 0.0, "pad_rows": 0.0,
            "hbm_headroom_frac": None,
        })
    return rows


def test_controller_seeded_determinism():
    def run():
        agg = sig.SignalAggregator()
        c = ctl.ScaleController(ctl.ScalePolicy(
            up_cooldown_s=0.3, down_cooldown_s=0.5))
        return [c.decide(agg.update(raw)) for raw in _raw_stream()]

    a, b = run(), run()
    # frozen dataclasses all the way down: == compares the full
    # decision record including the driving snapshot
    assert a == b
    assert any(d.action == ctl.UP for d in a)
    assert any(d.action == ctl.DOWN for d in a)


def test_no_flap_under_oscillating_pressure():
    """Pressure square-waves across both thresholds every sample; the
    cooldowns must keep the fleet from ping-ponging."""
    pol = ctl.ScalePolicy(min_replicas=1, max_replicas=3,
                          up_cooldown_s=0.5, down_cooldown_s=1.0)
    c = ctl.ScaleController(pol)
    n = 1
    changes = []
    for i in range(100):
        t = i * 0.05
        p = 2.0 if i % 2 == 0 else 0.0
        d = c.decide(_snap(t, p, n))
        if d.action == ctl.UP:
            n += d.delta
            changes.append((t, d.action))
        elif d.action == ctl.DOWN:
            n -= d.delta
            changes.append((t, d.action))
        assert pol.min_replicas <= n <= pol.max_replicas
    # 50 threshold crossings each way, but every applied change must
    # clear the cooldown of its direction from the PREVIOUS change
    for (t0, _a0), (t1, a1) in zip(changes, changes[1:]):
        floor = (pol.up_cooldown_s if a1 == ctl.UP
                 else pol.down_cooldown_s)
        assert t1 - t0 >= floor - 1e-9, changes
    assert len(changes) <= 8, changes


def test_aggregator_pure_fold_and_reset():
    agg = sig.SignalAggregator()
    snaps = [agg.update(r) for r in _raw_stream()]
    agg.reset()
    again = [agg.update(r) for r in _raw_stream()]
    assert snaps == again
    # the burst must push the composite past 1.0 and decay after
    assert max(s.pressure for s in snaps) > 1.0
    assert snaps[-1].pressure < 0.25
    # rates derive from counter deltas: quiet tail ~= 20 req/s
    assert snaps[-1].request_rate == pytest.approx(20.0, rel=0.5)


# ---------------------------------------------------------------------------
# predictive warmup planning
# ---------------------------------------------------------------------------


def _trace_rows():
    rows = []
    # hot small bucket: 3 repeat groups x 20 rows, bursty arrivals
    for g in range(3):
        for i in range(20):
            rows.append({
                "t_offset": g * 1.0 + (i // 4) * 0.1 + (i % 4) * 1e-4,
                "routine": "gesv", "bucket_shape": [12, 12, 2],
                "dtype": "float64", "repeat_fp": f"hot-{g}",
                "matrix_seed": g, "rhs_seed": i,
            })
    # rare large bucket: 4 singleton rows (no repeats, no bursts)
    for i in range(4):
        rows.append({
            "t_offset": 10.0 + i, "routine": "gesv",
            "bucket_shape": [48, 48, 2], "dtype": "float64",
            "repeat_fp": None, "matrix_seed": 100 + i,
            "rhs_seed": i,
        })
    return rows


def test_plan_ranking_traffic_times_cost():
    plan = wp.plan_from_trace(_trace_rows(), batch_max=4,
                              batch_window_s=0.005, dim_floor=FLOOR,
                              nrhs_floor=NRHS_FLOOR)
    assert plan.total_rows == 64
    scores = [e.score for e in plan.entries]
    assert scores == sorted(scores, reverse=True)
    labels = {(e.key.label, e.key.phase, e.batch)
              for e in plan.entries}
    # the bursty hot bucket plans its coalesced batch point too
    hot = [e for e in plan.entries
           if e.key.n == 16 and e.key.phase == "full"]
    assert {e.batch for e in hot} == {1, 4}
    # repeat groups dispatch the solve sibling on a warm factor
    # cache: the trsm-only family must be in the plan
    assert any(ph == "solve" for (_l, ph, _b) in labels)
    # the rare-but-huge bucket outranks the hot-but-tiny one:
    # 4/64 x flops(64) beats 60/64 x flops(16)
    big = next(e for e in plan.entries if e.key.n == 64)
    small_b1 = next(e for e in hot if e.batch == 1)
    assert big.score > small_b1.score


def test_plan_preload_ranks_by_bought_hits():
    plan = wp.plan_from_trace(_trace_rows(), dim_floor=FLOOR,
                              nrhs_floor=NRHS_FLOOR)
    assert [p.repeat_fp for p in plan.preload] == [
        "hot-0", "hot-1", "hot-2"]
    assert all(p.rows == 20 for p in plan.preload)
    # singletons buy no hits: never preloaded
    assert all(p.repeat_fp.startswith("hot-") for p in plan.preload)


def test_plan_save_load_round_trip(tmp_path):
    plan = wp.plan_from_trace(_trace_rows(), dim_floor=FLOOR,
                              nrhs_floor=NRHS_FLOOR)
    path = plan.save(str(tmp_path / "plan.jsonl"))
    back = wp.WarmupPlan.load(path)
    assert back.total_rows == plan.total_rows
    assert back.entries == plan.entries
    assert back.preload == plan.preload
    assert back.pairs(2) == plan.pairs(2)


def test_plan_from_generated_burst_trace():
    rows = replay.gen_burst(200, seed=3, base_rps=50, burst_rps=500,
                            burst_start_s=0.5, burst_len_s=0.5)
    plan = wp.plan_from_trace(rows, batch_max=8, dim_floor=FLOOR,
                              nrhs_floor=NRHS_FLOOR)
    assert plan.total_rows == 200
    assert plan.entries and plan.preload
    # the burst coalesces: some batch point above 1 is planned
    assert max(e.batch for e in plan.entries) > 1


def test_gen_burst_shape():
    rows = replay.gen_burst(400, seed=1, base_rps=30, burst_rps=300,
                            burst_start_s=1.0, burst_len_s=1.0)
    in_burst = [r for r in rows if 1.0 <= r["t_offset"] < 2.0]
    before = [r for r in rows if r["t_offset"] < 1.0]
    # ~30 arrivals in the first second, ~300 in the burst second
    assert len(before) < len(in_burst) / 3
    assert rows == sorted(rows, key=lambda r: r["t_offset"])


# ---------------------------------------------------------------------------
# zero-overhead-off + env arming + callable-module compatibility
# ---------------------------------------------------------------------------


def test_scaler_off_by_default(shared_cache, monkeypatch):
    monkeypatch.delenv(ctl.SCALE_ENV, raising=False)
    svc = _service(shared_cache)
    try:
        assert svc._scaler is None
        h = svc.health()
        assert h["capacity"] is None
        assert all(l["state"] == "live" for l in h["replicas"])
    finally:
        svc.stop()


def test_env_arms_scaler(shared_cache, monkeypatch):
    monkeypatch.setenv(ctl.SCALE_ENV, "min=1,max=2,period=30")
    svc = _service(shared_cache)
    try:
        assert svc._scaler is not None
        assert svc._scaler.policy.max_replicas == 2
        dec = svc._scaler.step()  # idle fleet at min: hold
        assert dec.action == ctl.HOLD
        cap = svc.health()["capacity"]
        assert cap["policy"]["max_replicas"] == 2
        assert cap["last_action"] == ctl.HOLD
        assert metrics.counters().get("scale.decisions") == 1
    finally:
        svc.stop()
    assert svc._scaler._thread is None  # stop() stops the sampler


def test_scale_module_still_callable_as_aux_driver():
    # slate_tpu.scale was the aux scaling routine long before it was
    # a package; importing the package must not break callers
    import slate_tpu as st
    import slate_tpu.scale as scale_pkg
    from slate_tpu.matrix.matrix import Matrix

    assert scale_pkg.ScalePolicy is ctl.ScalePolicy
    A0 = np.arange(16.0).reshape(4, 4)
    A2 = st.scale(3.0, 2.0, Matrix.from_global(A0.copy(), 4))
    np.testing.assert_allclose(np.asarray(A2.to_global()), A0 * 1.5)


# ---------------------------------------------------------------------------
# live lifecycle: add / remove / drain / re-home
# ---------------------------------------------------------------------------


def test_add_replica_then_steady_state_compile_free(shared_cache):
    svc = _service(shared_cache)
    rng = np.random.default_rng(0)
    try:
        A, B = _ops(rng)
        for f in [svc.submit("gesv", A, B) for _ in range(8)]:
            f.result(30)
        name = svc.add_replica()
        with svc._cond:
            assert len(svc._replicas) == 2
        # the new lane was primed inside add_replica: steady-state
        # traffic afterwards compiles nothing
        with metrics.deltas() as d:
            futs = [svc.submit("gesv", A, B) for _ in range(16)]
            for f in futs:
                f.result(30)
            assert d.get("jit.compilations") == 0
        h = svc.health()
        states = {l["name"]: l["state"] for l in h["replicas"]}
        assert states[name] == "live"
        assert metrics.counters().get("scale.replicas_added") == 1
    finally:
        svc.stop()


def test_remove_replica_drains_and_rehomes(shared_cache):
    svc = _service(shared_cache, replicas=2)
    rng = np.random.default_rng(1)
    try:
        # distinct matrices fill the factor cache with entries homed
        # on both lanes
        ops = [_ops(rng) for _ in range(24)]
        for f in [svc.submit("gesv", A, B) for A, B in ops]:
            f.result(30)
        pre = sum(1 for e in svc.factor_cache._entries.values()
                  if e.replica == "1")
        # repeat traffic (factor hits) in flight while lane 1 drains
        futs = [svc.submit("gesv", A, B) for A, B in ops]
        removed = svc.remove_replica("1", drain_timeout=60)
        assert removed == "1"
        for f in futs:  # every inflight/queued future still resolves
            np.asarray(f.result(60))
        with svc._cond:
            assert len(svc._replicas) == 1
        # no factor entry left homed on the dead lane
        assert not any(e.replica == "1"
                       for e in svc.factor_cache._entries.values())
        c = metrics.counters()
        if pre:
            assert c.get("scale.factors_rehomed", 0) >= pre
            assert c.get("serve.factor_cache.rehome", 0) >= pre
        assert c.get("serve.replica.1.removed") == 1
        # the lane stays visible as a terminal row, not a vanished one
        h = svc.health()
        states = {l["name"]: l["state"] for l in h["replicas"]}
        assert states["1"] == "removed"
        row = next(l for l in h["replicas"] if l["name"] == "1")
        assert row["worker_alive"] is False
        # and the survivor still serves
        A, B = ops[0]
        np.asarray(svc.submit("gesv", A, B).result(30))
    finally:
        svc.stop()


def test_remove_last_lane_refused(shared_cache):
    svc = _service(shared_cache)
    try:
        with pytest.raises(ValueError):
            svc.remove_replica()
        with pytest.raises(ValueError):
            svc.remove_replica("no-such-lane")
    finally:
        svc.stop()


def test_add_replica_after_stop_refused(shared_cache):
    svc = _service(shared_cache)
    svc.stop()
    with pytest.raises(RuntimeError):
        svc.add_replica()


def test_add_replica_with_plan(shared_cache):
    """A recorded-trace plan drives the new lane's priming order."""
    svc = _service(shared_cache)
    rng = np.random.default_rng(2)
    try:
        rows = [{
            "t_offset": i * 0.001, "routine": "gesv",
            "bucket_shape": [12, 12, 2], "dtype": "float64",
            "repeat_fp": "p0", "matrix_seed": 0, "rhs_seed": i,
        } for i in range(10)]
        plan = wp.plan_from_trace(rows, batch_max=1, dim_floor=FLOOR,
                                  nrhs_floor=NRHS_FLOOR)
        name = svc.add_replica(plan=plan)
        c = metrics.counters()
        primed = sum(v for k, v in c.items()
                     if k.startswith("scale.prime_"))
        assert primed >= 1
        A, B = _ops(rng)
        np.asarray(svc.submit("gesv", A, B).result(30))
        with svc._cond:
            assert [r.name for r in svc._replicas] == ["0", name]
    finally:
        svc.stop()


def test_read_raw_live_service(shared_cache):
    svc = _service(shared_cache, replicas=2)
    try:
        raw = sig.read_raw(svc)
        assert raw["replicas"] == 2.0
        assert raw["queue_depth"] >= 0.0
        snap = sig.SignalAggregator().update(raw)
        assert snap.replicas == 2
        assert snap.pressure >= 0.0
    finally:
        svc.stop()
