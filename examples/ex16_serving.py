"""ex16: the serving layer — warmup manifest, mixed-shape concurrent
requests, batching + deadline/backpressure semantics, metrics report.

Workflow demonstrated (README "Serving API"):
  1. drive traffic once; the cache records every bucket to a manifest
  2. restart (fresh cache), `warmup()` the manifest -> pre-compiled
  3. serve a concurrent mixed-shape stream: zero steady-state compiles
"""

import tempfile
from concurrent.futures import ThreadPoolExecutor

from _common import check, np

from slate_tpu.aux import metrics
from slate_tpu.serve.cache import ExecutableCache
from slate_tpu.serve.service import SolverService

metrics.on()
rng = np.random.default_rng(16)

# three shape classes that land in two buckets per routine
n_small, n_big, nrhs = 20, 50, 3
mk_gesv = lambda n, i: rng.standard_normal((n, n)) + (n + i) * np.eye(n)
G = rng.standard_normal((n_big, n_big))
A_spd = G @ G.T + n_big * np.eye(n_big)
rhs = lambda n: rng.standard_normal((n, nrhs))

manifest = tempfile.mktemp(suffix="_warmup.json")

# -- phase 1: record the bucket working set -------------------------------
cache1 = ExecutableCache(manifest_path=manifest)
with SolverService(cache=cache1, batch_max=4, dim_floor=32) as svc:
    futs = [svc.submit("gesv", mk_gesv(n_small, i), rhs(n_small)) for i in range(4)]
    futs += [svc.submit("posv", A_spd, rhs(n_big))]
    futs += [svc.submit("gels", rng.standard_normal((n_big, n_small)), rhs(n_big))]
    for f in futs:
        f.result()
print(f"manifest recorded: {len(cache1.entries())} (bucket, batch) entries")

# -- phase 2: fresh process-equivalent: warmup, then serve ----------------
cache2 = ExecutableCache(manifest_path=None)
compiled = cache2.warmup(manifest, batch_max=4)
print(f"warmup: {compiled} executables pre-compiled")

with SolverService(cache=cache2, batch_max=4, dim_floor=32) as svc:
    with metrics.deltas() as d:
        with ThreadPoolExecutor(8) as pool:  # concurrent mixed-shape clients
            def client(i):
                if i % 3 == 0:
                    A, B = mk_gesv(n_small, i), rhs(n_small)
                    X = svc.submit("gesv", A, B, deadline=30.0).result()
                elif i % 3 == 1:
                    A = A_spd + i * 1e-3 * np.eye(n_big)
                    B = rhs(n_big)
                    X = svc.submit("posv", A, B).result()
                else:
                    A, B = rng.standard_normal((n_big, n_small)), rhs(n_big)
                    X = svc.submit("gels", A, B).result()
                    return np.abs(X - np.linalg.lstsq(A, B, rcond=None)[0]).max()
                return np.abs(A @ X - B).max() / np.abs(B).max()

            errs = list(pool.map(client, range(24)))
        compiles = d.get("jit.compilations")
        batched = d.get("serve.batched")
    check("ex16 serving stream", max(errs), 1e-8)
    print(f"steady-state compiles: {compiles:g} (expect 0), "
          f"coalesced batches: {batched:g}, "
          f"pad waste: {d.get('serve.bucket_pad_waste'):g} elements")
    assert compiles == 0, "warmed steady state must not compile"
