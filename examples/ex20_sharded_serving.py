"""ex20: sharded serving — a mesh-aware SolverService on a forced
8-virtual-device CPU mesh (the same shape a TPU pod slice presents).

Placement tier demonstrated (README "Sharded serving"):
  * small requests data-parallel-replicate across 3 replica workers,
    each pinned to its own device, least-loaded dispatch;
  * large-n requests (past ``shard_threshold``) — and anything
    submitted ``sharded=True`` — route to the spmd drivers under
    shard_map on a 2x2 submesh (one request spans 4 devices);
  * after warmup the whole mixed stream is compile-free on EVERY
    replica, and ``health()`` shows per-replica dispatch counts.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 python ex20_sharded_serving.py
"""

import os
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_platforms", "cpu")

from _common import check, np  # noqa: E402

from slate_tpu.aux import metrics  # noqa: E402
from slate_tpu.serve import buckets as bk  # noqa: E402
from slate_tpu.serve.cache import ExecutableCache  # noqa: E402
from slate_tpu.serve.placement import PlacementPolicy  # noqa: E402
from slate_tpu.serve.service import SolverService  # noqa: E402

metrics.on()
rng = np.random.default_rng(20)
assert len(jax.devices()) >= 8, "run with xla_force_host_platform_device_count=8"

n_small, n_large, nrhs = 12, 50, 2
policy = PlacementPolicy(replicas=3, mesh="2x2", shard_threshold=40)
svc = SolverService(
    cache=ExecutableCache(manifest_path=None), batch_max=4,
    batch_window_s=0.002, dim_floor=16, nrhs_floor=4, placement=policy,
)

# warm both tiers up front: the small bucket's two batch points on all
# three replica devices, and the sharded bucket's spmd executable
key_small = bk.bucket_for("gesv", n_small, n_small, nrhs, np.float64,
                          floor=16, nrhs_floor=4)
key_large = bk.bucket_for("gesv", n_large, n_large, nrhs, np.float64,
                          floor=16, nrhs_floor=4, mesh="2x2")
svc.cache.ensure_manifest(key_small, (1, 4))
svc.cache.ensure_manifest(key_large, (1,))
compiled = svc.warmup()
print(f"warmup: {compiled} executables live "
      f"(replicas={policy.replicas}, mesh={policy.mesh})")


def problem(n, seed):
    r = np.random.default_rng(seed)
    return (r.standard_normal((n, n)) + n * np.eye(n),
            r.standard_normal((n, nrhs)))


problems = [problem(n_small, i) for i in range(18)]
problems += [problem(n_large, 100 + i) for i in range(2)]

with metrics.deltas() as d:
    futs = [svc.submit("gesv", A, B) for A, B in problems]
    worst = 0.0
    for (A, B), f in zip(problems, futs):
        X = f.result(timeout=600)
        worst = max(worst, np.abs(X - np.linalg.solve(A, B)).max())
    check("ex20 mixed-stream parity (replicated + sharded)", worst, 1e-8)
    assert d.get("jit.compilations") == 0, (
        f"warmed stream must not compile: {d.get('jit.compilations')}")
    print(f"routing: {int(d.get('serve.replicated_dispatch'))} replicated, "
          f"{int(d.get('serve.routed_sharded'))} sharded, "
          "0 steady-state compiles")

# one explicitly sharded solve: small shape, forced onto the submesh
A, B = problem(20, 7)
X = svc.submit("gesv", A, B, sharded=True).result(timeout=600)
check("ex20 explicit sharded=True parity",
      np.abs(X - np.linalg.solve(A, B)).max(), 1e-8)

h = svc.health()
for r in h["replicas"]:
    print(f"replica {r['name']}: dispatched={r['dispatched']} "
          f"queue_depth={r['queue_depth']} device={r['device']}")
print(f"sharded lane ({h['sharded']['mesh']}): "
      f"dispatched={h['sharded']['dispatched']}")
busy = [r["name"] for r in h["replicas"] if r["dispatched"] > 0]
assert len(busy) >= 2, f"expected >= 2 busy replicas, got {busy}"
svc.stop()
print("ex20 done: scale-out across replicas + spmd routing verified")
