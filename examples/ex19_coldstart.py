"""ex19: durable executable artifacts — the crash-safe cold start.

The restart drill from README "Deployment & cold start", end to end:

  1. warm a SolverService in THIS process with SLATE_TPU_ARTIFACTS set:
     every bucket executable is persisted (jax.export StableHLO +
     fingerprint + checksum) next to the warmup manifest
  2. restore in a FRESH interpreter pointed at the same directory: the
     service goes cold -> restoring -> ready with zero recompiles, and
     a 20-request mixed steady-state stream keeps jit.compilations flat
  3. byte-flip one artifact on disk and drill again: the checksum
     catches it (serve.artifact_corrupt), the bucket recompiles, every
     request still serves correctly, and the re-save self-heals the
     store for the NEXT replica

schedule="recursive" routes the PR3 pure-JAX kernels, whose exported
modules are custom-call free and therefore portable across processes
(schedule="auto" buckets land on vendor LAPACK on CPU and take the
cache_seed rung instead — durable, but a recompile).
"""

import json
import os
import pathlib
import subprocess
import sys
import tempfile

from _common import check, np

from slate_tpu.serve.artifacts import ArtifactStore
from slate_tpu.serve.cache import ExecutableCache
from slate_tpu.serve.service import SolverService

rng = np.random.default_rng(19)
n = 24
A = rng.standard_normal((n, n)) + n * np.eye(n)
B = rng.standard_normal((n, 2))

tmp = tempfile.mkdtemp(prefix="slate_ex19_")
art, man = os.path.join(tmp, "artifacts"), os.path.join(tmp, "warmup.json")

# -- 1. warm + persist ------------------------------------------------------
cache = ExecutableCache(manifest_path=man, artifact_dir=art)
svc = SolverService(cache=cache, batch_max=4, dim_floor=16, nrhs_floor=2,
                    schedule="recursive")
svc.wait_ready(120)
X = svc.submit("gesv", A, B).result(timeout=300)
check("warm-process gesv", np.abs(A @ X - B).max())
cache.warmup(batch_max=4)  # bake the remaining batch point
svc.stop()
arts = sorted(f for f in os.listdir(art) if f.endswith(".slate_exe"))
modes = [json.loads(open(os.path.join(art, f), "rb").readline())["mode"]
         for f in arts]
print(f"persisted {len(arts)} artifact(s): modes {sorted(set(modes))}")

# -- 2./3. restore legs run in a FRESH interpreter --------------------------
_RESTORE = """
import sys
from _common import check, np
from slate_tpu.aux import metrics
from slate_tpu.serve.cache import ExecutableCache
from slate_tpu.serve.service import SolverService

art, man, leg = sys.argv[1:4]
metrics.on()
rng = np.random.default_rng(19)
n = 24
A = rng.standard_normal((n, n)) + n * np.eye(n)
B = rng.standard_normal((n, 2))

svc = SolverService(
    cache=ExecutableCache(manifest_path=man, artifact_dir=art),
    batch_max=4, dim_floor=16, nrhs_floor=2, schedule="recursive",
)  # restores on start
assert svc.wait_ready(300), svc.health()
h = svc.health()
res = h["restore"]
print(f"  {leg}: phase={h['phase']} restored={res['restored']} "
      f"compiled={res['compiled']} failed={res['failed']}")
if leg == "clean":
    assert res["compiled"] == 0, res  # every entry from a verified blob
else:
    assert res["compiled"] >= 1, res  # flipped artifact -> recompile
    assert metrics.counters().get("serve.artifact_corrupt", 0) >= 1

with metrics.deltas() as d:
    futs = [svc.submit("gesv", A + i * 1e-3 * np.eye(n), B)
            for i in range(20)]
    for f in futs:
        assert np.all(np.isfinite(f.result(timeout=300)))
    assert d.get("serve.requests") >= 20
    assert d.get("jit.compilations") == 0, "steady state must not compile"
X = svc.submit("gesv", A, B).result(timeout=300)
svc.stop()
check(f"  {leg} fresh-process gesv (20+ requests, 0 compiles)",
      np.abs(A @ X - B).max())
"""


def fresh_process(leg):
    r = subprocess.run(
        [sys.executable, "-c", _RESTORE, art, man, leg],
        cwd=pathlib.Path(__file__).resolve().parent, timeout=600,
    )
    assert r.returncode == 0, f"{leg} restore leg failed"


print("fresh-process restore (clean store):")
fresh_process("clean")

victim = os.path.join(art, arts[0])
blob = bytearray(open(victim, "rb").read())
blob[-3] ^= 0xFF  # one payload byte: the checksum must catch this
open(victim, "wb").write(bytes(blob))
print("fresh-process restore (one artifact byte-flipped):")
fresh_process("flipped")

# the recompile re-saved the entry — the store healed itself: every
# entry load-verifies again (checksum + fingerprint + deserialize;
# entries() alone only parses headers and would not see payload rot)
st = ArtifactStore(art)
assert all(st.load(k, b) is not None for k, b in cache.entries())
print("store self-healed: all entries verify again")
