"""ex01: creating matrices (reference: examples/ex01_matrix.cc).

Build matrices from global arrays, inspect tiling, round-trip."""
from _common import np
import slate_tpu as st

A0 = np.arange(20.0 * 12).reshape(20, 12)
A = st.Matrix.from_global(A0, 8)
print(A)  # 20x12, tiles 8x8
assert A.mt == 3 and A.nt == 2
assert np.array_equal(np.asarray(A.to_global()), A0)
print("ex01 ok")
