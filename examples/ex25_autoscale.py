"""ex25: elastic capacity — a bursty stream against an autoscaling
SolverService (README "Elastic capacity").

One service starts with a single replica lane and ``SLATE_TPU_SCALE``
armed.  A recorded-shape bursty trace (quiet 30 req/s baseline, a
2 s step to 120 req/s) replays open-loop while a fixed per-dispatch
latency fault stands in for real solve weight on CPU; the capacity
plane must:

  * see the burst in its pressure signals and grow the fleet
    (scale_up decisions, every one carrying its driving snapshot);
  * warm each new lane inside ``add_replica`` BEFORE it takes
    traffic — the only compiles in the measured stream are the
    counted pre-traffic device primes (``serve.device_primes``);
    no request dispatch ever compiles, scale-ups included;
  * give the lanes back once the burst passes (scale_down on the
    quiet tail, fleet ends back at min_replicas), with the removed
    lanes still visible as terminal rows in ``health()``.

Run: python ex25_autoscale.py
"""

import os
import pathlib
import sys
import tempfile
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
# arm the capacity plane BEFORE the service is constructed: with the
# env unset the service never builds a scaler at all (zero overhead)
os.environ["SLATE_TPU_SCALE"] = (
    "min=1,max=3,up=1.0,down=0.2,up_cooldown=0.25,"
    "down_cooldown=2.0,step=2,period=0.05"
)

import jax

jax.config.update("jax_platforms", "cpu")

from _common import np  # noqa: E402

from slate_tpu.aux import faults, metrics  # noqa: E402
from slate_tpu.serve import buckets as bk  # noqa: E402
from slate_tpu.serve.cache import ExecutableCache  # noqa: E402
from slate_tpu.serve.factor_cache import FactorCache  # noqa: E402
from slate_tpu.serve.service import SolverService  # noqa: E402
from slate_tpu.soak import replay  # noqa: E402

metrics.on()
art = tempfile.mkdtemp(prefix="ex25_artifacts_")

svc = SolverService(
    cache=ExecutableCache(manifest_path=None, artifact_dir=art),
    batch_max=1, batch_window_s=0.0005, dim_floor=16, nrhs_floor=4,
    replicas=1, factor_cache=FactorCache(max_entries=16),
)
assert svc._scaler is not None, "SLATE_TPU_SCALE should arm the scaler"
k = bk.bucket_for("gesv", 12, 12, 2, np.float64, floor=16, nrhs_floor=4)
svc.cache.ensure_manifest(k, (1,))
svc.cache.ensure_manifest(k.solve_sibling(), (1,))
# warmup compiles once and exports to the artifact store — that store
# is what lets add_replica bring a NEW lane live without compiling
svc.warmup()

spec = replay.gen_burst(400, seed=25, base_rps=30, burst_rps=120,
                        burst_start_s=1.0, burst_len_s=2.0,
                        n=12, nrhs=2, distinct=4)
replay.replay(svc, replay.warm_spec(spec), speed=1.0, seed=0)
metrics.reset()

# a fixed 12 ms latency tax per dispatch: one lane saturates near
# 60 req/s, so the 120 req/s burst genuinely needs more lanes
faults.configure("latency:every=1,ms=12")
faults.on()
with metrics.deltas() as d:
    res = replay.replay(svc, spec, speed=1.0, seed=0)
    faults.reset()
    # quiet tail: the scaler must give the burst capacity back
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        with svc._cond:
            fleet = len(svc._replicas)
        if fleet == 1:
            break
        time.sleep(0.05)
    compiles = int(d.get("jit.compilations"))
    primes = int(d.get("serve.device_primes"))

print(f"replayed {res['submitted']} requests: "
      f"{res['delivered']} delivered, p99={(res['p99_s'] or 0) * 1e3:.0f}ms")
assert res["delivered"] == res["submitted"], res
assert res["bad_results"] == 0, res

print("decision timeline:")
for dec in svc._scaler.decisions:
    s = dec.snapshot
    print(f"  t={s.t:10.3f}s {dec.action:4} delta={dec.delta} "
          f"replicas={s.replicas} pressure={s.pressure:.2f} "
          f"({dec.reason})")

ups = sum(1 for dec in svc._scaler.decisions if dec.action == "up")
downs = sum(1 for dec in svc._scaler.decisions if dec.action == "down")
assert ups >= 1, "the burst never drove a scale-up"
assert downs >= 1, "the quiet tail never gave capacity back"
assert fleet == 1, f"fleet should end at min_replicas, got {fleet}"
# the zero-steady-state-compiles contract: every compile in the
# measured window is a pre-traffic lane prime inside add_replica
# (serve.device_primes — counted cold-start budget, never hidden);
# the dispatch path itself compiled NOTHING
assert primes >= 1, "scale-up never primed its lane"
assert compiles == primes, (
    f"steady state must be compile-free: {compiles} compiles but only "
    f"{primes} pre-traffic lane primes")

h = svc.health()
cap = h["capacity"]
print(f"capacity: fleet back to {fleet} lane(s), "
      f"{cap['decisions']} applied decisions, "
      f"terminal lanes {cap['terminal_lanes']}")
for row in h["replicas"]:
    print(f"  lane {row['name']}: state={row['state']} "
          f"dispatched={row.get('dispatched', 0)}")
assert any(r["state"] == "removed" for r in h["replicas"]), (
    "removed lanes must stay visible as terminal rows")
svc.stop()
print(f"ex25 done: burst absorbed elastically, {ups} up / {downs} down, "
      f"{primes} pre-traffic lane prime(s), 0 steady-state compiles")
