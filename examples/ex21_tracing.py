"""ex21: request-lifecycle tracing, latency histograms, SLO health.

Runs a warmed serve stream under fault injection with the span layer
on, then answers the question counters cannot: *where did the slow
request's time go?* (README "Tracing & latency"):

  1. every request gets a trace id and an admit -> deliver span chain
  2. a retried request's trace carries a `backoff` span whose interval
     IS the decorrelated-jitter delay it sat out
  3. the Chrome export (Perfetto / chrome://tracing) has one lane per
     replica/worker; no delivered request is an orphan
  4. per-bucket p50/p95/p99 with the queued-vs-execute split comes
     from the metrics histograms, and health() surfaces the SLO view
"""

import json

from _common import np

from slate_tpu.aux import faults, metrics, spans
from slate_tpu.serve import buckets as bk
from slate_tpu.serve.cache import ExecutableCache
from slate_tpu.serve.service import SolverService

metrics.on()
spans.on(ring=8192)  # flight recorder: the production spelling is
#                      SLATE_TPU_TRACE_RING=8192 in the environment

rng = np.random.default_rng(21)
n = 24
mk = lambda i: rng.standard_normal((n, n)) + (n + i) * np.eye(n)
rhs = lambda: rng.standard_normal((n, 2))

svc = SolverService(
    cache=ExecutableCache(manifest_path=None), batch_max=4,
    batch_window_s=0.002, dim_floor=32, retry_backoff_s=0.01,
    breaker_cooldown_s=0.05, retry_seed=21,
)
key = bk.bucket_for("gesv", n, n, 2, np.float64, floor=32)
svc.cache.ensure_manifest(key, (1, 4))
svc.warmup()  # warmed first: an execute fault during warmup would
#               (correctly) fail the precompile

# -- faulty stream, traced ------------------------------------------------
faults.arm("execute", every=5)  # every 5th dispatch fails -> retries
faults.arm("latency", p=0.3, ms=4, seed=21)
faults.on()

futs = [svc.submit("gesv", mk(i), rhs(), deadline=120.0, retries=2)
        for i in range(20)]
for f in futs:
    X = f.result(timeout=300)
    assert np.all(np.isfinite(X))
faults.reset()

# -- the retry span: the ISSUE assertion ----------------------------------
back = [s for s in spans.snapshot() if s.name == "backoff"]
assert back, "execute faults fired but no backoff span was recorded"
sp = back[0]
assert sp.trace is not None and sp.attrs["backoff_s"] > 0
assert abs(sp.dur_s - sp.attrs["backoff_s"]) < 1e-3
chain = {s.name for s in spans.by_trace()[sp.trace]}
assert {"request", "admit", "queued", "execute", "backoff"} <= chain
print(f"retry span: trace {sp.trace} sat out "
      f"{sp.attrs['backoff_s'] * 1e3:.1f} ms of backoff "
      f"(chain: {', '.join(sorted(chain))})")

# -- Chrome export: complete chains, no orphans ---------------------------
path = spans.export_chrome("/tmp/slate_tpu_ex21_trace.json")
data = json.load(open(path))
traces = {}
for e in data["traceEvents"]:
    tr = e.get("args", {}).get("trace")
    if tr:
        traces.setdefault(tr, set()).add(e["name"])
delivered = 0
for tr, names in traces.items():
    assert "request" in names, f"orphan trace {tr}"
    if "execute" in names or "direct" in names:
        delivered += 1
assert delivered >= 20
lanes = sorted(e["args"]["name"] for e in data["traceEvents"]
               if e.get("ph") == "M")
print(f"chrome export: {path} — {len(traces)} traces, 0 orphans, "
      f"lanes {lanes} (open in https://ui.perfetto.dev)")

# -- the latency split + SLO surface --------------------------------------
h = svc.health()
lbl = key.label
lat = h["latency"][lbl]
qh = metrics.hist_summary(f"serve.latency.{lbl}.queued")
xh = metrics.hist_summary(f"serve.latency.{lbl}.execute")
print(f"latency {lbl}: total p50/p95/p99 = "
      f"{lat['p50'] * 1e3:.1f}/{lat['p95'] * 1e3:.1f}/"
      f"{lat['p99'] * 1e3:.1f} ms over {lat['count']} requests "
      f"(queued p99 {qh['p99'] * 1e3:.1f} ms, "
      f"execute p99 {xh['p99'] * 1e3:.1f} ms)")
print(f"slo burn: {h['slo_burn']} — oldest queued now "
      f"{h['replicas'][0]['oldest_queued_s']:.3f}s")
assert lat["count"] == 20 and h["slo_burn"]["requests"] == 20

svc.stop()
print("ex21: tracing ok")
