"""ex03: SPD solve (reference: examples/ex06_linear_system_cholesky.cc)."""
from _common import check, np
import slate_tpu as st

rng = np.random.default_rng(1)
n, nrhs, nb = 100, 4, 16
A0 = rng.standard_normal((n, n)); A0 = A0 @ A0.T + n * np.eye(n)
B0 = rng.standard_normal((n, nrhs))
A = st.HermitianMatrix.from_global(A0, nb, uplo=st.Uplo.Lower)
B = st.Matrix.from_global(B0, nb)
X, L, info = st.posv(A, B)
assert int(info) == 0
check("ex03 posv", np.abs(A0 @ np.asarray(X.to_global()) - B0).max() / np.abs(B0).max())
