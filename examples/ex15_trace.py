"""ex15: phase tracing with SVG timeline (reference: --trace, Trace.hh)."""
import os
from _common import np
import slate_tpu as st
from slate_tpu.aux import trace

trace.on()
rng = np.random.default_rng(12)
n = 64
A0 = rng.standard_normal((n, n)); S = A0 @ A0.T + n * np.eye(n)
B0 = rng.standard_normal((n, 2))
st.posv(st.HermitianMatrix.from_global(S, 16, uplo=st.Uplo.Lower),
        st.Matrix.from_global(B0, 16))
path = trace.finish("/tmp/slate_tpu_trace.svg")
assert os.path.getsize(path) > 100
print(f"ex15 trace ok: {path}")
