"""ex17: fault injection + the self-healing serve layer.

Runs a mixed request stream while aux/faults kills the worker, fails
dispatches, corrupts results, and poisons info codes — then shows the
containment doing its job (README "Failure semantics"):

  1. every future resolves: a result or a typed SlateError, never a hang
  2. the supervisor respawns the dead worker (serve.worker_restarts)
  3. a failing bucket's breaker opens, and once the faults stop, a
     half-open probe restores the batched path (recovery, not one-way
     degradation)
  4. admission checks reject non-finite operands before any queue cost
  5. service.health() snapshots all of it for an external probe
"""

from _common import check, np

from slate_tpu.aux import faults, metrics
from slate_tpu.exceptions import InvalidInput, SlateError
from slate_tpu.serve.cache import ExecutableCache
from slate_tpu.serve.service import SolverService

metrics.on()
rng = np.random.default_rng(17)
n = 24

mk = lambda i: rng.standard_normal((n, n)) + (n + i) * np.eye(n)
rhs = lambda: rng.standard_normal((n, 2))

# the production route is SLATE_TPU_FAULTS="execute:p=0.25,seed=7;..."
faults.arm("execute", p=0.25, seed=7)
faults.arm("worker_death", p=0.15, seed=9)
faults.arm("result_corrupt", every=11)
faults.on()

svc = SolverService(
    cache=ExecutableCache(manifest_path=None), batch_max=4, dim_floor=32,
    retry_backoff_s=0.005, breaker_cooldown_s=0.05, retry_seed=17,
)

# -- phase 1: faulty stream -----------------------------------------------
futs = [svc.submit("gesv", mk(i), rhs(), retries=2) for i in range(40)]
ok = typed = 0
for i, f in enumerate(futs):
    try:
        X = f.result(timeout=300)
        assert np.all(np.isfinite(X)), "corrupted result must never deliver"
        ok += 1
    except SlateError as e:
        typed += 1  # typed, contextful: e.routine / e.bucket / e.attempt
assert ok + typed == len(futs), "every future must resolve"
c = metrics.counters()
print(f"stream under faults: {ok} solved, {typed} typed errors, 0 hangs")
print(f"  injected: " + ", ".join(
    f"{k.split('.')[-1]}={int(v)}" for k, v in sorted(c.items())
    if k.startswith("faults.injected.")))
print(f"  worker restarts: {int(c.get('serve.worker_restarts', 0))}, "
      f"retries: {int(c.get('serve.retries', 0))}, "
      f"fallbacks: {int(c.get('serve.fallbacks', 0))}, "
      f"corrupt results recovered: {int(c.get('serve.corrupt_result', 0))}")

# -- phase 2: admission checks --------------------------------------------
bad = mk(0)
bad[1, 1] = np.nan
try:
    svc.submit("gesv", bad, rhs())
    raise AssertionError("non-finite A must be rejected at admission")
except InvalidInput as e:
    print(f"admission check: rejected pre-queue ({e})")

# -- phase 3: corruption containment, deterministically -------------------
# result_corrupt fires only on the batched path; the service detects the
# non-finite X against finite inputs and re-solves the item directly.
# First close any breaker phase 1 left open (an open breaker would
# route the probe request direct, where the corrupt site never fires)
faults.reset()
import time

while svc.health()["open_buckets"]:
    time.sleep(0.06)  # past the breaker cooldown
    svc.submit("gesv", mk(49), rhs()).result(timeout=300)  # clean probe
faults.arm("result_corrupt", every=1)
faults.on()
with metrics.deltas() as d:
    A, B = mk(50), rhs()
    X = svc.submit("gesv", A, B).result(timeout=300)
assert np.all(np.isfinite(X)), "corrupt X must be re-solved, not delivered"
assert d.get("serve.corrupt_result") >= 1, "corruption must be detected"
check("ex17 corrupt-recovery solve", np.abs(A @ X - B).max(), 1e-8)
print(f"corrupt result: detected x{d.get('serve.corrupt_result'):g}, "
      f"re-solved per-item, clean X delivered")

# -- phase 4: breaker opens under a hard-failing bucket -------------------
faults.reset()
faults.arm("execute", every=1)  # every dispatch fails: batched AND direct
faults.on()
for i in range(2 * svc.degrade_after):
    try:
        svc.submit("gesv", mk(60 + i), rhs(), retries=0).result(timeout=300)
    except SlateError:
        pass  # expected: both paths are poisoned
h = svc.health()
assert h["open_buckets"], "consecutive batched failures must open the breaker"
print(f"breaker opened: open_buckets={h['open_buckets']}")

# -- phase 5: recovery to a clean steady state ----------------------------
faults.reset()  # chaos over
print(f"health mid-recovery: worker_alive={h['worker_alive']} "
      f"restarts={h['worker_restarts']} open_buckets={h['open_buckets']}")
time.sleep(0.06)  # past the breaker cooldown
with metrics.deltas() as d:
    errs = []
    for i in range(8):
        A, B = mk(100 + i), rhs()
        X = svc.submit("gesv", A, B).result(timeout=300)
        errs.append(np.abs(A @ X - B).max() / np.abs(B).max())
h2 = svc.health()
assert h2["open_buckets"] == [], "half-open probes must restore batching"
assert d.get("serve.breaker_closed") >= 1, "the probe must close the breaker"
check("ex17 post-chaos stream", max(errs), 1e-8)
print(f"recovered: open_buckets={h2['open_buckets']}, "
      f"breaker closes: {d.get('serve.breaker_closed'):g}, "
      f"clean requests served: 8")
svc.stop()
