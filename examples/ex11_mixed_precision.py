"""ex11: mixed-precision solvers (reference: examples using
gesv_mixed / posv_mixed; f32 factorization + f64 refinement)."""
from _common import check, np
import slate_tpu as st

rng = np.random.default_rng(8)
n, nb = 64, 16
A0 = rng.standard_normal((n, n)) + n * np.eye(n)
B0 = rng.standard_normal((n, 2))
X, info, iters = st.gesv_mixed(st.Matrix.from_global(A0, nb), st.Matrix.from_global(B0, nb))
assert int(info) == 0 and iters >= 0
check("ex11 gesv_mixed", np.abs(A0 @ np.asarray(X.to_global()) - B0).max() / np.abs(B0).max(), 1e-11)
S0 = A0 @ A0.T + n * np.eye(n)
X2, info, iters = st.posv_mixed_gmres(
    st.HermitianMatrix.from_global(S0, nb, uplo=st.Uplo.Lower),
    st.Matrix.from_global(B0, nb))
check("ex11 posv_mixed_gmres", np.abs(S0 @ np.asarray(X2.to_global()) - B0).max() / np.abs(B0).max(), 1e-10)
