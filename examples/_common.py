"""Shared example setup: path + jax config + residual helper."""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax

jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402


def check(name: str, err: float, tol: float = 1e-10) -> None:
    status = "ok" if err < tol else "FAILED"
    print(f"{name}: residual {err:.2e} {status}")
    assert err < tol, name
