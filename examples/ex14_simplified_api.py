"""ex14: verb-named simplified API (reference: simplified_api.hh)."""
from _common import check, np
import slate_tpu as st
from slate_tpu import simplified as sl

rng = np.random.default_rng(11)
n, nb = 64, 16
A0 = rng.standard_normal((n, n)) + n * np.eye(n)
B0 = rng.standard_normal((n, 3))
X = sl.lu_solve(st.Matrix.from_global(A0, nb), st.Matrix.from_global(B0, nb))
check("ex14 lu_solve", np.abs(A0 @ np.asarray(X.to_global()) - B0).max() / np.abs(B0).max())
