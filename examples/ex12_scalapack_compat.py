"""ex12: ScaLAPACK-layout compatibility (reference: scalapack_api/)."""
from _common import check, np
from slate_tpu.compat import scalapack as sca

rng = np.random.default_rng(9)
n = 64
grid = sca.BlacsGrid(2, 2)
desc = sca.descinit(n, n, 16, 16, grid)
db = sca.descinit(n, 4, 16, 16, grid)
A0 = rng.standard_normal((n, n)) + n * np.eye(n)
B0 = rng.standard_normal((n, 4))
la, lb = sca.to_scalapack(desc, A0), sca.to_scalapack(db, B0)
info = sca.pdgesv(n, 4, la, desc, lb, db)
assert info == 0
check("ex12 pdgesv", np.abs(sca.from_scalapack(db, lb) - np.linalg.solve(A0, B0)).max())
