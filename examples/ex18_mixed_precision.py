"""ex18: the refine/ mixed-precision subsystem end to end (README
"Mixed-precision solvers").

  1. speedup knobs: precision pair (policy), RefineMethod, Tolerance,
     MaxIterations — the f32-factor IR solve matching the direct f64
     driver within the LAPACK-style bound
  2. deterministic conditioning via matgen.cond_matrix: convergence in
     a handful of iterations at cond=1e4
  3. the fallback firing on an ill-conditioned system (cond >> 1/eps_f32):
     iters < 0, refine.fallbacks bumped, full-precision-quality result
  4. GMRES-IR converging where classical IR stalls
  5. serving in mixed precision: a warmed mixed bucket solving
     compile-free, with non-convergence demoted to the direct path
"""

from _common import check, np

import slate_tpu as st
from slate_tpu.aux import metrics
from slate_tpu.enums import Option
from slate_tpu.matgen import cond_matrix
from slate_tpu.refine import policy

metrics.on()
n = 64
B0 = np.arange(n * 2, dtype=np.float64).reshape(n, 2) / n

# -- 1. the pair the backend picked + a plain mixed solve -------------------
pol = policy.select(np.float64, n)
print(f"policy: working={pol.working} factor={pol.factor} "
      f"method={pol.method} tol={pol.tolerance:.1e}")

A0 = cond_matrix(n, 1e4)  # exactly cond_2 = 1e4, bit-reproducible
X, info, iters = st.gesv_mixed(
    st.Matrix.from_global(A0, 16), st.Matrix.from_global(B0, 16)
)
assert int(info) == 0 and 0 <= iters <= 8, (int(info), iters)
print(f"gesv_mixed @ cond=1e4: {iters} refinement steps")
check("ex18 gesv_mixed", np.abs(A0 @ np.asarray(X.to_global()) - B0).max())

# knobs: a looser tolerance buys fewer iterations
X, info, it_loose = st.gesv_mixed(
    st.Matrix.from_global(A0, 16), st.Matrix.from_global(B0, 16),
    {Option.Tolerance: 1e-8, Option.MaxIterations: 4},
)
assert it_loose <= iters
print(f"gesv_mixed @ tol=1e-8: {it_loose} steps (was {iters})")

# -- 2. SPD variant ---------------------------------------------------------
S0 = cond_matrix(n, 1e4, spd=True)
X, info, iters = st.posv_mixed(
    st.HermitianMatrix.from_global(S0, 16, uplo=st.Uplo.Lower),
    st.Matrix.from_global(B0, 16),
)
assert int(info) == 0 and iters <= 8
check("ex18 posv_mixed", np.abs(S0 @ np.asarray(X.to_global()) - B0).max())

# -- 3. the fallback firing on an ill-conditioned system --------------------
A_ill = cond_matrix(n, 1e9)  # cond * eps_f32 ~ 1e2: classical IR diverges
before = metrics.counters().get("refine.fallbacks", 0)
X, info, iters = st.gesv_mixed(
    st.Matrix.from_global(A_ill, 16), st.Matrix.from_global(B0, 16)
)
assert iters < 0 and int(info) == 0  # demoted to the full-precision solve
assert metrics.counters()["refine.fallbacks"] == before + 1
print(f"gesv_mixed @ cond=1e9: fallback fired (iters={iters})")
check("ex18 fallback result",
      np.abs(A_ill @ np.asarray(X.to_global()) - B0).max() / 1e9, 1e-10)

# -- 4. GMRES-IR converges where classical IR stalls ------------------------
Xg, info_g, iters_g = st.gesv_mixed_gmres(
    st.Matrix.from_global(A_ill, 16), st.Matrix.from_global(B0, 16)
)
assert int(info_g) == 0 and iters_g > 0  # no fallback needed
print(f"gesv_mixed_gmres @ cond=1e9: converged in {iters_g} inner iterations")

# -- 5. serving in mixed precision ------------------------------------------
from slate_tpu.serve.cache import ExecutableCache
from slate_tpu.serve.service import SolverService

svc = SolverService(
    cache=ExecutableCache(manifest_path=None), batch_max=4,
    dim_floor=16, nrhs_floor=4, precision="mixed", degrade_after=2,
    start=False,
)
Awell = cond_matrix(14, 1e3, seed=2)
Bs = B0[:14]
futs = [svc.submit("gesv", Awell, Bs) for _ in range(4)]
svc.start()
for f in futs:
    check("ex18 serve mixed", np.abs(Awell @ f.result(timeout=300) - Bs).max())
# one lone request warms the b1 batch point (two-batch-point invariant:
# the coalesced stream above compiled only the b4 executable)
svc.submit("gesv", Awell, Bs).result(timeout=300)
# warmed steady state must not compile
with metrics.deltas() as d:
    svc.submit("gesv", Awell, Bs).result(timeout=300)
    svc.submit("gesv", Awell, Bs).result(timeout=300)
    assert d.get("jit.compilations") == 0, "warmed mixed bucket compiled"
print("serve mixed bucket: steady state compile-free")

# ill-conditioned traffic demotes to the full-precision direct path
X = svc.submit("gesv", cond_matrix(14, 1e9, seed=3), Bs).result(timeout=300)
assert np.all(np.isfinite(X))
assert metrics.counters().get("serve.refine_demoted", 0) >= 1
print("serve mixed bucket: non-convergence re-solved on the direct path")
svc.stop()

print("ex18: all mixed-precision paths exercised")
