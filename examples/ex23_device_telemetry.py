"""ex23: the device telemetry plane — cost/memory registry, HBM
gauges, roofline attribution (README "Device telemetry").

A warmed serve stream with devmon on (``SLATE_TPU_DEVMON=1`` in
production; ``devmon.on()`` here):

  1. every cold build captures the executable's ``cost_analysis()``
     (flops, bytes accessed) and ``memory_analysis()`` (argument/
     output/temp/peak bytes) into the per-bucket registry, persisted
     beside the warmup manifest
  2. ``health()`` surfaces the registry per warmed bucket, threads
     peak-bytes into each latency row ("slow because big" vs "slow
     because cold"), and snapshots per-device memory — gracefully
     ``None`` on CPU, where ``memory_stats`` does not exist
  3. roofline attribution joins registry flops/bytes with the
     measured run wall: achieved GFLOP/s, arithmetic intensity, and
     the compute- vs memory-bound verdict against the device's peaks
     (``SLATE_TPU_PEAKS`` overrides the built-in table)
"""

from _common import check, np

from slate_tpu.aux import devmon, metrics
from slate_tpu.serve import api as serve
from slate_tpu.serve import buckets as bk
from slate_tpu.serve.cache import ExecutableCache

devmon.on()
metrics.on()
rng = np.random.default_rng(23)

n, nrhs, N = 24, 3, 8
svc = serve.configure(
    cache=ExecutableCache(manifest_path=None), batch_max=4,
    batch_window_s=0.002, dim_floor=16, nrhs_floor=4,
)
key = bk.bucket_for("gesv", n, n, nrhs, np.float64,
                    floor=16, nrhs_floor=4)
svc.cache.ensure_manifest(key, (1, 4))
svc.warmup()  # cold builds: the registry captures here

# -- 1: a warmed compile-free stream --------------------------------------
with metrics.deltas() as d:
    for _ in range(N):
        A = rng.standard_normal((n, n)) + n * np.eye(n)
        B = rng.standard_normal((n, nrhs))
        X = serve.gesv(A, B)
        check("warmed solve", np.abs(X - np.linalg.solve(A, B)).max(),
              1e-9)
    assert int(d.get("jit.compilations")) == 0, "steady state compiled"

# -- 2: the health() device surface ---------------------------------------
h = svc.health()
rec = h["cost"][key.label][1]
print(f"registry[{key.label}.b1]: {rec['flops']:.0f} flops, "
      f"{rec['bytes_accessed']:.0f} B accessed, "
      f"peak {rec['peak_bytes']} B "
      f"(arg {rec['argument_bytes']} + temp {rec['temp_bytes']})")
assert rec["flops"] > 0 and rec["peak_bytes"] > 0
lat = h["latency"][key.label]
print(f"latency[{key.label}]: p99 {lat['p99'] * 1e3:.2f} ms at peak "
      f"{lat['peak_bytes']} B — big or cold, one row answers it")
for dev in h["devices"]:
    # CPU has no memory_stats: byte fields are None, never a crash
    print(f"device {dev['id']} ({dev['kind']}): "
          f"bytes_in_use={dev['bytes_in_use']} "
          f"peak={dev['peak_bytes_in_use']}")

# -- 3: roofline attribution ----------------------------------------------
peaks = devmon.peaks_for()
run = metrics.timers()[f"serve.{key.label}.b1.run"]
rl = devmon.roofline(rec["flops"], rec["bytes_accessed"],
                     run["total_s"] / run["count"], peaks)
print(f"roofline[{key.label}.b1]: {rl['achieved_gflops']:.2f} GFLOP/s "
      f"at AI {rl['intensity']:.2f} flop/B vs ridge "
      f"{rl['ridge']:.2f} -> {rl['bound'].upper()}-bound, "
      f"{rl['frac_of_roof'] * 100:.1f}% of roof ({peaks['source']} peaks)")
assert rl["bound"] in ("compute", "memory")

svc.stop()
print("device telemetry: registry + gauges + roofline, all live")
