"""ex05: least squares (reference: examples/ex08_linear_system_lls.cc)."""
from _common import check, np
import slate_tpu as st

rng = np.random.default_rng(3)
m, n, nb = 120, 60, 16
A0 = rng.standard_normal((m, n))
B0 = rng.standard_normal((m, 2))
X = st.gels(st.Matrix.from_global(A0, nb), st.Matrix.from_global(B0, nb))
ref, *_ = np.linalg.lstsq(A0, B0, rcond=None)
check("ex05 gels", np.abs(np.asarray(X.to_global())[:n] - ref).max() / np.abs(ref).max())
