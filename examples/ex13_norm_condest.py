"""ex13: norms + condition estimation (reference: examples norm/cond)."""
from _common import np
import slate_tpu as st
from slate_tpu.enums import Norm

rng = np.random.default_rng(10)
n = 64
A0 = rng.standard_normal((n, n)) + n * np.eye(n)
A = st.Matrix.from_global(A0, 16)
assert np.isclose(float(st.norm(Norm.Fro, A)), np.linalg.norm(A0))
LU, piv, _ = st.getrf(A)
rcond = float(st.gecondest(LU, piv, np.linalg.norm(A0, 1)))
ref = 1.0 / (np.linalg.norm(A0, 1) * np.linalg.norm(np.linalg.inv(A0), 1))
assert ref * 0.99 <= rcond <= 3 * ref
print("ex13 norm+condest ok")
