"""ex09: distributed solve on a device mesh (reference: all examples run
under mpirun; here an 8-virtual-device 2x4 block-cyclic mesh).

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 python ex09_distributed.py
"""
import os
import pathlib, sys
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
import jax
jax.config.update("jax_platforms", "cpu")

from _common import check, np
import slate_tpu as st

grid = st.ProcessGrid.from_devices(jax.devices()[:4], p=2, q=2)
rng = np.random.default_rng(7)
n, nb = 96, 16
A0 = rng.standard_normal((n, n)); A0 = A0 @ A0.T + n * np.eye(n)
B0 = rng.standard_normal((n, 8))
A = st.HermitianMatrix.from_global(A0, nb, grid=grid, uplo=st.Uplo.Lower)
B = st.Matrix.from_global(B0, nb, grid=grid)
X, L, info = st.posv(A, B)  # SPMD potrf + SPMD trsm solves
assert int(info) == 0
check("ex09 distributed posv", np.abs(A0 @ np.asarray(X.to_global()) - B0).max() / np.abs(B0).max())
