"""ex07: Hermitian eigenvalues (reference: examples/ex12_hermitian_eig.cc)."""
from _common import check, np
import slate_tpu as st

rng = np.random.default_rng(5)
n, nb = 80, 8  # n > 4 nb: two-stage bulge-chase path
A0 = rng.standard_normal((n, n)); A0 = (A0 + A0.T) / 2
A = st.HermitianMatrix.from_global(A0, nb, uplo=st.Uplo.Lower)
w, Z = st.heev(A)
w, Zg = np.asarray(w), np.asarray(Z.to_global())
check("ex07 heev values", np.abs(w - np.linalg.eigvalsh(A0)).max() / np.abs(w).max())
check("ex07 heev residual", np.abs(A0 @ Zg - Zg * w[None, :]).max() / np.abs(A0).max())
