"""ex02: matrix multiply (reference: examples/ex05_blas.cc gemm)."""
from _common import check, np
import slate_tpu as st

rng = np.random.default_rng(0)
m, n, k, nb = 96, 64, 80, 16
A = st.Matrix.from_global(rng.standard_normal((m, k)), nb)
B = st.Matrix.from_global(rng.standard_normal((k, n)), nb)
C = st.Matrix.from_global(rng.standard_normal((m, n)), nb)
C2 = st.gemm(2.0, A, B, -1.0, C)
ref = 2.0 * np.asarray(A.to_global()) @ np.asarray(B.to_global()) - np.asarray(C.to_global())
check("ex02 gemm", np.abs(np.asarray(C2.to_global()) - ref).max() / np.abs(ref).max())
