"""ex06: QR factorization (reference: examples/ex09_*_qr)."""
from _common import check, np
import slate_tpu as st

rng = np.random.default_rng(4)
m, n, nb = 96, 64, 16
A0 = rng.standard_normal((m, n))
fac, T = st.geqrf(st.Matrix.from_global(A0, nb))
Q = np.asarray(st.ungqr(fac, T).to_global())
R = np.triu(np.asarray(fac.to_global()))[:n]
check("ex06 geqrf |A-QR|", np.abs(A0 - Q @ R).max() / np.abs(A0).max())
check("ex06 geqrf |QtQ-I|", np.abs(Q.T @ Q - np.eye(n)).max())
