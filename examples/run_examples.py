#!/usr/bin/env python
"""Run every example as a smoke test (reference: examples/run_tests.py)."""
import pathlib
import subprocess
import sys

here = pathlib.Path(__file__).parent
failures = []
for ex in sorted(here.glob("ex*.py")):
    r = subprocess.run([sys.executable, ex.name], cwd=here,
                       capture_output=True, text=True, timeout=600)
    status = "ok" if r.returncode == 0 else "FAILED"
    print(f"{ex.name}: {status}")
    if r.returncode != 0:
        print(r.stdout[-2000:], r.stderr[-2000:])
        failures.append(ex.name)
sys.exit(1 if failures else 0)
