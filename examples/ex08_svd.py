"""ex08: singular values (reference: examples/ex13_svd.cc)."""
from _common import check, np
import slate_tpu as st

rng = np.random.default_rng(6)
m, n, nb = 100, 60, 4
A0 = rng.standard_normal((m, n))
s, U, Vh = st.svd(st.Matrix.from_global(A0, nb), vectors=True)
s = np.asarray(s)
check("ex08 svd values", np.abs(s - np.linalg.svd(A0, compute_uv=False)).max() / s.max())
rec = (np.asarray(U.to_global()) * s[None, :]) @ np.asarray(Vh.to_global())
check("ex08 svd recon", np.abs(rec - A0).max() / np.abs(A0).max())
