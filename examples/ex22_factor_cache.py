"""ex22: the factor cache — factor once, solve many.

A repeated-A stream (one design matrix, a stream of right-hand sides)
through the serve tier with the factorization cache on (README "Factor
cache"):

  1. cold factor: the first submit pays the O(n^3) factorization once,
     the factor is cached and its trsm-only solve bucket registered
  2. warmup, then N same-A solves: every one is a cache hit dispatched
     on the warmed O(n^2) solve executable — zero steady-state
     compiles, exact parity with a direct re-solve
  3. one rank-1 update: A2 = A + u u^T re-keys the cached Cholesky
     factor in O(n^2) (no refactor), and A2 traffic hits immediately
  4. one invalidation: the next request pays a counted refactor —
     never a wrong X
"""

from _common import check, np

from slate_tpu.aux import metrics
from slate_tpu.serve import api as serve
from slate_tpu.serve.cache import ExecutableCache
from slate_tpu.serve.factor_cache import FactorCache

metrics.on()
rng = np.random.default_rng(22)

n, nrhs, N = 24, 3, 12
G = rng.standard_normal((n, n))
A = G @ G.T + n * np.eye(n)  # SPD: the posv family supports updates

svc = serve.configure(
    cache=ExecutableCache(manifest_path=None), batch_max=4,
    batch_window_s=0.002, dim_floor=32, nrhs_floor=4,
    factor_cache=FactorCache(max_entries=8),
)

# -- 1: cold factor (the one O(n^3) event of the whole stream) ------------
B0 = rng.standard_normal((n, nrhs))
X0 = serve.posv(A, B0)
check("cold factor solve", np.abs(X0 - np.linalg.solve(A, B0)).max(), 1e-9)
serve.warmup()  # the miss registered the solve bucket; precompile it

# -- 2: N warm trsm-only solves, compile-free, exact parity ---------------
Bs = [rng.standard_normal((n, nrhs)) for _ in range(N)]
with metrics.deltas() as d:
    Xs = [serve.posv(A, B) for B in Bs]
    hits = int(d.get("serve.factor_cache.hit"))
    compiles = int(d.get("jit.compilations"))
for B, X in zip(Bs, Xs):
    check("warm trsm-only solve", np.abs(X - np.linalg.solve(A, B)).max(),
          1e-9)
print(f"{N} same-A solves: {hits} cache hits, {compiles} compiles")
assert hits >= 1 and hits == N, hits
assert compiles == 0, "warmed repeated-A steady state must not compile"

# -- 3: rank-1 update: O(n^2) re-key instead of an O(n^3) refactor --------
fp = serve.factor_fingerprint("posv", A)
u = rng.standard_normal(n)
A2 = A + np.outer(u, u)
fp2 = serve.update_factor(fp, A2, u)
assert fp2 == serve.factor_fingerprint("posv", A2)
with metrics.deltas() as d:
    X2 = serve.posv(A2, B0)
    assert int(d.get("serve.factor_cache.hit")) == 1  # no refactor paid
check("post-update solve", np.abs(X2 - np.linalg.solve(A2, B0)).max(), 1e-8)
print("rank-1 update re-keyed the factor; A2 traffic hits immediately")

# -- 4: invalidation: the next request refactors (counted), correctly -----
assert serve.invalidate(fp2)
with metrics.deltas() as d:
    X3 = serve.posv(A2, B0)
    assert int(d.get("serve.factor_cache.miss")) == 1
    assert int(d.get("serve.factor_cache.hit")) == 0
check("post-invalidate solve", np.abs(X3 - np.linalg.solve(A2, B0)).max(),
      1e-9)
print("invalidation fell back to a counted refactor — never a wrong X")

serve.shutdown()
print("ex22 ok")
