"""ex04: LU solve + variants (reference: examples/ex07_linear_system_lu.cc)."""
from _common import check, np
import slate_tpu as st
from slate_tpu.enums import MethodLU, Option

rng = np.random.default_rng(2)
n, nb = 100, 16
A0 = rng.standard_normal((n, n)) + n * np.eye(n)
B0 = rng.standard_normal((n, 4))
for method in (MethodLU.PartialPiv, MethodLU.CALU, MethodLU.NoPiv, MethodLU.RBT):
    X, LU, piv, info = st.gesv(
        st.Matrix.from_global(A0, nb), st.Matrix.from_global(B0, nb),
        {Option.MethodLU: method},
    )
    assert int(info) == 0
    check(f"ex04 gesv[{method.name}]",
          np.abs(A0 @ np.asarray(X.to_global()) - B0).max() / np.abs(B0).max(), 1e-8)
