"""ex10: deterministic matrix generation (reference: matgen/ Philox
counter RNG — same matrix for any tiling or process count)."""
from _common import np
import slate_tpu as st
from slate_tpu.matgen.generate import generate_2d

A1 = np.asarray(generate_2d("rand", 64, 64, np.float64, seed=42)[0])
A2 = np.asarray(generate_2d("rand", 64, 64, np.float64, seed=42)[0])
assert np.array_equal(A1, A2)
H = np.asarray(generate_2d("hilb", 8, 8, np.float64)[0])
assert np.isclose(H[2, 3], 1.0 / 6.0)
print("ex10 matgen ok")
