/* Minimal standalone C client of the slate_tpu ABI (reference: the
 * reference's examples/c_api usage): solve a 64x64 system and print the
 * residual.  Build: make example_gesv; run with PYTHONPATH=<repo root>.
 */

#include "slate_tpu.h"

#include <math.h>
#include <stdio.h>
#include <stdlib.h>

int main(void) {
    const int64_t n = 64, nrhs = 2;
    double *a = malloc(sizeof(double) * n * n);
    double *a0 = malloc(sizeof(double) * n * n);
    double *b = malloc(sizeof(double) * n * nrhs);
    double *b0 = malloc(sizeof(double) * n * nrhs);
    int64_t *ipiv = malloc(sizeof(int64_t) * n);
    srand(7);
    for (int64_t j = 0; j < n; ++j)
        for (int64_t i = 0; i < n; ++i) {
            double v = (double)rand() / RAND_MAX - 0.5;
            if (i == j) v += n;
            a[j * n + i] = a0[j * n + i] = v;
        }
    for (int64_t i = 0; i < n * nrhs; ++i)
        b[i] = b0[i] = (double)rand() / RAND_MAX - 0.5;

    if (slate_tpu_init() != 0) return 1;
    int info = slate_tpu_dgesv(n, nrhs, a, n, ipiv, b, n);
    if (info != 0) {
        fprintf(stderr, "dgesv info=%d\n", info);
        return 2;
    }
    double rmax = 0.0;
    for (int64_t r = 0; r < nrhs; ++r)
        for (int64_t i = 0; i < n; ++i) {
            double s = 0.0;
            for (int64_t j = 0; j < n; ++j)
                s += a0[j * n + i] * b[r * n + j];
            double d = fabs(s - b0[r * n + i]);
            if (d > rmax) rmax = d;
        }
    printf("max residual |AX-B| = %.3e\n", rmax);
    slate_tpu_finalize();
    return rmax < 1e-8 ? 0 : 3;
}
