/* slate_tpu C API (reference: src/c_api/wrappers.cc + include/slate/c_api/
 * — the extern "C" LAPACK-style surface over the driver layer).
 *
 * All matrices are COLUMN-MAJOR (LAPACK convention) with an explicit
 * leading dimension.  Every routine returns the LAPACK info code
 * (0 = success, >0 = numerical failure, <0 = API error).  The library
 * embeds the Python runtime that hosts the JAX/XLA drivers; call
 * slate_tpu_init() once before any routine (idempotent, safe when the
 * caller is itself a Python process) and slate_tpu_finalize() at exit.
 */

#ifndef SLATE_TPU_H
#define SLATE_TPU_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

int  slate_tpu_init(void);
void slate_tpu_finalize(void);

/* ---- solves ---------------------------------------------------------- */

/* A X = B, general A: LU with partial pivoting.  On exit a holds L\U,
 * ipiv the 1-based sequential swap list, b the solution. */
int slate_tpu_dgesv(int64_t n, int64_t nrhs, double *a, int64_t lda,
                    int64_t *ipiv, double *b, int64_t ldb);

/* A X = B, SPD A ('l'/'u' = stored triangle).  a <- factor, b <- X. */
int slate_tpu_dposv(char uplo, int64_t n, int64_t nrhs, double *a,
                    int64_t lda, double *b, int64_t ldb);

/* min-norm least squares: b (max(m,n) x nrhs buffer) <- X. */
int slate_tpu_dgels(int64_t m, int64_t n, int64_t nrhs, double *a,
                    int64_t lda, double *b, int64_t ldb);

/* ---- factorizations -------------------------------------------------- */

int slate_tpu_dgetrf(int64_t m, int64_t n, double *a, int64_t lda,
                     int64_t *ipiv);
int slate_tpu_dpotrf(char uplo, int64_t n, double *a, int64_t lda);
int slate_tpu_dgeqrf(int64_t m, int64_t n, double *a, int64_t lda,
                     double *tau);

/* ---- eigen / singular values ---------------------------------------- */

/* jobz 'n'|'v'; on exit w holds eigenvalues ascending and (jobz='v')
 * a holds the eigenvectors. */
int slate_tpu_dsyev(char jobz, char uplo, int64_t n, double *a,
                    int64_t lda, double *w);

/* jobu/jobvt 'n'|'s': s (min(m,n)), u (m x min(m,n)), vt (min(m,n) x n);
 * u/vt may be NULL when not requested. */
int slate_tpu_dgesvd(char jobu, char jobvt, int64_t m, int64_t n,
                     double *a, int64_t lda, double *s, double *u,
                     int64_t ldu, double *vt, int64_t ldvt);

/* ---- BLAS3 ----------------------------------------------------------- */

/* C = alpha op(A) op(B) + beta C; transa/transb 'n'|'t'. */
int slate_tpu_dgemm(char transa, char transb, int64_t m, int64_t n,
                    int64_t k, double alpha, const double *a, int64_t lda,
                    const double *b, int64_t ldb, double beta, double *c,
                    int64_t ldc);

#ifdef __cplusplus
}
#endif

#endif /* SLATE_TPU_H */
