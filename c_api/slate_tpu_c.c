/* slate_tpu C API implementation (reference: src/c_api/wrappers.cc).
 *
 * Embeds the CPython runtime hosting the JAX/XLA drivers and forwards
 * each call to slate_tpu.compat.c_bridge with zero-copy writable
 * memoryviews over the caller's column-major buffers.  Works both as a
 * standalone embedding (any C/C++/Fortran program) and when loaded into
 * an existing Python process (init detects the live interpreter).
 */

#include "slate_tpu.h"

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <stdio.h>

static PyObject *g_bridge = NULL;  /* slate_tpu.compat.c_bridge */
static int g_we_initialized = 0;
static PyThreadState *g_saved_ts = NULL;

int slate_tpu_init(void) {
    if (g_bridge != NULL) return 0;
    if (!Py_IsInitialized()) {
        Py_InitializeEx(0);
        g_we_initialized = 1;
    }
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *mod = PyImport_ImportModule("slate_tpu.compat.c_bridge");
    int rc = 0;
    if (mod == NULL) {
        PyErr_Print();
        rc = -1;
    } else {
        g_bridge = mod;  /* keep the reference */
    }
    PyGILState_Release(st);
    if (g_we_initialized && g_saved_ts == NULL) {
        /* release the GIL held by Py_InitializeEx so any thread can
         * PyGILState_Ensure later */
        g_saved_ts = PyEval_SaveThread();
    }
    return rc;
}

void slate_tpu_finalize(void) {
    if (g_bridge == NULL) return;
    if (g_we_initialized) {
        if (g_saved_ts) PyEval_RestoreThread(g_saved_ts);
        Py_XDECREF(g_bridge);
        g_bridge = NULL;
        Py_Finalize();
        g_we_initialized = 0;
        g_saved_ts = NULL;
    } else {
        PyGILState_STATE st = PyGILState_Ensure();
        Py_XDECREF(g_bridge);
        g_bridge = NULL;
        PyGILState_Release(st);
    }
}

/* writable memoryview over a caller buffer (NULL -> Py None) */
static PyObject *mv(void *p, Py_ssize_t nbytes) {
    if (p == NULL) Py_RETURN_NONE;
    return PyMemoryView_FromMemory((char *)p, nbytes, PyBUF_WRITE);
}

static int call_bridge(const char *name, PyObject *args) {
    /* consumes args; returns the bridge's int, or -100x on API errors */
    if (g_bridge == NULL && slate_tpu_init() != 0) {
        Py_XDECREF(args);
        return -1001;
    }
    int rc;
    PyObject *fn = PyObject_GetAttrString(g_bridge, name);
    if (fn == NULL || args == NULL) {
        PyErr_Print();
        Py_XDECREF(fn);
        Py_XDECREF(args);
        return -1002;
    }
    PyObject *res = PyObject_CallObject(fn, args);
    Py_DECREF(fn);
    Py_DECREF(args);
    if (res == NULL) {
        PyErr_Print();
        return -1003;
    }
    rc = (int)PyLong_AsLong(res);
    Py_DECREF(res);
    return rc;
}

int slate_tpu_dgesv(int64_t n, int64_t nrhs, double *a, int64_t lda,
                    int64_t *ipiv, double *b, int64_t ldb) {
    if (slate_tpu_init() != 0) return -1001;
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *args = Py_BuildValue(
        "(LLNLNNL)", (long long)n, (long long)nrhs,
        mv(a, sizeof(double) * lda * n), (long long)lda,
        mv(ipiv, sizeof(int64_t) * n),
        mv(b, sizeof(double) * ldb * nrhs), (long long)ldb);
    int rc = call_bridge("dgesv", args);
    PyGILState_Release(st);
    return rc;
}

int slate_tpu_dposv(char uplo, int64_t n, int64_t nrhs, double *a,
                    int64_t lda, double *b, int64_t ldb) {
    if (slate_tpu_init() != 0) return -1001;
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *args = Py_BuildValue(
        "(bLLNLNL)", (char)uplo, (long long)n, (long long)nrhs,
        mv(a, sizeof(double) * lda * n), (long long)lda,
        mv(b, sizeof(double) * ldb * nrhs), (long long)ldb);
    int rc = call_bridge("dposv", args);
    PyGILState_Release(st);
    return rc;
}

int slate_tpu_dgels(int64_t m, int64_t n, int64_t nrhs, double *a,
                    int64_t lda, double *b, int64_t ldb) {
    if (slate_tpu_init() != 0) return -1001;
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *args = Py_BuildValue(
        "(LLLNLNL)", (long long)m, (long long)n, (long long)nrhs,
        mv(a, sizeof(double) * lda * n), (long long)lda,
        mv(b, sizeof(double) * ldb * nrhs), (long long)ldb);
    int rc = call_bridge("dgels", args);
    PyGILState_Release(st);
    return rc;
}

int slate_tpu_dgetrf(int64_t m, int64_t n, double *a, int64_t lda,
                     int64_t *ipiv) {
    if (slate_tpu_init() != 0) return -1001;
    PyGILState_STATE st = PyGILState_Ensure();
    int64_t k = m < n ? m : n;
    PyObject *args = Py_BuildValue(
        "(LLNLN)", (long long)m, (long long)n,
        mv(a, sizeof(double) * lda * n), (long long)lda,
        mv(ipiv, sizeof(int64_t) * k));
    int rc = call_bridge("dgetrf", args);
    PyGILState_Release(st);
    return rc;
}

int slate_tpu_dpotrf(char uplo, int64_t n, double *a, int64_t lda) {
    if (slate_tpu_init() != 0) return -1001;
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *args = Py_BuildValue(
        "(bLNL)", (char)uplo, (long long)n,
        mv(a, sizeof(double) * lda * n), (long long)lda);
    int rc = call_bridge("dpotrf", args);
    PyGILState_Release(st);
    return rc;
}

int slate_tpu_dgeqrf(int64_t m, int64_t n, double *a, int64_t lda,
                     double *tau) {
    if (slate_tpu_init() != 0) return -1001;
    PyGILState_STATE st = PyGILState_Ensure();
    int64_t k = m < n ? m : n;
    PyObject *args = Py_BuildValue(
        "(LLNLN)", (long long)m, (long long)n,
        mv(a, sizeof(double) * lda * n), (long long)lda,
        mv(tau, sizeof(double) * k));
    int rc = call_bridge("dgeqrf", args);
    PyGILState_Release(st);
    return rc;
}

int slate_tpu_dsyev(char jobz, char uplo, int64_t n, double *a,
                    int64_t lda, double *w) {
    if (slate_tpu_init() != 0) return -1001;
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *args = Py_BuildValue(
        "(bbLNLN)", (char)jobz, (char)uplo, (long long)n,
        mv(a, sizeof(double) * lda * n), (long long)lda,
        mv(w, sizeof(double) * n));
    int rc = call_bridge("dsyev", args);
    PyGILState_Release(st);
    return rc;
}

int slate_tpu_dgesvd(char jobu, char jobvt, int64_t m, int64_t n,
                     double *a, int64_t lda, double *s, double *u,
                     int64_t ldu, double *vt, int64_t ldvt) {
    if (slate_tpu_init() != 0) return -1001;
    PyGILState_STATE st = PyGILState_Ensure();
    int64_t k = m < n ? m : n;
    PyObject *args = Py_BuildValue(
        "(bbLLNLNNLNL)", (char)jobu, (char)jobvt, (long long)m,
        (long long)n, mv(a, sizeof(double) * lda * n), (long long)lda,
        mv(s, sizeof(double) * k),
        mv(u, u ? sizeof(double) * ldu * k : 0), (long long)ldu,
        mv(vt, vt ? sizeof(double) * ldvt * n : 0), (long long)ldvt);
    int rc = call_bridge("dgesvd", args);
    PyGILState_Release(st);
    return rc;
}

int slate_tpu_dgemm(char transa, char transb, int64_t m, int64_t n,
                    int64_t k, double alpha, const double *a, int64_t lda,
                    const double *b, int64_t ldb, double beta, double *c,
                    int64_t ldc) {
    if (slate_tpu_init() != 0) return -1001;
    PyGILState_STATE st = PyGILState_Ensure();
    int64_t acols = (transa == 'n' || transa == 'N') ? k : m;
    int64_t bcols = (transb == 'n' || transb == 'N') ? n : k;
    PyObject *args = Py_BuildValue(
        "(bbLLLdNLNLdNL)", (char)transa, (char)transb, (long long)m,
        (long long)n, (long long)k, alpha,
        mv((void *)a, sizeof(double) * lda * acols), (long long)lda,
        mv((void *)b, sizeof(double) * ldb * bcols), (long long)ldb, beta,
        mv(c, sizeof(double) * ldc * n), (long long)ldc);
    int rc = call_bridge("dgemm", args);
    PyGILState_Release(st);
    return rc;
}
