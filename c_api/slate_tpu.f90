! Fortran interfaces for the slate_tpu C ABI (reference: tools/fortran/
! — the reference generates these; here a hand-written ISO_C_BINDING
! module covering the same routine surface as slate_tpu.h).
!
! Usage:  use slate_tpu;  info = slate_tpu_dgesv(n, nrhs, a, n, ipiv, b, n)
! Link against libslate_tpu.so (see c_api/Makefile).

module slate_tpu
  use iso_c_binding
  implicit none

  interface
    integer(c_int) function slate_tpu_init() bind(C, name="slate_tpu_init")
      import
    end function

    subroutine slate_tpu_finalize() bind(C, name="slate_tpu_finalize")
    end subroutine

    integer(c_int) function slate_tpu_dgesv(n, nrhs, a, lda, ipiv, b, ldb) &
        bind(C, name="slate_tpu_dgesv")
      import
      integer(c_int64_t), value :: n, nrhs, lda, ldb
      real(c_double) :: a(*), b(*)
      integer(c_int64_t) :: ipiv(*)
    end function

    integer(c_int) function slate_tpu_dposv(uplo, n, nrhs, a, lda, b, ldb) &
        bind(C, name="slate_tpu_dposv")
      import
      character(kind=c_char), value :: uplo
      integer(c_int64_t), value :: n, nrhs, lda, ldb
      real(c_double) :: a(*), b(*)
    end function

    integer(c_int) function slate_tpu_dgels(m, n, nrhs, a, lda, b, ldb) &
        bind(C, name="slate_tpu_dgels")
      import
      integer(c_int64_t), value :: m, n, nrhs, lda, ldb
      real(c_double) :: a(*), b(*)
    end function

    integer(c_int) function slate_tpu_dgetrf(m, n, a, lda, ipiv) &
        bind(C, name="slate_tpu_dgetrf")
      import
      integer(c_int64_t), value :: m, n, lda
      real(c_double) :: a(*)
      integer(c_int64_t) :: ipiv(*)
    end function

    integer(c_int) function slate_tpu_dpotrf(uplo, n, a, lda) &
        bind(C, name="slate_tpu_dpotrf")
      import
      character(kind=c_char), value :: uplo
      integer(c_int64_t), value :: n, lda
      real(c_double) :: a(*)
    end function

    integer(c_int) function slate_tpu_dgeqrf(m, n, a, lda, tau) &
        bind(C, name="slate_tpu_dgeqrf")
      import
      integer(c_int64_t), value :: m, n, lda
      real(c_double) :: a(*), tau(*)
    end function

    integer(c_int) function slate_tpu_dsyev(jobz, uplo, n, a, lda, w) &
        bind(C, name="slate_tpu_dsyev")
      import
      character(kind=c_char), value :: jobz, uplo
      integer(c_int64_t), value :: n, lda
      real(c_double) :: a(*), w(*)
    end function

    integer(c_int) function slate_tpu_dgemm(transa, transb, m, n, k, alpha, &
        a, lda, b, ldb, beta, c, ldc) bind(C, name="slate_tpu_dgemm")
      import
      character(kind=c_char), value :: transa, transb
      integer(c_int64_t), value :: m, n, k, lda, ldb, ldc
      real(c_double), value :: alpha, beta
      real(c_double) :: a(*), b(*), c(*)
    end function
  end interface

end module slate_tpu
