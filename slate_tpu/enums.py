"""Enums and constants for slate_tpu.

TPU-native re-design of the reference enum set (reference:
include/slate/enums.hh).  Enums that only existed to drive the CPU/GPU
runtime (MOSI coherence states, LayoutConvert, HostNum device ids) are
intentionally dropped: on TPU there is a single device memory space per chip
and XLA owns data layout.  Everything that shapes the *algorithms* or the
user API is kept with identical spellings so testers/sweeps translate 1:1.
"""

from __future__ import annotations

import enum


class _StrParseMixin:
    """from_string/to_string helpers matching the reference's conventions
    (reference: include/slate/enums.hh from_string/to_c_string families)."""

    @classmethod
    def from_string(cls, s: str):
        key = s.strip().lower()
        for member in cls:  # type: ignore[attr-defined]
            names = {member.name.lower(), str(member.value).lower()}
            names |= set(getattr(member, "aliases", lambda: ())())
            if key in names:
                return member
        raise ValueError(f"unknown {cls.__name__}: {s!r}")

    def to_string(self) -> str:
        return self.name


class Op(_StrParseMixin, enum.Enum):
    """Transposition op applied to a matrix view (reference: blaspp blas::Op)."""

    NoTrans = "N"
    Trans = "T"
    ConjTrans = "C"

    def aliases(self):
        return {"n": ("notrans",), "t": ("trans",), "c": ("conjtrans",)}.get(
            self.value.lower(), ()
        )


class Uplo(_StrParseMixin, enum.Enum):
    Lower = "L"
    Upper = "U"
    General = "G"


class Diag(_StrParseMixin, enum.Enum):
    NonUnit = "N"
    Unit = "U"


class Side(_StrParseMixin, enum.Enum):
    Left = "L"
    Right = "R"


class Layout(_StrParseMixin, enum.Enum):
    """Kept for ScaLAPACK-compat buffer ingestion only; device tiles are
    always logical row-major jax arrays and XLA picks physical layouts."""

    ColMajor = "C"
    RowMajor = "R"


class Target(_StrParseMixin, enum.Enum):
    """Where bulk steps execute (reference: enums.hh:38-44 Target).

    On TPU all real work is XLA; `Devices` is the default and the Host*
    targets are kept for API parity and map to the same implementation
    (single jit computation), optionally forced onto the CPU backend for
    debugging.
    """

    Host = "H"
    HostTask = "T"
    HostNest = "N"
    HostBatch = "B"
    Devices = "D"

    def aliases(self):
        return {
            "H": ("h", "host"),
            "T": ("t", "task", "hosttask"),
            "N": ("n", "nest", "hostnest"),
            "B": ("b", "batch", "hostbatch"),
            "D": ("d", "dev", "device", "devices"),
        }[self.value]


class Norm(_StrParseMixin, enum.Enum):
    One = "1"
    Two = "2"
    Inf = "I"
    Fro = "F"
    Max = "M"

    def aliases(self):
        return {
            "1": ("one", "o"),
            "2": ("two",),
            "I": ("i", "inf"),
            "F": ("f", "fro"),
            "M": ("m", "max"),
        }[self.value]


class NormScope(_StrParseMixin, enum.Enum):
    """Matrix norm vs per-column / per-row norms (reference: enums.hh:514)."""

    Columns = "C"
    Rows = "R"
    Matrix = "M"


class GridOrder(_StrParseMixin, enum.Enum):
    """Order mapping processes onto the p x q tile grid (reference: enums.hh:524)."""

    Col = "C"
    Row = "R"
    Unknown = "U"


class TileKind(enum.Enum):
    """Provenance of a tile allocation (reference: Tile.hh:97-101).  In the
    functional TPU design only the user/owned distinction survives, used by
    the compat layer to decide write-back."""

    Workspace = 0
    SlateOwned = 1
    UserOwned = 2


# ---------------------------------------------------------------------------
# Method enums — algorithm variant selectors (reference: enums.hh:100-455).
# ---------------------------------------------------------------------------


class MethodGemm(_StrParseMixin, enum.Enum):
    Auto = "*"
    A = "A"  # stationary-A (gemmA: reduce C contributions)
    C = "C"  # stationary-C (SUMMA)

    def aliases(self):
        return {"*": ("auto",), "A": ("gemma",), "C": ("gemmc",)}[self.value]


class MethodHemm(_StrParseMixin, enum.Enum):
    Auto = "*"
    A = "A"
    C = "C"

    def aliases(self):
        return {"*": ("auto",), "A": ("hemma",), "C": ("hemmc",)}[self.value]


class MethodTrsm(_StrParseMixin, enum.Enum):
    Auto = "*"
    A = "A"  # stationary-A
    B = "B"  # stationary-B

    def aliases(self):
        return {"*": ("auto",), "A": ("trsma",), "B": ("trsmb",)}[self.value]


class MethodCholQR(_StrParseMixin, enum.Enum):
    Auto = "*"
    GemmA = "A"
    GemmC = "C"
    HerkA = "R"
    HerkC = "K"


class MethodGels(_StrParseMixin, enum.Enum):
    Auto = "*"
    QR = "Q"
    CholQR = "C"

    def aliases(self):
        return {"*": ("auto",), "Q": ("qr", "geqrf"), "C": ("cholqr",)}[self.value]


class MethodLU(_StrParseMixin, enum.Enum):
    """LU variants (reference: enums.hh:302-309).  On TPU the static-schedule
    friendly variants (NoPiv, RBT, CALU/tournament) are first-class."""

    Auto = "*"
    PartialPiv = "P"
    CALU = "C"
    NoPiv = "N"
    RBT = "R"
    BEAM = "B"

    def aliases(self):
        return {
            "*": ("auto",),
            "P": ("pplu", "partialpiv"),
            "C": ("calu",),
            "N": ("nopiv",),
            "R": ("rbt",),
            "B": ("beam",),
        }[self.value]


class MethodEig(_StrParseMixin, enum.Enum):
    Auto = "*"
    QR = "Q"
    DC = "D"
    Bisection = "B"
    MRRR = "M"

    def aliases(self):
        return {"*": ("auto",), "Q": ("qr",), "D": ("dc",), "B": (), "M": ()}[self.value]


class MethodSVD(_StrParseMixin, enum.Enum):
    Auto = "*"
    QR = "Q"
    DC = "D"
    Bisection = "B"

    def aliases(self):
        return {"*": ("auto",), "Q": ("qr",), "D": ("dc",), "B": ()}[self.value]


class RefineMethod(_StrParseMixin, enum.Enum):
    """Mixed-precision refinement algorithm (slate_tpu extension over
    the reference's fixed pairing of gesv_mixed = classical IR and
    gesv_mixed_gmres = GMRES-IR; here one Option selects the method so
    serve buckets and sweeps can switch without changing routine names):

    * ``IR``    — classical iterative refinement (Wilkinson; reference
      src/gesv_mixed.cc): correct with the low-precision factors,
      residual in working precision.  Converges when
      cond(A) * eps_factor is safely below 1.
    * ``GMRES`` — restarted GMRES-IR preconditioned by the low-precision
      factors (reference src/gesv_mixed_gmres.cc; Carson & Higham SISC
      2018): survives roughly a factor 1/eps_factor more
      ill-conditioning than classical IR at extra FLOPs per iteration.
    * ``Auto``  — classical IR (the cheap path; callers wanting the
      robust path use the ``*_mixed_gmres`` drivers or set GMRES).
    """

    Auto = "auto"
    IR = "ir"
    GMRES = "gmres"

    def aliases(self):
        return {"auto": ("*",), "ir": ("classical",), "gmres": ("gmres_ir",)}[
            self.value
        ]


class Schedule(_StrParseMixin, enum.Enum):
    """Factorization schedule family (slate_tpu extension; no reference
    analogue — the reference gets exact-shape trailing updates for free
    from its dynamic tile task graph, a TPU static schedule has to pick):

    * ``Flat``      — the pre-recursion native family: the coarse
      blocked kernels where the shape admits them (``blocked_potrf``,
      ``lu_fast``, ``geqrf_fast``), the single-compiled-shape loops
      (``chol_fori`` / ``blocked_getrf`` lineage) otherwise — masked
      full-shape inner steps, ~2-6x the model FLOPs.
    * ``Recursive`` — divide & conquer on the halving lattice
      (``chol_recursive`` / ``getrf_recursive`` / ``geqrf_recursive``):
      exact statically-shrinking shapes, O(log n) distinct compile
      units, near-model FLOPs.
    * ``Pallas``    — the recursive lattice with the panel/base-case
      layer swapped for fused Pallas kernels
      (``ops/pallas/panel_kernels.py``): in-register panel LU pivot
      search, fused unblocked Cholesky, compact-WY T assembly,
      triangle-aware syrk diagonal blocks.  Compiled Mosaic on TPU for
      eligible operands; the identical kernel bodies run in interpret
      mode (plain XLA lowering) everywhere else, so the family is
      portable and artifacts stay custom-call-free.
    * ``Auto``      — backend dispatch: vendor kernel on CPU (LAPACK is
      already optimal), pallas above the crossover on accelerators,
      flat/blocked below it.
    """

    Auto = "auto"
    Flat = "flat"
    Recursive = "recursive"
    Pallas = "pallas"

    def aliases(self):
        return {
            "auto": ("*",),
            "flat": (),
            "recursive": ("rec", "dc"),
            "pallas": ("panel",),
        }[self.value]


# ---------------------------------------------------------------------------
# Option keys (reference: enums.hh:461-498)
# ---------------------------------------------------------------------------


class Option(enum.Enum):
    # Option-keyed dicts travel through jax pytree flattening (the
    # metrics layer's Tracer scan, user opts captured in jit closures),
    # which sorts dict keys — so Option must be orderable, including
    # against the string keys options.py also accepts.
    def __lt__(self, other):
        if isinstance(other, Option):
            return self.value < other.value
        if isinstance(other, str):
            return self.value < other
        return NotImplemented

    def __gt__(self, other):
        if isinstance(other, Option):
            return self.value > other.value
        if isinstance(other, str):
            return self.value > other
        return NotImplemented

    ChunkSize = "chunk_size"
    Lookahead = "lookahead"
    BlockSize = "block_size"
    InnerBlocking = "inner_blocking"
    MaxPanelThreads = "max_panel_threads"
    Tolerance = "tolerance"
    Target = "target"
    HoldLocalWorkspace = "hold_local_workspace"
    Depth = "depth"
    MaxIterations = "max_iterations"
    UseFallbackSolver = "use_fallback_solver"
    PivotThreshold = "pivot_threshold"
    # printing
    PrintVerbose = "print_verbose"
    PrintEdgeItems = "print_edgeitems"
    PrintWidth = "print_width"
    PrintPrecision = "print_precision"
    # methods
    MethodCholQR = "method_cholqr"
    MethodEig = "method_eig"
    MethodGels = "method_gels"
    MethodGemm = "method_gemm"
    MethodHemm = "method_hemm"
    MethodLU = "method_lu"
    MethodTrsm = "method_trsm"
    MethodSVD = "method_svd"
    # slate_tpu extensions
    Schedule = "schedule"  # factorization schedule: flat|recursive|auto
    RefineMethod = "refine_method"  # mixed-precision refinement: ir|gmres|auto
    MaxUnrolledTiles = "max_unrolled_tiles"  # unroll k-loop below this nt
    UseShardMap = "use_shard_map"  # explicit SPMD fast path vs GSPMD
    RequireSpmd = "require_spmd"  # error instead of gathered fallback
    # serving layer (serve/)
    ServeQueueLimit = "serve_queue_limit"  # admission bound (-> Rejected)
    ServeBatchMax = "serve_batch_max"  # coalesced batch point per bucket
    ServeBatchWindow = "serve_batch_window"  # coalescing linger, seconds
    ServeRetryBackoff = "serve_retry_backoff"  # backoff base, seconds
    ServeBreakerCooldown = "serve_breaker_cooldown"  # open -> half-open, s
    ServeValidate = "serve_validate"  # admission finiteness checks
    ServePrecision = "serve_precision"  # bucket solve precision: full|mixed
    ServeArtifacts = "serve_artifacts"  # executable artifact dir (cold start)
    ServeReplicas = "serve_replicas"  # data-parallel replica worker count
    ServeMesh = "serve_mesh"  # spmd submesh "PxQ" for sharded routing
    ServeShardThreshold = "serve_shard_threshold"  # n >= this routes sharded
    ServeFactorCache = "serve_factor_cache"  # enable the factorization cache
    ServeFactorCacheEntries = "serve_factor_cache_entries"  # LRU entry cap
    ServeFactorCacheBytes = "serve_factor_cache_bytes"  # LRU byte budget
    ServeFactorArena = "serve_factor_arena"  # device factor arena (fabric/)
    ServeTenantQuota = "serve_tenant_quota"  # tenant spec (admission grammar)
    ServeAdaptiveWindow = "serve_adaptive_window"  # AIMD batch-window control
    ServeLatencyBudget = "serve_latency_budget"  # p99 budget, s (0 = off)
    ServeIntegrity = "serve_integrity"  # SDC certification policy (integrity/)
    ServeDrainTimeout = "serve_drain_timeout"  # stop(drain=True) bound, s
    ServeScale = "serve_scale"  # elastic capacity policy (scale/ grammar)
    Faults = "faults"  # fault-injection spec string (aux/faults grammar)


# Marker constants kept for API parity (reference: enums.hh:531-534).
HostNum = -1
AllDevices = -2
AnyDevice = -3
