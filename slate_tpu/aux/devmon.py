"""Device telemetry plane: per-executable cost/memory capture, HBM
gauges, and the roofline peaks table (Williams, Waterman & Patterson,
CACM 2009 — see PAPERS.md).

Three sensors, one module:

1. **Executable cost/memory capture** — :func:`analyze_compiled` reads
   ``cost_analysis()`` (flops, bytes accessed, transcendentals) and
   ``memory_analysis()`` (argument/output/temp bytes, peak when the
   runtime reports one) off a ``jax`` AOT-compiled executable;
   :func:`capture_jitted` does the lower -> compile -> analyze chain
   for a ``jax.jit`` callable and records the result into the metrics
   cost registry, so a ``SLATE_TPU_METRICS`` JSONL carries
   ``{"type": "cost", "name": ..., "flops": ..., "peak_bytes": ...}``
   rows ``tools/roofline_report.py`` and ``tools/warmup_report.py``
   join.  The serving cache (serve/cache.py) calls this at every cold
   build and artifact restore, keyed ``serve.<bucket>.b<batch>``, and
   persists the record beside the warmup manifest entry.
2. **Device memory gauges** — :func:`sample_devices` polls
   ``device.memory_stats()`` per visible device into
   ``serve.device.<i>.bytes_in_use`` gauges plus a process-lifetime
   high-water mark (``.bytes_in_use_peak``), with a graceful ``None``
   on backends without the API (XLA:CPU returns nothing) — the HBM
   pressure signal admission reads before the device arena exists.
3. **Roofline attribution** — :func:`peaks_for` resolves a device
   kind to (peak FLOP/s, peak bytes/s) from the built-in table or the
   ``SLATE_TPU_PEAKS`` JSON override; :func:`roofline` joins measured
   wall time with captured flops/bytes into achieved FLOP/s,
   arithmetic intensity, the compute-vs-memory-bound verdict, and
   fraction-of-roof.

Zero overhead when off (the registry design goal, metrics.py goal 1):
every producer call site gates on :func:`is_on` — one module-level
bool.  Activation: ``SLATE_TPU_DEVMON=1`` at import, or
:func:`on` programmatically.  The capture itself costs one extra
backend compile per (bucket, batch) at COLD BUILD time only (the AOT
lowering is not shared with the dispatch cache); steady state and the
devmon-off path never pay anything.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, List, Optional

from . import metrics as _metrics

_enabled = False
_lock = threading.Lock()
#: device id -> process-lifetime high-water mark of bytes_in_use (kept
#: here so backends whose memory_stats lacks peak_bytes_in_use still
#: get a monotone peak from repeated samples)
_hwm: Dict[Any, int] = {}

PEAKS_ENV = "SLATE_TPU_PEAKS"

#: built-in peak table: lowercase device-kind substring -> (peak
#: FLOP/s, peak bytes/s).  Matched by substring so "TPU v4 lite" finds
#: "tpu v4".  The cpu row is deliberately modest (a few Skylake-class
#: cores with AVX f64 and dual-channel DRAM) — the roofline verdict
#: needs the RATIO (the ridge point), not vendor-sheet precision, and
#: SLATE_TPU_PEAKS overrides per deployment.
DEFAULT_PEAKS: Dict[str, Dict[str, float]] = {
    "cpu": {"flops": 5.0e10, "bytes_per_s": 2.0e10},
    "tpu v4": {"flops": 2.75e14, "bytes_per_s": 1.2e12},
    "tpu v5": {"flops": 3.9e14, "bytes_per_s": 1.6e12},
    "tpu v6": {"flops": 9.2e14, "bytes_per_s": 1.6e12},
}

#: last-resort peaks when no table row matches the device kind: the
#: cpu row, labeled so reports show the verdict is on defaulted roofs
FALLBACK_KIND = "cpu"


# ---------------------------------------------------------------------------
# activation
# ---------------------------------------------------------------------------


def on() -> None:
    """Enable device telemetry capture (one bool flips)."""
    global _enabled
    _enabled = True


def off() -> None:
    global _enabled
    _enabled = False


def is_on() -> bool:
    return _enabled


def reset() -> None:
    """Clear the high-water marks (keeps on/off state) — test hygiene."""
    with _lock:
        _hwm.clear()


# ---------------------------------------------------------------------------
# executable cost/memory capture
# ---------------------------------------------------------------------------


def analyze_compiled(compiled) -> Optional[dict]:
    """Cost + memory record of one AOT-compiled executable: flops /
    bytes_accessed / transcendentals from ``cost_analysis()``,
    argument/output/temp/generated-code bytes from
    ``memory_analysis()``, and ``peak_bytes`` — the runtime's
    ``peak_memory_in_bytes`` when it reports one, else the
    argument+output+temp sum (the resident-set bound XLA:CPU gives
    us).  Missing APIs degrade to omitted fields; a record with
    nothing in it is None.  Never raises."""
    out: dict = {}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if isinstance(ca, dict):
            for key, label in (("flops", "flops"),
                               ("bytes accessed", "bytes_accessed"),
                               ("transcendentals", "transcendentals")):
                v = ca.get(key)
                # XLA reports -1 for unknowable costs (CPU while
                # loops): that is "no data", not a number to rate with
                if v is not None and float(v) >= 0:
                    out[label] = float(v)
    except Exception:  # noqa: BLE001 — attribution must never break a build
        pass
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            for attr, label in (
                ("argument_size_in_bytes", "argument_bytes"),
                ("output_size_in_bytes", "output_bytes"),
                ("temp_size_in_bytes", "temp_bytes"),
                ("alias_size_in_bytes", "alias_bytes"),
                ("generated_code_size_in_bytes", "generated_code_bytes"),
            ):
                v = getattr(ma, attr, None)
                if v is not None and int(v) >= 0:
                    out[label] = int(v)
            peak = getattr(ma, "peak_memory_in_bytes", None)
            # absent OR zero: some PJRT plugins expose the attribute
            # without filling it — either way the arg+out+temp sum is
            # the computable bound
            if not peak and (
                "argument_bytes" in out or "output_bytes" in out
                or "temp_bytes" in out
            ):
                # aliased (donated) buffers appear in BOTH the argument
                # and output totals — subtract them once or the bound
                # double-counts every donated batch operand
                peak = max(
                    out.get("argument_bytes", 0)
                    + out.get("output_bytes", 0)
                    + out.get("temp_bytes", 0)
                    - out.get("alias_bytes", 0),
                    0,
                )
            if peak is not None and int(peak) > 0:
                out["peak_bytes"] = int(peak)
    except Exception:  # noqa: BLE001
        pass
    return out or None


def capture_jitted(jitted, args, name: Optional[str] = None,
                   record: bool = True):
    """AOT lower -> compile -> analyze one ``jax.jit`` callable at
    ``args`` (arrays or ``jax.ShapeDtypeStruct`` specs).  Returns
    ``(compiled, cost)`` — the compiled executable (callable; reusable
    so the capture compile is not wasted) and the cost/memory record
    (either may be None on failure; capture must never break a build).
    With ``record`` and a ``name``, the record also lands in the
    metrics cost registry (when metrics are on), tagged with the
    default device kind so the roofline report can resolve peaks."""
    compiled = cost = None
    try:
        compiled = jitted.lower(*args).compile()
        cost = analyze_compiled(compiled)
    except Exception:  # noqa: BLE001 — capture must never break a build
        return compiled, None
    if cost is not None:
        cost["device_kind"] = default_device_kind()
        if record and name:
            _metrics.record_cost(name, cost)
    return compiled, cost


def default_device_kind() -> str:
    """Lowercased device kind of the default backend's first device
    (the peaks-table key); "unknown" when jax is unavailable."""
    try:
        import jax

        d = jax.devices()[0]
        return str(getattr(d, "device_kind", d.platform)).lower()
    except Exception:  # noqa: BLE001
        return "unknown"


# ---------------------------------------------------------------------------
# device memory gauges
# ---------------------------------------------------------------------------


def bytes_in_use(device=None) -> Optional[int]:
    """Instantaneous HBM bytes in use on one device (default: the
    default backend's first device), or None on backends without
    ``memory_stats`` (XLA:CPU) — the fabric arena's budget-pressure
    probe; graceful degradation, never a crash."""
    try:
        if device is None:
            import jax

            device = jax.devices()[0]
        fn = getattr(device, "memory_stats", None)
        stats = fn() if fn is not None else None
        if stats:
            v = stats.get("bytes_in_use")
            return int(v) if v is not None else None
    except Exception:  # noqa: BLE001 — telemetry must never crash
        pass
    return None


def sample_devices(devices=None) -> List[dict]:
    """One memory snapshot per device: ``{"id", "platform", "kind",
    "bytes_in_use", "bytes_limit", "peak_bytes_in_use"}`` with the
    byte fields None on backends without ``memory_stats`` (XLA:CPU) —
    graceful degradation, never a crash.  Maintains a process-lifetime
    high-water mark per device (the monotone peak even when the
    backend reports only instantaneous use) and, with metrics on,
    emits ``serve.device.<i>.bytes_in_use`` / ``.bytes_in_use_peak``
    gauges."""
    if devices is None:
        try:
            import jax

            devices = jax.devices()
        except Exception:  # noqa: BLE001 — telemetry must never crash
            return []
    out = []
    for d in devices:
        did = getattr(d, "id", None)
        row = {
            "id": did,
            "platform": getattr(d, "platform", None),
            "kind": getattr(d, "device_kind", None),
            "bytes_in_use": None,
            "bytes_limit": None,
            "peak_bytes_in_use": None,
        }
        stats = None
        try:
            fn = getattr(d, "memory_stats", None)
            stats = fn() if fn is not None else None
        except Exception:  # noqa: BLE001 — unsupported backend, not an error
            stats = None
        if stats:
            in_use = stats.get("bytes_in_use")
            row["bytes_in_use"] = (
                int(in_use) if in_use is not None else None
            )
            limit = stats.get("bytes_limit")
            row["bytes_limit"] = int(limit) if limit is not None else None
            peak = stats.get("peak_bytes_in_use")
            with _lock:
                prev = _hwm.get(did, 0)
                cand = max(
                    prev,
                    int(peak) if peak is not None else 0,
                    int(in_use) if in_use is not None else 0,
                )
                if cand > 0:
                    _hwm[did] = cand
                    row["peak_bytes_in_use"] = cand
            if _metrics.is_on():
                if row["bytes_in_use"] is not None:
                    _metrics.gauge(
                        f"serve.device.{did}.bytes_in_use",
                        row["bytes_in_use"],
                    )
                if row["peak_bytes_in_use"] is not None:
                    _metrics.gauge(
                        f"serve.device.{did}.bytes_in_use_peak",
                        row["peak_bytes_in_use"],
                    )
        out.append(row)
    return out


# ---------------------------------------------------------------------------
# roofline peaks + attribution
# ---------------------------------------------------------------------------


def _env_peaks() -> Dict[str, Dict[str, float]]:
    """The ``SLATE_TPU_PEAKS`` override table: a JSON object mapping
    device-kind substrings to ``{"flops": ..., "bytes_per_s": ...}``.
    A malformed value degrades to the built-in table (telemetry never
    crashes the host), counted ``devmon.peaks_parse_error``."""
    raw = os.environ.get(PEAKS_ENV)
    if not raw:
        return {}
    try:
        doc = json.loads(raw)
        out = {}
        for kind, row in doc.items():
            f, b = float(row["flops"]), float(row["bytes_per_s"])
            if f <= 0 or b <= 0:
                # zero/negative roofs would divide-by-zero the ridge
                # and the frac-of-roof — malformed, not a table row
                raise ValueError(f"peaks for {kind!r} must be positive")
            out[str(kind).lower()] = {"flops": f, "bytes_per_s": b}
        return out
    except Exception:  # noqa: BLE001
        _metrics.inc("devmon.peaks_parse_error")
        return {}


def peaks_for(kind: Optional[str] = None) -> dict:
    """Resolve a device kind to its roofline peaks: ``{"flops",
    "bytes_per_s", "ridge", "kind", "source"}`` with ridge = peak
    FLOP/s / peak bytes/s (the arithmetic intensity where the roof
    changes slope).  ``SLATE_TPU_PEAKS`` rows win over the built-in
    table; an unmatched kind falls back to the cpu row with
    ``source="fallback"`` so reports show the roofs are defaulted."""
    k = (kind if kind is not None else default_device_kind()).lower()
    table = dict(DEFAULT_PEAKS)
    source = "default"
    env = _env_peaks()
    row = None
    for sub, vals in env.items():
        if sub in k:
            row, source = vals, "env"
            break
    if row is None:
        for sub, vals in table.items():
            if sub in k:
                row = vals
                break
    if row is None:
        # unmatched kind: fall back to the cpu row — honoring an env
        # override of it (the operator who replaced the cpu roofs
        # meant them, fallback path included)
        row = env.get(FALLBACK_KIND, table[FALLBACK_KIND])
        source = "fallback"
    return {
        "kind": k,
        "flops": float(row["flops"]),
        "bytes_per_s": float(row["bytes_per_s"]),
        "ridge": float(row["flops"]) / float(row["bytes_per_s"]),
        "source": source,
    }


def roofline(flops: float, bytes_accessed: float, seconds: float,
             peaks: Optional[dict] = None) -> Optional[dict]:
    """Roofline attribution of one measured execution: achieved
    FLOP/s, arithmetic intensity (flops / bytes accessed), the
    compute- vs memory-bound verdict (intensity vs the ridge point),
    the attainable roof ``min(peak_flops, intensity * peak_bw)``, and
    the achieved fraction of it.  None when the inputs cannot rate
    (zero/negative flops, bytes, or wall) — the caller's
    "unclassifiable" signal, never a division error."""
    if not (flops and flops > 0 and bytes_accessed and bytes_accessed > 0
            and seconds and seconds > 0):
        return None
    pk = peaks if peaks is not None else peaks_for()
    if not (pk.get("flops", 0) > 0 and pk.get("bytes_per_s", 0) > 0):
        return None  # degenerate hand-passed roofs: unclassifiable
    # accept the bare SLATE_TPU_PEAKS row shape too: ridge is derived
    # when the caller did not pass a peaks_for() result
    ridge = pk.get("ridge") or pk["flops"] / pk["bytes_per_s"]
    achieved = flops / seconds
    intensity = flops / bytes_accessed
    roof = min(pk["flops"], intensity * pk["bytes_per_s"])
    return {
        "achieved_flops": achieved,
        "achieved_gflops": achieved / 1e9,
        "intensity": intensity,
        "ridge": ridge,
        "bound": "compute" if intensity >= ridge else "memory",
        "roof_flops": roof,
        "frac_of_roof": achieved / roof,
        "peaks_source": pk.get("source", "caller"),
    }


# ---------------------------------------------------------------------------
# env activation: SLATE_TPU_DEVMON=1
# ---------------------------------------------------------------------------

if os.environ.get("SLATE_TPU_DEVMON") not in (None, "", "0"):
    on()
