"""Debug dumps: tile layout, ownership, and device-placement inspection
(reference: src/core/Debug.cc:66-340 — checkTilesLives,
printTilesLives, printTilesMaps, printNumFreeMemBlocks; SURVEY §5).

The reference walks MatrixStorage's tile map and MOSI states; here the
analogous introspection shows the block-cyclic index math (which global
tile lives in which storage slot, owned by which process) and the JAX
sharding actually placed on the data — the two things that can disagree
with a driver's expectation and produce wrong-layout bugs.
"""

from __future__ import annotations

import io
from typing import Optional

import numpy as np

from ..matrix.base import BaseMatrix


def tiles_map(A: BaseMatrix, max_tiles: int = 32) -> str:
    """Ownership map of A's global tiles (reference: Debug.cc
    printTilesMaps): one cell per tile, showing 'pr,pc' owner ranks,
    truncated to max_tiles rows/cols."""
    lay = A.layout
    out = io.StringIO()
    mt, nt = lay.mt, lay.nt
    out.write(
        f"tiles_map: {lay.m}x{lay.n}, tile {lay.mb}x{lay.nb}, "
        f"grid {lay.p}x{lay.q}, storage {lay.storage_shape}\n"
    )
    for i in range(min(mt, max_tiles)):
        cells = []
        for j in range(min(nt, max_tiles)):
            pr, pc = lay.tileRank(i, j)
            cells.append(f"{pr},{pc}")
        ell = " ..." if nt > max_tiles else ""
        out.write("  " + " | ".join(cells) + ell + "\n")
    if mt > max_tiles:
        out.write("  ...\n")
    return out.getvalue()


def storage_map(A: BaseMatrix, max_slots: int = 32) -> str:
    """Storage-slot map (reference: MatrixStorage's tile map dump):
    which global tile each owner-major slot holds, plus padding flags."""
    lay = A.layout
    out = io.StringIO()
    out.write(
        f"storage_map: slots {lay.P}x{lay.Q} "
        f"(local {lay.mtl}x{lay.ntl} per process)\n"
    )
    for s in range(min(lay.P, max_slots)):
        i = lay.lrow(s)
        row = []
        for t in range(min(lay.Q, max_slots)):
            j = lay.lcol(t)
            pad = "" if (i < lay.mt and j < lay.nt) else "*"
            row.append(f"({i},{j}){pad}")
        out.write(f"  slot row {s:3d}: " + " ".join(row) + "\n")
    if lay.P > max_slots:
        out.write("  ...\n")
    out.write("  (* = padding slot beyond the matrix)\n")
    return out.getvalue()


def sharding_info(A: BaseMatrix) -> str:
    """The sharding actually on A.data vs the layout's expectation
    (reference: Debug.cc checkTilesLives — storage vs expectation)."""
    out = io.StringIO()
    data = A.data
    out.write(f"data: shape {tuple(data.shape)}, dtype {data.dtype}\n")
    sh = getattr(data, "sharding", None)
    if sh is None:
        out.write("sharding: none (host / uncommitted)\n")
        return out.getvalue()
    out.write(f"sharding: {sh}\n")
    try:
        dev_map = sh.devices_indices_map(tuple(data.shape))
        for dev, idx in list(dev_map.items())[:16]:
            out.write(f"  {dev}: {idx}\n")
        if len(dev_map) > 16:
            out.write(f"  ... ({len(dev_map)} devices total)\n")
    except Exception as e:  # pragma: no cover - backend-specific
        out.write(f"  (indices map unavailable: {e})\n")
    exp = (
        f"expected for grid {A.grid.p}x{A.grid.q}: "
        f"PartitionSpec('p','q') over storage axes 0,1\n"
        if A.grid is not None and A.grid.size > 1
        else "expected: single-device (no partitioning)\n"
    )
    out.write(exp)
    return out.getvalue()


def tiles_lives(A: BaseMatrix) -> str:
    """Per-tile liveness summary (reference: Debug.cc printTilesLives):
    on TPU there is no MOSI state — a tile is 'live' iff its slot holds
    non-padding data; report counts and any NaN/Inf tiles (the usual
    smoking gun a MOSI bug would have produced)."""
    lay = A.layout
    T = np.asarray(A.data)
    bad = ~np.isfinite(T).reshape(lay.P, lay.Q, -1).all(axis=2)
    valid = np.zeros((lay.P, lay.Q), dtype=bool)
    for s in range(lay.P):
        for t in range(lay.Q):
            valid[s, t] = lay.lrow(s) < lay.mt and lay.lcol(t) < lay.nt
    out = io.StringIO()
    out.write(
        f"tiles_lives: {valid.sum()} live / {lay.P * lay.Q} slots "
        f"({(~valid).sum()} padding)\n"
    )
    nonfinite = np.argwhere(bad & valid)
    if len(nonfinite):
        out.write(f"  NON-FINITE tiles at slots: {nonfinite.tolist()[:20]}\n")
    else:
        out.write("  all live tiles finite\n")
    return out.getvalue()


def dump(A: BaseMatrix, label: str = "matrix", file=None) -> str:
    """Full debug dump (layout + storage + sharding + liveness)."""
    s = (
        f"== debug dump: {label} ==\n"
        + tiles_map(A)
        + storage_map(A)
        + sharding_info(A)
        + tiles_lives(A)
    )
    if file is not None:
        print(s, file=file)
    return s
