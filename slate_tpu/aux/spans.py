"""Request-scoped span tracing: trace ids, parent/child spans, a
bounded ring-buffer flight recorder, and Chrome trace-event export.

This is the per-request half of the observability layer (the SLATE
SC'19 tracer renders per-task timelines because aggregate counters
cannot explain where one solve spent its time; Dapper is the
distributed ancestor — see PAPERS.md "Tracing").  ``aux/metrics``
answers "how much, on average"; this module answers "where did THIS
request's time go": every serve request gets a **trace id**, and the
lifecycle stages (admit -> queued -> coalesce -> execute | direct ->
retry/backoff -> deliver) record **spans** — named intervals with
monotonic timestamps, a parent link, a lane (replica/worker), and an
attrs dict (bucket label, backoff interval, refine iteration count,
artifact-restore outcome, ...).

Design rules, same as metrics/trace/faults:

1. **Zero overhead off** — every entry point starts with one
   module-level bool check; OFF is the default.  The serve hot path
   pays exactly one branch per call site when tracing is disabled.
2. **Bounded memory** — completed spans land in a ring buffer
   (``collections.deque(maxlen=ring)``): a long-running service keeps
   the LAST N spans, flight-recorder style, and ``evicted()`` counts
   what scrolled off.  Nothing ever grows without bound.
3. **Crash-safe cross-thread spans** — a span is appended to the ring
   only when it *ends* (Chrome "complete" events); a request whose
   root span never ended is visible as an orphan in the export, which
   is the bug signal, not a formatting problem.

Activation::

    SLATE_TPU_TRACE_RING=8192 python app.py   # on at import, ring of 8192
    # or programmatically:
    from slate_tpu.aux import spans
    spans.on(ring=4096)
    ...
    spans.export_chrome("trace.json")   # load in Perfetto / chrome://tracing

Span taxonomy the serve tier emits (service.py / cache.py):
``request`` (root: admit -> deliver, attrs ``routine``/``bucket``/
``outcome`` — plus ``tenant``/``priority`` on a tenancy-enabled
service), ``admit``, ``queued`` (ends at dispatch; attrs
``replica``), ``coalesce``, ``execute`` (the padded-batch dispatch;
attrs ``batch``), ``direct`` (fallback / keyless path), ``backoff``
(the planned retry delay; attrs ``backoff_s``/``retries_left``),
``build`` (cold executable build; attrs ``origin``), ``restore``
(artifact-restore entries; attrs ``outcome``/``origin``), and instant
events ``breaker_open``/``breaker_half_open``/``breaker_closed`` plus
the admission plane's ``shed`` (attrs ``tenant``/``priority``/
``level``), ``overload_enter``/``overload_exit`` (attrs ``level``/
``sheds``), and ``adaptive_window`` (attrs ``bucket``/``window_s``/
``direction`` — the AIMD trajectory, one instant per decision).
Driver phases (``@metrics.instrumented``) and ``trace.Block`` mirror
onto the same ring when both layers are on.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

#: default flight-recorder capacity for programmatic on()
DEFAULT_RING = 4096

RING_ENV = "SLATE_TPU_TRACE_RING"

_enabled = False
_lock = threading.Lock()
_ring: deque = deque(maxlen=DEFAULT_RING)
_evicted = 0
_t0: Optional[float] = None

_ids = itertools.count(1)  # span ids (next() is atomic under the GIL)
_trace_ids = itertools.count(1)
_tls = threading.local()  # per-thread stack of context-managed spans


def now() -> float:
    """The span clock (monotonic; shared with metrics/trace phases)."""
    return time.perf_counter()


class Span:
    """One named interval: ``[t_start, t_end]`` on a thread/lane, with
    a trace id, a parent span id, and an attrs dict.  Mutable until
    :func:`end` stamps ``t_end`` and pushes it onto the ring."""

    __slots__ = (
        "name", "trace", "sid", "parent", "t_start", "t_end", "thread",
        "lane", "kind", "attrs",
    )

    def __init__(self, name, trace=None, parent=None, lane=None,
                 kind="span", attrs=None, t_start=None):
        self.name = name
        self.trace = trace
        self.sid = next(_ids)
        self.parent = parent.sid if isinstance(parent, Span) else parent
        self.t_start = now() if t_start is None else t_start
        self.t_end: Optional[float] = None
        self.thread = threading.get_ident()
        self.lane = lane
        self.kind = kind
        self.attrs = dict(attrs) if attrs else {}

    @property
    def dur_s(self) -> float:
        return (self.t_end - self.t_start) if self.t_end is not None else 0.0

    def to_json(self) -> dict:
        d = {
            "name": self.name, "trace": self.trace, "span": self.sid,
            "parent": self.parent, "t_start": round(self.t_start, 6),
            "dur_s": round(self.dur_s, 6), "thread": self.thread,
            "lane": self.lane, "kind": self.kind,
        }
        if self.attrs:
            d["attrs"] = self.attrs
        return d

    def __repr__(self):  # debugging aid, never parsed
        return (f"Span({self.name!r}, trace={self.trace}, sid={self.sid}, "
                f"dur={self.dur_s:.6f}, attrs={self.attrs})")


# ---------------------------------------------------------------------------
# control
# ---------------------------------------------------------------------------


def on(ring: Optional[int] = None) -> None:
    """Enable span recording with a flight-recorder ring of ``ring``
    completed spans (oldest evicted; :func:`evicted` counts them).
    ``ring=None`` keeps the current capacity (:data:`DEFAULT_RING`
    initially, or whatever ``SLATE_TPU_TRACE_RING``/an earlier explicit
    ``on(ring=)`` configured) — a bare re-enable never shrinks it."""
    global _enabled, _ring, _t0
    with _lock:
        if ring is not None and _ring.maxlen != int(ring):
            _ring = deque(_ring, maxlen=max(1, int(ring)))
        if _t0 is None:
            _t0 = now()
        _enabled = True


def off() -> None:
    global _enabled
    _enabled = False


def is_on() -> bool:
    return _enabled

def capacity() -> int:
    return _ring.maxlen or 0


def clear() -> None:
    global _evicted, _t0
    with _lock:
        _ring.clear()
        _evicted = 0
        _t0 = now() if _enabled else None


def evicted() -> int:
    """Completed spans the bounded ring has dropped (oldest-first)."""
    return _evicted


def pressure() -> dict:
    """Eviction-pressure snapshot of the flight recorder: capacity,
    current fill, lifetime evictions, and the estimated coverage
    window (newest end minus oldest start across the ring) — the span
    of history a ring->spec soak recording can still reconstruct.  A
    nonzero ``evicted`` with a short ``window_s`` means a recording
    taken NOW is already truncated; ``health()["trace_ring"]``
    surfaces this so the gap is visible before it becomes a silently
    short load spec."""
    with _lock:
        size = len(_ring)
        if size:
            oldest = _ring[0]
            newest = _ring[-1]
            window = (newest.t_end if newest.t_end is not None
                      else newest.t_start) - oldest.t_start
        else:
            window = 0.0
        return {
            "capacity": _ring.maxlen or 0,
            "size": size,
            "evicted": _evicted,
            "window_s": round(max(window, 0.0), 6),
        }


def new_trace() -> str:
    """A fresh trace id (one per serve request)."""
    return f"t{os.getpid():x}-{next(_trace_ids):x}"


# ---------------------------------------------------------------------------
# recording
# ---------------------------------------------------------------------------


def _push(sp: Span) -> None:
    global _evicted
    with _lock:
        if len(_ring) == _ring.maxlen:
            _evicted += 1
        _ring.append(sp)


def start(name: str, trace: Optional[str] = None, parent=None,
          lane: Optional[str] = None, **attrs) -> Optional[Span]:
    """Open a span (not yet on the ring; :func:`end` completes it).
    For cross-thread lifecycle spans the caller holds the handle —
    the context-manager :func:`span` is the nested single-thread
    form.  Returns None when tracing is off."""
    if not _enabled:
        return None
    return Span(name, trace=trace, parent=parent, lane=lane, attrs=attrs)


def end(sp: Optional[Span], **attrs) -> None:
    """Stamp ``t_end``, merge ``attrs``, and push onto the ring.
    Idempotent: a span already ended is left untouched (resolution
    paths may race — first outcome wins, like Future.set_result)."""
    if sp is None or not _enabled:
        return
    if sp.t_end is not None:
        return
    sp.t_end = now()
    if attrs:
        sp.attrs.update(attrs)
    _push(sp)


def record(name: str, t_start: float, t_end: float,
           trace: Optional[str] = None, parent=None,
           lane: Optional[str] = None, kind: str = "span",
           **attrs) -> Optional[Span]:
    """Append one already-measured interval (both timestamps from
    :func:`now`'s clock).  The bulk path: per-item spans of a batch,
    metrics/trace mirrors, planned backoff windows."""
    if not _enabled:
        return None
    sp = Span(name, trace=trace, parent=parent, lane=lane, kind=kind,
              attrs=attrs, t_start=t_start)
    sp.t_end = t_end
    _push(sp)
    return sp


def event(name: str, trace: Optional[str] = None, parent=None,
          lane: Optional[str] = None, **attrs) -> Optional[Span]:
    """Instant event (zero-duration; breaker transitions and friends)."""
    if not _enabled:
        return None
    t = now()
    return record(name, t, t, trace=trace, parent=parent, lane=lane,
                  kind="instant", **attrs)


class span:
    """Context manager for nested single-thread spans: parents onto the
    innermost active span of this thread (or an explicit ``parent`` —
    e.g. a request's root span held by another thread) and becomes
    :func:`current` inside the block (so :func:`annotate` reaches it)::

        with spans.span("factor", trace=tr):
            ...
            spans.annotate(iters=3)
    """

    __slots__ = ("name", "trace", "lane", "parent", "attrs", "_sp")

    def __init__(self, name: str, trace: Optional[str] = None,
                 lane: Optional[str] = None, parent=None, **attrs):
        self.name = name
        self.trace = trace
        self.lane = lane
        self.parent = parent
        self.attrs = attrs
        self._sp: Optional[Span] = None

    def __enter__(self) -> Optional[Span]:
        if not _enabled:
            return None
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        parent = self.parent if self.parent is not None else (
            stack[-1] if stack else None
        )
        tr = self.trace
        if tr is None and isinstance(parent, Span):
            tr = parent.trace
        self._sp = Span(self.name, trace=tr, parent=parent, lane=self.lane,
                        attrs=self.attrs)
        stack.append(self._sp)
        return self._sp

    def __exit__(self, exc_type, *exc) -> bool:
        sp = self._sp
        if sp is None:
            return False
        stack = getattr(_tls, "stack", None)
        if stack and stack[-1] is sp:
            stack.pop()
        if exc_type is not None:
            sp.attrs.setdefault("outcome", exc_type.__name__)
        end(sp)
        return False


def current() -> Optional[Span]:
    """The innermost context-managed span on this thread (None when
    off or outside every block)."""
    if not _enabled:
        return None
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


def annotate(_sp: Optional[Span] = None, **attrs) -> None:
    """Merge attrs into ``_sp`` (or this thread's :func:`current` span).
    The hook the refine drivers use to stamp iteration counts onto
    whatever span their caller is inside.  No-op when off/outside."""
    if not _enabled:
        return
    sp = _sp if _sp is not None else current()
    if sp is not None:
        sp.attrs.update(attrs)


# ---------------------------------------------------------------------------
# snapshots + export
# ---------------------------------------------------------------------------


def snapshot() -> List[Span]:
    """The ring's completed spans, oldest first."""
    with _lock:
        return list(_ring)


def by_trace() -> Dict[str, List[Span]]:
    """Ring spans grouped by trace id (spans without one are dropped) —
    the orphan check: a delivered request's trace must contain a
    completed ``request`` root plus its lifecycle children."""
    out: Dict[str, List[Span]] = {}
    for sp in snapshot():
        if sp.trace is not None:
            out.setdefault(sp.trace, []).append(sp)
    return out


def export_chrome(path: str, extra=None,
                  process_name: Optional[str] = None) -> str:
    """Write the ring as Chrome trace-event JSON (the ``traceEvents``
    array format; open in Perfetto / chrome://tracing).  One lane per
    replica/worker: spans with a ``lane`` string share a named tid;
    lane-less spans fall back to one tid per OS thread.  ``extra``
    accepts legacy ``trace.Event``-shaped tuples ``(name, start, stop,
    thread)`` so ``trace.finish()`` can merge both timelines.  Spans
    carry ``trace``/``span``/``parent`` ids and attrs in ``args``.
    ``process_name`` labels this process's pid track (Chrome's
    ``process_name`` metadata) — the fleet tier's per-host exports set
    it so ``tools/trace_stitch.py`` renders each host as a named
    process in the stitched view."""
    items = snapshot()
    rows = []  # (name, t0, t1, lane, thread, kind, args)
    seen = set()  # dedup key against the legacy trace-event mirror
    for sp in items:
        args = {"span": sp.sid}
        if sp.trace is not None:
            args["trace"] = sp.trace
        if sp.parent is not None:
            args["parent"] = sp.parent
        args.update(sp.attrs)
        rows.append((sp.name, sp.t_start, sp.t_end, sp.lane, sp.thread,
                     sp.kind, args))
        seen.add((sp.name, round(sp.t_start, 9), sp.thread))
    for e in extra or ():
        name, start_t, stop_t, thread = (
            (e.name, e.start, e.stop, e.thread) if hasattr(e, "name") else e
        )
        # with trace AND spans both on, Block/phase mirror the same
        # interval into both recorders — emit it once, not twice
        if (name, round(start_t, 9), thread) in seen:
            continue
        rows.append((name, start_t, stop_t, None, thread, "span", {}))
    pid = os.getpid()
    tids: Dict[str, int] = {}

    def tid_for(lane, thread):
        key = lane if lane is not None else f"thread-{thread}"
        if key not in tids:
            tids[key] = len(tids)
        return tids[key]

    t0 = min((r[1] for r in rows), default=_t0 or 0.0)
    evs = []
    for name, start_t, stop_t, lane, thread, kind, args in rows:
        ev = {
            "name": name,
            "cat": kind,
            "pid": pid,
            "tid": tid_for(lane, thread),
            "ts": round((start_t - t0) * 1e6, 3),
            "args": args,
        }
        if kind == "instant":
            ev["ph"] = "i"
            ev["s"] = "p"
        else:
            ev["ph"] = "X"
            ev["dur"] = round(((stop_t or start_t) - start_t) * 1e6, 3)
        evs.append(ev)
    evs.sort(key=lambda e: e["ts"])
    meta = [
        {"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
         "args": {"name": key}}
        for key, tid in sorted(tids.items(), key=lambda kv: kv[1])
    ]
    if process_name is not None:
        meta.insert(0, {"name": "process_name", "ph": "M", "pid": pid,
                        "args": {"name": str(process_name)}})
    with open(path, "w") as f:
        json.dump({"traceEvents": meta + evs, "displayTimeUnit": "ms"}, f)
    return path


# ---------------------------------------------------------------------------
# env activation: SLATE_TPU_TRACE_RING=N
# ---------------------------------------------------------------------------

_env_ring = os.environ.get(RING_ENV)
if _env_ring:
    try:
        _n = int(_env_ring)
    except ValueError as e:
        raise ValueError(
            f"{RING_ENV}={_env_ring!r}: expected an integer ring size"
        ) from e
    if _n > 0:
        on(ring=_n)
