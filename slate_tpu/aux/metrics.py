"""Process-wide metrics registry: counters, gauges, timers, and a
JSONL event exporter (the observability layer the reference gets from
``include/slate/internal/Trace.hh`` plus its testers' GFLOP/s columns).

Design goals, in order:

1. **Zero overhead when off** — every public hot-path entry point
   (:func:`inc`, :func:`observe`, :class:`phase`, the
   :func:`instrumented` decorator, :func:`instrument_jit` wrappers)
   starts with a single module-level bool check, exactly like
   ``trace.on_`` in the reference and ``trace._enabled`` here.
2. **Compile-vs-execute split** — :func:`instrument_jit` wraps a
   ``jax.jit`` callable and detects first dispatch per shape signature
   (cache-size growth), so a recompile storm shows up as the
   ``jit.compilations`` counter and per-name ``<name>.compile`` timers
   instead of silently inflating "run" time.  BENCH_NOTES' warm/steady
   methodology maps onto exactly this split.
3. **FLOP/byte attribution** — at compile time the wrapper captures
   ``jitted.lower(...).compile().cost_analysis()`` so achieved vs.
   theoretical GFLOP/s needs no hand-derived formulas
   (:func:`costs`, ``flops`` gauges).  Skippable with
   ``SLATE_TPU_METRICS_COST=0`` (the AOT lower/compile is a second
   compile of the same program; cheap on CPU, noticeable on-chip).
4. **One timeline with trace.py** — phases recorded here also push
   :class:`trace.Event` rows when tracing is on, so
   ``trace.finish("trace.svg")`` renders driver phases and metric
   phases on the same SVG.

Activation::

    SLATE_TPU_METRICS=/path/out.jsonl python app.py   # on + dump at exit
    # or programmatically:
    from slate_tpu.aux import metrics
    metrics.on()
    ...
    print(metrics.report())
    metrics.dump("out.jsonl")

JSONL schema (one object per line): ``{"type": "meta"|"event"|
"counter"|"gauge"|"timer"|"hist"|"cost", ...}``; events carry ``name``,
``kind`` ("phase"|"compile"|"run"), ``t_start`` (seconds since the
metrics epoch), ``dur_s``, ``thread``, and the active :func:`context`
label.  Counters/gauges/timers/histograms are the end-of-run
summaries; ``hist`` lines carry count/min/max/p50/p95/p99 plus the
nonzero ``[le, count]`` bucket rows on the fixed log lattice
(:data:`HIST_EDGES`), so ``tools/latency_report.py`` re-ranks any
percentile from one dump.

Tail latency lives in :class:`Histogram` (:func:`observe_hist`,
:func:`percentile`): fixed log-spaced buckets, so p50/p95/p99 of every
driver phase (``kind="driver"`` phases feed a same-named histogram
automatically) and of the serve queued/execute/total split
(``serve.latency.*``, see serve/service.py) are one call away — means
hide the p99, and Clipper-style SLOs are stated in percentiles.
Per-request timelines are ``aux/spans`` (trace ids + Chrome export);
metric events mirror onto its ring when both layers are on.

The containment layers report through this registry too: serve/ emits
``serve.worker_restarts``, ``serve.breaker_open/half_open/closed``,
``serve.retries`` + the ``serve.retry_backoff_s`` timer,
``serve.invalid_input``, and the ``serve.deadline_miss_queued/_late``
split; ``aux/faults`` counts every injection as
``faults.injected.<site>`` — ``tools/chaos_report.py`` joins the
injected-vs-recovered pair from one JSONL.  The mixed-precision
drivers (drivers/mixed.py over refine/) emit the ``refine.calls`` /
``refine.iterations`` / ``refine.converged`` / ``refine.fallbacks``
counters and the ``refine.residual`` gauge, global and per-routine —
``tools/refine_report.py`` turns one JSONL into the per-routine
iterations/converged/fallback-rate table.
"""

from __future__ import annotations

import atexit
import functools
import json
import math
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from . import spans as _spans
from . import trace as _trace

_enabled = False
_lock = threading.RLock()
_t0: Optional[float] = None

_counters: Dict[str, float] = {}
_gauges: Dict[str, float] = {}
# name -> [count, total_s, min_s, max_s]
_timers: Dict[str, List[float]] = {}
_hists: Dict[str, "Histogram"] = {}
_events: List[dict] = []
_costs: Dict[str, dict] = {}
_timeline: List[dict] = []
_context = threading.local()

_MAX_EVENTS = 200_000
_MAX_TIMELINE = 100_000
_dropped_events = 0
_dropped_timeline = 0


# ---------------------------------------------------------------------------
# registry control
# ---------------------------------------------------------------------------


def on() -> None:
    """Enable metrics collection (one bool flips; nothing is allocated)."""
    global _enabled, _t0
    with _lock:
        _enabled = True
        if _t0 is None:
            _t0 = time.perf_counter()


def off() -> None:
    global _enabled
    _enabled = False


def is_on() -> bool:
    return _enabled


def reset() -> None:
    """Clear every counter/gauge/timer/event (keeps on/off state)."""
    global _t0, _dropped_events, _dropped_timeline
    with _lock:
        _counters.clear()
        _gauges.clear()
        _timers.clear()
        _hists.clear()
        _events.clear()
        _costs.clear()
        _timeline.clear()
        _dropped_events = 0
        _dropped_timeline = 0
        _t0 = time.perf_counter() if _enabled else None


# ---------------------------------------------------------------------------
# primitives: counters, gauges, timers, events
# ---------------------------------------------------------------------------


def inc(name: str, value: float = 1) -> None:
    """Increment a counter.  No-op (one bool check) when metrics are off."""
    if not _enabled:
        return
    with _lock:
        _counters[name] = _counters.get(name, 0) + value


def gauge(name: str, value: float) -> None:
    """Set a gauge to its latest value."""
    if not _enabled:
        return
    with _lock:
        _gauges[name] = float(value)


def observe(name: str, seconds: float) -> None:
    """Record one duration into the named timer's (count, total, min, max)."""
    if not _enabled:
        return
    with _lock:
        t = _timers.get(name)
        if t is None:
            _timers[name] = [1, seconds, seconds, seconds]
        else:
            t[0] += 1
            t[1] += seconds
            t[2] = min(t[2], seconds)
            t[3] = max(t[3], seconds)


# -- timeline rows (sampled time-series snapshots; the soak plane) ----------


def record_timeline(fields: Dict[str, Any]) -> None:
    """Append one time-series sample row (the soak fabric's
    ``{"type": "timeline"}`` JSONL rows — ``soak/timeline.py`` samples
    ``health()`` + devmon gauges through here on a background cadence).
    Every existing summary line is an END-OF-RUN aggregate; these rows
    are the mid-run trajectory — a quarantine storm that engaged and
    recovered before the dump is invisible to every other row type.
    Bounded like ``_events`` (oldest kept, newest dropped past
    :data:`_MAX_TIMELINE`, drop count surfaced in the meta line); a
    ``t`` stamp relative to the registry clock is added when absent.
    No-op (one bool check) when metrics are off."""
    if not _enabled:
        return
    global _dropped_timeline
    with _lock:
        if len(_timeline) >= _MAX_TIMELINE:
            _dropped_timeline += 1
            return
        row = dict(fields)
        if "t" not in row:
            row["t"] = round(time.perf_counter() - (_t0 or 0.0), 6)
        _timeline.append(row)


def timeline() -> List[dict]:
    """Snapshot of the recorded timeline rows, oldest first."""
    with _lock:
        return [dict(r) for r in _timeline]


# -- bounded-cardinality key families ---------------------------------------


class CappedKeys:
    """Cardinality cap for metric-name families keyed by an UNBOUNDED
    id (matrix fingerprints, tenant ids): the registry is a plain dict,
    so a churning id stream would otherwise leak one key per distinct
    id forever.  The first ``cap`` distinct ids are tracked —
    :meth:`track` returns True and the caller emits its per-id metrics
    — later ids return False and the caller routes the event into one
    overflow counter instead.  Thread-safe; one instance per family
    (serve.factor_cache.fp.*, serve.tenant.*)."""

    __slots__ = ("cap", "_seen", "_lock")

    def __init__(self, cap: int):
        self.cap = int(cap)
        self._seen: set = set()
        self._lock = threading.Lock()

    def track(self, key: str) -> bool:
        """True when ``key`` may emit per-key metrics (already tracked,
        or tracked now because the family is under its cap)."""
        with self._lock:
            if key in self._seen:
                return True
            if len(self._seen) < self.cap:
                self._seen.add(key)
                return True
            return False

    def __len__(self) -> int:
        return len(self._seen)

    def reset(self) -> None:
        with self._lock:
            self._seen.clear()


# -- histograms (fixed log-spaced buckets; the tail-latency primitive) ------

#: bucket lattice: 10 buckets per decade from 1 µs to 1000 s.  FIXED for
#: every histogram so JSONL dumps from different runs/replicas merge
#: bucket-by-bucket (the Prometheus argument), and recording is one
#: log10 + one list increment — no per-observation allocation.
HIST_PER_DECADE = 10
HIST_LO_S = 1e-6
HIST_EDGES = tuple(
    HIST_LO_S * 10.0 ** (i / HIST_PER_DECADE)
    for i in range(9 * HIST_PER_DECADE + 1)
)


class Histogram:
    """Fixed-bucket log-spaced histogram of seconds.  Bucket 0 is the
    underflow (< ``HIST_LO_S``), bucket ``i`` covers
    ``[EDGES[i-1], EDGES[i])``, the last bucket is the overflow.
    ``percentile`` interpolates geometrically inside the winning bucket
    and clamps to the observed min/max, so p50/p95/p99 are accurate to
    one bucket ratio (~26%) worst-case, exact at the extremes."""

    __slots__ = ("counts", "count", "total", "min", "max")

    def __init__(self):
        self.counts = [0] * (len(HIST_EDGES) + 1)
        self.count = 0
        self.total = 0.0
        self.min = math.inf
        self.max = 0.0

    def observe(self, seconds: float) -> None:
        v = max(float(seconds), 0.0)
        if v < HIST_LO_S:
            i = 0
        else:
            i = min(
                int(math.log10(v / HIST_LO_S) * HIST_PER_DECADE) + 1,
                len(HIST_EDGES),
            )
        self.counts[i] += 1
        self.count += 1
        self.total += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)

    @staticmethod
    def percentile_from(counts, p: float, lo: Optional[float] = None,
                        hi: Optional[float] = None) -> Optional[float]:
        """p-th percentile (0..100) from a bucket-count list laid out on
        ``HIST_EDGES`` (the shared static so :class:`deltas` and
        tools/latency_report.py rank windows/dumps the same way)."""
        total = sum(counts)
        if total <= 0:
            return None
        rank = max(1, math.ceil(p / 100.0 * total))
        cum = 0
        for i, k in enumerate(counts):
            cum += k
            if cum >= rank:
                if i == 0:
                    # underflow bucket: the observed min (when known) is
                    # strictly better than the lattice floor
                    est = lo if lo is not None else HIST_LO_S
                elif i >= len(HIST_EDGES):
                    est = hi if hi is not None else HIST_EDGES[-1]
                else:
                    b_lo, b_hi = HIST_EDGES[i - 1], HIST_EDGES[i]
                    frac = (rank - (cum - k)) / max(k, 1)
                    est = b_lo * (b_hi / b_lo) ** frac
                if lo is not None:
                    est = max(est, lo)
                if hi is not None:
                    est = min(est, hi)
                return est
        return None  # unreachable: cum == total >= rank

    def percentile(self, p: float) -> Optional[float]:
        return self.percentile_from(
            self.counts, p,
            lo=(self.min if self.count else None),
            hi=(self.max if self.count else None),
        )

    def summary(self) -> dict:
        return {
            "count": self.count,
            "total_s": round(self.total, 6),
            "min_s": round(self.min, 6) if self.count else 0.0,
            "max_s": round(self.max, 6),
            "p50": round(self.percentile(50) or 0.0, 6),
            "p95": round(self.percentile(95) or 0.0, 6),
            "p99": round(self.percentile(99) or 0.0, 6),
        }

    def bucket_rows(self) -> List[list]:
        """Nonzero ``[le, count]`` rows (le = bucket upper edge;
        ``"inf"`` for the overflow bucket) — the JSONL wire form."""
        rows = []
        for i, k in enumerate(self.counts):
            if not k:
                continue
            le = (
                "inf" if i >= len(HIST_EDGES)
                else float(f"{HIST_EDGES[min(i, len(HIST_EDGES) - 1)]:.9g}")
            )
            rows.append([le, k])
        return rows


def observe_hist(name: str, seconds: float) -> None:
    """Record one duration into the named histogram (log-spaced fixed
    buckets).  One bool check when metrics are off."""
    if not _enabled:
        return
    with _lock:
        h = _hists.get(name)
        if h is None:
            h = _hists[name] = Histogram()
        h.observe(seconds)


def percentile(name: str, p: float) -> Optional[float]:
    """p-th percentile (0..100) of a histogram; None when absent."""
    with _lock:
        h = _hists.get(name)
        return h.percentile(p) if h is not None else None


def hist_summary(name: str) -> Optional[dict]:
    """count/total/min/max/p50/p95/p99 of one histogram (None if
    absent) — what ``health()`` surfaces per bucket."""
    with _lock:
        h = _hists.get(name)
        return h.summary() if h is not None and h.count else None


def histograms() -> Dict[str, dict]:
    with _lock:
        return {k: h.summary() for k, h in _hists.items() if h.count}


def _hist_counts() -> Dict[str, tuple]:
    """Raw (counts, count, total) snapshot — the deltas window state."""
    with _lock:
        return {
            k: (tuple(h.counts), h.count, h.total)
            for k, h in _hists.items()
        }


def _emit_event(name: str, start: float, stop: float, kind: str,
                extra: Optional[dict] = None) -> None:
    """Append a timeline event (and mirror it onto trace's timeline so
    finish("trace.svg") shows metrics phases too)."""
    global _dropped_events
    ev = {
        "name": name,
        "kind": kind,
        "t_start": round(start - (_t0 or start), 6),
        "dur_s": round(stop - start, 6),
        "thread": threading.get_ident(),
    }
    ctx = getattr(_context, "label", None)
    if ctx:
        ev["context"] = ctx
    if extra:
        ev["extra"] = extra
    with _lock:
        if len(_events) < _MAX_EVENTS:
            _events.append(ev)
        else:
            _dropped_events += 1
    if _trace.is_on():
        with _trace._lock:
            _trace._events.append(_trace.Event(
                name, start, stop, threading.get_ident()))
    if _spans.is_on():
        # one flight recorder: metric events (driver phases, per-bucket
        # compile/run dispatches) land on the span ring so a Chrome
        # export shows them in the same lanes as the request spans
        _spans.record(name, start, stop, kind=kind)


class phase:
    """Context manager timing one phase: updates the named timer, appends
    a timeline event, and (if tracing is on) a trace.Event.

    ``always=True`` measures even with metrics off (for callers that
    need ``.seconds`` as a return value, e.g. heev_staged's stage dict)
    but only *records* when metrics are on.
    """

    __slots__ = ("name", "kind", "always", "seconds", "_start")

    def __init__(self, name: str, kind: str = "phase", always: bool = False):
        self.name = name
        self.kind = kind
        self.always = always
        self.seconds = 0.0
        self._start = 0.0

    def __enter__(self):
        if _enabled or self.always or _trace.is_on() or _spans.is_on():
            self._start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        # _start == 0.0 means nothing was armed at __enter__ (also guards
        # against metrics/trace/spans flipping on mid-block)
        if self._start == 0.0 or not (
            _enabled or self.always or _trace.is_on() or _spans.is_on()
        ):
            return False
        stop = time.perf_counter()
        self.seconds = stop - self._start
        if _enabled:
            observe(self.name, self.seconds)
            if self.kind == "driver":
                # per-driver latency distribution: the factor/solve
                # histograms percentile() and the latency report read
                observe_hist(self.name, self.seconds)
            _emit_event(self.name, self._start, stop, self.kind)
            return False
        if _trace.is_on():
            with _trace._lock:
                _trace._events.append(_trace.Event(
                    self.name, self._start, stop, threading.get_ident()))
        if _spans.is_on():
            _spans.record(self.name, self._start, stop, kind=self.kind)
        return False


class context:
    """Tag every event recorded inside with a label (tester/bench entry
    names), so a JSONL from a sweep is attributable per entry."""

    def __init__(self, label: str):
        self.label = label
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_context, "label", None)
        _context.label = self.label
        return self

    def __exit__(self, *exc):
        _context.label = self._prev
        return False


class deltas:
    """Counter-delta window: snapshot on enter, ``d.get(name)`` reads the
    live increment since.  The serving tests/bench use it to assert
    "no compiles in steady state" without global resets::

        with metrics.deltas() as d:
            ...
        assert d.get("jit.compilations") == 0
    """

    def __enter__(self):
        self._before = counters()
        self._hbefore = _hist_counts()
        return self

    def __exit__(self, *exc):
        return False

    def get(self, name: str) -> float:
        return counters().get(name, 0) - self._before.get(name, 0)

    def hist(self, name: str) -> Optional[dict]:
        """Windowed histogram stats: count/total/p50/p95/p99 over the
        observations recorded since __enter__ (bucket-count deltas —
        bench entries report per-entry tail latency without a global
        reset).  None when nothing landed in the window."""
        cur = _hist_counts().get(name)
        if cur is None:
            return None
        before = self._hbefore.get(name)
        if before is None:
            counts = list(cur[0])
            dc, dt = cur[1], cur[2]
        else:
            counts = [a - b for a, b in zip(cur[0], before[0])]
            dc, dt = cur[1] - before[1], cur[2] - before[2]
        if dc <= 0:
            return None
        return {
            "count": dc,
            "total_s": round(dt, 6),
            "p50": round(Histogram.percentile_from(counts, 50) or 0.0, 6),
            "p95": round(Histogram.percentile_from(counts, 95) or 0.0, 6),
            "p99": round(Histogram.percentile_from(counts, 99) or 0.0, 6),
        }

    def all(self) -> Dict[str, float]:
        now = counters()
        keys = set(now) | set(self._before)
        out = {
            k: now.get(k, 0) - self._before.get(k, 0) for k in sorted(keys)
        }
        return {k: v for k, v in out.items() if v}


def instrumented(name: str) -> Callable:
    """Decorator: record one phase per driver call (wall time, both
    timelines).  With metrics AND tracing off, the overhead is one bool
    check per call — the drop-in successor of ``trace.traced``."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kw):
            if not _enabled and not _trace.is_on() and not _spans.is_on():
                return fn(*args, **kw)
            import jax

            # calls inlined into an outer jit trace would record trace
            # wall time as a driver phase — pass through with a counter
            # instead (same rule as instrument_jit/gated_jit)
            if any(isinstance(a, jax.core.Tracer)
                   for a in jax.tree_util.tree_leaves((args, kw))):
                inc(f"{name}.traced_calls")
                return fn(*args, **kw)
            with phase(name, kind="driver"):
                return fn(*args, **kw)

        wrapper.__wrapped__ = fn
        return wrapper

    return deco


# ---------------------------------------------------------------------------
# jit instrumentation: compile/run split + cost_analysis attribution
# ---------------------------------------------------------------------------


def _capture_cost_enabled() -> bool:
    v = os.environ.get("SLATE_TPU_METRICS_COST")
    if v is not None:
        return v not in ("", "0")
    # default: on for CPU (the AOT second compile is cheap), OFF on
    # accelerators — over the remote-compile tunnel a second compile of a
    # large program can wedge for hours MID-entry, where no time-budget
    # check can fire (the BENCH_r05 failure mode).  SLATE_TPU_METRICS_COST=1
    # opts back in explicitly.
    try:
        import jax

        return jax.default_backend() == "cpu"
    except Exception:  # noqa: BLE001 — attribution must never break a run
        return True


def _cost_analysis(jitted, args, kw) -> Optional[dict]:
    """Cost/memory record via the AOT path (lower -> compile ->
    devmon.analyze_compiled — ONE extraction shared with the device
    telemetry plane, so this legacy capture emits the same record
    schema: flops/bytes plus the memory_analysis fields and the
    device kind the report tools key peaks on).  This compiles the
    program a second time (the dispatch cache is not shared with
    AOT), so it runs at most once per (name, signature) and only when
    SLATE_TPU_METRICS_COST is on."""
    try:
        from . import devmon  # lazy: devmon imports metrics at module load

        out = devmon.analyze_compiled(jitted.lower(*args, **kw).compile())
        if out:
            out["device_kind"] = devmon.default_device_kind()
        return out
    except Exception:  # noqa: BLE001 — attribution must never break a run
        return None


def instrument_jit(jitted, name: str, capture_cost: bool = True,
                   precompiled: bool = False):
    """Wrap a ``jax.jit`` callable: per dispatch, record wall time into
    ``<name>.compile`` (first dispatch for a new shape signature — the
    compile+trace+execute wall) or ``<name>.run`` (cached executable),
    count ``jit.compilations``, and capture ``cost_analysis`` flops/bytes
    at compile time.  Tracer arguments (calls inlined into an outer jit)
    pass straight through with only a ``<name>.traced_calls`` counter.

    ``precompiled=True`` declares the callable an already-built AOT
    executable (a ``Lowered.compile()`` result): every dispatch is a
    run, never a compile — the caller owns the compile accounting
    (bench.py's devmon capture path records it explicitly)."""
    seen_sigs = set()  # fallback signature tracking if _cache_size is absent

    def _cache_size():
        f = getattr(jitted, "_cache_size", None)
        if f is not None:
            try:
                return f()
            except Exception:  # noqa: BLE001
                return None
        return None

    @functools.wraps(getattr(jitted, "__wrapped__", jitted))
    def wrapper(*args, **kw):
        if not _enabled:
            return jitted(*args, **kw)
        import jax

        if any(isinstance(a, jax.core.Tracer)
               for a in jax.tree_util.tree_leaves((args, kw))):
            inc(f"{name}.traced_calls")
            return jitted(*args, **kw)
        before = _cache_size()
        start = time.perf_counter()
        out = jitted(*args, **kw)
        # execution barrier: without it an async backend returns a future
        # in ~1 ms and ".run" would time dispatch, not the kernel.  This
        # sync point exists only with metrics ON (the off path is
        # untouched).  Over the remote tunnel block_until_ready is a
        # lower bound (BENCH_NOTES: host readback is the true barrier) —
        # bench.py keeps its own readback barrier outside the wrapper.
        try:
            jax.block_until_ready(out)
        except Exception:  # noqa: BLE001 — metrics must never break a run
            pass
        stop = time.perf_counter()
        after = _cache_size()
        if precompiled:
            compiled = False
        elif after is not None:
            compiled = after > (before or 0)
        else:
            sig = tuple(
                (getattr(l, "shape", None), str(getattr(l, "dtype", type(l))))
                for l in jax.tree_util.tree_leaves((args, kw))
            )
            compiled = sig not in seen_sigs
            seen_sigs.add(sig)
        if compiled:
            inc("jit.compilations")
            inc(f"{name}.compilations")
            observe(f"{name}.compile", stop - start)
            extra = None
            if capture_cost and _capture_cost_enabled():
                cost = _cost_analysis(jitted, args, kw)
                if cost:
                    # one canonical store-and-gauge path with the
                    # devmon capture.  XLA's -1 "unknowable cost"
                    # sentinel is dropped by the shared extractor
                    # (devmon.analyze_compiled), so an absent key —
                    # not a raw -1 — is the registry's no-data marker
                    record_cost(name, cost)
                    extra = cost
            _emit_event(name, start, stop, "compile", extra)
        else:
            observe(f"{name}.run", stop - start)
            _emit_event(name, start, stop, "run")
        return out

    wrapper.jitted = jitted
    return wrapper


def jit(fn=None, *, name: Optional[str] = None, capture_cost: bool = True,
        **jit_kw):
    """``jax.jit`` drop-in that returns an instrumented callable:
    ``metrics.jit(f, name="potrf.kernel", static_argnums=(1,))``."""
    if fn is None:
        return functools.partial(jit, name=name, capture_cost=capture_cost,
                                 **jit_kw)
    import jax

    return instrument_jit(
        jax.jit(fn, **jit_kw),
        name or getattr(fn, "__name__", "jit"),
        capture_cost=capture_cost,
    )


def gated_jit(fn, name: str, donate_argnums=(), **jit_kw):
    """Metrics-gated jit for eager kernel call sites: with metrics OFF
    (or under tracing) the original unjitted function runs, bit-identical
    to the un-instrumented code; with metrics ON, dispatch goes through a
    lazily created instrumented jit so the compile/run split and
    cost_analysis land under `name`.  One shared helper so the gate logic
    (Tracer passthrough, lazy creation) lives in one place.

    ``donate_argnums`` is applied only on non-CPU backends (resolved at
    first dispatch): XLA:CPU does not implement donation and would warn
    on every call.  Callers must pass freshly built temporaries in
    donated positions — a donated buffer is invalidated after the call
    (drivers pass padded/mirrored copies, never user-held storage)."""
    holder: list = []

    @functools.wraps(fn)
    def gate(*args, **kw):
        if not _enabled:
            return fn(*args, **kw)
        import jax

        if any(isinstance(a, jax.core.Tracer)
               for a in jax.tree_util.tree_leaves((args, kw))):
            return fn(*args, **kw)
        if not holder:
            with _lock:  # double-check: racing first calls must not
                if not holder:  # build (and compile) the jit twice
                    kwj = dict(jit_kw)
                    if donate_argnums and jax.default_backend() != "cpu":
                        kwj["donate_argnums"] = donate_argnums
                    holder.append(instrument_jit(jax.jit(fn, **kwj), name))
        return holder[0](*args, **kw)

    return gate


def record_cost(name: str, cost: dict) -> None:
    """Record one executable's cost/memory attribution under ``name``
    (the devmon capture path: flops / bytes_accessed plus the
    memory_analysis argument/output/temp/peak byte fields), so the
    JSONL dump carries a ``{"type": "cost", ...}`` row per executable
    and :func:`costs` serves it to bench.py / the report tools.  Also
    mirrors flops/bytes onto the same gauges :func:`instrument_jit`'s
    capture would have set.  One bool check when metrics are off."""
    if not _enabled:
        return
    with _lock:
        _costs[name] = dict(cost)
    if cost.get("flops", -1) > 0:
        gauge(f"{name}.flops", cost["flops"])
    if cost.get("bytes_accessed") is not None:
        gauge(f"{name}.bytes_accessed", cost["bytes_accessed"])
    if cost.get("peak_bytes") is not None:
        gauge(f"{name}.peak_bytes", cost["peak_bytes"])


def record_factor_flops(routine: str, fl: dict) -> None:
    """Feed one factorization's schedule accounting (a dict with
    ``model``/``exec`` FLOP counts and a ``units`` shape set — see
    ops/*_kernels ``*_schedule_flops``) into the ``factor.flops_model``
    / ``factor.flops_exec`` counter pair, global and per-routine, plus
    a ``factor.<routine>.compile_units`` gauge — the waste ratio of
    every factorization schedule is then one counter read away."""
    if not _enabled:
        return
    inc("factor.flops_model", fl["model"])
    inc("factor.flops_exec", fl["exec"])
    inc(f"factor.{routine}.flops_model", fl["model"])
    inc(f"factor.{routine}.flops_exec", fl["exec"])
    gauge(f"factor.{routine}.compile_units", len(fl["units"]))


# ---------------------------------------------------------------------------
# snapshots, report, JSONL export
# ---------------------------------------------------------------------------


def counters() -> Dict[str, float]:
    with _lock:
        return dict(_counters)


def gauges() -> Dict[str, float]:
    with _lock:
        return dict(_gauges)


def timers() -> Dict[str, dict]:
    with _lock:
        return {
            k: {"count": int(v[0]), "total_s": v[1], "min_s": v[2],
                "max_s": v[3]}
            for k, v in _timers.items()
        }


def costs() -> Dict[str, dict]:
    with _lock:
        return {k: dict(v) for k, v in _costs.items()}


def summary() -> dict:
    """One structured dict with everything (bench/tester per-entry use)."""
    return {
        "counters": counters(),
        "gauges": gauges(),
        "timers": {
            k: {kk: (round(vv, 6) if isinstance(vv, float) else vv)
                for kk, vv in v.items()}
            for k, v in timers().items()
        },
        "histograms": histograms(),
        "costs": costs(),
    }


def report() -> str:
    """Human-readable summary table: timers (with achieved GFLOP/s where
    a cost_analysis capture matched the timer name), then counters."""
    with _lock:
        tsnap = {k: list(v) for k, v in _timers.items()}
        csnap = dict(_counters)
        costsnap = {k: dict(v) for k, v in _costs.items()}
        hsnap = {k: h.summary() for k, h in _hists.items() if h.count}
    lines = []
    if tsnap:
        hdr = (f"{'timer':40} {'count':>6} {'total(s)':>10} {'mean(s)':>10} "
               f"{'max(s)':>10} {'GFLOP/s':>9}")
        lines.append(hdr)
        lines.append("-" * len(hdr))
        for name in sorted(tsnap, key=lambda k: -tsnap[k][1]):
            cnt, total, mn, mx = tsnap[name]
            base = name.rsplit(".", 1)[0] if name.endswith((".run", ".compile")) else name
            gf = ""
            cost = costsnap.get(base)
            # rate only for run-time entries (compile wall is not a rate),
            # and only when the name compiled exactly once — with several
            # shape signatures the stored cost belongs to the LAST
            # compile, and flops(last)/mean(all shapes) is no real rate
            if (cost and cost.get("flops", -1) > 0
                    and not name.endswith(".compile")
                    and csnap.get(f"{base}.compilations", 0) == 1):
                mean = total / max(cnt, 1)
                if mean > 0:
                    gf = f"{cost['flops'] / mean / 1e9:9.1f}"
            lines.append(
                f"{name:40} {int(cnt):6d} {total:10.4f} "
                f"{total / max(cnt, 1):10.4f} {mx:10.4f} {gf:>9}"
            )
    if hsnap:
        lines.append("")
        hdr = (f"{'histogram':44} {'count':>6} {'p50(s)':>10} "
               f"{'p95(s)':>10} {'p99(s)':>10} {'max(s)':>10}")
        lines.append(hdr)
        lines.append("-" * len(hdr))
        for name in sorted(hsnap):
            h = hsnap[name]
            lines.append(
                f"{name:44} {h['count']:6d} {h['p50']:10.4f} "
                f"{h['p95']:10.4f} {h['p99']:10.4f} {h['max_s']:10.4f}"
            )
    if csnap:
        lines.append("")
        lines.append(f"{'counter':50} {'value':>12}")
        lines.append("-" * 63)
        for name in sorted(csnap):
            v = csnap[name]
            vs = f"{int(v)}" if float(v).is_integer() else f"{v:.3g}"
            lines.append(f"{name:50} {vs:>12}")
    return "\n".join(lines) if lines else "(no metrics recorded)"


def dump(path: Optional[str] = None) -> Optional[str]:
    """Write the full registry as JSONL: a meta line, every timeline
    event, then counter/gauge/timer/cost summary lines.  ``path``
    defaults to ``$SLATE_TPU_METRICS``.  Returns the path written (or
    None if there is nowhere to write)."""
    path = path or os.environ.get("SLATE_TPU_METRICS")
    if not path:
        return None
    with _lock:
        events = [dict(e) for e in _events]
        csnap = dict(_counters)
        gsnap = dict(_gauges)
        tsnap = {k: list(v) for k, v in _timers.items()}
        hsnap = {
            k: (h.summary(), h.bucket_rows())
            for k, h in _hists.items() if h.count
        }
        costsnap = {k: dict(v) for k, v in _costs.items()}
        tlsnap = [dict(r) for r in _timeline]
        dropped = _dropped_events
        dropped_tl = _dropped_timeline
    with open(path, "w") as f:
        meta = {"type": "meta", "schema": 1, "unix_time": time.time(),
                "pid": os.getpid()}
        if dropped:
            meta["dropped_events"] = dropped
        if dropped_tl:
            meta["dropped_timeline"] = dropped_tl
        f.write(json.dumps(meta) + "\n")
        for ev in events:
            f.write(json.dumps({"type": "event", **ev}) + "\n")
        for row in tlsnap:
            f.write(json.dumps({"type": "timeline", **row}) + "\n")
        for name in sorted(csnap):
            f.write(json.dumps(
                {"type": "counter", "name": name, "value": csnap[name]}
            ) + "\n")
        for name in sorted(gsnap):
            f.write(json.dumps(
                {"type": "gauge", "name": name, "value": gsnap[name]}
            ) + "\n")
        for name in sorted(tsnap):
            cnt, total, mn, mx = tsnap[name]
            f.write(json.dumps({
                "type": "timer", "name": name, "count": int(cnt),
                "total_s": round(total, 6), "min_s": round(mn, 6),
                "max_s": round(mx, 6),
            }) + "\n")
        for name in sorted(hsnap):
            summ, buckets = hsnap[name]
            f.write(json.dumps({
                "type": "hist", "name": name, **summ, "buckets": buckets,
            }) + "\n")
        for name in sorted(costsnap):
            f.write(json.dumps(
                {"type": "cost", "name": name, **costsnap[name]}
            ) + "\n")
    return path


def load_jsonl(path: str) -> List[dict]:
    """Parse a metrics JSONL back into a list of dicts (round-trip
    helper for tests and analysis notebooks)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


# ---------------------------------------------------------------------------
# measurement helpers (the shared methodology of bench.py and tools/)
# ---------------------------------------------------------------------------


def measure_best(fn, args, trials: int = 3, perturb=None,
                 name: Optional[str] = None) -> float:
    """Best-of wall time of a jitted scalarized call with HOST READBACK
    as the barrier (block_until_ready does not synchronize over the
    remote-dispatch tunnel — BENCH_NOTES methodology).  ``perturb(args,
    t) -> args`` varies the inputs per trial so no layer can serve a
    cached result.  Records ``<name>.best_s`` as a gauge when on."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    def _scal(leaf):
        x = jnp.asarray(leaf).ravel()
        return x[0].astype(jnp.float64) + x[-1].astype(jnp.float64)

    def scalarized(*a):
        return sum(_scal(l) for l in jax.tree_util.tree_leaves(fn(*a)))

    sj = instrument_jit(jax.jit(scalarized), name or "measure_best")
    # warmup/compile with a distinct perturbation
    float(np.asarray(sj(*(perturb(args, 17) if perturb else args))))
    best = float("inf")
    for t in range(trials):
        a = args if perturb is None else perturb(args, t)
        jax.block_until_ready(a)
        t0 = time.perf_counter()
        float(np.asarray(sj(*a)))
        best = min(best, time.perf_counter() - t0)
    if name:
        gauge(f"{name}.best_s", best)
    return best


def measure_steady(fn, *args, retries: int = 4, name: Optional[str] = None):
    """Steady-state (second-call) wall time with host readback barrier:
    compile+run once, rerun on perturbed input (the tunnel caches
    identical dispatches), read one scalar back.  The remote-compile
    service sporadically drops connections; retry with backoff.
    Returns ``(seconds, output)``."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    def run(a):
        out = fn(*a)
        s = jax.tree_util.tree_leaves(out)[0].ravel()[-1]
        float(np.asarray(s))
        return out

    import sys

    last = None
    for attempt in range(retries):
        try:
            run(args)
            break
        except Exception as e:  # noqa: BLE001 — transient tunnel failure
            last = e
            print(f"  [measure_steady retry {attempt + 1}: "
                  f"{type(e).__name__}]", file=sys.stderr, flush=True)
            time.sleep(10.0 * (attempt + 1))
    else:
        raise last
    a2 = jax.tree_util.tree_map(
        lambda x: x + jnp.asarray(1e-14, x.dtype)
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
        else x,
        args,
    )
    t0 = time.perf_counter()
    out = run(a2)
    dt = time.perf_counter() - t0
    if name:
        gauge(f"{name}.steady_s", dt)
    return dt, out


# ---------------------------------------------------------------------------
# env activation: SLATE_TPU_METRICS=/path/out.jsonl
# ---------------------------------------------------------------------------

if os.environ.get("SLATE_TPU_METRICS"):
    on()
    atexit.register(dump)
