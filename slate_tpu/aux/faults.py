"""Deterministic, seedable fault injection (the chaos layer).

The serving path has retries, deadlines, fallbacks, a supervisor, and a
circuit breaker — none of which mean anything until they are exercised
under induced failure.  This module is the induction coil: named fault
*sites* are threaded through serve/ and the driver dispatch path, and a
site that is *armed* fires per its trigger, deterministically under a
seed, so a chaos test can replay the exact failure pattern.

Sites (:data:`SITES`) and where they are checked:

    ``compile``        executable build fails
                       (``serve.cache.ExecutableCache.executable``)
    ``execute``        dispatch raises (``cache.run`` / ``direct_call``)
    ``result_corrupt`` NaN poisoned into the first batch item's output
                       (``cache.run``) / into the low-precision factor
                       (``drivers/mixed`` factor step — drives the
                       refinement into its fallback solver)
    ``latency``        injected sleep before dispatch, ``ms=`` spec key
                       (``cache.run`` / ``direct_call``)
    ``worker_death``   the service worker thread dies mid-loop with a
                       batch in flight (``service.SolverService._loop``)
    ``info_nonzero``   the first batch item's ``info`` forced nonzero,
                       ``info=`` spec key (``cache.run``); also a fake
                       nonzero factor info in the mixed drivers'
                       factor step (fallback-solver exercise)
    ``artifact_corrupt``   a loaded executable artifact's payload gets
                       one byte flipped before the checksum runs, so
                       the integrity check must catch it
                       (``serve.artifacts.ArtifactStore.load``)
    ``artifact_stale`` the load-time fingerprint is perturbed, as if
                       the artifact were written by a different
                       jaxlib/device/x64 environment (``ArtifactStore.load``)
    ``artifact_load_fail`` deserialization of a verified artifact
                       raises (``ArtifactStore.load``) — the degrade
                       ladder must fall through to a recompile
    ``factor_stale``   a factor-cache hit silently serves a factor
                       whose fingerprint no longer matches A (finite
                       but WRONG — unlike result_corrupt's NaN): the
                       hit path's residual validation must catch it,
                       bump ``serve.factor_cache.stale``, and re-solve
                       direct (``serve.service`` solve-phase dispatch)
    ``session_update`` silent corruption of a streaming session's
                       in-place Householder R update: one element is
                       perturbed to a FINITE wrong value after the
                       fold (``fabric.session.FactorSession.append``)
                       — the per-solve residual fence must catch it
                       and pay a counted refactor, never a wrong X
    ``sdc_factor``     silent data corruption in a freshly computed
                       factorization: one element of the factor is
                       perturbed to a FINITE wrong value
                       (``faults.perturb``) before the solve and the
                       cache put (``serve.service._factor_direct``) —
                       the delivery certificate (integrity/) must
                       catch the wrong X, and later hits on the
                       poisoned cached factor must fall to the
                       residual fence (``serve.factor_cache.stale``)
    ``sdc_solve``      silent data corruption in a delivered solution:
                       item 0 of a dispatched X is perturbed to a
                       FINITE wrong value after execution
                       (``serve.cache.run`` / gesv+posv
                       ``direct_call``) — models a device returning
                       plausible garbage; only delivery certification
                       (``Option.ServeIntegrity``) stands between it
                       and the client
    ``lock_contend``   injected sleep inside INSTRUMENTED lock
                       acquisitions (``aux/sync`` wrappers, armed by
                       ``SLATE_TPU_SYNC_CHECK``), ``ms=`` spec key —
                       inflates lock hold/wait times so the race
                       plane's stress runs widen the windows the
                       seeded yield points alone might not hit; inert
                       while the sync runtime is off
    ``tenant_flood``   a synthetic burst of ``burst=`` low-priority
                       requests from tenant ``"flood"`` cloning the
                       triggering request's operands is injected at
                       admission (``serve.service.SolverService._submit``
                       on a tenancy-enabled service) — the fairness
                       machinery must absorb it (token-bucket quota
                       rejections / overload shedding) without the
                       well-behaved tenants' SLO melting; joined by
                       tools/chaos_report.py against ``serve.shed`` /
                       ``serve.rejected``
    ``host_death``     one fleet worker PROCESS is SIGKILLed with
                       requests in flight (``fleet/router.py``
                       dispatch; connect-mode hosts get the
                       router-side signature of the same event) — the
                       host lifecycle must fail-fast the inflight
                       members and re-dispatch them to a live host
    ``host_partition`` fleet RPC blackhole: the bytes vanish and no
                       RST returns (``fleet/router.py`` ``_rpc``,
                       heartbeats included) — indistinguishable from a
                       timeout by design; drives the suspect -> dead
                       ladder when sustained
    ``rpc_timeout``    one fleet solve RPC times out transiently
                       (``fleet/router.py`` ``_rpc``) — absorbed by
                       the decorrelated-jitter retry ladder

Triggers (exactly one per site): probability ``p=0.2`` (seeded RNG per
site, so the fire pattern is a pure function of ``seed`` and the call
sequence), every-Nth call ``every=3``, or ``once`` (fires on the
``after=N``-th call, default the first, then never again).

Activation mirrors ``aux/metrics``: one module-level bool gates every
entry point, so with faults off each site costs a single bool check and
nothing else — production dispatch is untouched (**zero overhead when
disabled**).

::

    SLATE_TPU_FAULTS="execute:p=0.2,seed=7;worker_death:every=9" python app.py

or programmatically::

    from slate_tpu.aux import faults
    faults.arm("execute", p=0.2, seed=7)
    faults.on()
    ...
    faults.reset()

Spec grammar (``SLATE_TPU_FAULTS`` / :func:`configure`)::

    spec      := site_spec (';' site_spec)*
    site_spec := site ':' item (',' item)*
    item      := 'p=<float>' | 'every=<int>' | 'once'
               | 'after=<int>' | 'seed=<int>' | 'ms=<float>'
               | 'info=<int>' | 'burst=<int>'

Every injection increments ``faults.injected.<site>`` in the metrics
registry and the site's local stats (:func:`stats`), so
``tools/chaos_report.py`` can join injected-vs-recovered counts from a
single metrics JSONL.  Each site's recovery-counter families live in
:data:`SITE_SPECS` — the machine-readable registry the report derives
its join from and the ``fault-site`` lint rule checks call sites
against (one map, three consumers, zero drift).
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

import numpy as np

from ..exceptions import SlateError
from . import metrics


@dataclass(frozen=True)
class SiteSpec:
    """One fault site's machine-readable contract: the metric counter
    families whose sum is its recovery signal (what should have
    absorbed the injection), and whether a zero-recovery outcome is
    legitimate (``informational``).  This registry is the single
    source of truth three consumers share: :func:`arm` validates site
    names against it, ``tools/chaos_report.py`` derives its
    injected-vs-recovered join from it at runtime, and the
    ``fault-site`` lint rule checks statically that every call site is
    declared here and every recovery counter is actually emitted."""

    name: str
    recovery: Tuple[str, ...] = ()
    informational: bool = False


SITE_SPECS: Tuple[SiteSpec, ...] = (
    SiteSpec("compile", recovery=("serve.fallbacks", "serve.retries")),
    SiteSpec("execute", recovery=(
        "serve.retries", "serve.fallbacks", "serve.breaker_open",
    )),
    # the per-item direct re-solve of a corrupt batch bumps
    # serve.fallbacks, so it is part of this site's signal (and of the
    # shared-attribution overlap with compile/execute)
    SiteSpec("result_corrupt", recovery=(
        "serve.corrupt_result", "serve.fallbacks",
    )),
    # _miss_late() bumps both the split counter and the total; summing
    # them would double-count, so only the split counter is joined.
    # informational: added delay violates nothing unless requests carry
    # deadlines — a latency-only run with no deadline traffic is a
    # legitimate zero-signal outcome
    SiteSpec("latency", recovery=("serve.deadline_miss_late",),
             informational=True),
    SiteSpec("worker_death", recovery=("serve.worker_restarts",)),
    SiteSpec("info_nonzero", recovery=("serve.numerical_errors",)),
    # detection == containment for the artifact load ladder: a counted
    # rung means the bad artifact was recompiled, not served
    SiteSpec("artifact_corrupt", recovery=("serve.artifact_corrupt",)),
    SiteSpec("artifact_stale", recovery=("serve.artifact_stale",)),
    SiteSpec("artifact_load_fail", recovery=("serve.artifact_load_fail",)),
    # detection == containment for the factor-cache hit path too: a
    # counted stale means the residual validation caught the mismatched
    # factor and the item was re-solved direct, never delivered wrong
    SiteSpec("factor_stale", recovery=("serve.factor_cache.stale",)),
    # detection == containment for streaming sessions: the per-solve
    # residual fence catches a poisoned in-place R update and the
    # counted refactor rebuilds it from A — never a silent wrong X
    SiteSpec("session_update", recovery=(
        "fabric.session.fence_fail", "fabric.session.refactor",
    )),
    # detection == containment for the integrity plane: a counted
    # certificate failure means the wrong X was re-executed instead of
    # delivered (serve.integrity.recovered / a typed error — never a
    # silent wrong answer); hits on a factor poisoned by sdc_factor
    # additionally land on the factor-cache residual fence (stale)
    SiteSpec("sdc_factor", recovery=(
        "serve.integrity.fail", "serve.integrity.recovered",
        "serve.factor_cache.stale",
    )),
    SiteSpec("sdc_solve", recovery=(
        "serve.integrity.fail", "serve.integrity.recovered",
        "serve.factor_cache.stale",
    )),
    # lock-hold inflation for the race plane (aux/sync): the injected
    # sleep fires inside instrumented lock acquisitions, widening race
    # windows the seeded yield points alone might not hit.  Like
    # latency, added delay violates nothing by itself — deadline
    # traffic surfaces it through the late-miss counter, and a
    # contention-only run with no deadline traffic is a legitimate
    # zero-signal outcome
    SiteSpec("lock_contend", recovery=("serve.deadline_miss_late",),
             informational=True),
    # a synthetic tenant burst is absorbed when the admission plane
    # refused (some of) it: overload shedding, token-bucket/queue-share
    # quota rejections, or plain bounded-queue backpressure — a flood
    # with NO refusal signal means fairness never engaged and the
    # burst rode straight into the shared queue
    SiteSpec("tenant_flood", recovery=(
        "serve.shed", "serve.rejected_quota", "serve.rejected_share",
        "serve.rejected",
    )),
    # fleet-tier sites (fired in fleet/router.py): a dead host is
    # absorbed when its inflight requests were failed fast and
    # re-dispatched to a live host; a partitioned/blackholed RPC is
    # absorbed by the bounded-timeout retry ladder and, past it, the
    # same dead-host machinery
    SiteSpec("host_death", recovery=(
        "fleet.redispatched", "fleet.host_dead",
    )),
    SiteSpec("host_partition", recovery=(
        "fleet.rpc_retries", "fleet.redispatched", "fleet.host_dead",
    )),
    SiteSpec("rpc_timeout", recovery=("fleet.rpc_retries",)),
)

SITE_REGISTRY: Dict[str, SiteSpec] = {s.name: s for s in SITE_SPECS}

#: site names in declaration order (the legacy surface arm() validates
#: against; derived — never hand-edit separately from SITE_SPECS)
SITES: Tuple[str, ...] = tuple(s.name for s in SITE_SPECS)


class FaultInjected(SlateError):
    """An armed fault site fired (raised only under chaos testing —
    carries the site name so recovery paths and reports can attribute
    the failure)."""

    def __init__(self, message: str, site: str = ""):
        super().__init__(message)
        self.site = site


@dataclass
class _Site:
    """One armed site: trigger config + live counters."""

    name: str
    p: float = 0.0
    every: int = 0
    once: bool = False
    after: int = 1
    seed: int = 0
    ms: float = 1.0  # latency-site sleep duration
    info: int = 1  # info_nonzero-site injected value
    burst: int = 8  # tenant_flood-site synthetic request count
    calls: int = 0
    fired: int = 0
    rng: random.Random = field(default_factory=random.Random)


_enabled = False
_lock = threading.RLock()
_sites: Dict[str, _Site] = {}


# ---------------------------------------------------------------------------
# control
# ---------------------------------------------------------------------------


def on() -> None:
    """Enable injection (one bool flips; armed sites start evaluating)."""
    global _enabled
    _enabled = True


def off() -> None:
    global _enabled
    _enabled = False


def is_on() -> bool:
    return _enabled


def reset() -> None:
    """Disable and disarm everything (test teardown)."""
    global _enabled
    with _lock:
        _enabled = False
        _sites.clear()


def arm(
    site: str,
    p: float = 0.0,
    every: int = 0,
    once: bool = False,
    after: int = 1,
    seed: int = 0,
    ms: float = 1.0,
    info: int = 1,
    burst: int = 8,
) -> None:
    """Arm one site with exactly one trigger (p / every / once).  Does
    NOT enable injection — call :func:`on` (or let the env spec do it)."""
    if site not in SITES:
        raise ValueError(f"unknown fault site {site!r}; sites: {SITES}")
    triggers = sum((p > 0, every > 0, bool(once)))
    if triggers != 1:
        raise ValueError(
            f"{site}: exactly one trigger of p=/every=/once required"
        )
    s = _Site(
        name=site, p=float(p), every=int(every), once=bool(once),
        after=int(after), seed=int(seed), ms=float(ms), info=int(info),
        burst=int(burst),
    )
    # per-site stream: the same seed arms several sites independently
    s.rng = random.Random(f"{s.seed}:{site}")
    with _lock:
        _sites[site] = s


def disarm(site: str) -> None:
    with _lock:
        _sites.pop(site, None)


def configure(spec: str) -> None:
    """Parse the SLATE_TPU_FAULTS grammar and arm each site_spec."""
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        site, sep, items = part.partition(":")
        if not sep:
            raise ValueError(f"fault spec {part!r}: expected 'site:trigger'")
        kw: dict = {}
        for item in items.split(","):
            item = item.strip()
            if not item:
                continue
            if item == "once":
                kw["once"] = True
                continue
            k, sep, v = item.partition("=")
            k, v = k.strip(), v.strip()
            if not sep:
                raise ValueError(f"fault spec item {item!r} in {part!r}")
            if k in ("p", "ms"):
                kw[k] = float(v)
            elif k in ("every", "after", "seed", "info", "burst"):
                kw[k] = int(v)
            else:
                raise ValueError(
                    f"unknown fault spec key {k!r} in {part!r}"
                )
        arm(site.strip(), **kw)


# ---------------------------------------------------------------------------
# firing
# ---------------------------------------------------------------------------


def fire(site: str) -> Optional[_Site]:
    """Evaluate one site's trigger: returns the site record when it
    fires, None otherwise.  The per-site call counter advances on every
    evaluation, so p-mode patterns are a deterministic function of the
    seed and the call sequence."""
    if not _enabled:
        return None
    s = _sites.get(site)
    if s is None:
        return None
    with _lock:
        s.calls += 1
        if s.once:
            hit = s.calls >= s.after and s.fired == 0
        elif s.every > 0:
            hit = s.calls % s.every == 0
        else:
            hit = s.rng.random() < s.p
        if hit:
            s.fired += 1
    if hit:
        metrics.inc(f"faults.injected.{site}")
        return s
    return None


def check(site: str) -> None:
    """Raise :class:`FaultInjected` when the site fires (the compile /
    execute / worker_death call-site form)."""
    if not _enabled:
        return
    s = fire(site)
    if s is not None:
        raise FaultInjected(
            f"injected {site} fault (#{s.fired})", site=site
        )


def sleep(site: str = "latency") -> float:
    """Sleep ``ms`` milliseconds when the site fires; returns the
    seconds actually slept."""
    if not _enabled:
        return 0.0
    s = fire(site)
    if s is None:
        return 0.0
    time.sleep(s.ms / 1e3)
    return s.ms / 1e3


def corrupt(site: str, arr: np.ndarray) -> np.ndarray:
    """Return ``arr`` with its first element NaN-poisoned when the site
    fires (result_corrupt: for a batched (b, m, k) output this lands in
    item 0), unchanged otherwise."""
    if not _enabled:
        return arr
    if fire(site) is None:
        return arr
    out = np.array(arr)  # fresh writable copy — device views are read-only
    out.reshape(-1)[0] = np.nan
    return out


def perturb(site: str, arr: np.ndarray) -> np.ndarray:
    """Return ``arr`` with its first element perturbed to a FINITE but
    wrong value when the site fires (factor_stale: a silently-mismatched
    factor — NaN would trip the cheap finiteness check, which is not
    the validation under test), unchanged otherwise."""
    if not _enabled:
        return arr
    if fire(site) is None:
        return arr
    out = np.array(arr)  # fresh writable copy — cached views stay intact
    out.reshape(-1)[0] = out.reshape(-1)[0] * 2 + 1
    return out


def poison_info(site: str, info: np.ndarray) -> np.ndarray:
    """Force the first entry of an ``info`` vector to the site's
    ``info=`` value when it fires (info_nonzero: poisons exactly batch
    item 0), unchanged otherwise."""
    if not _enabled:
        return info
    s = fire(site)
    if s is None:
        return info
    out = np.array(info)
    out.reshape(-1)[0] = s.info
    return out


def stats() -> Dict[str, dict]:
    """Per-site {calls, fired} counters for every armed site."""
    with _lock:
        return {
            k: {"calls": v.calls, "fired": v.fired}
            for k, v in _sites.items()
        }


# ---------------------------------------------------------------------------
# env activation: SLATE_TPU_FAULTS="site:trigger[,k=v]*;..."
# ---------------------------------------------------------------------------

_env_spec = os.environ.get("SLATE_TPU_FAULTS")
if _env_spec:
    # fail loud but name the knob: silently disarming a chaos spec the
    # operator believes is active would be worse than refusing to start
    try:
        configure(_env_spec)
    except (ValueError, TypeError) as e:
        raise ValueError(f"SLATE_TPU_FAULTS={_env_spec!r}: {e}") from e
    on()
