"""Execution tracing with SVG timeline output (reference:
include/slate/internal/Trace.hh:24-110 — RAII trace::Block pushing
Event{name, start, stop, thread}; src/auxiliary/Trace.cc:330-370 —
per-rank gather + SVG timeline with a color legend, one row per thread).

TPU mapping: the reference traces OpenMP tasks on host threads; here the
interesting rows are *driver phases* on the host timeline (each jit
dispatch, including its compile on first call) plus optional XLA device
profiling.  Zero overhead when disabled (one bool check), like the
reference's static `Trace::on_`.

    from slate_tpu.aux import trace
    trace.on()
    with trace.Block("potrf"):
        L, info = st.potrf(A)
    trace.finish("trace.json")         # Chrome trace-event JSON
    trace.finish("trace.svg")          # legacy SVG timeline

    with trace.xla_profile("/tmp/prof"):   # jax.profiler device trace
        ...

Drivers annotated with @trace.traced("name") record automatically.

The documented output is now the **Chrome trace-event JSON** (load in
Perfetto / chrome://tracing — one lane per thread/replica, zoomable,
with span attrs): ``finish()`` defaults to it, and ``Block``/``traced``
mirror every interval onto the ``aux/spans`` ring buffer whenever that
layer is on, so driver phases and request-lifecycle spans share one
flight recorder.  A ``.svg`` path keeps the legacy self-contained SVG
renderer.
"""

from __future__ import annotations

import functools
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass
from typing import List, Optional

from . import spans as _spans

_enabled = False
_events: List["Event"] = []
_lock = threading.Lock()
_t0: Optional[float] = None


@dataclass
class Event:
    """One traced interval on the legacy flat event list.

    .. deprecated:: PR 9
        The unbounded ``trace._events`` list is superseded by the
        ``aux/spans`` ring buffer (bounded, trace-id aware, Chrome
        exportable).  ``Block``/``traced`` already mirror onto it;
        new code should read ``spans.snapshot()`` instead of
        ``trace._events``, which is kept only for the SVG renderer
        and back-compat consumers.
    """

    name: str
    start: float
    stop: float
    thread: int


def on() -> None:
    """Enable tracing (reference: Trace::on, Trace.hh:41)."""
    global _enabled, _t0
    _enabled = True
    if _t0 is None:
        _t0 = time.perf_counter()


def off() -> None:
    global _enabled
    _enabled = False


def is_on() -> bool:
    return _enabled


def clear() -> None:
    global _events, _t0
    with _lock:
        _events = []
        _t0 = None


class Block:
    """RAII trace block (reference: trace::Block, Trace.hh:24-38)."""

    __slots__ = ("name", "_start")

    def __init__(self, name: str):
        self.name = name
        self._start = 0.0

    def __enter__(self):
        if _enabled or _spans.is_on():
            self._start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._start == 0.0:
            return False
        stop = time.perf_counter()
        if _enabled:
            ev = Event(self.name, self._start, stop, threading.get_ident())
            with _lock:
                _events.append(ev)
        if _spans.is_on():
            # unified recorder: trace blocks are spans too, so one
            # export_chrome() carries driver phases AND request spans
            _spans.record(self.name, self._start, stop)
        self._start = 0.0
        return False


def traced(name: str):
    """Decorator: trace a driver call when tracing is on (the reference
    annotates impl:: functions the same way, e.g. gemmC.cc:48)."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kw):
            if not _enabled and not _spans.is_on():
                return fn(*args, **kw)
            with Block(name):
                return fn(*args, **kw)

        return wrapper

    return deco


@contextmanager
def xla_profile(log_dir: str):
    """Device-level XLA trace via jax.profiler (view with TensorBoard /
    xprof) — the TPU analogue of the reference's per-GPU rows."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


_PALETTE = [
    "#4878CF", "#D65F5F", "#6ACC65", "#B47CC7", "#C4AD66", "#77BEDB",
    "#EE854A", "#8C613C", "#DC7EC0", "#797979",
]


def finish(path: str = "trace.json", width: int = 1200) -> str:
    """Write the recorded timeline and return the path (clears nothing;
    call clear() to reset).

    The default (any non-``.svg`` path) is **Chrome trace-event JSON**:
    the legacy event list and the ``aux/spans`` ring are merged into
    one ``traceEvents`` array — load it in Perfetto /
    chrome://tracing.  A path ending in ``.svg`` keeps the reference's
    self-contained SVG renderer (Trace::finish, Trace.cc:330-370: one
    row per thread, legend below) over the legacy event list only."""
    with _lock:
        events = list(_events)
    if not path.endswith(".svg"):
        return _spans.export_chrome(path, extra=events)
    if not events:
        open(path, "w").write("<svg xmlns='http://www.w3.org/2000/svg'/>")
        return path
    t_min = min(e.start for e in events)
    t_max = max(e.stop for e in events)
    span = max(t_max - t_min, 1e-9)
    threads = sorted({e.thread for e in events})
    names = sorted({e.name for e in events})
    color = {n: _PALETTE[i % len(_PALETTE)] for i, n in enumerate(names)}
    row_h, pad = 28, 6
    legend_h = 20 * ((len(names) + 3) // 4) + 10
    height = len(threads) * (row_h + pad) + 40 + legend_h
    out = [
        f"<svg xmlns='http://www.w3.org/2000/svg' width='{width}' "
        f"height='{height}' font-family='monospace' font-size='11'>",
        f"<text x='4' y='14'>slate_tpu trace — {span:.3f}s, "
        f"{len(events)} events</text>",
    ]
    for row, th in enumerate(threads):
        y = 24 + row * (row_h + pad)
        out.append(
            f"<text x='4' y='{y + row_h / 2 + 4}' fill='#555'>t{row}</text>"
        )
        for e in (ev for ev in events if ev.thread == th):
            x = 40 + (e.start - t_min) / span * (width - 50)
            w = max((e.stop - e.start) / span * (width - 50), 1.0)
            out.append(
                f"<rect x='{x:.1f}' y='{y}' width='{w:.1f}' height='{row_h}'"
                f" fill='{color[e.name]}' stroke='#333' stroke-width='0.5'>"
                f"<title>{e.name}: {e.stop - e.start:.4f}s</title></rect>"
            )
    ly = 24 + len(threads) * (row_h + pad) + 10
    for i, n in enumerate(names):
        lx = 40 + (i % 4) * (width // 4)
        lyy = ly + (i // 4) * 20
        out.append(
            f"<rect x='{lx}' y='{lyy}' width='12' height='12' fill='{color[n]}'/>"
            f"<text x='{lx + 16}' y='{lyy + 10}'>{n}</text>"
        )
    out.append("</svg>")
    open(path, "w").write("\n".join(out))
    return path
