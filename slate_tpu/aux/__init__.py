"""Auxiliary subsystems (reference: src/auxiliary/ — Trace, Debug).

- aux.trace: RAII phase tracing + SVG/Chrome timeline + jax.profiler
  hook.
- aux.metrics: counters/gauges/timers/histograms registry,
  compile-vs-execute split, cost_analysis FLOP attribution, JSONL
  export (SLATE_TPU_METRICS=/path/out.jsonl).
- aux.spans: request-scoped span tracer — trace ids, parent/child
  spans, bounded ring-buffer flight recorder
  (SLATE_TPU_TRACE_RING=N), Chrome trace-event export for Perfetto.
- aux.faults: deterministic seedable fault injection over named sites
  in the serve/driver dispatch path (SLATE_TPU_FAULTS spec).
- aux.devmon: device telemetry plane — per-executable cost/memory
  capture (cost_analysis + memory_analysis at build time), per-device
  memory gauges with graceful None on backends without memory_stats,
  and the roofline peaks table (SLATE_TPU_PEAKS override); armed by
  SLATE_TPU_DEVMON=1, one bool per call site when off.
"""

from . import devmon, faults, metrics, spans, trace  # noqa: F401
