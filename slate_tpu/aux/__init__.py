"""Auxiliary subsystems (reference: src/auxiliary/ — Trace, Debug).

- aux.trace: RAII phase tracing + SVG/Chrome timeline + jax.profiler
  hook.
- aux.metrics: counters/gauges/timers/histograms registry,
  compile-vs-execute split, cost_analysis FLOP attribution, JSONL
  export (SLATE_TPU_METRICS=/path/out.jsonl).
- aux.spans: request-scoped span tracer — trace ids, parent/child
  spans, bounded ring-buffer flight recorder
  (SLATE_TPU_TRACE_RING=N), Chrome trace-event export for Perfetto.
- aux.faults: deterministic seedable fault injection over named sites
  in the serve/driver dispatch path (SLATE_TPU_FAULTS spec).
"""

from . import faults, metrics, spans, trace  # noqa: F401
