"""Auxiliary subsystems (reference: src/auxiliary/ — Trace, Debug).

- aux.trace: RAII phase tracing + SVG timeline + jax.profiler hook.
- aux.metrics: counters/gauges/timers registry, compile-vs-execute
  split, cost_analysis FLOP attribution, JSONL export
  (SLATE_TPU_METRICS=/path/out.jsonl).
"""

from . import metrics, trace  # noqa: F401
