"""Auxiliary subsystems (reference: src/auxiliary/ — Trace, Debug).

- aux.trace: RAII phase tracing + SVG/Chrome timeline + jax.profiler
  hook.
- aux.metrics: counters/gauges/timers/histograms registry,
  compile-vs-execute split, cost_analysis FLOP attribution, JSONL
  export (SLATE_TPU_METRICS=/path/out.jsonl).
- aux.spans: request-scoped span tracer — trace ids, parent/child
  spans, bounded ring-buffer flight recorder
  (SLATE_TPU_TRACE_RING=N), Chrome trace-event export for Perfetto.
- aux.faults: deterministic seedable fault injection over named sites
  in the serve/driver dispatch path (SLATE_TPU_FAULTS spec).
- aux.devmon: device telemetry plane — per-executable cost/memory
  capture (cost_analysis + memory_analysis at build time), per-device
  memory gauges with graceful None on backends without memory_stats,
  and the roofline peaks table (SLATE_TPU_PEAKS override); armed by
  SLATE_TPU_DEVMON=1, one bool per call site when off.
- aux.sync: instrumented Lock/RLock/Condition runtime — Eraser-style
  lockset checking over `# guarded by:` fields, live lock-order cycle
  detection with both stacks of an inversion, happens-before hand-off
  edges (Condition wait/notify, Future resolution), and seeded
  replayable yield points; armed by SLATE_TPU_SYNC_CHECK=1, plain
  threading primitives (zero overhead) when off.
"""

from . import devmon, faults, metrics, spans, sync, trace  # noqa: F401
