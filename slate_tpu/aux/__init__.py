"""Auxiliary subsystems (reference: src/auxiliary/ — Trace, Debug).

- aux.trace: RAII phase tracing + SVG timeline + jax.profiler hook.
- aux.metrics: counters/gauges/timers registry, compile-vs-execute
  split, cost_analysis FLOP attribution, JSONL export
  (SLATE_TPU_METRICS=/path/out.jsonl).
- aux.faults: deterministic seedable fault injection over named sites
  in the serve/driver dispatch path (SLATE_TPU_FAULTS spec).
"""

from . import faults, metrics, trace  # noqa: F401
