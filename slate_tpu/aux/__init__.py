"""Auxiliary subsystems (reference: src/auxiliary/ — Trace, Debug).

- aux.trace: RAII phase tracing + SVG timeline + jax.profiler hook.
"""

from . import trace  # noqa: F401
