"""Instrumented synchronization runtime: the dynamic half of the race
and deadlock detection plane (``slate_tpu/analysis/races.py`` is the
static half).

The serve tier is a deeply threaded system — replica worker pools,
hedge clones sharing futures, quarantine probes, WFQ admission,
background restore, graceful drain — and CHANGES.md shows concurrency
is where review passes keep catching real bugs.  The ``# guarded by:``
annotations are checked statically by slate-lint; this module checks
the SAME contracts at runtime, under real interleavings:

* **Drop-in lock wrappers** — :func:`Lock` / :func:`RLock` /
  :func:`Condition` return the plain ``threading`` primitives when the
  runtime is off (construction-time decision: steady state pays
  literally nothing), or checked wrappers when
  ``SLATE_TPU_SYNC_CHECK=1`` armed the plane.  Wrappers record each
  thread's held-lock set and the global acquisition-order graph.
* **Lock-order cycle detection** — acquiring B while holding A records
  the edge ``A -> B`` with the acquiring stack.  An acquisition that
  closes a cycle (``B -> ... -> A`` already recorded) is a potential
  deadlock: the violation carries BOTH stacks — the one that
  established the original ordering and the one that inverted it — so
  the fix is a diff away, not a core-dump away.
* **Eraser-style lockset checking** (Savage et al., SOSP '97) — shared
  fields annotated ``# guarded by:`` carry a ``guarded(obj, "field")``
  probe at their hot access sites (a no-op bool when off, like
  metrics/spans/faults).  Per field, the checker intersects the
  accessing threads' held-lock sets; an empty intersection on an
  unordered cross-thread access means NO lock consistently protects
  the field — reported with the two access stacks.
* **Happens-before hand-off edges** — pure lockset checking
  false-positives on hand-off patterns (a worker resolves a Future
  another thread then reads; a producer publishes under notify and
  the consumer reads after wait).  Condition ``notify``/``wait`` and
  :func:`hb_publish` / :func:`hb_receive` (threaded through Future
  resolution in ``serve/service.py``) record release/acquire edges:
  an access ordered after the previous one by such an edge transfers
  ownership instead of refining the lockset.
* **Seeded interleaving perturbation** (CHESS-flavored, Musuvathi et
  al., OSDI '08) — with ``yield=<p>`` in the spec, each lock
  acquisition flips a seeded per-thread coin and sleeps ``yield_us``
  microseconds on heads, widening race windows.  The coin sequence is
  a pure function of ``seed`` and the thread's name + acquisition
  sequence, so a failing schedule replays under the same spec.  The
  ``lock_contend`` fault site (aux/faults) adds targeted hold-time
  inflation on top.

Spec grammar (``SLATE_TPU_SYNC_CHECK`` / :func:`configure`)::

    SLATE_TPU_SYNC_CHECK=1                          # checks on
    SLATE_TPU_SYNC_CHECK=1,seed=7,yield=0.2,yield_us=200

Violations are recorded (never raised — the instrumented service must
keep serving so one stress run reports EVERY inversion, not the first)
and surfaced three ways: :func:`violations` / :func:`report` for
in-process asserts, :func:`dump` for the JSON file
``tools/race_report.py`` judges, and the
``sync.violation.{lock_order,lockset}`` metric counters for JSONL
joins.

Zero overhead off: every public entry point is one module-bool check,
and the factories return plain ``threading`` objects — the serve tier
with the plane unarmed is byte-identical to the pre-sync tier.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
import traceback
import weakref
from typing import Dict, List, Optional, Set, Tuple

from . import metrics

SYNC_ENV = "SLATE_TPU_SYNC_CHECK"

_enabled = False
_seed = 0
_yield_p = 0.0
_yield_us = 200.0

#: guards every global table below (edge graph, field states, hand-off
#: records, violations).  A plain threading.Lock on purpose: the
#: checker must never instrument itself.
_state = threading.Lock()

# (from, to) -> first-seen acquiring stack (the edge's provenance)
_edges: Dict[Tuple[str, str], str] = {}
_adj: Dict[str, Set[str]] = {}
_violations: List[dict] = []
_inversions_seen: Set[Tuple[str, str]] = set()
# (id(obj), field) -> _FieldState.  id-keyed, but NOT alias-tolerant:
# short-lived probed objects (hedge groups — one per straggler clone)
# die and CPython reuses the address, so a stale state whose lockset
# was refined to the DEAD object's lock would empty-intersect the new
# object's lock and report a false positive.  Each state pins a
# weakref whose death callback queues the key for removal (_dead,
# drained under _state — the callback itself must never take the lock:
# a GC triggered while _state is held would deadlock)
_fields: Dict[Tuple[int, str], "_FieldState"] = {}
_dead: List[Tuple[int, str]] = []
# every Class.field label ever probed — CUMULATIVE, unlike _fields
# whose entries die with their objects: coverage assertions (the
# --race stress gate) must not depend on a short-lived hedge group
# surviving until the dump
_probed_names: Set[str] = set()
# id(obj) -> (publishing thread ident, publisher clock at release).
# Insertion-ordered and FIFO-capped: a long armed run resolves a Future
# per request and nothing ever unpublishes, so without the cap this
# table grows unboundedly.  Evicting an old record can only SUPPRESS a
# hand-off edge, i.e. risk a false positive on a reader arriving after
# _RELEASES_CAP further publishes — acceptable for a debug runtime
_releases: Dict[int, Tuple[int, int]] = {}
_RELEASES_CAP = 4096


class _TLS(threading.local):
    def __init__(self):
        self.held: List[list] = []  # [lock wrapper, reentry count]
        self.clock = 0  # advances at each hb publish
        self.received: Dict[int, int] = {}  # thread ident -> clock
        self.rng: Optional[random.Random] = None


_tls = _TLS()


class _FieldState:
    __slots__ = (
        "name", "last_thread", "last_clock", "lockset", "stack", "reported",
        "wref",
    )

    def __init__(self, name: str, thread: int, clock: int, stack: str):
        self.name = name
        self.last_thread = thread
        self.last_clock = clock
        self.lockset: Optional[Set[int]] = None  # None = exclusive so far
        self.stack = stack
        self.reported = False
        self.wref = None  # keeps the id-reuse death callback alive


# ---------------------------------------------------------------------------
# control
# ---------------------------------------------------------------------------


def on() -> None:
    """Enable the checks (one bool flips).  Locks constructed BEFORE
    arming stay plain — arm first (the env path does), then build."""
    global _enabled
    _enabled = True


def off() -> None:
    global _enabled
    _enabled = False


def is_on() -> bool:
    return _enabled


def reset() -> None:
    """Disable and clear every table (test teardown) — the faults.reset
    shape.  Per-thread held lists are left alone: wrappers keep their
    release bookkeeping consistent even across a reset."""
    global _enabled
    with _state:
        _enabled = False
        _edges.clear()
        _adj.clear()
        _violations.clear()
        _inversions_seen.clear()
        _fields.clear()
        del _dead[:]
        _probed_names.clear()
        _releases.clear()


def configure(spec: str) -> bool:
    """Parse the :data:`SYNC_ENV` grammar and arm the runtime; returns
    whether it armed.  ``""``/``0``/``off`` disarm (False); ``1``/``on``
    arm with defaults; extra ``seed=``/``yield=``/``yield_us=`` items
    tune the interleaving perturbation."""
    global _seed, _yield_p, _yield_us
    spec = (spec or "").strip()
    if not spec or spec.lower() in ("0", "off", "false", "no"):
        off()
        return False
    items = [it.strip() for it in spec.split(",") if it.strip()]
    head = items[0].lower()
    if head not in ("1", "on", "true", "yes"):
        raise ValueError(
            f"expected 1|on followed by seed=/yield=/yield_us=, got "
            f"{items[0]!r}"
        )
    # "1" means DEFAULTS, not whatever a previous configure() in this
    # process left behind — a run armed plain must not inherit stale
    # perturbation tuning (and report() must describe the real spec)
    _seed, _yield_p, _yield_us = 0, 0.0, 200.0
    for item in items[1:]:
        k, sep, v = item.partition("=")
        k, v = k.strip().lower(), v.strip()
        if not sep:
            raise ValueError(f"expected k=v, got {item!r}")
        if k == "seed":
            _seed = int(v)
        elif k == "yield":
            _yield_p = float(v)
            if not 0.0 <= _yield_p <= 1.0:
                raise ValueError(f"yield probability out of [0, 1]: {v}")
        elif k == "yield_us":
            _yield_us = float(v)
        else:
            raise ValueError(
                f"unknown key {k!r} (seed=|yield=|yield_us=)"
            )
    on()
    return True


# ---------------------------------------------------------------------------
# internals shared by the wrappers
# ---------------------------------------------------------------------------


def _stack(skip: int = 2) -> str:
    """The current stack (probe/wrapper frames trimmed), newest last."""
    return "".join(traceback.format_stack()[:-skip])


def _maybe_yield() -> None:
    """The CHESS-flavored perturbation: a seeded per-thread coin per
    acquisition; heads sleeps ``yield_us``.  Each thread draws from a
    ``Random(seed x thread name)`` stream, one draw per acquisition —
    so the coin is a pure function of (seed, thread name, acquisition
    index) and a schedule that exposed a race replays under the same
    spec."""
    if _yield_p <= 0.0:
        return
    tls = _tls
    if tls.rng is None:
        tls.rng = random.Random(
            f"{_seed}:{threading.current_thread().name}"
        )
    if tls.rng.random() < _yield_p:
        time.sleep(_yield_us / 1e6)


def _record_edge(a: "_Checked", b: "_Checked") -> None:
    """Edge ``a.name -> b.name`` (b acquired while a held); an edge
    closing a cycle is a lock-order inversion, reported with the stack
    that established the original ordering AND the one inverting it."""
    an, bn = a.name, b.name
    if an == bn:
        return  # two instances from one allocation site never order
    cur = None  # build the (expensive) stack only for new edges
    with _state:
        if (an, bn) in _edges:
            return
        cur = _stack()
        _edges[(an, bn)] = cur
        _adj.setdefault(an, set()).add(bn)
        # reverse reachability bn ->* an means the new edge closes a
        # cycle; report once per unordered pair
        path = _find_path(bn, an)
        if path is None:
            return
        pair = (min(an, bn), max(an, bn))
        if pair in _inversions_seen:
            return
        _inversions_seen.add(pair)
        other = _edges.get((path[0], path[1]), "")
        _violations.append({
            "kind": "lock_order",
            "detail": (
                f"lock-order inversion: {an} -> {bn} acquired, but "
                f"{' -> '.join(path)} was already recorded"
            ),
            "locks": [an, bn],
            "cycle": path + [bn],
            "stacks": [other, cur],
        })
    metrics.inc("sync.violation.lock_order")


def _find_path(src: str, dst: str) -> Optional[List[str]]:
    """DFS path src ->* dst over the order graph (caller holds _state);
    None when unreachable."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in _adj.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _on_acquired(lock: "_Checked") -> None:
    held = _tls.held
    for ent in held:
        if ent[0] is lock:
            ent[1] += 1  # reentrant (RLock/Condition): no new edges
            return
    if _enabled:
        for ent in held:
            _record_edge(ent[0], lock)
    held.append([lock, 1])


def _on_release(lock: "_Checked") -> None:
    held = _tls.held
    for i in range(len(held) - 1, -1, -1):
        if held[i][0] is lock:
            held[i][1] -= 1
            if held[i][1] <= 0:
                del held[i]
            return


def _held_ids() -> Set[int]:
    return {id(ent[0]) for ent in _tls.held}


def _callsite_name() -> str:
    """Default lock name: the allocation site (file:line), so unnamed
    locks still aggregate per construction site in the order graph.
    Stack shape is fixed: [... caller, factory, __init__, here]."""
    fr = traceback.extract_stack(limit=4)[0]
    return f"{os.path.basename(fr.filename)}:{fr.lineno}"


# ---------------------------------------------------------------------------
# the wrappers
# ---------------------------------------------------------------------------


class _Checked:
    """Shared wrapper surface: held-set + order-graph bookkeeping
    around an inner threading primitive."""

    __slots__ = ("name", "_lk")

    def __init__(self, inner, name: Optional[str]):
        self._lk = inner
        self.name = name or _callsite_name()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if blocking:
            _maybe_yield()
            from . import faults  # late: avoid import-order surprises

            faults.sleep("lock_contend")
        ok = self._lk.acquire(blocking, timeout)
        if ok:
            _on_acquired(self)
        return ok

    def release(self) -> None:
        _on_release(self)
        self._lk.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False


class _CheckedLock(_Checked):
    __slots__ = ()

    def locked(self) -> bool:
        return self._lk.locked()


class _CheckedRLock(_Checked):
    __slots__ = ()


class _CheckedCondition:
    """Checked ``threading.Condition`` over its own RLock, with
    hand-off edges: ``notify``/``notify_all`` publish, a returning
    ``wait`` receives — so a field written before notify and read
    after wait is ordered, not a lockset violation."""

    __slots__ = ("name", "_inner", "_cond")

    def __init__(self, name: Optional[str]):
        self.name = name or _callsite_name()
        self._inner = threading.RLock()
        self._cond = threading.Condition(self._inner)

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if blocking:
            _maybe_yield()
            from . import faults

            faults.sleep("lock_contend")
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            _on_acquired(self)
            # receive the latest publish at ACQUIRE too, not only at a
            # wait() return: notify runs under this lock, so any
            # publish visible here is lock-ordered before us — without
            # this, a consumer that finds its predicate already true
            # (producer notified before the consumer entered the
            # with-block) never waits, never receives, and the
            # documented hand-off pattern false-positives the lockset
            # checker
            hb_receive(self)
        return ok

    def release(self) -> None:
        _on_release(self)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def wait(self, timeout: Optional[float] = None):
        # wait() drops the lock while blocked: the held set must agree,
        # or every waiter would deadlock the lockset/order accounting
        _on_release(self)
        try:
            got = self._cond.wait(timeout)
        finally:
            _on_acquired(self)
        # receive the latest publish even on a timeout wake: an
        # over-approximated hand-off can only SUPPRESS reports (this
        # checker is false-positive-averse by design)
        hb_receive(self)
        return got

    def wait_for(self, predicate, timeout: Optional[float] = None):
        # delegate to wait() so the held-set/hand-off bookkeeping
        # applies per wakeup, mirroring threading.Condition.wait_for
        endtime = None
        result = predicate()
        while not result:
            if timeout is not None:
                if endtime is None:
                    endtime = time.monotonic() + timeout
                waittime = endtime - time.monotonic()
                if waittime <= 0:
                    break
                self.wait(waittime)
            else:
                self.wait()
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        hb_publish(self)
        self._cond.notify(n)

    def notify_all(self) -> None:
        hb_publish(self)
        self._cond.notify_all()


def Lock(name: Optional[str] = None):
    """A mutex: plain ``threading.Lock`` when the runtime is off (the
    construction-time zero-overhead decision), a checked wrapper when
    armed.  ``name`` labels the lock in the order graph and reports —
    name per ALLOCATION SITE (all instances share the node), which is
    what lock-order analysis wants."""
    if not _enabled:
        return threading.Lock()
    return _CheckedLock(threading.Lock(), name)


def RLock(name: Optional[str] = None):
    if not _enabled:
        return threading.RLock()
    return _CheckedRLock(threading.RLock(), name)


def Condition(name: Optional[str] = None):
    if not _enabled:
        return threading.Condition()
    return _CheckedCondition(name)


# ---------------------------------------------------------------------------
# happens-before hand-off edges
# ---------------------------------------------------------------------------


def hb_publish(obj) -> None:
    """Record a release edge on ``obj`` (a Condition about to notify, a
    Future about to resolve): the publishing thread's writes so far
    happen-before any thread that later :func:`hb_receive`\\ s the same
    object.  One bool when off."""
    if not _enabled:
        return
    tls = _tls
    with _state:
        _releases.pop(id(obj), None)  # re-publish moves to newest
        _releases[id(obj)] = (threading.get_ident(), tls.clock)
        while len(_releases) > _RELEASES_CAP:
            _releases.pop(next(iter(_releases)))
    tls.clock += 1


def hb_receive(obj) -> None:
    """Record the acquire edge pairing :func:`hb_publish` (a waiter
    waking, a client reading a resolved Future's payload)."""
    if not _enabled:
        return
    with _state:
        rec = _releases.get(id(obj))
    if rec is None:
        return
    tid, clk = rec
    recv = _tls.received
    if recv.get(tid, -1) < clk:
        recv[tid] = clk


# ---------------------------------------------------------------------------
# the lockset checker
# ---------------------------------------------------------------------------


def guarded(obj, field: str, write: bool = True) -> None:
    """Eraser-style lockset probe on one annotated shared field.  Call
    adjacent to the access (``sync.guarded(rep, "q")``); one bool when
    the runtime is off.

    Algorithm (per ``(obj, field)``): the first thread owns the field
    exclusively; an access from a second thread that is happens-before
    ordered after the previous access (Condition hand-off, Future
    resolution) TRANSFERS ownership; an unordered cross-thread access
    intersects the candidate lockset with the accessing thread's held
    checked locks — an empty intersection means no lock consistently
    guards the field, reported once per field with both access
    stacks."""
    if not _enabled:
        return
    tls = _tls
    t = threading.get_ident()
    violation = None
    # format the stack BEFORE taking the global lock: every probe needs
    # one retained (the previous-access half of a future report), but
    # string-formatting it under _state would serialize every
    # instrumented thread on the hot path — flattening the very
    # interleavings the seeded yields exist to widen
    stk = _stack()
    with _state:
        while _dead:  # drain id-reuse invalidations queued by GC
            _fields.pop(_dead.pop(), None)
        key = (id(obj), field)
        st = _fields.get(key)
        if st is None:
            st = _FieldState(
                f"{type(obj).__name__}.{field}", t, tls.clock, stk
            )
            _probed_names.add(st.name)
            try:
                # when obj dies its address may be reused: queue the
                # state for removal (append only — taking _state from
                # a GC callback could deadlock)
                st.wref = weakref.ref(
                    obj, lambda _r, _k=key: _dead.append(_k)
                )
            except TypeError:
                pass  # not weakref-able: accept the rare alias
            _fields[key] = st
            return
        if st.last_thread != t:
            if tls.received.get(st.last_thread, -1) >= st.last_clock:
                # hand-off: ownership transfers, lockset resets — the
                # Condition/Future publication pattern is not a race
                st.lockset = None
            else:
                held = _held_ids()
                st.lockset = (
                    held if st.lockset is None else st.lockset & held
                )
                if not st.lockset and not st.reported:
                    st.reported = True
                    violation = {
                        "kind": "lockset",
                        "detail": (
                            f"unguarded shared access: {st.name} "
                            "touched by two threads with no common "
                            "lock and no happens-before edge"
                        ),
                        "field": st.name,
                        "write": bool(write),
                        "stacks": [st.stack, stk],
                    }
                    _violations.append(violation)
        st.last_thread = t
        st.last_clock = tls.clock
        st.stack = stk
    if violation is not None:
        metrics.inc("sync.violation.lockset")


# ---------------------------------------------------------------------------
# reporting
# ---------------------------------------------------------------------------


def violations() -> List[dict]:
    with _state:
        return [dict(v) for v in _violations]


def order_edges() -> List[dict]:
    """The runtime lock-order graph observed so far."""
    with _state:
        return [
            {"from": a, "to": b} for a, b in sorted(_edges)
        ]


def report() -> dict:
    """One JSON-able snapshot: violations (with stacks), the observed
    order graph, and table sizes — what :func:`dump` writes and
    ``tools/race_report.py`` judges."""
    with _state:
        return {
            "version": 1,
            "enabled": _enabled,
            "seed": _seed,
            "yield_p": _yield_p,
            "violations": [dict(v) for v in _violations],
            "edges": [
                {"from": a, "to": b} for a, b in sorted(_edges)
            ],
            "fields": len(_fields),
            # distinct Class.field labels EVER probed (cumulative, not
            # just live states) — the stress gate asserts COVERAGE with
            # these (a fields count alone cannot tell rep.q on two
            # lanes from a hedge-group probe, and a dead hedge group
            # must still count as covered)
            "field_names": sorted(_probed_names),
        }


def dump(path: str) -> str:
    """Write :func:`report` as JSON; returns the path."""
    doc = report()
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2)
        fh.write("\n")
    os.replace(tmp, path)
    return path


# ---------------------------------------------------------------------------
# env activation: SLATE_TPU_SYNC_CHECK=1[,seed=N,yield=P,yield_us=U]
# ---------------------------------------------------------------------------

_env_spec = os.environ.get(SYNC_ENV)
if _env_spec:
    # fail loud but name the knob (the faults-env pattern): silently
    # disarming a check the operator believes is active would be worse
    # than refusing to start
    try:
        configure(_env_spec)
    except (ValueError, TypeError) as e:
        raise ValueError(f"{SYNC_ENV}={_env_spec!r}: {e}") from e
