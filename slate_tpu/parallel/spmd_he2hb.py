"""Distributed two-stage eigenreduction stage 1: he2hb over the mesh.

TPU-native re-design of the reference he2hb driver (reference:
src/he2hb.cc:98-185 — per panel k: internal::geqrf of the subdiagonal
panel over the panel's process column, tileBcast of V/T, then the
two-sided trailing update assembled from internal::he2hb_hemm /
he2hb_her2k_offdiag / he2hb_gemm tasks; SURVEY §3.5).  The reference
asserts Uplo::Lower (he2hb.cc:36); so does this pipeline.

The mesh schedule per panel k (one lax.fori_loop body, static shapes):

1. the subdiagonal panel column is rebuilt on every process by two
   all_gathers (the panel-gather strategy shared with spmd_chol/lu/qr)
   and factored redundantly — panel FLOPs are O(n nb^2) per step,
   negligible next to the O(h^2 nb) trailing update;
2. the Hermitian product P = A22 (V T) is evaluated from the *stored
   lower triangle only*: each stored tile A_ij (i >= j) contributes
   A_ij W_j to P_i and, for i > j, A_ij^H W_i to P_j — two masked
   einsums over the local tile stack + a scatter-add into natural tile
   order + psum over both mesh axes (the reference's he2hb_hemm tile
   reduce, internal_he2hb_hemm.cc);
3. the rank-2b two-sided update A22 -= V P^H + P V^H - V (T^H V^H P) V^H
   is applied tile-locally to the stored lower triangle from the
   replicated V, P (the he2hb_her2k/gemm task group);
4. R overwrites the panel column on its owner; V is stashed into its own
   distributed tile array for unmtr_he2hb.

No full_global() anywhere: the only cross-device traffic is the panel
gather and the P psum, both O(n nb) per step over ICI.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..internal.precision import KCHUNK, emulated_f64
from ..ops.householder import geqrf as _geqrf_kernel, larft
from ..parallel.grid import COL_AXIS, ROW_AXIS, ProcessGrid
from ..parallel.layout import TileLayout
from .spmd_blas import shard_map

from ..aux.metrics import instrumented


@instrumented("spmd.he2hb")
def spmd_he2hb(
    grid: ProcessGrid, T: jnp.ndarray, layout: TileLayout
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Reduce a lower-Hermitian storage tile array to band form (kd = nb).

    T: (P, Q, mb, mb) storage-order tiles; only the lower triangle
    (global element row >= col) is referenced.  Returns
    (band_tiles, V_tiles, Tstack): band_tiles hold the Hermitian band in
    the lower triangle (diagonal blocks + subdiagonal R blocks),
    V_tiles store panel k's reflectors in tile column k (rows k+1..),
    Tstack is (kt-1 or 1, nb, nb) replicated compact-WY factors.
    """
    p, q = grid.p, grid.q
    mb = layout.mb
    assert mb == layout.nb, "he2hb requires square tiles"
    n = layout.n
    kt = layout.nt
    mtl, ntl = layout.mtl, layout.ntl
    m_pad = layout.P * mb
    nsteps = max(kt - 1, 0)
    row_scatter = jnp.asarray(layout.row_scatter)
    row_gather = jnp.asarray(layout.row_gather)
    complex_t = jnp.issubdtype(T.dtype, jnp.complexfloating)

    def conj(x):
        return jnp.conj(x) if complex_t else x

    def local(tl):
        r = lax.axis_index(ROW_AXIS)
        c = lax.axis_index(COL_AXIS)
        gi = jnp.arange(mtl) * p + r  # global tile rows of local slots
        gj = jnp.arange(ntl) * q + c
        g_rows = jnp.arange(m_pad, dtype=jnp.int32)
        # elementwise global coordinates of the local shard
        er = gi[:, None] * mb + jnp.arange(mb)[None, :]  # (mtl, mb)
        ec = gj[:, None] * mb + jnp.arange(mb)[None, :]  # (ntl, mb)
        low_el = er[:, None, :, None] >= ec[None, :, None, :]
        slow_el = er[:, None, :, None] > ec[None, :, None, :]

        def step(k, carry):
            tl, Vs, Ts = carry
            lo = (k + 1) * mb
            active_len = n - lo

            # -- 1. gather subdiagonal panel column k ---------------------
            pan_loc = lax.dynamic_slice_in_dim(tl, k // q, 1, axis=1)[:, 0]
            pan_q = lax.all_gather(pan_loc, COL_AXIS)
            pan_rows = lax.dynamic_index_in_dim(pan_q, k % q, 0, keepdims=False)
            pan_full = lax.all_gather(pan_rows, ROW_AXIS).reshape(p * mtl, mb, mb)
            panel2d = pan_full[row_scatter].reshape(m_pad, mb)
            pact = jnp.roll(panel2d, -lo, axis=0)
            pact = jnp.where((g_rows < active_len)[:, None], pact, 0)

            # -- 2. redundant panel QR + T ------------------------------
            vr, taus = _geqrf_kernel(pact)
            rows_ = g_rows[:, None]
            cols_ = jnp.arange(mb)[None, :]
            V_act = jnp.where(rows_ > cols_, vr, 0) + jnp.where(
                rows_ == cols_, jnp.ones_like(vr), 0
            )
            V_act = jnp.where((g_rows < active_len)[:, None], V_act, 0)
            Tk = larft(V_act, taus)
            Ts = lax.dynamic_update_index_in_dim(
                Ts, Tk.astype(Ts.dtype), k, 0
            )

            # -- 3. write [R; 0] back on the panel's owner column --------
            R2d = jnp.roll(
                jnp.where((g_rows < active_len)[:, None], jnp.triu(vr), 0),
                lo,
                axis=0,
            )
            fac_st = R2d.reshape(layout.P, mb, mb)[row_gather]
            mine = lax.dynamic_slice_in_dim(fac_st, r * mtl, mtl, axis=0)
            cur_col = lax.dynamic_slice_in_dim(tl, k // q, 1, axis=1)[:, 0]
            sel = ((gi > k)[:, None, None]) & (c == k % q)
            new_col = jnp.where(sel, mine, cur_col)
            tl = lax.dynamic_update_slice_in_dim(
                tl, new_col[:, None], k // q, axis=1
            )

            # -- 4. replicated V, W = V Tk in natural tile order ---------
            V2d = jnp.roll(V_act, lo, axis=0)  # global row coords
            W2d = V2d @ Tk
            V_nat = V2d.reshape(layout.P, mb, mb)
            W_nat = W2d.reshape(layout.P, mb, mb)
            V_rows = V_nat[gi]  # (mtl, mb, nb)
            V_cols = V_nat[gj]  # (ntl, mb, nb)
            W_rows = W_nat[gi]
            W_cols = W_nat[gj]

            # -- 5. P = Herm(A22) W from the stored lower triangle -------
            act_r = ((er >= lo) & (er < n))[:, None, :, None]
            act_c = ((ec >= lo) & (ec < n))[None, :, None, :]
            Alow = jnp.where(low_el & act_r & act_c, tl, 0)
            Aslow = jnp.where(slow_el & act_r & act_c, tl, 0)
            P1 = jnp.einsum("ijab,jbv->iav", Alow, W_cols)
            P2 = jnp.einsum("ijab,iav->jbv", conj(Aslow), W_rows)
            P_nat = (
                jnp.zeros((layout.P, mb, mb), P1.dtype)
                .at[gi].add(P1)
                .at[gj].add(P2)
            )
            P_nat = lax.psum(lax.psum(P_nat, COL_AXIS), ROW_AXIS)
            P2d = P_nat.reshape(m_pad, mb)

            # -- 6. Q2 = Tk^H (V^H P), replicated ------------------------
            Q2 = conj(Tk).T @ (conj(V2d).T @ P2d)

            # -- 7. two-sided trailing update on the stored triangle -----
            P_rows = P_nat[gi]
            P_cols = P_nat[gj]
            t1 = jnp.einsum("iav,jbv->ijab", V_rows, conj(P_cols))
            t2 = jnp.einsum("iav,jbv->ijab", P_rows, conj(V_cols))
            t3 = jnp.einsum("iav,vw,jbw->ijab", V_rows, Q2, conj(V_cols))
            upd = t1 + t2 - t3
            tl = tl - jnp.where(low_el & act_r & act_c, upd, 0)

            # -- 8. stash V on its owner column --------------------------
            V_st = V_nat[row_gather]
            vmine = lax.dynamic_slice_in_dim(V_st, r * mtl, mtl, axis=0)
            cur_v = lax.dynamic_slice_in_dim(Vs, k // q, 1, axis=1)[:, 0]
            new_v = jnp.where(sel, vmine, cur_v)
            Vs = lax.dynamic_update_slice_in_dim(
                Vs, new_v[:, None], k // q, axis=1
            )
            return tl, Vs, Ts

        Vs0 = jnp.zeros_like(tl)
        Ts0 = jnp.zeros((max(nsteps, 1), mb, mb), tl.dtype)
        return lax.fori_loop(0, nsteps, step, (tl, Vs0, Ts0))

    spec = P(ROW_AXIS, COL_AXIS)
    fn = shard_map(
        local, mesh=grid.mesh, in_specs=(spec,), out_specs=(spec, spec, P())
    )
    return fn(T)


@instrumented("spmd.unmtr_he2hb_left")
def spmd_unmtr_he2hb_left(
    grid: ProcessGrid,
    V_tiles: jnp.ndarray,
    Tstack: jnp.ndarray,
    C_tiles: jnp.ndarray,
    v_layout: TileLayout,
    c_layout: TileLayout,
    trans: bool,
) -> jnp.ndarray:
    """C <- Q C (trans=False) or Q^H C (True) with Q from spmd_he2hb
    (reference: src/unmtr_he2hb.cc, Side::Left).

    Q = H_0 H_1 ... H_{np-1}, H_k = I - V_k T_k V_k^H with V_k gathered
    from tile column k of V_tiles.  One fori_loop over panels; per panel
    the same panel-gather + distributed compact-WY apply as spmd_qr's
    trailing update: W = V^H C is a local contraction + psum over 'p',
    then C -= V (T W) locally.
    """
    p, q = grid.p, grid.q
    mb = v_layout.mb
    assert mb == v_layout.nb and mb == c_layout.mb
    n = v_layout.n
    nsteps = Tstack.shape[0]
    mtl, ntl = v_layout.mtl, v_layout.ntl
    ntl_c = c_layout.ntl
    m_pad = v_layout.P * mb
    row_scatter = jnp.asarray(v_layout.row_scatter)
    complex_t = jnp.issubdtype(C_tiles.dtype, jnp.complexfloating)

    def conj(x):
        return jnp.conj(x) if complex_t else x

    # forward (k ascending) applies H_{np-1} ... H_0; Q C needs k
    # descending (apply H_{np-1} first), Q^H C ascending.
    ascending = trans

    def local(vt, Ts, ct):
        r = lax.axis_index(ROW_AXIS)
        c = lax.axis_index(COL_AXIS)
        gi = jnp.arange(mtl) * p + r
        g_rows = jnp.arange(m_pad, dtype=jnp.int32)

        def step(i, ct):
            k = i if ascending else nsteps - 1 - i
            lo = (k + 1) * mb
            # gather V panel column k
            pan_loc = lax.dynamic_slice_in_dim(vt, k // q, 1, axis=1)[:, 0]
            pan_q = lax.all_gather(pan_loc, COL_AXIS)
            pan_rows = lax.dynamic_index_in_dim(pan_q, k % q, 0, keepdims=False)
            pan_full = lax.all_gather(pan_rows, ROW_AXIS).reshape(p * mtl, mb, mb)
            V2d = pan_full[row_scatter].reshape(m_pad, mb)
            V2d = jnp.where((g_rows >= lo)[:, None] & (g_rows < n)[:, None], V2d, 0)
            V_nat = V2d.reshape(v_layout.P, mb, mb)
            V_rows = V_nat[gi]
            Tk = lax.dynamic_index_in_dim(Ts, k, 0, keepdims=False)
            Tm = conj(Tk).T if trans else Tk
            # the V^H C gram is cancellation-heavy; past ~4096 local
            # rows the chip's f64 emulation drops its compensation
            # terms on exactly this shape (BENCH_NOTES round-5 cliff;
            # the gathered-path gram was heev's whole orthogonality
            # budget at n=4096) — chunk the tile-stack contraction at
            # <= 2048 rows and accumulate across chunks in f64
            mtl_l = V_rows.shape[0]
            tchunk = max(1, KCHUNK // mb)
            if (
                emulated_f64(ct.dtype)
                and mtl_l * mb >= 2 * KCHUNK
                and mtl_l > tchunk
            ):
                W = jnp.einsum(
                    "iav,ijab->vjb",
                    conj(V_rows[:tchunk]), ct[:tchunk],
                    precision=lax.Precision.HIGHEST,
                )
                for t0 in range(tchunk, mtl_l, tchunk):
                    W = W + jnp.einsum(
                        "iav,ijab->vjb",
                        conj(V_rows[t0 : t0 + tchunk]),
                        ct[t0 : t0 + tchunk],
                        precision=lax.Precision.HIGHEST,
                    )
            else:
                W = jnp.einsum(
                    "iav,ijab->vjb", conj(V_rows), ct,
                    precision=lax.Precision.HIGHEST,
                )
            W = lax.psum(W, ROW_AXIS)  # (nb, ntl_c, nbc)
            upd = jnp.einsum("iav,vw,wjb->ijab", V_rows, Tm, W)
            return ct - upd

        return lax.fori_loop(0, nsteps, step, ct)

    spec = P(ROW_AXIS, COL_AXIS)
    fn = shard_map(
        local,
        mesh=grid.mesh,
        in_specs=(spec, P(), spec),
        out_specs=spec,
    )
    return fn(V_tiles, Tstack, C_tiles)
