"""Thin traceable entry over the spmd drivers for the serving cache.

The serving tier's sharded buckets (``BucketKey.mesh == "PxQ"``) need
one jit-able callable ``core(Ag, Bg) -> (Xg, info)`` over padded global
arrays — the same contract as the single-device serve cores
(serve/cache._build_core) — but executing the explicit mesh algorithms
from this package under ``shard_map``: distributed LU / Cholesky of the
tile array, pivot row exchange, and the trsm pipelines, never a
gathered global factorization.

The cache traces these per bucket exactly like the replicated cores, so
the warmed executable set, manifest, and artifact fingerprints all key
by mesh shape (serve/buckets.content_fields carries ``mesh``).  Inputs
arrive as whole (replicated) global arrays; ``tiles_from_global`` packs
them into the storage-order tile layout and GSPMD moves the shards onto
the mesh at the ``shard_map`` boundary — the serving boundary stays
"plain arrays in, plain arrays out" while the math runs distributed.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Tuple

import jax.numpy as jnp
import numpy as np

from ..exceptions import DistributedException
from .grid import ProcessGrid
from .layout import TileLayout, eye_splice, tiles_from_global, tiles_to_global

#: ProcessGrid per mesh string — grids wrap jax.sharding.Mesh objects
#: whose identity matters for shard_map tracing caches, so each mesh
#: shape maps to ONE grid per process (the lock keeps concurrent
#: builders — e.g. the restore thread racing a sharded worker's cold
#: build — from creating duplicate Mesh objects that would split the
#: tracing caches)
_grids: Dict[Tuple[str, int], ProcessGrid] = {}
_grids_lock = threading.Lock()


def grid_for(mesh: str) -> ProcessGrid:
    """The process-wide ProcessGrid for a ``"PxQ"`` mesh string, built
    over the first P*Q visible devices (cached per shape)."""
    from ..serve.buckets import parse_mesh

    p, q = parse_mesh(mesh)
    if p == 0:
        raise ValueError("grid_for requires a non-empty mesh shape")
    import jax

    devs = jax.devices()
    if p * q > len(devs):
        raise DistributedException(
            f"mesh {mesh} needs {p * q} devices, only {len(devs)} visible"
        )
    key = (f"{p}x{q}", id(devs[0].client) if hasattr(devs[0], "client") else 0)
    with _grids_lock:
        grid = _grids.get(key)
        if grid is None:
            grid = _grids[key] = ProcessGrid.from_devices(
                devs[: p * q], p=p, q=q
            )
    return grid


def _diag_info(T: jnp.ndarray, lay: TileLayout) -> jnp.ndarray:
    """info code from an LU-packed tile array: exact zero / non-finite
    on U's diagonal (the tile-array twin of drivers/lu._udiag_info —
    a masked reduction GSPMD lowers to local work + psum)."""
    dmin = min(lay.m, lay.n)
    gr = jnp.asarray(lay.global_rows_np)[:, None, :, None]
    gc = jnp.asarray(lay.global_cols_np)[None, :, None, :]
    dmask = (gr == gc) & (gr < dmin)
    if jnp.issubdtype(T.dtype, jnp.complexfloating):
        bad = (T == 0) | ~(
            jnp.isfinite(jnp.real(T)) & jnp.isfinite(jnp.imag(T))
        )
    else:
        bad = (T == 0) | ~jnp.isfinite(T)
    return jnp.where(jnp.any(bad & dmask), 1, 0).astype(jnp.int32)


def build_solve_core(
    routine: str, grid: ProcessGrid, n: int, nrhs: int, nb: int
) -> Callable:
    """``core(Ag, Bg) -> (Xg, info)`` solving one padded square system
    on the mesh: gesv = spmd tournament-free LU + pivot exchange + two
    trsm pipelines; posv = spmd right-looking Cholesky + the L / L^H
    pipelines.  ``Ag`` is the serve-padded (n, n) global (identity
    trailing block from buckets.pad_square keeps the padded rows
    pivot-inert), ``Bg`` the (n, nrhs) padded right-hand sides."""
    from . import spmd_chol, spmd_lu, spmd_trsm

    if routine not in ("gesv", "posv"):
        raise ValueError(f"no sharded serving core for {routine!r}")
    layA = TileLayout(n, n, nb, nb, grid.p, grid.q)
    layB = TileLayout(n, nrhs, nb, nb, grid.p, grid.q)

    if routine == "gesv":

        def core(Ag, Bg):
            T = eye_splice(layA, tiles_from_global(Ag, layA))
            Td, perm = spmd_lu.spmd_getrf(grid, T, layA)
            TB = tiles_from_global(Bg, layB)
            TB = spmd_trsm.spmd_permute_rows(grid, TB, layB, perm)
            TT = eye_splice(layA, Td)
            Y = spmd_trsm.spmd_trsm_left(
                grid, TT, layA, TB, layB,
                lower=True, trans=False, conj=False, unit_diag=True,
            )
            X = spmd_trsm.spmd_trsm_left(
                grid, TT, layA, Y, layB,
                lower=False, trans=False, conj=False, unit_diag=False,
            )
            return tiles_to_global(X, layB), _diag_info(Td, layA)

        return core

    def core(Ag, Bg):
        # posv reads the lower triangle only (serve pads SPD systems
        # with an identity trailing block, itself SPD)
        T = eye_splice(layA, tiles_from_global(Ag, layA))
        Ld = spmd_chol.spmd_potrf_lower(grid, T, layA)
        # non-SPD surfaces as NaNs out of the diagonal-tile Cholesky and
        # propagates through the trailing updates (drivers/chol checks
        # the whole tile array the same way)
        info = jnp.where(jnp.all(jnp.isfinite(Ld)), 0, 1).astype(jnp.int32)
        TT = eye_splice(layA, Ld)
        TB = tiles_from_global(Bg, layB)
        Y = spmd_trsm.spmd_trsm_left(
            grid, TT, layA, TB, layB,
            lower=True, trans=False, conj=False, unit_diag=False,
        )
        X = spmd_trsm.spmd_trsm_left(
            grid, TT, layA, Y, layB,
            lower=True, trans=True, conj=True, unit_diag=False,
        )
        return tiles_to_global(X, layB), info

    return core


def serve_core(key) -> Callable:
    """The sharded serving core for one mesh-keyed BucketKey — what
    serve/cache traces when ``key.mesh`` is set."""
    grid = grid_for(key.mesh)
    return build_solve_core(key.routine, grid, key.n, key.nrhs, key.nb)
