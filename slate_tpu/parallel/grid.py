"""Process grid over a jax device mesh.

TPU-native replacement for the reference's MPI p x q process grid
(reference: BaseMatrix.hh:80-122, func.hh:207).  A ``ProcessGrid`` wraps a
``jax.sharding.Mesh`` with axes ``('p', 'q')``; the 2D block-cyclic tile
distribution is realized by storing tiles in owner-major ("storage") order
(see layout.py) so a plain block NamedSharding over ('p', 'q') yields the
cyclic distribution.  Collectives ride mesh sub-axes over ICI/DCN instead of
MPI communicators.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..enums import GridOrder
from ..exceptions import DistributedException

ROW_AXIS = "p"
COL_AXIS = "q"


def _factor_2d(n: int) -> tuple:
    """Most-square p x q factorization of n, p <= q."""
    p = int(math.isqrt(n))
    while n % p != 0:
        p -= 1
    return p, n // p


@dataclass(frozen=True)
class ProcessGrid:
    """A p x q grid of devices with named mesh axes ('p', 'q').

    ``order`` controls how linear device order maps to the grid, mirroring
    the reference's GridOrder for BLACS compatibility (enums.hh:524):
    Col => device k sits at (k % p, k // p); Row => (k // q, k % q).
    """

    mesh: Mesh
    order: GridOrder = GridOrder.Col

    @property
    def p(self) -> int:
        return self.mesh.shape[ROW_AXIS]

    @property
    def q(self) -> int:
        return self.mesh.shape[COL_AXIS]

    @property
    def size(self) -> int:
        return self.p * self.q

    # -- constructors -------------------------------------------------------

    @staticmethod
    def from_devices(
        devices: Optional[Sequence] = None,
        p: Optional[int] = None,
        q: Optional[int] = None,
        order: GridOrder = GridOrder.Col,
    ) -> "ProcessGrid":
        devices = list(devices if devices is not None else jax.devices())
        n = len(devices)
        if (p is not None and p <= 0) or (q is not None and q <= 0):
            raise DistributedException(f"grid dims must be positive, got {p}x{q}")
        if p is None and q is None:
            p, q = _factor_2d(n)
        elif p is None:
            p = n // q
        elif q is None:
            q = n // p
        if p * q != n:
            raise DistributedException(
                f"grid {p}x{q} does not match device count {n}"
            )
        dev = np.asarray(devices, dtype=object)
        if order == GridOrder.Col:
            dev = dev.reshape(q, p).T  # device k at (k % p, k // p)
        else:
            dev = dev.reshape(p, q)
        return ProcessGrid(Mesh(dev, (ROW_AXIS, COL_AXIS)), order)

    @staticmethod
    def single(device=None) -> "ProcessGrid":
        """1x1 grid on one device (the degenerate, no-comm case)."""
        dev = device if device is not None else jax.devices()[0]
        return ProcessGrid.from_devices([dev], p=1, q=1)

    # -- shardings ----------------------------------------------------------

    def tile_sharding(self) -> NamedSharding:
        """Sharding for a (P, Q, mb, nb) storage-order tile array."""
        return NamedSharding(self.mesh, PartitionSpec(ROW_AXIS, COL_AXIS))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec())

    def row_sharding(self) -> NamedSharding:
        """Sharding for arrays distributed over process rows only."""
        return NamedSharding(self.mesh, PartitionSpec(ROW_AXIS))

    def col_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec(COL_AXIS))


_default_grid: Optional[ProcessGrid] = None


def default_grid() -> ProcessGrid:
    """Module-level default grid: 1x1 on the first device.

    Multi-device runs should construct an explicit ProcessGrid; the default
    keeps the single-chip path zero-config.
    """
    global _default_grid
    if _default_grid is None:
        _default_grid = ProcessGrid.single()
    return _default_grid


def set_default_grid(grid: ProcessGrid) -> None:
    global _default_grid
    _default_grid = grid
