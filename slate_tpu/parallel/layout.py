"""Tile layout: 2D block-cyclic distribution as owner-major storage.

This module is the TPU-native replacement for the reference's
MatrixStorage + 2D block-cyclic index maps (reference:
include/slate/internal/MatrixStorage.hh:151, func.hh:100-265,
BaseMatrix.hh:211-223 tileRank/tileDevice).

Design: a distributed matrix is ONE jax array of tiles with shape

    (P, Q, mb, nb),   P = p * mtl,  Q = q * ntl

stored in *owner-major* (cyclic-permuted) order: global tile (i, j) lives at
storage slot (srow(i), scol(j)) with

    srow(i) = (i % p) * mtl + i // p        (mtl = ceil(mt / p))
    scol(j) = (j % q) * ntl + j // q        (ntl = ceil(nt / q))

A plain block NamedSharding over mesh axes ('p', 'q') then gives process
(r, c) exactly its block-cyclic tile set {i : i % p == r} x {j : j % q == c},
contiguously, as local shard (mtl, ntl, mb, nb) — the same local layout
ScaLAPACK uses.  Inside ``shard_map`` each process sees precisely its local
tile stack, so one fused XLA dot per bulk step replaces the reference's
batched-BLAS groups (internal_gemm.cc:455-511).

Edge tiles are padded to uniform (mb, nb); SURVEY §7 hard-part (4).  Padding
rows/cols are zero, and factorization drivers locally splice an identity
into the padded diagonal so static-shape kernels stay nonsingular.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import cached_property
from typing import Tuple

import jax.numpy as jnp
import numpy as np


def ceil_div(a: int, b: int) -> int:
    return -(-a // b)


@dataclass(frozen=True)
class TileLayout:
    """Static index math for an m x n matrix tiled mb x nb on a p x q grid."""

    m: int
    n: int
    mb: int
    nb: int
    p: int = 1
    q: int = 1

    # -- tile counts --------------------------------------------------------

    @property
    def mt(self) -> int:
        return ceil_div(self.m, self.mb)

    @property
    def nt(self) -> int:
        return ceil_div(self.n, self.nb)

    @property
    def mtl(self) -> int:
        """Local (per process-row) padded tile-row count."""
        return ceil_div(self.mt, self.p)

    @property
    def ntl(self) -> int:
        return ceil_div(self.nt, self.q)

    @property
    def P(self) -> int:
        """Padded global tile-row count (= p * mtl)."""
        return self.p * self.mtl

    @property
    def Q(self) -> int:
        return self.q * self.ntl

    @property
    def storage_shape(self) -> Tuple[int, int, int, int]:
        return (self.P, self.Q, self.mb, self.nb)

    # -- per-tile queries (reference: BaseMatrix.hh:211-223, func.hh) -------

    def tileMb(self, i: int) -> int:
        """Row count of tile row i (short last tile; func.hh:39-43)."""
        return self.m - i * self.mb if (i + 1) * self.mb > self.m else self.mb

    def tileNb(self, j: int) -> int:
        return self.n - j * self.nb if (j + 1) * self.nb > self.n else self.nb

    def tileRank(self, i: int, j: int) -> Tuple[int, int]:
        """Owning (process-row, process-col) of tile (i, j)."""
        return (i % self.p, j % self.q)

    def tileIsLocal(self, i: int, j: int, r: int, c: int) -> bool:
        return self.tileRank(i, j) == (r, c)

    # -- storage permutation -------------------------------------------------

    def srow(self, i):
        """Storage row slot of global tile-row i (works on ints or traced)."""
        return (i % self.p) * self.mtl + i // self.p

    def scol(self, j):
        return (j % self.q) * self.ntl + j // self.q

    def lrow(self, s):
        """Inverse of srow: global tile-row stored at slot s."""
        return (s % self.mtl) * self.p + s // self.mtl

    def lcol(self, s):
        return (s % self.ntl) * self.q + s // self.ntl

    @cached_property
    def row_gather(self) -> np.ndarray:
        """index array g with storage[s] = natural[g[s]] (natural padded to P)."""
        return np.array([self.lrow(s) for s in range(self.P)], dtype=np.int32)

    @cached_property
    def col_gather(self) -> np.ndarray:
        return np.array([self.lcol(s) for s in range(self.Q)], dtype=np.int32)

    @cached_property
    def row_scatter(self) -> np.ndarray:
        """index array h with natural[i] = storage[h[i]]."""
        return np.array([self.srow(i) for i in range(self.P)], dtype=np.int32)

    @cached_property
    def col_scatter(self) -> np.ndarray:
        return np.array([self.scol(j) for j in range(self.Q)], dtype=np.int32)

    # -- masks for ragged edges ---------------------------------------------

    @cached_property
    def row_mask_np(self) -> np.ndarray:
        """(P, mb) bool: valid rows of each storage tile-row slot."""
        mask = np.zeros((self.P, self.mb), dtype=bool)
        for s in range(self.P):
            i = self.lrow(s)
            if i < self.mt:
                mask[s, : self.tileMb(i)] = True
        return mask

    @cached_property
    def col_mask_np(self) -> np.ndarray:
        mask = np.zeros((self.Q, self.nb), dtype=bool)
        for s in range(self.Q):
            j = self.lcol(s)
            if j < self.nt:
                mask[s, : self.tileNb(j)] = True
        return mask

    def element_mask(self) -> jnp.ndarray:
        """(P, Q, mb, nb) bool mask of valid (non-padding) elements."""
        rm = jnp.asarray(self.row_mask_np)[:, None, :, None]
        cm = jnp.asarray(self.col_mask_np)[None, :, None, :]
        return rm & cm

    # -- global element index maps ------------------------------------------

    @cached_property
    def global_rows_np(self) -> np.ndarray:
        """(P, mb) int32: global row index of each storage element row
        (padding slots point past m; clip before use)."""
        out = np.zeros((self.P, self.mb), dtype=np.int32)
        for s in range(self.P):
            i = self.lrow(s)
            out[s] = i * self.mb + np.arange(self.mb)
        return out

    @cached_property
    def global_cols_np(self) -> np.ndarray:
        out = np.zeros((self.Q, self.nb), dtype=np.int32)
        for s in range(self.Q):
            j = self.lcol(s)
            out[s] = j * self.nb + np.arange(self.nb)
        return out

    @cached_property
    def trivial_perm(self) -> bool:
        """True when storage order == natural order (p == q == 1), letting
        pack/unpack skip the index gathers entirely (XLA fuses the
        remaining reshapes into consumer layouts)."""
        return bool(
            np.array_equal(self.row_gather, np.arange(self.P))
            and np.array_equal(self.col_gather, np.arange(self.Q))
        )

    # -- derived layouts -----------------------------------------------------

    def transposed(self) -> "TileLayout":
        """Layout of A^T: dims, tiles and grid swap."""
        return TileLayout(self.n, self.m, self.nb, self.mb, self.q, self.p)

    def with_grid(self, p: int, q: int) -> "TileLayout":
        return TileLayout(self.m, self.n, self.mb, self.nb, p, q)


# ---------------------------------------------------------------------------
# Conversions: global 2D array <-> storage-order tile array.
# Pure jnp; usable inside jit and differentiable.
# ---------------------------------------------------------------------------


def tiles_from_global(A: jnp.ndarray, layout: TileLayout) -> jnp.ndarray:
    """Pack a (m, n) array into storage-order tiles (P, Q, mb, nb).

    Reference analogue: Matrix::fromLAPACK / insert+copy of all tiles
    (Matrix.hh:58).  Padding elements are zero.
    """
    m, n = layout.m, layout.n
    assert A.shape == (m, n), f"expected {(m, n)}, got {A.shape}"
    Pm, Qn = layout.P * layout.mb, layout.Q * layout.nb
    A = jnp.pad(A, ((0, Pm - m), (0, Qn - n)))
    T = A.reshape(layout.P, layout.mb, layout.Q, layout.nb).transpose(0, 2, 1, 3)
    if layout.trivial_perm:
        return T
    # natural -> storage permutation (static gather)
    return T[layout.row_gather][:, layout.col_gather]


def tiles_to_global(T: jnp.ndarray, layout: TileLayout) -> jnp.ndarray:
    """Unpack storage-order tiles back to the (m, n) global array."""
    assert T.shape == layout.storage_shape, (T.shape, layout.storage_shape)
    Tn = T if layout.trivial_perm else T[layout.row_scatter][:, layout.col_scatter]
    A = Tn.transpose(0, 2, 1, 3).reshape(layout.P * layout.mb, layout.Q * layout.nb)
    return A[: layout.m, : layout.n]


def zeros_tiles(layout: TileLayout, dtype=jnp.float32) -> jnp.ndarray:
    return jnp.zeros(layout.storage_shape, dtype=dtype)


def eye_splice(layout: TileLayout, T: jnp.ndarray, scale=1.0) -> jnp.ndarray:
    """Return T with `scale` written on the *padding* diagonal so that
    factorizations of the padded matrix stay nonsingular (SURVEY §7
    hard-part (4): prefer padding to uniform nb on TPU)."""
    mask = ~layout.element_mask()
    gr = jnp.asarray(layout.global_rows_np)[:, None, :, None]
    gc = jnp.asarray(layout.global_cols_np)[None, :, None, :]
    diag_pad = mask & (gr == gc)
    return jnp.where(diag_pad, jnp.asarray(scale, T.dtype), T)
