"""Distributed two-sided generalized-eigenproblem reduction (hegst).

TPU-native re-design of the reference's distributed hegst (reference:
src/hegst.cc + src/internal/internal_hegst.cc — a blocked two-sided
reduction C = L^-1 A L^-H built from trsm/hemm/her2k tasks over the
mesh).  Here the same product is computed from the in-repo SPMD
pieces, all column-pipelined over ICI:

1. ``spmd_hermitian_full``: materialize the DISTRIBUTED full tile
   array of Hermitian A from its stored triangle — each process writes
   only its own tiles of each assembled column (the spmd_hemm
   stored-triangle panel assembly, one column per step; O(n nb) ICI
   per step, no global mirror round trip);
2. ``Y = L^-1 A``  via the left column-pipeline trsm;
3. ``C = Y L^-H``  via the right column-pipeline trsm (trans+conj).

itype 2/3 (C = L^H A L) keeps the driver's gathered route (rare path;
recorded by internal/fallbacks).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .grid import COL_AXIS, ROW_AXIS, ProcessGrid
from .layout import TileLayout, eye_splice
from .spmd_blas import _resize_rows_3d, shard_map
from .spmd_trsm import spmd_trsm_left, spmd_trsm_right

from ..aux.metrics import instrumented


def spmd_hermitian_full(
    grid: ProcessGrid,
    TA: jnp.ndarray,
    layA: TileLayout,
    *,
    lower: bool,
    hermitian: bool = True,
) -> jnp.ndarray:
    """Distributed full tile array of Hermitian/symmetric A from its
    stored triangle: column k is assembled on the fly from the stored
    tile column (stored side) + stored tile row (mirror side) and each
    process keeps its own tiles — memory stays O(n^2 / (p q)) per
    process."""
    p, q = grid.p, grid.q
    mb = layA.mb
    assert layA.mb == layA.nb and layA.m == layA.n
    nt = layA.nt
    n = layA.n
    mtl, ntl = layA.mtl, layA.ntl
    row_scatter = jnp.asarray(layA.row_scatter)
    col_scatter = jnp.asarray(layA.col_scatter)
    complex_t = jnp.issubdtype(TA.dtype, jnp.complexfloating)

    def cj(x):
        return jnp.conj(x) if (complex_t and hermitian) else x

    def local(ta):
        r = lax.axis_index(ROW_AXIS)
        c = lax.axis_index(COL_AXIS)
        gi = jnp.arange(mtl) * p + r
        t_idx_r = jnp.arange(layA.P)
        a_el = jnp.arange(mb)

        def gather_colA(k):
            loc = lax.dynamic_slice_in_dim(ta, k // q, 1, axis=1)[:, 0]
            aq = lax.all_gather(loc, COL_AXIS)
            rows = lax.dynamic_index_in_dim(aq, k % q, 0, keepdims=False)
            full = lax.all_gather(rows, ROW_AXIS)
            return full.reshape(p * mtl, mb, mb)[row_scatter]

        def gather_rowA(k):
            loc = lax.dynamic_slice_in_dim(ta, k // p, 1, axis=0)[0]
            ap = lax.all_gather(loc, ROW_AXIS)
            cols = lax.dynamic_index_in_dim(ap, k % p, 0, keepdims=False)
            full = lax.all_gather(cols, COL_AXIS)
            return full.reshape(q * ntl, mb, mb)[col_scatter]

        def herm_col(k):
            colp = gather_colA(k)
            rowp = _resize_rows_3d(gather_rowA(k), layA.P)
            mirror = cj(jnp.swapaxes(rowp, -1, -2))
            gr = t_idx_r[:, None, None] * mb + a_el[:, None]
            gc = k * mb + a_el[None, None, :]
            from_stored = (gr >= gc) if lower else (gr <= gc)
            valid = (gr < n) & (gc < n)
            out = jnp.where(valid & from_stored, colp, 0) + jnp.where(
                valid & ~from_stored, mirror, 0
            )
            if complex_t and hermitian:
                out = jnp.where(
                    gr == gc, jnp.real(out).astype(out.dtype), out
                )
            return out

        def step(k, out):
            colk = herm_col(k)[gi]  # this process's tile rows of col k
            own = c == (k % q)
            cur = lax.dynamic_slice_in_dim(out, k // q, 1, axis=1)[:, 0]
            new = jnp.where(own, colk, cur)
            return lax.dynamic_update_slice_in_dim(
                out, new[:, None], k // q, axis=1
            )

        return lax.fori_loop(0, nt, step, jnp.zeros_like(ta))

    spec = P(ROW_AXIS, COL_AXIS)
    fn = shard_map(local, mesh=grid.mesh, in_specs=(spec,), out_specs=spec)
    return fn(TA)


@instrumented("spmd.hegst_itype1")
def spmd_hegst_itype1(
    grid: ProcessGrid,
    TA: jnp.ndarray,
    layA: TileLayout,
    TL: jnp.ndarray,
    layL: TileLayout,
    *,
    lower_a: bool,
    unit_diag: bool = False,
) -> jnp.ndarray:
    """C = L^-1 A L^-H over the mesh (itype 1, L lower; reference:
    src/hegst.cc).  Returns C's full distributed tile array (Hermitian;
    callers may view either triangle)."""
    Afull = spmd_hermitian_full(grid, TA, layA, lower=lower_a)
    TLs = eye_splice(layL, TL)
    Y = spmd_trsm_left(
        grid, TLs, layL, Afull, layA,
        lower=True, trans=False, conj=False, unit_diag=unit_diag,
    )
    C = spmd_trsm_right(
        grid, TLs, layL, Y, layA,
        lower=True, trans=True, conj=True, unit_diag=unit_diag,
    )
    return C
