"""Distributed right-looking LU with partial pivoting over the mesh.

TPU-native re-design of the reference getrf (reference: src/getrf.cc:85-214
+ internal_getrf.cc:21-119 + Tile_getrf.hh:164-452 + internal_swap.cc).
The reference's panel is a multithreaded MPI sub-communicator doing
per-column MPI_Allreduce(MAX_LOC) pivot search and per-row Isend/Irecv
swaps; none of that maps to XLA's static schedules.  The TPU schedule
(SURVEY §7 hard part (1)) per step k, inside one lax.fori_loop:

1. **panel gather**: rebuild tile column k on every process (two
   all_gathers, as in spmd_chol) and roll it so the active rows
   [k*mb, m_pad) sit at the top — replacing the panel sub-communicator
   (internal_getrf.cc:64-70);
2. **redundant panel factor**: every process runs the (m_pad x nb) panel
   LU locally (XLA lu); the per-column argmax+allreduce of
   Tile_getrf.hh:238-268 disappears because every process owns the whole
   gathered panel — pivot decisions are made identically everywhere, no
   broadcast needed;
3. **collective row exchange**: the <= nb row swaps are composed into a
   step permutation; affected rows are fetched with a masked psum over the
   'p' axis and written back by their owners — the analogue of
   internal_swap.cc's batched rank<->root row exchanges (:255-370), but as
   one dense collective instead of per-row messages;
4. **U row + trailing update**: row k is triangular-solved locally on its
   owner row, broadcast down the 'p' axis, and the trailing tiles take one
   masked einsum — internal::trsm + listBcast + internal::gemm
   (getrf.cc:193-214) fused into two collectives and one contraction.

The net row permutation is carried as a vector (see types.Pivots).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..ops.lu_kernels import lu_supported, panel_lu
from ..parallel.grid import COL_AXIS, ROW_AXIS, ProcessGrid
from ..parallel.layout import TileLayout
from .spmd_blas import shard_map

from ..aux.metrics import instrumented


def _fetch_rows(tl, row_idx, p, r, mb):
    """Fetch global rows `row_idx` (traced, (S,)) of the local column
    shard; returns (S, ntl, nb) with zeros for unowned rows.  psum over
    'p' completes the fetch."""
    ti = row_idx // mb
    li = ti // p
    off = row_idx % mb
    own = (ti % p) == r

    def get_one(l, o):
        return lax.dynamic_index_in_dim(tl, l, 0, keepdims=False)[
            :, o, :
        ]  # (ntl, nb) -- index tile row l, element row o

    vals = jax.vmap(lambda l, o: tl[l, :, o, :])(li, off)
    return jnp.where(own[:, None, None], vals, jnp.zeros_like(vals))


def _write_rows(tl, row_idx, vals, p, r, mb):
    """Write rows `row_idx` <- vals on their owners (duplicate indices in
    row_idx must carry identical vals).

    Unowned rows must not be written AT ALL: a global row owned by another
    process aliases some local slot here (same li/off), and a "no-op"
    write of the current value would race the real write in the scatter.
    Out-of-bounds indices + mode='drop' skip them instead."""
    ti = row_idx // mb
    li = ti // p
    off = row_idx % mb
    own = (ti % p) == r
    mtl = tl.shape[0]
    li_w = jnp.where(own, li, mtl)  # out of bounds -> dropped
    return tl.at[li_w, :, off, :].set(vals, mode="drop")


@instrumented("spmd.getrf")
def spmd_getrf(
    grid: ProcessGrid,
    T: jnp.ndarray,
    layout: TileLayout,
    num_steps: int = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Factor P A = L U over the mesh.

    T: storage-order tiles of the padded matrix (padding diag spliced 1,
    mb == nb).  Returns (tiles with L\\U, perm) where perm is the net
    forward row permutation over the padded rows.
    """
    p, q = grid.p, grid.q
    nt = min(layout.mt, layout.nt) if num_steps is None else num_steps
    mtl, ntl = layout.mtl, layout.ntl
    mb = layout.mb
    m_pad = layout.P * mb
    row_scatter = jnp.asarray(layout.row_scatter)  # natural -> storage slot
    row_gather = jnp.asarray(layout.row_gather)  # storage slot -> natural

    def local(tl):
        r = lax.axis_index(ROW_AXIS)
        c = lax.axis_index(COL_AXIS)
        gi = jnp.arange(mtl) * p + r
        gj = jnp.arange(ntl) * q + c

        g_rows = jnp.arange(m_pad, dtype=jnp.int32)

        def step(k, carry):
            tl, perm_total = carry
            # -- 1. gather panel column k in natural row order ------------
            pan_loc = lax.dynamic_slice_in_dim(tl, k // q, 1, axis=1)[:, 0]
            pan_q = lax.all_gather(pan_loc, COL_AXIS)
            pan_rows = lax.dynamic_index_in_dim(pan_q, k % q, 0, keepdims=False)
            pan_full = lax.all_gather(pan_rows, ROW_AXIS)  # (p, mtl, mb, nb)
            pan_full = pan_full.reshape(p * mtl, mb, mb)
            pan_nat = pan_full[row_scatter]  # natural tile order
            panel2d = pan_nat.reshape(m_pad, mb)
            # roll active rows [k*mb, m_pad) to the top; zero the wrapped
            # already-factored rows so they can never be chosen as pivots
            active_len = m_pad - k * mb
            panel_act = jnp.roll(panel2d, -k * mb, axis=0)
            panel_act = jnp.where(
                (g_rows < active_len)[:, None], panel_act, jnp.zeros_like(panel_act)
            )

            # -- 2. redundant panel LU ------------------------------------
            # vendor LU where the backend supports the dtype; the native
            # unblocked panel kernel otherwise (TPU f64/c128)
            if lu_supported(panel_act.dtype):
                lu_pan, _, piv_perm = lax.linalg.lu(panel_act)
            else:
                lu_pan, piv_perm = panel_lu(panel_act)
            # piv_perm (active frame): permuted[i] = panel_act[piv_perm[i]]
            # -> global step permutation, identity above the panel
            act_idx = g_rows - k * mb
            mapped = piv_perm.astype(jnp.int32)[jnp.clip(act_idx, 0, m_pad - 1)] + k * mb
            mapped = jnp.where(mapped < m_pad, mapped, mapped - m_pad)
            step_perm = jnp.where(act_idx >= 0, mapped, g_rows)

            # -- 3. collective row exchange for changed rows --------------
            # changed rows are within {panel rows} U {their pivot sources};
            # each dst row's new value is old row step_perm[dst], so
            # duplicate dsts carry identical values (safe scatter).
            panel_rows = k * mb + jnp.arange(mb, dtype=jnp.int32)
            cand_dst = jnp.concatenate([panel_rows, step_perm[panel_rows]])
            src = step_perm[cand_dst]
            contrib = _fetch_rows(tl, src, p, r, mb)
            fetched = lax.psum(contrib, ROW_AXIS)  # (2nb, ntl, nb)
            tl = _write_rows(tl, cand_dst, fetched, p, r, mb)
            perm_total = perm_total[step_perm]

            # -- 4. write factored panel back (rows >= k only) ------------
            lu_nat = jnp.roll(lu_pan, k * mb, axis=0).reshape(layout.P, mb, mb)
            pan_storage = lu_nat[row_gather]  # storage order
            mine = lax.dynamic_slice_in_dim(pan_storage, r * mtl, mtl, axis=0)
            cur_col = lax.dynamic_slice_in_dim(tl, k // q, 1, axis=1)[:, 0]
            row_ge_k = (gi >= k)[:, None, None]
            owner_c = c == (k % q)
            new_col = jnp.where(row_ge_k & owner_c, mine, cur_col)
            tl = lax.dynamic_update_slice_in_dim(tl, new_col[:, None], k // q, axis=1)

            # -- 5. U row: Lkk^-1 A(k, j) on the owner row, bcast over 'p'
            Lkk_full = lu_nat[k]  # (mb, mb) L\U diagonal block
            Lkk = jnp.tril(Lkk_full, -1) + jnp.eye(mb, dtype=Lkk_full.dtype)
            row_tiles = lax.dynamic_index_in_dim(tl, k // p, 0, keepdims=False)
            U_row = lax.linalg.triangular_solve(
                jnp.broadcast_to(Lkk, row_tiles.shape),
                row_tiles,
                left_side=True,
                lower=True,
                unit_diagonal=True,
            )
            own_row = r == (k % p)
            U_row = jnp.where(own_row, U_row, jnp.zeros_like(U_row))
            U_row = lax.psum(U_row, ROW_AXIS)  # broadcast down columns

            # write U row back on its owner for trailing cols j > k
            j_gt = (gj > k)[:, None, None]
            new_row = jnp.where(j_gt & own_row, U_row, row_tiles)
            tl = lax.dynamic_update_index_in_dim(tl, new_row, k // p, axis=0)

            # -- 6. trailing update --------------------------------------
            left = mine  # local rows of the L panel (storage block r*mtl..)
            upd = jnp.einsum("iab,jbc->ijac", left, U_row)
            mask = ((gi[:, None] > k) & (gj[None, :] > k))[:, :, None, None]
            tl = tl - jnp.where(mask, upd, jnp.zeros_like(upd))
            return tl, perm_total

        perm0 = jnp.arange(m_pad, dtype=jnp.int32)
        tl, perm = lax.fori_loop(0, nt, step, (tl, perm0))
        return tl, perm

    spec = P(ROW_AXIS, COL_AXIS)
    fn = shard_map(
        local,
        mesh=grid.mesh,
        in_specs=(spec,),
        out_specs=(spec, P()),
    )
    return fn(T)


@instrumented("spmd.getrf_tntpiv")
def spmd_getrf_tntpiv(
    grid: ProcessGrid,
    T: jnp.ndarray,
    layout: TileLayout,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Distributed LU with tournament pivoting (CALU) — the tournament
    rides the mesh row axis (reference: src/getrf_tntpiv.cc:1-498 +
    internal_getrf_tntpiv.cc: per-rank local panel LU elects nb
    candidate rows, winners advance up an MPI binary tree, then the
    panel factors without further exchanges).

    Per step k, inside one lax.fori_loop:

    1. the panel column is psum-broadcast along 'q' so every process
       holds its LOCAL row chunk — no full panel gather for pivoting;
    2. each process row runs the tournament leaves + intra-process
       rounds on its own rows (ops/lu_kernels.py::tournament_pivots);
    3. the nb winners per process row all_gather over 'p' (the mesh
       reduction round) and the final playoff runs redundantly;
    4. winner_i swaps with panel row k*nb+i — at most 2 nb changed rows,
       exchanged with the same masked-psum fetch as partial pivoting;
    5. the post-exchange panel is rebuilt by two all_gathers and
       factored redundantly with NO further pivoting, then write-back /
       U row / trailing update proceed exactly as spmd_getrf.

    Returns (tiles with L\\U, perm) like spmd_getrf.
    """
    from ..ops.lu_kernels import tournament_pivots

    p, q = grid.p, grid.q
    nt = min(layout.mt, layout.nt)
    mtl, ntl = layout.mtl, layout.ntl
    mb = layout.mb
    m_pad = layout.P * mb
    row_scatter = jnp.asarray(layout.row_scatter)
    row_gather = jnp.asarray(layout.row_gather)

    def local(tl):
        r = lax.axis_index(ROW_AXIS)
        c = lax.axis_index(COL_AXIS)
        gi = jnp.arange(mtl) * p + r
        gj = jnp.arange(ntl) * q + c
        g_rows = jnp.arange(m_pad, dtype=jnp.int32)
        grow = (gi[:, None] * mb + jnp.arange(mb)[None, :]).reshape(-1)

        def step(k, carry):
            tl, perm_total = carry
            # -- 1. broadcast panel column k along 'q' --------------------
            col_loc = lax.dynamic_slice_in_dim(tl, k // q, 1, axis=1)[:, 0]
            own_col = c == (k % q)
            col_loc = lax.psum(
                jnp.where(own_col, col_loc, jnp.zeros_like(col_loc)), COL_AXIS
            )
            loc2d = col_loc.reshape(mtl * mb, mb)
            active = grow >= (k * mb)
            loc_act = jnp.where(active[:, None], loc2d, jnp.zeros_like(loc2d))

            # -- 2. local tournament (leaves + intra-process rounds) ------
            win_loc = tournament_pivots(loc_act, mb, mb)
            cand_vals = loc_act[win_loc]  # (nb, nb)
            cand_gidx = grow[win_loc]  # (nb,) global rows

            # -- 3. inter-process round over the mesh row axis ------------
            vals_all = lax.all_gather(cand_vals, ROW_AXIS).reshape(p * mb, mb)
            idx_all = lax.all_gather(cand_gidx, ROW_AXIS).reshape(p * mb)
            fin = tournament_pivots(vals_all, mb, mb)
            winners = idx_all[fin].astype(jnp.int32)  # pivot order

            # -- 4. exchange: winners to the panel rows (in pivot order),
            # displaced panel rows into the vacated winner positions —
            # a direct construction, NOT sequential swaps (a winner
            # already inside the panel block breaks swap chains)
            panel_rows = k * mb + jnp.arange(mb, dtype=jnp.int32)
            is_winner = jnp.zeros((m_pad,), bool).at[winners].set(True)
            in_panel = (g_rows >= k * mb) & (g_rows < k * mb + mb)
            hole = is_winner & ~in_panel  # vacated positions
            disp = in_panel & ~is_winner  # panel rows needing a home
            hrank = jnp.cumsum(hole) - 1
            drank = jnp.cumsum(disp) - 1
            disp_by_rank = (
                jnp.zeros((m_pad,), jnp.int32)
                .at[jnp.where(disp, drank, m_pad)]
                .set(g_rows, mode="drop")
            )
            step_perm = jnp.arange(m_pad, dtype=jnp.int32)
            step_perm = step_perm.at[panel_rows].set(winners)
            step_perm = jnp.where(hole, disp_by_rank[hrank], step_perm)
            cand_dst = jnp.concatenate([panel_rows, winners])
            src = step_perm[cand_dst]
            contrib = _fetch_rows(tl, src, p, r, mb)
            fetched = lax.psum(contrib, ROW_AXIS)
            tl = _write_rows(tl, cand_dst, fetched, p, r, mb)
            perm_total = perm_total[step_perm]

            # -- 5. panel gather (post-exchange) + no-pivot factor --------
            pan_loc = lax.dynamic_slice_in_dim(tl, k // q, 1, axis=1)[:, 0]
            pan_q = lax.all_gather(pan_loc, COL_AXIS)
            pan_rows = lax.dynamic_index_in_dim(pan_q, k % q, 0, keepdims=False)
            pan_full = lax.all_gather(pan_rows, ROW_AXIS).reshape(p * mtl, mb, mb)
            panel2d = pan_full[row_scatter].reshape(m_pad, mb)
            active_len = m_pad - k * mb
            panel_act = jnp.roll(panel2d, -k * mb, axis=0)
            panel_act = jnp.where(
                (g_rows < active_len)[:, None],
                panel_act,
                jnp.zeros_like(panel_act),
            )
            lu_pan, _ = panel_lu(panel_act, pivot=False)

            # -- 6. write factored panel back (rows >= k only) ------------
            lu_nat = jnp.roll(lu_pan, k * mb, axis=0).reshape(layout.P, mb, mb)
            pan_storage = lu_nat[row_gather]
            mine = lax.dynamic_slice_in_dim(pan_storage, r * mtl, mtl, axis=0)
            cur_col = lax.dynamic_slice_in_dim(tl, k // q, 1, axis=1)[:, 0]
            row_ge_k = (gi >= k)[:, None, None]
            owner_c = c == (k % q)
            new_col = jnp.where(row_ge_k & owner_c, mine, cur_col)
            tl = lax.dynamic_update_slice_in_dim(
                tl, new_col[:, None], k // q, axis=1
            )

            # -- 7. U row on its owner, bcast down 'p' --------------------
            Lkk_full = lu_nat[k]
            Lkk = jnp.tril(Lkk_full, -1) + jnp.eye(mb, dtype=Lkk_full.dtype)
            row_tiles = lax.dynamic_index_in_dim(tl, k // p, 0, keepdims=False)
            U_row = lax.linalg.triangular_solve(
                jnp.broadcast_to(Lkk, row_tiles.shape),
                row_tiles,
                left_side=True,
                lower=True,
                unit_diagonal=True,
            )
            own_row = r == (k % p)
            U_row = jnp.where(own_row, U_row, jnp.zeros_like(U_row))
            U_row = lax.psum(U_row, ROW_AXIS)
            j_gt = (gj > k)[:, None, None]
            new_row = jnp.where(j_gt & own_row, U_row, row_tiles)
            tl = lax.dynamic_update_index_in_dim(tl, new_row, k // p, axis=0)

            # -- 8. trailing update ---------------------------------------
            upd = jnp.einsum("iab,jbc->ijac", mine, U_row)
            mask = ((gi[:, None] > k) & (gj[None, :] > k))[:, :, None, None]
            tl = tl - jnp.where(mask, upd, jnp.zeros_like(upd))
            return tl, perm_total

        perm0 = jnp.arange(m_pad, dtype=jnp.int32)
        return lax.fori_loop(0, nt, step, (tl, perm0))

    spec = P(ROW_AXIS, COL_AXIS)
    fn = shard_map(
        local, mesh=grid.mesh, in_specs=(spec,), out_specs=(spec, P())
    )
    return fn(T)
