"""Distributed Householder QR over the mesh (shard_map).

TPU-native re-design of the reference geqrf (reference: src/geqrf.cc:26-230:
per-panel internal::geqrf local panel + internal::ttqrt inter-rank binary
tpqrt tree + listBcast of V/Tlocal/Treduce + internal::unmqr/ttmqr trailing
application; SURVEY §3.4).

Instead of the CAQR tree, the panel is rebuilt on every process by two
all_gathers and factored redundantly — the same panel-gather strategy as
spmd_chol/spmd_lu (the tree's log2(p) latency win matters at very large p;
the gather costs one ICI hop and removes the tree's send/recv choreography
entirely).  The trailing update is the compact-WY rank-nb update

    C <- (I - V T^H V^H) C

evaluated distributed: W = V^H C is a local contraction + psum over 'p'
(the reference's tile-reduce), then C -= V (T^H W) locally — one einsum
per step, batched over all local tiles (the analogue of internal::unmqr's
batched device gemms).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..ops.householder import geqrf as _geqrf_kernel, larft
from ..parallel.grid import COL_AXIS, ROW_AXIS, ProcessGrid
from ..parallel.layout import TileLayout
from .spmd_blas import shard_map

from ..aux.metrics import instrumented


@instrumented("spmd.geqrf")
def spmd_geqrf(
    grid: ProcessGrid, T: jnp.ndarray, layout: TileLayout
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Factor A = Q R over the mesh.

    Returns (tiles, Tstack): tiles hold R on/above the diagonal and the
    Householder V (unit diag implicit) below; Tstack is (kt, nb, nb) with
    the compact-WY T factor of every panel, replicated.
    """
    p, q = grid.p, grid.q
    mb = layout.mb
    assert mb == layout.nb, "geqrf requires square tiles"
    kt = min(layout.mt, layout.nt)
    mtl, ntl = layout.mtl, layout.ntl
    m_pad = layout.P * mb
    row_scatter = jnp.asarray(layout.row_scatter)
    row_gather = jnp.asarray(layout.row_gather)
    complex_t = jnp.issubdtype(T.dtype, jnp.complexfloating)

    def conj(x):
        return jnp.conj(x) if complex_t else x

    def local(tl):
        r = lax.axis_index(ROW_AXIS)
        c = lax.axis_index(COL_AXIS)
        gi = jnp.arange(mtl) * p + r
        gj = jnp.arange(ntl) * q + c
        g_rows = jnp.arange(m_pad, dtype=jnp.int32)

        def step(k, carry):
            tl, Tstack = carry
            # -- 1. gather panel column k, roll active rows on top --------
            pan_loc = lax.dynamic_slice_in_dim(tl, k // q, 1, axis=1)[:, 0]
            pan_q = lax.all_gather(pan_loc, COL_AXIS)
            pan_rows = lax.dynamic_index_in_dim(pan_q, k % q, 0, keepdims=False)
            pan_full = lax.all_gather(pan_rows, ROW_AXIS).reshape(p * mtl, mb, mb)
            panel2d = pan_full[row_scatter].reshape(m_pad, mb)
            active_len = m_pad - k * mb
            pact = jnp.roll(panel2d, -k * mb, axis=0)
            pact = jnp.where((g_rows < active_len)[:, None], pact, 0)

            # -- 2. redundant panel QR + T factor -------------------------
            vr, taus = _geqrf_kernel(pact)
            rows = g_rows[:, None]
            cols = jnp.arange(mb)[None, :]
            V_act = jnp.where(rows > cols, vr, 0) + jnp.where(
                rows == cols, jnp.ones_like(vr), 0
            )
            V_act = jnp.where((g_rows < active_len)[:, None], V_act, 0)
            Tk = larft(V_act, taus)
            Tstack = lax.dynamic_update_index_in_dim(
                Tstack, Tk.astype(Tstack.dtype), k, 0
            )

            # -- 3. write factored column back (rows >= k) ----------------
            fac_nat = jnp.roll(vr, k * mb, axis=0).reshape(layout.P, mb, mb)
            fac_st = fac_nat[row_gather]
            mine = lax.dynamic_slice_in_dim(fac_st, r * mtl, mtl, axis=0)
            cur_col = lax.dynamic_slice_in_dim(tl, k // q, 1, axis=1)[:, 0]
            row_ge = (gi >= k)[:, None, None]
            owner_c = c == (k % q)
            new_col = jnp.where(row_ge & owner_c, mine, cur_col)
            tl = lax.dynamic_update_slice_in_dim(tl, new_col[:, None], k // q, axis=1)

            # -- 4. trailing update: C <- (I - V T^H V^H) C ---------------
            V_nat = jnp.roll(V_act, k * mb, axis=0).reshape(layout.P, mb, mb)
            V_st = V_nat[row_gather]
            V_loc = lax.dynamic_slice_in_dim(V_st, r * mtl, mtl, axis=0)
            # W = sum over local row tiles of V_i^H C_ij, psum over 'p'
            W = jnp.einsum("iav,ijab->vjb", conj(V_loc), tl)
            W = lax.psum(W, ROW_AXIS)  # (mb, ntl, nb)
            TW = jnp.einsum("vw,vjb->wjb", conj(Tk), W)
            upd = jnp.einsum("iaw,wjb->ijab", V_loc, TW)
            jmask = (gj > k)[None, :, None, None]
            tl = tl - jnp.where(jmask, upd, jnp.zeros_like(upd))
            return tl, Tstack

        T0 = jnp.zeros((kt, mb, mb), tl.dtype)
        return lax.fori_loop(0, kt, step, (tl, T0))

    spec = P(ROW_AXIS, COL_AXIS)
    fn = shard_map(local, mesh=grid.mesh, in_specs=(spec,), out_specs=(spec, P()))
    return fn(T)
