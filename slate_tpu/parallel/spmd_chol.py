"""Distributed right-looking Cholesky over the mesh (shard_map).

TPU-native re-design of the reference potrf driver (reference:
src/potrf.cc:84-209 — per-k: diagonal tile potrf, tileBcast down the
column, internal::trsm of the panel, listBcastMT along rows/cols,
internal::herk trailing update with lookahead queues).

The TPU schedule per step k (inside one lax.fori_loop, static shapes):

1. gather panel column k: two all_gathers (over 'q' then 'p') rebuild the
   full tile column on every process — this fuses the reference's column
   tileBcast + row/col listBcastMT into ICI collectives;
2. every process redundantly factors the mb x mb diagonal tile and
   triangular-solves the gathered panel (panel flops are O(mt mb^3),
   negligible next to the trailing update, and redundancy removes a
   broadcast round-trip — replacing the MPI sub-communicator dance of
   internal_potrf.cc:57-75);
3. local trailing update: one einsum over the local tile stack, masked to
   tiles (i > k, j > k) — the analogue of internal::herk's one batched
   device call (internal_gemm.cc batching);
4. the panel column of L is written back into local storage on its owner
   column.

Numerical failure (non-SPD) surfaces as NaNs from the Cholesky of the
diagonal tile; the driver reduces an info code afterwards (reference:
internal::reduce_info, potrf.cc:208).

Option.Lookahead note: the reference's lookahead queues overlap the
next panel's factor with the trailing herk on separate host/device
streams.  Inside one compiled shard_map fori_loop there is no stream
to schedule — XLA already overlaps independent ops within the step,
and the k+1 panel column depends on the k trailing update, so an
explicit lookahead here has nothing to control.  The option instead
drives the eager-panel peel of the single-chip recursive schedules
(ops/chol_kernels.chol_recursive, ops/lu_kernels.getrf_recursive),
threaded through drivers/chol.resolve_schedule_opts.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..ops import chol_kernels
from ..parallel.grid import COL_AXIS, ROW_AXIS, ProcessGrid
from ..parallel.layout import TileLayout
from .spmd_blas import shard_map

from ..aux.metrics import instrumented


@instrumented("spmd.potrf_lower")
def spmd_potrf_lower(
    grid: ProcessGrid, T: jnp.ndarray, layout: TileLayout
) -> jnp.ndarray:
    """In the lower triangle of the returned tile array: L with A = L L^H.

    T must be the storage-order tile array of a padded-SPD matrix (padding
    diagonal spliced to 1) with mb == nb.
    """
    p, q = grid.p, grid.q
    nt = layout.nt
    mtl, ntl = layout.mtl, layout.ntl
    mb = layout.mb
    complex_t = jnp.issubdtype(T.dtype, jnp.complexfloating)

    def conj_t(x):
        return jnp.conj(x) if complex_t else x

    def local(tl):
        r = lax.axis_index(ROW_AXIS)
        c = lax.axis_index(COL_AXIS)
        # global tile indices of local rows/cols
        gi = jnp.arange(mtl) * p + r  # (mtl,)
        gj = jnp.arange(ntl) * q + c  # (ntl,)

        def step(k, tl):
            # -- 1. gather panel column k ---------------------------------
            pan_loc = lax.dynamic_slice_in_dim(tl, k // q, 1, axis=1)[:, 0]
            pan_q = lax.all_gather(pan_loc, COL_AXIS)  # (q, mtl, mb, mb)
            pan_rows = lax.dynamic_index_in_dim(pan_q, k % q, 0, keepdims=False)
            pan_full = lax.all_gather(pan_rows, ROW_AXIS)  # (p, mtl, mb, mb)
            pan_full = pan_full.reshape(p * mtl, mb, mb)  # storage-row order

            # -- 2. redundant diagonal factor + panel trsm ----------------
            slot_k = (k % p) * mtl + k // p
            Akk = lax.dynamic_index_in_dim(pan_full, slot_k, 0, keepdims=False)
            # backend-dispatched tile factor: native strip kernel on the
            # chip (the vendor cholesky lowering runs at ~1-5 GF/s at
            # tile sizes there), vendor LAPACK on CPU
            Lkk = chol_kernels.cholesky(Akk, mb)
            # L(i,k) = A(i,k) Lkk^-H  (right solve with lower^H)
            Lcol = lax.linalg.triangular_solve(
                jnp.broadcast_to(Lkk, pan_full.shape),
                pan_full,
                left_side=False,
                lower=True,
                transpose_a=True,
                conjugate_a=complex_t,
            )
            # write Lkk into the panel's diagonal slot
            Lcol = lax.dynamic_update_index_in_dim(Lcol, Lkk, slot_k, 0)

            # -- 3. local trailing update --------------------------------
            # left factor: rows of L(:,k) this process owns (contiguous
            # storage block r*mtl .. r*mtl+mtl)
            left = lax.dynamic_slice_in_dim(Lcol, r * mtl, mtl, axis=0)
            # right factor: L(j,k) for local column indices j
            slots_j = (gj % p) * mtl + gj // p
            right = Lcol[slots_j]  # (ntl, mb, mb) dynamic gather
            upd = jnp.einsum(
                "iab,jcb->ijac", left, conj_t(right),
            )
            mask = ((gi[:, None] > k) & (gj[None, :] > k))[:, :, None, None]
            tl = tl - jnp.where(mask, upd, jnp.zeros_like(upd))

            # -- 4. write the L panel back on its owner column ------------
            row_mask = (gi >= k)[:, None, None]
            new_col = jnp.where(row_mask, left, pan_loc)
            cur_col = lax.dynamic_slice_in_dim(tl, k // q, 1, axis=1)[:, 0]
            owner = (c == k % q)
            new_col = jnp.where(owner, new_col, cur_col)
            tl = lax.dynamic_update_slice_in_dim(
                tl, new_col[:, None], k // q, axis=1
            )
            return tl

        return lax.fori_loop(0, nt, step, tl)

    spec = P(ROW_AXIS, COL_AXIS)
    fn = shard_map(local, mesh=grid.mesh, in_specs=(spec,), out_specs=spec)
    return fn(T)
