"""Distributed triangular solve + row permutation over the mesh.

TPU-native re-design of the reference's trsm work pipelines (reference:
src/trsm.cc:1-150 -> trsmA/trsmB dispatch, src/work/work_trsm.cc:106-140 —
per-k: tileBcast of the diagonal block down the column, internal::trsm of
block row k, listBcast of X's row k, internal::gemm trailing update with
lookahead) and of internal_swap.cc's pivot row exchanges.

The TPU schedule per step k (inside one lax.fori_loop, static shapes):

1. **factor column/row gather**: rebuild the tiles op(T)(i, k) needed by
   this process's local rows — one all_gather over the 'q' axis (NoTrans:
   T's tile column k stays row-distributed) or an all_gather + psum
   broadcast (Trans/ConjTrans: T's tile row k lives on one process row) —
   replacing the reference's per-tile MPI broadcasts with ICI collectives;
2. **block-row solve**: the owner process row triangular-solves
   op(T)(k,k)^-1 B(k,:) locally and the result is psum-broadcast down the
   'p' axis (work_trsm.cc's bcast of the solved row);
3. **trailing update**: B(i,:) -= op(T)(i,k) X(k,:) for the not-yet-solved
   local rows — one masked einsum over the local tile stack, the analogue
   of internal::gemm's one batched device call.

Forward (effective-lower) solves run k = 0..nt-1; backward
(effective-upper) run k = nt-1..0; both directions share the same step.

Unlike the reference there is no stationary-A variant: on TPU the solved
row broadcast rides ICI and XLA overlaps it with the trailing einsum, so
the single pipeline covers both regimes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel.grid import COL_AXIS, ROW_AXIS, ProcessGrid
from ..parallel.layout import TileLayout
from .spmd_blas import shard_map

from ..aux.metrics import instrumented


@instrumented("spmd.trsm_left")
def spmd_trsm_left(
    grid: ProcessGrid,
    TT: jnp.ndarray,
    layT: TileLayout,
    TB: jnp.ndarray,
    layB: TileLayout,
    *,
    lower: bool,
    trans: bool,
    conj: bool,
    unit_diag: bool,
    alpha=1.0,
) -> jnp.ndarray:
    """Solve op(T) X = alpha B in place of B's tile array.

    TT: storage-order tiles of the square triangular matrix (mb == nb;
    padding diagonal spliced to 1 by the caller, see layout.eye_splice).
    ``lower`` refers to the *storage* triangle; ``trans``/``conj`` give the
    op of the view being solved.  Only the relevant triangle of TT is read,
    so an LU-packed tile array works for both its L and U solves.
    """
    p, q = grid.p, grid.q
    assert layT.m == layT.n and layT.mb == layT.nb, "trsm T must be square tiles"
    assert layT.mb == layB.mb, "T/B tile-row mismatch"
    assert (layT.p, layT.q) == (layB.p, layB.q) == (p, q), "grid mismatch"
    nt = layT.nt
    assert layB.mt == nt, "T/B tile-count mismatch"
    mtlT, ntlT = layT.mtl, layT.ntl
    mtlB = layB.mtl
    mb = layT.mb
    eff_lower = lower != trans  # triangle of op(T)
    forward = eff_lower
    complex_t = jnp.issubdtype(TT.dtype, jnp.complexfloating)
    do_conj = conj and complex_t

    def local(tt, tb):
        r = lax.axis_index(ROW_AXIS)
        c = lax.axis_index(COL_AXIS)
        gi = jnp.arange(mtlB) * p + r  # global tile rows of local B rows

        tb = (jnp.asarray(alpha, tb.dtype) * tb) if alpha != 1.0 else tb

        def step(kk, tb):
            k = kk if forward else nt - 1 - kk

            # -- 1. tiles op(T)(gi, k) for local rows + replicated diag ---
            if not trans:
                col_loc = lax.dynamic_slice_in_dim(tt, k // q, 1, axis=1)[:, 0]
                col_q = lax.all_gather(col_loc, COL_AXIS)  # (q, mtlT, mb, mb)
                left_tiles = lax.dynamic_index_in_dim(
                    col_q, k % q, 0, keepdims=False
                )  # (mtlT, mb, mb) = T(gi, k)
                own_diag = r == (k % p)
                dcand = lax.dynamic_index_in_dim(
                    left_tiles, k // p, 0, keepdims=False
                )
                Tkk = lax.psum(
                    jnp.where(own_diag, dcand, jnp.zeros_like(dcand)), ROW_AXIS
                )
                if do_conj:
                    # solve conj(T) X = B (Op.Conj view without transpose)
                    left_tiles = jnp.conj(left_tiles)
                    Tkk = jnp.conj(Tkk)
            else:
                row_loc = lax.dynamic_index_in_dim(tt, k // p, 0, keepdims=False)
                row_q = lax.all_gather(row_loc, COL_AXIS)  # (q, ntlT, mb, mb)
                row_full = row_q.reshape(q * ntlT, mb, mb)
                own_row_T = r == (k % p)
                row_full = lax.psum(
                    jnp.where(own_row_T, row_full, jnp.zeros_like(row_full)),
                    ROW_AXIS,
                )  # replicated T(k, :) in storage-column order
                slots = (gi % q) * ntlT + gi // q
                sel = row_full[slots]  # T(k, gi)
                left_tiles = jnp.swapaxes(sel, -1, -2)
                dslot = (k % q) * ntlT + k // q
                Tkk = jnp.swapaxes(row_full[dslot], -1, -2)
                if do_conj:
                    left_tiles = jnp.conj(left_tiles)
                    Tkk = jnp.conj(Tkk)

            # -- 2. solve block row k on its owner process row ------------
            row_tiles = lax.dynamic_index_in_dim(tb, k // p, 0, keepdims=False)
            X_row = lax.linalg.triangular_solve(
                jnp.broadcast_to(Tkk, row_tiles.shape[:1] + Tkk.shape),
                row_tiles,
                left_side=True,
                lower=eff_lower,
                unit_diagonal=unit_diag,
            )
            own_row = r == (k % p)
            X_row = lax.psum(
                jnp.where(own_row, X_row, jnp.zeros_like(X_row)), ROW_AXIS
            )
            new_row = jnp.where(own_row, X_row, row_tiles)
            tb = lax.dynamic_update_index_in_dim(tb, new_row, k // p, axis=0)

            # -- 3. trailing update over not-yet-solved local rows --------
            mask_i = (gi > k) if forward else (gi < k)
            left_act = jnp.where(
                mask_i[:, None, None], left_tiles, jnp.zeros_like(left_tiles)
            )
            upd = jnp.einsum("iab,jbc->ijac", left_act, X_row)
            return tb - upd.astype(tb.dtype)

        return lax.fori_loop(0, nt, step, tb)

    spec = P(ROW_AXIS, COL_AXIS)
    fn = shard_map(local, mesh=grid.mesh, in_specs=(spec, spec), out_specs=spec)
    return fn(TT, TB)


@instrumented("spmd.permute_rows")
def spmd_permute_rows(
    grid: ProcessGrid,
    TB: jnp.ndarray,
    layB: TileLayout,
    perm: jnp.ndarray,
) -> jnp.ndarray:
    """Apply a global row permutation: new row i = old row perm[i].

    TPU-native analogue of internal::permuteRows (reference:
    internal_swap.cc:115-370 — per-row MPI exchanges with the pivot root):
    every destination row is fetched from its owner with one masked psum
    over the 'p' axis.  ``perm`` indexes the padded natural element rows
    (length layB.P * mb), as produced by spmd_lu.spmd_getrf.
    """
    p = layB.p
    mtl, mb = layB.mtl, layB.mb
    P_ = layB.P

    def local(tb, perm):
        # The psum must carry contributions for EVERY destination row (all
        # process rows sum the same array), so fetch the full padded row
        # space and extract the local tile rows afterwards.
        r = lax.axis_index(ROW_AXIS)
        src = perm  # (P_*mb,) source element row of each dest row
        sti = src // mb
        sli = sti // p
        soff = src % mb
        own = (sti % p) == r
        vals = jax.vmap(lambda l, o: tb[l, :, o, :])(sli, soff)
        vals = jnp.where(own[:, None, None], vals, jnp.zeros_like(vals))
        vals = lax.psum(vals, ROW_AXIS)  # (P_*mb, ntl, nb)
        vals = vals.reshape(P_, mb, tb.shape[1], tb.shape[3])
        gi = jnp.arange(mtl) * p + r  # global tile rows stored locally
        return vals[gi].transpose(0, 2, 1, 3)

    spec = P(ROW_AXIS, COL_AXIS)
    fn = shard_map(
        local, mesh=grid.mesh, in_specs=(spec, P()), out_specs=spec
    )
    return fn(TB, perm.astype(jnp.int32))


@instrumented("spmd.trsm_right")
def spmd_trsm_right(
    grid: ProcessGrid,
    TT: jnp.ndarray,
    layT: TileLayout,
    TB: jnp.ndarray,
    layB: TileLayout,
    *,
    lower: bool,
    trans: bool,
    conj: bool,
    unit_diag: bool,
    alpha=1.0,
) -> jnp.ndarray:
    """Solve X op(T) = alpha B in place of B's tile array — the
    column-pipeline dual of spmd_trsm_left (reference: trsmB's right-side
    work pipeline, src/work/work_trsm.cc): per step the solved block
    COLUMN is broadcast along 'q' and the trailing update runs over the
    not-yet-solved local columns.
    """
    p, q = grid.p, grid.q
    assert layT.m == layT.n and layT.mb == layT.nb, "trsm T must be square tiles"
    assert layT.mb == layB.nb, "T/B tile-col mismatch"
    assert (layT.p, layT.q) == (layB.p, layB.q) == (p, q), "grid mismatch"
    nt = layT.nt
    assert layB.nt == nt, "T/B tile-count mismatch"
    mtlT, ntlT = layT.mtl, layT.ntl
    ntlB = layB.ntl
    mb = layT.mb
    eff_lower = lower != trans  # triangle of op(T)
    forward = not eff_lower  # X U = B solves column 0 first
    complex_t = jnp.issubdtype(TT.dtype, jnp.complexfloating)
    do_conj = conj and complex_t

    def local(tt, tb):
        r = lax.axis_index(ROW_AXIS)
        c = lax.axis_index(COL_AXIS)
        gj = jnp.arange(ntlB) * q + c  # global tile cols of local B cols

        tb = (jnp.asarray(alpha, tb.dtype) * tb) if alpha != 1.0 else tb

        def step(kk, tb):
            k = kk if forward else nt - 1 - kk

            # -- 1. tiles op(T)(k, gj) for local cols + replicated diag ---
            if not trans:
                # T's tile row k: owner process row k % p, columns already
                # distributed the way B's are -> psum-broadcast down 'p'
                row_loc = lax.dynamic_index_in_dim(tt, k // p, 0, keepdims=False)
                own_row_T = r == (k % p)
                right_tiles = lax.psum(
                    jnp.where(own_row_T, row_loc, jnp.zeros_like(row_loc)),
                    ROW_AXIS,
                )  # (ntlT, mb, mb) = T(k, gj)
                dcand = lax.dynamic_index_in_dim(
                    right_tiles, k // q, 0, keepdims=False
                )
                own_diag = c == (k % q)
                Tkk = lax.psum(
                    jnp.where(own_diag, dcand, jnp.zeros_like(dcand)), COL_AXIS
                )
                if do_conj:
                    right_tiles = jnp.conj(right_tiles)
                    Tkk = jnp.conj(Tkk)
            else:
                # op(T)(k, gj) = T(gj, k)^T: T's tile column k, owner
                # process col k % q -> psum-broadcast along 'q', then
                # select the slots of this process's gj and transpose
                col_loc = lax.dynamic_slice_in_dim(tt, k // q, 1, axis=1)[:, 0]
                own_col_T = c == (k % q)
                col_bc = lax.psum(
                    jnp.where(own_col_T, col_loc, jnp.zeros_like(col_loc)),
                    COL_AXIS,
                )  # (mtlT, mb, mb) local storage rows of T(:, k)
                col_full = lax.all_gather(col_bc, ROW_AXIS).reshape(
                    p * mtlT, mb, mb
                )  # replicated T(:, k) in storage-row order
                slots = (gj % p) * mtlT + gj // p
                sel = col_full[slots]  # T(gj, k)
                right_tiles = jnp.swapaxes(sel, -1, -2)
                dslot = (k % p) * mtlT + k // p
                Tkk = jnp.swapaxes(col_full[dslot], -1, -2)
                if do_conj:
                    right_tiles = jnp.conj(right_tiles)
                    Tkk = jnp.conj(Tkk)

            # -- 2. solve block column k on its owner process column ------
            col_tiles = lax.dynamic_slice_in_dim(tb, k // q, 1, axis=1)[:, 0]
            X_col = lax.linalg.triangular_solve(
                jnp.broadcast_to(Tkk, col_tiles.shape[:1] + Tkk.shape),
                col_tiles,
                left_side=False,
                lower=eff_lower,
                unit_diagonal=unit_diag,
            )
            own_col = c == (k % q)
            X_col = lax.psum(
                jnp.where(own_col, X_col, jnp.zeros_like(X_col)), COL_AXIS
            )
            new_col = jnp.where(own_col, X_col, col_tiles)
            tb = lax.dynamic_update_slice_in_dim(
                tb, new_col[:, None], k // q, axis=1
            )

            # -- 3. trailing update over not-yet-solved local columns -----
            mask_j = (gj > k) if forward else (gj < k)
            right_act = jnp.where(
                mask_j[:, None, None], right_tiles, jnp.zeros_like(right_tiles)
            )
            upd = jnp.einsum("iab,jbc->ijac", X_col, right_act)
            return tb - upd.astype(tb.dtype)

        return lax.fori_loop(0, nt, step, tb)

    spec = P(ROW_AXIS, COL_AXIS)
    fn = shard_map(local, mesh=grid.mesh, in_specs=(spec, spec), out_specs=spec)
    return fn(TT, TB)
