"""Distribution-to-distribution tile re-send over the mesh.

TPU-native redistribute (reference: src/redistribute.cc — per-tile
MPI sends between two layouts).  The GSPMD element-gather route in
drivers/aux.py is free to replicate the source; this kernel bounds the
traffic explicitly with two masked-psum phases, the same primitive the
pivot row-exchange uses (spmd_trsm.spmd_permute_rows):

1. row phase: every destination element row is fetched from its owner
   process row with one psum over 'p' (columns stay source-distributed
   — O(n^2 / q) per process);
2. column phase: dual over 'q' (rows now destination-distributed —
   O(n^2 / p) per process).

Both layouts must live on the same process grid (p, q); the driver
falls back to the recorded gather route otherwise.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from .grid import COL_AXIS, ROW_AXIS, ProcessGrid
from .layout import TileLayout
from .spmd_blas import shard_map

from ..aux.metrics import instrumented


@instrumented("spmd.redistribute")
def spmd_redistribute(
    grid: ProcessGrid,
    TA: jnp.ndarray,
    layA: TileLayout,
    layB: TileLayout,
    out_dtype=None,
) -> jnp.ndarray:
    """Return B's (P_B, Q_B, mbB, nbB) tile array holding A's elements."""
    p, q = grid.p, grid.q
    assert (layA.p, layA.q) == (p, q) and (layB.p, layB.q) == (p, q)
    assert (layA.m, layA.n) == (layB.m, layB.n)
    m, n = layA.m, layA.n
    mbA, nbA = layA.mb, layA.nb
    mbB, nbB = layB.mb, layB.nb
    mtlA, ntlA = layA.mtl, layA.ntl
    mtlB, ntlB = layB.mtl, layB.ntl
    out_dtype = out_dtype or TA.dtype

    # static element maps: destination padded element row -> source
    # (tile-row slot local index, in-tile offset, owner process row)
    dst_rows = np.minimum(
        layB.global_rows_np.reshape(-1), m - 1
    )  # (P_B * mbB,)
    src_ti = np.minimum(dst_rows // mbA, layA.mt - 1)
    row_src_local = (src_ti // p).astype(np.int32)  # local tile-row slot
    row_src_owner = (src_ti % p).astype(np.int32)
    row_src_off = (dst_rows % mbA).astype(np.int32)

    dst_cols = np.minimum(layB.global_cols_np.reshape(-1), n - 1)
    src_tj = np.minimum(dst_cols // nbA, layA.nt - 1)
    col_src_local = (src_tj // q).astype(np.int32)
    col_src_owner = (src_tj % q).astype(np.int32)
    col_src_off = (dst_cols % nbA).astype(np.int32)

    rl = jnp.asarray(row_src_local)
    ro = jnp.asarray(row_src_owner)
    rf = jnp.asarray(row_src_off)
    cl = jnp.asarray(col_src_local)
    co = jnp.asarray(col_src_owner)
    cf = jnp.asarray(col_src_off)

    def local(ta):
        r = lax.axis_index(ROW_AXIS)
        c = lax.axis_index(COL_AXIS)
        # -- phase 1: rows -> B distribution (psum over 'p') -----------
        # vals[d] = A element row for padded destination row d, over
        # this process's LOCAL source columns
        vals = jax.vmap(lambda sl, so: ta[sl, :, so, :])(rl, rf)
        own = (ro == r)[:, None, None]
        vals = jnp.where(own, vals, 0)
        vals = lax.psum(vals, ROW_AXIS)  # (P_B*mbB, ntlA, nbA)
        # keep this process row's destination tile rows
        vals = vals.reshape(layB.P, mbB, ntlA, nbA)
        gi = jnp.arange(mtlB) * p + r  # global B tile rows held here
        slots = (gi % p) * mtlB + gi // p  # storage slots of those rows
        mine = vals[slots]  # (mtlB, mbB, ntlA, nbA)

        # -- phase 2: columns -> B distribution (psum over 'q') --------
        flat = mine.reshape(mtlB * mbB, ntlA * nbA)
        cols = jax.vmap(lambda sl, so: flat[:, sl * nbA + so])(cl, cf)
        cvals = jnp.where((co == c)[:, None], cols, 0)
        cvals = lax.psum(cvals, COL_AXIS)  # (Q_B*nbB, mtlB*mbB)
        cvals = cvals.reshape(layB.Q, nbB, mtlB * mbB)
        gj = jnp.arange(ntlB) * q + c
        cslots = (gj % q) * ntlB + gj // q
        minec = cvals[cslots]  # (ntlB, nbB, mtlB*mbB)
        out = minec.transpose(2, 0, 1).reshape(mtlB, mbB, ntlB, nbB)
        out = out.transpose(0, 2, 1, 3)
        # zero the padding elements of B's layout
        rm = jnp.asarray(layB.row_mask_np)[slots]  # (mtlB, mbB)
        cm = jnp.asarray(layB.col_mask_np)[cslots]  # (ntlB, nbB)
        mask = rm[:, None, :, None] & cm[None, :, None, :]
        return jnp.where(mask, out, 0).astype(out_dtype)

    spec = P(ROW_AXIS, COL_AXIS)
    fn = shard_map(local, mesh=grid.mesh, in_specs=(spec,), out_specs=spec)
    return fn(TA)
