"""Explicit SPMD BLAS3 over the device mesh (shard_map + ICI collectives).

TPU-native replacement for the reference's SUMMA gemm with MPI tile
broadcasts (reference: src/gemmC.cc:76-201 impl::gemmC — per-k listBcastMT
of A's column k along process rows and B's row k along process columns,
then one batched device gemm per step; internal_gemm.cc:355-518).

The mapping (SURVEY §2.5):
  * tile broadcast along a process row/col  -> lax.all_gather over the
    'q'/'p' mesh sub-axis + static owner select (rides ICI),
  * per-device batched BLAS over local tiles -> one einsum over the local
    (mtl, ntl, mb, nb) tile stack,
  * the OpenMP lookahead pipeline            -> software pipelining in the
    lax.fori_loop carry: the gather for step k+1 is issued before the
    step-k einsum, letting XLA overlap communication with compute.

Everything is static-shape: the k-loop runs over global tile indices with
dynamic_slice into the cyclic local slots (slot = k // q on owner k % q).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel.grid import COL_AXIS, ROW_AXIS, ProcessGrid
from ..parallel.layout import TileLayout

from ..aux.metrics import instrumented

try:  # jax >= 0.4.35 spells it jax.shard_map
    from jax import shard_map as _shard_map_mod  # noqa: F401

    _shard_map = jax.shard_map
except (ImportError, AttributeError):  # pragma: no cover - older spelling
    from jax.experimental.shard_map import shard_map as _shard_map  # type: ignore


def shard_map(f, *, mesh, in_specs, out_specs):
    """shard_map with the varying-manual-axes check disabled: our SPMD
    kernels mix collective-produced and replicated values in loop carries,
    which the vma checker (jax >= 0.7) rejects despite being well-defined."""
    try:
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )
    except TypeError:
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
        )


def _acc_dtype(dt):
    if jnp.issubdtype(dt, jnp.complexfloating):
        return dt
    return jnp.promote_types(dt, jnp.float32)


@instrumented("spmd.summa_gemm")
def summa_gemm(
    grid: ProcessGrid,
    alpha,
    TA: jnp.ndarray,
    layA: TileLayout,
    TB: jnp.ndarray,
    layB: TileLayout,
    beta,
    TC: jnp.ndarray,
    layC: TileLayout,
) -> jnp.ndarray:
    """C = alpha A B + beta C over storage-order tile arrays on the mesh.

    A: m x k tiles (mb x kb), B: k x n tiles (kb x nb), C: m x n (mb x nb),
    all on the same p x q grid.  Returns C's new tile array.
    """
    p, q = grid.p, grid.q
    kt_total = layA.nt
    assert layB.mt == kt_total, "A/B tile-k mismatch"
    acc_t = _acc_dtype(TC.dtype)

    def local(ta, tb, tc):
        # local shards: ta (mtl, ktlA, mb, kb), tb (ktlB, ntl, kb, nb),
        # tc (mtl, ntl, mb, nb)
        def gather_k(kt):
            a_slice = lax.dynamic_slice_in_dim(ta, kt // q, 1, axis=1)
            a_all = lax.all_gather(a_slice, COL_AXIS)  # (q, mtl, 1, mb, kb)
            a_col = lax.dynamic_index_in_dim(a_all, kt % q, 0, keepdims=False)[:, 0]
            b_slice = lax.dynamic_slice_in_dim(tb, kt // p, 1, axis=0)
            b_all = lax.all_gather(b_slice, ROW_AXIS)  # (p, 1, ntl, kb, nb)
            b_row = lax.dynamic_index_in_dim(b_all, kt % p, 0, keepdims=False)[0]
            return a_col, b_row  # (mtl, mb, kb), (ntl, kb, nb)

        def step(kt, carry):
            acc, (a_col, b_row) = carry
            nxt = gather_k(kt + 1)  # issued before the einsum: lookahead
            upd = jnp.einsum(
                "iak,jkb->ijab", a_col, b_row, preferred_element_type=acc_t
            )
            return acc + upd, nxt

        acc0 = jnp.zeros(tc.shape, acc_t)
        acc, _ = lax.fori_loop(0, kt_total, step, (acc0, gather_k(0)))
        out = alpha * acc + beta * tc.astype(acc_t)
        return out.astype(tc.dtype)

    spec = P(ROW_AXIS, COL_AXIS)
    fn = shard_map(
        local,
        mesh=grid.mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(TA, TB, TC)


def gemm_reduce_a(
    grid: ProcessGrid,
    alpha,
    TA: jnp.ndarray,
    layA: TileLayout,
    TB: jnp.ndarray,
    layB: TileLayout,
    beta,
    TC: jnp.ndarray,
    layC: TileLayout,
) -> jnp.ndarray:
    """Stationary-A gemm (reference: src/gemmA.cc + internal_gemmA.cc):
    each process multiplies its local A tiles by gathered B and the partial
    C contributions are tree-reduced — here a psum_scatter over the 'q'
    axis (SURVEY §2.5 tile-reduce -> psum_scatter).

    Chosen by method auto when k is small relative to m (A tall, C small),
    mirroring gemm.cc:12-24's selection.
    """
    p, q = grid.p, grid.q
    kt_total = layA.nt
    acc_t = _acc_dtype(TC.dtype)
    ntl = layC.ntl
    ktlB = layB.mtl

    def local(ta, tb, tc):
        # Replicate B (the reference broadcasts B's rows in gemmA; B/C are
        # narrow when method A is selected).  Two gathers rebuild B's full
        # storage-order tile array on every process.
        b_p = lax.all_gather(tb, ROW_AXIS)  # (p, ktlB, ntlB, kb, nb)
        b_p = b_p.reshape((p * ktlB,) + tb.shape[1:])  # owner-major == storage
        b_full = lax.all_gather(b_p, COL_AXIS)  # (q, p*ktlB, ntlB, kb, nb)
        b_full = jnp.moveaxis(b_full, 0, 1).reshape(
            p * ktlB, q * tb.shape[1], *tb.shape[2:]
        )  # (p*ktlB, q*ntlB, kb, nb) storage order

        def step(kt, acc):
            # local A column kt (valid only on owner column kt % q)
            a_col = lax.dynamic_slice_in_dim(ta, kt // q, 1, axis=1)[:, 0]
            # full B row kt from the replicated copy (storage row slot)
            b_row = lax.dynamic_index_in_dim(
                b_full, (kt % p) * ktlB + kt // p, 0, keepdims=False
            )  # (q*ntlB, kb, nb)
            is_owner = lax.axis_index(COL_AXIS) == (kt % q)
            upd = jnp.einsum(
                "iak,jkb->ijab", a_col, b_row, preferred_element_type=acc_t
            )
            return acc + jnp.where(is_owner, upd, jnp.zeros_like(upd))

        # partial over ALL C columns (storage order), then reduce-scatter
        # over 'q' so each process keeps the sum for its own column slots
        # (reference: gemmA's reverse-tree tile reduce -> psum_scatter).
        part = lax.fori_loop(
            0, kt_total, step,
            jnp.zeros((tc.shape[0], q * ntl) + tc.shape[2:], acc_t),
        )
        total = lax.psum_scatter(part, COL_AXIS, scatter_dimension=1, tiled=True)
        out = alpha * total + beta * tc.astype(acc_t)
        return out.astype(tc.dtype)

    spec = P(ROW_AXIS, COL_AXIS)
    fn = shard_map(local, mesh=grid.mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(TA, TB, TC)


@instrumented("spmd.herk")
def spmd_herk(
    grid: ProcessGrid,
    alpha,
    TA: jnp.ndarray,
    layA: TileLayout,
    beta,
    TC: jnp.ndarray,
    layC: TileLayout,
    conj: bool,
    trans: bool,
    alpha2=None,
    TB: jnp.ndarray = None,
    layB: TileLayout = None,
    lower: bool = True,
) -> jnp.ndarray:
    """Rank-k update C = alpha op(A) op(A)^(H|T) + beta C directly from
    A's stored tiles (reference: src/herk.cc + internal_herk.cc's batched
    symmetric update).

    Unlike routing through summa_gemm, no transposed copy of A is ever
    materialized (a resolved A^H lives on the TRANSPOSED process grid —
    unusable for p != q meshes) and C needs no Hermitian mirror: per step
    k the full tile column (trans=False) or tile row (trans=True) of A is
    rebuilt on every process by two all_gathers.  With TB given this is
    the rank-2k her2k/syr2k: alpha A B^H + alpha2 B A^H + beta C.

    Triangle-aware accumulation (internal::herk touches stored tiles
    only): each process enumerates its local STORED-triangle tile pairs
    (a static-size packed list, indices traced from the mesh
    coordinates), accumulates the rank-k updates as one batched matmul
    over that packed list per step — half the all-pairs FLOPs — and
    scatters into the tile array once at the end.  Non-stored local
    tiles come back as beta * C only (the Hermitian wrapper never
    references them).
    """
    p, q = grid.p, grid.q
    kt_total = layA.mt if trans else layA.nt
    mtl, ntl = layC.mtl, layC.ntl
    rank2 = TB is not None
    acc_t = _acc_dtype(TC.dtype)
    complex_t = jnp.issubdtype(TC.dtype, jnp.complexfloating)
    row_scatter = jnp.asarray(layA.row_scatter)
    col_scatter = jnp.asarray(layA.col_scatter)

    # static upper bound of stored-triangle local pairs over all
    # processes (the packed batch size; per-process indices are traced)
    npairs = 0
    for rr in range(p):
        for cc in range(q):
            gi_ = np.arange(mtl) * p + rr
            gj_ = np.arange(ntl) * q + cc
            st = (
                (gi_[:, None] >= gj_[None, :])
                if lower
                else (gi_[:, None] <= gj_[None, :])
            )
            st &= (gi_[:, None] < layC.mt) & (gj_[None, :] < layC.nt)
            npairs = max(npairs, int(st.sum()))
    npairs = max(npairs, 1)

    def cj(x):
        return jnp.conj(x) if (conj and complex_t) else x

    def local(ta, tc, *tbs):
        r = lax.axis_index(ROW_AXIS)
        c = lax.axis_index(COL_AXIS)
        gi = jnp.arange(mtl) * p + r
        gj = jnp.arange(ntl) * q + c

        stored = (
            (gi[:, None] >= gj[None, :])
            if lower
            else (gi[:, None] <= gj[None, :])
        )
        stored &= (gi[:, None] < layC.mt) & (gj[None, :] < layC.nt)
        flat = stored.reshape(-1)
        order = jnp.argsort(~flat, stable=True)[:npairs]
        I_idx = order // ntl
        J_idx = order % ntl
        slot_ok = flat[order]  # False on padding slots (non-stored)

        def gather_col(t, k):
            # tile column k in NATURAL tile-row order: (layA.P, mb, kb)
            loc = lax.dynamic_slice_in_dim(t, k // q, 1, axis=1)[:, 0]
            aq = lax.all_gather(loc, COL_AXIS)
            rows = lax.dynamic_index_in_dim(aq, k % q, 0, keepdims=False)
            full = lax.all_gather(rows, ROW_AXIS)
            return full.reshape((layA.P,) + full.shape[2:])[row_scatter]

        def gather_row(t, k):
            # tile row k in NATURAL tile-col order: (layA.Q, kb, nb)
            loc = lax.dynamic_slice_in_dim(t, k // p, 1, axis=0)[0]
            ap = lax.all_gather(loc, ROW_AXIS)
            cols = lax.dynamic_index_in_dim(ap, k % p, 0, keepdims=False)
            full = lax.all_gather(cols, COL_AXIS)
            return full.reshape((layA.Q,) + full.shape[2:])[col_scatter]

        def panels(k):
            if trans:
                pa = gather_row(ta, k)
                pb = gather_row(tbs[0], k) if rank2 else pa
            else:
                pa = gather_col(ta, k)
                pb = gather_col(tbs[0], k) if rank2 else pa
            return pa, pb

        gi_p = gi[I_idx]  # global tile rows of the packed pairs
        gj_p = gj[J_idx]

        def tile_upd(pl, pr):
            # packed batch: C_pair += op(L)_i,k op(R)_j,k^(H|T) over the
            # stored-triangle pairs only (half the all-pairs FLOPs)
            if trans:
                # op(M)_{i,k} = M_{k,i}^(H|T): contraction over panel rows
                return jnp.einsum(
                    "pca,pcb->pab", cj(pl[gi_p]), pr[gj_p],
                    preferred_element_type=acc_t,
                )
            return jnp.einsum(
                "pak,pbk->pab", pl[gi_p], cj(pr[gj_p]),
                preferred_element_type=acc_t,
            )

        def apply(acc, pa, pb):
            if rank2:
                return acc + alpha * tile_upd(pa, pb) + alpha2 * tile_upd(pb, pa)
            return acc + alpha * tile_upd(pa, pa)

        acc = jnp.zeros((npairs,) + tc.shape[2:], acc_t)

        def step(k, carry):
            acc, (pa, pb) = carry
            nxt = panels(k + 1)  # lookahead: gather before the einsum
            return apply(acc, pa, pb), nxt

        if kt_total > 0:
            # loop stops one short so the lookahead never gathers an
            # out-of-range panel; the last panel applies after the loop
            acc, (pa, pb) = lax.fori_loop(
                0, kt_total - 1, step, (acc, panels(0))
            )
            acc = apply(acc, pa, pb)
        # one scatter back to tile-array form (padding slots zeroed; a
        # duplicate padding pair can only target a non-stored tile)
        acc = jnp.where(slot_ok[:, None, None], acc, 0)
        acc_full = (
            jnp.zeros(tc.shape, acc_t).at[I_idx, J_idx].add(acc)
        )
        out = acc_full + beta * tc.astype(acc_t)
        return out.astype(tc.dtype)

    spec = P(ROW_AXIS, COL_AXIS)
    args = (TA, TC) + ((TB,) if rank2 else ())
    fn = shard_map(
        local,
        mesh=grid.mesh,
        in_specs=(spec,) * len(args),
        out_specs=spec,
    )
    return fn(*args)


@instrumented("spmd.trmm")
def spmd_trmm(
    grid: ProcessGrid,
    side_left: bool,
    alpha,
    TA: jnp.ndarray,
    layA: TileLayout,
    lower: bool,
    unit_diag: bool,
    opa_trans: bool,
    opa_conj: bool,
    TB: jnp.ndarray,
    layB: TileLayout,
) -> jnp.ndarray:
    """Triangular multiply B <- alpha op(A) B (side_left) or
    alpha B op(A) over the mesh (reference: src/trmm.cc ->
    work::trmm's in-place pipeline, src/work/work_trmm.cc).

    Being functional, there is no in-place aliasing hazard to pipeline
    around: per step k the needed panel of op(A) is rebuilt (masked to
    the referenced triangle elementwise, honoring Diag::Unit) and B's
    block row/column k is psum-broadcast from its owner — a SUMMA over
    a triangular operand.  `lower`/`unit_diag` describe A's STORAGE
    triangle; `opa_trans`/`opa_conj` the view being multiplied.
    """
    p, q = grid.p, grid.q
    assert layA.m == layA.n and layA.mb == layA.nb
    mb = layA.mb
    nt = layA.nt
    n = layA.n
    mtlA, ntlA = layA.mtl, layA.ntl
    mtlB, ntlB = layB.mtl, layB.ntl
    acc_t = _acc_dtype(TB.dtype)
    complex_t = jnp.issubdtype(TB.dtype, jnp.complexfloating)
    row_scatter = jnp.asarray(layA.row_scatter)
    col_scatter = jnp.asarray(layA.col_scatter)

    def cjA(x):
        return jnp.conj(x) if (opa_conj and complex_t) else x

    def tri_mask_panel(pan, k, panel_is_col):
        """Mask gathered panel tiles to A's stored triangle (elementwise,
        with Diag::Unit substitution and padding zeroed)."""
        t = jnp.arange(pan.shape[0])
        a = jnp.arange(mb)
        if panel_is_col:  # pan[t] = A(t, k): rows t*mb+a, cols k*mb+b
            gr = (t[:, None, None] * mb + a[:, None])
            gc = (k * mb + a)[None, None, :]
        else:  # pan[t] = A(k, t): rows k*mb+a, cols t*mb+b
            gr = (k * mb + a)[None, :, None]
            gc = (t[:, None, None] * mb + a[None, None, :])
        keep = (gr >= gc) if lower else (gr <= gc)
        if unit_diag:
            keep = keep & (gr != gc)
        keep = keep & (gr < n) & (gc < n)
        out = jnp.where(keep, pan, jnp.zeros_like(pan))
        if unit_diag:
            out = out + jnp.where(
                (gr == gc) & (gr < n),
                jnp.ones_like(pan),
                jnp.zeros_like(pan),
            )
        return out

    def local(ta, tb):
        r = lax.axis_index(ROW_AXIS)
        c = lax.axis_index(COL_AXIS)
        gi = jnp.arange(mtlB) * p + r
        gj = jnp.arange(ntlB) * q + c

        def gather_colA(k):
            loc = lax.dynamic_slice_in_dim(ta, k // q, 1, axis=1)[:, 0]
            aq = lax.all_gather(loc, COL_AXIS)
            rows = lax.dynamic_index_in_dim(aq, k % q, 0, keepdims=False)
            full = lax.all_gather(rows, ROW_AXIS)
            return full.reshape(p * mtlA, mb, mb)[row_scatter]

        def gather_rowA(k):
            loc = lax.dynamic_slice_in_dim(ta, k // p, 1, axis=0)[0]
            ap = lax.all_gather(loc, ROW_AXIS)
            cols = lax.dynamic_index_in_dim(ap, k % p, 0, keepdims=False)
            full = lax.all_gather(cols, COL_AXIS)
            return full.reshape(q * ntlA, mb, mb)[col_scatter]

        def opA_col(k):
            """op(A)'s tile column k, natural order, triangle-masked."""
            if not opa_trans:
                return cjA(tri_mask_panel(gather_colA(k), k, True))
            pan = tri_mask_panel(gather_rowA(k), k, False)  # A(k, t)
            return cjA(jnp.swapaxes(pan, -1, -2))

        def opA_row(k):
            """op(A)'s tile row k, natural order, triangle-masked."""
            if not opa_trans:
                return cjA(tri_mask_panel(gather_rowA(k), k, False))
            pan = tri_mask_panel(gather_colA(k), k, True)  # A(t, k)
            return cjA(jnp.swapaxes(pan, -1, -2))

        def step(k, acc):
            if side_left:
                # acc(i, :) += op(A)(gi, k) B(k, :)
                pan = opA_col(k)[gi]
                b_row = lax.dynamic_index_in_dim(tb, k // p, 0, keepdims=False)
                own = r == (k % p)
                b_row = lax.psum(
                    jnp.where(own, b_row, jnp.zeros_like(b_row)), ROW_AXIS
                )
                upd = jnp.einsum(
                    "iab,jbc->ijac", pan, b_row, preferred_element_type=acc_t
                )
            else:
                # acc(:, j) += B(:, k) op(A)(k, gj)
                pan = opA_row(k)[gj]
                b_col = lax.dynamic_slice_in_dim(tb, k // q, 1, axis=1)[:, 0]
                own = c == (k % q)
                b_col = lax.psum(
                    jnp.where(own, b_col, jnp.zeros_like(b_col)), COL_AXIS
                )
                upd = jnp.einsum(
                    "iab,jbc->ijac", b_col, pan, preferred_element_type=acc_t
                )
            return acc + upd

        acc = lax.fori_loop(0, nt, step, jnp.zeros(tb.shape, acc_t))
        return (alpha * acc).astype(tb.dtype)

    spec = P(ROW_AXIS, COL_AXIS)
    fn = shard_map(local, mesh=grid.mesh, in_specs=(spec, spec), out_specs=spec)
    return fn(TA, TB)


@instrumented("spmd.hemm")
def spmd_hemm(
    grid: ProcessGrid,
    side_left: bool,
    alpha,
    TA: jnp.ndarray,
    layA: TileLayout,
    lower: bool,
    TB: jnp.ndarray,
    layB: TileLayout,
    beta,
    TC: jnp.ndarray,
    layC: TileLayout,
    hermitian: bool = True,
) -> jnp.ndarray:
    """C = alpha A B + beta C (side_left) or alpha B A + beta C, with A
    Hermitian and ONE triangle stored (reference: src/hemmA.cc's
    broadcast/reduce DAG).

    SUMMA over k where the op-full tile column (or row) k of A is
    assembled on the fly from the stored triangle: the stored tile
    column supplies the stored side of the diagonal and the stored tile
    ROW supplies the mirror A(i, k) = A(k, i)^H on the other side — two
    panel gathers per step, no global mirror round trip (the previous
    implementation materialized full_global())."""
    p, q = grid.p, grid.q
    mb = layA.mb
    nt = layA.nt
    n = layA.n
    mtlA, ntlA = layA.mtl, layA.ntl
    mtlB, ntlB = layB.mtl, layB.ntl
    acc_t = _acc_dtype(TC.dtype)
    complex_t = jnp.issubdtype(TC.dtype, jnp.complexfloating)
    row_scatter = jnp.asarray(layA.row_scatter)
    col_scatter = jnp.asarray(layA.col_scatter)

    def cj(x):
        # the mirror conjugates for Hermitian A only: complex SYMMETRIC
        # operands (symm) mirror without conjugation
        return jnp.conj(x) if (complex_t and hermitian) else x

    def local(ta, tb, tc):
        r = lax.axis_index(ROW_AXIS)
        c = lax.axis_index(COL_AXIS)
        gi = jnp.arange(layC.mtl) * p + r
        gj = jnp.arange(layC.ntl) * q + c

        def gather_colA(k):
            loc = lax.dynamic_slice_in_dim(ta, k // q, 1, axis=1)[:, 0]
            aq = lax.all_gather(loc, COL_AXIS)
            rows = lax.dynamic_index_in_dim(aq, k % q, 0, keepdims=False)
            full = lax.all_gather(rows, ROW_AXIS)
            return full.reshape(p * mtlA, mb, mb)[row_scatter]

        def gather_rowA(k):
            loc = lax.dynamic_slice_in_dim(ta, k // p, 1, axis=0)[0]
            ap = lax.all_gather(loc, ROW_AXIS)
            cols = lax.dynamic_index_in_dim(ap, k % p, 0, keepdims=False)
            full = lax.all_gather(cols, COL_AXIS)
            return full.reshape(q * ntlA, mb, mb)[col_scatter]

        t_idx_r = jnp.arange(layA.P)
        t_idx_c = jnp.arange(layA.Q)
        a_el = jnp.arange(mb)

        def realify_diag(panel, gr, gc):
            # zhemm contract: the Hermitian diagonal's imaginary parts
            # "need not be set" — drop them (full_global did the same)
            if not (complex_t and hermitian):
                return panel
            return jnp.where(
                gr == gc, jnp.real(panel).astype(panel.dtype), panel
            )

        def herm_col(k):
            """Op-full tile column k of Hermitian A, natural order."""
            colp = gather_colA(k)
            rowp = _resize_rows_3d(gather_rowA(k), layA.P)
            mirror = cj(jnp.swapaxes(rowp, -1, -2))
            gr = t_idx_r[:, None, None] * mb + a_el[:, None]
            gc = k * mb + a_el[None, None, :]
            from_stored = (gr >= gc) if lower else (gr <= gc)
            valid = (gr < n) & (gc < n)
            out = jnp.where(valid & from_stored, colp, 0) + jnp.where(
                valid & ~from_stored, mirror, 0
            )
            return realify_diag(out, gr, gc)

        def herm_row(k):
            """Op-full tile row k of Hermitian A, natural order."""
            rowp = gather_rowA(k)
            colp = _resize_rows_3d(gather_colA(k), layA.Q)
            mirror = cj(jnp.swapaxes(colp, -1, -2))
            gr = k * mb + a_el[None, :, None]
            gc = t_idx_c[:, None, None] * mb + a_el[None, None, :]
            from_stored = (gr >= gc) if lower else (gr <= gc)
            valid = (gr < n) & (gc < n)
            out = jnp.where(valid & from_stored, rowp, 0) + jnp.where(
                valid & ~from_stored, mirror, 0
            )
            return realify_diag(out, gr, gc)

        def step(k, acc):
            if side_left:
                a_col = herm_col(k)[gi]
                b_row = lax.dynamic_slice_in_dim(tb, k // p, 1, axis=0)[0]
                own = r == (k % p)
                b_row = lax.psum(
                    jnp.where(own, b_row, jnp.zeros_like(b_row)), ROW_AXIS
                )
                upd = jnp.einsum(
                    "iab,jbc->ijac", a_col, b_row,
                    preferred_element_type=acc_t,
                )
            else:
                a_row = herm_row(k)[gj]
                b_col = lax.dynamic_slice_in_dim(tb, k // q, 1, axis=1)[:, 0]
                own = c == (k % q)
                b_col = lax.psum(
                    jnp.where(own, b_col, jnp.zeros_like(b_col)), COL_AXIS
                )
                upd = jnp.einsum(
                    "iab,jbc->ijac", b_col, a_row,
                    preferred_element_type=acc_t,
                )
            return acc + upd

        acc = lax.fori_loop(0, nt, step, jnp.zeros(tc.shape, acc_t))
        out = alpha * acc + beta * tc.astype(acc_t)
        return out.astype(tc.dtype)

    spec = P(ROW_AXIS, COL_AXIS)
    fn = shard_map(
        local, mesh=grid.mesh, in_specs=(spec, spec, spec), out_specs=spec
    )
    return fn(TA, TB, TC)


def _resize_rows_3d(x: jnp.ndarray, rows: int) -> jnp.ndarray:
    if x.shape[0] == rows:
        return x
    if x.shape[0] > rows:
        return x[:rows]
    return jnp.pad(x, ((0, rows - x.shape[0]), (0, 0), (0, 0)))
