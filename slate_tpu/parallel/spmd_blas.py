"""Explicit SPMD BLAS3 over the device mesh (shard_map + ICI collectives).

TPU-native replacement for the reference's SUMMA gemm with MPI tile
broadcasts (reference: src/gemmC.cc:76-201 impl::gemmC — per-k listBcastMT
of A's column k along process rows and B's row k along process columns,
then one batched device gemm per step; internal_gemm.cc:355-518).

The mapping (SURVEY §2.5):
  * tile broadcast along a process row/col  -> lax.all_gather over the
    'q'/'p' mesh sub-axis + static owner select (rides ICI),
  * per-device batched BLAS over local tiles -> one einsum over the local
    (mtl, ntl, mb, nb) tile stack,
  * the OpenMP lookahead pipeline            -> software pipelining in the
    lax.fori_loop carry: the gather for step k+1 is issued before the
    step-k einsum, letting XLA overlap communication with compute.

Everything is static-shape: the k-loop runs over global tile indices with
dynamic_slice into the cyclic local slots (slot = k // q on owner k % q).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel.grid import COL_AXIS, ROW_AXIS, ProcessGrid
from ..parallel.layout import TileLayout

try:  # jax >= 0.4.35 spells it jax.shard_map
    from jax import shard_map as _shard_map_mod  # noqa: F401

    _shard_map = jax.shard_map
except AttributeError:  # pragma: no cover - older spelling
    from jax.experimental.shard_map import shard_map as _shard_map  # type: ignore


def shard_map(f, *, mesh, in_specs, out_specs):
    """shard_map with the varying-manual-axes check disabled: our SPMD
    kernels mix collective-produced and replicated values in loop carries,
    which the vma checker (jax >= 0.7) rejects despite being well-defined."""
    try:
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )
    except TypeError:
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
        )


def _acc_dtype(dt):
    if jnp.issubdtype(dt, jnp.complexfloating):
        return dt
    return jnp.promote_types(dt, jnp.float32)


def summa_gemm(
    grid: ProcessGrid,
    alpha,
    TA: jnp.ndarray,
    layA: TileLayout,
    TB: jnp.ndarray,
    layB: TileLayout,
    beta,
    TC: jnp.ndarray,
    layC: TileLayout,
) -> jnp.ndarray:
    """C = alpha A B + beta C over storage-order tile arrays on the mesh.

    A: m x k tiles (mb x kb), B: k x n tiles (kb x nb), C: m x n (mb x nb),
    all on the same p x q grid.  Returns C's new tile array.
    """
    p, q = grid.p, grid.q
    kt_total = layA.nt
    assert layB.mt == kt_total, "A/B tile-k mismatch"
    acc_t = _acc_dtype(TC.dtype)

    def local(ta, tb, tc):
        # local shards: ta (mtl, ktlA, mb, kb), tb (ktlB, ntl, kb, nb),
        # tc (mtl, ntl, mb, nb)
        def gather_k(kt):
            a_slice = lax.dynamic_slice_in_dim(ta, kt // q, 1, axis=1)
            a_all = lax.all_gather(a_slice, COL_AXIS)  # (q, mtl, 1, mb, kb)
            a_col = lax.dynamic_index_in_dim(a_all, kt % q, 0, keepdims=False)[:, 0]
            b_slice = lax.dynamic_slice_in_dim(tb, kt // p, 1, axis=0)
            b_all = lax.all_gather(b_slice, ROW_AXIS)  # (p, 1, ntl, kb, nb)
            b_row = lax.dynamic_index_in_dim(b_all, kt % p, 0, keepdims=False)[0]
            return a_col, b_row  # (mtl, mb, kb), (ntl, kb, nb)

        def step(kt, carry):
            acc, (a_col, b_row) = carry
            nxt = gather_k(kt + 1)  # issued before the einsum: lookahead
            upd = jnp.einsum(
                "iak,jkb->ijab", a_col, b_row, preferred_element_type=acc_t
            )
            return acc + upd, nxt

        acc0 = jnp.zeros(tc.shape, acc_t)
        acc, _ = lax.fori_loop(0, kt_total, step, (acc0, gather_k(0)))
        out = alpha * acc + beta * tc.astype(acc_t)
        return out.astype(tc.dtype)

    spec = P(ROW_AXIS, COL_AXIS)
    fn = shard_map(
        local,
        mesh=grid.mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(TA, TB, TC)


def gemm_reduce_a(
    grid: ProcessGrid,
    alpha,
    TA: jnp.ndarray,
    layA: TileLayout,
    TB: jnp.ndarray,
    layB: TileLayout,
    beta,
    TC: jnp.ndarray,
    layC: TileLayout,
) -> jnp.ndarray:
    """Stationary-A gemm (reference: src/gemmA.cc + internal_gemmA.cc):
    each process multiplies its local A tiles by gathered B and the partial
    C contributions are tree-reduced — here a psum_scatter over the 'q'
    axis (SURVEY §2.5 tile-reduce -> psum_scatter).

    Chosen by method auto when k is small relative to m (A tall, C small),
    mirroring gemm.cc:12-24's selection.
    """
    p, q = grid.p, grid.q
    kt_total = layA.nt
    acc_t = _acc_dtype(TC.dtype)
    ntl = layC.ntl
    ktlB = layB.mtl

    def local(ta, tb, tc):
        # Replicate B (the reference broadcasts B's rows in gemmA; B/C are
        # narrow when method A is selected).  Two gathers rebuild B's full
        # storage-order tile array on every process.
        b_p = lax.all_gather(tb, ROW_AXIS)  # (p, ktlB, ntlB, kb, nb)
        b_p = b_p.reshape((p * ktlB,) + tb.shape[1:])  # owner-major == storage
        b_full = lax.all_gather(b_p, COL_AXIS)  # (q, p*ktlB, ntlB, kb, nb)
        b_full = jnp.moveaxis(b_full, 0, 1).reshape(
            p * ktlB, q * tb.shape[1], *tb.shape[2:]
        )  # (p*ktlB, q*ntlB, kb, nb) storage order

        def step(kt, acc):
            # local A column kt (valid only on owner column kt % q)
            a_col = lax.dynamic_slice_in_dim(ta, kt // q, 1, axis=1)[:, 0]
            # full B row kt from the replicated copy (storage row slot)
            b_row = lax.dynamic_index_in_dim(
                b_full, (kt % p) * ktlB + kt // p, 0, keepdims=False
            )  # (q*ntlB, kb, nb)
            is_owner = lax.axis_index(COL_AXIS) == (kt % q)
            upd = jnp.einsum(
                "iak,jkb->ijab", a_col, b_row, preferred_element_type=acc_t
            )
            return acc + jnp.where(is_owner, upd, jnp.zeros_like(upd))

        # partial over ALL C columns (storage order), then reduce-scatter
        # over 'q' so each process keeps the sum for its own column slots
        # (reference: gemmA's reverse-tree tile reduce -> psum_scatter).
        part = lax.fori_loop(
            0, kt_total, step,
            jnp.zeros((tc.shape[0], q * ntl) + tc.shape[2:], acc_t),
        )
        total = lax.psum_scatter(part, COL_AXIS, scatter_dimension=1, tiled=True)
        out = alpha * total + beta * tc.astype(acc_t)
        return out.astype(tc.dtype)

    spec = P(ROW_AXIS, COL_AXIS)
    fn = shard_map(local, mesh=grid.mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(TA, TB, TC)
