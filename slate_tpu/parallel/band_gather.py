"""Band-limited stage-2 gather: tile array -> packed band storage.

The reference moves O(n kd) data between the eigensolver stages: the
band produced by he2hb/ge2tb is gathered into a 1D-distributed band
matrix, never the dense n x n (reference:
include/slate/HermitianBandMatrix.hh:310 he2hbGather,
TriangularBandMatrix.hh:327 ge2tbGather, src/heev.cc:133-151).  These
helpers are the TPU equivalents: they extract the (kd+1) stored
diagonals straight from the (P, Q, mb, nb) tile array into the
diagonal-major chase storage W[d, c] = A[c+d, c] of ops/bulge.py —
O(n kd) data, never materializing the dense matrix.

Two entry points:
* band_storage_tiles  — single-device / replicated tile arrays (also
  replaces the to_global + band_to_storage O(n^2) route everywhere);
* spmd_band_storage   — shard_map version: each process extracts its
  local diagonal/subdiagonal tiles, one psum of the packed O(n kd)
  band replicates the result (the he2hbGather analogue).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from .grid import COL_AXIS, ROW_AXIS, ProcessGrid
from .layout import TileLayout
from .spmd_blas import shard_map

from ..aux.metrics import instrumented


def _band_rowidx(nb: int) -> np.ndarray:
    """(nb+1, nb) row indices: stacked[rowidx[d, c], c] = A[c+d, c] for
    a (2nb, nb) stacked [diag; subdiag] tile pair."""
    return np.arange(nb + 1)[:, None] + np.arange(nb)[None, :]


def _assemble_w(E: jnp.ndarray, layout: TileLayout, n_pad: int) -> jnp.ndarray:
    """(nt, nb+1, nb) per-tile-column band -> (2nb+1, n_pad) W."""
    nb = layout.nb
    n = layout.n
    Wtop = E.transpose(1, 0, 2).reshape(nb + 1, layout.nt * nb)[:, :n]
    return jnp.pad(Wtop, ((0, nb), (0, n_pad - n)))


@instrumented("spmd.band_storage_tiles")
def band_storage_tiles(
    T: jnp.ndarray, layout: TileLayout, n_pad: int
) -> jnp.ndarray:
    """Pack the Hermitian band (kd = nb, lower storage) held in tile
    array T into (2nb+1, n_pad) diagonal-major storage, touching only
    the nt diagonal + nt-1 subdiagonal tiles (O(n kd) data)."""
    nb = layout.nb
    assert layout.mb == nb, "band storage requires square tiles"
    nt = layout.nt
    js = np.arange(nt)
    diag = T[np.asarray(layout.row_scatter)[js],
             np.asarray(layout.col_scatter)[js]]
    jsub = np.minimum(js + 1, layout.P - 1)
    sub = T[np.asarray(layout.row_scatter)[jsub],
            np.asarray(layout.col_scatter)[js]]
    sub = jnp.where((js < nt - 1)[:, None, None], sub, 0)
    stacked = jnp.concatenate([diag, sub], axis=1)  # (nt, 2nb, nb)
    rowidx = jnp.asarray(_band_rowidx(nb))
    E = stacked[:, rowidx, jnp.arange(nb)[None, :]]  # (nt, nb+1, nb)
    return _assemble_w(E, layout, n_pad)


@instrumented("spmd.band_storage")
def spmd_band_storage(
    grid: ProcessGrid, T: jnp.ndarray, layout: TileLayout, n_pad: int
) -> jnp.ndarray:
    """shard_map he2hbGather: every process extracts the band pieces it
    owns from its local shard; one psum of the packed (nt, nb+1, nb)
    band — O(n kd) ICI traffic — replicates W on all processes."""
    p, q = grid.p, grid.q
    nb = layout.nb
    assert layout.mb == nb, "band storage requires square tiles"
    nt = layout.nt
    mtl, ntl = layout.mtl, layout.ntl
    rowidx = jnp.asarray(_band_rowidx(nb))
    js = jnp.arange(nt)

    def local(tl):
        r = lax.axis_index(ROW_AXIS)
        c = lax.axis_index(COL_AXIS)
        # diagonal tile (j, j): local slot (j // p, j // q) when owned
        own_d = (js % p == r) & (js % q == c)
        D = tl[jnp.clip(js // p, 0, mtl - 1), jnp.clip(js // q, 0, ntl - 1)]
        D = jnp.where(own_d[:, None, None], D, 0)
        # subdiagonal tile (j+1, j)
        j1 = js + 1
        own_s = (j1 % p == r) & (js % q == c) & (j1 < layout.mt)
        S = tl[jnp.clip(j1 // p, 0, mtl - 1), jnp.clip(js // q, 0, ntl - 1)]
        S = jnp.where(own_s[:, None, None], S, 0)
        stacked = jnp.concatenate([D, S], axis=1)  # (nt, 2nb, nb)
        E = stacked[:, rowidx, jnp.arange(nb)[None, :]]
        E = lax.psum(lax.psum(E, COL_AXIS), ROW_AXIS)
        return _assemble_w(E, layout, n_pad)

    fn = shard_map(
        local,
        mesh=grid.mesh,
        in_specs=(P(ROW_AXIS, COL_AXIS),),
        out_specs=P(),
    )
    return fn(T)


# ---------------------------------------------------------------------------
# Upper-triangular band (ge2tb output): packed superdiagonals for the
# Jordan-Wielandt SVD stage (ge2tbGather analogue).
# ---------------------------------------------------------------------------


def _upper_band_extract(stacked: jnp.ndarray, nb: int) -> jnp.ndarray:
    """stacked: (nt, nb, 2nb) [diag | right] tile pairs.  Returns
    (nt, nb+1, nb) E with E[j, t, a] = B[j nb + a, j nb + a + t]."""
    colidx = jnp.asarray(_band_rowidx(nb))  # (nb+1, nb): t + a
    return stacked[:, jnp.arange(nb)[None, :], colidx]


def upper_band_diagonals_tiles(
    T: jnp.ndarray, layout: TileLayout, n: int
) -> jnp.ndarray:
    """Extract the nb+1 stored superdiagonals of an upper-triangular
    band matrix (kd = nb) from its tile array: returns (nb+1, n) D with
    D[t, i] = B[i, i+t] (zero where i+t >= n) — O(n kd) data."""
    nb = layout.nb
    assert layout.mb == nb, "band storage requires square tiles"
    nt = layout.nt
    js = np.arange(nt)
    row_sc = np.asarray(layout.row_scatter)
    col_sc = np.asarray(layout.col_scatter)
    diag = T[row_sc[js], col_sc[js]]
    jr = np.minimum(js + 1, layout.Q - 1)
    right = T[row_sc[js], col_sc[jr]]
    right = jnp.where((js < nt - 1)[:, None, None], right, 0)
    stacked = jnp.concatenate([diag, right], axis=2)  # (nt, nb, 2nb)
    E = _upper_band_extract(stacked, nb)
    Dg = E.transpose(1, 0, 2).reshape(nb + 1, nt * nb)[:, :n]
    # zero entries running past column n
    t = jnp.arange(nb + 1)[:, None]
    i = jnp.arange(n)[None, :]
    return jnp.where(i + t < n, Dg, 0)


def spmd_upper_band_diagonals(
    grid: ProcessGrid, T: jnp.ndarray, layout: TileLayout, n: int
) -> jnp.ndarray:
    """shard_map ge2tbGather: O(n kd) psum of the packed superdiagonals."""
    p, q = grid.p, grid.q
    nb = layout.nb
    assert layout.mb == nb, "band storage requires square tiles"
    nt = layout.nt
    mtl, ntl = layout.mtl, layout.ntl
    js = jnp.arange(nt)

    def local(tl):
        r = lax.axis_index(ROW_AXIS)
        c = lax.axis_index(COL_AXIS)
        own_d = (js % p == r) & (js % q == c)
        D = tl[jnp.clip(js // p, 0, mtl - 1), jnp.clip(js // q, 0, ntl - 1)]
        D = jnp.where(own_d[:, None, None], D, 0)
        j1 = js + 1
        own_r = (js % p == r) & (j1 % q == c) & (j1 < layout.nt)
        R = tl[jnp.clip(js // p, 0, mtl - 1), jnp.clip(j1 // q, 0, ntl - 1)]
        R = jnp.where(own_r[:, None, None], R, 0)
        stacked = jnp.concatenate([D, R], axis=2)
        E = _upper_band_extract(stacked, nb)
        E = lax.psum(lax.psum(E, COL_AXIS), ROW_AXIS)
        Dg = E.transpose(1, 0, 2).reshape(nb + 1, nt * nb)[:, :n]
        t = jnp.arange(nb + 1)[:, None]
        i = jnp.arange(n)[None, :]
        return jnp.where(i + t < n, Dg, 0)

    fn = shard_map(
        local,
        mesh=grid.mesh,
        in_specs=(P(ROW_AXIS, COL_AXIS),),
        out_specs=P(),
    )
    return fn(T)
