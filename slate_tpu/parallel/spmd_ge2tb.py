"""Distributed two-stage SVD stage 1: ge2tb over the mesh.

TPU-native re-design of the reference ge2tb driver (reference:
src/ge2tb.cc — per panel k: internal::geqrf of column panel k +
compact-WY trailing update from the left, then internal::gelqf of row
panel k + trailing update from the right; SURVEY §3.5).

Mesh schedule per panel (one lax.fori_loop body, static shapes):

* the QR panel (column block k, rows k*nb..) is rebuilt everywhere by two
  all_gathers and factored redundantly; the left update
  C <- (I - V T^H V^H) C is W = V^H C (local einsum + psum over 'p')
  followed by a local rank-nb correction — the spmd_qr pattern;
* the LQ panel is the conj-transposed row block k (gathered by the dual
  pair of all_gathers over 'p' then 'q'); the right update
  C <- C (I - VL TL^H VL^H)^H is Wb = C VL (psum over 'q') + local
  correction;
* R / L^H overwrite their panel on the owner; U/V reflectors are stashed
  into distributed tile arrays for unmbr_ge2tb.

No full_global(): cross-device traffic is two panel gathers and two
rank-nb psums per step, O((m+n) nb) over ICI.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..ops.householder import geqrf as _geqrf_kernel, larft
from ..parallel.grid import COL_AXIS, ROW_AXIS, ProcessGrid
from ..parallel.layout import TileLayout
from .spmd_blas import shard_map

from ..aux.metrics import instrumented


def _resize_rows(x: jnp.ndarray, rows: int) -> jnp.ndarray:
    if x.shape[0] == rows:
        return x
    if x.shape[0] > rows:
        return x[:rows]
    return jnp.pad(x, ((0, rows - x.shape[0]), (0, 0)))


@instrumented("spmd.ge2tb")
def spmd_ge2tb(
    grid: ProcessGrid, T: jnp.ndarray, layout: TileLayout, v_layout: TileLayout
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Reduce general storage tiles to upper-triangular band (kd = nb).

    T: (P, Q, mb, nb) storage tiles with mb == nb.  v_layout is the
    (n, n) layout of the right-reflector array.  Returns
    (band_tiles, UV_tiles, UT, VV_tiles, VT): the band lives in the
    diagonal + first superdiagonal tile blocks; UV stores panel k's left
    reflectors in tile column k (rows k..), VV the right reflectors in
    tile column k (rows k+1..); UT/VT are (kt, nb, nb) replicated.
    """
    p, q = grid.p, grid.q
    mb = layout.mb
    assert mb == layout.nb, "ge2tb requires square tiles"
    m, n = layout.m, layout.n
    kt = min(layout.mt, layout.nt)
    mtl, ntl = layout.mtl, layout.ntl
    m_pad = layout.P * mb
    n_pad = layout.Q * mb
    mtl_v, ntl_v = v_layout.mtl, v_layout.ntl
    v_pad = v_layout.P * mb
    row_scatter = jnp.asarray(layout.row_scatter)
    row_gather = jnp.asarray(layout.row_gather)
    col_scatter = jnp.asarray(layout.col_scatter)
    col_gather = jnp.asarray(layout.col_gather)
    v_row_gather = jnp.asarray(v_layout.row_gather)
    complex_t = jnp.issubdtype(T.dtype, jnp.complexfloating)

    def conj(x):
        return jnp.conj(x) if complex_t else x

    def local(tl):
        r = lax.axis_index(ROW_AXIS)
        c = lax.axis_index(COL_AXIS)
        gi = jnp.arange(mtl) * p + r
        gj = jnp.arange(ntl) * q + c
        gvi = jnp.arange(mtl_v) * p + r
        er = gi[:, None] * mb + jnp.arange(mb)[None, :]  # (mtl, mb)
        ec = gj[:, None] * mb + jnp.arange(mb)[None, :]  # (ntl, mb)
        g_rowsM = jnp.arange(m_pad, dtype=jnp.int32)
        g_rowsN = jnp.arange(n_pad, dtype=jnp.int32)
        pcols = jnp.arange(mb)

        def step(k, carry):
            tl, UV, VV, UT, VT = carry
            lo = k * mb
            co = (k + 1) * mb

            # ===== left QR panel: column block k, rows lo.. =============
            pan_loc = lax.dynamic_slice_in_dim(tl, k // q, 1, axis=1)[:, 0]
            pan_q = lax.all_gather(pan_loc, COL_AXIS)
            pan_rows = lax.dynamic_index_in_dim(pan_q, k % q, 0, keepdims=False)
            pan_full = lax.all_gather(pan_rows, ROW_AXIS).reshape(p * mtl, mb, mb)
            panel2d = pan_full[row_scatter].reshape(m_pad, mb)
            hM = m - lo
            pact = jnp.roll(panel2d, -lo, axis=0)
            pact = jnp.where((g_rowsM < hM)[:, None], pact, 0)
            pact = jnp.where((pcols < (n - lo))[None, :], pact, 0)
            vr, taus = _geqrf_kernel(pact)
            rows_ = g_rowsM[:, None]
            V_act = jnp.where(rows_ > pcols[None, :], vr, 0) + jnp.where(
                rows_ == pcols[None, :], jnp.ones_like(vr), 0
            )
            V_act = jnp.where((g_rowsM < hM)[:, None], V_act, 0)
            V_act = jnp.where((pcols < (n - lo))[None, :], V_act, 0)
            Tk = larft(V_act, taus)
            UT = lax.dynamic_update_index_in_dim(UT, Tk.astype(UT.dtype), k, 0)

            # write [R; 0] back on the owner column (tile rows >= k)
            R2d = jnp.roll(
                jnp.where((g_rowsM < hM)[:, None], jnp.triu(vr), 0), lo, axis=0
            )
            fac_st = R2d.reshape(layout.P, mb, mb)[row_gather]
            mine = lax.dynamic_slice_in_dim(fac_st, r * mtl, mtl, axis=0)
            cur_col = lax.dynamic_slice_in_dim(tl, k // q, 1, axis=1)[:, 0]
            sel = ((gi >= k)[:, None, None]) & (c == k % q)
            tl = lax.dynamic_update_slice_in_dim(
                tl, jnp.where(sel, mine, cur_col)[:, None], k // q, axis=1
            )

            # left trailing update on columns >= co
            V2d = jnp.roll(V_act, lo, axis=0)
            V_nat = V2d.reshape(layout.P, mb, mb)
            V_rows = V_nat[gi]
            cmask = ((ec >= co) & (ec < n))[None, :, None, :]
            Cm = jnp.where(cmask, tl, 0)
            W = jnp.einsum("iav,ijab->vjb", conj(V_rows), Cm)
            W = lax.psum(W, ROW_AXIS)
            upd = jnp.einsum("iav,vw,wjb->ijab", V_rows, conj(Tk).T, W)
            tl = tl - jnp.where(cmask, upd, 0)

            # stash U reflectors (UV tile column k, rows >= k)
            V_st = V_nat[row_gather]
            vmine = lax.dynamic_slice_in_dim(V_st, r * mtl, mtl, axis=0)
            cur_uv = lax.dynamic_slice_in_dim(UV, k // q, 1, axis=1)[:, 0]
            UV = lax.dynamic_update_slice_in_dim(
                UV, jnp.where(sel, vmine, cur_uv)[:, None], k // q, axis=1
            )

            # ===== right LQ panel: row block k, columns co.. ============
            row_loc = lax.dynamic_slice_in_dim(tl, k // p, 1, axis=0)[0]
            row_p = lax.all_gather(row_loc, ROW_AXIS)
            row_cols = lax.dynamic_index_in_dim(row_p, k % p, 0, keepdims=False)
            row_full = lax.all_gather(row_cols, COL_AXIS).reshape(q * ntl, mb, mb)
            row2d = (
                row_full[col_scatter].transpose(1, 0, 2).reshape(mb, n_pad)
            )
            P2 = conj(row2d).T  # (n_pad, mb): rows are global columns
            hN = n - co
            P2 = jnp.roll(P2, -co, axis=0)
            P2 = jnp.where((g_rowsN < hN)[:, None], P2, 0)
            P2 = jnp.where((pcols < (m - lo))[None, :], P2, 0)
            vrL, tausL = _geqrf_kernel(P2)
            rowsN_ = g_rowsN[:, None]
            VL_act = jnp.where(rowsN_ > pcols[None, :], vrL, 0) + jnp.where(
                rowsN_ == pcols[None, :], jnp.ones_like(vrL), 0
            )
            VL_act = jnp.where((g_rowsN < hN)[:, None], VL_act, 0)
            VL_act = jnp.where((pcols < (m - lo))[None, :], VL_act, 0)
            TkL = larft(VL_act, tausL)
            VT = lax.dynamic_update_index_in_dim(VT, TkL.astype(VT.dtype), k, 0)

            # write L^H = conj(triu(vrL))^T back on the owner row
            # (tile cols >= k+1)
            RL2d = jnp.roll(
                jnp.where((g_rowsN < hN)[:, None], jnp.triu(vrL), 0), co, axis=0
            )
            RL_tiles = conj(jnp.swapaxes(RL2d.reshape(layout.Q, mb, mb), 1, 2))
            RL_st = RL_tiles[col_gather]
            rmine = lax.dynamic_slice_in_dim(RL_st, c * ntl, ntl, axis=0)
            cur_row = lax.dynamic_slice_in_dim(tl, k // p, 1, axis=0)[0]
            rsel = ((gj > k)[:, None, None]) & (r == k % p)
            tl = lax.dynamic_update_slice_in_dim(
                tl, jnp.where(rsel, rmine, cur_row)[None], k // p, axis=0
            )

            # right trailing update on rows >= co
            VL2d = jnp.roll(VL_act, co, axis=0)
            VL_nat = VL2d.reshape(layout.Q, mb, mb)
            VL_cols = VL_nat[gj]
            rmask = ((er >= co) & (er < m))[:, None, :, None]
            Cb = jnp.where(rmask, tl, 0)
            Wb = jnp.einsum("ijab,jbv->iav", Cb, VL_cols)
            Wb = lax.psum(Wb, COL_AXIS)
            updR = jnp.einsum("iav,vw,jbw->ijab", Wb, TkL, conj(VL_cols))
            tl = tl - jnp.where(rmask, updR, 0)

            # stash V reflectors (VV tile column k, rows >= k+1) in the
            # (n, n) v_layout
            VL2d_v = _resize_rows(VL2d, v_pad)
            VL_stv = VL2d_v.reshape(v_layout.P, mb, mb)[v_row_gather]
            vvmine = lax.dynamic_slice_in_dim(VL_stv, r * mtl_v, mtl_v, axis=0)
            cur_vv = lax.dynamic_slice_in_dim(VV, k // q, 1, axis=1)[:, 0]
            vsel = ((gvi > k)[:, None, None]) & (c == k % q)
            VV = lax.dynamic_update_slice_in_dim(
                VV, jnp.where(vsel, vvmine, cur_vv)[:, None], k // q, axis=1
            )
            return tl, UV, VV, UT, VT

        UV0 = jnp.zeros_like(tl)
        VV0 = jnp.zeros((mtl_v, ntl_v, mb, mb), tl.dtype)
        UT0 = jnp.zeros((kt, mb, mb), tl.dtype)
        VT0 = jnp.zeros((kt, mb, mb), tl.dtype)
        tl, UV, VV, UT, VT = lax.fori_loop(
            0, kt, step, (tl, UV0, VV0, UT0, VT0)
        )
        return tl, UV, UT, VV, VT

    spec = P(ROW_AXIS, COL_AXIS)
    fn = shard_map(
        local,
        mesh=grid.mesh,
        in_specs=(spec,),
        out_specs=(spec, spec, P(), spec, P()),
    )
    return fn(T)


@instrumented("spmd.unmbr_ge2tb_left")
def spmd_unmbr_ge2tb_left(
    grid: ProcessGrid,
    UV_tiles: jnp.ndarray,
    UT: jnp.ndarray,
    C_tiles: jnp.ndarray,
    v_layout: TileLayout,
    c_layout: TileLayout,
) -> jnp.ndarray:
    """C <- Q_U C with Q_U = H_0 ... H_{kt-1} from spmd_ge2tb (reference:
    src/unmbr_ge2tb.cc, left side): panels applied in descending order,
    each via panel-gather + distributed compact-WY apply."""
    p, q = grid.p, grid.q
    mb = v_layout.mb
    kt = UT.shape[0]
    mtl = v_layout.mtl
    m_pad = v_layout.P * mb
    n = v_layout.m
    row_scatter = jnp.asarray(v_layout.row_scatter)
    complex_t = jnp.issubdtype(C_tiles.dtype, jnp.complexfloating)

    def conj(x):
        return jnp.conj(x) if complex_t else x

    def local(vt, Ts, ct):
        r = lax.axis_index(ROW_AXIS)
        c = lax.axis_index(COL_AXIS)
        gi = jnp.arange(mtl) * p + r
        g_rows = jnp.arange(m_pad, dtype=jnp.int32)

        def step(i, ct):
            k = kt - 1 - i
            lo = k * mb
            pan_loc = lax.dynamic_slice_in_dim(vt, k // q, 1, axis=1)[:, 0]
            pan_q = lax.all_gather(pan_loc, COL_AXIS)
            pan_rows = lax.dynamic_index_in_dim(pan_q, k % q, 0, keepdims=False)
            pan_full = lax.all_gather(pan_rows, ROW_AXIS).reshape(p * mtl, mb, mb)
            V2d = pan_full[row_scatter].reshape(m_pad, mb)
            V2d = jnp.where(
                (g_rows >= lo)[:, None] & (g_rows < v_layout.m)[:, None], V2d, 0
            )
            V_rows = V2d.reshape(v_layout.P, mb, mb)[gi]
            Tk = lax.dynamic_index_in_dim(Ts, k, 0, keepdims=False)
            W = jnp.einsum("iav,ijab->vjb", conj(V_rows), ct)
            W = lax.psum(W, ROW_AXIS)
            upd = jnp.einsum("iav,vw,wjb->ijab", V_rows, Tk, W)
            return ct - upd

        return lax.fori_loop(0, kt, step, ct)

    spec = P(ROW_AXIS, COL_AXIS)
    fn = shard_map(
        local, mesh=grid.mesh, in_specs=(spec, P(), spec), out_specs=spec
    )
    return fn(UV_tiles, UT, C_tiles)


@instrumented("spmd.unmbr_ge2tb_right")
def spmd_unmbr_ge2tb_right(
    grid: ProcessGrid,
    VV_tiles: jnp.ndarray,
    VT: jnp.ndarray,
    C_tiles: jnp.ndarray,
    v_layout: TileLayout,
    c_layout: TileLayout,
) -> jnp.ndarray:
    """C <- C Q_V^H with Q_V from spmd_ge2tb's right reflectors: per panel
    k (descending) C <- C (I - V_k T_k^H V_k^H), the dual of the left
    apply with the contraction over the column axis."""
    p, q = grid.p, grid.q
    mb = v_layout.mb
    kt = VT.shape[0]
    mtl_v = v_layout.mtl
    ntl_c = c_layout.ntl
    v_pad = v_layout.P * mb
    nc_pad = c_layout.Q * mb
    n = v_layout.m
    row_scatter = jnp.asarray(v_layout.row_scatter)
    complex_t = jnp.issubdtype(C_tiles.dtype, jnp.complexfloating)

    def conj(x):
        return jnp.conj(x) if complex_t else x

    def local(vt, Ts, ct):
        r = lax.axis_index(ROW_AXIS)
        c = lax.axis_index(COL_AXIS)
        gj = jnp.arange(ntl_c) * q + c
        g_rows = jnp.arange(v_pad, dtype=jnp.int32)

        def step(i, ct):
            k = kt - 1 - i
            co = (k + 1) * mb
            pan_loc = lax.dynamic_slice_in_dim(vt, k // q, 1, axis=1)[:, 0]
            pan_q = lax.all_gather(pan_loc, COL_AXIS)
            pan_rows = lax.dynamic_index_in_dim(pan_q, k % q, 0, keepdims=False)
            pan_full = lax.all_gather(pan_rows, ROW_AXIS).reshape(
                p * mtl_v, mb, mb
            )
            V2d = pan_full[row_scatter].reshape(v_pad, mb)
            V2d = jnp.where(
                (g_rows >= co)[:, None] & (g_rows < n)[:, None], V2d, 0
            )
            V2d_c = _resize_rows(V2d, nc_pad)
            VL_cols = V2d_c.reshape(c_layout.Q, mb, mb)[gj]
            Tk = lax.dynamic_index_in_dim(Ts, k, 0, keepdims=False)
            Wb = jnp.einsum("ijab,jbv->iav", ct, VL_cols)
            Wb = lax.psum(Wb, COL_AXIS)
            upd = jnp.einsum("iav,vw,jbw->ijab", Wb, conj(Tk).T, conj(VL_cols))
            return ct - upd

        return lax.fori_loop(0, kt, step, ct)

    spec = P(ROW_AXIS, COL_AXIS)
    fn = shard_map(
        local, mesh=grid.mesh, in_specs=(spec, P(), spec), out_specs=spec
    )
    return fn(VV_tiles, VT, C_tiles)
