"""Norm-based residual checks — the tester's acceptance criteria.

Reproduces the reference tester's norm-scaled residual bounds (reference:
test/test_gemm.cc:192-207: ||C - C_ref|| / (sqrt(k) |alpha| ||A|| ||B|| +
2 |beta| ||C0||) <= 3 eps; analogous scalings per routine in
test/test_*.cc).  All checks are computed in the working precision's
epsilon, in f64 arithmetic for the norms themselves.
"""

from __future__ import annotations

import numpy as np


def eps_of(dtype) -> float:
    dt = np.dtype(dtype)
    if dt.kind == "c":
        dt = np.dtype("f4") if dt == np.complex64 else np.dtype("f8")
    return float(np.finfo(dt).eps)


def _norm1(X) -> float:
    X = np.asarray(X)
    if X.ndim == 1:
        return float(np.abs(X).sum())
    return float(np.abs(X).sum(axis=0).max())


def gemm_residual(C_test, C_ref, alpha, A, B, beta, C0) -> float:
    """Scaled gemm residual (test_gemm.cc:192-207)."""
    k = np.asarray(A).shape[1]
    denom = (
        np.sqrt(float(k)) * abs(alpha) * _norm1(A) * _norm1(B)
        + 2 * abs(beta) * _norm1(C0)
    )
    denom = max(denom, np.finfo(np.float64).tiny)
    return _norm1(np.asarray(C_test) - np.asarray(C_ref)) / denom


def solve_residual(A, X, B) -> float:
    """||B - A X|| / (||A|| ||X|| n) — the standard backward-error check
    used by the factorization testers (test_gesv.cc, test_posv.cc)."""
    A, X, B = map(np.asarray, (A, X, B))
    n = A.shape[1]
    R = B - A @ X
    denom = max(_norm1(A) * _norm1(X) * n, np.finfo(np.float64).tiny)
    return _norm1(R) / denom


def factor_residual(A, L, U=None, P=None) -> float:
    """||A - P L U|| / (||A|| n) for LU; ||A - L L^H|| / (||A|| n) for
    Cholesky when U is None (test_getrf/test_potrf semantics)."""
    A, L = np.asarray(A), np.asarray(L)
    n = A.shape[0]
    if U is None:
        Rec = L @ np.conj(L.T)
    else:
        Rec = L @ np.asarray(U)
        if P is not None:
            Rec = np.asarray(P) @ Rec
    denom = max(_norm1(A) * n, np.finfo(np.float64).tiny)
    return _norm1(A - Rec) / denom


def ortho_residual(Q) -> float:
    """||Q^H Q - I|| / n — orthogonality check (test_geqrf.cc)."""
    Q = np.asarray(Q)
    n = Q.shape[1]
    I = np.eye(n, dtype=Q.dtype)
    return _norm1(np.conj(Q.T) @ Q - I) / n


def passed(error: float, dtype, factor: float = 3.0) -> bool:
    """Acceptance: error <= factor * eps (test_gemm.cc:207)."""
    return bool(error <= factor * eps_of(dtype))
