"""Parameter-sweep tester (reference: test/test.cc + testsweeper dispatch
table test.cc:117-260, per-routine testers test/test_*.cc, sweep runner
test/run_tests.py with JUnit XML output).

CLI:
    python -m slate_tpu.testing.tester --dim 64:128 --type s,d --nb 16 \
        --grid 2x2 --xml out.xml gemm posv gesv

Each routine test generates inputs with the Philox matgen, runs the
driver, and accepts on the reference's norm-scaled residual bound
(error <= tol_factor * eps; test_gemm.cc:192-207).  Timing is wall-clock
around the blocked driver call (first call includes compile, a repeat
measures steady state).

--metrics (or SLATE_TPU_METRICS=/path/out.jsonl) turns on the
observability layer: each sweep entry runs inside
metrics.context(label) and prints its per-entry compilation/fallback/
precision-activation deltas, with the full metrics.report() table (and
the JSONL dump when the env var is set) after the sweep.
"""

from __future__ import annotations

import argparse
import sys
import time
import xml.etree.ElementTree as ET
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np

_TYPES = {"s": np.float32, "d": np.float64, "c": np.complex64, "z": np.complex128}
_EPS_FACTOR = {"default": 50.0}


@dataclass
class Params:
    m: int
    n: int
    k: int
    nb: int
    dtype: type
    type_char: str
    p: int = 1
    q: int = 1
    seed: int = 42
    check: bool = True
    uplo: str = "lower"
    grid=None


@dataclass
class Result:
    routine: str
    params: str
    seconds: float
    gflops: float
    error: float
    passed: bool
    message: str = ""


def _rng_matrix(kind, m, n, dtype, seed):
    from ..matgen.generate import generate_2d

    A, _ = generate_2d(kind, m, n, dtype, seed=seed)
    return np.asarray(A)


def _eps(dtype):
    from .checks import eps_of

    return eps_of(dtype)


def _grid(pr: Params):
    if pr.p * pr.q == 1:
        return None
    import jax

    from ..parallel.grid import ProcessGrid

    devs = jax.devices()
    if len(devs) < pr.p * pr.q:
        raise RuntimeError(f"grid {pr.p}x{pr.q} needs {pr.p*pr.q} devices")
    return ProcessGrid.from_devices(devs[: pr.p * pr.q], p=pr.p, q=pr.q)


# ---------------------------------------------------------------------------
# routine testers — each returns (seconds, gflop, error)
# ---------------------------------------------------------------------------


def _test_gemm(pr: Params):
    import slate_tpu as st
    from .checks import gemm_residual

    g = _grid(pr)
    A0 = _rng_matrix("rand", pr.m, pr.k, pr.dtype, pr.seed)
    B0 = _rng_matrix("rand", pr.k, pr.n, pr.dtype, pr.seed + 1)
    C0 = _rng_matrix("rand", pr.m, pr.n, pr.dtype, pr.seed + 2)
    A = st.Matrix.from_global(A0, pr.nb, grid=g)
    B = st.Matrix.from_global(B0, pr.nb, grid=g)
    C = st.Matrix.from_global(C0, pr.nb, grid=g)
    t0 = time.perf_counter()
    C2 = st.gemm(2.0, A, B, -1.0, C)
    got = np.asarray(C2.to_global())
    dt = time.perf_counter() - t0
    err = gemm_residual(got, 2.0 * A0 @ B0 - C0, 2.0, A0, B0, -1.0, C0)
    return dt, 2e-9 * pr.m * pr.n * pr.k / dt, err


def _test_posv(pr: Params):
    import slate_tpu as st
    from .checks import solve_residual

    g = _grid(pr)
    n = pr.n
    A0 = _rng_matrix("rand_dominant", n, n, pr.dtype, pr.seed)
    A0 = ((A0 + A0.conj().T) / 2 + n * np.eye(n)).astype(pr.dtype)
    B0 = _rng_matrix("rand", n, max(pr.k, 1), pr.dtype, pr.seed + 1)
    A = st.HermitianMatrix.from_global(A0, pr.nb, grid=g, uplo=st.Uplo.Lower)
    B = st.Matrix.from_global(B0, pr.nb, grid=g)
    t0 = time.perf_counter()
    X, L, info = st.posv(A, B)
    got = np.asarray(X.to_global())
    dt = time.perf_counter() - t0
    if int(info) != 0:
        return dt, 0.0, float("inf")
    return dt, 1e-9 * n**3 / 3 / dt, solve_residual(A0, got, B0)


def _test_potrf(pr: Params):
    import slate_tpu as st
    from .checks import factor_residual

    g = _grid(pr)
    n = pr.n
    A0 = _rng_matrix("rand", n, n, pr.dtype, pr.seed)
    A0 = A0 @ A0.conj().T + n * np.eye(n)
    A0 = A0.astype(pr.dtype)
    A = st.HermitianMatrix.from_global(A0, pr.nb, grid=g, uplo=st.Uplo.Lower)
    t0 = time.perf_counter()
    L, info = st.potrf(A)
    Lg = np.tril(np.asarray(L.to_global()))
    dt = time.perf_counter() - t0
    if int(info) != 0:
        return dt, 0.0, float("inf")
    return dt, 1e-9 * n**3 / 3 / dt, factor_residual(A0, Lg)


def _test_gesv(pr: Params):
    import slate_tpu as st
    from .checks import solve_residual

    g = _grid(pr)
    n = pr.n
    A0 = _rng_matrix("rand", n, n, pr.dtype, pr.seed)
    B0 = _rng_matrix("rand", n, max(pr.k, 1), pr.dtype, pr.seed + 1)
    A = st.Matrix.from_global(A0, pr.nb, grid=g)
    B = st.Matrix.from_global(B0, pr.nb, grid=g)
    t0 = time.perf_counter()
    X, LU, piv, info = st.gesv(A, B)
    got = np.asarray(X.to_global())
    dt = time.perf_counter() - t0
    if int(info) != 0:
        return dt, 0.0, float("inf")
    return dt, 2e-9 * n**3 / 3 / dt, solve_residual(A0, got, B0)


def _test_geqrf(pr: Params):
    import slate_tpu as st
    from .checks import factor_residual, ortho_residual

    g = _grid(pr)
    m, n = pr.m, pr.n
    A0 = _rng_matrix("rand", m, n, pr.dtype, pr.seed)
    A = st.Matrix.from_global(A0, pr.nb, grid=g)
    t0 = time.perf_counter()
    fac, T = st.geqrf(A)
    Q = np.asarray(st.ungqr(fac, T).to_global())
    dt = time.perf_counter() - t0
    R = np.triu(np.asarray(fac.to_global()))[: min(m, n), :]
    err = max(factor_residual(A0, Q, R), ortho_residual(Q))
    return dt, 2e-9 * m * n * n / dt, err


def _test_gels(pr: Params):
    import slate_tpu as st

    g = _grid(pr)
    m, n = max(pr.m, pr.n), min(pr.m, pr.n)
    A0 = _rng_matrix("rand", m, n, pr.dtype, pr.seed)
    B0 = _rng_matrix("rand", m, max(pr.k, 1), pr.dtype, pr.seed + 1)
    A = st.Matrix.from_global(A0, pr.nb, grid=g)
    B = st.Matrix.from_global(B0, pr.nb, grid=g)
    t0 = time.perf_counter()
    X = st.gels(A, B)
    got = np.asarray(X.to_global())[:n]
    dt = time.perf_counter() - t0
    ref, *_ = np.linalg.lstsq(A0, B0, rcond=None)
    scale = max(np.abs(ref).max(), 1.0)
    err = np.abs(got - ref).max() / scale / max(m, 1)
    return dt, 2e-9 * m * n * n / dt, err


def _test_heev(pr: Params):
    import slate_tpu as st

    g = _grid(pr)
    n = pr.n
    A0 = _rng_matrix("rand", n, n, pr.dtype, pr.seed)
    A0 = ((A0 + A0.conj().T) / 2).astype(pr.dtype)
    A = st.HermitianMatrix.from_global(A0, pr.nb, grid=g, uplo=st.Uplo.Lower)
    t0 = time.perf_counter()
    w, Z = st.heev(A)
    dt = time.perf_counter() - t0
    ref = np.linalg.eigvalsh(A0)
    err = np.abs(np.asarray(w) - ref).max() / max(np.abs(ref).max(), 1.0) / n
    if Z is not None:
        Zg = np.asarray(Z.to_global())
        res = np.abs(A0 @ Zg - Zg * np.asarray(w)[None, :]).max()
        err = max(err, res / max(np.abs(ref).max(), 1.0) / n)
    return dt, 4e-9 * n**3 / 3 / dt, err


def _test_svd(pr: Params):
    import slate_tpu as st

    g = _grid(pr)
    m, n = pr.m, pr.n
    A0 = _rng_matrix("rand", m, n, pr.dtype, pr.seed)
    A = st.Matrix.from_global(A0, pr.nb, grid=g)
    t0 = time.perf_counter()
    s, _, _ = st.svd(A)
    dt = time.perf_counter() - t0
    ref = np.linalg.svd(A0, compute_uv=False)
    err = np.abs(np.asarray(s) - ref).max() / max(ref.max(), 1.0) / max(m, n)
    return dt, 4e-9 * m * n * min(m, n) / dt, err


def _test_norm(pr: Params):
    import slate_tpu as st

    g = _grid(pr)
    A0 = _rng_matrix("rand", pr.m, pr.n, pr.dtype, pr.seed)
    A = st.Matrix.from_global(A0, pr.nb, grid=g)
    t0 = time.perf_counter()
    errs = []
    for nt, ref in (
        (st.Norm.Max, np.abs(A0).max()),
        (st.Norm.One, np.abs(A0).sum(axis=0).max()),
        (st.Norm.Inf, np.abs(A0).sum(axis=1).max()),
        (st.Norm.Fro, np.linalg.norm(A0, "fro")),
    ):
        got = float(st.norm(nt, A))
        errs.append(abs(got - ref) / max(ref, 1e-300))
    dt = time.perf_counter() - t0
    return dt, 1e-9 * pr.m * pr.n / dt, max(errs)


def _test_trsm(pr: Params):
    import slate_tpu as st
    from .checks import solve_residual

    g = _grid(pr)
    n, m = pr.n, max(pr.k, 1)
    T0 = np.tril(_rng_matrix("rand", n, n, pr.dtype, pr.seed)) + n * np.eye(n)
    T0 = T0.astype(pr.dtype)
    B0 = _rng_matrix("rand", n, m, pr.dtype, pr.seed + 1)
    T = st.TriangularMatrix.from_global(T0, pr.nb, grid=g, uplo=st.Uplo.Lower)
    B = st.Matrix.from_global(B0, pr.nb, grid=g)
    t0 = time.perf_counter()
    X = st.trsm(st.Side.Left, 1.0, T, B)
    got = np.asarray(X.to_global())
    dt = time.perf_counter() - t0
    return dt, 1e-9 * n * n * m / dt, solve_residual(T0, got, B0)


def _simple(fn):
    return fn




def _spd_np(pr, n, shift=None):
    A0 = _rng_matrix("rand_dominant", n, n, pr.dtype, pr.seed)
    A0 = ((A0 + A0.conj().T) / 2 + (shift or n) * np.eye(n)).astype(pr.dtype)
    return A0


def _test_symm(pr: Params):
    import slate_tpu as st

    g = _grid(pr)
    n = pr.n
    A0 = _rng_matrix("rand", n, n, pr.dtype, pr.seed)
    A0 = ((A0 + A0.T) / 2).astype(pr.dtype)
    B0 = _rng_matrix("rand", n, pr.k, pr.dtype, pr.seed + 1)
    C0 = _rng_matrix("rand", n, pr.k, pr.dtype, pr.seed + 2)
    A = st.SymmetricMatrix.from_global(A0, pr.nb, grid=g, uplo=st.Uplo.Lower)
    B = st.Matrix.from_global(B0, pr.nb, grid=g)
    C = st.Matrix.from_global(C0, pr.nb, grid=g)
    t0 = time.perf_counter()
    out = st.symm(st.Side.Left, 1.5, A, B, -0.5, C)
    got = np.asarray(out.to_global())
    dt = time.perf_counter() - t0
    ref = 1.5 * A0 @ B0 - 0.5 * C0
    scale = max(np.abs(ref).max(), 1.0)
    return dt, 2e-9 * n * n * pr.k / dt, np.abs(got - ref).max() / scale / n


def _test_hemm(pr: Params):
    import slate_tpu as st

    g = _grid(pr)
    n = pr.n
    A0 = _rng_matrix("rand", n, n, pr.dtype, pr.seed)
    A0 = ((A0 + A0.conj().T) / 2).astype(pr.dtype)
    B0 = _rng_matrix("rand", n, pr.k, pr.dtype, pr.seed + 1)
    A = st.HermitianMatrix.from_global(A0, pr.nb, grid=g, uplo=st.Uplo.Lower)
    B = st.Matrix.from_global(B0, pr.nb, grid=g)
    C = st.Matrix.from_global(np.zeros_like(B0), pr.nb, grid=g)
    t0 = time.perf_counter()
    out = st.hemm(st.Side.Left, 1.0, A, B, 0.0, C)
    got = np.asarray(out.to_global())
    dt = time.perf_counter() - t0
    ref = A0 @ B0
    return dt, 2e-9 * n * n * pr.k / dt, np.abs(got - ref).max() / max(np.abs(ref).max(), 1.0) / n


def _test_herk(pr: Params):
    import slate_tpu as st

    g = _grid(pr)
    n, k = pr.n, pr.k
    A0 = _rng_matrix("rand", n, k, pr.dtype, pr.seed)
    C0 = _spd_np(pr, n, shift=1)
    A = st.Matrix.from_global(A0, pr.nb, grid=g)
    C = st.HermitianMatrix.from_global(C0, pr.nb, grid=g, uplo=st.Uplo.Lower)
    t0 = time.perf_counter()
    out = st.herk(1.0, A, 0.5, C)
    got = np.asarray(out.full_global())
    dt = time.perf_counter() - t0
    ref = A0 @ A0.conj().T + 0.5 * C0
    return dt, 1e-9 * n * n * k / dt, np.abs(got - ref).max() / max(np.abs(ref).max(), 1.0) / n


def _test_syrk(pr: Params):
    import slate_tpu as st

    g = _grid(pr)
    n, k = pr.n, pr.k
    A0 = _rng_matrix("rand", n, k, pr.dtype, pr.seed)
    M = _rng_matrix("rand", n, n, pr.dtype, pr.seed + 1)
    C0 = ((M + M.T) / 2).astype(pr.dtype)
    A = st.Matrix.from_global(A0, pr.nb, grid=g)
    C = st.SymmetricMatrix.from_global(C0, pr.nb, grid=g, uplo=st.Uplo.Lower)
    t0 = time.perf_counter()
    out = st.syrk(1.0, A, 1.0, C)
    got = np.asarray(out.full_global())
    dt = time.perf_counter() - t0
    ref = A0 @ A0.T + C0
    return dt, 1e-9 * n * n * k / dt, np.abs(got - ref).max() / max(np.abs(ref).max(), 1.0) / n


def _test_her2k(pr: Params):
    import slate_tpu as st

    g = _grid(pr)
    n, k = pr.n, pr.k
    A0 = _rng_matrix("rand", n, k, pr.dtype, pr.seed)
    B0 = _rng_matrix("rand", n, k, pr.dtype, pr.seed + 1)
    C0 = _spd_np(pr, n, shift=1)
    A = st.Matrix.from_global(A0, pr.nb, grid=g)
    B = st.Matrix.from_global(B0, pr.nb, grid=g)
    C = st.HermitianMatrix.from_global(C0, pr.nb, grid=g, uplo=st.Uplo.Lower)
    t0 = time.perf_counter()
    out = st.her2k(1.0, A, B, 1.0, C)
    got = np.asarray(out.full_global())
    dt = time.perf_counter() - t0
    ref = A0 @ B0.conj().T + B0 @ A0.conj().T + C0
    return dt, 2e-9 * n * n * k / dt, np.abs(got - ref).max() / max(np.abs(ref).max(), 1.0) / n


def _test_trmm(pr: Params):
    import slate_tpu as st

    g = _grid(pr)
    n = pr.n
    T0 = (np.tril(_rng_matrix("rand", n, n, pr.dtype, pr.seed)) + n * np.eye(n)).astype(pr.dtype)
    B0 = _rng_matrix("rand", n, pr.k, pr.dtype, pr.seed + 1)
    T = st.TriangularMatrix.from_global(T0, pr.nb, grid=g, uplo=st.Uplo.Lower)
    B = st.Matrix.from_global(B0, pr.nb, grid=g)
    t0 = time.perf_counter()
    out = st.trmm(st.Side.Left, 1.0, T, B)
    got = np.asarray(out.to_global())
    dt = time.perf_counter() - t0
    ref = T0 @ B0
    return dt, 1e-9 * n * n * pr.k / dt, np.abs(got - ref).max() / max(np.abs(ref).max(), 1.0) / n


def _test_getri(pr: Params):
    import slate_tpu as st

    g = _grid(pr)
    n = pr.n
    A0 = (_rng_matrix("rand", n, n, pr.dtype, pr.seed) + n * np.eye(n)).astype(pr.dtype)
    A = st.Matrix.from_global(A0, pr.nb, grid=g)
    t0 = time.perf_counter()
    LU, piv, info = st.getrf(A)
    Ainv = st.getri(LU, piv)
    got = np.asarray(Ainv.to_global())
    dt = time.perf_counter() - t0
    if int(info) != 0:
        return dt, 0.0, float("inf")
    err = np.abs(got @ A0 - np.eye(n)).max() / n
    return dt, 2e-9 * n ** 3 / dt, err


def _test_potri(pr: Params):
    import slate_tpu as st

    g = _grid(pr)
    n = pr.n
    A0 = _spd_np(pr, n)
    A = st.HermitianMatrix.from_global(A0, pr.nb, grid=g, uplo=st.Uplo.Lower)
    t0 = time.perf_counter()
    L, info = st.potrf(A)
    Ainv = st.potri(L)
    got = np.asarray(Ainv.full_global())
    dt = time.perf_counter() - t0
    if int(info) != 0:
        return dt, 0.0, float("inf")
    err = np.abs(got @ A0 - np.eye(n)).max() / n
    return dt, 1e-9 * n ** 3 / dt, err


def _test_trtri(pr: Params):
    import slate_tpu as st

    g = _grid(pr)
    n = pr.n
    T0 = (np.tril(_rng_matrix("rand", n, n, pr.dtype, pr.seed)) + n * np.eye(n)).astype(pr.dtype)
    T = st.TriangularMatrix.from_global(T0, pr.nb, grid=g, uplo=st.Uplo.Lower)
    t0 = time.perf_counter()
    Tinv = st.trtri(T)
    got = np.tril(np.asarray(Tinv.to_global()))
    dt = time.perf_counter() - t0
    err = np.abs(got @ T0 - np.eye(n)).max() / n
    return dt, 0.33e-9 * n ** 3 / dt, err


def _test_gelqf(pr: Params):
    import slate_tpu as st

    g = _grid(pr)
    m, n = min(pr.m, pr.n), max(pr.m, pr.n)
    A0 = _rng_matrix("rand", m, n, pr.dtype, pr.seed)
    A = st.Matrix.from_global(A0, pr.nb, grid=g)
    t0 = time.perf_counter()
    fac, T = st.gelqf(A)
    Lf = np.tril(np.asarray(fac.to_global()))[:, :m]
    dt = time.perf_counter() - t0
    # L L^H must match A A^H (Q orthonormal)
    ref = A0 @ A0.conj().T
    got = Lf @ Lf.conj().T
    err = np.abs(got - ref).max() / max(np.abs(ref).max(), 1.0) / m
    return dt, 2e-9 * m * m * n / dt, err


def _test_cholqr(pr: Params):
    import slate_tpu as st
    from .checks import factor_residual, ortho_residual

    g = _grid(pr)
    m, n = max(pr.m, pr.n), min(pr.m, pr.n)
    A0 = _rng_matrix("rand", m, n, pr.dtype, pr.seed)
    A = st.Matrix.from_global(A0, pr.nb, grid=g)
    t0 = time.perf_counter()
    Q, R, info = st.cholqr(A)
    Qg = np.asarray(Q.to_global())
    Rg = np.triu(np.asarray(R.to_global()))[:n, :n]
    dt = time.perf_counter() - t0
    if int(info) != 0:
        return dt, 0.0, float("inf")
    err = max(factor_residual(A0, Qg, Rg), ortho_residual(Qg))
    return dt, 2e-9 * m * n * n / dt, err


def _test_hegv(pr: Params):
    import slate_tpu as st

    g = _grid(pr)
    n = pr.n
    A0 = _rng_matrix("rand", n, n, pr.dtype, pr.seed)
    A0 = ((A0 + A0.conj().T) / 2).astype(pr.dtype)
    B0 = _spd_np(pr, n)
    A = st.HermitianMatrix.from_global(A0, pr.nb, grid=g, uplo=st.Uplo.Lower)
    B = st.HermitianMatrix.from_global(B0, pr.nb, grid=g, uplo=st.Uplo.Lower)
    t0 = time.perf_counter()
    w, X, info = st.hegv(1, A, B, vectors=False)
    w = np.asarray(w)
    dt = time.perf_counter() - t0
    if int(info) != 0:
        return dt, 0.0, float("inf")
    L = np.linalg.cholesky(B0)
    C = np.linalg.solve(L, np.linalg.solve(L, A0.conj().T).conj().T)
    ref = np.linalg.eigvalsh((C + C.conj().T) / 2)
    return dt, 0.0, np.abs(w - ref).max() / max(np.abs(ref).max(), 1.0) / n


def _test_gesv_mixed(pr: Params):
    import slate_tpu as st
    from .checks import solve_residual

    g = _grid(pr)
    n = pr.n
    A0 = (_rng_matrix("rand", n, n, pr.dtype, pr.seed) + n * np.eye(n)).astype(pr.dtype)
    B0 = _rng_matrix("rand", n, max(pr.k, 1), pr.dtype, pr.seed + 1)
    t0 = time.perf_counter()
    X, info, iters = st.gesv_mixed(
        st.Matrix.from_global(A0, pr.nb, grid=g),
        st.Matrix.from_global(B0, pr.nb, grid=g),
    )
    got = np.asarray(X.to_global())
    dt = time.perf_counter() - t0
    return dt, 0.67e-9 * n ** 3 / dt, solve_residual(A0, got, B0)


def _test_posv_mixed(pr: Params):
    import slate_tpu as st
    from .checks import solve_residual

    g = _grid(pr)
    n = pr.n
    A0 = _spd_np(pr, n)
    B0 = _rng_matrix("rand", n, max(pr.k, 1), pr.dtype, pr.seed + 1)
    t0 = time.perf_counter()
    X, info, iters = st.posv_mixed(
        st.HermitianMatrix.from_global(A0, pr.nb, grid=g, uplo=st.Uplo.Lower),
        st.Matrix.from_global(B0, pr.nb, grid=g),
    )
    got = np.asarray(X.to_global())
    dt = time.perf_counter() - t0
    return dt, 0.33e-9 * n ** 3 / dt, solve_residual(A0, got, B0)


def _test_gesv_mixed_gmres(pr: Params):
    import slate_tpu as st
    from .checks import solve_residual

    g = _grid(pr)
    n = pr.n
    A0 = (_rng_matrix("rand", n, n, pr.dtype, pr.seed) + n * np.eye(n)).astype(pr.dtype)
    B0 = _rng_matrix("rand", n, max(pr.k, 1), pr.dtype, pr.seed + 1)
    t0 = time.perf_counter()
    X, info, iters = st.gesv_mixed_gmres(
        st.Matrix.from_global(A0, pr.nb, grid=g),
        st.Matrix.from_global(B0, pr.nb, grid=g),
    )
    got = np.asarray(X.to_global())
    dt = time.perf_counter() - t0
    return dt, 0.67e-9 * n ** 3 / dt, solve_residual(A0, got, B0)


def _test_posv_mixed_gmres(pr: Params):
    import slate_tpu as st
    from .checks import solve_residual

    g = _grid(pr)
    n = pr.n
    A0 = _spd_np(pr, n)
    B0 = _rng_matrix("rand", n, max(pr.k, 1), pr.dtype, pr.seed + 1)
    t0 = time.perf_counter()
    X, info, iters = st.posv_mixed_gmres(
        st.HermitianMatrix.from_global(A0, pr.nb, grid=g, uplo=st.Uplo.Lower),
        st.Matrix.from_global(B0, pr.nb, grid=g),
    )
    got = np.asarray(X.to_global())
    dt = time.perf_counter() - t0
    return dt, 0.33e-9 * n ** 3 / dt, solve_residual(A0, got, B0)


def _test_gesv_rbt(pr: Params):
    import slate_tpu as st
    from .checks import solve_residual
    from ..enums import MethodLU, Option

    g = _grid(pr)
    n = pr.n
    A0 = (_rng_matrix("rand", n, n, pr.dtype, pr.seed) + n * np.eye(n)).astype(pr.dtype)
    B0 = _rng_matrix("rand", n, max(pr.k, 1), pr.dtype, pr.seed + 1)
    t0 = time.perf_counter()
    X, LU, piv, info = st.gesv(
        st.Matrix.from_global(A0, pr.nb, grid=g),
        st.Matrix.from_global(B0, pr.nb, grid=g),
        {Option.MethodLU: MethodLU.RBT},
    )
    got = np.asarray(X.to_global())
    dt = time.perf_counter() - t0
    if int(info) != 0:
        return dt, 0.0, float("inf")
    return dt, 0.67e-9 * n ** 3 / dt, solve_residual(A0, got, B0)


def _test_gesv_calu(pr: Params):
    import slate_tpu as st
    from .checks import solve_residual
    from ..enums import MethodLU, Option

    g = _grid(pr)
    n = pr.n
    A0 = _rng_matrix("rand", n, n, pr.dtype, pr.seed).astype(pr.dtype)
    B0 = _rng_matrix("rand", n, max(pr.k, 1), pr.dtype, pr.seed + 1)
    t0 = time.perf_counter()
    X, LU, piv, info = st.gesv(
        st.Matrix.from_global(A0, pr.nb, grid=g),
        st.Matrix.from_global(B0, pr.nb, grid=g),
        {Option.MethodLU: MethodLU.CALU},
    )
    got = np.asarray(X.to_global())
    dt = time.perf_counter() - t0
    if int(info) != 0:
        return dt, 0.0, float("inf")
    return dt, 0.67e-9 * n ** 3 / dt, solve_residual(A0, got, B0)


def _test_hesv(pr: Params):
    import slate_tpu as st
    from .checks import solve_residual

    g = _grid(pr)
    n = pr.n
    A0 = _rng_matrix("rand", n, n, pr.dtype, pr.seed)
    A0 = ((A0 + A0.conj().T) / 2 + 0.5 * n * np.eye(n)).astype(pr.dtype)
    B0 = _rng_matrix("rand", n, max(pr.k, 1), pr.dtype, pr.seed + 1)
    t0 = time.perf_counter()
    X, fac, d_blk, info = st.hesv(
        st.HermitianMatrix.from_global(A0, pr.nb, grid=g, uplo=st.Uplo.Lower),
        st.Matrix.from_global(B0, pr.nb, grid=g),
    )
    got = np.asarray(X.to_global())
    dt = time.perf_counter() - t0
    if int(info) != 0:
        return dt, 0.0, float("inf")
    return dt, 0.33e-9 * n ** 3 / dt, solve_residual(A0, got, B0)


def _test_condest(pr: Params):
    import slate_tpu as st

    g = _grid(pr)
    n = pr.n
    A0 = (_rng_matrix("rand", n, n, pr.dtype, pr.seed) + n * np.eye(n)).astype(pr.dtype)
    A = st.Matrix.from_global(A0, pr.nb, grid=g)
    t0 = time.perf_counter()
    LU, piv, _ = st.getrf(A)
    rcond = float(st.gecondest(LU, piv, np.abs(A0).sum(axis=0).max()))
    dt = time.perf_counter() - t0
    ref = 1.0 / (np.linalg.norm(A0, 1) * np.linalg.norm(np.linalg.inv(A0), 1))
    ok = ref * 0.99 <= rcond <= 3.0 * ref
    return dt, 0.0, 0.0 if ok else float("inf")


def _test_sterf(pr: Params):
    import slate_tpu as st

    n = pr.n
    rng = np.random.default_rng(pr.seed)
    d = rng.standard_normal(n).astype(np.float64)
    e = rng.standard_normal(n - 1).astype(np.float64)
    T = np.diag(d) + np.diag(e, 1) + np.diag(e, -1)
    t0 = time.perf_counter()
    w = np.asarray(st.sterf(d, e))
    dt = time.perf_counter() - t0
    ref = np.linalg.eigvalsh(T)
    return dt, 0.0, np.abs(w - ref).max() / max(np.abs(ref).max(), 1.0) / n


def _test_steqr(pr: Params):
    import slate_tpu as st

    n = pr.n
    rng = np.random.default_rng(pr.seed)
    d = rng.standard_normal(n).astype(np.float64)
    e = rng.standard_normal(n - 1).astype(np.float64)
    T = np.diag(d) + np.diag(e, 1) + np.diag(e, -1)
    t0 = time.perf_counter()
    w, Z = st.steqr(d, e, vectors=True)
    w, Z = np.asarray(w), np.asarray(Z)
    dt = time.perf_counter() - t0
    err = np.abs(w - np.linalg.eigvalsh(T)).max() / max(np.abs(w).max(), 1.0) / n
    res = np.abs(T @ Z - Z * w[None, :]).max() / max(np.abs(w).max(), 1.0) / n
    return dt, 0.0, max(err, res)


def _test_serve(pr: Params):
    """Serving layer end-to-end: a private SolverService (so the sweep
    never perturbs the process singleton) coalescing mixed-shape
    gesv/posv traffic; error = worst scaled solve residual across the
    stream (the padded-and-cropped results must meet the same bound as
    the direct drivers)."""
    from ..serve.cache import ExecutableCache
    from ..serve.service import SolverService
    from .checks import solve_residual

    n = pr.n
    n2 = max(n // 2, 4)
    rng = np.random.default_rng(pr.seed)
    dt_ = pr.dtype if pr.dtype in (np.float32, np.float64) else np.float64
    A1 = rng.standard_normal((n, n)).astype(dt_) + n * np.eye(n, dtype=dt_)
    G = rng.standard_normal((n2, n2)).astype(dt_)
    A2 = (G @ G.T + n2 * np.eye(n2, dtype=dt_)).astype(dt_)
    B1 = rng.standard_normal((n, max(pr.k, 1))).astype(dt_)
    B2 = rng.standard_normal((n2, max(pr.k, 1))).astype(dt_)
    svc = SolverService(
        cache=ExecutableCache(manifest_path=None), batch_max=4,
        dim_floor=min(32, pr.nb * 2), start=False,
    )
    t0 = time.perf_counter()
    futs = []
    for i in range(3):
        futs.append(("gesv", A1 + i * 0.01 * np.eye(n, dtype=dt_), B1))
    futs.append(("posv", A2, B2))
    futs = [(r, A, B, svc.submit(r, A, B)) for r, A, B in futs]
    svc.start()
    try:
        worst = 0.0
        for r, A, B, f in futs:
            X = f.result(timeout=600)
            worst = max(worst, solve_residual(A, X, B))
    finally:
        svc.stop()
    dt = time.perf_counter() - t0
    return dt, 0.0, worst


ROUTINES: Dict[str, Callable[[Params], tuple]] = {
    "gemm": _test_gemm,
    "posv": _test_posv,
    "potrf": _test_potrf,
    "gesv": _test_gesv,
    "geqrf": _test_geqrf,
    "gels": _test_gels,
    "heev": _test_heev,
    "svd": _test_svd,
    "norm": _test_norm,
    "trsm": _test_trsm,
    "symm": _test_symm,
    "hemm": _test_hemm,
    "herk": _test_herk,
    "syrk": _test_syrk,
    "her2k": _test_her2k,
    "trmm": _test_trmm,
    "getri": _test_getri,
    "potri": _test_potri,
    "trtri": _test_trtri,
    "gelqf": _test_gelqf,
    "cholqr": _test_cholqr,
    "hegv": _test_hegv,
    "gesv_mixed": _test_gesv_mixed,
    "posv_mixed": _test_posv_mixed,
    "gesv_mixed_gmres": _test_gesv_mixed_gmres,
    "posv_mixed_gmres": _test_posv_mixed_gmres,
    "gesv_rbt": _test_gesv_rbt,
    "gesv_calu": _test_gesv_calu,
    "hesv": _test_hesv,
    "condest": _test_condest,
    "steqr": _test_steqr,
    "sterf": _test_sterf,
    "serve": _test_serve,
}

# Reference-style tolerance factors per routine class.  The reference
# accepts error <= 3*eps under per-routine scalings (test_gemm.cc:192-207
# and analogues); our metrics use the same scalings but looser factors
# because (a) the TPU f64 emulation's effective unit roundoff is ~10x
# IEEE (BENCH_NOTES), and (b) several redesigns trade constants for
# schedule-friendliness.  Factors <= 50 are plain headroom over measured
# worst cases (~30x eps on-chip).  Every factor > 50 carries its bound:
#
#   norm (100)     max-reduction over n^2 terms in emulated f64; bound
#                  ~n*eps against the elementwise reference.
#   svd (200)      bisection-based singular vectors: residual constant
#                  ~n^1.5 at small n (measured worst 144x at n=50).
#   getri/potri    inverse residual bound scales with cond(A); matgen's
#   (500)          default kinds run cond up to ~1e4 at sweep sizes.
#   trtri/gelqf    one extra triangular solve / transpose composition
#   (100)          over the base factorization bound.
#   cholqr (50000) error ~ eps * cond(A)^2 by construction (documented
#                  CholQR bound; the reference tester uses the same).
#   hegv (300)     compounds potrf(B) + hegst congruence + heev: bound
#                  ~cond(B) * heev bound.
#   gesv_rbt (5000) no-pivot LU after the butterfly: growth is bounded
#                  only probabilistically; IR restores backward error
#                  but the factor-based metric keeps the growth term.
#   gesv_calu (500) tournament pivoting's growth bound is 2^(H) vs
#                  partial pivoting's 2^(n-1) worst case; in practice a
#                  small multiple of partial pivoting's residual.
#   hesv (500)     pivot-free LDL^H with growth/d-ratio breakdown
#                  detection + RBT fallback + 2 IR steps (was 5000 with
#                  exact-zero-only detection; the growth trigger now
#                  bounds the surviving factors' conditioning).
TOL_FACTOR = {
    "gemm": 10, "norm": 100, "trsm": 30, "posv": 50, "potrf": 50,
    "gesv": 50, "geqrf": 50, "gels": 50, "heev": 50, "svd": 200,
    "symm": 10, "hemm": 10, "herk": 30, "syrk": 30, "her2k": 30,
    "trmm": 30, "getri": 500, "potri": 500, "trtri": 100, "gelqf": 100,
    "cholqr": 50000,
    "hegv": 300, "gesv_mixed": 50, "posv_mixed": 50,
    "gesv_mixed_gmres": 50, "posv_mixed_gmres": 50,
    "gesv_rbt": 5000, "gesv_calu": 500, "hesv": 500, "condest": 1,
    "steqr": 50, "sterf": 50, "serve": 50,
}


def run(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="slate_tpu tester")
    ap.add_argument("routines", nargs="+", choices=sorted(ROUTINES) + ["all"])
    ap.add_argument("--dim", default="64", help="comma list of n (or m:n:k)")
    ap.add_argument("--nb", default="16", help="comma list of tile sizes")
    ap.add_argument("--type", default="d", help="comma list from s,d,c,z")
    ap.add_argument("--grid", default="1x1", help="pxq process grid")
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--check", default="y", choices=["y", "n"])
    ap.add_argument("--xml", default=None, help="write JUnit XML here")
    ap.add_argument("--target", default="d", help="accepted for parity (h/t/b/d)")
    ap.add_argument(
        "--metrics", action="store_true",
        help="per-entry metrics: print compilations/fallbacks per sweep "
             "entry and a final summary table (implied by SLATE_TPU_METRICS)",
    )
    args = ap.parse_args(argv)

    import os as _os

    from ..aux import metrics
    metrics_on = args.metrics or bool(_os.environ.get("SLATE_TPU_METRICS"))
    if metrics_on:
        metrics.on()

    routines = sorted(ROUTINES) if "all" in args.routines else args.routines
    p, q = (int(x) for x in args.grid.split("x"))
    dims = []
    for d in args.dim.split(","):
        parts = [int(x) for x in d.split(":")]
        if len(parts) == 1:
            dims.append((parts[0], parts[0], parts[0]))
        else:
            while len(parts) < 3:
                parts.append(parts[-1])
            dims.append(tuple(parts))

    results: List[Result] = []
    header = (
        f"{'routine':10} {'type':4} {'m':>6} {'n':>6} {'k':>6} {'nb':>4} "
        f"{'grid':>5} {'time(s)':>9} {'GFLOPs':>9} {'error':>10} {'status':>7}"
    )
    print(header)
    print("-" * len(header))
    for routine in routines:
        fn = ROUTINES[routine]
        for tc in args.type.split(","):
            dtype = _TYPES[tc]
            for (m, n, k) in dims:
                for nb in (int(x) for x in args.nb.split(",")):
                    pr = Params(
                        m=m, n=n, k=k, nb=nb, dtype=dtype, type_char=tc,
                        p=p, q=q, seed=args.seed, check=args.check == "y",
                    )
                    label = f"{routine}_{tc}_m{m}n{n}k{k}nb{nb}_{p}x{q}"
                    c_before = metrics.counters() if metrics_on else {}
                    try:
                        with metrics.context(label):
                            dt, gflops, err = fn(pr)
                        tol = TOL_FACTOR.get(routine, 100) * _eps(dtype)
                        ok = (err <= tol) if pr.check else True
                        results.append(
                            Result(routine, label, dt, gflops, err, ok)
                        )
                        status = "pass" if ok else "FAILED"
                        print(
                            f"{routine:10} {tc:4} {m:6} {n:6} {k:6} {nb:4} "
                            f"{p}x{q:>3} {dt:9.4f} {gflops:9.2f} "
                            f"{err:10.2e} {status:>7}"
                        )
                    except Exception as e:  # noqa: BLE001 — harness boundary
                        results.append(
                            Result(routine, label, 0, 0, float("inf"), False, str(e))
                        )
                        print(f"{routine:10} {tc:4} {label}: ERROR {e}")
                    if metrics_on:
                        c_now = metrics.counters()
                        delta = {
                            k2: c_now.get(k2, 0) - c_before.get(k2, 0)
                            for k2 in ("jit.compilations", "fallbacks.gathered",
                                       "precision.accurate_matmul_activations")
                            if c_now.get(k2, 0) != c_before.get(k2, 0)
                        }
                        if delta:
                            print(f"           metrics: {delta}")

    npass = sum(r.passed for r in results)
    print(f"\n{npass} / {len(results)} passed")
    if metrics_on:
        print("\n" + metrics.report())
        if _os.environ.get("SLATE_TPU_METRICS"):
            metrics.dump()
    if args.xml:
        _write_junit(args.xml, results)
        print(f"wrote {args.xml}")
    return 0 if npass == len(results) else 1


def _write_junit(path: str, results: List[Result]) -> None:
    """JUnit XML like the reference's run_tests.py --xml (SURVEY §4)."""
    suite = ET.Element(
        "testsuite",
        name="slate_tpu",
        tests=str(len(results)),
        failures=str(sum(not r.passed for r in results)),
    )
    for r in results:
        case = ET.SubElement(
            suite, "testcase", classname=r.routine, name=r.params,
            time=f"{r.seconds:.4f}",
        )
        if not r.passed:
            fail = ET.SubElement(case, "failure", message=r.message or "tolerance")
            fail.text = f"error={r.error:.3e} {r.message}"
    ET.ElementTree(suite).write(path, encoding="unicode", xml_declaration=False)


if __name__ == "__main__":
    sys.exit(run())
