"""Shared value types (reference: include/slate/types.hh).

The reference's Pivot{tile_index, element_offset} lists (types.hh:84-117)
become a single global row-permutation vector on TPU: the factorization's
net row permutation, directly applicable with one gather — the natural
form for XLA (no per-row MPI exchanges at solve time).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp


@jax.tree_util.register_pytree_node_class
@dataclass
class Pivots:
    """Row pivots as a forward permutation: (P A)[i] = A[perm[i]].

    Length covers the padded row space; rows >= m map to themselves.
    Reference analogue: Pivots = vector<vector<Pivot>> (types.hh:117),
    applied by internal::permuteRows (internal_swap.cc).

    Band factorizations (windowed gbtrf) additionally carry the
    per-window local pivot orders (``band_lperms``, (steps, W1) int32)
    and the window step ``band_w``: their LU stores LAPACK-style
    in-place multipliers whose solve must interleave the window swaps
    (ops/band_kernels.py::band_getrs) — the net ``perm`` alone does not
    reproduce that factorization (reference: gbtrf.cc's banded ipiv
    semantics vs getrf's fully-swapped rows).
    """

    perm: jnp.ndarray  # (m_pad,) int32
    band_lperms: Optional[jnp.ndarray] = None  # (steps, W1) int32
    band_w: Optional[int] = None

    def tree_flatten(self):
        return (self.perm, self.band_lperms), (self.band_w,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux[0])

    def apply(self, B: jnp.ndarray) -> jnp.ndarray:
        """B <- P B (rows permuted forward)."""
        return B[self.perm[: B.shape[0]]]

    def apply_inverse(self, B: jnp.ndarray) -> jnp.ndarray:
        inv = jnp.zeros_like(self.perm)
        inv = inv.at[self.perm].set(jnp.arange(self.perm.shape[0], dtype=self.perm.dtype))
        return B[inv[: B.shape[0]]]

    def to_ipiv(self) -> jnp.ndarray:
        """Net permutation is not uniquely an ipiv sequence; exposed for
        ScaLAPACK-shim interop where only the permutation matters."""
        return self.perm


@jax.tree_util.register_pytree_node_class
@dataclass
class TriangularFactors:
    """Householder panel factors for QR/LQ (reference: slate.hh
    TriangularFactors = vector<Matrix>: Tlocal + Treduce).

    On TPU: V is stored in the factored matrix's lower (upper for LQ)
    triangle; T holds the nb x nb compact-WY block factors, one per panel,
    stacked: (nt_panels, nb, nb).
    """

    T: jnp.ndarray  # (num_panels, nb, nb)

    def tree_flatten(self):
        return (self.T,), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0])
