"""Tile distribution functions (reference: include/slate/func.hh:39-265).

These map tile indices (i, j) to block sizes, process ranks, or devices.
In the TPU design they serve two roles:

1. API parity — users of the reference construct matrices with these
   lambdas; here they configure a ``TileLayout``.
2. Compat ingestion — ``is_2d_cyclic_grid`` detects whether an arbitrary
   lambda is a plain 2D cyclic grid so it can be mapped onto the jax mesh
   without a gather/redistribute.
"""

from __future__ import annotations

from typing import Callable, Tuple

from .enums import GridOrder
from .exceptions import slate_assert

IJFunc = Callable[[Tuple[int, int]], int]
SizeFunc = Callable[[int], int]


def uniform_blocksize(n: int, nb: int) -> SizeFunc:
    """Block i size = nb, except a short last block (reference: func.hh:39-43)."""
    return lambda j: (n % nb) if (j + 1) * nb > n and n % nb != 0 else nb


def max_blocksize(nt: int, size: SizeFunc) -> int:
    """Largest block under ``size`` over nt tiles (reference: func.hh:57-66)."""
    return max((size(i) for i in range(nt)), default=0)


def device_2d_grid(order: GridOrder, m: int, n: int, p: int, q: int) -> IJFunc:
    """2D block-cyclic map with m x n tile blocks (reference: func.hh:100-116)."""
    slate_assert(order != GridOrder.Unknown, "grid order must be Col or Row")
    if order == GridOrder.Col:
        return lambda ij: int((ij[0] // m) % p + ((ij[1] // n) % q) * p)
    return lambda ij: int(((ij[0] // m) % p) * q + (ij[1] // n) % q)


def device_1d_grid(order: GridOrder, block_size: int, size: int) -> IJFunc:
    """1D block-cyclic map (reference: func.hh:145-158)."""
    slate_assert(order != GridOrder.Unknown, "grid order must be Col or Row")
    if order == GridOrder.Col:
        return device_2d_grid(order, block_size, 1, size, 1)
    return device_2d_grid(order, 1, block_size, 1, size)


def round_robin(size: int) -> IJFunc:
    """Round-robin over flattened (i, j) (reference: func.hh:178 family)."""
    return lambda ij: int((ij[0] + ij[1]) % size)


def process_2d_grid(order: GridOrder, p: int, q: int) -> IJFunc:
    """Tile-cyclic 2D process grid (reference: func.hh:207-214)."""
    return device_2d_grid(order, 1, 1, p, q)


def process_1d_grid(order: GridOrder, size: int) -> IJFunc:
    """Tile-cyclic 1D process grid (reference: func.hh:218-226)."""
    slate_assert(order != GridOrder.Unknown, "grid order must be Col or Row")
    if order == GridOrder.Col:
        return process_2d_grid(order, size, 1)
    return process_2d_grid(order, 1, size)


def transpose_grid(old_func: IJFunc) -> IJFunc:
    """Swap (i, j) before applying ``old_func`` (reference: func.hh:229-238)."""
    return lambda ij: old_func((ij[1], ij[0]))


def is_2d_cyclic_grid(
    mt: int, nt: int, func: IJFunc
) -> Tuple[bool, GridOrder, int, int]:
    """Detect whether ``func`` equals process_2d_grid(order, p, q) on the
    mt x nt tile grid (reference: func.hh:265+).

    Returns (is_cyclic, order, p, q); (False, Unknown, -1, -1) otherwise.
    """
    if mt == 0 or nt == 0 or (mt == 1 and nt == 1):
        return True, GridOrder.Col, 1, 1

    # p = first row where column 0 repeats rank of row 0; q likewise.
    base = func((0, 0))
    p = mt
    for i in range(1, mt):
        if func((i, 0)) == base:
            p = i
            break
    q = nt
    for j in range(1, nt):
        if func((0, j)) == base:
            q = j
            break

    for order in (GridOrder.Col, GridOrder.Row):
        cand = process_2d_grid(order, p, q)
        ok = all(
            func((i, j)) == cand((i, j)) for i in range(mt) for j in range(nt)
        )
        if ok:
            # 1-row/1-col grids are order-ambiguous; report Col like the ref.
            return True, order if (p > 1 and q > 1) else GridOrder.Col, p, q
    return False, GridOrder.Unknown, -1, -1
