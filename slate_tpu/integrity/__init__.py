"""slate_tpu.integrity — silent-data-corruption defense for the
serving tier (ISSUE 14).

Crashes, NaNs and overload are loud; a flaky chip that returns a
finite-but-wrong X is silent — the breaker never opens, the
finiteness fence passes, the client gets garbage.  This package is
the defense plane the service threads through dispatch:

* ``abft`` — Huang & Abraham-style algorithm-based fault tolerance:
  checksum relations verified in-trace against the factors
  (post-factor) and the solution (post-trsm) at O(n^2) extra work,
  plus the cheap host-side delivery certificate and the
  ``phase_flops``-style accounting mirror of the overhead.
* ``policy`` — the ``SLATE_TPU_INTEGRITY`` / ``Option.ServeIntegrity``
  certification policy (``off | sample=<p> | full``, ``,abft`` for
  checksummed bucket cores) and the per-replica
  :class:`~slate_tpu.integrity.policy.IntegrityScore` quarantine state
  machine (certificate-failure EWMA, breaker-shaped probe/recovery —
  distinct from the breaker, which only ever sees exceptions).

The enforcement lives in ``serve/service.py``: a failed certificate
never reaches the client — the request re-executes (hedged to a
different replica when one exists, Dean & Barroso's tail-at-scale
shape), quarantined lanes shed new admissions until a probe passes,
and every event is counted (``serve.integrity.*``, ``serve.hedge.*``,
``tools/integrity_report.py``).
"""

from __future__ import annotations

from .abft import (  # noqa: F401
    ABFT_BAD,
    ABFT_TAG,
    abft_flops,
    checksum_certificate,
    encode,
    encode_rhs,
    overhead_ratio,
)
from .policy import (  # noqa: F401
    INTEGRITY_ENV,
    IntegrityPolicy,
    IntegrityScore,
    from_options,
    parse_spec,
)

__all__ = [
    "ABFT_BAD", "ABFT_TAG", "abft_flops", "checksum_certificate",
    "encode", "encode_rhs", "overhead_ratio",
    "INTEGRITY_ENV", "IntegrityPolicy", "IntegrityScore",
    "from_options", "parse_spec",
]
