"""Algorithm-based fault tolerance (ABFT) for the served factorizations.

Huang & Abraham (IEEE Trans. Computers 1984, PAPERS.md) encode a
matrix with checksum rows/columns so that corruption introduced by a
faulty processing element is *detectable from an invariant* in O(n^2)
extra work, instead of O(n^3) recomputation.  The canonical encoding
borders the operand::

    A  ->  [[A,      A e],        (e = the ones vector; the bordered
            [e^T A,  e^T A e]]     row/column carry the running sums)

:func:`encode` / :func:`encode_rhs` build exactly that reference form
(the unit tests prove the checksum identities on it).  **Design delta
this repo takes**: the bordered matrix of an invertible A is *exactly
singular* (its last row is the sum of the others), so factoring the
bordered operand through partial pivoting would hinge the certificate
on a rounding-noise pivot.  The serve cores therefore keep the operand
unchanged — the bucket lattice, pads and BucketKey are untouched — and
verify the *checksum relations* the encoding exists for, in-trace,
against the factors the drivers already return:

* **post-factor** (LU):  ``L (U e) == P (A e)``  — every element of L
  and U participates in the product, so corruption anywhere in the
  factor flips the relation; two triangular matvecs, O(n^2).
  For Cholesky: ``L (L^H e) == A_sym e``.
* **post-trsm**: ``(e^T A) X == e^T B`` — the column-checksum row
  applied to the delivered solution; corruption in X (or in the trsm
  sweeps that produced it) breaks the compressed residual, O(n nrhs)
  after the O(n^2) ``e^T A``.

Both relations are fenced at ``sqrt(eps)`` against an |L||U|e-style
magnitude bound (the componentwise scale, so pivot growth does not
false-positive), and their verdict is folded into the executable's
``info`` output as :data:`ABFT_BAD` — a per-item flag the service's
certification reads for free (``serve/service.py``).

An ABFT-built bucket is keyed by ``BucketKey.tag == ABFT_TAG`` (the
existing options-fingerprint field, so manifests, warmup and artifact
fingerprints distinguish checksummed executables without a schema
change).  :func:`abft_flops` is the pure accounting mirror of the
extra work, the ``phase_flops`` counterpart behind the <= 15%-overhead
acceptance bound (:func:`overhead_ratio`).

Host-side, :func:`checksum_certificate` runs the post-trsm relation
over the *true* (uncropped-request) operands at delivery — the cheap
certificate for ABFT buckets, covering the device->host leg the
in-trace flag cannot see (``faults.perturb`` injects exactly there).
"""

from __future__ import annotations

import numpy as np

#: BucketKey.tag of executables whose cores carry the traced checksum
#: checks (serve/cache._build_core routes on it)
ABFT_TAG = "abft"

#: ``info`` value of a batch item whose checksum relation failed — the
#: in-trace per-item ``bad`` flag.  Negative so it can never collide
#: with the drivers' nonzero-info contract (singular U / non-SPD are
#: strictly positive) and costs the service one sign check to read.
ABFT_BAD = -1


# ---------------------------------------------------------------------------
# reference encoding (Huang & Abraham's bordered operand)
# ---------------------------------------------------------------------------


def encode(A: np.ndarray) -> np.ndarray:
    """The reference bordered encoding ``[[A, A e], [e^T A, e^T A e]]``
    — an (n+1) x (n+1) array whose last column is the row sums and
    last row the column sums of A.  Exact-singularity is the reason the
    serve cores verify the relations instead of factoring this form
    (module docstring); the unit tests prove the identities on it."""
    A = np.asarray(A)
    n = A.shape[0]
    e = np.ones((n,), dtype=A.dtype)
    c = A @ e
    w = e @ A
    out = np.zeros((n + 1, n + 1), dtype=A.dtype)
    out[:n, :n] = A
    out[:n, n] = c
    out[n, :n] = w
    out[n, n] = c.sum()
    return out


def encode_rhs(B: np.ndarray) -> np.ndarray:
    """The matching RHS encoding: B with its column sums appended as a
    checksum row ((n+1) x nrhs)."""
    B = np.asarray(B)
    if B.ndim == 1:
        B = B[:, None]
    return np.vstack([B, B.sum(axis=0, keepdims=True)])


# ---------------------------------------------------------------------------
# accounting mirror (the phase_flops counterpart)
# ---------------------------------------------------------------------------


def abft_flops(n: int, nrhs: int) -> float:
    """Model FLOPs of the in-trace checks per item: the two checksum
    vectors A e / e^T A (2n^2 each), the two triangular matvecs of the
    factor relation plus their |L||U|e magnitude bound (~4n^2), and
    the O(n nrhs) compressed solve residual with its scale."""
    n, r = float(n), float(nrhs)
    return 8.0 * n * n + 4.0 * n * r


def overhead_ratio(key) -> float:
    """ABFT overhead as a fraction of the bucket's model FLOPs — the
    measured-by-mirror acceptance bound (<= 0.15 at n=2048).  ``key``
    is a serve ``BucketKey``."""
    from ..serve.buckets import phase_flops

    return abft_flops(key.n, key.nrhs) / max(phase_flops(key), 1.0)


# ---------------------------------------------------------------------------
# host-side certificate (delivery-time, true-request operands)
# ---------------------------------------------------------------------------


def checksum_certificate(A: np.ndarray, B: np.ndarray, X: np.ndarray) -> bool:
    """The post-trsm checksum relation over the delivered solve:
    ``max|(e^T A) X - e^T B| <= sqrt(eps) * scale`` with the
    componentwise magnitude scale ``|e^T A| |X| + |e^T B|`` — O(n^2)
    against ``residual_ok``'s O(n^2 nrhs), and the same fence shape.
    False on any non-finite X.  Square solves only (a least-squares
    residual is not small by construction)."""
    A = np.asarray(A)
    B = np.asarray(B)
    X = np.asarray(X)
    if not np.all(np.isfinite(X)):
        return False
    if B.ndim == 1:
        B = B[:, None]
    if X.ndim == 1:
        X = X[:, None]
    w = A.sum(axis=0)  # e^T A
    sb = B.sum(axis=0)  # e^T B
    r = w @ X - sb
    dt = np.result_type(A, X)
    eps = float(np.finfo(np.dtype(dt).type(0).real.dtype).eps)
    scale = float((np.abs(w) @ np.abs(X) + np.abs(sb)).max(initial=0.0))
    return float(np.abs(r).max(initial=0.0)) <= np.sqrt(eps) * max(
        scale, eps
    )


# ---------------------------------------------------------------------------
# traced checks + serve cores (jax imported lazily, like serve/cache)
# ---------------------------------------------------------------------------


def _sqrt_eps(dtype) -> float:
    """sqrt(machine eps) of a dtype's real field, as a static float
    (the dtype is static at trace time — no traced coercion)."""
    return float(
        np.sqrt(np.finfo(np.dtype(dtype).type(0).real.dtype).eps)
    )


def gesv_check(Ag, Bg, Fg, perm, Xg):
    """Traced checksum verdict for one LU solve: True = BAD.

    ``Fg`` is the packed LU global (unit-lower L below, U on/above),
    ``perm`` the forward row permutation (at least n entries), ``Xg``
    the solved X.  Post-factor: ``L (U e) == (A e)[perm]``; post-trsm:
    ``(e^T A) X == e^T B``.  Both fenced at sqrt(eps) against
    componentwise magnitude bounds, so pivot growth never
    false-positives."""
    import jax.numpy as jnp

    n = Ag.shape[0]
    e = jnp.ones((n,), Ag.dtype)
    tol = _sqrt_eps(Ag.dtype)
    tiny = tol * tol  # eps of the real field
    # post-factor relation
    c = Ag @ e
    cp = c[perm[:n]]
    u = jnp.triu(Fg) @ e
    v = jnp.tril(Fg, -1) @ u + u  # L (U e), unit diagonal
    s = jnp.abs(jnp.triu(Fg)) @ e.real
    s = jnp.abs(jnp.tril(Fg, -1)) @ s + s  # |L| |U| e magnitude bound
    scale_f = jnp.max(s) + jnp.max(jnp.abs(c))
    bad_f = jnp.max(jnp.abs(v - cp)) > tol * jnp.maximum(scale_f, tiny)
    # post-trsm relation
    w = e @ Ag
    sb = e @ Bg
    r = w @ Xg - sb
    scale_s = jnp.max(jnp.abs(w) @ jnp.abs(Xg) + jnp.abs(sb))
    bad_s = jnp.max(jnp.abs(r)) > tol * jnp.maximum(scale_s, tiny)
    return bad_f | bad_s


def posv_check(Ag, Bg, Lg, Xg):
    """Traced checksum verdict for one Cholesky solve: True = BAD.
    ``Lg`` is the (clean lower) factor global.  The operand checksum is
    taken over the symmetrized lower triangle — posv reads only the
    lower triangle of A, so junk above the diagonal must not flip the
    certificate."""
    import jax.numpy as jnp

    n = Ag.shape[0]
    e = jnp.ones((n,), Ag.dtype)
    tol = _sqrt_eps(Ag.dtype)
    tiny = tol * tol
    lo = jnp.tril(Ag)
    Asym = lo + jnp.conj(jnp.tril(Ag, -1)).T
    c = Asym @ e
    t = jnp.conj(Lg).T @ e  # L^H e
    v = Lg @ t
    s1 = jnp.abs(Lg).T @ e.real
    s2 = jnp.abs(Lg) @ s1  # |L| |L^H| e magnitude bound
    scale_f = jnp.max(s2) + jnp.max(jnp.abs(c))
    bad_f = jnp.max(jnp.abs(v - c)) > tol * jnp.maximum(scale_f, tiny)
    w = e @ Asym
    sb = e @ Bg
    r = w @ Xg - sb
    scale_s = jnp.max(jnp.abs(w) @ jnp.abs(Xg) + jnp.abs(sb))
    bad_s = jnp.max(jnp.abs(r)) > tol * jnp.maximum(scale_s, tiny)
    return bad_f | bad_s


def build_core(routine: str, nb: int, schedule: str):
    """The checksummed serve core for one ABFT bucket: the same
    driver pipeline as the plain full-phase core (serve/cache), plus
    the traced post-factor and post-trsm checks, whose verdict rides
    out as ``info = ABFT_BAD`` on flagged items (driver info wins when
    positive — a singular input is a numerical property, not
    corruption).  Called by ``serve/cache._build_core`` for keys whose
    ``tag == ABFT_TAG``; vmapped per batch item by the cache."""
    from ..drivers import chol as _chol
    from ..drivers import lu as _lu
    from ..enums import Option, Uplo
    from ..matrix.matrix import HermitianMatrix, Matrix

    opts = {Option.Schedule: schedule}

    if routine == "gesv":

        def core(Ag, Bg):
            import jax.numpy as jnp

            A = Matrix.from_global(Ag, nb)
            B = Matrix.from_global(Bg, nb)
            X, LU, piv, info = _lu.gesv(A, B, opts)
            Xg = X.to_global()
            bad = gesv_check(Ag, Bg, LU.to_global(), piv.perm, Xg)
            info = jnp.where(
                info > 0, info,
                jnp.where(bad, jnp.int32(ABFT_BAD), jnp.int32(0)),
            )
            return Xg, info

        return core

    if routine == "posv":

        def core(Ag, Bg):
            import jax.numpy as jnp

            A = HermitianMatrix.from_global(Ag, nb, uplo=Uplo.Lower)
            B = Matrix.from_global(Bg, nb)
            X, L, info = _chol.posv(A, B, opts)
            Xg = X.to_global()
            Lg = jnp.tril(L.to_global())
            bad = posv_check(Ag, Bg, Lg, Xg)
            info = jnp.where(
                info > 0, info,
                jnp.where(bad, jnp.int32(ABFT_BAD), jnp.int32(0)),
            )
            return Xg, info

        return core

    raise ValueError(f"ABFT serving supports gesv/posv, not {routine!r}")
